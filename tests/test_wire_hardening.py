"""Wire-hardening suite (ISSUE 20): exactly-once semantics over a
faulty network, zero new compiled programs.

Three layers, each pinned end to end over a real socket against the
deterministic injection harness (``NetworkFaultPlan``):

- IDEMPOTENT RESUBMISSION: every wire attempt of one submission
  carries the same idempotency key, so a retried ambiguous POST
  attaches to the live request server-side (same ``request_id``,
  single admission) instead of double-executing; dropped connections
  retry with bounded exponential backoff;
- MID-STREAM RESUME: a torn ``/generate`` stream reconnects to the
  SAME replica with ``idem_key`` + ``from_token`` and replays only the
  missing tail against warm KV — resume strictly precedes failover in
  the trace timeline, and the final tokens are bitwise identical to an
  unfaulted run;
- INTEGRITY-CHECKED KV SHIPPING: framed exports carry blake2b
  checksums (whole payload + per block); a corrupt or truncated
  arrival is rejected whole (typed ``KVIntegrityError``, nothing
  installed — the allocator's ``check()`` stays green under
  ``debug_pages``), the shipper re-ships once, and past the front's
  integrity budget decode falls back to local prefill;

plus the chaos matrix (delay / drop / half-close / corrupt x generate
/ kv_import) with token parity throughout, and the zero-new-programs
assertion: none of the recovery paths compiles anything the steady
state didn't already have.
"""
import json
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor, tracing
from paddle_tpu.inference.generation import (GenerationConfig,
                                             PagedContinuousBatchingEngine)
from paddle_tpu.serving import (DisaggregatedFront, KVIntegrityError,
                                RemoteReplica, RequestFailed, Server,
                                serve_http)
from paddle_tpu.serving.remote import (decode_kv_payload,
                                       encode_kv_payload)
from paddle_tpu.testing.faults import NetworkFaultPlan

PROMPT = list(range(1, 18))     # 17 tokens -> 2 full blocks @ page 8


def tiny_model(layers=1, seed=0):
    paddle.seed(seed)
    from paddle_tpu.models import LlamaForCausalLM, llama_config
    cfg = llama_config("tiny", num_hidden_layers=layers)
    return LlamaForCausalLM(cfg), cfg


def live_server(prefix=False, **kw):
    """(server, RAW engine, httpd, port) — debug_pages armed so any
    reclaim/install bug on a recovery path fails loudly."""
    model, _ = tiny_model()
    eng = PagedContinuousBatchingEngine(
        model, max_batch=3, num_pages=24, page_size=8, max_pages=8,
        prefix_cache=prefix, debug_pages=True)
    srv = Server(eng, segment_steps=2, **kw)
    httpd = serve_http(srv)
    return srv, eng, httpd, httpd.server_address[1]


def shut(reps, httpds, srvs):
    for r in reps:
        r.close()
    for h in httpds:
        h.shutdown()
    for s in srvs:
        s.shutdown(drain=False)


def _greedy(n):
    return GenerationConfig(max_new_tokens=n, eos_token_id=None)


def _toks(handle, timeout=120):
    return [int(t) for t in handle.result(timeout=timeout)]


def _post(port, path, body):
    """Raw JSON POST (no client-side hardening in the way)."""
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("POST", path, body=json.dumps(body).encode(),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


def _healthz(port):
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        return json.loads(resp.read())
    finally:
        conn.close()


# -- the corrupt_at spec (satellite 1) ----------------------------------------
class TestCorruptSpec:
    def test_fire_and_log(self):
        plan = NetworkFaultPlan()
        plan.corrupt_at("kv_import", nth=1, mode="flip")
        plan.corrupt_at("generate", nth=1, mode="truncate", after=3)
        assert plan.fire("kv_import") == {
            "action": "corrupt", "mode": "flip", "after": 1}
        assert plan.fire("generate") == {
            "action": "corrupt", "mode": "truncate", "after": 3}
        assert plan.fire("generate") is None      # rule retired
        assert plan.injected == [("kv_import", 1, "corrupt"),
                                 ("generate", 1, "corrupt")]

    def test_validation(self):
        plan = NetworkFaultPlan()
        with pytest.raises(ValueError, match="mode"):
            plan.corrupt_at("generate", mode="scramble")
        with pytest.raises(ValueError, match="after"):
            plan.corrupt_at("generate", after=0)
        with pytest.raises(ValueError, match="unknown site"):
            plan.corrupt_at("decode")


# -- chaos matrix: the /generate column ---------------------------------------
class TestGenerateChaos:
    def test_matrix_token_parity(self):
        """delay / drop / half-close / corrupt(flip) /
        corrupt(truncate) against a live stream: every faulted run
        lands the SAME tokens as the unfaulted reference, absorbed by
        retry (pre-admission tears) or resume (mid-stream tears)."""
        srv, eng, httpd, port = live_server()
        rep = RemoteReplica(f"http://127.0.0.1:{port}")
        try:
            assert rep.wait_ready(timeout=120)
            ref = _toks(rep.submit(PROMPT, _greedy(8)))
            assert len(ref) == 8

            def faulted(arm):
                plan = NetworkFaultPlan()
                arm(plan)
                rep.fault_plan = plan
                try:
                    return _toks(rep.submit(PROMPT, _greedy(8)))
                finally:
                    rep.fault_plan = None

            assert faulted(lambda p: p.delay_at(
                "generate", nth=1, seconds=0.02)) == ref
            assert faulted(lambda p: p.drop_at("generate", nth=1)) == ref
            assert rep.submit_retries == 1
            assert faulted(lambda p: p.half_close_at(
                "generate", nth=1, after=2)) == ref
            assert rep.resumes == 1
            assert faulted(lambda p: p.corrupt_at(
                "generate", nth=1, mode="flip", after=2)) == ref
            assert faulted(lambda p: p.corrupt_at(
                "generate", nth=1, mode="truncate", after=1)) == ref
            assert rep.resumes == 3
            # recovery never leaked capacity
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and (
                    eng.free_slots() != eng.max_batch
                    or eng.alloc.free_pages != eng.num_pages):
                time.sleep(0.02)
            assert eng.free_slots() == eng.max_batch
            assert eng.alloc.free_pages == eng.num_pages
            eng.alloc.check()
        finally:
            shut([rep], [httpd], [srv])

    def test_resume_disabled_fails_fast(self):
        """With the resume budget at zero a half-close is a terminal
        stream failure — the raw surface the hardening layers wrap."""
        srv, _, httpd, port = live_server()
        rep = RemoteReplica(f"http://127.0.0.1:{port}",
                            wire_retries=0, max_resumes=0)
        try:
            assert rep.wait_ready(timeout=120)
            plan = NetworkFaultPlan()
            plan.half_close_at("generate", nth=1, after=1)
            rep.fault_plan = plan
            h = rep.submit(PROMPT, _greedy(6))
            with pytest.raises(RequestFailed, match="stream"):
                h.result(timeout=120)
            assert rep.resumes == 0
        finally:
            shut([rep], [httpd], [srv])


# -- chaos matrix: the kv_import column + never-installs ----------------------
class TestKVChaos:
    def test_matrix_and_corrupt_never_installs(self):
        """Every kv_import fault is refused whole: after delay / drop
        / half-close / corrupt(flip) / corrupt(truncate) attempts, the
        decode pool holds NOTHING (the eventual clean import installs
        every block with zero dedup hits) and the allocator validator
        stays green."""
        srv_a, eng_a, httpd_a, port_a = live_server(prefix=True)
        srv_b, eng_b, httpd_b, port_b = live_server(prefix=True)
        rep_a = RemoteReplica(f"http://127.0.0.1:{port_a}")
        rep_b = RemoteReplica(f"http://127.0.0.1:{port_b}")
        try:
            assert rep_a.wait_ready(timeout=120)
            assert rep_b.wait_ready(timeout=120)
            _toks(rep_a.submit(PROMPT, _greedy(1)))   # prefill A
            raw = rep_a.export_kv_raw(PROMPT)
            free0 = eng_b.alloc.free_pages

            def faulted(arm):
                plan = NetworkFaultPlan()
                arm(plan)
                rep_b.fault_plan = plan
                try:
                    return rep_b.import_kv_raw(raw)
                finally:
                    rep_b.fault_plan = None

            with pytest.raises(ConnectionResetError):
                faulted(lambda p: p.drop_at("kv_import", nth=1))
            with pytest.raises(KVIntegrityError):
                faulted(lambda p: p.half_close_at("kv_import", nth=1))
            with pytest.raises(KVIntegrityError, match="integrity|truncated"):
                faulted(lambda p: p.corrupt_at(
                    "kv_import", nth=1, mode="flip"))
            with pytest.raises(KVIntegrityError):
                faulted(lambda p: p.corrupt_at(
                    "kv_import", nth=1, mode="truncate"))
            assert rep_b.integrity_rejects == 3
            assert _healthz(port_b)["wire"]["integrity_rejects"] >= 2
            # nothing installed by any rejected arrival: pool
            # untouched, validator green, and the clean import now
            # installs EVERY block fresh (a partial install would
            # surface here as a dedup hit)
            assert eng_b.alloc.free_pages == free0
            eng_b.alloc.check()
            out = rep_b.import_kv_raw(raw)
            assert out["imported"] == 2 and out["deduped"] == 0
            assert eng_b.alloc.free_pages == free0 - 2
            eng_b.alloc.check()
            # delay: slow but clean, and a replayed ship through a
            # slow wire is IDEMPOTENT (chain-hash dedup, no growth)
            out = faulted(lambda p: p.delay_at(
                "kv_import", nth=1, seconds=0.02))
            assert out["imported"] == 0 and out["deduped"] == 2
            assert eng_b.alloc.free_pages == free0 - 2
            eng_b.alloc.check()
        finally:
            shut([rep_a, rep_b], [httpd_a, httpd_b], [srv_a, srv_b])


# -- idempotent resubmission (dedup regression) -------------------------------
class TestIdempotentSubmit:
    def test_retried_post_single_admission(self):
        """The ambiguous-retry contract: a second POST carrying the
        same idem_key returns the SAME request_id and tokens — one
        admission, one slot, one SLO count — and the server says so
        (`wire.idem_attaches`)."""
        srv, eng, httpd, port = live_server()
        try:
            body = {"prompt": PROMPT, "max_new_tokens": 6,
                    "stream": False, "idem_key": "dedup-test#0"}
            s1, r1 = _post(port, "/generate", body)
            s2, r2 = _post(port, "/generate", body)
            assert s1 == 200 and s2 == 200
            assert r1["request_id"] == r2["request_id"]
            assert r1["tokens"] == r2["tokens"]
            assert len(r1["tokens"]) == 6
            h = _healthz(port)
            assert h["wire"]["idem_attaches"] == 1
            # single admission also means single completion: exactly
            # one request's capacity was ever claimed (and released —
            # retire lands on the next scheduler tick)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and (
                    eng.free_slots() != eng.max_batch
                    or eng.alloc.free_pages != eng.num_pages):
                time.sleep(0.02)
            assert eng.free_slots() == eng.max_batch
            assert eng.alloc.free_pages == eng.num_pages
        finally:
            shut([], [httpd], [srv])

    def test_resume_miss_is_409(self):
        """A resume aimed at a request this server never held must
        refuse loudly (409 resume_miss) — never a silent fresh decode
        that would double-emit tokens."""
        srv, _, httpd, port = live_server()
        try:
            status, body = _post(port, "/generate", {
                "prompt": PROMPT, "max_new_tokens": 4,
                "stream": False, "idem_key": "never-seen#7",
                "from_token": 2})
            assert status == 409
            assert body["reason"] == "resume_miss"
            assert _healthz(port)["wire"]["resume_misses"] == 1
        finally:
            shut([], [httpd], [srv])


# -- resume-before-failover ordering ------------------------------------------
class TestResumeOrdering:
    def test_resume_precedes_failover_in_trace(self):
        """A torn stream resumes on the SAME replica: the request's
        timeline reads first_token -> wire.resume -> finish with no
        failover event, the server counts ONE attach (the resume
        reattach), and the tokens match the unfaulted reference."""
        tracing.clear()
        tracing.enable()
        srv, _, httpd, port = live_server()
        rep = RemoteReplica(f"http://127.0.0.1:{port}")
        try:
            assert rep.wait_ready(timeout=120)
            ref = _toks(rep.submit(PROMPT, _greedy(8)))
            plan = NetworkFaultPlan()
            plan.half_close_at("generate", nth=1, after=2)
            rep.fault_plan = plan
            h = rep.submit(PROMPT, _greedy(8))
            assert _toks(h) == ref
            assert rep.resumes == 1
            phases = [e["phase"]
                      for e in tracing.events(rid=h._trace_rid)]
            assert "failover" not in phases
            assert phases.index("first_token") \
                < phases.index("wire.resume") < phases.index("finish")
            assert _healthz(port)["wire"]["idem_attaches"] == 1
        finally:
            shut([rep], [httpd], [srv])
            tracing.disable()
            tracing.clear()


# -- the KV integrity codec ---------------------------------------------------
def _payload(nblocks=2, layers=2, page=8, heads=2):
    rng = np.arange(nblocks * page * heads,
                    dtype=np.float32).reshape(nblocks, page, heads)
    return {"version": 1, "kv_dtype": "float32", "page_size": page,
            "salt": "", "coverage": nblocks * page,
            "blocks": [{"hash": f"{b:02x}" * 4, "tokens": page}
                       for b in range(nblocks)],
            "layers": [{"k": rng + li, "v": rng - li}
                       for li in range(layers)]}


class TestIntegrityCodec:
    def test_round_trip(self):
        p = _payload()
        out = decode_kv_payload(encode_kv_payload(p))
        assert out["blocks"] == p["blocks"]
        for got, want in zip(out["layers"], p["layers"]):
            assert np.array_equal(got["k"], want["k"])
            assert np.array_equal(got["v"], want["v"])

    def test_flip_names_the_block(self):
        raw = bytearray(encode_kv_payload(_payload()))
        raw[-1] ^= 0xFF                   # last array byte -> block 1
        with pytest.raises(KVIntegrityError, match="block 1"):
            decode_kv_payload(bytes(raw))

    def test_truncation_is_typed(self):
        raw = encode_kv_payload(_payload())
        with pytest.raises(KVIntegrityError, match="truncated|trailing"):
            decode_kv_payload(raw[:len(raw) - 8])

    def test_digestless_payload_still_decodes(self):
        """Hand-built payloads without digests (older writers, the
        remote suite's fixtures) decode unverified; their truncation
        stays a PLAIN ValueError — no integrity claim was made."""
        p = _payload(layers=1)
        arr = np.ascontiguousarray(p["layers"][0]["k"])
        hdr = json.dumps({
            "version": 1, "kv_dtype": "float32", "page_size": 8,
            "salt": "", "coverage": p["coverage"],
            "blocks": p["blocks"],
            "layers": [{"k": {"dtype": "float32",
                              "shape": list(arr.shape)},
                        "v": {"dtype": "float32",
                              "shape": list(arr.shape)}}]}).encode()
        raw = (len(hdr).to_bytes(4, "big") + hdr
               + arr.tobytes() + arr.tobytes())
        out = decode_kv_payload(raw)
        assert np.array_equal(out["layers"][0]["v"], arr)
        with pytest.raises(ValueError) as ei:
            decode_kv_payload(raw[:len(raw) - 8])
        assert not isinstance(ei.value, KVIntegrityError)


# -- the disaggregated front under a rotten wire ------------------------------
class TestFrontIntegrityFallback:
    def test_reship_then_local_prefill_fallback(self):
        """Ship corrupt -> re-ship once; re-ship corrupt too -> decode
        falls back to the prefill replica (pages never travelled,
        parity holds). Past max_integrity_failures the front stops
        shipping entirely."""
        srv_a, _, httpd_a, port_a = live_server(prefix=True)
        srv_b, eng_b, httpd_b, port_b = live_server(prefix=True)
        rep_a = RemoteReplica(f"http://127.0.0.1:{port_a}")
        rep_b = RemoteReplica(f"http://127.0.0.1:{port_b}")
        try:
            assert rep_a.wait_ready(timeout=120)
            assert rep_b.wait_ready(timeout=120)
            ref = _toks(rep_a.submit(PROMPT, _greedy(8)))
            front = DisaggregatedFront(rep_a, rep_b,
                                       max_integrity_failures=2)
            plan = NetworkFaultPlan()
            plan.corrupt_at("kv_import", nth=1, mode="flip")
            plan.corrupt_at("kv_import", nth=2, mode="truncate")
            rep_b.fault_plan = plan
            free0 = eng_b.alloc.free_pages
            assert _toks(front.generate(PROMPT, _greedy(8))) == ref
            assert front.reships == 1
            assert front.integrity_rejects == 2
            assert front.failovers == 0
            assert rep_b.integrity_rejects == 2
            # both arrivals were refused whole: decode pool untouched
            assert eng_b.alloc.free_pages == free0
            eng_b.alloc.check()
            # integrity budget spent: the next request never ships
            assert _toks(front.generate(PROMPT, _greedy(8))) == ref
            assert plan.calls["kv_import"] == 2
        finally:
            shut([rep_a, rep_b], [httpd_a, httpd_b], [srv_a, srv_b])


# -- zero new programs --------------------------------------------------------
class TestZeroNewPrograms:
    def test_recovery_paths_compile_nothing(self):
        """The tentpole's no-new-programs bar: retry, resume, idem
        attach, integrity reject and re-ship are all host-side wire
        work — after one clean disaggregated run has warmed the
        programs, a chaos round pays ZERO monitored jit misses."""
        monitor.enable()
        try:
            srv_a, _, httpd_a, port_a = live_server(prefix=True)
            srv_b, _, httpd_b, port_b = live_server(prefix=True)
            rep_a = RemoteReplica(f"http://127.0.0.1:{port_a}")
            rep_b = RemoteReplica(f"http://127.0.0.1:{port_b}")
            try:
                assert rep_a.wait_ready(timeout=120)
                assert rep_b.wait_ready(timeout=120)
                front = DisaggregatedFront(rep_a, rep_b)
                ref = _toks(front.generate(PROMPT, _greedy(6)))
                # second clean round walks the warm-prefix/dedup
                # variants too, so the snapshot below covers every
                # program a steady-state replay touches
                assert _toks(front.generate(PROMPT, _greedy(6))) == ref
                before = monitor.jit_miss_by_fn()
                plan_a = NetworkFaultPlan()
                plan_a.drop_at("generate", nth=1)
                rep_a.fault_plan = plan_a
                plan_b = NetworkFaultPlan()
                plan_b.corrupt_at("kv_import", nth=1, mode="flip")
                plan_b.half_close_at("generate", nth=1, after=1)
                rep_b.fault_plan = plan_b
                assert _toks(front.generate(PROMPT, _greedy(6))) == ref
                assert rep_a.submit_retries >= 1
                assert rep_b.resumes >= 1
                assert front.reships == 1
                after = monitor.jit_miss_by_fn()
                assert after == before, (before, after)
            finally:
                shut([rep_a, rep_b], [httpd_a, httpd_b],
                     [srv_a, srv_b])
        finally:
            monitor.reset()
            monitor.disable()
