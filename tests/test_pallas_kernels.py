"""Pallas kernel + incubate fused layer tests. Off-TPU the kernels run in
pallas interpret mode, so these exercise the REAL kernel code path
(reference analog: test/legacy_test fused-op tests compare fused vs
composed)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.ops import pallas_kernels as pk


class TestRMSNorm:
    def test_matches_reference(self):
        x = np.random.randn(6, 64).astype(np.float32)
        w = np.random.randn(64).astype(np.float32)
        y = pk.rms_norm(jnp.asarray(x), jnp.asarray(w))
        ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * w
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5)

    def test_grad_matches_jax(self):
        x = jnp.asarray(np.random.randn(4, 32).astype(np.float32))
        w = jnp.asarray(np.random.randn(32).astype(np.float32))

        def ref(x, w):
            return jnp.sum(
                (x * jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6)
                 * w) ** 2)

        def ours(x, w):
            return jnp.sum(pk.rms_norm(x, w) ** 2)

        gx, gw = jax.grad(ours, (0, 1))(x, w)
        rx, rw = jax.grad(ref, (0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(rw), rtol=1e-4,
                                   atol=1e-5)

    def test_bf16_io(self):
        x = jnp.ones((8, 128), jnp.bfloat16)
        w = jnp.ones((128,), jnp.bfloat16)
        assert pk.rms_norm(x, w).dtype == jnp.bfloat16


class TestFusedLayerNorm:
    def _ref(self, x, r, b, g, beta, eps=1e-5):
        z = x + (b if b is not None else 0) + (r if r is not None else 0)
        mu = z.mean(-1, keepdims=True)
        var = z.var(-1, keepdims=True)
        return (z - mu) / np.sqrt(var + eps) * g + beta

    def test_full_fusion(self):
        x = np.random.randn(6, 64).astype(np.float32)
        r = np.random.randn(6, 64).astype(np.float32)
        b = np.random.randn(64).astype(np.float32)
        g = np.random.randn(64).astype(np.float32)
        beta = np.random.randn(64).astype(np.float32)
        y = pk.fused_layer_norm(*(jnp.asarray(a) for a in (x, r, b, g, beta)))
        np.testing.assert_allclose(np.asarray(y), self._ref(x, r, b, g, beta),
                                   rtol=1e-4, atol=1e-5)

    def test_no_residual_no_bias(self):
        x = np.random.randn(4, 32).astype(np.float32)
        g = np.ones(32, np.float32)
        beta = np.zeros(32, np.float32)
        y = pk.fused_layer_norm(jnp.asarray(x), gamma=jnp.asarray(g),
                                beta=jnp.asarray(beta))
        np.testing.assert_allclose(np.asarray(y), self._ref(x, None, None, g,
                                                            beta),
                                   rtol=1e-4, atol=1e-5)

    def test_grads_match(self):
        x = jnp.asarray(np.random.randn(4, 32).astype(np.float32))
        r = jnp.asarray(np.random.randn(4, 32).astype(np.float32))
        g = jnp.asarray(np.random.randn(32).astype(np.float32))
        beta = jnp.asarray(np.random.randn(32).astype(np.float32))

        def ours(x, r, g, beta):
            return jnp.sum(pk.fused_layer_norm(x, r, None, g, beta) ** 3)

        def ref(x, r, g, beta):
            z = x + r
            mu = jnp.mean(z, -1, keepdims=True)
            zc = z - mu
            rstd = jax.lax.rsqrt(jnp.mean(zc * zc, -1, keepdims=True) + 1e-5)
            return jnp.sum((zc * rstd * g + beta) ** 3)

        got = jax.grad(ours, (0, 1, 2, 3))(x, r, g, beta)
        want = jax.grad(ref, (0, 1, 2, 3))(x, r, g, beta)
        for a, b in zip(got, want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)


class TestRope:
    def _rope_ref(self, x, cos, sin):
        d = x.shape[-1]
        x1, x2 = x[..., : d // 2], x[..., d // 2:]
        c = cos[None, :, None, :]
        s = sin[None, :, None, :]
        return np.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], -1)

    def _cos_sin(self, S, D):
        inv = 1.0 / (10000 ** (np.arange(0, D, 2) / D))
        ang = np.outer(np.arange(S), inv)
        return np.cos(ang).astype(np.float32), np.sin(ang).astype(np.float32)

    def test_matches_reference(self):
        B, S, H, D = 2, 8, 4, 16
        x = np.random.randn(B, S, H, D).astype(np.float32)
        cos, sin = self._cos_sin(S, D)
        y = pk.fused_rope(jnp.asarray(x), jnp.asarray(cos), jnp.asarray(sin))
        np.testing.assert_allclose(np.asarray(y), self._rope_ref(x, cos, sin),
                                   rtol=1e-5, atol=1e-5)

    def test_grad_is_inverse_rotation(self):
        B, S, H, D = 1, 4, 2, 8
        x = jnp.asarray(np.random.randn(B, S, H, D).astype(np.float32))
        cos, sin = self._cos_sin(S, D)
        cos, sin = jnp.asarray(cos), jnp.asarray(sin)
        g = jax.grad(lambda x: jnp.sum(pk.fused_rope(x, cos, sin) ** 2))(x)
        # rotation preserves norms → |g| == |2·rope(x)|
        np.testing.assert_allclose(
            float(jnp.linalg.norm(g)),
            float(2 * jnp.linalg.norm(pk.fused_rope(x, cos, sin))), rtol=1e-4)


class TestDecodeMHA:
    def test_matches_masked_softmax(self):
        B, S, H, D = 2, 16, 4, 8
        q = np.random.randn(B, H, D).astype(np.float32)
        kc = np.random.randn(B, S, H, D).astype(np.float32)
        vc = np.random.randn(B, S, H, D).astype(np.float32)
        lens = np.array([5, 16], np.int32)
        y = pk.decode_mha(*(jnp.asarray(a) for a in (q, kc, vc)),
                          jnp.asarray(lens))
        for bi in range(B):
            L = lens[bi]
            s = np.einsum("hd,shd->hs", q[bi], kc[bi, :L]) / np.sqrt(D)
            p = np.exp(s - s.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            ref = np.einsum("hs,shd->hd", p, vc[bi, :L])
            np.testing.assert_allclose(np.asarray(y[bi]), ref, rtol=1e-4,
                                       atol=1e-5)


class TestGradAdd:
    def test_accumulates_fp32(self):
        x = np.random.randn(12, 16).astype(np.float32)
        dy = np.random.randn(12, 8).astype(np.float32)
        acc = np.random.randn(16, 8).astype(np.float32)
        out = pk.fused_linear_param_grad_add(
            jnp.asarray(x), jnp.asarray(dy), jnp.asarray(acc))
        np.testing.assert_allclose(np.asarray(out), acc + x.T @ dy, rtol=1e-4)
        assert out.dtype == jnp.float32

    def test_bf16_inputs_fp32_accum(self):
        x = jnp.ones((4, 8), jnp.bfloat16)
        dy = jnp.ones((4, 8), jnp.bfloat16)
        acc = jnp.zeros((8, 8), jnp.float32)
        out = pk.fused_linear_param_grad_add(x, dy, acc)
        np.testing.assert_allclose(np.asarray(out), np.full((8, 8), 4.0))


class TestIncubateFunctional:
    def test_fused_rms_norm_tensor_api(self):
        from paddle_tpu.incubate.nn import functional as IF

        x = paddle.to_tensor(np.random.randn(4, 32).astype(np.float32))
        w = paddle.to_tensor(np.ones(32, np.float32))
        y = IF.fused_rms_norm(x, w)
        assert tuple(y.shape) == (4, 32)
        # autograd flows
        loss = (y ** 2).mean()
        x.stop_gradient = False
        loss.backward()

    def test_fused_bias_dropout_residual_layer_norm(self):
        from paddle_tpu.incubate.nn import functional as IF

        x = paddle.to_tensor(np.random.randn(2, 4, 16).astype(np.float32))
        r = paddle.to_tensor(np.random.randn(2, 4, 16).astype(np.float32))
        g = paddle.to_tensor(np.ones(16, np.float32))
        b = paddle.to_tensor(np.zeros(16, np.float32))
        y = IF.fused_bias_dropout_residual_layer_norm(
            x, r, ln_scale=g, ln_bias=b, dropout_rate=0.0)
        assert tuple(y.shape) == (2, 4, 16)
        np.testing.assert_allclose(float(y.mean()), 0.0, atol=1e-5)

    def test_fused_rope_api(self):
        from paddle_tpu.incubate.nn import functional as IF

        B, S, H, D = 2, 8, 4, 16
        q = paddle.to_tensor(np.random.randn(B, S, H, D).astype(np.float32))
        k = paddle.to_tensor(np.random.randn(B, S, H, D).astype(np.float32))
        inv = 1.0 / (10000 ** (np.arange(0, D, 2) / D))
        ang = np.outer(np.arange(S), inv)
        qq, kk, _ = IF.fused_rotary_position_embedding(
            q, k, None, sin=np.sin(ang).astype(np.float32),
            cos=np.cos(ang).astype(np.float32))
        assert tuple(qq.shape) == (B, S, H, D)
        assert tuple(kk.shape) == (B, S, H, D)

    def test_memory_efficient_attention(self):
        from paddle_tpu.incubate.nn import memory_efficient_attention

        B, S, H, D = 2, 16, 4, 8
        q = paddle.to_tensor(np.random.randn(B, S, H, D).astype(np.float32))
        out = memory_efficient_attention(q, q, q)
        assert tuple(out.shape) == (B, S, H, D)


class TestFusedMultiTransformer:
    def _model(self, L=2, E=32, H=4, F_=64):
        from paddle_tpu.incubate.nn import FusedMultiTransformer

        return FusedMultiTransformer(E, H, F_, num_layers=L,
                                     dropout_rate=0.0)

    def test_context_forward(self):
        m = self._model()
        x = paddle.to_tensor(np.random.randn(2, 8, 32).astype(np.float32))
        y = m(x)
        assert tuple(y.shape) == (2, 8, 32)

    def test_decode_matches_context(self):
        """Greedy decode step t must equal position t of the context pass —
        the KV-cache correctness contract of fused_multi_transformer."""
        import jax.numpy as jnp

        m = self._model(L=2, E=32, H=4)
        m.eval()
        B, S, E = 1, 6, 32
        x = np.random.randn(B, S, E).astype(np.float32)

        ref = m(paddle.to_tensor(x))  # full causal context pass

        caches = m.make_caches(2, B, S, 4, 8)
        outs = []
        for t in range(S):
            step = paddle.to_tensor(x[:, t:t + 1])
            y, caches = m(step, time_step=t, caches=caches)
            outs.append(y.numpy())
        dec = np.concatenate(outs, axis=1)
        np.testing.assert_allclose(dec, ref.numpy(), rtol=2e-3, atol=2e-4)

    def test_training_grads(self):
        from paddle_tpu.optimizer import AdamW

        m = self._model(L=1)
        opt = AdamW(learning_rate=1e-3, parameters=m.parameters())
        x = paddle.to_tensor(np.random.randn(2, 4, 32).astype(np.float32))
        losses = []
        for _ in range(3):
            loss = (m(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]
