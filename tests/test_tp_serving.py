"""Tensor-parallel sharded serving (ISSUE 14 acceptance on CPU).

A ``tp_degree=k`` engine runs every serving program — the one-compiled
decode segment, bucketed/chunked prefill, spec verify — under a 1-D
``"mp"`` mesh: weights and KV pools shard on the (kv_)head axis,
per-slot vectors and the page table replicate, and the page
allocator / prefix-cache / CoW host logic is untouched (TP-invariant by
construction). The bar here is BITWISE-GREEDY parity TP=2 and TP=4 vs
TP=1 on the conftest's forced-8-device CPU mesh, across the full
composition matrix (prefix-cache warm hits, int8 KV, speculative
slots, LoRA adapter mixes, preempt-replay, engine restart), with zero
post-warmup compiles and ``debug_pages`` validators green.

Skips CLEANLY when the forced host devices are unavailable (e.g. a
runner that stripped XLA_FLAGS) — TP needs the virtual mesh.
"""
import numpy as np
import pytest

import jax

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.inference.generation import (ContinuousBatchingEngine,
                                             GenerationConfig,
                                             PagedContinuousBatchingEngine)
from paddle_tpu.models import LlamaForCausalLM, llama_config

# the conftest forces an 8-device virtual CPU platform; if a foreign
# runner stripped XLA_FLAGS the mesh cannot exist — skip, don't error
pytestmark = pytest.mark.skipif(
    jax.device_count() < 4,
    reason="tensor-parallel tests need >= 4 devices (run under "
           "XLA_FLAGS=--xla_force_host_platform_device_count=8)")

CFG = llama_config("tiny", num_hidden_layers=1)
GQA_CFG = llama_config("tiny", num_hidden_layers=1,
                       num_key_value_heads=2)
PROMPT = np.arange(1, 20, dtype=np.int32)
SHORT = np.arange(3, 11, dtype=np.int32)
REP = np.asarray([5, 6, 7, 8] * 6, np.int32)   # n-gram friendly


def paged_engine(tp=1, cfg=CFG, **kw):
    """Fresh seeded model + paged engine; seeds are pinned so TP=1 and
    TP=k arms hold bitwise-identical weights (TP changes placement,
    never values)."""
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    kw.setdefault("max_batch", 2)
    kw.setdefault("num_pages", 32)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_pages", 8)
    kw.setdefault("debug_pages", True)
    return PagedContinuousBatchingEngine(model, tp_degree=tp, **kw)


def drain(eng, prompts, cfgs, steps=4):
    rids = [eng.add_request(p, c) for p, c in zip(prompts, cfgs)]
    while eng.decode_segment(steps):
        pass
    fin = eng.collect_finished()
    return [fin[r].tolist() for r in rids]


def greedy(n, **kw):
    return GenerationConfig(max_new_tokens=n, **kw)


def _assert_no_leaks(eng):
    assert len(eng._free) == eng.max_batch
    assert eng.alloc.used_pages == 0
    eng.alloc.check()


# -- construction-time validation --------------------------------------------
class TestValidation:
    def test_tp_degree_validated(self):
        with pytest.raises(ValueError, match="tp_degree"):
            paged_engine(tp=0)
        with pytest.raises(ValueError, match="tp_degree"):
            paged_engine(tp="two")

    def test_tp_needs_enough_devices(self):
        with pytest.raises(ValueError, match="devices"):
            paged_engine(tp=jax.device_count() + 1)

    def test_tp_must_divide_heads(self):
        # tiny has 4 query heads / 4 kv heads: tp=3 cannot shard them
        with pytest.raises(ValueError, match="divide"):
            paged_engine(tp=3)

    def test_tp1_has_no_mesh(self):
        eng = paged_engine(tp=1)
        assert eng.tp_mesh is None and eng.tp_degree == 1
        assert "tp" not in eng.load()
        eng.close()


# -- bitwise-greedy parity ----------------------------------------------------
class TestParity:
    @pytest.fixture(scope="class")
    def ref_tokens(self):
        eng = paged_engine(tp=1)
        out = drain(eng, [PROMPT, SHORT],
                    [greedy(8), greedy(10, eos_token_id=3)])
        _assert_no_leaks(eng)
        eng.close()
        return out

    @pytest.mark.parametrize("tp", [2, 4])
    def test_mixed_batch_parity(self, tp, ref_tokens):
        """Greedy mixed-length batch: TP=k tokens are bitwise the TP=1
        tokens, and the pools live sharded between segments."""
        eng = paged_engine(tp=tp)
        out = drain(eng, [PROMPT, SHORT],
                    [greedy(8), greedy(10, eos_token_id=3)])
        assert out == ref_tokens
        pools, _ = eng.caches
        spec = pools[0][0].sharding.spec
        assert spec[2] is not None, (
            f"kv pool not head-sharded under tp={tp}: {spec}")
        _assert_no_leaks(eng)
        eng.close()

    def test_gqa_parity_tp2(self):
        """GQA (4 q-heads over 2 kv-heads): both axes divide tp=2 and
        the grouped kernel sees a consistent per-shard ratio."""
        ref = paged_engine(tp=1, cfg=GQA_CFG)
        a = drain(ref, [PROMPT], [greedy(8)])
        ref.close()
        eng = paged_engine(tp=2, cfg=GQA_CFG)
        b = drain(eng, [PROMPT], [greedy(8)])
        eng.close()
        assert a == b

    def test_sampled_rows_ride_along_tp2(self):
        """A sampled slot shares the one program with greedy slots at
        TP=2: the greedy row stays bitwise the TP=1 greedy row (the
        sampled row's trajectory is seed-dependent float sampling —
        not part of the bitwise bar, but it must complete and respect
        its budget)."""
        ref = paged_engine(tp=1)
        a = drain(ref, [PROMPT], [greedy(8)])
        ref.close()
        eng = paged_engine(tp=2)
        out = drain(eng, [PROMPT, SHORT],
                    [greedy(8),
                     greedy(6, do_sample=True, temperature=0.8,
                            top_k=5, seed=7)])
        assert out[0] == a[0]
        assert len(out[1]) == 6
        _assert_no_leaks(eng)
        eng.close()

    def test_dense_engine_parity_tp2(self):
        """The dense continuous-batching engine shards its [B, max_len]
        slabs the same way (ISSUE: 'and the dense engine')."""
        def dense(tp):
            paddle.seed(0)
            return ContinuousBatchingEngine(
                LlamaForCausalLM(CFG), max_batch=2, max_len=64,
                tp_degree=tp)

        ref = dense(1)
        a = drain(ref, [PROMPT, SHORT], [greedy(8), greedy(8)])
        eng = dense(2)
        b = drain(eng, [PROMPT, SHORT], [greedy(8), greedy(8)])
        assert a == b
        assert eng.caches[0][0].sharding.spec[2] is not None
        ref.close()
        eng.close()


# -- the composition matrix ---------------------------------------------------
class TestComposition:
    """Every serving capability PRs 3-13 built, running TOGETHER on a
    TP mesh: chunked prefill + prefix-cache warm hits + int8 KV pages
    + speculative slots + a LoRA adapter mix, optimistic admission,
    debug_pages validators on — bitwise vs the identically-knobbed
    TP=1 engine."""

    KNOBS = dict(prefill_chunk=8, prefix_cache=True, kv_dtype="int8",
                 draft_k=4, lora_capacity=2, lora_rank=4,
                 admission_mode="optimistic", num_pages=48)

    @staticmethod
    def adapter(seed, shapes, rank=4):
        g = np.random.default_rng(seed)
        return {t: (g.standard_normal((rank, di)).astype(np.float32)
                    * 0.05,
                    g.standard_normal((do, rank)).astype(np.float32)
                    * 0.05)
                for t, (di, do) in shapes.items()}

    def run_matrix(self, tp):
        eng = paged_engine(tp=tp, **self.KNOBS)
        eng.load_adapter("t1", self.adapter(11, eng.adapters.shapes))
        # cold: base + adapter + speculating slots mixed in one batch
        cold = drain(eng, [PROMPT, REP],
                     [greedy(6, adapter="t1"),
                      greedy(10, speculative=True)])
        # warm: the same prompts re-admit over the cached prefix (the
        # adapter request hits its SALTED namespace, base hits base)
        warm = drain(eng, [PROMPT, REP],
                     [greedy(6, adapter="t1"),
                      greedy(10, speculative=True)])
        hits = eng.alloc.prefix_hits
        _assert_no_leaks(eng)
        eng.close()
        return cold, warm, hits

    @pytest.mark.parametrize("tp", [2, 4])
    def test_full_composition_parity(self, tp):
        ref_cold, ref_warm, ref_hits = self.run_matrix(1)
        assert ref_cold == ref_warm        # warm-hit bitwise contract
        assert ref_hits >= 1
        cold, warm, hits = self.run_matrix(tp)
        assert cold == ref_cold
        assert warm == ref_warm
        assert hits == ref_hits            # hashing is TP-invariant


# -- preempt-replay + restart under pressure ---------------------------------
class TestPressureAndRestart:
    def test_preempt_replay_parity_tp2(self):
        """Optimistic admission on a pool too small for both requests:
        the youngest is preempted and replayed (engine.serve's relief
        loop) — TP=2 results bitwise match TP=1, with >= 1 preemption
        actually forced on both arms."""
        def run(tp):
            eng = paged_engine(tp=tp, max_batch=3, num_pages=8,
                               max_pages=8,
                               admission_mode="optimistic",
                               kv_watermark=1.0)
            outs = eng.serve([PROMPT, SHORT, REP], greedy(20),
                             segment_steps=4)
            pre = eng.alloc.preemptions
            _assert_no_leaks(eng)
            eng.close()
            return [o.tolist() for o in outs], pre

        a, pre1 = run(1)
        b, pre2 = run(2)
        assert pre1 >= 1 and pre2 >= 1, (pre1, pre2)
        assert a == b

    def test_restart_replay_parity_tp2(self):
        """PR 4's supervised-recovery contract on a mesh: reset_state
        rebuilds SHARDED pools + replicated vectors (one shared
        _init_decode_state), and a greedy replay of prompt + emitted
        prefix is bitwise the uninterrupted run."""
        ref = paged_engine(tp=1)
        want = drain(ref, [PROMPT], [greedy(12)])[0]
        ref.close()

        eng = paged_engine(tp=2)
        rid = eng.add_request(PROMPT, greedy(12))
        eng.decode_segment(4)
        prefix = eng.partial_tokens(rid)
        assert 0 < len(prefix) < 12
        eng.reset_state()
        pools, _ = eng.caches
        assert pools[0][0].sharding.spec[2] is not None
        replay = np.concatenate([PROMPT,
                                 np.asarray(prefix, np.int32)])
        out = drain(eng, [replay], [greedy(12 - len(prefix))])[0]
        assert prefix + out == want
        _assert_no_leaks(eng)
        eng.close()


# -- one program / zero post-warmup compiles ----------------------------------
class TestOneProgram:
    def test_zero_compiles_post_warmup_tp2(self):
        """After warmup() on a TP=2 engine with EVERY knob on, a hot
        adapter load + a mixed cold/warm/spec/adapter run pays zero
        monitored jit compiles — the one-program invariant extended to
        the mesh (shardings are committed at construction, so no
        program ever recompiles on a sharding change)."""
        monitor.enable()
        eng = paged_engine(tp=2, **TestComposition.KNOBS)
        eng.warmup(segment_steps=4)

        def misses():
            return monitor.jit_miss_by_fn()

        before = misses()
        eng.load_adapter("a1", TestComposition.adapter(
            11, eng.adapters.shapes))
        drain(eng, [PROMPT, REP],
              [greedy(6, adapter="a1"), greedy(8, speculative=True)])
        drain(eng, [PROMPT], [greedy(6, adapter="a1")])   # warm hit
        after = misses()
        assert after == before, (before, after)
        _assert_no_leaks(eng)
        eng.close()


# -- serving surfaces ---------------------------------------------------------
class TestSurfaces:
    def test_engine_load_surfaces_mesh(self):
        eng = paged_engine(tp=2)
        snap = eng.load()
        assert snap["tp_degree"] == 2
        assert snap["tp"]["degree"] == 2
        assert snap["tp"]["axis"] == "mp"
        assert len(snap["tp"]["devices"]) == 2
        eng.close()

    def test_server_healthz_surfaces_mesh(self):
        import json
        from urllib.request import urlopen

        from paddle_tpu.serving import Server, serve_http

        srv = Server(paged_engine(tp=2), segment_steps=2)
        try:
            assert srv.load()["tp"]["degree"] == 2
            httpd = serve_http(srv, port=0)
            try:
                port = httpd.server_address[1]
                with urlopen(f"http://127.0.0.1:{port}/healthz",
                             timeout=10) as r:
                    body = json.loads(r.read())
                assert body["tp"]["degree"] == 2
                assert body["tp_degree"] == 2
            finally:
                httpd.shutdown()
        finally:
            srv.shutdown(drain=False)


# -- fleet composition: ReplicaSpec devices + failover at TP=2 ----------------
class TestFleet:
    def test_replica_spec_pins_device_subsets(self):
        """An N-replica × TP-k fleet partitions one slice: each
        ReplicaSpec pins its replica's devices, the factory receives
        them, and the engines' meshes are disjoint."""
        from paddle_tpu.serving import ReplicaSpec, Router

        devs = jax.devices()
        seen = {}

        def factory_for(i):
            def factory(devices):
                eng = paged_engine(tp=2, tp_devices=devices)
                seen[i] = [str(d) for d in eng.tp_mesh.devices.flat]
                return eng
            return factory

        specs = [ReplicaSpec(factory_for(i),
                             server_kwargs={"segment_steps": 2,
                                            "idle_wait_s": 0.005},
                             devices=devs[2 * i:2 * i + 2])
                 for i in range(2)]
        r = Router(specs, monitor_interval_s=0.05)
        try:
            r.wait_ready()
            assert seen[0] == [str(d) for d in devs[0:2]]
            assert seen[1] == [str(d) for d in devs[2:4]]
            assert not set(seen[0]) & set(seen[1])
            h = r.submit(PROMPT, greedy(6))
            assert len(h.result(timeout=120).tolist()) == 6
        finally:
            r.shutdown(drain=False)

    def test_replica_spec_devices_validated(self):
        from paddle_tpu.serving import ReplicaSpec

        with pytest.raises(ValueError, match="devices"):
            ReplicaSpec(lambda: None, devices=[])

    def test_midstream_kill_failover_parity_tp2(self):
        """ACCEPTANCE: a TP=2 engine serves under the PR 9 router
        unchanged — the serving replica is killed mid-stream and the
        request migrates with failover replay intact, the client's one
        uninterrupted stream bitwise matching an unfaulted TP=1 run."""
        from paddle_tpu.serving import ReplicaSpec, Router, Server
        from paddle_tpu.testing.faults import FaultPlan, FaultyEngine

        ref = Server(paged_engine(tp=1), segment_steps=2,
                     idle_wait_s=0.005)
        try:
            want = ref.submit(PROMPT, greedy(24)).result(
                timeout=120).tolist()
        finally:
            ref.shutdown(drain=False)

        plans = {}
        builds = {"n": 0}

        def factory(devices):
            i = builds["n"]
            builds["n"] += 1
            eng = paged_engine(tp=2, tp_devices=devices)
            if i < 2:          # first build of each replica slot
                plans[i] = FaultPlan()
                return FaultyEngine(eng, plans[i])
            return eng

        devs = jax.devices()
        specs = [ReplicaSpec(factory,
                             server_kwargs={"segment_steps": 2,
                                            "idle_wait_s": 0.005,
                                            "max_restarts": 0},
                             devices=devs[2 * i:2 * i + 2])
                 for i in range(2)]
        r = Router(specs, monitor_interval_s=0.02,
                   replica_backoff_s=0.05, degraded_poll_s=0.1)
        try:
            h = r.submit(PROMPT, greedy(24))
            stream = h.stream(timeout=120)
            toks = [next(stream)]          # first token pins a replica
            first_rep = h.replica
            plans[first_rep].kill("decode")
            toks.extend(stream)            # SAME iterator keeps going
            assert h.status == "finished"
            assert h.failovers >= 1 and h.replica != first_rep
            assert toks == want
        finally:
            r.shutdown(drain=False)
