"""Tests for the hand-written GQA flash attention Pallas kernel.

Reference test analog: test/legacy_test/test_flash_attention.py (parity of
flash_attn vs naive SDPA composition across shapes/dtypes/causality).
Runs the REAL kernel in interpret mode on CPU (conftest pins cpu), covering:
parity vs naive SDPA, GQA grouping, cross (Sq != Sk) bottom-right causal,
gradients, in-kernel dropout statistics + determinism, and the functional /
model integration points.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.flash_attention_kernel import (flash_attention_bhsd,
                                                   supports)


def require_tileable(sq, sk):
    """Direct-kernel tests at shapes the platform can't tile skip loudly:
    on real TPU blocks must be 128-multiples, and the PUBLIC router
    (ops.pallas.flash_attention) falls back to the chunked XLA path for
    exactly these shapes — the skip mirrors production routing."""
    if not supports(sq, sk):
        pytest.skip(f"seq lens ({sq}, {sk}) not tileable on this platform "
                    "— router falls back to chunked XLA")


def sdpa(q, k, v, causal=False, scale=None):
    """Naive [B, H, S, D] reference with GQA repeat + bottom-right causal."""
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / np.sqrt(d)
    hq, hkv = q.shape[1], k.shape[1]
    if hq != hkv:
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    sc = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * s
    if causal:
        sq, sk = sc.shape[-2], sc.shape[-1]
        m = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        sc = jnp.where(m, sc, -jnp.inf)
    p = jax.nn.softmax(sc, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def rand(*shape, dtype=jnp.float32, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape), dtype)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [
    (2, 4, 4, 128, 128, 32),      # MHA
    (1, 8, 2, 128, 128, 64),      # GQA group=4
    (2, 4, 1, 64, 128, 32),       # MQA + cross lengths (decode-style)
])
def test_forward_parity(shape, causal):
    b, hq, hkv, sq, sk, d = shape
    require_tileable(sq, sk)
    q = rand(b, hq, sq, d, seed=1)
    k = rand(b, hkv, sk, d, seed=2)
    v = rand(b, hkv, sk, d, seed=3)
    out = flash_attention_bhsd(q, k, v, causal=causal)
    ref = sdpa(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_causal_sq_gt_sk_empty_rows_grads_zero_and_finite():
    """offset < 0: the first sq-sk query rows attend NO keys. fwd must
    return zeros there; bwd must produce exactly-zero (not garbage) dq for
    those rows and finite dk/dv (regression: the bwd kernels' re-mask is
    load-bearing only in this case)."""
    b, h, sq, sk, d = 1, 2, 128, 64, 32
    require_tileable(sq, sk)
    q = rand(b, h, sq, d, seed=1)
    k = rand(b, h, sk, d, seed=2)
    v = rand(b, h, sk, d, seed=3)
    out = flash_attention_bhsd(q, k, v, causal=True)
    empty = sq - sk  # rows with no valid keys under bottom-right alignment
    np.testing.assert_array_equal(np.asarray(out[:, :, :empty]), 0.0)

    def f(q, k, v):
        return jnp.sum(flash_attention_bhsd(q, k, v, causal=True) ** 2)

    dq, dk, dv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    assert np.all(np.isfinite(np.asarray(dq)))
    assert np.all(np.isfinite(np.asarray(dk)))
    assert np.all(np.isfinite(np.asarray(dv)))
    np.testing.assert_array_equal(np.asarray(dq[:, :, :empty]), 0.0)
    # valid region matches the naive reference
    ref_dq = jax.grad(
        lambda q: jnp.sum(sdpa(q, k, v, causal=True)[:, :, empty:] ** 2))(q)
    got_dq = jax.grad(
        lambda q: jnp.sum(
            flash_attention_bhsd(q, k, v, causal=True)[:, :, empty:] ** 2))(q)
    np.testing.assert_allclose(np.asarray(got_dq[:, :, empty:]),
                               np.asarray(ref_dq[:, :, empty:]),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_grad_parity(causal):
    b, hq, hkv, s, d = 2, 4, 2, 128, 32
    q = rand(b, hq, s, d, seed=4)
    k = rand(b, hkv, s, d, seed=5)
    v = rand(b, hkv, s, d, seed=6)
    g = rand(b, hq, s, d, seed=7)

    def f(fn):
        return jax.grad(
            lambda q, k, v: jnp.vdot(fn(q, k, v).astype(jnp.float32),
                                     g.astype(jnp.float32)),
            argnums=(0, 1, 2))

    got = f(lambda q, k, v: flash_attention_bhsd(q, k, v, causal=causal))(
        q, k, v)
    want = f(lambda q, k, v: sdpa(q, k, v, causal=causal))(q, k, v)
    for a, b_ in zip(got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-3, atol=2e-3)


def test_bf16_roundtrip():
    b, h, s, d = 1, 2, 128, 64
    q = rand(b, h, s, d, dtype=jnp.bfloat16, seed=8)
    k = rand(b, h, s, d, dtype=jnp.bfloat16, seed=9)
    v = rand(b, h, s, d, dtype=jnp.bfloat16, seed=10)
    out = flash_attention_bhsd(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    ref = sdpa(q.astype(jnp.float32), k.astype(jnp.float32),
               v.astype(jnp.float32), causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=3e-2, atol=3e-2)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("shape", [
    (1, 4, 4, 256, 256, 64),    # MHA, sub-native head dim (fp32-upcast
                                # path on real TPU — Mosaic rejects bf16
                                # dots with D % 128 != 0)
    (1, 4, 2, 256, 256, 128),   # GQA, native-lane head dim (bf16 MXU path)
])
def test_device_scale_parity(shape, dtype, causal):
    """Parity at shapes real-TPU tiling accepts (seq/blocks 128-multiples)
    in BOTH head-dim regimes and dtypes — the on-chip analog of
    test_forward_parity, exercised by experiments/tpu_session.sh."""
    b, hq, hkv, sq, sk, d = shape
    tol = 3e-2 if dtype == jnp.bfloat16 else 2e-4
    q = rand(b, hq, sq, d, dtype=dtype, seed=31)
    k = rand(b, hkv, sk, d, dtype=dtype, seed=32)
    v = rand(b, hkv, sk, d, dtype=dtype, seed=33)
    out = flash_attention_bhsd(q, k, v, causal=causal)
    assert out.dtype == dtype
    ref = sdpa(q.astype(jnp.float32), k.astype(jnp.float32),
               v.astype(jnp.float32), causal=causal)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=tol, atol=tol)


def test_device_scale_causal_cross_empty_rows():
    """Device-tileable variant of the sq>sk empty-rows regression (sq=256,
    sk=128 — both 128-multiples): the bwd re-mask path gets on-chip
    coverage even though the original 128/64 test skips on real TPU."""
    b, h, sq, sk, d = 1, 2, 256, 128, 64
    q = rand(b, h, sq, d, seed=51)
    k = rand(b, h, sk, d, seed=52)
    v = rand(b, h, sk, d, seed=53)
    out = flash_attention_bhsd(q, k, v, causal=True)
    empty = sq - sk
    np.testing.assert_array_equal(np.asarray(out[:, :, :empty]), 0.0)
    dq = jax.grad(lambda q: jnp.sum(
        flash_attention_bhsd(q, k, v, causal=True) ** 2))(q)
    assert np.all(np.isfinite(np.asarray(dq)))
    np.testing.assert_array_equal(np.asarray(dq[:, :, :empty]), 0.0)
    ref_dq = jax.grad(lambda q: jnp.sum(
        sdpa(q, k, v, causal=True)[:, :, empty:] ** 2))(q)
    got_dq = jax.grad(lambda q: jnp.sum(
        flash_attention_bhsd(q, k, v, causal=True)[:, :, empty:] ** 2))(q)
    np.testing.assert_allclose(np.asarray(got_dq[:, :, empty:]),
                               np.asarray(ref_dq[:, :, empty:]),
                               rtol=2e-4, atol=2e-4)


def test_hb_kernel_gated_off_device(monkeypatch):
    """The head-batched kernel's original batched-3D-dot form was
    Mosaic-rejected on real TPU; until the per-head-unrolled restructure
    is hardware-verified, supports_hb must refuse device routing unless
    the PADDLE_TPU_HB_ON_DEVICE=1 escape hatch is set — regardless of the
    platform this test runs on."""
    from paddle_tpu.ops.flash_attention_hb import supports_hb
    monkeypatch.delenv("PADDLE_TPU_HB_ON_DEVICE", raising=False)
    assert not supports_hb((1, 256, 8, 128), (1, 256, 8, 128), 0.0,
                           interpret=False)
    monkeypatch.setenv("PADDLE_TPU_HB_ON_DEVICE", "1")
    assert supports_hb((1, 256, 8, 128), (1, 256, 8, 128), 0.0,
                       interpret=False)


@pytest.mark.parametrize("d", [64, 128])
def test_device_scale_grad_parity(d):
    """bf16 backward at device-tileable shapes: covers the D-contracting
    dO·vᵀ dot in both the native-bf16 (d=128) and fp32-upcast (d=64)
    regimes."""
    b, hq, hkv, s = 1, 4, 2, 256
    q = rand(b, hq, s, d, dtype=jnp.bfloat16, seed=41)
    k = rand(b, hkv, s, d, dtype=jnp.bfloat16, seed=42)
    v = rand(b, hkv, s, d, dtype=jnp.bfloat16, seed=43)

    def f(fn):
        return jax.grad(
            lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2))

    got = f(lambda q, k, v: flash_attention_bhsd(q, k, v, causal=True))(
        q, k, v)
    want = f(lambda q, k, v: sdpa(q, k, v, causal=True))(q, k, v)
    for a, b_ in zip(got, want):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b_, np.float32),
                                   rtol=6e-2, atol=6e-2)


class TestDropout:
    def test_deterministic_in_seed(self):
        q = rand(1, 2, 128, 32, seed=11)
        k = rand(1, 2, 128, 32, seed=12)
        v = rand(1, 2, 128, 32, seed=13)
        a = flash_attention_bhsd(q, k, v, dropout_p=0.3, seed=42)
        b = flash_attention_bhsd(q, k, v, dropout_p=0.3, seed=42)
        c = flash_attention_bhsd(q, k, v, dropout_p=0.3, seed=43)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert np.abs(np.asarray(a) - np.asarray(c)).max() > 1e-6

    def test_mean_preserved(self):
        # E[dropout(P)] = P: averaged over many heads/rows the dropped
        # output converges to the undropped one (upscale_in_train)
        q = rand(4, 8, 128, 32, seed=14)
        k = rand(4, 8, 128, 32, seed=15)
        v = jnp.ones((4, 8, 128, 32), jnp.float32)
        # with v == 1, out = sum(P_drop) per row; E = 1
        out = flash_attention_bhsd(q, k, v, dropout_p=0.25, seed=7)
        mean = float(jnp.mean(out))
        assert abs(mean - 1.0) < 0.02, mean

    def test_drop_fraction(self):
        # with v one-hot over keys the kept entries are visible directly
        q = rand(2, 4, 128, 32, seed=16)
        k = rand(2, 4, 128, 32, seed=17)
        v = jnp.ones((2, 4, 128, 32), jnp.float32)
        p = 0.4
        out_nd = flash_attention_bhsd(q, k, v, dropout_p=0.0)
        out = flash_attention_bhsd(q, k, v, dropout_p=p, seed=3)
        # row sums fluctuate around 1 with variance from dropped mass;
        # fraction of rows exactly equal to no-dropout result ~ 0
        diff = np.asarray(jnp.abs(out - out_nd)).mean()
        assert diff > 0.01

    def test_grad_runs_and_matches_expectation(self):
        q = rand(1, 2, 128, 32, seed=18)
        k = rand(1, 2, 128, 32, seed=19)
        v = rand(1, 2, 128, 32, seed=20)

        def loss(q, k, v):
            return jnp.sum(flash_attention_bhsd(
                q, k, v, dropout_p=0.2, seed=5).astype(jnp.float32))

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for t in g:
            assert np.isfinite(np.asarray(t)).all()

    def test_finite_difference_dq(self):
        # same seed → same mask → finite differences must match the
        # analytic gradient even WITH dropout active
        require_tileable(8, 8)
        q = rand(1, 1, 8, 16, seed=21).astype(jnp.float64).astype(jnp.float32)
        k = rand(1, 1, 8, 16, seed=22)
        v = rand(1, 1, 8, 16, seed=23)

        def loss(qv):
            return float(jnp.sum(flash_attention_bhsd(
                qv, k, v, dropout_p=0.3, seed=11)))

        g = jax.grad(lambda qv: jnp.sum(flash_attention_bhsd(
            qv, k, v, dropout_p=0.3, seed=11)))(q)
        eps = 1e-3
        idx = (0, 0, 3, 5)
        qp = q.at[idx].add(eps)
        qm = q.at[idx].add(-eps)
        fd = (loss(qp) - loss(qm)) / (2 * eps)
        assert abs(fd - float(g[idx])) < 5e-2, (fd, float(g[idx]))


class TestIntegration:
    def test_functional_gqa(self):
        import paddle_tpu as paddle
        from paddle_tpu.nn import functional as F

        # [B, S, H, D] paddle layout, GQA heads
        q = paddle.Tensor(rand(2, 128, 8, 32, seed=24))
        k = paddle.Tensor(rand(2, 128, 2, 32, seed=25))
        v = paddle.Tensor(rand(2, 128, 2, 32, seed=26))
        out, _ = F.flash_attention(q, k, v, causal=True)
        ref = sdpa(jnp.swapaxes(q.value, 1, 2), jnp.swapaxes(k.value, 1, 2),
                   jnp.swapaxes(v.value, 1, 2), causal=True)
        np.testing.assert_allclose(np.asarray(out.value),
                                   np.asarray(jnp.swapaxes(ref, 1, 2)),
                                   rtol=2e-4, atol=2e-4)

    def test_functional_dropout_routes_to_kernel(self):
        import paddle_tpu as paddle
        from paddle_tpu.nn import functional as F

        q = paddle.Tensor(rand(1, 128, 2, 32, seed=27))
        out, _ = F.flash_attention(q, q, q, dropout=0.3, causal=True,
                                   training=True)
        out2, _ = F.flash_attention(q, q, q, dropout=0.3, causal=True,
                                    training=False)
        # training dropout differs from eval; eval == exact attention
        assert np.abs(np.asarray(out.value) -
                      np.asarray(out2.value)).max() > 1e-6

    def test_llama_gqa_no_repeat(self):
        """GQA model forward equals the repeat-KV formulation."""
        import paddle_tpu as paddle
        from paddle_tpu.models import LlamaForCausalLM, llama_config

        cfg = llama_config("tiny", num_attention_heads=4,
                           num_key_value_heads=2)
        model = LlamaForCausalLM(cfg)
        ids = paddle.Tensor(np.random.randint(0, cfg.vocab_size, (2, 16),
                                              dtype=np.int64))
        out = model(ids)
        assert tuple(out.shape) == (2, 16, cfg.vocab_size)
        assert np.isfinite(np.asarray(out.value)).all()


class TestSparseAttentionGather:
    """CSR gather path == dense-mask path, without the [s, s] buffer
    (reference sparse_attention computes only stored pairs)."""

    def _random_csr(self, rng, bh, s, max_row):
        offs = np.zeros((bh, s + 1), np.int32)
        cols_l = []
        for b in range(bh):
            cs = []
            for q in range(s):
                n = rng.randint(1, max_row + 1)
                cs.append(np.sort(rng.choice(s, size=n, replace=False)))
                offs[b, q + 1] = offs[b, q] + n
            cols_l.append(np.concatenate(cs))
        nnz = max(len(c) for c in cols_l)
        cols = np.zeros((bh, nnz), np.int32)
        for b, c in enumerate(cols_l):
            cols[b, :len(c)] = c
        return offs, cols

    def test_gather_matches_dense_mask(self):
        from paddle_tpu.nn.functional.flash_attention import sparse_attention
        import paddle_tpu as paddle

        rng = np.random.RandomState(0)
        b, h, s, d = 2, 2, 32, 8
        offs, cols = self._random_csr(rng, b * h, s, max_row=6)  # R<<s/2
        q = rng.randn(b, h, s, d).astype(np.float32)
        k = rng.randn(b, h, s, d).astype(np.float32)
        v = rng.randn(b, h, s, d).astype(np.float32)
        o3 = offs.reshape(b, h, s + 1)
        c3 = cols.reshape(b, h, -1)
        got = sparse_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                               paddle.to_tensor(v), paddle.to_tensor(o3),
                               paddle.to_tensor(c3))
        # dense reference: mask-built softmax over stored pairs only
        mask = np.zeros((b * h, s, s), bool)
        for bi in range(b * h):
            for qi in range(s):
                mask[bi, qi, cols[bi, offs[bi, qi]:offs[bi, qi + 1]]] = True
        scores = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
        scores = np.where(mask.reshape(b, h, s, s), scores, -np.inf)
        p = np.exp(scores - scores.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        want = np.einsum("bhqk,bhkd->bhqd", p, v)
        np.testing.assert_allclose(np.asarray(got.value), want,
                                   rtol=2e-4, atol=2e-4)

    def test_gather_never_builds_s2_buffer(self):
        """Long sequence, narrow rows: compiled temp memory must stay
        far below the dense [bh, s, s] score matrix."""
        from paddle_tpu.nn.functional.flash_attention import sparse_attention

        rng = np.random.RandomState(1)
        b, h, s, d, row = 1, 2, 1024, 16, 8
        offs = np.tile(np.arange(s + 1, dtype=np.int32) * row, (b * h, 1))
        cols = np.tile(
            np.concatenate([np.sort(rng.choice(s, row, replace=False))
                            for _ in range(s)]).astype(np.int32),
            (b * h, 1))
        q = jnp.asarray(rng.randn(b, h, s, d).astype(np.float32))
        o3 = jnp.asarray(offs.reshape(b, h, s + 1))
        c3 = jnp.asarray(cols.reshape(b, h, -1))

        def f(q, k, v):
            return sparse_attention(q, k, v, o3, c3)

        c = jax.jit(f).lower(q, q, q).compile()
        tmp = c.memory_analysis().temp_size_in_bytes
        dense_scores = b * h * s * s * 4        # 8.4 MB fp32
        assert tmp < dense_scores // 2, (tmp, dense_scores)


class TestSublaneModes:
    """Native bf16 at head_dim % 128 != 0 (VERDICT r4 Missing #2): the
    Mosaic sub-lane constraint is satisfied by zero-padding D to a lane
    multiple — host-side ('pad', the default: the kernel then runs the
    on-chip-proven D=128 shapes) or in-kernel ('kpad', no extra HBM,
    needs the staged on-chip check) — instead of the r4 fp32 upcast that
    quartered MXU rate on the 350M bench's own hd=64 shapes.  FORCE=1
    applies the plan in interpret mode so this suite exercises the exact
    padded numerics the device will run, including through the
    explicit-residual entry points that bypass flash_attention_bhsd."""

    @pytest.fixture(autouse=True)
    def _force(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLASH_SUBLANE_FORCE", "1")

    @pytest.mark.parametrize("mode", ["pad", "kpad", "fp32"])
    @pytest.mark.parametrize("causal", [False, True])
    def test_hd64_bf16_forward_parity(self, monkeypatch, mode, causal):
        monkeypatch.setenv("PADDLE_TPU_FLASH_SUBLANE", mode)
        require_tileable(128, 128)
        b, h, s, d = 2, 4, 128, 64
        q = rand(b, h, s, d, dtype=jnp.bfloat16, seed=1)
        k = rand(b, h, s, d, dtype=jnp.bfloat16, seed=2)
        v = rand(b, h, s, d, dtype=jnp.bfloat16, seed=3)
        out = flash_attention_bhsd(q, k, v, causal=causal)
        assert out.dtype == jnp.bfloat16 and out.shape == (b, h, s, d)
        ref = sdpa(q.astype(jnp.float32), k.astype(jnp.float32),
                   v.astype(jnp.float32), causal=causal)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref), rtol=2e-2, atol=2e-2)

    @pytest.mark.parametrize("mode", ["pad", "kpad"])
    def test_hd64_bf16_grad_matches_unpadded(self, monkeypatch, mode):
        """Grads through the padded plan == grads through the native
        interpret path (no plan), bit-comparable at fp32 inputs and
        close at bf16."""
        require_tileable(128, 128)
        b, h, s, d = 1, 2, 128, 64
        q = rand(b, h, s, d, dtype=jnp.bfloat16, seed=4)
        k = rand(b, h, s, d, dtype=jnp.bfloat16, seed=5)
        v = rand(b, h, s, d, dtype=jnp.bfloat16, seed=6)

        def loss(q, k, v):
            return jnp.sum(
                flash_attention_bhsd(q, k, v, causal=True)
                .astype(jnp.float32) ** 2)

        monkeypatch.setenv("PADDLE_TPU_FLASH_SUBLANE", mode)
        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        monkeypatch.delenv("PADDLE_TPU_FLASH_SUBLANE_FORCE")
        rq, rk, rv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for got, ref in ((gq, rq), (gk, rk), (gv, rv)):
            assert got.shape == ref.shape and got.dtype == ref.dtype
            np.testing.assert_allclose(np.asarray(got, np.float32),
                                       np.asarray(ref, np.float32),
                                       rtol=2e-2, atol=2e-2)

    @pytest.mark.parametrize("mode", ["pad", "kpad"])
    def test_residual_pair_hd64_bf16(self, monkeypatch, mode):
        """ops/flash_residual.py calls _fwd_impl/_bwd_impl DIRECTLY —
        before this round it bypassed the sub-lane guard entirely and
        would have hit the Mosaic rejection on-chip at hd64 bf16."""
        from paddle_tpu.ops.flash_residual import (flash_bwd_res,
                                                   flash_fwd_res)

        monkeypatch.setenv("PADDLE_TPU_FLASH_SUBLANE", mode)
        require_tileable(128, 128)
        b, s, h, d = 1, 128, 2, 64                    # [B, S, H, D] layout
        q = rand(b, s, h, d, dtype=jnp.bfloat16, seed=7)
        k = rand(b, s, h, d, dtype=jnp.bfloat16, seed=8)
        v = rand(b, s, h, d, dtype=jnp.bfloat16, seed=9)
        out, lse = flash_fwd_res(q, k, v, causal=True)
        assert out.shape == (b, s, h, d) and out.dtype == jnp.bfloat16
        do = rand(b, s, h, d, dtype=jnp.bfloat16, seed=10)
        dq, dk, dv = flash_bwd_res(q, k, v, out, lse, do, causal=True)
        assert dq.shape == q.shape and dk.shape == k.shape
        # against the jnp composition (interpret=False forces it off the
        # kernel path entirely: independent reference)
        ref_out, ref_lse = flash_fwd_res(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), causal=True, interpret=False)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref_out), rtol=2e-2,
                                   atol=2e-2)
        rq, rk, rv = flash_bwd_res(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), ref_out, ref_lse,
            do.astype(jnp.float32), causal=True, interpret=False)
        for got, ref in ((dq, rq), (dk, rk), (dv, rv)):
            np.testing.assert_allclose(np.asarray(got, np.float32),
                                       np.asarray(ref), rtol=5e-2,
                                       atol=5e-2)

    def test_bad_mode_rejected(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLASH_SUBLANE", "fastest")
        require_tileable(128, 128)
        q = rand(1, 2, 128, 64, dtype=jnp.bfloat16, seed=1)
        with pytest.raises(ValueError, match="PADDLE_TPU_FLASH_SUBLANE"):
            flash_attention_bhsd(q, q, q)

    def test_native_lane_multiple_untouched(self, monkeypatch):
        """D=128 stays on the native plan even under FORCE (no padding,
        no behavior change on the flagship path)."""
        from paddle_tpu.ops.flash_attention_kernel import _sublane_plan

        monkeypatch.setenv("PADDLE_TPU_FLASH_SUBLANE", "pad")
        assert _sublane_plan(128, jnp.bfloat16, False) == (None, 128)
        assert _sublane_plan(64, jnp.float32, False) == (None, 64)
        assert _sublane_plan(64, jnp.bfloat16, False) == ("pad", 128)
        assert _sublane_plan(192, jnp.bfloat16, False) == ("pad", 256)
