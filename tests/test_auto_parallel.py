"""Auto-parallel tests (reference analogs: test/auto_parallel/ — engine API,
shard_tensor placements, reshard)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.auto_parallel import (Engine, Partial,
                                                  ProcessMesh, Replicate,
                                                  Shard, Strategy, reshard,
                                                  shard_op, shard_tensor)
from paddle_tpu.distributed.topology import build_mesh, set_mesh
from paddle_tpu.io import Dataset
from paddle_tpu.optimizer import AdamW


class TestProcessMesh:
    def test_build(self):
        pm = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["x", "y"])
        assert pm.shape == [2, 4]
        assert pm.dim_names == ["x", "y"]
        assert pm.mesh.shape == {"x": 2, "y": 4}

    def test_too_many_devices(self):
        with pytest.raises(ValueError):
            ProcessMesh(np.arange(16).reshape(2, 8))


class TestShardTensor:
    def test_placement_to_sharding(self):
        pm = ProcessMesh([[0, 1], [2, 3], [4, 5], [6, 7]],
                         dim_names=["dp_", "mp_"])
        x = np.random.randn(8, 4).astype(np.float32)
        t = shard_tensor(x, pm, [Shard(0), Shard(1)])
        spec = t._value.sharding.spec
        assert spec == P("dp_", "mp_")
        np.testing.assert_array_equal(np.asarray(t._value), x)

    def test_replicate(self):
        pm = ProcessMesh(list(range(8)), dim_names=["all"])
        t = shard_tensor(np.ones((4, 4), np.float32), pm, [Replicate()])
        assert t._value.sharding.spec == P(None, None) or not any(
            t._value.sharding.spec)

    def test_double_shard_same_dim_raises(self):
        pm = ProcessMesh([[0, 1], [2, 3], [4, 5], [6, 7]],
                         dim_names=["a", "b"])
        with pytest.raises(ValueError):
            shard_tensor(np.ones((4, 4), np.float32), pm,
                         [Shard(0), Shard(0)])

    def test_reshard(self):
        pm = ProcessMesh(list(range(8)), dim_names=["all"])
        t = shard_tensor(np.random.randn(8, 8).astype(np.float32), pm,
                         [Shard(0)])
        t2 = reshard(t, pm, [Shard(1)])
        assert t2._value.sharding.spec == P(None, "all")

    def test_shard_op(self):
        pm = ProcessMesh(list(range(8)), dim_names=["all"])

        @jax.jit
        def f(xv):
            op = shard_op(paddle.tanh, pm, out_placements=[Shard(0)])
            return op(paddle.Tensor(xv))._value

        out = f(jnp.ones((8, 4)))
        assert np.allclose(np.asarray(out), np.tanh(1.0))


class ToyDS(Dataset):
    def __init__(self, n=64):
        rng = np.random.RandomState(0)
        self.x = rng.randn(n, 8).astype(np.float32)
        w = rng.randn(8, 1).astype(np.float32)
        self.y = self.x @ w

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


class TestEngine:
    def setup_method(self, _):
        set_mesh(build_mesh(dp=8))

    def _engine(self, **strategy_kw):
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
        loss = lambda out, y: ((out - y) ** 2).mean()
        opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
        s = Strategy()
        for k, v in strategy_kw.items():
            cfg, key = k.split(".")
            getattr(s, cfg)[key] = v
        return Engine(model=model, loss=loss, optimizer=opt, strategy=s)

    def test_fit_reduces_loss(self):
        e = self._engine()
        hist = e.fit(ToyDS(), epochs=3, batch_size=32, verbose=0)
        assert hist[-1] < hist[0]

    def test_evaluate_and_predict(self):
        e = self._engine()
        e.fit(ToyDS(), epochs=2, batch_size=32, verbose=0)
        res = e.evaluate(ToyDS(), batch_size=32)
        assert np.isfinite(res["loss"])
        outs = e.predict(ToyDS(), batch_size=32)
        assert outs[0].shape == (32, 1)

    def test_recompute_strategy_matches(self):
        paddle.seed(2024)
        np.random.seed(2024)  # DataLoader shuffle order must match too
        hist_plain = self._engine().fit(ToyDS(), epochs=1, batch_size=32,
                                        verbose=0)
        paddle.seed(2024)
        np.random.seed(2024)
        hist_remat = self._engine(**{"recompute.enable": True}).fit(
            ToyDS(), epochs=1, batch_size=32, verbose=0)
        np.testing.assert_allclose(hist_plain[0], hist_remat[0], rtol=1e-4)

    def test_grad_accum_strategy(self):
        e = self._engine(**{"pipeline.accumulate_steps": 4})
        hist = e.fit(ToyDS(), epochs=2, batch_size=32, verbose=0)
        assert hist[-1] < hist[0]

    def test_params_written_back(self):
        model = nn.Linear(8, 1)
        w0 = model.weight.numpy().copy()
        e = Engine(model=model, loss=lambda o, y: ((o - y) ** 2).mean(),
                   optimizer=AdamW(learning_rate=1e-2,
                                   parameters=model.parameters()))
        e.fit(ToyDS(), epochs=1, batch_size=32, verbose=0)
        assert not np.allclose(model.weight.numpy(), w0)
