"""KV memory-pressure suite (ISSUE 5): optimistic paged admission with
preempt-and-replay.

Covers the graceful-degradation contract on CPU:

- ``PageAllocator.check()`` invariant validator (free ∪ owned
  partitions the pool; page-table rows mirror ownership) and the
  ``debug_pages`` per-op arming;
- admission modes: optimistic claims prompt + one page and GROWS per
  gap; the ``kv_watermark`` pauses new admissions under crowding (but
  never an idle pool); validation of the knobs;
- PARITY: a greedy run with forced preemption (small pool) is
  bitwise-identical to the same workload unpreempted;
- ACCEPTANCE: optimistic mode completes a workload reserved mode
  cannot even admit at equal ``num_pages``, with >= 1 preemption
  observed, zero leaked pages, and the oldest request never preempted;
- rails: per-request ``max_preemptions`` fails a thrasher with
  ``PreemptionBudgetExceeded``; a request the pool cannot hold even
  alone fails ALONE with ``PagePoolExhausted`` as its typed cause
  (request-scoped, not an engine restart);
- races: preempt-then-cancel and preempt-then-engine-restart compose
  with the PR 4 recovery machinery (handles terminal exactly once,
  ``fault_stats``/drain stay accurate), and pressure during a chunked
  admission aborts the claim without leaking slot/pages;
- queue priority aging (``age_after_s``) un-starves low-priority work;
- the ``pressure`` surface: ``Server.pressure()`` and ``/healthz``.

Every paged engine here runs with ``debug_pages=True`` — the
allocator's invariant validator is armed at every page op and every
gap, so any reclaim bug in the preemption paths fails the suite
loudly.
"""
import json
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.generation import (
    ADMISSION_MODES, CausalLMEngine, ContinuousBatchingEngine,
    EngineFault, GenerationConfig, PagedContinuousBatchingEngine,
    PagePoolExhausted)
from paddle_tpu.inference.paged_cache import PageAllocator
from paddle_tpu.serving import (RequestCancelled, RequestFailed, Server,
                                serve_http)
from paddle_tpu.serving.queue import RequestHandle, RequestQueue
from paddle_tpu.serving.scheduler import PreemptionBudgetExceeded

_MODEL = None


def tiny_model():
    """ONE tiny llama shared by the whole module: jit programs are
    keyed on shapes, so reusing the model (and the same page_size /
    bucket shapes below) keeps the suite to a handful of compiles."""
    global _MODEL
    if _MODEL is None:
        paddle.seed(0)
        from paddle_tpu.models import LlamaForCausalLM, llama_config
        cfg = llama_config("tiny", num_hidden_layers=1)
        _MODEL = (LlamaForCausalLM(cfg), cfg)
    return _MODEL


def paged_engine(model, max_batch=4, num_pages=64, page_size=4,
                 max_pages=8, **kw):
    kw.setdefault("debug_pages", True)
    return PagedContinuousBatchingEngine(
        model, max_batch=max_batch, num_pages=num_pages,
        page_size=page_size, max_pages=max_pages, **kw)


def _greedy(n, eos=None):
    return GenerationConfig(max_new_tokens=n, eos_token_id=eos)


def _prompts(cfg, n, plen=6, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, cfg.vocab_size, (plen,)).astype(np.int32)
            for _ in range(n)]


def _reference(prompts, maxes, eos=None):
    """Expected greedy tokens via a big reserved-mode pool (no
    pressure possible) — the parity baseline."""
    model, _ = tiny_model()
    eng = paged_engine(model)
    srv = Server(eng, segment_steps=4)
    hs = [srv.submit(p, _greedy(m, eos)) for p, m in zip(prompts, maxes)]
    out = [h.result(timeout=180) for h in hs]
    srv.shutdown()
    return out


def _assert_no_leaks(eng):
    assert eng.free_slots() == eng.max_batch
    assert eng.alloc.free_pages == eng.num_pages
    eng.alloc.check()


# -- allocator invariant validator ------------------------------------------
class TestAllocatorCheck:
    def _alloc(self, debug=False):
        return PageAllocator(num_pages=8, page_size=4, max_batch=2,
                             max_pages=6, debug=debug)

    def test_clean_states_pass(self):
        a = self._alloc()
        a.check()                       # empty pool
        a.ensure(0, 10)                 # 3 pages
        a.ensure(1, 4)
        a.check()
        a.free_slot(0)
        a.check()

    def test_double_owned_page_detected(self):
        a = self._alloc()
        a.ensure(0, 4)
        a._owned[1] = [a._owned[0][0]]  # same page owned twice
        # the sharing-era check reports this as a refcount mismatch
        # (two appearances, refcount 1) — sharing is only legal with
        # matching refcount accounting
        with pytest.raises(RuntimeError, match="matching refcount"):
            a.check()

    def test_lost_page_detected(self):
        a = self._alloc()
        a.ensure(0, 4)
        a._owned[0] = []                # page vanished from both sides
        a.page_table[0, :] = -1
        # refcount says 1, appears nowhere: the sharing-era check
        # flags the leak before the partition sweep reports 'missing'
        with pytest.raises(RuntimeError, match="refcount leak|missing"):
            a.check()

    def test_free_list_duplicate_detected(self):
        a = self._alloc()
        pid = a._free[0]
        a._free.append(pid)
        with pytest.raises(RuntimeError, match="twice in the free"):
            a.check()

    def test_stale_table_row_detected(self):
        a = self._alloc()
        a.ensure(0, 8)
        a.page_table[0, 0] = 99         # table disagrees with _owned
        with pytest.raises(RuntimeError, match="row 0 inconsistent"):
            a.check()

    def test_debug_flag_arms_every_op(self):
        a = self._alloc(debug=True)
        a.ensure(0, 8)
        a.page_table[0, 1] = -1         # corrupt between ops
        with pytest.raises(RuntimeError, match="inconsistent"):
            a.ensure(1, 4)              # next op trips the validator


# -- admission-mode knobs ----------------------------------------------------
class TestAdmissionModes:
    def test_knob_validation(self):
        model, _ = tiny_model()
        with pytest.raises(ValueError, match="admission_mode"):
            paged_engine(model, admission_mode="eager")
        for bad in (0, -0.1, 1.5):
            with pytest.raises(ValueError, match="kv_watermark"):
                paged_engine(model, admission_mode="optimistic",
                             kv_watermark=bad)
        assert ADMISSION_MODES == ("reserved", "optimistic")

    def test_server_mirror_needs_idle_paged_engine(self):
        model, _ = tiny_model()
        dense = ContinuousBatchingEngine(model, max_batch=2, max_len=32)
        with pytest.raises(ValueError, match="paged engine"):
            Server(dense, admission_mode="optimistic", start=False)
        with pytest.raises(ValueError, match="admission_mode"):
            Server(paged_engine(model), admission_mode="nope",
                   start=False)
        eng = paged_engine(model)
        srv = Server(eng, admission_mode="optimistic", start=False)
        assert eng.admission_mode == "optimistic"
        srv.shutdown(drain=False)
        busy = paged_engine(model)
        busy.add_request(np.arange(4, dtype=np.int32), _greedy(4))
        with pytest.raises(ValueError, match="idle"):
            Server(busy, admission_mode="optimistic", start=False)

    def test_optimistic_claim_is_prompt_plus_one_page(self):
        model, _ = tiny_model()
        eng = paged_engine(model, admission_mode="optimistic")
        cfg = _greedy(20)
        assert eng._optimistic_claim(6, cfg) == 6 + eng.page_size
        # never beyond the reserved worst case
        assert (eng._optimistic_claim(6, _greedy(1))
                == eng._reserved(6, _greedy(1)))

    def test_watermark_pauses_new_admissions_but_not_idle(self):
        model, _ = tiny_model()
        eng = paged_engine(model, num_pages=8, admission_mode="optimistic",
                           kv_watermark=0.5)
        cfg = _greedy(8)
        # idle pool: the watermark must NOT block a lone admission
        assert eng.can_admit(6, cfg)
        eng.add_request(np.arange(6, dtype=np.int32), cfg)  # 3 pages
        # 3 used + 3 more would cross 0.5 * 8 = 4 -> paused
        assert not eng.can_admit(6, cfg)
        # reserved mode at the same occupancy would also refuse (worst
        # case 14 tokens = 4 pages > 5 free is fine, but watermark is
        # not consulted): check the optimistic refusal came from the
        # watermark, not can_fit
        assert eng.alloc.can_fit(eng._free[0],
                                 eng._optimistic_claim(6, cfg))
        eng.cancel_request(next(iter(eng._slot_req.values())))
        _assert_no_leaks(eng)


# -- engine-level grow / preempt / exhaustion guard --------------------------
class TestEngineGrowPreempt:
    def test_exhaustion_is_loud_and_preempt_unblocks(self):
        """A bare engine driver that ignores pressure sees
        PagePoolExhausted from decode_segment (never a silent dropped
        KV write); preempt_request reclaims the victim and decoding
        continues."""
        model, mcfg = tiny_model()
        eng = paged_engine(model, num_pages=10,
                           admission_mode="optimistic", kv_watermark=1.0)
        p1, p2 = _prompts(mcfg, 2)
        r1 = eng.add_request(p1, _greedy(24))
        r2 = eng.add_request(p2, _greedy(24))
        with pytest.raises(PagePoolExhausted) as ei:
            for _ in range(8):
                eng.decode_segment(4)
        assert set(ei.value.rids) <= {r1, r2}
        toks = eng.preempt_request(r2)
        assert toks is not None and len(toks) >= 1
        assert eng.preempt_request(r2) is None      # not active now
        assert eng.alloc.preemptions == 1
        while eng.decode_segment(4):
            pass
        done = eng.collect_finished()
        assert len(done[r1]) == 24
        _assert_no_leaks(eng)

    def test_serve_parity_under_repeated_preemption(self):
        """Bare ``engine.serve()`` on a tight pool preempts the SAME
        request more than once (each replay re-admits with the newest
        rid, so it stays the preferred victim while the oldest
        survives) — its replay budget must be measured against the
        ORIGINAL cfg each time; measuring against an earlier replay's
        already-reduced ``max_new_tokens`` double-subtracts the first
        prefix and silently truncates the result."""
        model, mcfg = tiny_model()
        prompts = _prompts(mcfg, 3)
        ref = paged_engine(model).serve(prompts, _greedy(24),
                                        segment_steps=4)
        eng = paged_engine(model, num_pages=12,
                           admission_mode="optimistic", kv_watermark=1.0)
        out = eng.serve(prompts, _greedy(24), segment_steps=4)
        # more preemptions than preemptable requests: some request
        # replayed with a non-empty prior prefix (oldest is never
        # the victim, so at most 2 of the 3 are preemptable)
        assert eng.alloc.preemptions >= 3
        for a, b in zip(ref, out):
            assert np.array_equal(a, b)
        _assert_no_leaks(eng)

    def test_grow_noop_in_reserved_mode(self):
        model, mcfg = tiny_model()
        eng = paged_engine(model, num_pages=10)
        eng.add_request(_prompts(mcfg, 1)[0], _greedy(8))
        assert eng.grow_for_segment(4) == []
        while eng.decode_segment(4):
            pass
        eng.collect_finished()
        _assert_no_leaks(eng)

    def test_growth_stamp_skips_redundant_recheck(self):
        """A clean grow_for_segment(n) stamps the engine so the
        scheduler's decode_segment(n) in the same gap skips its
        (device-syncing) exhaustion re-check; the stamp is single-shot
        (the segment advances lens) and any new admission invalidates
        it, so the loud-failure guard still fires for bare drivers
        that skip pressure relief."""
        model, mcfg = tiny_model()
        eng = paged_engine(model, num_pages=64,
                           admission_mode="optimistic", kv_watermark=1.0)
        p = _prompts(mcfg, 2)
        eng.add_request(p[0], _greedy(8))
        assert eng._growth_stamp is None     # admission invalidates
        assert eng.grow_for_segment(4) == []
        assert eng._growth_stamp == 4
        eng.add_request(p[1], _greedy(8))
        assert eng._growth_stamp is None     # new slot: stamp is stale
        assert eng.grow_for_segment(4) == []
        eng.decode_segment(4)
        assert eng._growth_stamp is None     # consumed single-shot
        while eng.decode_segment(4):
            pass
        eng.collect_finished()
        _assert_no_leaks(eng)


# -- server-level preemption -------------------------------------------------
class TestServerPreemption:
    def test_parity_and_acceptance_under_forced_preemption(self):
        """THE acceptance test: greedy tokens under forced preemption
        are bitwise-identical to the unpreempted baseline; >= 1
        preemption actually happened; the oldest request was never
        preempted; zero pages leaked (validator clean at exit)."""
        model, mcfg = tiny_model()
        prompts = _prompts(mcfg, 4)
        ref = _reference(prompts, [20] * 4)
        # 4 x (6 + 20) tokens = 28 worst-case pages; 14 forces pressure
        eng = paged_engine(model, num_pages=14,
                           admission_mode="optimistic", kv_watermark=1.0)
        srv = Server(eng, segment_steps=4, max_preemptions=50)
        hs = [srv.submit(p, _greedy(20)) for p in prompts]
        out = [h.result(timeout=180) for h in hs]
        for a, b in zip(ref, out):
            assert np.array_equal(a, b)
        assert eng.alloc.preemptions >= 1
        assert sum(h._preempts for h in hs) >= 1
        assert hs[0]._preempts == 0        # oldest never preempted
        assert srv.drain(timeout=30)
        _assert_no_leaks(eng)
        pr = srv.pressure()
        assert pr["preemptions"] == eng.alloc.preemptions
        assert pr["admission_mode"] == "optimistic"
        assert pr["waiting_on_pages"] == 0 and pr["occupancy"] == 0.0
        srv.shutdown()

    def test_optimistic_completes_what_reserved_cannot_admit(self):
        """Equal num_pages: reserved mode cannot even ADMIT the
        requests (worst case 26 tokens = 7 pages > the 6-page pool),
        optimistic completes all three because they stop on EOS early
        (10 generated tokens = 4 pages actually used) — the whole
        EOS-early gap the optimistic policy exists to harvest."""
        model, mcfg = tiny_model()
        # IDENTICAL prompts: greedy streams are identical, so one EOS
        # value (the reference's 10th token) cuts every request at 10
        # generated tokens while max_new_tokens stays 20
        p = _prompts(mcfg, 1)[0]
        ref = list(map(int, _reference([p], [20])[0]))
        eos = ref[9]
        assert ref.index(eos) == 9      # seeded run: first occurrence
        want = ref[:10]

        def build(mode):
            return paged_engine(model, num_pages=6,
                                admission_mode=mode, kv_watermark=1.0)

        res = build("reserved")
        srv = Server(res, segment_steps=4)
        h = srv.submit(p, _greedy(20, eos))
        with pytest.raises(RequestFailed, match="never be admitted"):
            h.result(timeout=60)
        srv.shutdown()
        _assert_no_leaks(res)

        opt = build("optimistic")
        srv2 = Server(opt, segment_steps=4, max_preemptions=50)
        hs = [srv2.submit(p, _greedy(20, eos)) for _ in range(3)]
        out = [list(map(int, h.result(timeout=180))) for h in hs]
        assert out == [want] * 3
        assert opt.alloc.preemptions >= 1
        assert hs[0]._preempts == 0
        assert srv2.drain(timeout=30)
        _assert_no_leaks(opt)
        srv2.shutdown()

    def test_preemption_budget_exceeded_typed_failure(self):
        """max_preemptions=0: the first preemption fails the victim
        with PreemptionBudgetExceeded as the cause instead of
        replaying it — and everyone else still completes."""
        model, mcfg = tiny_model()
        prompts = _prompts(mcfg, 3)
        eng = paged_engine(model, num_pages=10,
                           admission_mode="optimistic", kv_watermark=1.0)
        srv = Server(eng, segment_steps=4, max_preemptions=0)
        hs = [srv.submit(p, _greedy(16)) for p in prompts]
        failed = 0
        for h in hs:
            try:
                assert len(h.result(timeout=180)) == 16
            except RequestFailed as e:
                assert isinstance(e.__cause__,
                                  PreemptionBudgetExceeded)
                failed += 1
        assert failed >= 1
        assert hs[0].status == "finished"    # oldest always survives
        assert srv.drain(timeout=30)
        _assert_no_leaks(eng)
        srv.shutdown()

    def test_unsatisfiable_request_fails_alone(self):
        """A request whose growth cannot fit even with the pool to
        itself fails with PagePoolExhausted as its typed cause — a
        request-scoped, contained event (no engine restart, no other
        victims)."""
        model, mcfg = tiny_model()
        # pool holds 16 tokens; request wants 6 + 20 = 26 <= max_len 32
        eng = paged_engine(model, num_pages=4,
                           admission_mode="optimistic", kv_watermark=1.0)
        srv = Server(eng, segment_steps=4)
        h = srv.submit(_prompts(mcfg, 1)[0], _greedy(20))
        with pytest.raises(RequestFailed) as ei:
            h.result(timeout=120)
        assert isinstance(ei.value.__cause__, PagePoolExhausted)
        assert srv.restarts == 0             # contained, not recovered
        assert srv.fault_stats()["faults"] == {}
        # the server still serves: a fitting request completes
        h2 = srv.submit(_prompts(mcfg, 1)[0], _greedy(4))
        assert len(h2.result(timeout=120)) == 4
        assert srv.drain(timeout=30)
        _assert_no_leaks(eng)
        srv.shutdown()

    def test_preempt_then_cancel(self):
        """A preempted handle parked on the replay list is cancelled:
        it finishes CANCELLED exactly once, never re-admits, and no
        capacity leaks."""
        model, mcfg = tiny_model()
        eng = paged_engine(model, num_pages=10,
                           admission_mode="optimistic", kv_watermark=1.0)
        srv = Server(eng, segment_steps=4, max_preemptions=50)
        p = _prompts(mcfg, 2)
        h_old = srv.submit(p[0], _greedy(24))   # hogs the pool
        h_vic = srv.submit(p[1], _greedy(24))
        deadline = time.monotonic() + 120
        while h_vic._preempts == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert h_vic._preempts >= 1
        h_vic.cancel()
        with pytest.raises(RequestCancelled):
            h_vic.result(timeout=120)
        assert len(h_old.result(timeout=120)) == 24
        assert srv.drain(timeout=30)
        _assert_no_leaks(eng)
        srv.shutdown()

    def test_preempt_then_engine_restart_composes(self):
        """An engine-scoped fault while a preempted handle sits on the
        replay list: recovery replays BOTH the in-flight and the
        preempted requests; greedy tokens stay bitwise-identical;
        fault_stats/drain stay accurate."""
        from paddle_tpu.testing.faults import FaultPlan, FaultyEngine

        model, mcfg = tiny_model()
        prompts = _prompts(mcfg, 3)
        ref = _reference(prompts, [16] * 3)
        plan = FaultPlan().raise_at(
            "decode", nth=4, exc=EngineFault("injected"))
        eng = paged_engine(model, num_pages=10,
                           admission_mode="optimistic", kv_watermark=1.0)
        srv = Server(FaultyEngine(eng, plan), segment_steps=4,
                     max_preemptions=50, max_restarts=3, max_replays=8,
                     restart_backoff_s=0.01)
        hs = [srv.submit(p, _greedy(16)) for p in prompts]
        out = [h.result(timeout=180) for h in hs]
        for a, b in zip(ref, out):
            assert np.array_equal(a, b)
        assert srv.restarts == 1
        assert eng.alloc.preemptions >= 1
        fs = srv.fault_stats()
        assert fs["faults"].get(("engine", "decode")) == 1
        assert fs["degraded"] is None
        assert srv.drain(timeout=30)
        _assert_no_leaks(eng)
        srv.shutdown()

    def test_pressure_during_chunked_admission_aborts_claim(self):
        """When growth pressure hits with only the oldest request
        active, the in-flight chunked admission is the victim: its
        claim aborts (slot + pages reclaimed), the handle parks with a
        preemption charged, and it completes via replay once the pool
        breathes — zero leaks throughout (validator armed)."""
        model, mcfg = tiny_model()
        rng = np.random.RandomState(3)
        long_p = rng.randint(0, mcfg.vocab_size, (12,)).astype(np.int32)
        short_p = _prompts(mcfg, 1)[0]
        ref = _reference([short_p, long_p], [20, 8])
        eng = paged_engine(model, num_pages=8,
                           admission_mode="optimistic",
                           kv_watermark=1.0, prefill_chunk=4)
        srv = Server(eng, segment_steps=4, max_preemptions=50)
        h_old = srv.submit(short_p, _greedy(20))
        time.sleep(0.05)                 # oldest admits first
        h_chk = srv.submit(long_p, _greedy(8))
        out = [h_old.result(timeout=180), h_chk.result(timeout=180)]
        assert np.array_equal(out[0], ref[0])
        assert np.array_equal(out[1], ref[1])
        assert eng.alloc.preemptions >= 1
        assert h_old._preempts == 0
        assert srv.drain(timeout=30)
        _assert_no_leaks(eng)
        srv.shutdown()

    def test_pressure_aborted_admission_keeps_deadline(self):
        """A handle parked for replay WITHOUT ever completing an
        admission (``engine_rid is None`` — its in-flight chunked
        claim was aborted by pressure relief) still honours its
        admission deadline: ``_admit_replays`` expires it instead of
        serving it late. A handle that DID admit once (``engine_rid``
        set) is exempt — its deadline was met the first time, so a
        crowded pool defers it rather than expiring it."""
        from paddle_tpu.serving.queue import DeadlineExpired

        model, mcfg = tiny_model()
        eng = paged_engine(model, num_pages=4,
                           admission_mode="optimistic", kv_watermark=1.0)
        srv = Server(eng, segment_steps=4, max_preemptions=50)
        srv.shutdown()       # loop stopped, engine alive: the test
        #                      thread drives _admit_replays directly
        hog = eng.add_request(_prompts(mcfg, 1)[0], _greedy(24))
        p = _prompts(mcfg, 1, seed=7)[0]
        dead = RequestHandle(990, p, len(p), _greedy(8),
                             deadline=time.monotonic() - 0.1)
        met = RequestHandle(991, p, len(p), _greedy(8),
                            deadline=time.monotonic() - 0.1)
        met.engine_rid = 12345      # admitted once, then preempted
        srv._replay.extend([dead, met])
        srv._admit_replays()
        assert dead.status == "expired"
        with pytest.raises(DeadlineExpired):
            dead.result(timeout=1)
        assert met.status == "queued"       # deferred, NOT expired
        assert met in srv._replay
        eng.cancel_request(hog)
        _assert_no_leaks(eng)

    def test_pressure_surface_healthz(self):
        """/healthz carries the pressure block for a paged engine
        (occupancy, waiting_on_pages, preemptions) and omits it for a
        dense engine — operators can tell memory-pressure degradation
        apart from the stall/fault degraded reason."""
        model, mcfg = tiny_model()
        eng = paged_engine(model, num_pages=10,
                           admission_mode="optimistic", kv_watermark=1.0)
        srv = Server(eng, segment_steps=4, max_preemptions=50)
        hs = [srv.submit(p, _greedy(16)) for p in _prompts(mcfg, 3)]
        for h in hs:
            h.result(timeout=180)
        httpd = serve_http(srv, port=0)
        try:
            port = httpd.server_address[1]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz") as r:
                body = json.loads(r.read())
            assert body["status"] == "ok"
            pr = body["pressure"]
            assert pr["admission_mode"] == "optimistic"
            assert pr["preemptions"] == eng.alloc.preemptions >= 1
            assert pr["free_pages"] == eng.num_pages
        finally:
            httpd.shutdown()
            srv.shutdown()
        dense = ContinuousBatchingEngine(model, max_batch=2, max_len=32)
        srv2 = Server(dense, segment_steps=4)
        assert srv2.pressure() is None
        httpd2 = serve_http(srv2, port=0)
        try:
            port = httpd2.server_address[1]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz") as r:
                body = json.loads(r.read())
            assert "pressure" not in body
        finally:
            httpd2.shutdown()
            srv2.shutdown()


# -- monitor export ----------------------------------------------------------
class TestMonitorExport:
    def test_preemption_family_exported_and_retired(self):
        """paddle_tpu_kv_preemptions_total{pool,reason} and the
        per-server kv_pressure gauge export while serving and retire
        with alloc.close()/server shutdown (the monitor_report
        --serving families)."""
        from paddle_tpu import monitor
        monitor.enable()
        monitor.reset()
        try:
            model, mcfg = tiny_model()
            eng = paged_engine(model, num_pages=10,
                               admission_mode="optimistic",
                               kv_watermark=1.0)
            srv = Server(eng, segment_steps=4, max_preemptions=50)
            hs = [srv.submit(p, _greedy(16)) for p in _prompts(mcfg, 3)]
            for h in hs:
                h.result(timeout=180)
            snap = monitor.snapshot()["metrics"]
            samples = snap.get("paddle_tpu_kv_preemptions_total",
                               {}).get("samples", [])
            assert sum(s["value"] for s in samples) \
                == eng.alloc.preemptions >= 1
            assert any(s["labels"].get("reason") == "pressure"
                       for s in samples)
            assert snap.get("paddle_tpu_serving_kv_pressure",
                            {}).get("samples")
            srv.shutdown()
            eng.close()
            snap2 = monitor.snapshot()["metrics"]
            assert not snap2.get("paddle_tpu_kv_preemptions_total",
                                 {}).get("samples", [])
            assert not snap2.get("paddle_tpu_serving_kv_pressure",
                                 {}).get("samples", [])
        finally:
            monitor.reset()
            monitor.disable()


# -- queue priority aging ----------------------------------------------------
class TestPriorityAging:
    def _handle(self, rid, priority, age_s=0.0):
        h = RequestHandle(rid, np.arange(4, dtype=np.int32), 4,
                          _greedy(4), priority=priority)
        h.submit_ts -= age_s
        return h

    def test_validation(self):
        with pytest.raises(ValueError, match="age_after_s"):
            RequestQueue(4, age_after_s=0.0)
        with pytest.raises(ValueError, match="age_after_s"):
            RequestQueue(4, age_after_s=-1)

    def test_static_priority_starves_without_aging(self):
        q = RequestQueue(4)
        q.put(self._handle(0, priority=5, age_s=100.0))
        q.put(self._handle(1, priority=0))
        q.reap(time.monotonic())
        assert q.pop_if(lambda h: True).id == 1

    def test_aging_bumps_long_waiters(self):
        q = RequestQueue(4, age_after_s=10.0)
        q.put(self._handle(0, priority=5, age_s=100.0))   # 10 levels
        q.put(self._handle(1, priority=0))
        q.reap(time.monotonic())
        # effective priority 5 - 10 = -5 beats the fresh 0
        assert q.pop_if(lambda h: True).id == 0
        assert q.pop_if(lambda h: True).id == 1

    def test_fifo_within_effective_level_preserved(self):
        q = RequestQueue(4, age_after_s=10.0)
        a = self._handle(0, priority=1, age_s=11.0)   # -> effective 0
        b = self._handle(1, priority=0)
        c = self._handle(2, priority=0)
        q.put(b)
        q.put(c)
        q.put(a)
        q.reap(time.monotonic())
        # a reached level 0 but entered the queue LAST: b, c keep
        # their FIFO precedence at that level
        assert [q.pop_if(lambda h: True).id for _ in range(3)] \
            == [1, 2, 0]

    def test_server_passes_age_after_s_through(self):
        model, _ = tiny_model()
        eng = paged_engine(model)
        srv = Server(eng, age_after_s=0.5, start=False)
        assert srv.queue.age_after_s == 0.5
        srv.shutdown(drain=False)
