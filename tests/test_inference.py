"""Serving/inference-engine tests.

Reference contract: AnalysisPredictor load/run (test/cpp/inference/api
predictor tests) + decode-loop correctness (fused_multi_transformer decode
must match the uncached full forward)."""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn


class TinyNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        return self.fc2(paddle.tanh(self.fc1(x)))


class TestPredictor:
    def test_from_layer_run(self):
        net = TinyNet()
        x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
        pred = paddle.inference.Predictor.from_layer(net, [x])
        out = pred.run([x])[0]
        want = np.asarray(net(paddle.Tensor(x)).value)
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)

    def test_handle_style_api(self):
        net = TinyNet()
        x = np.random.RandomState(1).randn(3, 8).astype(np.float32)
        pred = paddle.inference.Predictor.from_layer(net, [x])
        names = pred.get_input_names()
        pred.get_input_handle(names[0]).copy_from_cpu(x)
        pred.run()
        out = pred.get_output_handle("out0").copy_to_cpu()
        want = np.asarray(net(paddle.Tensor(x)).value)
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)

    def test_aot_export_roundtrip(self, tmp_path):
        from paddle_tpu.inference.aot import (export_fn, load_exported,
                                              save_exported)

        def f(x):
            return jnp.tanh(x) * 2

        x = np.random.RandomState(2).randn(4, 4).astype(np.float32)
        exp = export_fn(f, x)
        p = str(tmp_path / "f.stablehlo")
        save_exported(exp, p)
        loaded = load_exported(p)
        np.testing.assert_allclose(np.asarray(loaded.call(x)),
                                   np.tanh(x) * 2, rtol=1e-6)

    def test_jit_save_predictor_load(self, tmp_path):
        net = TinyNet()
        prefix = str(tmp_path / "tinynet")

        class Spec:
            shape = [2, 8]
            dtype = "float32"

        paddle.jit.save(net, prefix, input_spec=[Spec()])
        assert os.path.exists(prefix + ".pdiparams")
        assert os.path.exists(prefix + ".stablehlo")
        cfg = paddle.inference.Config(prefix)
        pred = paddle.inference.create_predictor(cfg)
        x = np.random.RandomState(3).randn(2, 8).astype(np.float32)
        out = pred.run([x])[0]
        want = np.asarray(net(paddle.Tensor(x)).value)
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)

    def test_load_inference_model(self, tmp_path):
        net = TinyNet()
        prefix = str(tmp_path / "m")

        class Spec:
            shape = [1, 8]
            dtype = "float32"

        paddle.jit.save(net, prefix, input_spec=[Spec()])
        exe = paddle.static.Executor()
        prog, feed_names, fetch = paddle.static.load_inference_model(
            prefix, exe)
        x = np.random.RandomState(4).randn(1, 8).astype(np.float32)
        out = prog.run([x])[0]
        want = np.asarray(net(paddle.Tensor(x)).value)
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)

    def test_mixed_precision_conversion(self, tmp_path):
        net = TinyNet()
        prefix = str(tmp_path / "fp32")
        paddle.jit.save(net, prefix)
        dst = str(tmp_path / "bf16.pdiparams")
        paddle.inference.convert_to_mixed_precision(
            None, prefix + ".pdiparams", None, dst)
        from paddle_tpu.framework.io import load as fload

        params = fload(dst)
        vals = [v.value if hasattr(v, "value") else v
                for v in params.values()]
        assert all(v.dtype == jnp.bfloat16 for v in vals)


class TestGeneration:
    def _model(self):
        from paddle_tpu.models import LlamaForCausalLM, llama_config

        cfg = llama_config("tiny", num_hidden_layers=2)
        return LlamaForCausalLM(cfg), cfg

    def test_cached_forward_matches_full(self):
        """Prefill+decode through the KV cache must equal the uncached
        forward at every position (reference decode-parity contract)."""
        model, cfg = self._model()
        model.eval()
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (2, 12)).astype(np.int32)
        full = np.asarray(model(paddle.Tensor(ids)).value)

        caches = model.init_cache(2, 16)
        logits_p, caches = model.forward_with_cache(
            paddle.Tensor(ids[:, :8]), caches, 0)
        lp = logits_p.value if hasattr(logits_p, "value") else logits_p
        np.testing.assert_allclose(np.asarray(lp), full[:, :8], rtol=2e-4,
                                   atol=2e-4)
        # decode the remaining 4 tokens one at a time
        for t in range(8, 12):
            logits_d, caches = model.forward_with_cache(
                paddle.Tensor(ids[:, t:t + 1]), caches, t)
            ld = logits_d.value if hasattr(logits_d, "value") else logits_d
            np.testing.assert_allclose(np.asarray(ld)[:, 0], full[:, t],
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=f"pos {t}")

    def test_greedy_generate_matches_naive(self):
        from paddle_tpu.inference.generation import (CausalLMEngine,
                                                     GenerationConfig)

        model, cfg = self._model()
        model.eval()
        rng = np.random.RandomState(1)
        ids = rng.randint(0, cfg.vocab_size, (2, 6)).astype(np.int32)
        eng = CausalLMEngine(model, max_batch=2, max_len=32)
        out = eng.generate(paddle.Tensor(ids),
                           GenerationConfig(max_new_tokens=5))
        assert out.shape == (2, 11)
        # naive greedy: full forward each step
        cur = ids
        for _ in range(5):
            logits = np.asarray(model(paddle.Tensor(cur)).value)
            nxt = logits[:, -1].argmax(-1).astype(np.int32)
            cur = np.concatenate([cur, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(out, cur)

    def test_sampling_modes_run(self):
        from paddle_tpu.inference.generation import (CausalLMEngine,
                                                     GenerationConfig)

        model, cfg = self._model()
        ids = np.random.RandomState(2).randint(
            0, cfg.vocab_size, (1, 4)).astype(np.int32)
        eng = CausalLMEngine(model, max_batch=1, max_len=16)
        for gc in (GenerationConfig(max_new_tokens=3, do_sample=True,
                                    temperature=0.8, seed=1),
                   GenerationConfig(max_new_tokens=3, do_sample=True,
                                    top_k=5, seed=2),
                   GenerationConfig(max_new_tokens=3, do_sample=True,
                                    top_p=0.9, seed=3)):
            out = eng.generate(ids, gc)
            assert out.shape == (1, 7)
            assert (out[:, :4] == ids).all()

    def test_eos_stops(self):
        from paddle_tpu.inference.generation import (CausalLMEngine,
                                                     GenerationConfig)

        model, cfg = self._model()
        ids = np.random.RandomState(3).randint(
            0, cfg.vocab_size, (1, 4)).astype(np.int32)
        eng = CausalLMEngine(model, max_batch=1, max_len=32)
        out = eng.generate(ids, GenerationConfig(max_new_tokens=8,
                                                 eos_token_id=0))
        gen = out[0, 4:]
        hits = np.where(gen == 0)[0]
        if hits.size:  # everything after first EOS must be EOS
            assert (gen[hits[0]:] == 0).all()

    def test_gqa_model_generates(self):
        from paddle_tpu.inference.generation import (CausalLMEngine,
                                                     GenerationConfig)
        from paddle_tpu.models import LlamaForCausalLM, llama_config

        cfg = llama_config("tiny", num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=2)
        model = LlamaForCausalLM(cfg)
        ids = np.random.RandomState(4).randint(
            0, cfg.vocab_size, (2, 5)).astype(np.int32)
        eng = CausalLMEngine(model, max_batch=2, max_len=16)
        out = eng.generate(ids, GenerationConfig(max_new_tokens=4))
        assert out.shape == (2, 9)


class TestScanOverLayers:
    """Scan-over-layers functional llama must match the Layer model exactly
    (fwd, loss, grads) — it is the jit/compile-time architecture bench and
    large-scale training use."""

    def _setup(self):
        from paddle_tpu.distributed.topology import set_mesh
        from paddle_tpu.models import LlamaForCausalLM, llama_config
        from paddle_tpu.models.llama_functional import stack_params

        set_mesh(None)  # other tests may leave a pp/mp mesh installed
        cfg = llama_config("tiny", num_hidden_layers=3,
                           num_attention_heads=4, num_key_value_heads=2)
        model = LlamaForCausalLM(cfg)
        model.eval()
        params = {k: p.value for k, p in model.named_parameters()}
        return cfg, model, params, stack_params(params, cfg)

    def test_forward_parity(self):
        from paddle_tpu.models.llama_functional import forward
        from paddle_tpu.nn.functional_call import functional_call

        cfg, model, params, (stacked, rest) = self._setup()
        ids = np.random.RandomState(0).randint(
            0, cfg.vocab_size, (8, 16)).astype(np.int32)
        got = forward(stacked, rest, ids, cfg, remat=False)
        want = functional_call(model, params, paddle.Tensor(ids))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)

    def test_grad_parity_with_remat(self):
        from paddle_tpu.models.llama_functional import (build_loss_fn,
                                                        unstack_params)
        from paddle_tpu.nn.functional_call import functional_call

        cfg, model, params, (stacked, rest) = self._setup()
        rng = np.random.RandomState(1)
        ids = rng.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32)
        labels = rng.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32)
        lf = build_loss_fn(cfg, remat=True)
        gs, gr = jax.grad(lambda s, r: lf(s, r, ids, labels),
                          argnums=(0, 1))(stacked, rest)
        g_ref = jax.grad(lambda p: functional_call(
            model, p, paddle.Tensor(ids), paddle.Tensor(labels)))(params)
        gu = unstack_params(gs, gr)
        for k in g_ref:
            np.testing.assert_allclose(np.asarray(gu[k]),
                                       np.asarray(g_ref[k]),
                                       rtol=2e-3, atol=2e-4, err_msg=k)

    def test_stack_roundtrip(self):
        from paddle_tpu.models.llama_functional import unstack_params

        cfg, model, params, (stacked, rest) = self._setup()
        rt = unstack_params(stacked, rest)
        assert set(rt) == set(params)
        for k in params:
            np.testing.assert_array_equal(np.asarray(rt[k]),
                                          np.asarray(params[k]))
