"""Long-context feasibility proof: ring attention at 256k-1M tokens,
compile-only (the task brief makes long-context first-class; the
reference snapshot has no context parallelism at all — SURVEY §5.7).

The contract under test: ring attention's score memory is
O(block_q · block_k) per device — never O((S/R)²) — so context length is
bounded by the q/k/v + fp32 accumulator footprint. XLA's buffer
assignment (memory_analysis) is the evidence, same method as the
config-3 proof (tests/test_hybrid_memory.py).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from paddle_tpu.distributed.sequence_parallel import ring_attention
from paddle_tpu.distributed.topology import build_mesh, set_mesh

# minutes-scale compile-only memory-analysis proofs (3 tests, ~45s of
# 256k-1M-token compiles): rides the slow tier (run with -m slow), not
# tier-1 — moved when the prefix-cache suite (round 11) pushed tier-1
# against its 870s timeout
pytestmark = pytest.mark.slow


def _compiled(seq, sp, b=1, h=8, d=128, causal=True, dtype=jnp.bfloat16,
              block=1024):
    mesh = build_mesh(sp=sp, dp=8 // sp)
    set_mesh(mesh)
    sh = NamedSharding(mesh, P(None, "sp", None, None))
    aval = jax.ShapeDtypeStruct((b, seq, h, d), dtype, sharding=sh)

    def f(q, k, v):
        return ring_attention(q, k, v, causal=causal, mesh=mesh,
                              block_q=block, block_k=block)

    return jax.jit(f).lower(aval, aval, aval).compile()


class TestLongContextMemory:
    def test_256k_tokens_sp8(self):
        c = _compiled(256 * 1024, sp=8)
        ma = c.memory_analysis()
        peak = ma.argument_size_in_bytes + ma.temp_size_in_bytes
        # per device: q/k/v 3 x (32k x 8 x 128) bf16 = 192 MiB args,
        # fp32 acc 128 MiB + ring double-buffer; an (S/R)^2 score buffer
        # would be 32k^2 x8 heads x4B = 32 GiB and instantly fail
        assert peak < 4 << 30, peak
        assert ma.temp_size_in_bytes < 2 << 30, ma.temp_size_in_bytes

    @pytest.mark.slow
    def test_1m_tokens_sp8(self):
        c = _compiled(1024 * 1024, sp=8)
        ma = c.memory_analysis()
        peak = ma.argument_size_in_bytes + ma.temp_size_in_bytes
        # 1M tokens / 8 devices = 128k local: args ~768 MiB, acc 512 MiB
        # fp32, ring buffers — comfortably inside one v5p HBM
        assert peak < 8 << 30, peak

    def test_causal_skips_future_ring_steps(self):
        """Causal must RUN substantially faster than full attention: the
        future-source ring steps are skipped at runtime via lax.cond
        (static cost_analysis counts both branches, so wall time is the
        honest signal — expected ~(R+1)/2R ≈ 0.56x work at R=8)."""
        import time

        mesh = build_mesh(sp=8)
        set_mesh(mesh)
        rng = np.random.RandomState(0)
        b, s, h, d = 1, 16384, 4, 64
        q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)

        def timed(causal):
            f = jax.jit(lambda a, k, v: ring_attention(
                a, k, v, causal=causal, mesh=mesh,
                block_q=512, block_k=512))
            f(q, q, q).block_until_ready()       # compile + warm
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                f(q, q, q).block_until_ready()
                best = min(best, time.perf_counter() - t0)
            return best

        t_causal, t_full = timed(True), timed(False)
        assert t_causal < 0.85 * t_full, (t_causal, t_full)


class TestChunkedParity:
    def test_chunked_matches_reference_sdpa(self):
        """The doubly-chunked local path must stay exact (tiny blocks
        force many chunk iterations)."""
        rng = np.random.RandomState(0)
        mesh = build_mesh(sp=4, dp=2)
        set_mesh(mesh)
        b, s, h, d = 2, 64, 2, 16
        q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        for causal in (True, False):
            out = jax.jit(lambda a, b, c, _c=causal: ring_attention(
                a, b, c, causal=_c, mesh=mesh, block_q=8, block_k=4))(
                    q, k, v)
            # dense reference
            sc = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
            if causal:
                mask = np.tril(np.ones((s, s), bool))
                sc = np.where(mask[None, None], sc, -np.inf)
            p = np.exp(sc - sc.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            want = np.einsum("bhqk,bkhd->bqhd", p, v)
            np.testing.assert_allclose(np.asarray(out), want, rtol=2e-4,
                                       atol=2e-4, err_msg=f"causal={causal}")
