"""Static meta-optimizer transform tests.

Reference analogs: fleet/meta_optimizers/{gradient_merge,localsgd,dgc,
lars,fp16_allreduce}_optimizer.py (+ test/collective/fleet counterparts).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.distributed.fleet.meta_optimizers import (
    DGCMomentumOptimizer, FP16AllReduceOptimizer, GradientMergeOptimizer,
    LarsMomentumOptimizer, LocalSGDOptimizer)


def _loss(m, x, y):
    return paddle.mean((m(paddle.to_tensor(x)) - paddle.to_tensor(y)) ** 2)


def _data(rng, n=8):
    return (rng.randn(n, 4).astype(np.float32),
            rng.randn(n, 3).astype(np.float32))


class TestGradientMerge:
    def test_updates_only_every_k_steps(self):
        rng = np.random.RandomState(0)
        m = nn.Linear(4, 3)
        inner = opt.SGD(learning_rate=0.1, parameters=m.parameters())
        gm = GradientMergeOptimizer(inner, k_steps=2, avg=True)
        w0 = np.asarray(m.weight.numpy()).copy()
        x, y = _data(rng)
        _loss(m, x, y).backward()
        gm.step()
        gm.clear_grad()
        np.testing.assert_array_equal(np.asarray(m.weight.numpy()), w0)
        x2, y2 = _data(rng)
        _loss(m, x2, y2).backward()
        gm.step()
        gm.clear_grad()
        assert not np.allclose(np.asarray(m.weight.numpy()), w0)


class TestGradientMergeMath:
    def test_equals_single_step_on_averaged_grads(self):
        rng = np.random.RandomState(2)
        batches = [_data(rng) for _ in range(2)]
        m1 = nn.Linear(4, 3)
        init_w = np.asarray(m1.weight.numpy()).copy()
        init_b = np.asarray(m1.bias.numpy()).copy()
        gm = GradientMergeOptimizer(
            opt.SGD(learning_rate=0.1, parameters=m1.parameters()),
            k_steps=2, avg=True)
        for x, y in batches:
            _loss(m1, x, y).backward()
            gm.step()
            gm.clear_grad()

        m2 = nn.Linear(4, 3)
        m2.weight.set_value(paddle.to_tensor(init_w).value)
        m2.bias.set_value(paddle.to_tensor(init_b).value)
        sgd = opt.SGD(learning_rate=0.1, parameters=m2.parameters())
        loss = (_loss(m2, *batches[0]) + _loss(m2, *batches[1])) / 2
        loss.backward()
        sgd.step()
        np.testing.assert_allclose(np.asarray(m1.weight.numpy()),
                                   np.asarray(m2.weight.numpy()),
                                   rtol=1e-5, atol=1e-6)


class TestDGC:
    def test_masks_gradients_and_converges(self):
        rng = np.random.RandomState(3)
        m = nn.Linear(4, 3)
        inner = opt.Momentum(learning_rate=0.05, momentum=0.9,
                             parameters=m.parameters())
        dgc = DGCMomentumOptimizer.from_momentum(inner, sparsity=0.5)
        x, y = _data(rng, 16)  # fixed batch
        losses = []
        for _ in range(40):
            l = _loss(m, x, y)
            losses.append(float(l.numpy()))
            l.backward()
            dgc.step()
            dgc.clear_grad()
        assert losses[-1] < 0.5 * losses[0]
        # error feedback buffers exist and are nonzero somewhere
        assert any(float(np.abs(np.asarray(e)).sum()) > 0
                   for e in dgc._e.values())

    def test_single_momentum_application(self):
        # DGC with sparsity ramped OFF must match plain Momentum exactly —
        # proving momentum is not applied twice (wrapper + inner)
        rng = np.random.RandomState(8)
        x, y = _data(rng, 16)
        m1, m2 = nn.Linear(4, 3), nn.Linear(4, 3)
        m2.weight.set_value(m1.weight.value)
        m2.bias.set_value(m1.bias.value)
        mom = opt.Momentum(learning_rate=0.05, momentum=0.9,
                           parameters=m1.parameters())
        dgc = DGCMomentumOptimizer(learning_rate=0.05, momentum=0.9,
                                   parameters=m2.parameters(), sparsity=0.5,
                                   rampup_begin_step=1000)
        for _ in range(3):
            _loss(m1, x, y).backward()
            mom.step()
            mom.clear_grad()
            _loss(m2, x, y).backward()
            dgc.step()
            dgc.clear_grad()
        np.testing.assert_allclose(np.asarray(m1.weight.numpy()),
                                   np.asarray(m2.weight.numpy()),
                                   rtol=1e-6, atol=1e-7)

    def test_rampup_passes_through(self):
        rng = np.random.RandomState(4)
        m = nn.Linear(4, 3)
        inner = opt.Momentum(learning_rate=0.05, parameters=m.parameters())
        dgc = DGCMomentumOptimizer.from_momentum(inner, sparsity=0.5,
                                                 rampup_begin_step=100)
        x, y = _data(rng)
        _loss(m, x, y).backward()
        dgc.step()
        assert not dgc._e  # pre-rampup: no compression state


class TestLars:
    def test_trust_ratio_update_reduces_loss(self):
        rng = np.random.RandomState(5)
        m = nn.Linear(4, 3)
        lars = LarsMomentumOptimizer(learning_rate=1.0, momentum=0.9,
                                     lars_coeff=0.1,
                                     parameters=m.parameters())
        x, y = _data(rng, 16)  # fixed batch: loss must actually descend
        losses = []
        for _ in range(40):
            l = _loss(m, x, y)
            losses.append(float(l.numpy()))
            l.backward()
            lars.step()
            lars.clear_grad()
        assert losses[-1] < 0.5 * losses[0]


class TestFP16AllReduce:
    def test_grads_rounded_through_bf16(self):
        rng = np.random.RandomState(6)
        m = nn.Linear(4, 3)
        seen = {}

        class Probe(opt.SGD):
            def step(self):
                for p, g in self._collect_params_grads():
                    if g is not None:
                        seen[id(p)] = np.asarray(g.value)
                super().step()

        inner = Probe(learning_rate=0.1, parameters=m.parameters())
        fp16 = FP16AllReduceOptimizer(inner)
        x, y = _data(rng)
        _loss(m, x, y).backward()
        fp16.step()
        import jax.numpy as jnp

        assert seen
        for g in seen.values():
            rounded = np.asarray(jnp.asarray(g).astype(jnp.bfloat16)
                                 .astype(jnp.float32))
            np.testing.assert_array_equal(g, rounded)


class TestLocalSGD:
    def test_step_and_sync_preserve_replicated_params(self):
        rng = np.random.RandomState(7)
        fleet.init(is_collective=True)
        m = nn.Linear(4, 3)
        inner = opt.SGD(learning_rate=0.1, parameters=m.parameters())
        ls = LocalSGDOptimizer(inner, k_steps=2)
        w_hist = []
        for _ in range(4):
            x, y = _data(rng)
            _loss(m, x, y).backward()
            ls.step()
            ls.clear_grad()
            w_hist.append(np.asarray(m.weight.numpy()).copy())
        # single-controller: params are logically replicated; the dp
        # average must be a no-op on values while steps keep training
        assert not np.allclose(w_hist[0], w_hist[-1])
        assert np.all(np.isfinite(w_hist[-1]))


class TestWrapperDelegation:
    def test_minimize_routes_through_wrapper_step(self):
        # regression: a bound inner minimize would call the RAW step and
        # silently skip gradient merging
        rng = np.random.RandomState(9)
        m = nn.Linear(4, 3)
        gm = GradientMergeOptimizer(
            opt.SGD(learning_rate=0.1, parameters=m.parameters()),
            k_steps=2, avg=True)
        w0 = np.asarray(m.weight.numpy()).copy()
        x, y = _data(rng)
        gm.minimize(_loss(m, x, y))
        gm.clear_grad()
        # first minimize banked the grads — weights must be untouched
        np.testing.assert_array_equal(np.asarray(m.weight.numpy()), w0)
        gm.minimize(_loss(m, x, y))
        assert not np.allclose(np.asarray(m.weight.numpy()), w0)

    def test_state_dict_roundtrip_restores_bank_and_count(self):
        rng = np.random.RandomState(10)
        m = nn.Linear(4, 3)
        gm = GradientMergeOptimizer(
            opt.SGD(learning_rate=0.1, parameters=m.parameters()),
            k_steps=3, avg=True)
        x, y = _data(rng)
        _loss(m, x, y).backward()
        gm.step()  # banked, count=1
        sd = gm.state_dict()
        gm2 = GradientMergeOptimizer(
            opt.SGD(learning_rate=0.1, parameters=m.parameters()),
            k_steps=3, avg=True)
        gm2.set_state_dict(sd)
        assert gm2._count == 1
        assert set(gm2._acc) == set(gm._acc)

    def test_dgc_state_dict_keeps_rampup_and_error_feedback(self):
        rng = np.random.RandomState(11)
        m = nn.Linear(4, 3)
        dgc = DGCMomentumOptimizer(learning_rate=0.05, momentum=0.9,
                                   parameters=m.parameters(), sparsity=0.5)
        x, y = _data(rng)
        for _ in range(3):
            _loss(m, x, y).backward()
            dgc.step()
            dgc.clear_grad()
        sd = dgc.state_dict()
        dgc2 = DGCMomentumOptimizer(learning_rate=0.05, momentum=0.9,
                                    parameters=m.parameters(), sparsity=0.5)
        dgc2.set_state_dict(sd)
        assert dgc2._count == 3
        assert set(dgc2._e) == set(dgc._e)
        # momentum velocity must be restored on a FRESH instance too —
        # a resume that restarts velocity from zero is a different optimizer
        assert "velocity" in dgc2._accumulators
        for pkey, v in dgc._accumulators["velocity"].items():
            np.testing.assert_array_equal(
                np.asarray(dgc2._accumulators["velocity"][pkey]),
                np.asarray(v))

    def test_dgc_rejects_adaptive_optimizers(self):
        fleet.init(is_collective=True)
        m = nn.Linear(4, 3)
        strategy = DistributedStrategy()
        strategy.dgc = True
        with pytest.raises(TypeError, match="Momentum"):
            fleet.distributed_optimizer(
                opt.AdamW(learning_rate=1e-3, parameters=m.parameters()),
                strategy=strategy)


class TestStrategyComposition:
    def test_distributed_optimizer_applies_strategy_transforms(self):
        fleet.init(is_collective=True)
        m = nn.Linear(4, 3)
        strategy = DistributedStrategy()
        strategy.gradient_merge = True
        strategy.gradient_merge_configs = {"k_steps": 4, "avg": True}
        strategy.fp16_allreduce = True
        o = fleet.distributed_optimizer(
            opt.SGD(learning_rate=0.1, parameters=m.parameters()),
            strategy=strategy)
        # unwrap: HybridParallelOptimizer → GradientMerge → FP16 → SGD
        chain = []
        cur = o
        for _ in range(5):
            cur = getattr(cur, "_inner_opt", None)
            if cur is None:
                break
            chain.append(type(cur).__name__)
        assert "GradientMergeOptimizer" in chain
        assert "FP16AllReduceOptimizer" in chain

    def test_lars_strategy_swaps_optimizer(self):
        fleet.init(is_collective=True)
        m = nn.Linear(4, 3)
        strategy = DistributedStrategy()
        strategy.lars = True
        strategy.lars_configs = {"lars_coeff": 0.002}
        o = fleet.distributed_optimizer(
            opt.Momentum(learning_rate=0.1, parameters=m.parameters()),
            strategy=strategy)
        inner = o._inner_opt
        assert isinstance(inner, LarsMomentumOptimizer)
        assert inner._lars_coeff == 0.002
