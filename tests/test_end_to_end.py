"""End-to-end slice: ResNet forward/backward/update + io + jit.to_static.

SURVEY.md §7 step 1 milestone: minimum end-to-end training on one chip with
parity between the eager path and the compiled (to_static) path.
"""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt
from paddle_tpu.io import DataLoader, Dataset, TensorDataset
from paddle_tpu.vision.models import resnet18, resnet50


def t2n(t):
    return np.asarray(t.numpy(), dtype=np.float32)


class TestSaveLoad:
    def test_state_dict_roundtrip_file(self, tmp_path):
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        p = str(tmp_path / "model.pdparams")
        paddle.save(m.state_dict(), p)
        loaded = paddle.load(p)
        m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        m2.set_state_dict(loaded)
        x = paddle.randn([3, 4])
        np.testing.assert_allclose(t2n(m(x)), t2n(m2(x)), rtol=1e-6)

    def test_optimizer_state_save_load(self, tmp_path):
        m = nn.Linear(4, 2)
        o = opt.Adam(0.01, parameters=m.parameters())
        paddle.sum(m(paddle.randn([2, 4]))).backward()
        o.step()
        p = str(tmp_path / "opt.pdopt")
        paddle.save(o.state_dict(), p)
        sd = paddle.load(p)
        assert "global_step" in sd

    def test_nested_structures(self, tmp_path):
        obj = {"a": paddle.to_tensor(np.arange(5)), "b": [1, "x", paddle.ones([2])]}
        p = str(tmp_path / "obj.pkl")
        paddle.save(obj, p)
        back = paddle.load(p)
        np.testing.assert_array_equal(t2n(back["a"]), np.arange(5))
        assert back["b"][1] == "x"


class TestDataLoader:
    def test_tensor_dataset_batching(self):
        xs = paddle.randn([10, 3])
        ys = paddle.arange(10)
        ds = TensorDataset([xs, ys])
        loader = DataLoader(ds, batch_size=4, drop_last=False)
        batches = list(loader)
        assert len(batches) == 3
        assert batches[0][0].shape == [4, 3]
        assert batches[2][0].shape == [2, 3]

    def test_shuffle_covers_all(self):
        class Ds(Dataset):
            def __getitem__(self, i):
                return np.asarray([i], np.int64)

            def __len__(self):
                return 20

        loader = DataLoader(Ds(), batch_size=5, shuffle=True)
        seen = np.sort(np.concatenate([t2n(b).ravel() for b in loader]))
        np.testing.assert_array_equal(seen, np.arange(20))

    def test_num_workers_parallel(self):
        class Ds(Dataset):
            def __getitem__(self, i):
                return np.full((2,), i, np.float32)

            def __len__(self):
                return 16

        loader = DataLoader(Ds(), batch_size=4, num_workers=2)
        batches = list(loader)
        assert len(batches) == 4
        # order must be deterministic (sequential sampler)
        np.testing.assert_allclose(t2n(batches[0])[:, 0], [0, 1, 2, 3])

    def test_distributed_batch_sampler_shards(self):
        from paddle_tpu.io import DistributedBatchSampler

        class Ds(Dataset):
            def __getitem__(self, i):
                return i

            def __len__(self):
                return 8

        s0 = DistributedBatchSampler(Ds(), batch_size=2, num_replicas=2, rank=0)
        s1 = DistributedBatchSampler(Ds(), batch_size=2, num_replicas=2, rank=1)
        i0 = [i for b in s0 for i in b]
        i1 = [i for b in s1 for i in b]
        assert sorted(i0 + i1) == list(range(8))
        assert not set(i0) & set(i1)


class TestToStatic:
    def test_function_parity(self):
        def f(x, y):
            return paddle.matmul(x, y) + paddle.sin(x).sum()

        sf = paddle.jit.to_static(f)
        x = paddle.randn([3, 3])
        y = paddle.randn([3, 3])
        np.testing.assert_allclose(t2n(sf(x, y)), t2n(f(x, y)), rtol=1e-5)

    def test_layer_forward_and_grad_parity(self):
        m1 = nn.Sequential(nn.Linear(4, 8), nn.GELU(), nn.Linear(8, 2))
        m2 = nn.Sequential(nn.Linear(4, 8), nn.GELU(), nn.Linear(8, 2))
        m2.set_state_dict(m1.state_dict())
        sm = paddle.jit.to_static(m2)
        x = paddle.randn([5, 4])

        loss1 = paddle.mean(m1(x) ** 2)
        loss1.backward()
        loss2 = paddle.mean(sm(x) ** 2)
        loss2.backward()
        np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
        for (n1, p1), (n2, p2) in zip(m1.named_parameters(), m2.named_parameters()):
            np.testing.assert_allclose(t2n(p1.grad), t2n(p2.grad),
                                       rtol=1e-4, atol=1e-6)

    def test_batchnorm_buffers_update_through_jit(self):
        m = nn.Sequential(nn.Conv2D(2, 4, 3, padding=1), nn.BatchNorm2D(4))
        sm = paddle.jit.to_static(m)
        before = t2n(m[1]._mean).copy()
        x = paddle.randn([4, 2, 8, 8]) + 3.0
        sm(x)
        after = t2n(m[1]._mean)
        assert not np.allclose(before, after)

    def test_training_flag_recompiles(self):
        m = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.5))
        sm = paddle.jit.to_static(m)
        x = paddle.ones([1, 4])
        m.eval()
        out_eval = t2n(sm(x))
        m.train()
        out_train = t2n(sm(x))
        np.testing.assert_allclose(out_eval, t2n(m[0](x)))
        assert (out_train == 0).any() or not np.allclose(out_train, out_eval)


# ~31s of ResNet compiles (and one pre-existing train-step failure,
# unchanged since seed): rides the slow tier (run with -m slow) —
# moved when the prefix-cache suite (round 11) pushed tier-1 against
# its 870s timeout; the cheap save/load, to_static, and dataloader
# end-to-end tests stay tier-1
@pytest.mark.slow
class TestResNetEndToEnd:
    def test_resnet18_train_step_decreases_loss(self):
        model = resnet18(num_classes=10)
        model.train()
        o = opt.Momentum(0.05, 0.9, parameters=model.parameters())
        x = paddle.randn([4, 3, 32, 32])
        y = paddle.to_tensor(np.random.randint(0, 10, (4,)))
        ce = nn.CrossEntropyLoss()
        losses = []
        for _ in range(4):
            logits = model(x)
            loss = ce(logits, y)
            loss.backward()
            o.step()
            o.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_resnet50_forward_shape(self):
        model = resnet50(num_classes=100)
        model.eval()
        out = model(paddle.randn([2, 3, 64, 64]))
        assert out.shape == [2, 100]

    def test_resnet18_jitted_step_matches_eager(self):
        m1 = resnet18(num_classes=5)
        m2 = resnet18(num_classes=5)
        m2.set_state_dict(m1.state_dict())
        for m in (m1, m2):
            m.eval()  # freeze BN for exact parity
        sm2 = paddle.jit.to_static(m2)
        x = paddle.randn([2, 3, 32, 32])
        y = paddle.to_tensor(np.array([1, 3]))
        ce = nn.CrossEntropyLoss()

        l1 = ce(m1(x), y)
        l1.backward()
        l2 = ce(sm2(x), y)
        l2.backward()
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-4)
        g1 = t2n(m1.conv1.weight.grad)
        g2 = t2n(m2.conv1.weight.grad)
        np.testing.assert_allclose(g1, g2, rtol=1e-3, atol=1e-6)

    def test_full_loop_with_dataloader(self):
        xs = np.random.randn(16, 3, 16, 16).astype(np.float32)
        ys = np.random.randint(0, 4, (16,))

        class Ds(Dataset):
            def __getitem__(self, i):
                return xs[i], ys[i]

            def __len__(self):
                return 16

        model = resnet18(num_classes=4)
        model.train()
        o = opt.Adam(1e-3, parameters=model.parameters())
        ce = nn.CrossEntropyLoss()
        loader = DataLoader(Ds(), batch_size=8, shuffle=True)
        for xb, yb in loader:
            loss = ce(model(xb), yb)
            loss.backward()
            o.step()
            o.clear_grad()
        assert np.isfinite(float(loss))
