"""Static-graph tests (reference analogs: test/legacy_test/test_executor_*,
test_program.py): record/compose/run, feeds+fetches, training via
minimize, append_backward grad fetch, program_guard isolation, save/load."""
import numpy as np
import pytest

import paddle_tpu as paddle


@pytest.fixture
def static_mode():
    paddle.enable_static()
    # fresh programs per test
    from paddle_tpu.static.program import Program, static_state

    static_state.main_program = Program()
    static_state.startup_program = Program()
    yield
    paddle.disable_static()


class TestRecordRun:
    def test_simple_forward(self, static_mode):
        x = paddle.static.data("x", [None, 4])
        y = paddle.tanh(x)
        exe = paddle.static.Executor()
        X = np.random.randn(3, 4).astype(np.float32)
        (out,) = exe.run(feed={"x": X}, fetch_list=[y])
        np.testing.assert_allclose(out, np.tanh(X), rtol=1e-6)

    def test_multiple_fetches(self, static_mode):
        x = paddle.static.data("x", [None, 4])
        a = paddle.exp(x)
        b = a + 1.0
        exe = paddle.static.Executor()
        X = np.zeros((2, 4), np.float32)
        out_a, out_b = exe.run(feed={"x": X}, fetch_list=[a, b])
        np.testing.assert_allclose(out_a, np.ones((2, 4)))
        np.testing.assert_allclose(out_b, np.full((2, 4), 2.0))

    def test_layer_params_become_state(self, static_mode):
        from paddle_tpu import nn

        x = paddle.static.data("x", [None, 8])
        lin = nn.Linear(8, 2)
        out = lin(x)
        prog = paddle.static.default_main_program()
        assert len(prog.param_vars) == 2  # weight + bias
        exe = paddle.static.Executor()
        X = np.random.randn(4, 8).astype(np.float32)
        (o,) = exe.run(feed={"x": X}, fetch_list=[out])
        ref = X @ lin.weight.numpy() + lin.bias.numpy()
        np.testing.assert_allclose(o, ref, rtol=1e-5)

    def test_dynamic_batch_dim(self, static_mode):
        x = paddle.static.data("x", [None, 4])
        y = x * 2.0
        exe = paddle.static.Executor()
        for bs in (2, 5):
            (out,) = exe.run(
                feed={"x": np.ones((bs, 4), np.float32)}, fetch_list=[y])
            assert out.shape == (bs, 4)

    def test_eager_unaffected_after_disable(self, static_mode):
        paddle.disable_static()
        t = paddle.tanh(paddle.ones([2]))
        assert float(t.sum()) > 0  # concrete execution
        paddle.enable_static()


class TestStaticTraining:
    def _build(self, opt_cls, **kw):
        from paddle_tpu import nn

        x = paddle.static.data("x", [None, 13])
        y = paddle.static.data("y", [None, 1])
        lin = nn.Linear(13, 1)
        loss = ((lin(x) - y) ** 2).mean()
        opt = opt_cls(**kw)
        opt.minimize(loss)
        return loss

    def _train(self, loss, steps=40):
        exe = paddle.static.Executor()
        exe.run(paddle.static.default_startup_program())
        rng = np.random.RandomState(0)
        X = rng.randn(32, 13).astype(np.float32)
        Y = X @ rng.randn(13, 1).astype(np.float32)
        losses = []
        for _ in range(steps):
            (l,) = exe.run(feed={"x": X, "y": Y}, fetch_list=[loss])
            losses.append(float(l))
        return losses

    def test_sgd_minimize(self, static_mode):
        from paddle_tpu.optimizer import SGD

        losses = self._train(self._build(SGD, learning_rate=0.05))
        assert losses[-1] < losses[0] * 0.2

    def test_adamw_minimize(self, static_mode):
        from paddle_tpu.optimizer import AdamW

        losses = self._train(self._build(AdamW, learning_rate=0.05))
        assert losses[-1] < losses[0] * 0.5

    def test_lr_change_takes_effect(self, static_mode):
        """LR is a traced argument: set_lr between runs must change the
        update magnitude without re-tracing."""
        from paddle_tpu import nn
        from paddle_tpu.optimizer import SGD

        x = paddle.static.data("x", [None, 4])
        lin = nn.Linear(4, 1, bias_attr=False)
        loss = (lin(x) ** 2).mean()
        opt = SGD(learning_rate=0.1)
        opt.minimize(loss)
        exe = paddle.static.Executor()
        exe.run(paddle.static.default_startup_program())
        X = np.ones((2, 4), np.float32)
        w0 = np.array(paddle.static.global_scope().vars.get(
            lin.weight.name, lin.weight.numpy()))
        exe.run(feed={"x": X}, fetch_list=[loss])
        step1 = np.abs(lin.weight.numpy() - w0).max()
        opt.set_lr(0.0)  # freeze
        w1 = lin.weight.numpy().copy()
        exe.run(feed={"x": X}, fetch_list=[loss])
        assert step1 > 0
        np.testing.assert_array_equal(lin.weight.numpy(), w1)

    def test_param_objs_stay_synced(self, static_mode):
        from paddle_tpu import nn
        from paddle_tpu.optimizer import SGD

        x = paddle.static.data("x", [None, 4])
        lin = nn.Linear(4, 1)
        w0 = lin.weight.numpy().copy()
        loss = (lin(x) ** 2).mean()
        SGD(learning_rate=0.1).minimize(loss)
        exe = paddle.static.Executor()
        exe.run(paddle.static.default_startup_program())
        exe.run(feed={"x": np.ones((2, 4), np.float32)}, fetch_list=[loss])
        assert not np.allclose(lin.weight.numpy(), w0)


class TestAppendBackward:
    def test_grad_fetch(self, static_mode):
        from paddle_tpu import nn

        x = paddle.static.data("x", [None, 4])
        lin = nn.Linear(4, 1, bias_attr=False)
        loss = (lin(x) ** 2).mean()
        pg = paddle.static.append_backward(loss)
        assert len(pg) == 1
        p, g = pg[0]
        exe = paddle.static.Executor()
        X = np.random.randn(8, 4).astype(np.float32)
        l, gw = exe.run(feed={"x": X}, fetch_list=[loss, g])
        # numeric check: dL/dW = 2/N * X^T (XW)
        W = lin.weight.numpy()
        ref = 2.0 * X.T @ (X @ W) / X.shape[0] / W.shape[1]
        np.testing.assert_allclose(gw, ref, rtol=1e-4)


class TestProgramGuard:
    def test_isolation(self, static_mode):
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data("x", [None, 2])
            y = x + 1.0
        assert len(main.nodes) == 1
        assert len(paddle.static.default_main_program().nodes) == 0

    def test_clone_for_test_drops_train_config(self, static_mode):
        from paddle_tpu import nn
        from paddle_tpu.optimizer import SGD

        x = paddle.static.data("x", [None, 2])
        loss = (nn.Linear(2, 1)(x) ** 2).mean()
        SGD(learning_rate=0.1).minimize(loss)
        prog = paddle.static.default_main_program()
        test_prog = prog.clone(for_test=True)
        assert prog.train_config is not None
        assert test_prog.train_config is None


class TestScopeGuard:
    def test_scope_isolation(self, static_mode):
        from paddle_tpu import nn
        from paddle_tpu.static import Scope, scope_guard

        x = paddle.static.data("x", [None, 4])
        lin = nn.Linear(4, 1, bias_attr=False)
        out = lin(x)
        exe = paddle.static.Executor()
        X = np.ones((2, 4), np.float32)
        s1, s2 = Scope(), Scope()
        with scope_guard(s1):
            exe.run(feed={"x": X}, fetch_list=[out])
        with scope_guard(s2):
            exe.run(feed={"x": X}, fetch_list=[out])
        # each scope holds its own copy of the weight; the default global
        # scope was never touched
        assert s1.var(lin.weight.name) is not None
        assert s2.var(lin.weight.name) is not None
        assert paddle.static.global_scope().var(lin.weight.name) is None

    def test_mode_flags(self, static_mode):
        assert not paddle.in_dynamic_mode()
        paddle.disable_static()
        assert paddle.in_dynamic_mode()
        paddle.enable_static()


class TestStaticIO:
    def test_save_load_roundtrip(self, static_mode, tmp_path):
        from paddle_tpu import nn

        x = paddle.static.data("x", [None, 4])
        lin = nn.Linear(4, 2)
        out = lin(x)
        prog = paddle.static.default_main_program()
        exe = paddle.static.Executor()
        exe.run(paddle.static.default_startup_program())
        path = str(tmp_path / "model")
        paddle.static.save(prog, path)
        w0 = lin.weight.numpy().copy()
        lin.weight.set_value(np.zeros_like(w0))
        paddle.static.global_scope().set(lin.weight.name,
                                         np.zeros_like(w0))
        paddle.static.load(prog, path)
        np.testing.assert_array_equal(lin.weight.numpy(), w0)


def test_static_input_gradients(static_mode):
    """paddle.static.gradients wrt feed vars (reference static autodiff)."""
    import numpy as np

    from paddle_tpu import nn

    if True:
        x = paddle.static.data("xg", [4, 3], "float32")
        lin = nn.Linear(3, 2, bias_attr=False)
        y = lin(x)
        out = paddle.sum(y * y)
        (gx,) = paddle.static.gradients([out], [x])
        exe = paddle.static.Executor()
        exe.run(paddle.static.default_startup_program())
        xv = np.random.RandomState(0).randn(4, 3).astype(np.float32)
        res = exe.run(feed={"xg": xv}, fetch_list=[out, gx])
        wv = np.asarray(lin.weight.value)
        want = 2 * (xv @ wv) @ wv.T
        np.testing.assert_allclose(res[1], want, rtol=1e-4, atol=1e-5)
