"""Distributed pass framework tests (reference distributed/passes:
new_pass/PassManager/PassContext + the auto_parallel pass set)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.passes import (PassContext, PassManager,
                                           new_pass)


@pytest.fixture
def static_mode():
    paddle.enable_static()
    from paddle_tpu.static.program import Program, static_state

    static_state.main_program = Program()
    static_state.startup_program = Program()
    yield
    paddle.disable_static()


def _prog(h=8, o=4):
    x = paddle.static.data("x", [None, h])
    out = paddle.tanh(nn.Linear(h, o)(x))
    return out, paddle.static.default_main_program()


class TestRegistry:
    def test_unknown_pass_raises(self):
        with pytest.raises(ValueError, match="unknown pass"):
            new_pass("no_such_pass")

    def test_manager_names(self):
        pm = PassManager([new_pass("auto_parallel_amp"),
                          new_pass("auto_parallel_recompute")])
        assert pm.names == ["auto_parallel_amp", "auto_parallel_recompute"]


class TestAMPPass(object):
    def test_bf16_compute_close_not_identical(self, static_mode):
        out, prog = _prog()
        amped = new_pass("auto_parallel_amp").apply(prog)
        exe = paddle.static.Executor()
        X = np.random.RandomState(0).randn(8, 8).astype(np.float32) * 3
        (ref,) = exe.run(prog, feed={"x": X}, fetch_list=[out])
        (got,) = exe.run(amped, feed={"x": X}, fetch_list=[out])
        assert got.dtype == np.float32          # casts back at op edges
        err = np.abs(got - ref).max()
        assert 0 < err < 0.1, err               # bf16 compute really ran
        assert len(prog.nodes) == len(amped.nodes)  # in-place wrap, no new ops

    def test_context_attr_set(self, static_mode):
        _, prog = _prog()
        pm = PassManager([new_pass("auto_parallel_amp")])
        pm.apply(prog)
        assert pm.context.get_attr("amp_applied") is True


class TestRecomputePass(object):
    def test_numerics_unchanged_and_counted(self, static_mode):
        out, prog = _prog()
        ctx = PassContext()
        rp = new_pass("auto_parallel_recompute")
        rc = rp.apply(prog, None, ctx)
        exe = paddle.static.Executor()
        X = np.random.RandomState(1).randn(4, 8).astype(np.float32)
        (ref,) = exe.run(prog, feed={"x": X}, fetch_list=[out])
        (got,) = exe.run(rc, feed={"x": X}, fetch_list=[out])
        np.testing.assert_allclose(got, ref, rtol=1e-6)
        assert ctx.get_attr("recomputed_ops") == 1   # the linear node

    def test_trains_through_recompute(self, static_mode):
        x = paddle.static.data("x", [None, 8])
        y = paddle.static.data("y", [None, 1])
        pred = nn.Linear(8, 1)(x)
        loss = paddle.mean((pred - y) ** 2)
        prog = paddle.static.default_main_program()
        rc = new_pass("auto_parallel_recompute").apply(prog)
        from paddle_tpu.optimizer import SGD

        with paddle.static.program_guard(rc):
            SGD(learning_rate=0.1).minimize(loss)
        exe = paddle.static.Executor()
        rng = np.random.RandomState(0)
        X = rng.randn(16, 8).astype(np.float32)
        Y = X @ rng.randn(8, 1).astype(np.float32)
        losses = [float(exe.run(rc, feed={"x": X, "y": Y},
                                fetch_list=[loss])[0]) for _ in range(15)]
        assert losses[-1] < losses[0] * 0.7, losses[::5]


class TestQuantizationPass(object):
    def test_delegates_to_qat_transform(self, static_mode):
        _, prog = _prog()
        q = new_pass("auto_parallel_quantization",
                     {"weight_bits": 8}).apply(prog)
        names = [n.name for n in q.nodes]
        assert "fake_quantize_dequantize_absmax" in names


class TestCloneSemantics:
    """Review regressions: pass clones must keep grad fetch + opt state."""

    def test_grad_fetch_survives_transform(self, static_mode):
        x = paddle.static.data("x", [None, 8])
        lin = nn.Linear(8, 1)
        loss = paddle.mean(lin(x) ** 2)
        prog = paddle.static.default_main_program()
        from paddle_tpu.static import append_backward

        with paddle.static.program_guard(prog):
            grads = append_backward(loss)
        fetch = next(g for p, g in grads if p is lin.weight)
        rc = new_pass("auto_parallel_recompute").apply(prog)
        exe = paddle.static.Executor()
        X = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        (g_ref,) = exe.run(prog, feed={"x": X}, fetch_list=[fetch])
        (g_rc,) = exe.run(rc, feed={"x": X}, fetch_list=[fetch])
        assert g_ref.shape == tuple(lin.weight.shape)
        np.testing.assert_allclose(g_rc, g_ref, rtol=1e-5)

    def test_opt_state_survives_transform(self, static_mode):
        x = paddle.static.data("x", [None, 8])
        y = paddle.static.data("y", [None, 1])
        loss = paddle.mean((nn.Linear(8, 1)(x) - y) ** 2)
        prog = paddle.static.default_main_program()
        from paddle_tpu.optimizer import Adam

        with paddle.static.program_guard(prog):
            Adam(learning_rate=0.05).minimize(loss)
        exe = paddle.static.Executor()
        rng = np.random.RandomState(0)
        X = rng.randn(16, 8).astype(np.float32)
        Y = X @ rng.randn(8, 1).astype(np.float32)
        for _ in range(5):
            exe.run(prog, feed={"x": X, "y": Y}, fetch_list=[loss])
        from paddle_tpu.static.program import global_scope

        key = f"__opt_state_{prog._origin_id}" if hasattr(
            prog, "_origin_id") else f"__opt_state_{prog.id}"
        st_before = global_scope().var(key)
        rc = new_pass("auto_parallel_recompute").apply(prog)
        (l1,) = exe.run(rc, feed={"x": X, "y": Y}, fetch_list=[loss])
        st_after = global_scope().var(key)
        assert st_after is not None
        # moments continued, not re-zeroed: step counter advanced past 1
        import jax

        leaves = jax.tree.leaves(st_after)
        assert any(np.asarray(l).size == 1 and float(np.asarray(l)) >= 6
                   for l in leaves), "optimizer step count should be >= 6"

    def test_fp16_alias_uses_float16(self, static_mode):
        import jax.numpy as jnp

        _, prog = _prog()
        p = new_pass("auto_parallel_fp16")
        # peek at the chosen dtype through a probe node run
        amped = p.apply(prog)
        seen = {}
        orig_fn = amped.nodes[0].fn

        def probe(*flat):
            out = orig_fn(*flat)
            return out

        # indirect check: pass name resolved and default dtype is fp16
        assert p.name == "auto_parallel_fp16"
        assert p.get_attr("dtype", "float16") == "float16"


class TestStaticAMP:
    """paddle.static.amp surface (reference static/amp: decorate /
    CustomOpLists / cast_model_to_fp16 / fp16_guard)."""

    def test_cast_model_to_bf16_runs_close(self, static_mode):
        out, prog = _prog()
        from paddle_tpu.static import amp as samp

        casted = samp.cast_model_to_bf16(prog)
        exe = paddle.static.Executor()
        X = np.random.RandomState(0).randn(8, 8).astype(np.float32) * 3
        (ref,) = exe.run(prog, feed={"x": X}, fetch_list=[out])
        (got,) = exe.run(casted, feed={"x": X}, fetch_list=[out])
        err = np.abs(got - ref).max()
        assert 0 < err < 0.1, err

    def test_decorated_optimizer_trains(self, static_mode):
        from paddle_tpu.static import amp as samp

        x = paddle.static.data("x", [None, 8])
        y = paddle.static.data("y", [None, 1])
        loss = paddle.mean((nn.Linear(8, 1)(x) - y) ** 2)
        from paddle_tpu.optimizer import SGD

        opt = samp.decorate(SGD(learning_rate=0.1), dtype="bfloat16")
        assert opt.get_loss_scaling() == 1.0
        opt.minimize(loss)
        prog = paddle.static.default_main_program()
        exe = paddle.static.Executor()
        rng = np.random.RandomState(0)
        X = rng.randn(32, 8).astype(np.float32)
        Y = X @ rng.randn(8, 1).astype(np.float32)
        losses = [float(exe.run(prog, feed={"x": X, "y": Y},
                                fetch_list=[loss])[0]) for _ in range(20)]
        assert losses[-1] < losses[0] * 0.5, losses[::5]

    def test_op_lists_and_guard(self, static_mode):
        from paddle_tpu.static import amp as samp

        lists = samp.CustomOpLists(custom_white_list=["tanh"],
                                   custom_black_list=["softmax"])
        assert "tanh" in lists.white_list
        assert "softmax" not in lists.white_list
        with samp.fp16_guard():
            pass  # parity surface; records fine

    def test_cast_parameters(self, static_mode):
        import jax.numpy as jnp

        _, prog = _prog()
        from paddle_tpu.static import amp as samp

        samp.cast_parameters_to_bf16(program=prog)
        for name, p in prog.param_objs.items():
            if hasattr(p, "_value"):
                assert p._value.dtype == jnp.bfloat16, name


class TestAMPBlackList:
    def test_black_list_blocks_cast(self, static_mode):
        out, prog = _prog()
        amped = new_pass("auto_parallel_amp",
                         {"custom_black_list": ["linear"]}).apply(prog)
        exe = paddle.static.Executor()
        X = np.random.RandomState(0).randn(8, 8).astype(np.float32) * 3
        (ref,) = exe.run(prog, feed={"x": X}, fetch_list=[out])
        (got,) = exe.run(amped, feed={"x": X}, fetch_list=[out])
        # linear was the only white op in this program: with it black-
        # listed the pass is an exact no-op
        np.testing.assert_array_equal(got, ref)

    def test_decorated_minimize_returns_casted_program(self, static_mode):
        from paddle_tpu.static import amp as samp

        x = paddle.static.data("x", [None, 8])
        y = paddle.static.data("y", [None, 1])
        loss = paddle.mean((nn.Linear(8, 1)(x) - y) ** 2)
        from paddle_tpu.optimizer import SGD

        opt = samp.decorate(SGD(learning_rate=0.1), dtype="bfloat16")
        opt.minimize(loss)
        assert opt.program is not None
        assert opt.program is paddle.static.default_main_program()
