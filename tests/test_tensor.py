"""Tensor facade + op surface tests (reference pattern: OpTest check_output,
test/legacy_test/eager_op_test.py:2193)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_basic():
    t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert t.shape == [2, 2]
    assert t.dtype == paddle.float32
    np.testing.assert_allclose(t.numpy(), [[1, 2], [3, 4]])
    assert t.stop_gradient


def test_scalar_and_int_dtypes():
    assert paddle.to_tensor(3).dtype == paddle.int64
    assert paddle.to_tensor(3.5).dtype == paddle.float32
    assert paddle.to_tensor(np.float64(1.5)).dtype == paddle.float64


def test_arithmetic_dunders():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([4.0, 5.0, 6.0])
    np.testing.assert_allclose((a + b).numpy(), [5, 7, 9])
    np.testing.assert_allclose((a - b).numpy(), [-3, -3, -3])
    np.testing.assert_allclose((a * b).numpy(), [4, 10, 18])
    np.testing.assert_allclose((b / a).numpy(), [4, 2.5, 2])
    np.testing.assert_allclose((a**2).numpy(), [1, 4, 9])
    np.testing.assert_allclose((2 + a).numpy(), [3, 4, 5])
    np.testing.assert_allclose((-a).numpy(), [-1, -2, -3])


def test_matmul():
    a = paddle.to_tensor(np.random.randn(3, 4).astype("float32"))
    b = paddle.to_tensor(np.random.randn(4, 5).astype("float32"))
    np.testing.assert_allclose(
        paddle.matmul(a, b).numpy(), a.numpy() @ b.numpy(), rtol=1e-5
    )
    np.testing.assert_allclose(
        paddle.matmul(a, a, transpose_y=True).numpy(),
        a.numpy() @ a.numpy().T,
        rtol=1e-5,
    )


def test_reductions():
    x = paddle.to_tensor(np.arange(24, dtype="float32").reshape(2, 3, 4))
    np.testing.assert_allclose(paddle.sum(x).numpy(), x.numpy().sum())
    np.testing.assert_allclose(
        paddle.mean(x, axis=1).numpy(), x.numpy().mean(axis=1)
    )
    np.testing.assert_allclose(
        paddle.max(x, axis=[0, 2]).numpy(), x.numpy().max(axis=(0, 2))
    )
    np.testing.assert_allclose(
        x.sum(axis=-1, keepdim=True).numpy(), x.numpy().sum(-1, keepdims=True)
    )


def test_manipulation():
    x = paddle.arange(12, dtype="float32")
    y = paddle.reshape(x, [3, 4])
    assert y.shape == [3, 4]
    z = paddle.transpose(y, [1, 0])
    assert z.shape == [4, 3]
    c = paddle.concat([y, y], axis=0)
    assert c.shape == [6, 4]
    s = paddle.split(c, 3, axis=0)
    assert len(s) == 3 and s[0].shape == [2, 4]
    st = paddle.stack([y, y], axis=0)
    assert st.shape == [2, 3, 4]
    assert paddle.squeeze(paddle.unsqueeze(y, 0), 0).shape == [3, 4]
    assert paddle.flatten(st, 1).shape == [2, 12]
    assert paddle.tile(y, [2, 1]).shape == [6, 4]


def test_indexing():
    x = paddle.to_tensor(np.arange(20, dtype="float32").reshape(4, 5))
    np.testing.assert_allclose(x[1].numpy(), np.arange(5, 10))
    np.testing.assert_allclose(x[1:3, 2].numpy(), [7, 12])
    np.testing.assert_allclose(x[:, -1].numpy(), [4, 9, 14, 19])
    idx = paddle.to_tensor([0, 2])
    np.testing.assert_allclose(paddle.gather(x, idx, axis=0).numpy(), x.numpy()[[0, 2]])


def test_setitem():
    x = paddle.zeros([3, 3])
    x[1] = 5.0
    np.testing.assert_allclose(x.numpy()[1], [5, 5, 5])
    x[0, 0] = 1.0
    assert x.numpy()[0, 0] == 1.0


def test_comparison_and_logic():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([3.0, 2.0, 1.0])
    np.testing.assert_array_equal((a < b).numpy(), [True, False, False])
    np.testing.assert_array_equal((a == b).numpy(), [False, True, False])
    assert bool(paddle.allclose(a, a))
    assert (a < b).stop_gradient


def test_cast():
    x = paddle.to_tensor([1.5, 2.5])
    y = x.astype("int32")
    assert y.dtype == paddle.int32
    z = x.astype(paddle.bfloat16)
    assert z.dtype == paddle.bfloat16


def test_search_ops():
    x = paddle.to_tensor([[3.0, 1.0, 2.0], [6.0, 5.0, 4.0]])
    np.testing.assert_array_equal(paddle.argmax(x, axis=1).numpy(), [0, 0])
    v, i = paddle.topk(x, 2, axis=1)
    np.testing.assert_allclose(v.numpy(), [[3, 2], [6, 5]])
    np.testing.assert_array_equal(i.numpy(), [[0, 2], [0, 1]])
    s = paddle.sort(x, axis=1)
    np.testing.assert_allclose(s.numpy(), np.sort(x.numpy(), axis=1))


def test_where_and_masked():
    x = paddle.to_tensor([1.0, -2.0, 3.0])
    y = paddle.where(x > 0, x, paddle.zeros_like(x))
    np.testing.assert_allclose(y.numpy(), [1, 0, 3])
    m = paddle.masked_select(x, x > 0)
    np.testing.assert_allclose(m.numpy(), [1, 3])


def test_creation_ops():
    assert paddle.zeros([2, 3]).numpy().sum() == 0
    assert paddle.ones([2, 3], dtype="int32").dtype == paddle.int32
    np.testing.assert_allclose(paddle.full([2], 7.0).numpy(), [7, 7])
    np.testing.assert_allclose(paddle.arange(0, 10, 2).numpy(), [0, 2, 4, 6, 8])
    np.testing.assert_allclose(np.diagonal(paddle.eye(3).numpy()), [1, 1, 1])
    tri = paddle.tril(paddle.ones([3, 3]))
    assert tri.numpy()[0, 2] == 0 and tri.numpy()[2, 0] == 1


def test_random_reproducible():
    paddle.seed(42)
    a = paddle.rand([4, 4])
    paddle.seed(42)
    b = paddle.rand([4, 4])
    np.testing.assert_allclose(a.numpy(), b.numpy())
    c = paddle.rand([4, 4])
    assert not np.allclose(b.numpy(), c.numpy())


def test_inplace_guards():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 2
    with pytest.raises(RuntimeError):
        y.add_(paddle.to_tensor([1.0, 1.0]))
    with paddle.no_grad():
        x.add_(paddle.to_tensor([1.0, 1.0]))
    np.testing.assert_allclose(x.numpy(), [2, 3])


def test_einsum():
    a = paddle.to_tensor(np.random.randn(2, 3).astype("float32"))
    b = paddle.to_tensor(np.random.randn(3, 4).astype("float32"))
    out = paddle.einsum("ij,jk->ik", a, b)
    np.testing.assert_allclose(out.numpy(), a.numpy() @ b.numpy(), rtol=1e-5)
