"""Program-level quantization pass tests (VERDICT r2 #9).

Reference contract (static/quantization/quantization_pass.py): the
transform pass inserts fake-quant ops in front of quantizable ops, the
QAT'd program still trains (STE), and the freeze pass rewrites weight
quants to fixed calibrated scales — a full quantize-program round trip.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.static.quantization import (QuantizationFreezePass,
                                            QuantizationTransformPass,
                                            convert, quant_aware)


@pytest.fixture
def static_mode():
    paddle.enable_static()
    from paddle_tpu.static.program import Program, static_state

    static_state.main_program = Program()
    static_state.startup_program = Program()
    yield
    paddle.disable_static()


def _build_linear_prog(h=8, o=4):
    x = paddle.static.data("x", [None, h])
    lin = nn.Linear(h, o)
    out = paddle.tanh(lin(x))
    return x, lin, out


class TestTransformPass:
    def test_inserts_fake_quant_nodes(self, static_mode):
        _, _, out = _build_linear_prog()
        prog = paddle.static.default_main_program()
        n_before = len(prog.nodes)
        qprog = quant_aware(prog)
        # linear has 3 float inputs (x, W, b) -> 3 inserted quant nodes
        assert qprog._quant_inserted == 3
        assert len(qprog.nodes) == n_before + 3
        assert len(prog.nodes) == n_before  # original untouched
        names = [n.name for n in qprog.nodes]
        assert names.count("fake_quantize_dequantize_absmax") == 3

    def test_quantized_forward_close_but_not_identical(self, static_mode):
        x, lin, out = _build_linear_prog()
        prog = paddle.static.default_main_program()
        qprog = quant_aware(prog)
        exe = paddle.static.Executor()
        X = np.random.RandomState(0).randn(16, 8).astype(np.float32)
        (ref,) = exe.run(prog, feed={"x": X}, fetch_list=[out])
        (q,) = exe.run(qprog, feed={"x": X}, fetch_list=[out])
        err = np.abs(q - ref).max()
        assert 0 < err < 0.1, err  # int8 sim: close, not bit-equal

    def test_non_quantizable_ops_untouched(self, static_mode):
        x = paddle.static.data("x", [None, 4])
        y = paddle.tanh(paddle.exp(x))
        prog = paddle.static.default_main_program()
        qprog = quant_aware(prog)
        assert qprog._quant_inserted == 0
        assert len(qprog.nodes) == len(prog.nodes)


class TestQATTrains:
    def test_minimize_through_fake_quant(self, static_mode):
        """STE: the QAT'd program must still reduce the loss."""
        x = paddle.static.data("x", [None, 8])
        y = paddle.static.data("y", [None, 1])
        lin = nn.Linear(8, 1)
        pred = lin(x)
        loss = paddle.mean((pred - y) ** 2)
        prog = paddle.static.default_main_program()
        qprog = quant_aware(prog)
        from paddle_tpu.optimizer import SGD

        with paddle.static.program_guard(qprog):
            SGD(learning_rate=0.1).minimize(loss)
        exe = paddle.static.Executor()
        rng = np.random.RandomState(0)
        X = rng.randn(32, 8).astype(np.float32)
        W = rng.randn(8, 1).astype(np.float32)
        Y = X @ W
        losses = []
        for _ in range(25):
            (l,) = exe.run(qprog, feed={"x": X, "y": Y},
                           fetch_list=[loss])
            losses.append(float(l))
        assert losses[-1] < losses[0] * 0.5, losses[::6]


class TestFreezePass:
    def test_weight_scales_frozen(self, static_mode):
        x, lin, out = _build_linear_prog()
        prog = paddle.static.default_main_program()
        qprog = quant_aware(prog)
        exe = paddle.static.Executor()
        X = np.random.RandomState(1).randn(4, 8).astype(np.float32)
        exe.run(qprog, feed={"x": X}, fetch_list=[out])  # calibrate scope
        fprog = convert(qprog)
        # weight + bias scales recorded; frozen nodes present
        assert len(fprog._quant_scales) == 2
        for pname, s in fprog._quant_scales.items():
            assert s > 0  # zero-init bias clamps to the epsilon scale
        names = [n.name for n in fprog.nodes]
        assert names.count("fake_quantize_dequantize_frozen") == 2
        assert names.count("fake_quantize_dequantize_absmax") == 1  # act
        # frozen program runs and matches the dynamic-quant forward (scales
        # identical while weights unchanged)
        (q,) = exe.run(qprog, feed={"x": X}, fetch_list=[out])
        (f,) = exe.run(fprog, feed={"x": X}, fetch_list=[out])
        np.testing.assert_allclose(f, q, rtol=1e-5, atol=1e-6)


class TestSharedVarDedup:
    def test_shared_input_quantized_once(self, static_mode):
        x = paddle.static.data("x", [None, 8])
        a = nn.Linear(8, 4)(x)
        b = nn.Linear(8, 4)(x)   # same activation feeds two matmuls
        out = a + b
        prog = paddle.static.default_main_program()
        qprog = quant_aware(prog)
        # x quantized ONCE (reference dequantized_vars cache), each linear's
        # own W/b once -> 1 + 2*2 = 5, not 6
        assert qprog._quant_inserted == 5
