"""ERNIE-MoE model family tests (BASELINE config 4 as a real model).

Contract: bidirectional encoder forward, MoE layers on the configured
cadence, MLM loss (with GShard aux) trains, and the expert dim composes
with the ep mesh axis.
"""
import numpy as np
import pytest

# minutes-scale multi-device/parity suite on the CPU backend:
# rides the slow tier (run with -m slow), not tier-1
pytestmark = pytest.mark.slow

import jax
import paddle_tpu as paddle
from paddle_tpu.distributed.topology import build_mesh, set_mesh
from paddle_tpu.models import (ErnieMoEForMaskedLM, ErnieMoEModel,
                               ernie_moe_config)


def tiny():
    return ernie_moe_config("tiny", num_hidden_layers=2, num_experts=4,
                            moe_every=2)


def batch(cfg, b=4, s=16, mask_frac=0.25, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, (b, s)).astype(np.int32)
    labels = np.full((b, s), -100, np.int64)
    m = rng.rand(b, s) < mask_frac
    labels[m] = ids[m]
    return paddle.to_tensor(ids), paddle.to_tensor(labels)


class TestErnieMoE:
    def setup_method(self, _):
        set_mesh(build_mesh(ep=4, dp=2))

    def test_moe_cadence(self):
        m = ErnieMoEModel(tiny())
        from paddle_tpu.incubate.distributed.models.moe import MoELayer

        kinds = [isinstance(l.ffn, MoELayer) for l in m.layers]
        assert kinds == [False, True]   # every 2nd layer is MoE

    def test_forward_shapes(self):
        cfg = tiny()
        m = ErnieMoEForMaskedLM(cfg)
        ids, _ = batch(cfg)
        logits = m(ids)
        assert list(logits.shape) == [4, 16, cfg.vocab_size]

    def test_bidirectional_not_causal(self):
        """Encoder attention must see the future: changing a LATER token
        must change an EARLIER position's representation. The RNG is
        re-seeded before each forward so gate random-routing can't fake
        the difference."""
        cfg = tiny()
        m = ErnieMoEModel(cfg)
        m.eval()
        ids, _ = batch(cfg)
        paddle.seed(99)
        h1 = np.asarray(m(ids).value)
        # same seed, same input → identical (routing noise controlled)
        paddle.seed(99)
        h1b = np.asarray(m(ids).value)
        np.testing.assert_allclose(h1, h1b, rtol=1e-6)
        ids2 = np.asarray(ids.value).copy()
        ids2[:, -1] = (ids2[:, -1] + 1) % cfg.vocab_size
        paddle.seed(99)
        h2 = np.asarray(m(paddle.to_tensor(ids2)).value)
        assert np.abs(h1[:, 0] - h2[:, 0]).max() > 1e-6

    def test_mlm_trains_with_aux_loss(self):
        cfg = tiny()
        m = ErnieMoEForMaskedLM(cfg)
        m.train()
        from paddle_tpu.optimizer import AdamW

        opt = AdamW(learning_rate=5e-3, parameters=m.parameters())
        ids, labels = batch(cfg)
        losses = []
        for _ in range(5):
            loss, _logits = m(ids, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.value))
        assert losses[-1] < losses[0], losses

    def test_expert_dispatch_rides_ep_axis(self):
        """The MoE layer's dispatched expert compute must actually be
        placed over the ep mesh axis (BASELINE config 4: dispatch over
        ICI) — asserted on the dispatch constraint spec the MoELayer
        applies, not just on layer types."""
        import paddle_tpu.incubate.distributed.models.moe.moe_layer as ml

        cfg = tiny()
        m = ErnieMoEForMaskedLM(cfg)
        ids, labels = batch(cfg)
        seen = []
        orig = ml.constraint

        def spy(x, spec, *a, **kw):
            seen.append(str(spec))
            return orig(x, spec, *a, **kw)

        ml.constraint = spy
        try:
            m(ids, labels)
        finally:
            ml.constraint = orig
        assert any("ep" in s for s in seen), seen
