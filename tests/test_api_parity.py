"""API-surface regression guard: the parity report must stay at 100%.

Audits every public name in the reference's module ``__all__`` lists
against this package (tools/api_parity_report.py). Any regression shows
up as a named missing symbol.
"""
import os
import sys

import pytest

REF = "/root/reference"

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
def test_exact_name_parity_is_complete():
    from api_parity_report import MODULES, our_surface, parse_all

    base = os.path.join(REF, "python", "paddle")
    top_extra = parse_all(os.path.join(base, "tensor/__init__.py")) or []
    missing_all = {}
    for rel, ours in MODULES:
        if ours is None:
            continue
        ref_names = parse_all(os.path.join(base, rel))
        if ref_names is None:
            continue
        if rel == "__init__.py":
            ref_names = sorted(set(ref_names) | set(top_extra))
        have = our_surface(ours)
        missing = [n for n in ref_names if n.split(".")[0] not in have]
        if missing:
            missing_all["paddle." + ours if ours else "paddle"] = missing
    assert not missing_all, f"API parity regressed: {missing_all}"


def test_distributed_extras_single_process():
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    # aliases dispatch to the canonical collectives
    assert dist.alltoall.__name__ == "alltoall"
    t = paddle.to_tensor(np.ones(3, np.float32))
    dist.wait(t)
    out = ["x"]
    assert dist.broadcast_object_list(out) == ["x"]
    dest = []
    dist.scatter_object_list(dest, [1, 2, 3], src=0)
    assert dest  # single-process: src's first shard
    got = []
    dist.gather(t, got, dst=0)
    assert len(got) >= 1
    dist.destroy_process_group()
    assert dist.is_available()


def test_fleet_role_and_util():
    from paddle_tpu.distributed import fleet

    rm = fleet.UserDefinedRoleMaker(current_id=1, worker_num=4)
    assert rm.worker_index() == 1 and rm.worker_num() == 4
    u = fleet.UtilBase()
    assert u.get_file_shard(["a", "b", "c"]) == ["a", "b", "c"]
    import numpy as np

    r = u.all_reduce(np.asarray([2.0]), mode="min")
    assert float(np.asarray(r)[0]) == 2.0


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
def test_behavior_smoke_no_new_gated_stubs():
    """'present' != 'works' (VERDICT r2 #10): gated raise-on-call stubs
    must not grow. The allowlist is exactly the documented descopes —
    parameter-server data plumbing and non-TPU hardware helpers."""
    from api_parity_report import MODULES, parse_all, smoke_module

    ALLOWED_GATED = {
        # brpc parameter-server world (DESIGN.md descope)
        "InMemoryDataset", "QueueDataset", "CountFilterEntry",
        "ProbabilityEntry", "ShowClickEntry", "MultiSlotDataGenerator",
        "MultiSlotStringDataGenerator",
        # non-TPU hardware
        "xpu_places",
    }
    base = os.path.join(REF, "python", "paddle")
    top_extra = parse_all(os.path.join(base, "tensor/__init__.py")) or []
    unexpected = {}
    for rel, ours in MODULES:
        if ours is None:
            continue
        ref_names = parse_all(os.path.join(base, rel))
        if ref_names is None:
            continue
        if rel == "__init__.py":
            ref_names = sorted(set(ref_names) | set(top_extra))
        smoke = smoke_module(ours, ref_names)
        bad = sorted(set(smoke["gated"]) - ALLOWED_GATED)
        if bad:
            unexpected["paddle." + ours if ours else "paddle"] = bad
    assert not unexpected, (
        f"new gated raise-on-call stubs (implement or document the "
        f"descope): {unexpected}")


def test_class_center_sample():
    """PartialFC sampling now works (was a gated stub)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.nn import functional as F

    label = paddle.to_tensor(np.asarray([2, 5, 2, 9], np.int64))
    remapped, centers = F.class_center_sample(label, num_classes=20,
                                              num_samples=8)
    c = np.asarray(centers.value)
    r = np.asarray(remapped.value)
    assert len(c) == 8 and len(set(c.tolist())) == 8
    for orig in (2, 5, 9):
        assert orig in c          # positives always kept
    np.testing.assert_array_equal(c[r], [2, 5, 2, 9])  # remap round-trip
    # more positives than num_samples: keep all positives
    label2 = paddle.to_tensor(np.arange(12, dtype=np.int64))
    r2, c2 = F.class_center_sample(label2, 20, 8)
    assert len(np.asarray(c2.value)) == 12
