"""Cross-PROCESS pipeline parallelism: the fleet_executor role end-to-end.

VERDICT r2 (fleet_executor partial): 'no cross-host PP run exists — the
multihost test is a 2-proc gloo psum, not a pipeline'. This test runs the
compiled 1F1B schedule with the pp axis SPANNING two OS processes (each
process owns one pipeline stage; activations cross the process boundary
through the ppermute collective over gloo — the CPU stand-in for ICI/DCN),
and checks the loss agrees with the single-process serial model.

Reference analog: fleet_executor's Carrier/Interceptor message-passing
runtime (distributed/fleet_executor/) whose role here is carried by the
SPMD program + collective transport.
"""
import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


WORKER = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.config.update("jax_default_matmul_precision", "highest")
    import numpy as np

    import paddle_tpu.distributed as dist

    env = dist.init_parallel_env(pp=2)
    rank = env.rank

    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.distributed.topology import get_mesh
    from paddle_tpu.distributed.fleet.meta_parallel.pp_sharded import (
        blocks_from_stacked, build_sharded_1f1b_grad_fn)
    from paddle_tpu.models.llama import LlamaConfig
    from paddle_tpu.models.llama_functional import build_loss_fn
    from paddle_tpu.models.llama_pp import llama_pp_fns

    mesh = get_mesh()
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=4, num_attention_heads=4,
                      num_key_value_heads=4, max_position_embeddings=64)

    # both processes build IDENTICAL params from a shared seed
    rng = np.random.RandomState(7)
    def mk(*shape):
        return (rng.randn(*shape) * 0.05).astype(np.float32)
    stacked = {{
        "input_layernorm.weight": np.ones((4, 32), np.float32),
        "post_attention_layernorm.weight": np.ones((4, 32), np.float32),
        "self_attn.q_proj.weight": mk(4, 32, 32),
        "self_attn.k_proj.weight": mk(4, 32, 32),
        "self_attn.v_proj.weight": mk(4, 32, 32),
        "self_attn.o_proj.weight": mk(4, 32, 32),
        "mlp.gate_proj.weight": mk(4, 32, 64),
        "mlp.up_proj.weight": mk(4, 32, 64),
        "mlp.down_proj.weight": mk(4, 64, 32),
    }}
    rest = {{
        "model.embed_tokens.weight": mk(64, 32),
        "model.norm.weight": np.ones((32,), np.float32),
    }}
    drng = np.random.RandomState(3)
    ids = drng.randint(0, 64, (4, 16)).astype(np.int32)
    labels = drng.randint(0, 64, (4, 16)).astype(np.int32)

    from paddle_tpu.distributed.fleet.meta_parallel.pp_sharded import (
        build_sharded_1f1b_resid_grad_fn)
    from paddle_tpu.models.llama_residual import make_body_fwd_bwd

    first, body, last = llama_pp_fns(cfg, remat=False)
    gf = build_sharded_1f1b_grad_fn(first, body, last, accumulate_steps=2,
                                    mesh=mesh)
    body_fwd, body_bwd = make_body_fwd_bwd(cfg)
    gf_resid = build_sharded_1f1b_resid_grad_fn(
        first, body_fwd, body_bwd, last, accumulate_steps=2, mesh=mesh)
    blocks = blocks_from_stacked(stacked, 2, 1)
    # global arrays across BOTH processes: stage dim sharded over pp
    sh = NamedSharding(mesh, P("pp"))
    def to_global(v):
        local = np.asarray(v)[rank:rank + 1]
        return jax.make_array_from_process_local_data(sh, local, v.shape)
    blocks = {{k: to_global(v) for k, v in blocks.items()}}
    loss, (gb, ge) = jax.jit(gf)(blocks, rest, ids, labels)
    loss = float(loss)
    # the residual-stashing schedule must agree ACROSS the same two
    # processes (activations + cotangents + stashed residuals all ride
    # gloo ppermutes)
    loss_r, _ = jax.jit(gf_resid)(blocks, rest, ids, labels)
    loss_r = float(loss_r)

    # serial single-process reference (computed in-process, full model)
    ref = float(build_loss_fn(cfg, remat=False)(
        {{k: np.asarray(v) for k, v in stacked.items()}}, rest, ids, labels))
    print(json.dumps({{"rank": rank, "loss": loss, "loss_resid": loss_r,
                       "ref": ref}}))
""")


@pytest.mark.slow
class TestCrossProcessPipeline:
    def test_two_process_1f1b_matches_serial(self, tmp_path):
        coord = _free_port()
        master = _free_port()
        script = tmp_path / "ppworker.py"
        script.write_text(WORKER.format(repo=REPO))
        procs = []
        for rank in range(2):
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)  # one CPU device per process
            env.update({
                "JAX_PLATFORMS": "cpu",
                "PADDLE_TRAINER_ENDPOINTS":
                    f"127.0.0.1:{coord},127.0.0.1:{coord}",
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_NNODES": "2",
                "PADDLE_TRAINERS_NUM": "2",
                "MASTER_ADDR": "127.0.0.1",
                "MASTER_PORT": str(master),
            })
            procs.append(subprocess.Popen(
                [sys.executable, str(script)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
        outs = []
        for rank, p in enumerate(procs):
            try:
                out, err = p.communicate(timeout=240)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail(f"rank {rank} timed out")
            assert p.returncode == 0, f"rank {rank} failed:\n{err[-3000:]}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
        assert {o["rank"] for o in outs} == {0, 1}
        for o in outs:
            np.testing.assert_allclose(o["loss"], o["ref"], rtol=2e-4,
                                       atol=2e-5)
            np.testing.assert_allclose(o["loss_resid"], o["ref"],
                                       rtol=2e-4, atol=2e-5)
        # both ranks computed the SAME global loss
        np.testing.assert_allclose(outs[0]["loss"], outs[1]["loss"],
                                   rtol=1e-6)
        np.testing.assert_allclose(outs[0]["loss_resid"],
                                   outs[1]["loss_resid"], rtol=1e-6)
