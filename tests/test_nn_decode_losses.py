"""Tests for the nn loss/decode tail: rnnt, hsigmoid, multi-margin,
margin CE, Softmax2D, gather_tree, beam search.

Reference analogs: test/legacy_test/test_rnnt_loss.py, test_hsigmoid_op
.py, test_multi_margin_loss.py, test_margin_cross_entropy_op.py,
test_gather_tree_op.py, test_rnn_decode_api.py.
"""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn


class TestMultiMargin:
    @pytest.mark.parametrize("p,margin", [(1, 1.0), (2, 0.5)])
    def test_matches_torch(self, p, margin):
        rng = np.random.RandomState(0)
        x = rng.randn(6, 5).astype(np.float32)
        y = rng.randint(0, 5, (6,))
        ours = float(F.multi_margin_loss(
            paddle.to_tensor(x), paddle.to_tensor(y), p=p,
            margin=margin).numpy())
        ref = float(torch.nn.functional.multi_margin_loss(
            torch.tensor(x), torch.tensor(y), p=p, margin=margin))
        np.testing.assert_allclose(ours, ref, rtol=1e-5)

    def test_layer_and_weight(self):
        rng = np.random.RandomState(1)
        x = rng.randn(4, 3).astype(np.float32)
        y = rng.randint(0, 3, (4,))
        w = rng.rand(3).astype(np.float32)
        ours = float(nn.MultiMarginLoss(weight=paddle.to_tensor(w))(
            paddle.to_tensor(x), paddle.to_tensor(y)).numpy())
        ref = float(torch.nn.functional.multi_margin_loss(
            torch.tensor(x), torch.tensor(y), weight=torch.tensor(w)))
        np.testing.assert_allclose(ours, ref, rtol=1e-5)


class TestRNNT:
    def test_brute_force_parity(self):
        B, T, U, V = 1, 3, 2, 4
        rng = np.random.RandomState(0)
        lg = rng.randn(B, T, U + 1, V).astype(np.float32)
        lbl = np.asarray([[1, 2]])

        def logsoftmax(a):
            a = a - a.max(-1, keepdims=True)
            return a - np.log(np.exp(a).sum(-1, keepdims=True))

        lp = logsoftmax(lg)[0]
        total = [-np.inf]

        def rec(t, u, acc):
            if t == T - 1 and u == U:
                total[0] = np.logaddexp(total[0], acc + lp[t, u, 0])
            if u < U:
                rec(t, u + 1, acc + lp[t, u, lbl[0, u]])
            if t + 1 <= T - 1:
                rec(t + 1, u, acc + lp[t, u, 0])

        rec(0, 0, 0.0)
        ours = np.asarray(F.rnnt_loss(
            paddle.to_tensor(lg), paddle.to_tensor(lbl),
            paddle.to_tensor(np.asarray([T])),
            paddle.to_tensor(np.asarray([U])),
            reduction="none").numpy()).item()
        np.testing.assert_allclose(ours, -total[0], rtol=1e-5)

    def test_grads_finite_and_training_decreases(self):
        B, T, U, V = 2, 4, 3, 5
        rng = np.random.RandomState(2)
        lg = paddle.to_tensor(rng.randn(B, T, U + 1, V).astype(np.float32))
        lg.stop_gradient = False
        lbl = paddle.to_tensor(rng.randint(1, V, (B, U)))
        il = paddle.to_tensor(np.asarray([T, T], np.int64))
        ll = paddle.to_tensor(np.asarray([U, U], np.int64))
        loss = nn.RNNTLoss()(lg, lbl, il, ll)
        loss.backward()
        g = np.asarray(lg.grad.numpy())
        assert np.all(np.isfinite(g)) and np.abs(g).sum() > 0


class TestHSigmoid:
    def test_loss_shape_and_training(self):
        from paddle_tpu import optimizer as opt

        m = nn.HSigmoidLoss(8, 10)
        o = opt.Adam(learning_rate=0.1, parameters=m.parameters())
        rng = np.random.RandomState(3)
        x = rng.randn(16, 8).astype(np.float32)
        y = rng.randint(0, 10, (16, 1))
        losses = []
        for _ in range(15):
            loss = paddle.mean(m(paddle.to_tensor(x), paddle.to_tensor(y)))
            losses.append(float(loss.numpy()))
            loss.backward()
            o.step()
            o.clear_grad()
        assert losses[-1] < 0.5 * losses[0]

    def test_functional_custom_path(self):
        x = paddle.to_tensor(np.random.randn(2, 4).astype(np.float32))
        w = paddle.to_tensor(np.random.randn(3, 4).astype(np.float32))
        lbl = paddle.to_tensor(np.asarray([[0], [1]]))
        pt = paddle.to_tensor(np.asarray([[0, 1], [0, 2]]))
        pc = paddle.to_tensor(np.asarray([[0.0, 1.0], [1.0, -1.0]],
                                         np.float32))
        out = F.hsigmoid_loss(x, lbl, 4, w, path_table=pt, path_code=pc)
        assert np.asarray(out.numpy()).shape == (2, 1)
        assert np.all(np.isfinite(np.asarray(out.numpy())))


class TestMarginCE:
    def test_zero_margin_equals_scaled_softmax_ce(self):
        rng = np.random.RandomState(4)
        cos = rng.uniform(-1, 1, (5, 8)).astype(np.float32)
        y = rng.randint(0, 8, (5,))
        ours = float(F.margin_cross_entropy(
            paddle.to_tensor(cos), paddle.to_tensor(y), margin1=1.0,
            margin2=0.0, margin3=0.0, scale=10.0).numpy())
        z = torch.tensor(cos) * 10.0
        ref = float(torch.nn.functional.cross_entropy(z, torch.tensor(y)))
        np.testing.assert_allclose(ours, ref, rtol=1e-4)


class TestSoftmax2D:
    def test_channel_softmax(self):
        x = np.random.RandomState(5).rand(2, 3, 4, 4).astype(np.float32)
        out = np.asarray(nn.Softmax2D()(paddle.to_tensor(x)).numpy())
        np.testing.assert_allclose(out.sum(1), 1.0, atol=1e-5)


class TestDecode:
    def test_gather_tree_backtrace(self):
        ids = np.asarray([[[2, 5]], [[3, 6]], [[4, 7]]], np.int64)
        par = np.asarray([[[0, 0]], [[1, 0]], [[0, 1]]], np.int64)
        out = np.asarray(F.gather_tree(paddle.to_tensor(ids),
                                       paddle.to_tensor(par)).numpy())
        assert out[:, 0, 0].tolist() == [5, 3, 4]
        assert out[:, 0, 1].tolist() == [2, 6, 7]

    def test_beam_search_decoder_greedy_chain(self):
        V, beam = 6, 3

        class ToyCell:
            def __call__(self, ids, states):
                iv = np.asarray(ids.numpy()).astype(int)
                logits = np.full((iv.shape[0], V), -5.0, np.float32)
                nxt = np.minimum(iv + 1, V - 1)
                logits[np.arange(iv.shape[0]), nxt] = 5.0
                return paddle.to_tensor(logits), states

        dec = nn.BeamSearchDecoder(ToyCell(), start_token=0,
                                   end_token=V - 1, beam_size=beam)
        out, lens = nn.dynamic_decode(
            dec, inits={"h": np.zeros((2, 4), np.float32)},
            max_step_num=10, return_length=True)
        o = np.asarray(out.numpy())
        assert o.shape[0] == 2 and o.shape[2] == beam
        assert list(o[0, :5, 0]) == [1, 2, 3, 4, 5]
