"""Batched speculative decoding in the online serving path (ISSUE 8).

The tentpole contract, CPU-verified:

- BITWISE-GREEDY PARITY: a speculating request's output is identical
  to the same request decoded plain, on the dense AND paged engines,
  MHA and GQA — speculation changes the schedule, never the tokens;
- ONE COMPILED PROGRAM: a mixed speculating/plain/sampled batch rides
  a single compiled verify-step program per (engine, draft_k) —
  asserted via the monitored_jit cache-miss counter;
- INTERACTION SUITES: a spec slot preempted mid-draft under KV
  pressure (PR 5), replayed through an engine restart (PR 4), and
  sharing a cached prefix with copy-on-write on divergence (PR 6) all
  keep greedy parity; eos landing mid-accepted-draft truncates
  exactly like the plain path;
- the extracted n-gram proposer (inference/ngram.py) is the same
  tested unit the offline path consumes.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.inference.generation import (CausalLMEngine,
                                             ContinuousBatchingEngine,
                                             GenerationConfig,
                                             PagedContinuousBatchingEngine)
from paddle_tpu.inference.ngram import NgramIndex, NgramProposer
from paddle_tpu.models import LlamaForCausalLM, llama_config
from paddle_tpu.serving import Server


def tiny_model(layers=2, kv_heads=None, seed=0):
    paddle.seed(seed)
    cfg = llama_config("tiny", num_hidden_layers=layers,
                       num_key_value_heads=kv_heads)
    return LlamaForCausalLM(cfg), cfg


@pytest.fixture()
def mon():
    monitor.enable()
    monitor.reset()
    yield monitor
    monitor.reset()
    monitor.disable()


REP = np.tile(np.array([5, 6, 7, 8], np.int32), 6)       # accepting
RND = np.random.RandomState(0).randint(0, 64, (9,)).astype(np.int32)


def _greedy(n, **kw):
    return GenerationConfig(max_new_tokens=n, eos_token_id=None, **kw)


def _spec(n, **kw):
    return GenerationConfig(max_new_tokens=n, eos_token_id=None,
                            speculative=True, **kw)


def _run(eng, prompts, cfgs, steps=4):
    rids = [eng.add_request(p, c) for p, c in zip(prompts, cfgs)]
    while eng.decode_segment(steps):
        pass
    outs = eng.collect_finished()
    return [outs[r] for r in rids]


class TestNgramProposer:
    """The extracted unit (inference/ngram.py) both paths consume."""

    def test_index_proposes_recent_continuation(self):
        idx = NgramIndex(3)
        ctx = [1, 2, 3, 9, 1, 2, 3]
        assert idx.propose(ctx, 2) == [9, 1]

    def test_miss_pads_with_tail_token(self):
        assert NgramIndex(2).propose([4, 5, 6], 3) == [6, 6, 6]

    def test_proposer_state_is_incremental(self):
        p = NgramProposer([1, 2, 3, 9], draft_k=3, ngram_max=3)
        p.extend([1, 2, 3])
        # suffix [1,2,3] matched at position 0 -> continue with 9, then
        # the next occurrence's continuation
        d = p.propose()
        assert d[0] == 9
        assert p.proposed == 3
        assert len(p.ctx) == 7

    def test_validation(self):
        with pytest.raises(ValueError, match="draft_k"):
            NgramProposer([1], draft_k=0)
        with pytest.raises(ValueError, match="ngram_max"):
            NgramIndex(0)

    def test_offline_path_consumes_it(self):
        """generate_speculative rides the shared proposer and keeps
        its exact-match contract (the offline suite asserts the rest)."""
        model, cfg = tiny_model()
        eng = CausalLMEngine(model, max_batch=1, max_len=256)
        gc = _greedy(24)
        ref = eng.generate(REP[None], gc)
        out = eng.generate_speculative(REP[None], gc, draft_k=6)
        np.testing.assert_array_equal(ref, out)
        assert eng.last_spec_stats["accepted_draft_tokens"] > 0


class TestConfigKnobs:
    def test_generation_config_fields(self):
        cfg = GenerationConfig(speculative=True, draft_k=4)
        assert cfg.speculative and cfg.draft_k == 4
        assert GenerationConfig().speculative is False
        assert GenerationConfig().draft_k is None
        with pytest.raises(ValueError, match="draft_k"):
            GenerationConfig(draft_k=0)
        with pytest.raises(ValueError, match="draft_k"):
            GenerationConfig(draft_k=300)
        with pytest.raises(ValueError, match="draft_k"):
            GenerationConfig(draft_k=2.5)

    def test_engine_draft_k_validation(self):
        model, _ = tiny_model(layers=1)
        with pytest.raises(ValueError, match="draft_k"):
            ContinuousBatchingEngine(model, max_batch=1, max_len=64,
                                     draft_k=-1)

    def test_spec_k_eligibility(self):
        """Sampled requests and draft_k=0 engines fall back to plain;
        a request's own draft_k caps the engine's, never widens it."""
        model, _ = tiny_model(layers=1)
        eng = ContinuousBatchingEngine(model, max_batch=1, max_len=64,
                                       draft_k=6)
        assert eng._spec_k_for(_spec(4)) == 6
        assert eng._spec_k_for(_spec(4, draft_k=3)) == 3
        assert eng._spec_k_for(_spec(4, draft_k=200)) == 6
        assert eng._spec_k_for(_greedy(4)) == 0
        assert eng._spec_k_for(GenerationConfig(
            max_new_tokens=4, do_sample=True, speculative=True,
            eos_token_id=None)) == 0
        off = ContinuousBatchingEngine(model, max_batch=1, max_len=64)
        assert off._spec_k_for(_spec(4)) == 0


class TestBitwiseParity:
    """Greedy spec-vs-plain output is bitwise identical per slot —
    dense + paged, MHA + GQA, accepting and adversarial prompts."""

    @pytest.mark.parametrize("kv_heads", [None, 2],
                             ids=["mha", "gqa"])
    def test_dense(self, kv_heads):
        model, _ = tiny_model(kv_heads=kv_heads)
        ref = _run(ContinuousBatchingEngine(model, max_batch=2,
                                            max_len=128),
                   [REP, RND], [_greedy(24), _greedy(24)])
        eng = ContinuousBatchingEngine(model, max_batch=2, max_len=128,
                                       draft_k=6)
        out = _run(eng, [REP, RND], [_spec(24), _spec(24)])
        for a, b in zip(ref, out):
            np.testing.assert_array_equal(a, b)
        st = eng.spec_stats()
        assert st["accepted"] > 0          # drafts did real work
        assert st["tokens_per_forward"] > 1.0
        # accounting identity per slot-forward: every emitted token is
        # either the forward's own pick or an accepted draft
        assert st["emitted"] == st["slot_steps"] + st["accepted"]

    @pytest.mark.parametrize("kv_heads", [None, 2],
                             ids=["mha", "gqa"])
    def test_paged(self, kv_heads):
        model, _ = tiny_model(kv_heads=kv_heads)
        ref = _run(PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=24, page_size=8,
            max_pages=16, debug_pages=True),
            [REP, RND], [_greedy(24), _greedy(24)])
        eng = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=24, page_size=8,
            max_pages=16, draft_k=6, debug_pages=True)
        out = _run(eng, [REP, RND], [_spec(24), _spec(24)])
        for a, b in zip(ref, out):
            np.testing.assert_array_equal(a, b)
        assert eng.spec_stats()["accepted"] > 0
        # all capacity reclaimed, validator armed throughout
        assert eng.alloc.free_pages == eng.num_pages

    def test_budget_smaller_than_draft_window(self):
        """A budget below draft_k must be respected exactly (the
        device lim-cap cuts acceptance; host never over-collects)."""
        model, _ = tiny_model()
        ref = _run(ContinuousBatchingEngine(model, max_batch=1,
                                            max_len=128),
                   [REP], [_greedy(3)])
        eng = ContinuousBatchingEngine(model, max_batch=1, max_len=128,
                                       draft_k=6)
        out = _run(eng, [REP], [_spec(3)])
        np.testing.assert_array_equal(ref[0], out[0])
        assert len(out[0]) == 3

    def test_near_max_len_stops_clean(self):
        """A spec row whose window would cross max_len caps its
        acceptance there instead of clamp-corrupting the cache tail."""
        model, _ = tiny_model()
        # plen 24 + 8 new = max_len exactly
        ref = _run(ContinuousBatchingEngine(model, max_batch=1,
                                            max_len=32),
                   [REP], [_greedy(8)])
        eng = ContinuousBatchingEngine(model, max_batch=1, max_len=32,
                                       draft_k=6)
        out = _run(eng, [REP], [_spec(8)])
        np.testing.assert_array_equal(ref[0], out[0])


class TestMixedBatchOneProgram:
    def test_mixed_spec_plain_sampled_single_compile(self, mon):
        """A mixed speculating/plain/sampled batch is served by ONE
        compiled verify-step program (per draft_k) — and the greedy
        rows keep bitwise parity while riding it."""
        model, _ = tiny_model()
        ref = _run(ContinuousBatchingEngine(model, max_batch=2,
                                            max_len=128),
                   [REP, RND], [_greedy(20), _greedy(20)])
        monitor.reset()         # count only the MIXED run's compiles
        eng = ContinuousBatchingEngine(model, max_batch=3, max_len=128,
                                       draft_k=6)
        outs = _run(eng, [REP, RND, REP],
                    [_spec(20), _greedy(20),
                     GenerationConfig(max_new_tokens=10, do_sample=True,
                                      temperature=0.8, seed=7,
                                      eos_token_id=None)])
        np.testing.assert_array_equal(outs[0], ref[0])   # spec row
        np.testing.assert_array_equal(outs[1], ref[1])   # plain row
        assert len(outs[2]) == 10                        # sampled row
        misses = monitor.jit_miss_by_fn()
        # ONE spec-step compile serves the whole spec/plain/sampled mix
        # (segments after the spec row retires revert to the plain scan
        # program, itself compiled at most once per n_steps)
        assert misses.get("cb_spec_step") == 1, misses
        assert misses.get("cb_segment", 0) <= 1, misses

    def test_draft_k_keys_the_program(self, mon):
        """Two engines with different draft_k compile their own width;
        within one engine every segment reuses the first compile."""
        model, _ = tiny_model(layers=1)
        for k in (2, 4):
            eng = ContinuousBatchingEngine(model, max_batch=1,
                                           max_len=64, draft_k=k)
            _run(eng, [REP[:8]], [_spec(10)])
        misses = monitor.jit_miss_by_fn()
        assert misses.get("cb_spec_step") == 2, misses


class TestEosMidDraft:
    def test_eos_landing_mid_accepted_draft_truncates(self):
        """eos inside an accepted draft window: the emitted sequence
        truncates AT eos (stale device tail dies with retirement) and
        matches the plain path bitwise."""
        model, _ = tiny_model()
        probe = ContinuousBatchingEngine(model, max_batch=1,
                                         max_len=128)
        free = _run(probe, [REP], [_greedy(24)])[0]
        eos = int(free[7])          # something it emits mid-stream
        kw = dict(max_new_tokens=24, eos_token_id=eos)
        ref = _run(ContinuousBatchingEngine(model, max_batch=1,
                                            max_len=128),
                   [REP], [GenerationConfig(**kw)])[0]
        eng = ContinuousBatchingEngine(model, max_batch=1, max_len=128,
                                       draft_k=6)
        out = _run(eng, [REP],
                   [GenerationConfig(speculative=True, **kw)])[0]
        np.testing.assert_array_equal(ref, out)
        assert out[-1] == eos and len(out) < 24
        # the slot retired cleanly — engine is idle and reusable
        assert eng.free_slots() == 1
        out2 = _run(eng, [RND], [_spec(6)])[0]
        assert len(out2) == 6


class TestServerIntegration:
    def test_server_knobs_and_default_opt_in(self, mon):
        """Server(draft_k=..., speculative=True) mirrors the engine
        knob and opts eligible requests in by default; warmup
        pre-compiles the verify program so requests pay zero segment
        compiles."""
        model, cfg = tiny_model()
        eng = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=24, page_size=8, max_pages=8)
        srv = Server(eng, segment_steps=3, warmup=True, draft_k=4,
                     speculative=True)
        try:
            assert srv.wait_ready(120) and srv.status == "ok"
            pre = monitor.jit_miss_by_fn()
            h = srv.submit(REP, _greedy(12))      # no explicit opt-in
            out = h.result(timeout=120)
            assert len(out) == 12
            post = monitor.jit_miss_by_fn()
            assert post.get("cb_spec_step") == pre.get("cb_spec_step")
            assert eng.spec_stats()["forwards"] > 0   # it DID speculate
        finally:
            srv.shutdown(drain=False)

    def test_server_knob_validation(self):
        model, _ = tiny_model(layers=1)
        eng = ContinuousBatchingEngine(model, max_batch=1, max_len=64)
        with pytest.raises(ValueError, match="draft_k"):
            Server(eng, start=False, draft_k=-2)
        with pytest.raises(ValueError, match="speculative"):
            Server(eng, start=False, speculative=True)   # draft_k == 0
        srv = Server(eng, start=False, draft_k=5)
        assert eng.draft_k == 5
        srv.shutdown(drain=False)

    def test_spec_metrics_exported_and_retired(self, mon):
        """paddle_tpu_spec_draft_tokens_total{engine,outcome} counts
        proposed/accepted per engine and retires in engine.close()."""
        model, _ = tiny_model()
        eng = ContinuousBatchingEngine(model, max_batch=1, max_len=128,
                                       draft_k=6)
        _run(eng, [REP], [_spec(16)])
        snap = monitor.snapshot()["metrics"]
        by = {s["labels"]["outcome"]: s["value"]
              for s in snap["paddle_tpu_spec_draft_tokens_total"]
              ["samples"]
              if s["labels"]["engine"] == eng._monitor_engine}
        assert by["proposed"] > 0 and 0 <= by["accepted"] <= by["proposed"]
        eng.close()
        snap = monitor.snapshot()["metrics"]
        left = [s for s in snap.get(
            "paddle_tpu_spec_draft_tokens_total", {}).get("samples", [])
            if s["labels"].get("engine") == eng._monitor_engine]
        assert not left


class TestPressureInteraction:
    """PR 5 composition: spec slots under optimistic admission grow
    their widened window per gap, get preempted mid-draft when the
    pool is dry, and replay warm with greedy parity."""

    def test_spec_slot_preempted_mid_draft_replays_bitwise(self):
        model, _ = tiny_model()
        big = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=32, page_size=8, max_pages=16,
            debug_pages=True)
        ref = _run(big, [REP, REP[:20]], [_greedy(24), _greedy(24)])
        # 10 pages = 80 tokens for two requests needing (24+24)+(20+24)
        # worst case — optimistic admission with spec growth forces
        # preemption mid-decode
        eng = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=10, page_size=8, max_pages=16,
            admission_mode="optimistic", draft_k=6, debug_pages=True)
        srv = Server(eng, segment_steps=4, max_preemptions=10,
                     speculative=True, idle_wait_s=0.005)
        try:
            h1 = srv.submit(REP, _greedy(24))
            h2 = srv.submit(REP[:20], _greedy(24))
            o1 = h1.result(timeout=180)
            o2 = h2.result(timeout=180)
            np.testing.assert_array_equal(ref[0], o1)
            np.testing.assert_array_equal(ref[1], o2)
            assert eng.alloc.preemptions >= 1, \
                "pool was sized to force at least one preemption"
            assert srv.drain(timeout=60)
        finally:
            srv.shutdown(drain=False)
        assert eng.alloc.free_pages == eng.num_pages

    def test_spec_growth_accounts_window_width(self):
        """grow_for_segment targets n_steps * (spec_k+1) for a
        speculating row — the draft window's worst-case advance."""
        model, _ = tiny_model(layers=1)
        eng = PagedContinuousBatchingEngine(
            model, max_batch=1, num_pages=16, page_size=8, max_pages=16,
            admission_mode="optimistic", draft_k=3, debug_pages=True)
        eng.add_request(REP[:8], _spec(40))
        before = eng.alloc.covered_tokens(0)     # prompt + 1 page = 16
        assert eng.grow_for_segment(4) == []
        # plain target would be lens(8) + 4 = 12 (inside the existing
        # 16-token claim); spec must cover lens + 4*(3+1) = 24
        covered = eng.alloc.covered_tokens(0)
        assert covered >= 24 > before


class TestRestartInteraction:
    """PR 4 composition: a spec slot survives an engine-scoped fault —
    reset_state + replay re-prefills prompt + generated, the proposer
    rebuilds from full context, greedy parity holds."""

    def test_spec_slot_through_restart_replay_bitwise(self):
        from paddle_tpu.inference.generation import EngineFault
        from paddle_tpu.testing.faults import FaultPlan, FaultyEngine

        model, _ = tiny_model()
        clean = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=24, page_size=8, max_pages=8,
            debug_pages=True)
        ref = _run(clean, [REP], [_greedy(20)])
        plan = FaultPlan().raise_at("decode", nth=2,
                                    exc=EngineFault("injected"))
        raw = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=24, page_size=8, max_pages=8,
            draft_k=6, debug_pages=True)
        srv = Server(FaultyEngine(raw, plan), segment_steps=3,
                     restart_backoff_s=0.01, speculative=True)
        try:
            h = srv.submit(REP, _greedy(20))
            out = h.result(timeout=180)
            np.testing.assert_array_equal(ref[0], out)
            assert srv.restarts == 1
            assert srv.drain(timeout=60)
        finally:
            srv.shutdown(drain=False)
        assert raw.free_slots() == raw.max_batch
        assert raw.alloc.free_pages == raw.num_pages


class TestPrefixCacheInteraction:
    """PR 6 composition: a spec slot admits WARM off a cached prefix,
    copy-on-writes the partial boundary page before its first draft
    write, and still matches the cold plain run bitwise."""

    def test_spec_warm_admission_cow_on_divergence_bitwise(self):
        model, _ = tiny_model()
        # prompt B shares a 20-token head with A, diverges mid-block
        # (page_size 8 -> coverage ends mid page 2), then decodes
        # speculatively: the divergent suffix + drafts must CoW, never
        # write A's shared pages
        pa = REP                                   # 24 tokens
        pb = np.concatenate([REP[:20], np.array([9, 9], np.int32)])
        cold = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=32, page_size=8, max_pages=8,
            debug_pages=True)
        ref_a = _run(cold, [pa], [_greedy(16)])[0]
        ref_b = _run(cold, [pb], [_greedy(16)])[0]
        eng = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=32, page_size=8, max_pages=8,
            prefix_cache=True, draft_k=6, debug_pages=True)
        out_a = _run(eng, [pa], [_spec(16)])[0]
        np.testing.assert_array_equal(ref_a, out_a)
        # warm re-run of A's exact prompt (fully cached head), then B
        out_a2 = _run(eng, [pa], [_spec(16)])[0]
        np.testing.assert_array_equal(ref_a, out_a2)
        out_b = _run(eng, [pb], [_spec(16)])[0]
        np.testing.assert_array_equal(ref_b, out_b)
        assert eng.alloc.prefix_hits >= 2
        assert eng.alloc.cow_copies >= 1


class TestSpecStatsSurface:
    def test_spec_stats_identity_and_reset(self):
        model, _ = tiny_model()
        eng = ContinuousBatchingEngine(model, max_batch=2, max_len=128,
                                       draft_k=4)
        _run(eng, [REP, RND], [_spec(12), _spec(12)])
        st = eng.spec_stats()
        assert st["emitted"] == st["slot_steps"] + st["accepted"]
        assert 0.0 <= st["acceptance_rate"] <= 1.0
        assert st["tokens_per_forward"] >= 1.0
        eng.reset_state()
        assert eng._spec == {}          # proposers die with the slots
        # totals survive reset (engine-lifetime accounting)
        assert eng.spec_stats()["emitted"] == st["emitted"]
