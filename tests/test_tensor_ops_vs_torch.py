"""Tensor-op semantics vs torch: reduction/sort/index conventions
(interpolation modes, tie handling, side conventions, stability) where
implementations silently diverge.
"""
import numpy as np
import pytest

import paddle_tpu as paddle

torch = pytest.importorskip("torch")


def _t(a):
    return paddle.to_tensor(np.ascontiguousarray(a))


def _np(x):
    return np.asarray(x.value if hasattr(x, "value") else x)


def rand(*s, seed=0):
    return np.random.RandomState(seed).randn(*s).astype(np.float32)


class TestSortTopk:
    def test_topk_values_and_indices(self):
        x = rand(3, 8, seed=1)
        for largest in (True, False):
            v, i = paddle.topk(_t(x), k=3, largest=largest)
            tv, ti = torch.topk(torch.from_numpy(x), 3, largest=largest)
            np.testing.assert_allclose(_np(v), tv.numpy(), rtol=1e-6)
            np.testing.assert_array_equal(_np(i), ti.numpy())

    def test_sort_descending_with_indices(self):
        x = rand(4, 6, seed=2)
        v = paddle.sort(_t(x), axis=-1, descending=True)
        i = paddle.argsort(_t(x), axis=-1, descending=True)
        tv, ti = torch.sort(torch.from_numpy(x), dim=-1, descending=True)
        np.testing.assert_allclose(_np(v), tv.numpy(), rtol=1e-6)
        np.testing.assert_array_equal(_np(i), ti.numpy())

    def test_kthvalue_and_mode(self):
        x = rand(3, 7, seed=3)
        v, i = paddle.kthvalue(_t(x), k=3, axis=-1)
        tv, ti = torch.kthvalue(torch.from_numpy(x), 3, dim=-1)
        np.testing.assert_allclose(_np(v), tv.numpy(), rtol=1e-6)
        np.testing.assert_array_equal(_np(i), ti.numpy())
        xm = np.array([[1, 2, 2, 3], [3, 3, 1, 2]], np.float32)
        v, i = paddle.mode(_t(xm), axis=-1)
        tv, ti = torch.mode(torch.from_numpy(xm), dim=-1)
        np.testing.assert_allclose(_np(v), tv.numpy(), rtol=1e-6)


class TestReductions:
    def test_quantile_linear_and_axis(self):
        # the reference quantile has NO interpolation param (stat.py:579,
        # linear only); check values + axis/keepdim against numpy
        x = rand(4, 20, seed=4)
        np.testing.assert_allclose(
            float(_np(paddle.quantile(_t(x), q=0.3))),
            np.quantile(x, 0.3), rtol=1e-5)
        np.testing.assert_allclose(
            _np(paddle.quantile(_t(x), q=[0.25, 0.75], axis=1)),
            np.quantile(x, [0.25, 0.75], axis=1), rtol=1e-5)
        np.testing.assert_allclose(
            _np(paddle.quantile(_t(x), q=0.5, axis=0, keepdim=True)),
            np.quantile(x, 0.5, axis=0, keepdims=True), rtol=1e-5)

    def test_median_even_count(self):
        # paddle median averages the two middle values by default
        # (torch.median takes the LOWER) — use numpy as the contract
        x = rand(6, seed=5)
        got = float(_np(paddle.median(_t(x))))
        np.testing.assert_allclose(got, np.median(x), rtol=1e-6)

    def test_cumsum_cumprod_logcumsumexp(self):
        x = rand(3, 5, seed=6)
        np.testing.assert_allclose(
            _np(paddle.cumsum(_t(x), axis=1)),
            torch.cumsum(torch.from_numpy(x), 1).numpy(), rtol=1e-5)
        np.testing.assert_allclose(
            _np(paddle.cumprod(_t(x), dim=1)),
            torch.cumprod(torch.from_numpy(x), 1).numpy(), rtol=1e-5)
        np.testing.assert_allclose(
            _np(paddle.logcumsumexp(_t(x), axis=1)),
            torch.logcumsumexp(torch.from_numpy(x), 1).numpy(),
            rtol=1e-4, atol=1e-5)

    def test_nanmean_nansum_nanquantile(self):
        x = rand(8, seed=7)
        x[2] = np.nan
        np.testing.assert_allclose(
            float(_np(paddle.nanmean(_t(x)))), np.nanmean(x), rtol=1e-6)
        np.testing.assert_allclose(
            float(_np(paddle.nansum(_t(x)))), np.nansum(x), rtol=1e-6)
        np.testing.assert_allclose(
            float(_np(paddle.nanquantile(_t(x), 0.5))),
            np.nanquantile(x, 0.5), rtol=1e-6)


class TestIndexing:
    @pytest.mark.parametrize("right", [False, True])
    def test_searchsorted_sides(self, right):
        sorted_x = np.array([1.0, 2.0, 2.0, 3.0, 5.0], np.float32)
        q = np.array([0.5, 2.0, 2.5, 5.0, 6.0], np.float32)
        got = _np(paddle.searchsorted(_t(sorted_x), _t(q), right=right))
        want = torch.searchsorted(torch.from_numpy(sorted_x),
                                  torch.from_numpy(q),
                                  right=right).numpy()
        np.testing.assert_array_equal(got, want)

    def test_unique_with_inverse_and_counts(self):
        x = np.array([3, 1, 2, 3, 1, 1], np.int64)
        u, inv, cnt = paddle.unique(_t(x), return_inverse=True,
                                    return_counts=True)
        np.testing.assert_array_equal(_np(u), [1, 2, 3])
        np.testing.assert_array_equal(_np(u)[_np(inv)], x)
        np.testing.assert_array_equal(_np(cnt), [3, 1, 2])

    def test_take_along_axis_put_along_axis(self):
        x = rand(3, 4, seed=8)
        idx = np.array([[0, 3], [1, 2], [2, 0]], np.int64)
        got = _np(paddle.take_along_axis(_t(x), _t(idx), axis=1))
        want = torch.gather(torch.from_numpy(x),
                            1, torch.from_numpy(idx)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-6)
        vals = np.full((3, 2), 9.0, np.float32)
        got = _np(paddle.put_along_axis(_t(x), _t(idx), _t(vals), axis=1))
        want = torch.from_numpy(x.copy()).scatter_(
            1, torch.from_numpy(idx), torch.from_numpy(vals)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_index_add_index_put(self):
        x = rand(4, 3, seed=9)
        idx = np.array([1, 3], np.int64)
        vals = np.ones((2, 3), np.float32)
        got = _np(paddle.index_add(_t(x), _t(idx), 0, _t(vals)))
        want = torch.from_numpy(x.copy()).index_add_(
            0, torch.from_numpy(idx), torch.from_numpy(vals)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_roll_flip_diff(self):
        x = rand(3, 5, seed=10)
        np.testing.assert_allclose(
            _np(paddle.roll(_t(x), shifts=2, axis=1)),
            np.roll(x, 2, axis=1), rtol=1e-6)
        np.testing.assert_allclose(
            _np(paddle.flip(_t(x), axis=[0, 1])),
            np.flip(x, (0, 1)), rtol=1e-6)
        np.testing.assert_allclose(
            _np(paddle.diff(_t(x), axis=1)), np.diff(x, axis=1),
            rtol=1e-6)


class TestSpecialFunctions:
    """Special-function values vs scipy (erf family, gamma family,
    Bessel, sinc) — formula/branch mistakes show up immediately."""

    def test_erf_family(self):
        import scipy.special as sp

        x = rand(64, seed=20) * 2
        np.testing.assert_allclose(_np(paddle.erf(_t(x))), sp.erf(x),
                                   rtol=1e-5, atol=1e-6)
        u = (np.random.RandomState(21).rand(32).astype(np.float32)
             * 1.8 - 0.9)
        np.testing.assert_allclose(_np(paddle.erfinv(_t(u))),
                                   sp.erfinv(u), rtol=1e-4, atol=1e-5)

    def test_gamma_family(self):
        import scipy.special as sp

        x = np.abs(rand(32, seed=22)) * 4 + 0.2
        np.testing.assert_allclose(_np(paddle.lgamma(_t(x))),
                                   sp.gammaln(x), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(_np(paddle.digamma(_t(x))),
                                   sp.digamma(x), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(_np(paddle.polygamma(_t(x), 1)),
                                   sp.polygamma(1, x), rtol=1e-3,
                                   atol=1e-3)

    def test_bessel_i0_i1(self):
        import scipy.special as sp

        x = np.abs(rand(32, seed=23)) * 3
        np.testing.assert_allclose(_np(paddle.i0(_t(x))), sp.i0(x),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(_np(paddle.i1(_t(x))), sp.i1(x),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(_np(paddle.i0e(_t(x))), sp.i0e(x),
                                   rtol=1e-4, atol=1e-5)

    def test_logit(self):
        # (no sinc: not in the reference snapshot's tensor surface)
        import scipy.special as sp

        p = np.random.RandomState(25).rand(32).astype(np.float32) * 0.9 \
            + 0.05
        np.testing.assert_allclose(_np(paddle.logit(_t(p))),
                                   sp.logit(p), rtol=1e-4, atol=1e-4)


class TestEinsumAndSetitem:
    def test_einsum_patterns(self):
        a, b = rand(3, 4, seed=30), rand(4, 5, seed=31)
        c = rand(2, 3, 4, seed=32)
        for pat, ops in (("ij,jk->ik", (a, b)),
                         ("bij,jk->bik", (c, b)),
                         ("ij->ji", (a,)),
                         ("bij->b", (c,)),
                         ("ij,ij->", (a, a))):
            got = _np(paddle.einsum(pat, *[_t(o) for o in ops]))
            want = np.einsum(pat, *ops)
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5,
                                       err_msg=pat)

    def test_setitem_slices_and_masks(self):
        x = rand(4, 5, seed=33)
        t = _t(x.copy())
        t[1:3, ::2] = 7.0
        want = x.copy()
        want[1:3, ::2] = 7.0
        np.testing.assert_allclose(_np(t), want)
        t2 = _t(x.copy())
        t2[x > 0.5] = 0.0
        want2 = x.copy()
        want2[x > 0.5] = 0.0
        np.testing.assert_allclose(_np(t2), want2)

    def test_getitem_forms(self):
        x = rand(4, 5, 6, seed=34)
        t = _t(x)
        np.testing.assert_allclose(_np(t[::2, -1]), x[::2, -1])
        np.testing.assert_allclose(_np(t[..., 2]), x[..., 2])
        np.testing.assert_allclose(_np(t[None, 1]), x[None, 1])
        idx = np.array([2, 0, 3], np.int64)
        np.testing.assert_allclose(_np(t[_t(idx)]), x[idx])

    def test_broadcast_binary_ops(self):
        a = rand(4, 1, 5, seed=35)
        b = rand(3, 1, seed=36)
        np.testing.assert_allclose(_np(_t(a) + _t(b)), a + b, rtol=1e-6)
        np.testing.assert_allclose(_np(_t(a) * _t(b)), a * b, rtol=1e-6)
        np.testing.assert_allclose(
            _np(paddle.maximum(_t(a), _t(b))), np.maximum(a, b),
            rtol=1e-6)
