"""Residual-stashing 1F1B: grad parity + the FLOPs contract.

VERDICT r3 #2: the input-stashing 1F1B re-runs each chunk's forward inside
the backward tick's jax.vjp (~1.33x ideal FLOPs). The residual-stashing
schedule (pp_sharded.build_sharded_1f1b_resid_grad_fn over the hand-split
decoder backward, models/llama_residual.py) must:

1. produce EXACTLY the serial model's loss and grads (parity tests), and
2. compile to ~ideal fwd+bwd FLOPs — asserted against XLA cost analysis,
   with the input-stashing builder as the re-run reference point.

Reference: meta_parallel/pipeline_parallel.py:372 (forward outputs held)
+ :677 (_backward_step consumes them) — stored-activation 1F1B.
"""
import numpy as np
import pytest

# minutes-scale multi-device/parity suite on the CPU backend:
# rides the slow tier (run with -m slow), not tier-1
pytestmark = pytest.mark.slow

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from paddle_tpu.distributed.fleet.meta_parallel.pp_sharded import (
    blocks_from_stacked, build_sharded_1f1b_grad_fn,
    build_sharded_1f1b_resid_grad_fn, stacked_from_blocks)
from paddle_tpu.distributed.topology import build_mesh, set_mesh
from paddle_tpu.models.llama import LlamaConfig, _rope_cos_sin
from paddle_tpu.models.llama_functional import (_layer_fwd, build_loss_fn,
                                                stack_params)
from paddle_tpu.models.llama_pp import llama_pp_fns
from paddle_tpu.models.llama_residual import (layer_bwd_res, layer_fwd_res,
                                              make_body_fwd_bwd)


def tiny_cfg(layers=8, kvh=None):
    return LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=layers, num_attention_heads=4,
        num_key_value_heads=kvh or 4, max_position_embeddings=64)


def make_params(cfg, seed=0):
    from paddle_tpu.models import LlamaForCausalLM

    np.random.seed(seed)
    model = LlamaForCausalLM(cfg)
    params = {k: p.value for k, p in model.named_parameters()}
    return stack_params(params, cfg)


def batch(cfg, b=8, s=16, seed=1):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, (b, s)).astype(np.int32)
    y = rng.randint(0, cfg.vocab_size, (b, s)).astype(np.int32)
    return ids, y


class TestLayerSplit:
    """Hand-split layer backward == jax.vjp of the production forward."""

    @pytest.mark.parametrize("kvh", [4, 2])
    def test_layer_grad_parity(self, kvh):
        cfg = tiny_cfg(2, kvh=kvh)
        stacked, _ = make_params(cfg)
        lp = jax.tree.map(lambda v: v[0], stacked)
        rng = np.random.RandomState(3)
        x = jnp.array(rng.randn(2, 16, cfg.hidden_size) * 0.5, jnp.float32)
        gy = jnp.array(rng.randn(2, 16, cfg.hidden_size), jnp.float32)
        cos, sin = _rope_cos_sin(16, cfg.head_dim, cfg.rope_theta, x.dtype)
        yref, vjp = jax.vjp(
            lambda lp, x: _layer_fwd(lp, x, cos, sin, cfg), lp, x)
        glp_ref, gx_ref = vjp(gy)
        y, res = layer_fwd_res(lp, x, cos, sin, cfg)
        np.testing.assert_allclose(np.asarray(y), np.asarray(yref),
                                   rtol=1e-4, atol=1e-4)
        glp, gx = layer_bwd_res(lp, res, gy, cos, sin, cfg)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                                   rtol=1e-3, atol=1e-4)
        for k in glp_ref:
            np.testing.assert_allclose(np.asarray(glp[k]),
                                       np.asarray(glp_ref[k]),
                                       rtol=1e-3, atol=1e-4, err_msg=k)

    def test_body_bwd_linear_in_g(self):
        # the schedule masks invalid ticks by zeroing the cotangent seed
        cfg = tiny_cfg(4)
        stacked, _ = make_params(cfg)
        body_fwd, body_bwd = make_body_fwd_bwd(cfg)
        chunk = jax.tree.map(lambda v: v[:2], stacked)
        x = jnp.array(np.random.RandomState(5).randn(2, 16, 32) * 0.5,
                      jnp.float32)
        _, res = body_fwd(chunk, x)
        gc, gh = body_bwd(chunk, res, jnp.zeros_like(x))
        assert float(jnp.max(jnp.abs(gh))) == 0.0
        assert all(float(jnp.max(jnp.abs(g))) == 0.0
                   for g in jax.tree.leaves(gc))


class TestResidParity:
    """pp residual-stashing 1F1B == serial llama loss AND grads."""

    def _parity(self, S, V, mesh):
        cfg = tiny_cfg(8)
        stacked, rest = make_params(cfg)
        ids, y = batch(cfg)
        ref = jax.value_and_grad(
            lambda p: build_loss_fn(cfg, remat=False)(
                p["s"], p["r"], ids, y))({"s": stacked, "r": rest})
        first, _, last = llama_pp_fns(cfg, remat=False)
        body_fwd, body_bwd = make_body_fwd_bwd(cfg)
        gf = build_sharded_1f1b_resid_grad_fn(
            first, body_fwd, body_bwd, last, accumulate_steps=4, mesh=mesh,
            num_virtual_stages=V)
        blocks = blocks_from_stacked(stacked, S, V)
        blocks = {k: jax.device_put(v, NamedSharding(mesh, P("pp")))
                  for k, v in blocks.items()}
        loss, (gb, ge) = jax.jit(gf)(blocks, rest, ids, y)
        ref_loss, ref_g = ref
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=2e-4, atol=2e-5)
        got = stacked_from_blocks(gb)
        for k in stacked:
            np.testing.assert_allclose(np.asarray(got[k]),
                                       np.asarray(ref_g["s"][k]),
                                       rtol=2e-3, atol=2e-4, err_msg=k)
        for k in rest:
            np.testing.assert_allclose(np.asarray(ge[k]),
                                       np.asarray(ref_g["r"][k]),
                                       rtol=2e-3, atol=2e-4, err_msg=k)

    def test_pp4_parity(self):
        mesh = build_mesh(pp=4, dp=2)
        set_mesh(mesh)
        self._parity(4, 1, mesh)

    def test_pp2_interleaved_v2_parity(self):
        mesh = build_mesh(pp=2, dp=4)
        set_mesh(mesh)
        self._parity(2, 2, mesh)

    def test_pp2_wraparound_m12_parity(self):
        # M=12 >> G=2S=4: slots are reused 3x — proves the tight stash
        # bound (a too-small G would corrupt stashed residuals and break
        # grad parity, which the tiny-M tests cannot detect)
        mesh = build_mesh(pp=2, dp=4)
        set_mesh(mesh)
        cfg = tiny_cfg(4)
        stacked, rest = make_params(cfg)
        ids, y = batch(cfg, b=12, s=16)
        ref = jax.value_and_grad(
            lambda p: build_loss_fn(cfg, remat=False)(
                p["s"], p["r"], ids, y))({"s": stacked, "r": rest})
        first, _, last = llama_pp_fns(cfg, remat=False)
        body_fwd, body_bwd = make_body_fwd_bwd(cfg)
        gf = build_sharded_1f1b_resid_grad_fn(
            first, body_fwd, body_bwd, last, accumulate_steps=12, mesh=mesh)
        blocks = blocks_from_stacked(stacked, 2, 1)
        blocks = {k: jax.device_put(v, NamedSharding(mesh, P("pp")))
                  for k, v in blocks.items()}
        loss, (gb, ge) = jax.jit(gf)(blocks, rest, ids, y)
        np.testing.assert_allclose(float(loss), float(ref[0]),
                                   rtol=2e-4, atol=2e-5)
        got = stacked_from_blocks(gb)
        for k in stacked:
            np.testing.assert_allclose(np.asarray(got[k]),
                                       np.asarray(ref[1]["s"][k]),
                                       rtol=2e-3, atol=2e-4, err_msg=k)

    def test_serial_s1_matches(self):
        cfg = tiny_cfg(4)
        stacked, rest = make_params(cfg)
        ids, y = batch(cfg, b=4)
        mesh = build_mesh(dp=8)
        first, _, last = llama_pp_fns(cfg, remat=False)
        body_fwd, body_bwd = make_body_fwd_bwd(cfg)
        gf = build_sharded_1f1b_resid_grad_fn(
            first, body_fwd, body_bwd, last, accumulate_steps=2, mesh=mesh)
        blocks = blocks_from_stacked(stacked, 1, 1)
        loss, _ = gf(blocks, rest, ids, y)
        ref = build_loss_fn(cfg, remat=False)(stacked, rest, ids, y)
        np.testing.assert_allclose(float(loss), float(ref), rtol=2e-4,
                                   atol=2e-5)


class TestFlopsContract:
    """Compiled-HLO FLOPs: resid 1F1B ~= ideal fwd+bwd; input-stash pays
    the re-run. (VERDICT done-bar: cost analysis <= ~1.1x ideal vs ~1.33x.)

    The comparison isolates the BODY by using a large enough body/edge
    ratio; ppermute/masking overhead is counted against the budget."""

    def _flops(self, grad_fn, blocks, rest, ids, y, mesh):
        c = jax.jit(grad_fn).lower(blocks, rest, ids, y).compile()
        ca = c.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca["flops"])

    def test_resid_beats_input_stash_and_is_near_ideal(self):
        cfg = tiny_cfg(8)
        # widen so the decoder body dominates embedding/head FLOPs
        cfg.hidden_size, cfg.intermediate_size = 64, 192
        S = 4
        stacked, rest = make_params(cfg)
        mesh = build_mesh(pp=S, dp=8 // S)
        set_mesh(mesh)
        ids, y = batch(cfg, b=8, s=16)
        first, body, last = llama_pp_fns(cfg, remat=False)
        body_fwd, body_bwd = make_body_fwd_bwd(cfg)
        blocks = blocks_from_stacked(stacked, S, 1)
        blocks = {k: jax.device_put(v, NamedSharding(mesh, P("pp")))
                  for k, v in blocks.items()}

        gf_resid = build_sharded_1f1b_resid_grad_fn(
            first, body_fwd, body_bwd, last, accumulate_steps=4, mesh=mesh)
        gf_input = build_sharded_1f1b_grad_fn(
            first, body, last, accumulate_steps=4, mesh=mesh)
        f_resid = self._flops(gf_resid, blocks, rest, ids, y, mesh)
        f_input = self._flops(gf_input, blocks, rest, ids, y, mesh)

        # ideal = serial fwd+bwd, no remat, same global batch
        loss_fn = build_loss_fn(cfg, remat=False)
        ideal = jax.jit(jax.value_and_grad(
            lambda p: loss_fn(p["s"], p["r"], ids, y))).lower(
                {"s": stacked, "r": rest}).compile()
        ca = ideal.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        # cost_analysis of the shard_map'd program reports PER-DEVICE
        # flops; the serial program is whole-model — compare per device
        # (the pipeline splits layers S ways; dp replicates compute here
        # because the grad fns take the batch replicated)
        f_ideal_dev = float(ca["flops"]) / S

        # the double-forward is gone: resid saves ~the body-forward cost
        # (measured 0.753x on this config — 3F vs 4F)
        assert f_resid < 0.85 * f_input, (f_resid, f_input)
        # and sits at ~ideal fwd+bwd (measured 1.001x; schedule overhead
        # — ppermute, masking, edge vjps — is noise)
        assert f_resid < 1.10 * f_ideal_dev, (f_resid, f_ideal_dev)
        # sanity: the input-stash path really does pay the re-run
        # (measured 1.329x)
        assert f_input > 1.20 * f_ideal_dev, (f_input, f_ideal_dev)
