"""Automatic prefix caching suite (ISSUE 6): refcounted
copy-on-write shared KV pages.

Covers the content-addressable paged-pool contract on CPU:

- the sharing-era ``PageAllocator.check()`` invariant validator: free
  ∪ parked ∪ referenced partitions the pool by REFCOUNT ACCOUNTING (a
  page may appear in several slots' rows iff its refcount matches the
  appearance count), and a refcount leak / double-own / index leak
  fails loudly;
- ``check_coverage``: the per-gap net under ``debug_pages`` for
  :func:`write_tokens`' silent drop — a live length past the mapped
  pages, or an imminent write into a shared/indexed page (forgotten
  copy-on-write), raises instead of corrupting KV downstream;
- BITWISE PARITY (greedy): a warm-prefix admission produces exactly
  the tokens of a cold run — one-shot and chunked, MHA and GQA, full
  hits, divergence at a block boundary, divergence mid-block (CoW),
  and decode appending into a partially-filled shared tail page (CoW);
- lifecycle: cancel / preempt / replay / chunked-admission abort all
  DECREMENT instead of freeing, leak-free with the validator armed;
  shared pages survive their sharer's preemption; ``reset_state``
  drops the index with the pools;
- LRU: fully-released cached pages park indexed-but-reclaimable, are
  evicted oldest-first when the pool needs pages, and lookups refresh
  recency;
- the metrics surface: hits / lookups / tokens-saved counters,
  ``Server.pressure()`` prefix fields, monitor series retired by
  ``alloc.close()``.

Every paged engine here runs with ``debug_pages=True`` — the
refcount-aware validator is armed at every page op and every gap, so
any sharing bug in these paths fails the suite loudly.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.generation import (
    ContinuousBatchingEngine, GenerationConfig,
    PagedContinuousBatchingEngine)
from paddle_tpu.inference.paged_cache import PageAllocator
from paddle_tpu.serving import Server

_MODELS = {}
_REFS = {}


def tiny_model(kv_heads=4):
    """One tiny llama per kv-head layout (4 = MHA, 2 = GQA), shared by
    the whole module: jit programs are keyed on shapes, so reusing the
    model keeps the suite to a handful of compiles."""
    if kv_heads not in _MODELS:
        paddle.seed(0)
        from paddle_tpu.models import LlamaForCausalLM, llama_config
        cfg = llama_config("tiny", num_hidden_layers=1,
                           num_key_value_heads=kv_heads)
        _MODELS[kv_heads] = (LlamaForCausalLM(cfg), cfg)
    return _MODELS[kv_heads]


def ref_tokens(ids, n=6, kv_heads=4):
    """Greedy reference tokens from a module-cached plain paged engine
    (no prefix cache). Engines here serve one request at a time and
    drain fully, so reuse is safe — and each request's greedy tokens
    are batching-independent (PR 2's mixed-config parity bar), so a
    sequential reference is valid for concurrent runs too."""
    if kv_heads not in _REFS:
        _REFS[kv_heads] = paged_engine(tiny_model(kv_heads)[0])
    return _run_one(_REFS[kv_heads], np.asarray(ids, np.int32), n=n)


def paged_engine(model, max_batch=4, num_pages=64, page_size=4,
                 max_pages=8, **kw):
    kw.setdefault("debug_pages", True)
    return PagedContinuousBatchingEngine(
        model, max_batch=max_batch, num_pages=num_pages,
        page_size=page_size, max_pages=max_pages, **kw)


def _greedy(n, eos=None):
    return GenerationConfig(max_new_tokens=n, eos_token_id=eos)


def _run_one(eng, ids, n=6, seg=4):
    rid = eng.add_request(ids, _greedy(n))
    while eng.decode_segment(seg):
        pass
    return list(dict(eng.collect_finished())[rid])


def _assert_no_leaks(eng):
    """All references released: every page is free or parked, no slot
    holds anything, and the refcount-aware validator is clean."""
    assert eng.free_slots() == eng.max_batch
    assert eng.alloc.used_pages == 0
    assert (eng.alloc.free_pages + eng.alloc.cached_pages
            == eng.num_pages)
    eng.alloc.check()


# -- allocator: refcount-aware invariant validator ---------------------------
class TestAllocatorSharing:
    def _alloc(self, num_pages=12, **kw):
        kw.setdefault("prefix_cache", True)
        return PageAllocator(num_pages=num_pages, page_size=4,
                             max_batch=3, max_pages=6, **kw)

    def _populate(self, a, toks, slot=0):
        """Cold-path bookkeeping: claim pages, register full blocks,
        release — the blocks park in the LRU. Returns the chain
        hashes."""
        _, _, hashes = a.lookup_prefix(toks)
        a.ensure(slot, len(toks))
        a.register_blocks(slot, hashes, toks, 0,
                          len(toks) // a.page_size)
        a.free_slot(slot)
        return hashes

    def test_shared_page_partitions_by_refcount(self):
        a = self._alloc()
        toks = np.arange(8, dtype=np.int32)
        self._populate(a, toks)
        assert a.cached_pages == 2
        pids, cov, _ = a.lookup_prefix(toks)
        assert cov == 8
        a.map_shared(0, pids)
        a.map_shared(1, list(pids))
        a.check()                       # refcount 2, two appearances
        assert a.shared_pages == 2
        a.free_slot(0)
        a.check()                       # refcount 1, one appearance
        assert a.shared_pages == 0
        a.free_slot(1)
        a.check()                       # parked again, still indexed
        assert a.cached_pages == 2 and a.used_pages == 0

    def test_appearance_without_refcount_detected(self):
        a = self._alloc()
        a.ensure(0, 4)
        a._owned[1] = [a._owned[0][0]]  # double-own, no refcount
        a.page_table[1, 0] = a._owned[0][0]
        with pytest.raises(RuntimeError, match="matching refcount"):
            a.check()

    def test_refcount_leak_detected(self):
        a = self._alloc()
        a.ensure(0, 4)
        a._ref[a._owned[0][0]] = 2      # refcount says 2, appears once
        with pytest.raises(RuntimeError, match="refcount"):
            a.check()

    def test_parked_page_also_free_detected(self):
        a = self._alloc()
        self._populate(a, np.arange(4, dtype=np.int32))
        pid = next(iter(a._parked))
        a._free.append(pid)
        with pytest.raises(RuntimeError, match="parked"):
            a.check()

    def test_indexed_unparked_orphan_detected(self):
        a = self._alloc()
        self._populate(a, np.arange(4, dtype=np.int32))
        a._parked.clear()               # indexed, ref 0, not parked
        with pytest.raises(RuntimeError, match="not.*parked|missing"):
            a.check()

    def test_lookup_is_token_verified(self):
        a = self._alloc()
        toks = np.arange(8, dtype=np.int32)
        self._populate(a, toks)
        # identical hash chain but corrupted recorded tokens: the
        # match must fail token verification, not alias KV
        pid = a._index[a.lookup_prefix(toks)[2][0]]
        a._tok_of[pid] = a._tok_of[pid] + 1
        pids, cov, _ = a.lookup_prefix(toks)
        assert cov == 0 and pids == []

    def test_partial_block_match(self):
        a = self._alloc()
        toks = np.arange(8, dtype=np.int32)
        self._populate(a, toks)
        # shares the first block and HALF the second
        probe = np.array([0, 1, 2, 3, 4, 5, 99, 98], np.int32)
        pids, cov, _ = a.lookup_prefix(probe)
        assert len(pids) == 2 and cov == 6

    def test_lru_reclaim_oldest_first_and_touch(self):
        a = self._alloc(num_pages=3)
        blocks = [np.full((4,), 10 + i, np.int32) for i in range(3)]
        for i, b in enumerate(blocks):
            self._populate(a, b, slot=0)
        assert a.cached_pages == 3 and a.free_pages == 0
        a.lookup_prefix(blocks[0])      # touch: 0 becomes most recent
        a.ensure(1, 4)                  # needs one page -> evict LRU
        assert a.cached_pages == 2
        assert a.lookup_prefix(blocks[1])[1] == 0     # evicted
        assert a.lookup_prefix(blocks[0])[1] == 4     # survived
        a.free_slot(1)
        a.check()

    def test_available_counts_parked(self):
        a = self._alloc(num_pages=3)
        self._populate(a, np.arange(12, dtype=np.int32))
        assert a.free_pages == 0 and a.available_pages == 3
        assert a.can_fit(1, 12)
        a.ensure(1, 12)                 # reclaims all parked pages
        assert a.cached_pages == 0
        a.free_slot(1)
        a.check()

    def test_cow_bookkeeping(self):
        a = self._alloc()
        toks = np.arange(4, dtype=np.int32)
        self._populate(a, toks)
        pids, _, _ = a.lookup_prefix(toks)
        a.map_shared(0, pids)
        a.map_shared(1, list(pids))
        old, new = a.cow(1, 0)
        assert old == pids[0] and new != old
        assert a._ref[old] == 1 and a._ref[new] == 1
        assert a.page_table[1, 0] == new
        assert a.cow_copies == 1
        a.check()
        a.free_slot(0)
        a.free_slot(1)
        # the original survived for slot 0 and re-parked after
        assert a.lookup_prefix(toks)[1] == 4
        a.check()

    def test_map_shared_needs_empty_slot(self):
        a = self._alloc()
        toks = np.arange(4, dtype=np.int32)
        self._populate(a, toks)
        a.ensure(0, 4)
        with pytest.raises(RuntimeError, match="empty slot"):
            a.map_shared(0, a.lookup_prefix(toks)[0])
        a.free_slot(0)

    def test_check_coverage_past_mapping(self):
        a = self._alloc()
        a.ensure(0, 8)                  # 2 pages = 8 positions
        a.check_coverage(0, 8)          # boundary: next write unmapped
        with pytest.raises(RuntimeError, match="extends past"):
            a.check_coverage(0, 9)

    def test_check_coverage_shared_write_detected(self):
        a = self._alloc()
        toks = np.arange(8, dtype=np.int32)
        self._populate(a, toks)
        pids, _, _ = a.lookup_prefix(toks)
        a.map_shared(0, pids)
        # live length 6: the next write (position 6) lands mid-way
        # into an indexed page — a forgotten copy-on-write
        with pytest.raises(RuntimeError, match="copy-on-write"):
            a.check_coverage(0, 6)
        a.cow(0, 1)
        a.check_coverage(0, 6)          # private now: fine
        a.free_slot(0)

    def test_disabled_prefix_cache_is_plain_allocator(self):
        a = self._alloc(prefix_cache=False)
        toks = np.arange(8, dtype=np.int32)
        pids, cov, _ = a.lookup_prefix(toks)
        a.ensure(0, 8)
        a.register_blocks(0, [], toks, 0, 2)   # no-op when disabled
        a.free_slot(0)
        assert a.cached_pages == 0 and a.free_pages == a.num_pages
        a.check()


# -- engine: bitwise parity cold vs warm -------------------------------------
class TestParity:
    @pytest.mark.parametrize("kv_heads", [4, 2])
    def test_cold_warm_cow_parity(self, kv_heads):
        model, cfg = tiny_model(kv_heads)
        rng = np.random.RandomState(0)
        eng = paged_engine(model, prefix_cache=True)

        donor = rng.randint(0, cfg.vocab_size, (12,)).astype(np.int32)
        want = ref_tokens(donor, kv_heads=kv_heads)
        assert _run_one(eng, donor) == want       # cold populates
        assert eng.alloc.cached_pages == 3
        assert _run_one(eng, donor) == want       # full block hit
        assert eng.alloc.prefix_hits == 1

        # divergence exactly at a block boundary: no CoW needed
        pb = donor.copy()
        pb[8] = (pb[8] + 1) % cfg.vocab_size
        assert _run_one(eng, pb) == ref_tokens(pb, kv_heads=kv_heads)
        assert eng.alloc.cow_copies == 0

        # divergent suffix mid-block: CoW before the first write
        pm = donor.copy()
        pm[10] = (pm[10] + 1) % cfg.vocab_size
        assert _run_one(eng, pm) == ref_tokens(pm, kv_heads=kv_heads)
        assert eng.alloc.cow_copies == 1

        # fully-cached prompt ending mid-page: decode's first append
        # lands in the shared tail page -> CoW
        pt = donor[:10].copy()
        assert _run_one(eng, pt) == ref_tokens(pt, kv_heads=kv_heads)
        assert eng.alloc.cow_copies == 2

        assert eng.alloc.prefix_hits >= 3
        assert eng.alloc.prefix_tokens_saved > 0
        _assert_no_leaks(eng)

        if kv_heads == 4:
            # the dense engine has no prefix-cache machinery at all —
            # and its tokens agree with the paged warm path
            dense = ContinuousBatchingEngine(model, max_batch=2,
                                             max_len=32)
            assert _run_one(dense, donor) == want

    def test_concurrent_sharing_parity(self):
        model, cfg = tiny_model()
        rng = np.random.RandomState(1)
        shared = rng.randint(0, cfg.vocab_size, (8,)).astype(np.int32)
        prompts = [np.concatenate(
            [shared, rng.randint(0, cfg.vocab_size, (2,)).astype(np.int32)])
            for _ in range(3)]
        want = [ref_tokens(p) for p in prompts]

        eng = paged_engine(model, prefix_cache=True)
        srv = Server(eng, segment_steps=4)
        hs = [srv.submit(p, _greedy(6)) for p in prompts]
        got = [list(h.result(timeout=120)) for h in hs]
        hits = eng.alloc.prefix_hits
        srv.shutdown()
        _assert_no_leaks(eng)
        assert got == want
        assert hits >= 1

    def test_chunked_warm_parity(self):
        model, cfg = tiny_model()
        rng = np.random.RandomState(2)
        shared = rng.randint(0, cfg.vocab_size, (16,)).astype(np.int32)
        prompts = [np.concatenate(
            [shared, rng.randint(0, cfg.vocab_size, (6,)).astype(np.int32)])
            for _ in range(2)]
        # chunked admission is bitwise-equal to one-shot (PR 3), so the
        # plain one-shot reference engine is a valid chunked baseline
        want = [ref_tokens(p, n=5) for p in prompts]

        eng = paged_engine(model, prefill_chunk=8, prefix_cache=True)
        srv = Server(eng, segment_steps=4)
        hs = [srv.submit(p, _greedy(5)) for p in prompts]
        got = [list(h.result(timeout=120)) for h in hs]
        saved = eng.alloc.prefix_tokens_saved
        srv.shutdown()
        _assert_no_leaks(eng)
        assert got == want
        # the second admission starts its chunk cursor past the cached
        # coverage: whole chunks of prefill compute skipped
        assert saved >= 8


# -- lifecycle: every retirement decrements, never frees shared --------------
class TestLifecycle:
    def test_cancel_and_reset_state_decrement_leak_free(self):
        model, cfg = tiny_model()
        rng = np.random.RandomState(4)
        shared = rng.randint(0, cfg.vocab_size, (8,)).astype(np.int32)
        p1 = np.concatenate([shared, [1, 2]]).astype(np.int32)
        p2 = np.concatenate([shared, [3, 4]]).astype(np.int32)
        want = ref_tokens(p1, n=10)

        eng = paged_engine(model, prefix_cache=True)
        r1 = eng.add_request(p1, _greedy(10))
        r2 = eng.add_request(p2, _greedy(10))
        eng.decode_segment(2)
        assert eng.alloc.shared_pages == 2
        eng.cancel_request(r2)
        eng.alloc.check()
        # the shared blocks survive for r1 (refcount 2 -> 1)
        assert eng.alloc.shared_pages == 0
        while eng.decode_segment(4):
            pass
        assert list(dict(eng.collect_finished())[r1]) == want
        _assert_no_leaks(eng)

        # reset_state on the same engine: the pools rebuild from
        # zeros, so the content index MUST go with them
        assert eng.alloc.cached_pages > 0
        eng.reset_state()
        assert eng.alloc.cached_pages == 0
        assert eng.alloc.free_pages == eng.num_pages
        assert eng.alloc.lookup_prefix(p1)[1] == 0
        eng.alloc.check()
        # and a fresh cold run still produces the same tokens
        assert _run_one(eng, p1, n=10) == want

    def test_chunked_abort_decrements_leak_free(self):
        model, cfg = tiny_model()
        rng = np.random.RandomState(5)
        shared = rng.randint(0, cfg.vocab_size, (16,)).astype(np.int32)
        eng = paged_engine(model, max_pages=16, prefill_chunk=8,
                           prefix_cache=True)
        donor = np.concatenate(
            [shared, rng.randint(0, cfg.vocab_size, (4,)).astype(np.int32)])
        want = _run_one(eng, donor, n=4)          # populates the cache
        cached = eng.alloc.cached_pages
        assert cached > 0
        # a warm chunked admission maps shared pages at begin_admit;
        # aborting mid-flight must release exactly its references.
        # The uncached tail spans >1 chunk so the first admit_chunk
        # cannot complete the admission
        adm = eng.begin_admit(np.concatenate(
            [shared, rng.randint(0, cfg.vocab_size, (17,)).astype(np.int32)]),
            _greedy(4))
        assert eng.admit_chunk(adm) is False
        eng.abort_admit(adm)
        eng.alloc.check()
        assert eng.alloc.cached_pages == cached
        _assert_no_leaks(eng)
        # the cache is still intact: the donor replays warm, same tokens
        assert _run_one(eng, donor, n=4) == want
        assert eng.alloc.prefix_hits >= 1

        # partial-block warm CHUNKED admission: coverage ends mid-page
        # (18 % 4 != 0), so the shared page copy-on-writes EAGERLY at
        # begin_admit — the claim is atomic with the reservation, gaps
        # before install cannot steal the spare page
        probe = np.concatenate(
            [donor[:18],
             rng.randint(0, cfg.vocab_size, (6,)).astype(np.int32)])
        adm2 = eng.begin_admit(probe, _greedy(4))
        assert eng.alloc.cow_copies >= 1
        while not eng.admit_chunk(adm2):
            pass
        while eng.decode_segment(4):
            pass
        got = list(dict(eng.collect_finished())[adm2.rid])
        assert got == ref_tokens(probe, n=4)
        _assert_no_leaks(eng)

    def test_preempt_releases_only_own_refs(self):
        model, cfg = tiny_model()
        rng = np.random.RandomState(6)
        shared = rng.randint(0, cfg.vocab_size, (8,)).astype(np.int32)
        p1 = np.concatenate([shared, [5, 6]]).astype(np.int32)
        p2 = np.concatenate([shared, [7, 8]]).astype(np.int32)
        want = ref_tokens(p1, n=10)

        eng = paged_engine(model, prefix_cache=True,
                           admission_mode="optimistic")
        r1 = eng.add_request(p1, _greedy(10))
        r2 = eng.add_request(p2, _greedy(10))
        eng.decode_segment(2)
        assert eng.alloc.shared_pages == 2
        toks = eng.preempt_request(r2, reason="pressure")
        assert toks is not None
        eng.alloc.check()
        # r2's references released; the shared blocks stay mapped for
        # r1 — preemption must never free a page another slot reads
        slot1 = [s for s, r in eng._slot_req.items() if r == r1][0]
        row1 = set(eng.alloc._owned[slot1])
        assert all(eng.alloc._ref.get(p, 0) >= 1 for p in row1)
        while eng.decode_segment(4):
            pass
        assert list(dict(eng.collect_finished())[r1]) == want
        _assert_no_leaks(eng)

    def test_preempt_replay_warm_parity_under_pressure(self):
        """Optimistic small pool + shared prefixes: pressure preempts
        a sharer, the replay re-admits WARM, and every request's
        greedy tokens still match an unpressured run (with the
        refcount-aware validator armed per gap)."""
        model, cfg = tiny_model()
        rng = np.random.RandomState(7)
        shared = rng.randint(0, cfg.vocab_size, (8,)).astype(np.int32)
        prompts = [np.concatenate(
            [shared, rng.randint(0, cfg.vocab_size, (2,)).astype(np.int32)])
            for _ in range(3)]
        maxes = [12, 12, 12]

        want = [ref_tokens(p, n=m) for p, m in zip(prompts, maxes)]
        eng = paged_engine(model, max_batch=3, num_pages=12,
                           prefix_cache=True,
                           admission_mode="optimistic")
        srv = Server(eng, segment_steps=4, max_preemptions=10)
        hs = [srv.submit(p, _greedy(m)) for p, m in zip(prompts, maxes)]
        got = [list(h.result(timeout=180)) for h in hs]
        preempts = eng.alloc.preemptions
        srv.shutdown()
        _assert_no_leaks(eng)
        assert got == want
        assert preempts >= 1


# -- LRU reclaim under pressure ----------------------------------------------
class TestReclaim:
    def test_parked_pages_reclaimed_on_demand(self):
        model, cfg = tiny_model()
        rng = np.random.RandomState(9)
        # pool of 8: a retired 12-token donor parks 3 cached pages
        eng = paged_engine(model, max_batch=2, num_pages=8,
                           prefix_cache=True)
        donor = rng.randint(0, cfg.vocab_size, (12,)).astype(np.int32)
        _run_one(eng, donor, n=4)
        assert eng.alloc.cached_pages == 3
        # can_admit == True must mean add_request cannot raise for
        # capacity, even with most of the pool parked
        probe = rng.randint(0, cfg.vocab_size, (8,)).astype(np.int32)
        if eng.can_admit(len(probe), _greedy(4)):
            _run_one(eng, probe, n=4)
        # an unrelated request needing more than the strictly-free
        # pages must succeed by evicting parked cache pages
        other = rng.randint(0, cfg.vocab_size, (12,)).astype(np.int32)
        need = eng.alloc.pages_for(12 + 10)
        assert need > eng.alloc.free_pages
        _run_one(eng, other, n=10)
        eng.alloc.check()
        assert eng.free_slots() == eng.max_batch

    def test_full_pool_request_still_admits(self):
        """A request whose worst case exactly fills the pool must
        admit with the cache on (the probe never demands CoW slack);
        a warm partial-block hit DEGRADES to full blocks instead of
        demanding the page the pool cannot spare — parity holds."""
        model, cfg = tiny_model()
        rng = np.random.RandomState(13)
        eng = paged_engine(model, max_batch=2, num_pages=8,
                           prefix_cache=True)
        donor = rng.randint(0, cfg.vocab_size, (20,)).astype(np.int32)
        g = _greedy(12)                     # 32 tokens = whole pool
        assert eng.can_admit(20, g)
        assert _run_one(eng, donor, n=12) == ref_tokens(donor, n=12)
        # warm, partial-block coverage (18 % 4 != 0), full pool again:
        # the partial page's CoW cannot fit -> hit degrades to 16
        probe = donor[:18].copy()
        gp = _greedy(14)
        assert eng.can_admit(18, gp)
        assert _run_one(eng, probe, n=14) == ref_tokens(probe, n=14)
        assert eng.alloc.cow_copies == 0    # degraded, never CoW'd
        assert eng.alloc.prefix_hits == 1
        eng.alloc.check()


# -- metrics and surfaces ----------------------------------------------------
class TestMetrics:
    def test_counters_pressure_surface_and_series_lifecycle(self):
        from paddle_tpu import monitor

        model, cfg = tiny_model()
        ids = np.random.RandomState(11).randint(
            0, cfg.vocab_size, (10,)).astype(np.int32)
        monitor.enable()
        try:
            eng = paged_engine(model, prefix_cache=True)
            pool = eng.alloc.monitor_pool
            srv = Server(eng, segment_steps=4)
            assert list(srv.submit(ids, _greedy(4)).result(timeout=60))
            assert list(srv.submit(ids, _greedy(4)).result(timeout=60))
            p = srv.pressure()
            assert p["prefix_cache"] is True
            assert p["prefix_hits"] == 1
            assert p["prefix_lookups"] == 2
            assert p["prefix_tokens_saved"] > 0
            assert p["cached_pages"] > 0
            srv.shutdown()

            def series(name):
                snap = monitor.snapshot()["metrics"]
                return [s for s in snap.get(name, {}).get("samples", [])
                        if s["labels"].get("pool") == pool]

            hits = series("paddle_tpu_kv_prefix_hits_total")
            assert hits and hits[0]["value"] == 1
            saved = series("paddle_tpu_kv_prefix_tokens_saved_total")
            assert saved and saved[0]["value"] > 0
            assert series("paddle_tpu_kv_shared_pages") != []
            eng.close()
            for name in ("paddle_tpu_kv_prefix_hits_total",
                         "paddle_tpu_kv_prefix_tokens_saved_total",
                         "paddle_tpu_kv_shared_pages"):
                assert series(name) == [], name
        finally:
            monitor.disable()


@pytest.mark.slow
def test_serve_bench_prefix_ab_smoke(capsys):
    """serve_bench --shared-prefix-len/--cache-prefixes end to end: the
    warm run records a positive hit rate and tokens saved."""
    import json

    from tools.serve_bench import main as bench_main

    rc = bench_main(["--shared-prefix-len", "32", "--cache-prefixes",
                     "on", "--requests", "8", "--rate", "16",
                     "--max-new", "4", "--prompt-len", "2:4",
                     "--num-pages", "64", "--max-pages", "16",
                     "--warmup"])
    assert rc == 0
    recs = {}
    for line in capsys.readouterr().out.splitlines():
        try:
            r = json.loads(line)
            recs[r["metric"]] = r["value"]
        except (json.JSONDecodeError, KeyError):
            continue
    assert recs["serve_prefix_hit_rate"] > 0
    assert recs["serve_prefill_tokens_saved"] > 0
