"""Tests for paddle.device / paddle.reader / paddle.dataset parity.

Reference analogs: test/legacy_test/test_device.py, test_reader_*.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestDevice:
    def test_surface(self):
        from paddle_tpu import device

        assert isinstance(device.get_all_device_type(), list)
        assert device.get_available_device()
        assert device.is_compiled_with_cinn() is True
        assert device.is_compiled_with_rocm() is False
        device.synchronize()  # must not raise

    def test_cuda_shims(self):
        from paddle_tpu.device import cuda

        cuda.empty_cache()
        s = cuda.current_stream()
        s.synchronize()
        e = s.record_event()
        assert e.query() is True
        with cuda.stream_guard(s):
            pass
        assert isinstance(cuda.memory_allocated(), int)
        assert isinstance(cuda.get_device_name(), str)

    def test_xpu_gated(self):
        from paddle_tpu.device import xpu

        with pytest.raises(RuntimeError):
            xpu.synchronize()


class TestReader:
    @staticmethod
    def _r(n=10):
        def reader():
            yield from range(n)

        return reader

    def test_cache_and_firstn(self):
        from paddle_tpu import reader as R

        c = R.cache(self._r(5))
        assert list(c()) == list(range(5)) == list(c())
        assert list(R.firstn(self._r(10), 3)()) == [0, 1, 2]

    def test_map_and_chain_and_compose(self):
        from paddle_tpu import reader as R

        m = R.map_readers(lambda a, b: a + b, self._r(3), self._r(3))
        assert list(m()) == [0, 2, 4]
        ch = R.chain(self._r(2), self._r(2))
        assert list(ch()) == [0, 1, 0, 1]
        co = R.compose(self._r(3), self._r(3))
        assert list(co()) == [(0, 0), (1, 1), (2, 2)]

    def test_compose_misaligned_raises(self):
        from paddle_tpu import reader as R

        co = R.compose(self._r(2), self._r(3))
        with pytest.raises(R.ComposeNotAligned):
            list(co())

    def test_shuffle_preserves_multiset(self):
        from paddle_tpu import reader as R

        out = list(R.shuffle(self._r(20), 5)())
        assert sorted(out) == list(range(20))

    def test_buffered_and_xmap(self):
        from paddle_tpu import reader as R

        assert sorted(R.buffered(self._r(10), 3)()) == list(range(10))
        xm = R.xmap_readers(lambda x: x * 2, self._r(10), 3, 4, order=True)
        assert list(xm()) == [2 * i for i in range(10)]
        xm2 = R.xmap_readers(lambda x: x * 2, self._r(10), 3, 4, order=False)
        assert sorted(xm2()) == [2 * i for i in range(10)]

    def test_multiprocess_reader_merges(self):
        from paddle_tpu import reader as R

        out = list(R.multiprocess_reader([self._r(5), self._r(5)])())
        assert sorted(out) == sorted(list(range(5)) * 2)


class TestDataset:
    def test_common_md5_and_split(self, tmp_path):
        from paddle_tpu.dataset import common

        p = tmp_path / "x.bin"
        p.write_bytes(b"hello")
        assert common.md5file(str(p)) == "5d41402abc4b2a76b9719d911017c592"
        with pytest.raises(RuntimeError, match="egress"):
            common.download("http://x/y.tgz", "m", "0")

    def test_uci_housing_reader_contract(self, tmp_path):
        import numpy as np

        from paddle_tpu import dataset

        raw = np.random.RandomState(0).rand(20, 14).astype(np.float32)
        path = str(tmp_path / "housing.data")
        np.savetxt(path, raw)
        r = dataset.uci_housing.train(data_file=path)
        samples = list(r())
        assert len(samples) == 16
        x, y = samples[0]
        assert x.shape == (13,) and y.shape == (1,)
