"""Device-resident speculative decoding (ISSUE 18).

The tentpole contract, CPU-verified:

- DEVICE DRAFTS, SAME TOKENS: ``spec_mode="device"`` moves the n-gram
  proposer onto the chip (`propose_device`, the fixed-shape twin of
  ``NgramIndex.propose``) and fuses propose→verify→accept→KV-write for
  a whole segment into ONE compiled ``lax.scan`` program — emitted
  tokens stay bitwise identical to host-mode spec AND to plain greedy
  decode, because acceptance only ever decides HOW MANY of the model's
  own picks ship, never WHICH;
- ZERO PER-STEP HOST SYNCS: the fused segment reads back once per
  segment like the plain path — ``spec_stats()["host_syncs"]`` is
  structurally 0 in device mode (host mode counts one per verify
  forward), and the ledger shows ONE ``cb_spec_device_segment``
  program with dispatches == segments, not steps;
- FULL-MATRIX COMPOSITION: dense+paged × MHA+GQA × int8 KV × LoRA mix
  × TP, prefix warm hits with CoW, optimistic-admission preemption and
  engine-restart replay (the history ring rebuilds from
  prompt+generated exactly like the host proposer), all under
  ``debug_pages=True`` and leak-free;
- ZERO POST-WARMUP COMPILES: Server warmup pre-compiles the fused
  program keyed on ``(n_steps, draft_k, spec_draft)`` alone.
"""
import numpy as np
import pytest

import jax

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.inference.generation import (ContinuousBatchingEngine,
                                             GenerationConfig,
                                             PagedContinuousBatchingEngine)
from paddle_tpu.inference.ngram import NgramIndex, propose_device
from paddle_tpu.models import LlamaForCausalLM, llama_config
from paddle_tpu.monitor import ledger
from paddle_tpu.serving import Server


def tiny_model(layers=2, kv_heads=None, seed=0):
    paddle.seed(seed)
    cfg = llama_config("tiny", num_hidden_layers=layers,
                       num_key_value_heads=kv_heads)
    return LlamaForCausalLM(cfg), cfg


def make_adapter(model, seed, targets=("q", "v"), rank=2, scale=0.6):
    _, shapes = model.lora_shapes(targets)
    rng = np.random.default_rng(seed)
    return {t: (rng.standard_normal((rank, d_in)).astype(np.float32)
                * scale,
                rng.standard_normal((d_out, rank)).astype(np.float32)
                * scale)
            for t, (d_in, d_out) in shapes.items()}


@pytest.fixture()
def mon():
    monitor.enable()
    monitor.reset()
    yield monitor
    monitor.reset()
    monitor.disable()


@pytest.fixture()
def led():
    monitor.enable()
    monitor.reset()
    ledger.reset()
    ledger.enable()
    yield ledger
    ledger.disable()
    ledger.reset()
    monitor.reset()
    monitor.disable()


REP = np.tile(np.array([5, 6, 7, 8], np.int32), 6)       # accepting
RND = np.random.RandomState(0).randint(0, 64, (9,)).astype(np.int32)


def _greedy(n, **kw):
    return GenerationConfig(max_new_tokens=n, eos_token_id=None, **kw)


def _spec(n, **kw):
    return GenerationConfig(max_new_tokens=n, eos_token_id=None,
                            speculative=True, **kw)


def _run(eng, prompts, cfgs, steps=4):
    rids = [eng.add_request(p, c) for p, c in zip(prompts, cfgs)]
    while eng.decode_segment(steps):
        pass
    outs = eng.collect_finished()
    return [outs[r] for r in rids]


class TestProposeDeviceUnit:
    """propose_device is the EXACT windowed twin of NgramIndex.propose
    — same longest-suffix-first / most-recent-tie / pad-with-tail
    semantics, as a fixed-shape jax computation."""

    def test_recent_continuation_and_miss(self):
        H = 16
        rows = np.zeros((2, H), np.int32)
        ctx = [1, 2, 3, 9, 1, 2, 3]
        rows[0, :len(ctx)] = ctx            # suffix [1,2,3] seen at 0
        rows[1, :3] = [4, 5, 6]             # total miss -> tail token
        out = np.asarray(propose_device(
            rows, np.array([len(ctx), 3], np.int32), 3, 3))
        assert out[0].tolist() == NgramIndex(3).propose(ctx, 3)
        assert out[0, :2].tolist() == [9, 1]
        assert out[1].tolist() == [6, 6, 6]

    @pytest.mark.parametrize("k", [3, 6])
    def test_fuzz_matches_host_index_exact(self, k):
        """Every context that fits the window drafts IDENTICALLY to
        the host proposer — small vocab forces real n-gram collisions,
        lengths sweep the window edges."""
        H, n_max, cases = 64, 3, 48
        rng = np.random.RandomState(7 + k)
        ctxs, rows, lens = [], np.zeros((cases, H), np.int32), []
        for i in range(cases):
            L = int(rng.randint(2, H + 1))
            ctx = rng.randint(0, 6, (L,)).astype(np.int32)
            ctxs.append([int(t) for t in ctx])
            rows[i, :L] = ctx
            lens.append(L)
        out = np.asarray(propose_device(
            rows, np.asarray(lens, np.int32), k, n_max))
        for i, ctx in enumerate(ctxs):
            want = NgramIndex(n_max).propose(ctx, k)
            assert out[i].tolist() == want, (i, ctx)

    def test_fixed_shape_output(self):
        out = propose_device(np.zeros((3, 8), np.int32),
                             np.array([2, 5, 8], np.int32), 4, 2)
        assert out.shape == (3, 4) and out.dtype == np.int32


class TestKnobs:
    def test_engine_validation(self):
        model, _ = tiny_model(layers=1)
        kw = dict(max_batch=1, max_len=64, draft_k=4)
        with pytest.raises(ValueError, match="spec_mode"):
            ContinuousBatchingEngine(model, spec_mode="gpu", **kw)
        with pytest.raises(ValueError, match="spec_draft"):
            ContinuousBatchingEngine(model, spec_draft="eagle", **kw)
        for bad in (7, True, 2.5, "128"):
            with pytest.raises(ValueError, match="spec_history"):
                ContinuousBatchingEngine(model, spec_history=bad, **kw)
        eng = ContinuousBatchingEngine(model, spec_mode="device", **kw)
        assert eng.spec_mode == "device"
        assert eng.spec_draft == "ngram" and eng.spec_history == 128

    def test_paged_passthrough(self):
        model, _ = tiny_model(layers=1)
        eng = PagedContinuousBatchingEngine(
            model, max_batch=1, num_pages=8, page_size=8, max_pages=4,
            draft_k=3, spec_mode="device", spec_draft="self",
            spec_history=64)
        assert (eng.spec_mode, eng.spec_draft, eng.spec_history) == \
            ("device", "self", 64)

    def test_server_mirror_knob(self):
        model, _ = tiny_model(layers=1)
        eng = ContinuousBatchingEngine(model, max_batch=1, max_len=64,
                                       draft_k=3)
        with pytest.raises(ValueError, match="spec_mode"):
            Server(eng, start=False, spec_mode="turbo")
        assert eng.spec_mode == "host"       # rejected before mutation
        srv = Server(eng, start=False, spec_mode="device")
        assert eng.spec_mode == "device"
        srv.shutdown(drain=False)


class TestBitwiseParity:
    """Device-mode emitted tokens == host-mode == plain decode, per
    slot, across engines and head layouts."""

    @pytest.mark.parametrize("kv_heads", [None, 2],
                             ids=["mha", "gqa"])
    def test_dense_device_vs_host_vs_plain(self, kv_heads):
        model, _ = tiny_model(kv_heads=kv_heads)
        ref = _run(ContinuousBatchingEngine(model, max_batch=2,
                                            max_len=128),
                   [REP, RND], [_greedy(24), _greedy(24)])
        host = _run(ContinuousBatchingEngine(
            model, max_batch=2, max_len=128, draft_k=6),
            [REP, RND], [_spec(24), _spec(24)])
        dev_eng = ContinuousBatchingEngine(
            model, max_batch=2, max_len=128, draft_k=6,
            spec_mode="device")
        dev = _run(dev_eng, [REP, RND], [_spec(24), _spec(24)])
        for a, b, c in zip(ref, host, dev):
            np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(a, c)
        st = dev_eng.spec_stats()
        assert st["accepted"] > 0           # drafts did real work
        assert st["emitted"] == st["slot_steps"] + st["accepted"]
        assert st["host_syncs"] == 0

    @pytest.mark.parametrize("kv_heads", [None, 2],
                             ids=["mha", "gqa"])
    def test_paged_device_vs_plain(self, kv_heads):
        model, _ = tiny_model(kv_heads=kv_heads)
        ref = _run(PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=24, page_size=8,
            max_pages=16, debug_pages=True),
            [REP, RND], [_greedy(24), _greedy(24)])
        eng = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=24, page_size=8,
            max_pages=16, draft_k=6, spec_mode="device",
            debug_pages=True)
        out = _run(eng, [REP, RND], [_spec(24), _spec(24)])
        for a, b in zip(ref, out):
            np.testing.assert_array_equal(a, b)
        assert eng.spec_stats()["accepted"] > 0
        assert eng.alloc.free_pages == eng.num_pages

    def test_self_draft_parity(self):
        """spec_draft="self" (verify-window logits as next drafts)
        changes the draft SOURCE only — greedy parity is structural."""
        model, _ = tiny_model()
        ref = _run(ContinuousBatchingEngine(model, max_batch=2,
                                            max_len=128),
                   [REP, RND], [_greedy(20), _greedy(20)])
        eng = ContinuousBatchingEngine(
            model, max_batch=2, max_len=128, draft_k=4,
            spec_mode="device", spec_draft="self")
        out = _run(eng, [REP, RND], [_spec(20), _spec(20)])
        for a, b in zip(ref, out):
            np.testing.assert_array_equal(a, b)
        st = eng.spec_stats()
        assert st["emitted"] == st["slot_steps"] + st["accepted"]

    def test_budget_smaller_than_draft_window(self):
        model, _ = tiny_model()
        ref = _run(ContinuousBatchingEngine(model, max_batch=1,
                                            max_len=128),
                   [REP], [_greedy(3)])
        eng = ContinuousBatchingEngine(
            model, max_batch=1, max_len=128, draft_k=6,
            spec_mode="device")
        out = _run(eng, [REP], [_spec(3)])
        np.testing.assert_array_equal(ref[0], out[0])
        assert len(out[0]) == 3

    def test_near_max_len_stops_clean(self):
        model, _ = tiny_model()
        ref = _run(ContinuousBatchingEngine(model, max_batch=1,
                                            max_len=32),
                   [REP], [_greedy(8)])
        eng = ContinuousBatchingEngine(
            model, max_batch=1, max_len=32, draft_k=6,
            spec_mode="device")
        out = _run(eng, [REP], [_spec(8)])
        np.testing.assert_array_equal(ref[0], out[0])

    def test_eos_mid_accepted_draft_truncates(self):
        """eos landing inside an accepted window truncates ON DEVICE
        (the fused program's per-step mask) — bitwise vs plain."""
        model, _ = tiny_model()
        probe = ContinuousBatchingEngine(model, max_batch=1,
                                         max_len=128)
        free = _run(probe, [REP], [_greedy(24)])[0]
        eos = int(free[7])
        kw = dict(max_new_tokens=24, eos_token_id=eos)
        ref = _run(ContinuousBatchingEngine(model, max_batch=1,
                                            max_len=128),
                   [REP], [GenerationConfig(**kw)])[0]
        eng = ContinuousBatchingEngine(
            model, max_batch=1, max_len=128, draft_k=6,
            spec_mode="device")
        out = _run(eng, [REP],
                   [GenerationConfig(speculative=True, **kw)])[0]
        np.testing.assert_array_equal(ref, out)
        assert out[-1] == eos and len(out) < 24
        # the slot retired cleanly — engine is idle and reusable
        assert eng.free_slots() == 1
        out2 = _run(eng, [RND], [_spec(6)])[0]
        assert len(out2) == 6

    def test_int8_kv_parity(self):
        """Quantized paged KV: device-mode spec matches the SAME
        engine config decoded plain (int8 changes numerics vs bf16,
        never spec-vs-plain agreement)."""
        model, _ = tiny_model()
        kw = dict(max_batch=2, num_pages=24, page_size=8, max_pages=16,
                  kv_dtype="int8", debug_pages=True)
        ref = _run(PagedContinuousBatchingEngine(model, **kw),
                   [REP, RND], [_greedy(20), _greedy(20)])
        eng = PagedContinuousBatchingEngine(
            model, draft_k=6, spec_mode="device", **kw)
        out = _run(eng, [REP, RND], [_spec(20), _spec(20)])
        for a, b in zip(ref, out):
            np.testing.assert_array_equal(a, b)
        assert eng.alloc.free_pages == eng.num_pages

    def test_lora_mix_parity(self):
        """A base + adapter mix in one device-mode batch: per-slot
        adapter vectors ride the fused program unchanged."""
        model, _ = tiny_model()
        kw = dict(max_batch=2, num_pages=32, page_size=8, max_pages=8,
                  lora_capacity=2, lora_rank=4, lora_targets=("q", "v"),
                  debug_pages=True)
        params = make_adapter(model, 11)
        ref_eng = PagedContinuousBatchingEngine(model, **kw)
        ref_eng.load_adapter("a1", params)
        ref = _run(ref_eng, [REP, REP],
                   [_greedy(12, adapter="a1"), _greedy(12)])
        eng = PagedContinuousBatchingEngine(
            model, draft_k=4, spec_mode="device", **kw)
        eng.load_adapter("a1", params)
        out = _run(eng, [REP, REP],
                   [_spec(12, adapter="a1"), _spec(12)])
        np.testing.assert_array_equal(ref[0], out[0])
        np.testing.assert_array_equal(ref[1], out[1])
        # the adapter actually changed the base row's trajectory
        assert list(ref[0]) != list(ref[1])


class TestComposition:
    """THE acceptance scenario: paged int8 KV + prefix warm hit + LoRA
    + optimistic admission with a pool sized to force preemption, all
    speculating in device mode under debug_pages — bitwise vs plain,
    leak-free (preempt-replay rebuilds the history ring from
    prompt+generated exactly like the host proposer)."""

    def test_full_matrix_pressure_bitwise(self):
        model, _ = tiny_model()
        kw = dict(kv_dtype="int8", lora_capacity=2, lora_rank=4,
                  lora_targets=("q", "v"), debug_pages=True)
        params = make_adapter(model, 11)
        big = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=32, page_size=8,
            max_pages=16, **kw)
        big.load_adapter("a1", params)
        ref = _run(big, [REP, REP[:20]],
                   [_greedy(24, adapter="a1"), _greedy(24)])
        # 10 pages = 80 tokens for two requests needing (24+24)+(20+24)
        # worst case — optimistic admission with spec growth forces
        # preemption mid-decode
        eng = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=10, page_size=8,
            max_pages=16, admission_mode="optimistic", draft_k=6,
            spec_mode="device", prefix_cache=True, **kw)
        eng.load_adapter("a1", params)
        srv = Server(eng, segment_steps=4, max_preemptions=10,
                     speculative=True, idle_wait_s=0.005)
        try:
            h1 = srv.submit(REP, _greedy(24, adapter="a1"))
            h2 = srv.submit(REP[:20], _greedy(24))
            np.testing.assert_array_equal(ref[0], h1.result(timeout=180))
            np.testing.assert_array_equal(ref[1], h2.result(timeout=180))
            assert eng.alloc.preemptions >= 1, \
                "pool was sized to force at least one preemption"
            # warm re-run of the first prompt hits the prefix cache
            # and still matches bitwise
            h3 = srv.submit(REP, _greedy(24, adapter="a1"))
            np.testing.assert_array_equal(ref[0], h3.result(timeout=180))
            assert eng.alloc.prefix_hits >= 1
            assert srv.drain(timeout=60)
        finally:
            srv.shutdown(drain=False)
        assert (eng.alloc.free_pages + eng.alloc.cached_pages
                == eng.num_pages)
        assert eng.spec_stats()["host_syncs"] == 0


class TestRestartReplay:
    """PR 4 composition: an engine-scoped fault mid-decode — replay
    re-prefills prompt + generated and re-seeds the device history
    ring from the full context, greedy parity holds."""

    def test_device_spec_through_restart_bitwise(self):
        from paddle_tpu.inference.generation import EngineFault
        from paddle_tpu.testing.faults import FaultPlan, FaultyEngine

        model, _ = tiny_model()
        clean = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=24, page_size=8, max_pages=8,
            debug_pages=True)
        ref = _run(clean, [REP], [_greedy(20)])
        plan = FaultPlan().raise_at("decode", nth=2,
                                    exc=EngineFault("injected"))
        raw = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=24, page_size=8, max_pages=8,
            draft_k=6, spec_mode="device", debug_pages=True)
        srv = Server(FaultyEngine(raw, plan), segment_steps=3,
                     restart_backoff_s=0.01, speculative=True)
        try:
            out = srv.submit(REP, _greedy(20)).result(timeout=180)
            np.testing.assert_array_equal(ref[0], out)
            assert srv.restarts == 1
            assert srv.drain(timeout=60)
        finally:
            srv.shutdown(drain=False)
        assert raw.free_slots() == raw.max_batch
        assert raw.alloc.free_pages == raw.num_pages


class TestZeroCompiles:
    def test_warmup_precompiles_fused_segment(self, mon):
        """Server warmup compiles the fused device-segment program;
        a real speculating request then pays ZERO further compiles of
        it — and zero host syncs."""
        model, _ = tiny_model()
        eng = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=24, page_size=8, max_pages=8,
            spec_mode="device")
        srv = Server(eng, segment_steps=3, warmup=True, draft_k=4,
                     speculative=True)
        try:
            assert srv.wait_ready(120) and srv.status == "ok"
            pre = monitor.jit_miss_by_fn()
            assert pre.get("cb_spec_device_segment", 0) >= 1, pre
            out = srv.submit(REP, _greedy(12)).result(timeout=120)
            assert len(out) == 12
            post = monitor.jit_miss_by_fn()
            assert (post.get("cb_spec_device_segment")
                    == pre.get("cb_spec_device_segment")), (pre, post)
            st = eng.spec_stats()
            assert st["forwards"] > 0          # it DID speculate
            assert st["host_syncs"] == 0
        finally:
            srv.shutdown(drain=False)

    def test_program_keys_on_steps_and_k_only(self, mon):
        """Two segment widths compile two programs; rerunning either
        reuses its first compile (per-request state never keys it)."""
        model, _ = tiny_model(layers=1)
        eng = ContinuousBatchingEngine(
            model, max_batch=1, max_len=64, draft_k=3,
            spec_mode="device")
        for _ in range(2):
            _run(eng, [REP[:8]], [_spec(10)], steps=4)
        _run(eng, [REP[:8]], [_spec(6)], steps=2)
        misses = monitor.jit_miss_by_fn()
        assert misses.get("cb_spec_device_segment") == 2, misses


class TestLedgerDispatches:
    def test_one_program_dispatches_equal_segments(self, led):
        """The ledger sees ONE cb_spec_device_segment program whose
        dispatch count equals the number of SEGMENTS run — the fused
        loop never dispatches per verify step."""
        model, _ = tiny_model(layers=1)
        eng = ContinuousBatchingEngine(
            model, max_batch=2, max_len=128, draft_k=4,
            spec_mode="device")
        eng.add_request(REP, _spec(16))
        eng.add_request(RND, _spec(16))
        segs = 0
        while True:
            segs += 1
            if not eng.decode_segment(3):
                break
        recs = [r for r in ledger.profile()["programs"].values()
                if r["name"] == "cb_spec_device_segment"]
        assert len(recs) == 1, recs
        assert recs[0]["dispatches"] == segs
        assert recs[0]["compiles"] == 1


class TestStatsAndSyncs:
    def test_host_and_device_accounting_agree(self):
        """Same workload, both modes: identical speculative accounting
        (equal acceptance — the drafts are the same), differing ONLY
        in host_syncs: one per verify forward vs structurally zero."""
        model, _ = tiny_model()
        outs, stats = {}, {}
        for mode in ("host", "device"):
            eng = ContinuousBatchingEngine(
                model, max_batch=2, max_len=128, draft_k=4,
                spec_mode=mode)
            outs[mode] = _run(eng, [REP, RND], [_spec(12), _spec(12)])
            stats[mode] = eng.spec_stats()
        for a, b in zip(outs["host"], outs["device"]):
            np.testing.assert_array_equal(a, b)
        h, d = stats["host"], stats["device"]
        for key_ in ("proposed", "accepted", "forwards", "slot_steps",
                     "emitted", "acceptance_rate",
                     "tokens_per_forward"):
            assert h[key_] == d[key_], (key_, h, d)
        assert h["host_syncs"] == h["forwards"] > 0
        assert d["host_syncs"] == 0
        assert h["host_syncs_per_token"] > 0.0
        assert d["host_syncs_per_token"] == 0.0
        assert d["emitted"] == d["slot_steps"] + d["accepted"]

    def test_identity_survives_reset_state(self):
        model, _ = tiny_model()
        eng = ContinuousBatchingEngine(
            model, max_batch=2, max_len=128, draft_k=4,
            spec_mode="device")
        _run(eng, [REP, RND], [_spec(12), _spec(12)])
        st = eng.spec_stats()
        eng.reset_state()
        assert eng._spec == {}          # proposers die with the slots
        st2 = eng.spec_stats()
        assert st2["emitted"] == st["emitted"]
        assert st2["emitted"] == st2["slot_steps"] + st2["accepted"]
        # and the engine decodes again post-reset, still device mode
        out = _run(eng, [REP], [_spec(6)])
        assert len(out[0]) == 6
        assert eng.spec_stats()["host_syncs"] == 0


@pytest.mark.skipif(
    jax.device_count() < 4,
    reason="tensor-parallel tests need >= 4 devices (run under "
           "XLA_FLAGS=--xla_force_host_platform_device_count=8)")
class TestTensorParallel:
    """The history ring replicates across the mesh — TP=2 device-mode
    spec is bitwise vs TP=1 plain (same pinned seed, TP changes
    placement, never values)."""

    def _engine(self, tp, **kw):
        paddle.seed(0)
        cfg = llama_config("tiny", num_hidden_layers=1)
        model = LlamaForCausalLM(cfg)
        kw.setdefault("max_batch", 2)
        kw.setdefault("num_pages", 32)
        kw.setdefault("page_size", 8)
        kw.setdefault("max_pages", 8)
        kw.setdefault("debug_pages", True)
        return PagedContinuousBatchingEngine(model, tp_degree=tp, **kw)

    def test_tp2_device_spec_bitwise(self):
        ref = _run(self._engine(1), [REP, RND],
                   [_greedy(16), _greedy(16)])
        eng = self._engine(2, draft_k=4, spec_mode="device")
        out = _run(eng, [REP, RND], [_spec(16), _spec(16)])
        for a, b in zip(ref, out):
            np.testing.assert_array_equal(a, b)
        st = eng.spec_stats()
        assert st["accepted"] > 0 and st["host_syncs"] == 0
        assert eng.alloc.free_pages == eng.num_pages
