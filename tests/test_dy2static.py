"""dy2static control-flow conversion tests (VERDICT r2 #8).

Reference contract (jit/dy2static/program_translator.py:305 + ifelse/loop/
logical transformers): data-dependent Python `if`/`while` must either run
correctly (converted to graph control flow — here lax.cond/lax.while_loop)
or fail loudly with actionable guidance; never silently specialize.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit import to_static
from paddle_tpu.jit.dy2static import (convert_ifelse, convert_to_static,
                                      convert_while)


def t(x):
    return paddle.to_tensor(np.asarray(x, dtype=np.float32))


class TestConvertIfElse:
    def test_tensor_predicate_both_sides(self):
        @to_static
        def f(x):
            if paddle.sum(x) > 0:
                y = x * 2
            else:
                y = x - 10
            return y

        out = f(t([1.0, 2.0]))
        np.testing.assert_allclose(np.asarray(out.value), [2.0, 4.0])
        out = f(t([-5.0, 2.0]))
        np.testing.assert_allclose(np.asarray(out.value), [-15.0, -8.0])

    def test_branch_updates_existing_var(self):
        @to_static
        def f(x):
            y = x + 1
            if paddle.max(x) > 3:
                y = y * 10
            return y

        np.testing.assert_allclose(np.asarray(f(t([5.0])).value), [60.0])
        np.testing.assert_allclose(np.asarray(f(t([1.0])).value), [2.0])

    def test_python_predicate_keeps_python_semantics(self):
        calls = []

        @to_static
        def f(x, flag):
            if flag:                       # concrete bool: no lax.cond
                calls.append(1)
                return x * 2
            return x

        out = f(t([3.0]), True)
        np.testing.assert_allclose(np.asarray(out.value), [6.0])

    def test_nested_if(self):
        @to_static
        def f(x):
            if paddle.sum(x) > 0:
                if paddle.max(x) > 10:
                    y = x * 100
                else:
                    y = x * 2
            else:
                y = x * 0
            return y

        np.testing.assert_allclose(np.asarray(f(t([20.0])).value), [2000.0])
        np.testing.assert_allclose(np.asarray(f(t([1.0])).value), [2.0])
        np.testing.assert_allclose(np.asarray(f(t([-1.0])).value), [-0.0])


class TestConvertWhile:
    def test_tensor_trip_count(self):
        """THE reference pattern: loop whose trip count depends on a
        tensor value (silently specializing this was the r2 bug)."""

        @to_static
        def f(x):
            s = paddle.zeros([1])
            while paddle.sum(s) < paddle.sum(x):
                s = s + 1.0
            return s

        np.testing.assert_allclose(np.asarray(f(t([7.3])).value), [8.0])
        np.testing.assert_allclose(np.asarray(f(t([2.0])).value), [2.0])

    def test_while_multiple_carried_vars(self):
        @to_static
        def f(n):
            i = paddle.zeros([])
            acc = paddle.zeros([])
            while i < n:
                acc = acc + i
                i = i + 1
            return acc

        assert float(f(t(5.0)).value) == 10.0  # 0+1+2+3+4

    def test_logical_ops_on_tensors(self):
        @to_static
        def f(x):
            i = paddle.zeros([])
            while (i < 10) and (i < x):
                i = i + 1
            return i

        assert float(f(t(4.0)).value) == 4.0
        assert float(f(t(99.0)).value) == 10.0


class TestLoudErrors:
    def test_break_in_tensor_while_raises_actionably(self):
        @to_static
        def f(x):
            i = paddle.zeros([])
            while i < paddle.sum(x):
                i = i + 1
                if float(i) > 3:        # forces concretization mid-trace
                    break
            return i

        with pytest.raises(RuntimeError) as ei:
            f(t([10.0]))
        msg = str(ei.value)
        assert "dy2static" in msg and "lax.cond" in msg.replace(
            "lax.while_loop", "lax.cond") or "Supported rewrites" in msg

    def test_tensor_bool_outside_if_raises_actionably(self):
        @to_static
        def f(x):
            flags = [bool(v > 0) for v in [paddle.sum(x)]]
            return x if flags[0] else -x

        with pytest.raises(RuntimeError, match="Supported rewrites"):
            f(t([1.0]))


class TestRuntimeConverters:
    def test_convert_ifelse_concrete(self):
        r = convert_ifelse(True, lambda a: a + 1, lambda a: a - 1, (5,))
        assert r == 6

    def test_convert_while_concrete(self):
        out = convert_while(lambda i: i < 3, lambda i: (i + 1,), (0,))
        assert out == (3,)

    def test_transform_fallback_no_source(self):
        # builtins have no retrievable source: must return fn unchanged
        assert convert_to_static(len) is len


class TestGradThroughControlFlow:
    def test_grad_through_cond(self):
        from paddle_tpu import nn

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                h = self.fc(x)
                if paddle.sum(h) > 0:
                    out = h * 3
                else:
                    out = h * 5
                return paddle.sum(out)

        m = to_static(M())
        x = t(np.ones((2, 4)))
        loss = m(x)
        loss.backward()
        g = m.fc.weight.grad
        assert g is not None
        assert np.isfinite(np.asarray(g.value)).all()


class TestConcreteSemanticsPreserved:
    """Regression guards: converted code must keep plain-Python semantics
    for concrete predicates (branch-asymmetric and loop-born locals)."""

    def test_branch_asymmetric_assignment(self):
        from paddle_tpu.jit.dy2static import convert_to_static

        def f(flag, x):
            if flag:
                msg = x + 1
            return x

        g = convert_to_static(f)
        assert g(False, 3) == 3
        assert g(True, 3) == 3

    def test_loop_born_local_visible_after(self):
        from paddle_tpu.jit.dy2static import convert_to_static

        def f(n):
            i = 0
            while i < n:
                out = i * 2
                i = i + 1
            return out

        g = convert_to_static(f)
        assert g(3) == 4

    def test_use_before_assign_fails_at_use(self):
        from paddle_tpu.jit.dy2static import convert_to_static

        def f(flag):
            if flag:
                v = 1
            return v + 1   # value-use of a maybe-unbound local

        g = convert_to_static(f)
        assert g(True) == 2
        with pytest.raises(UnboundLocalError, match="'v'"):
            g(False)
