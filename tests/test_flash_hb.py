"""Head-batched BSHD flash kernel numerics (PERF.md headroom #2).

Must match the dense reference attention in forward AND gradients —
same contract as tests/test_flash_attention.py for the per-head kernel.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.flash_attention_hb import (flash_attention_bshd_hb,
                                               supports_hb)

# The hb kernel's ORIGINAL batched-3D-dot form was Mosaic-rejected on-chip
# ("Bad lhs type", experiments/tpu_session.log 2026-07-31); it has been
# restructured to per-head 2D dots but that form is unverified on hardware,
# so supports_hb refuses device routing (and this module skips on device)
# unless the PADDLE_TPU_HB_ON_DEVICE=1 escape hatch opts in — the session
# script's on-chip test step sets it.
import os

from paddle_tpu.ops.flash_attention_kernel import _interpret

pytestmark = pytest.mark.skipif(
    not _interpret() and os.environ.get("PADDLE_TPU_HB_ON_DEVICE") != "1",
    reason="hb kernel not hardware-verified (original batched-dot form "
           "was Mosaic-rejected; set PADDLE_TPU_HB_ON_DEVICE=1 to test "
           "the per-head-unrolled restructure on-chip)")


def ref_attention(q, k, v, causal, offset):
    # [B, S, H, D] dense reference
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) / np.sqrt(q.shape[-1])
    if causal:
        iq = jnp.arange(q.shape[1])[:, None]
        ik = jnp.arange(k.shape[1])[None, :]
        s = jnp.where((ik <= iq + offset)[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, vf)


def make(b=2, sq=32, sk=32, h=4, d=8, seed=0):
    rng = np.random.RandomState(seed)
    q = rng.randn(b, sq, h, d).astype(np.float32)
    k = rng.randn(b, sk, h, d).astype(np.float32)
    v = rng.randn(b, sk, h, d).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


class TestForward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = make()
        out = flash_attention_bshd_hb(q, k, v, causal=causal)
        ref = ref_attention(q, k, v, causal, 0)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_cross_lengths_bottom_right(self):
        q, k, v = make(sq=16, sk=32)
        out = flash_attention_bshd_hb(q, k, v, causal=True)
        ref = ref_attention(q, k, v, True, 16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_supports_gate(self):
        assert supports_hb((2, 32, 4, 8), (2, 32, 4, 8), 0.0)
        assert not supports_hb((2, 32, 8, 8), (2, 32, 4, 8), 0.0)  # GQA
        assert not supports_hb((2, 32, 4, 8), (2, 32, 4, 8), 0.1)  # dropout


class TestBackward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_reference(self, causal):
        q, k, v = make(b=1, sq=16, sk=16, h=2, d=8)

        def f_ours(q, k, v):
            return jnp.sum(flash_attention_bshd_hb(q, k, v, causal=causal)
                           ** 2)

        def f_ref(q, k, v):
            return jnp.sum(ref_attention(q, k, v, causal, 0) ** 2)

        g_ours = jax.grad(f_ours, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g_ours, g_ref, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-5, err_msg=name)

    def test_grads_cross_length(self):
        q, k, v = make(b=1, sq=8, sk=24, h=2, d=8)

        def f_ours(q, k, v):
            return jnp.sum(flash_attention_bshd_hb(q, k, v, causal=True)
                           * jnp.arange(8.0)[None, :, None, None])

        def f_ref(q, k, v):
            return jnp.sum(ref_attention(q, k, v, True, 16)
                           * jnp.arange(8.0)[None, :, None, None])

        g_ours = jax.grad(f_ours, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g_ours, g_ref, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-5, err_msg=name)


class TestOffsetNegative:
    """sq > sk causal (offset < 0): rows with NO valid key must produce
    zero output and zero, finite grads — the lse there is ~-1e30 and
    exp(0)=1 garbage would leak without the valid re-mask (mirrors
    test_flash_attention.py's empty-rows regression for the HB kernel)."""

    def test_empty_rows_zero_output(self):
        q, k, v = make(b=1, sq=32, sk=16, h=2, d=8)
        out = np.asarray(flash_attention_bshd_hb(q, k, v, causal=True))
        # offset = -16: rows i < 16 attend keys <= i-16 -> none
        np.testing.assert_allclose(out[:, :16], 0.0, atol=1e-6)
        # non-empty rows match the reference
        ref = np.asarray(ref_attention(q, k, v, True, -16))
        np.testing.assert_allclose(out[:, 16:], ref[:, 16:], rtol=2e-5,
                                   atol=2e-5)

    def test_empty_rows_grads_zero_and_finite(self):
        q, k, v = make(b=1, sq=32, sk=16, h=2, d=8)

        def f(q, k, v):
            return jnp.sum(flash_attention_bshd_hb(q, k, v, causal=True)
                           ** 2)

        gq, gk, gv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        for g in (gq, gk, gv):
            assert np.isfinite(np.asarray(g)).all()
        np.testing.assert_allclose(np.asarray(gq)[:, :16], 0.0, atol=1e-6)

    def test_supports_hb_vmem_gate(self):
        # 32 heads at 512 blocks = 64MB of scores+probs: must be rejected
        assert not supports_hb((1, 1024, 32, 128), (1, 1024, 32, 128), 0.0)
        assert supports_hb((1, 1024, 8, 128), (1, 1024, 8, 128), 0.0)
