"""Ragged / continuous batching decode (VERDICT r3 #6).

The reference decode kernel serves mixed-length batches after
remove_padding (fused_multi_transformer_op.cu.h:1641) with per-sequence
lengths (:1680). ContinuousBatchingEngine must:

1. produce EXACTLY the per-request outputs of the dense engine (greedy),
   regardless of batch composition (rows are independent),
2. admit new requests between decode segments (more requests than slots),
3. keep per-row lengths: rows advance independently, dead rows don't move.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.inference.generation import (CausalLMEngine,
                                             ContinuousBatchingEngine,
                                             GenerationConfig)
from paddle_tpu.models import LlamaForCausalLM
from paddle_tpu.models.llama import LlamaConfig


def tiny_model(seed=0):
    np.random.seed(seed)
    cfg = LlamaConfig(vocab_size=97, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=4, max_position_embeddings=128)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def prompts_mixed(rng, vocab, lens):
    return [rng.randint(0, vocab, (n,)).astype(np.int32) for n in lens]


class TestRaggedParity:
    def test_mixed_lengths_match_dense_engine(self):
        m = tiny_model()
        rng = np.random.RandomState(3)
        lens = [5, 11, 3, 8]
        prompts = prompts_mixed(rng, 97, lens)
        cfg = GenerationConfig(max_new_tokens=9)

        dense = CausalLMEngine(m, max_batch=1, max_len=64)
        want = [dense.generate(p[None], cfg)[0, len(p):] for p in prompts]

        eng = ContinuousBatchingEngine(m, max_batch=4, max_len=64)
        got = eng.serve(prompts, cfg, segment_steps=4)
        for i, (w, g) in enumerate(zip(want, got)):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w),
                                          err_msg=f"request {i}")

    def test_admission_between_segments(self):
        """5 requests through 2 slots: later requests are admitted only
        after earlier ones retire — outputs must still match the dense
        engine per request."""
        m = tiny_model()
        rng = np.random.RandomState(4)
        lens = [4, 9, 6, 3, 7]
        prompts = prompts_mixed(rng, 97, lens)
        cfg = GenerationConfig(max_new_tokens=6)

        dense = CausalLMEngine(m, max_batch=1, max_len=64)
        want = [dense.generate(p[None], cfg)[0, len(p):] for p in prompts]

        eng = ContinuousBatchingEngine(m, max_batch=2, max_len=64)
        got = eng.serve(prompts, cfg, segment_steps=3)
        for i, (w, g) in enumerate(zip(want, got)):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w),
                                          err_msg=f"request {i}")
        # every slot freed afterwards
        assert sorted(eng._free) == [0, 1]
        assert not eng._slot_req

    def test_eos_stops_row_early(self):
        """Force an EOS hit: the row must retire early and its slot be
        reused, with the other row unaffected."""
        m = tiny_model()
        rng = np.random.RandomState(5)
        prompts = prompts_mixed(rng, 97, [6, 6, 6])
        # run once greedy to discover a token that actually appears, then
        # use it as the eos id for one request
        probe = CausalLMEngine(m, max_batch=1, max_len=64)
        base = probe.generate(prompts[0][None],
                              GenerationConfig(max_new_tokens=8))[0, 6:]
        eos = int(base[2])             # third generated token
        cfg = GenerationConfig(max_new_tokens=8, eos_token_id=eos)

        dense = CausalLMEngine(m, max_batch=1, max_len=64)
        want = [dense.generate(p[None], cfg)[0, len(p):] for p in prompts]

        def trim(seq):                  # dense pads with eos after the hit
            seq = list(np.asarray(seq))
            if eos in seq:
                return seq[:seq.index(eos) + 1]
            return seq

        eng = ContinuousBatchingEngine(m, max_batch=2, max_len=64)
        got = eng.serve(prompts, cfg, segment_steps=4)
        for i, (w, g) in enumerate(zip(want, got)):
            assert list(np.asarray(g)) == trim(w), (i, g, trim(w))


class TestRaggedState:
    def test_dead_rows_do_not_advance(self):
        m = tiny_model()
        rng = np.random.RandomState(6)
        eng = ContinuousBatchingEngine(m, max_batch=3, max_len=64)
        cfg = GenerationConfig(max_new_tokens=20)
        eng.add_request(rng.randint(0, 97, (5,)).astype(np.int32), cfg)
        lens_before = np.asarray(eng.lens).copy()
        assert lens_before[0] == 5 and lens_before[1] == 0
        eng.decode_segment(4, cfg)
        lens_after = np.asarray(eng.lens)
        assert lens_after[0] == 9          # live row advanced 4 steps
        assert lens_after[1] == 0 and lens_after[2] == 0  # empty slots froze

    def test_lengths_are_per_row(self):
        """Two rows admitted with different prompt lengths keep distinct
        positions after a shared segment."""
        m = tiny_model()
        rng = np.random.RandomState(7)
        eng = ContinuousBatchingEngine(m, max_batch=2, max_len=64)
        cfg = GenerationConfig(max_new_tokens=30)
        eng.add_request(rng.randint(0, 97, (4,)).astype(np.int32), cfg)
        eng.add_request(rng.randint(0, 97, (12,)).astype(np.int32), cfg)
        eng.decode_segment(5, cfg)
        lens = np.asarray(eng.lens)
        assert lens[0] == 9 and lens[1] == 17, lens
