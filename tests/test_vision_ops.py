"""Tests for paddle.vision.ops and the transforms tail.

Reference analogs: test/legacy_test/test_roi_align_op.py,
test_roi_pool_op.py, test_nms_op.py, test_matrix_nms_op.py,
test_prior_box_op.py, test_yolo_box_op.py, test_deformable_conv_op.py,
test_distribute_fpn_proposals_op.py, test_transforms.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.vision.ops as vops
import paddle_tpu.vision.transforms as T
from paddle_tpu.nn import functional as F


def t(x):
    return paddle.to_tensor(np.asarray(x))


class TestRoIFamily:
    def test_roi_align_uniform_region(self):
        x = np.zeros((1, 1, 8, 8), np.float32)
        x[0, 0, 2:6, 2:6] = 1.0
        out = vops.roi_align(t(x), t([[2.0, 2.0, 6.0, 6.0]]),
                             t(np.asarray([1], np.int32)), 2,
                             aligned=False)
        o = np.asarray(out.numpy())
        assert o.shape == (1, 1, 2, 2)
        assert o[0, 0, 0, 0] > 0.95          # interior bin fully inside
        assert o.mean() > 0.7                # edge bins interpolate out

    def test_roi_align_batch_mapping(self):
        x = np.zeros((2, 1, 4, 4), np.float32)
        x[1] = 1.0  # second image all ones
        boxes = np.asarray([[0, 0, 4, 4], [0, 0, 4, 4]], np.float32)
        out = vops.roi_align(t(x), t(boxes), t(np.asarray([1, 1],
                                               np.int32)), 2)
        o = np.asarray(out.numpy())
        assert o[0].max() == 0.0 and o[1].min() > 0.9

    def test_roi_pool_max(self):
        x = np.zeros((1, 1, 8, 8), np.float32)
        x[0, 0, 3, 3] = 7.0
        out = vops.roi_pool(t(x), t([[0.0, 0.0, 7.0, 7.0]]),
                            t(np.asarray([1], np.int32)), 2)
        assert np.asarray(out.numpy()).max() == 7.0

    def test_psroi_pool_channel_groups(self):
        oh = ow = 2
        out_c = 3
        x = np.random.RandomState(0).rand(1, out_c * oh * ow, 8,
                                          8).astype(np.float32)
        out = vops.psroi_pool(t(x), t([[0.0, 0.0, 8.0, 8.0]]),
                              t(np.asarray([1], np.int32)), oh)
        assert np.asarray(out.numpy()).shape == (1, out_c, oh, ow)


class TestNMS:
    def test_nms_suppression_order(self):
        b = np.asarray([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]],
                       np.float32)
        s = np.asarray([0.9, 0.8, 0.7], np.float32)
        keep = np.asarray(vops.nms(t(b), 0.5, t(s)).numpy())
        assert keep.tolist() == [0, 2]

    def test_nms_category_aware(self):
        b = np.asarray([[0, 0, 10, 10], [1, 1, 11, 11]], np.float32)
        s = np.asarray([0.9, 0.8], np.float32)
        cats = np.asarray([0, 1])
        keep = np.asarray(vops.nms(t(b), 0.5, t(s), t(cats),
                                   categories=[0, 1]).numpy())
        assert keep.tolist() == [0, 1]  # different class: no suppression

    def test_matrix_nms_decays_overlaps(self):
        b = np.zeros((1, 3, 4), np.float32)
        b[0, 0] = [0, 0, 10, 10]
        b[0, 1] = [0.5, 0.5, 10.5, 10.5]
        b[0, 2] = [20, 20, 30, 30]
        sc = np.zeros((1, 2, 3), np.float32)
        sc[0, 1] = [0.9, 0.85, 0.8]
        out, nums = vops.matrix_nms(t(b), t(sc), score_threshold=0.1,
                                    post_threshold=0.0, nms_top_k=10,
                                    keep_top_k=10, background_label=0)
        o = np.asarray(out.numpy())
        assert int(np.asarray(nums.numpy())[0]) == 3
        assert o[:, 1].min() < 0.5  # the overlapping box got decayed
        assert o[:, 1].max() == pytest.approx(0.9)


class TestAnchors:
    def test_prior_box_shapes_and_range(self):
        pb, pv = vops.prior_box(
            t(np.zeros((1, 3, 4, 4), np.float32)),
            t(np.zeros((1, 3, 32, 32), np.float32)),
            min_sizes=[8.0], aspect_ratios=(1.0, 2.0), flip=True,
            clip=True)
        b = np.asarray(pb.numpy())
        assert b.shape[:2] == (4, 4) and b.shape[-1] == 4
        assert b.min() >= 0.0 and b.max() <= 1.0

    def test_box_coder_encode_decode_roundtrip(self):
        rng = np.random.RandomState(0)
        priors = np.asarray([[10, 10, 30, 30], [5, 5, 20, 25]], np.float32)
        var = np.full((2, 4), 0.1, np.float32)
        targets = np.asarray([[12, 11, 28, 33]], np.float32)
        enc = vops.box_coder(t(priors), t(var), t(targets),
                             code_type="encode_center_size")
        dec = vops.box_coder(t(priors), t(var), enc,
                             code_type="decode_center_size", axis=0)
        d = np.asarray(dec.numpy())
        np.testing.assert_allclose(d[0, 0], targets[0], atol=1e-3)

    def test_yolo_box_shapes(self):
        yb, ys = vops.yolo_box(
            t(np.random.RandomState(0).rand(2, 3 * 7, 4, 4)
              .astype(np.float32)),
            t(np.asarray([[64, 64], [64, 64]], np.int32)),
            anchors=[10, 13, 16, 30, 33, 23], class_num=2,
            conf_thresh=0.01, downsample_ratio=16)
        assert np.asarray(yb.numpy()).shape == (2, 48, 4)
        assert np.asarray(ys.numpy()).shape == (2, 48, 2)

    def test_yolo_box_iou_aware_gated(self):
        with pytest.raises(NotImplementedError):
            vops.yolo_box(t(np.zeros((1, 21, 4, 4), np.float32)),
                          t(np.asarray([[64, 64]], np.int32)),
                          anchors=[10, 13, 16, 30, 33, 23], class_num=2,
                          conf_thresh=0.01, downsample_ratio=16,
                          iou_aware=True)

    def test_yolo_loss_runs_and_grads(self):
        x = t(np.random.RandomState(1).rand(1, 3 * 7, 4, 4)
              .astype(np.float32))
        x.stop_gradient = False
        loss = vops.yolo_loss(
            x, t(np.asarray([[[0.5, 0.5, 0.3, 0.3]]], np.float32)),
            t(np.asarray([[1]], np.int64)),
            anchors=[10, 13, 16, 30, 33, 23], anchor_mask=[0, 1, 2],
            class_num=2, ignore_thresh=0.5, downsample_ratio=16)
        paddle.sum(loss).backward()
        g = np.asarray(x.grad.numpy())
        assert np.all(np.isfinite(g)) and np.abs(g).sum() > 0


class TestDeformConv:
    def test_zero_offset_matches_dense_conv(self):
        rng = np.random.RandomState(0)
        x = rng.randn(1, 2, 6, 6).astype(np.float32)
        w = rng.randn(3, 2, 3, 3).astype(np.float32)
        off = np.zeros((1, 2 * 9, 4, 4), np.float32)
        dc = vops.deform_conv2d(t(x), t(off), t(w))
        ref = F.conv2d(t(x), t(w))
        np.testing.assert_allclose(np.asarray(dc.numpy()),
                                   np.asarray(ref.numpy()), atol=1e-4)

    def test_layer_with_mask(self):
        layer = vops.DeformConv2D(2, 3, 3)
        x = t(np.random.RandomState(2).randn(1, 2, 6, 6)
              .astype(np.float32))
        off = t(np.zeros((1, 18, 4, 4), np.float32))
        mask = t(np.ones((1, 9, 4, 4), np.float32))
        out = layer(x, off, mask=mask)
        assert tuple(out.shape) == (1, 3, 4, 4)


class TestProposals:
    def test_distribute_fpn_per_image_counts(self):
        rois = np.asarray([[0, 0, 10, 10], [0, 0, 100, 100],
                           [0, 0, 12, 12]], np.float32)
        outs, restore, nums = vops.distribute_fpn_proposals(
            t(rois), 2, 4, 3, 30,
            rois_num=t(np.asarray([2, 1], np.int32)))
        counts = [np.asarray(n.numpy()) for n in nums]
        assert all(c.shape == (2,) for c in counts)
        total = np.stack(counts).sum(0)
        np.testing.assert_array_equal(total, [2, 1])
        assert sorted(np.asarray(restore.numpy()).tolist()) == [0, 1, 2]

    def test_generate_proposals(self):
        rng = np.random.RandomState(3)
        H = W = 4
        A = 3
        scores = rng.rand(1, A, H, W).astype(np.float32)
        deltas = (rng.rand(1, A * 4, H, W).astype(np.float32) - 0.5)
        anchors = rng.rand(H, W, A, 4).astype(np.float32) * 10
        anchors[..., 2:] += 20
        var = np.full((H, W, A, 4), 0.1, np.float32)
        rois, rscores, rnum = vops.generate_proposals(
            t(scores), t(deltas), t(np.asarray([[64.0, 64.0]],
                                               np.float32)),
            t(anchors), t(var), pre_nms_top_n=12, post_nms_top_n=5,
            return_rois_num=True)
        assert np.asarray(rois.numpy()).shape[1] == 4
        assert int(np.asarray(rnum.numpy())[0]) <= 5


class TestFileOps:
    def test_read_file_and_decode_jpeg(self, tmp_path):
        from PIL import Image

        p = str(tmp_path / "x.jpg")
        Image.fromarray(np.full((8, 8, 3), 128, np.uint8)).save(p)
        raw = vops.read_file(p)
        assert np.asarray(raw.numpy()).dtype == np.uint8
        img = vops.decode_jpeg(raw)
        assert np.asarray(img.numpy()).shape == (3, 8, 8)


class TestTransformsTail:
    def _img(self):
        return np.random.RandomState(0).randint(
            0, 255, (32, 32, 3)).astype(np.float32)

    def test_color_adjust_identity_factors(self):
        img = self._img()
        np.testing.assert_allclose(T.adjust_brightness(img, 1.0), img)
        np.testing.assert_allclose(T.adjust_contrast(img, 1.0), img,
                                   atol=1e-3)
        np.testing.assert_allclose(T.adjust_saturation(img, 1.0), img,
                                   atol=1e-3)
        np.testing.assert_allclose(T.adjust_hue(img, 0.0), img, atol=2.0)

    def test_rotate_full_turn_is_identity_interior(self):
        img = self._img()
        out = T.rotate(img, 360.0)
        assert np.abs(out[8:24, 8:24] - img[8:24, 8:24]).mean() < 2.0

    def test_affine_shear_tilts_vertical_line(self):
        img = np.zeros((21, 21, 1), np.float32)
        img[:, 10] = 1.0
        sh = T.affine(img, shear=(30, 0))
        rows = [int(np.argmax(sh[r, :, 0])) for r in (2, 18)]
        assert rows[0] != rows[1]

    def test_perspective_identity(self):
        img = self._img()
        pts = [(0, 0), (31, 0), (31, 31), (0, 31)]
        np.testing.assert_allclose(T.perspective(img, pts, pts), img,
                                   atol=1e-3)

    def test_random_classes_shapes(self):
        img = self._img()
        assert T.ColorJitter(0.2, 0.2, 0.2, 0.1)._apply_image(
            img).shape == img.shape
        assert T.RandomResizedCrop(16)._apply_image(img).shape[:2] \
            == (16, 16)
        out = T.RandomErasing(prob=1.0)._apply_image(img)
        assert out.shape == img.shape and not np.allclose(out, img)
        assert T.RandomRotation(10)._apply_image(img).shape == img.shape
        assert T.RandomPerspective(prob=1.0)._apply_image(
            img).shape == img.shape
