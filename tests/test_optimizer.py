"""Optimizer tests — parity vs torch.optim on identical trajectories."""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt


def t2n(t):
    return np.asarray(t.numpy(), dtype=np.float32)


def _pair_models():
    m = nn.Linear(4, 3)
    tm = torch.nn.Linear(4, 3)
    with torch.no_grad():
        tm.weight.copy_(torch.tensor(t2n(m.weight).T))
        tm.bias.copy_(torch.tensor(t2n(m.bias)))
    return m, tm


def _run_both(m, tm, optimizer, toptimizer, steps=5):
    for i in range(steps):
        x = np.random.randn(8, 4).astype(np.float32)
        y = np.random.randn(8, 3).astype(np.float32)
        loss = paddle.mean((m(paddle.to_tensor(x)) - paddle.to_tensor(y)) ** 2)
        loss.backward()
        optimizer.step()
        optimizer.clear_grad()

        tloss = ((tm(torch.tensor(x)) - torch.tensor(y)) ** 2).mean()
        toptimizer.zero_grad()
        tloss.backward()
        toptimizer.step()
    np.testing.assert_allclose(t2n(m.weight), tm.weight.detach().numpy().T,
                               rtol=1e-4, atol=1e-5)


class TestOptimizers:
    def test_sgd_vs_torch(self):
        m, tm = _pair_models()
        _run_both(m, tm, opt.SGD(0.1, parameters=m.parameters()),
                  torch.optim.SGD(tm.parameters(), lr=0.1))

    def test_momentum_vs_torch(self):
        m, tm = _pair_models()
        _run_both(m, tm,
                  opt.Momentum(0.1, 0.9, parameters=m.parameters()),
                  torch.optim.SGD(tm.parameters(), lr=0.1, momentum=0.9))

    def test_momentum_nesterov(self):
        m, tm = _pair_models()
        _run_both(m, tm,
                  opt.Momentum(0.05, 0.9, parameters=m.parameters(), use_nesterov=True),
                  torch.optim.SGD(tm.parameters(), lr=0.05, momentum=0.9, nesterov=True))

    def test_adam_vs_torch(self):
        m, tm = _pair_models()
        _run_both(m, tm,
                  opt.Adam(0.01, parameters=m.parameters()),
                  torch.optim.Adam(tm.parameters(), lr=0.01))

    def test_adamw_vs_torch(self):
        m, tm = _pair_models()
        _run_both(m, tm,
                  opt.AdamW(0.01, parameters=m.parameters(), weight_decay=0.1),
                  torch.optim.AdamW(tm.parameters(), lr=0.01, weight_decay=0.1))

    def test_rmsprop_vs_torch(self):
        m, tm = _pair_models()
        _run_both(m, tm,
                  opt.RMSProp(0.01, rho=0.9, epsilon=1e-8, parameters=m.parameters()),
                  torch.optim.RMSprop(tm.parameters(), lr=0.01, alpha=0.9, eps=1e-8))

    def test_adagrad_vs_torch(self):
        m, tm = _pair_models()
        _run_both(m, tm,
                  opt.Adagrad(0.05, epsilon=1e-10, parameters=m.parameters()),
                  torch.optim.Adagrad(tm.parameters(), lr=0.05))

    def test_l2_weight_decay_coupled(self):
        # paddle weight_decay on SGD == torch SGD weight_decay (coupled L2)
        m, tm = _pair_models()
        _run_both(m, tm,
                  opt.SGD(0.1, parameters=m.parameters(), weight_decay=0.01),
                  torch.optim.SGD(tm.parameters(), lr=0.1, weight_decay=0.01))

    def test_grad_clip_global_norm(self):
        m = nn.Linear(4, 3)
        o = opt.SGD(1.0, parameters=m.parameters(),
                    grad_clip=nn.ClipGradByGlobalNorm(0.001))
        before = t2n(m.weight).copy()
        loss = paddle.sum(m(paddle.randn([2, 4])) * 100)
        loss.backward()
        o.step()
        delta = np.linalg.norm(t2n(m.weight) - before) ** 2 + \
            np.linalg.norm(t2n(m.bias) - np.zeros(3)) ** 2
        assert np.sqrt(delta) <= 0.0011

    def test_state_dict_roundtrip(self):
        m = nn.Linear(4, 3)
        o = opt.Adam(0.01, parameters=m.parameters())
        loss = paddle.sum(m(paddle.randn([2, 4])))
        loss.backward()
        o.step()
        sd = o.state_dict()
        o2 = opt.Adam(0.01, parameters=m.parameters())
        loss = paddle.sum(m(paddle.randn([2, 4])))
        loss.backward()
        o2.step()  # populate accumulators
        o2.set_state_dict(sd)
        k = m.weight.name
        np.testing.assert_allclose(
            np.asarray(o2._accumulators["moment1"][k]),
            np.asarray(o._accumulators["moment1"][k]))

    def test_lbfgs_quadratic(self):
        p = nn.Parameter(paddle.to_tensor(np.array([3.0, -2.0], np.float32)).value)
        o = opt.LBFGS(parameters=[p], max_iter=20)

        def closure():
            o.clear_grad()
            loss = paddle.sum((paddle.to_tensor(p) - paddle.to_tensor(
                np.array([1.0, 1.0], np.float32))) ** 2)
            from paddle_tpu.core.autograd import run_backward
            # p is a leaf; recompute loss through p directly
            p2 = paddle.to_tensor(p.value, stop_gradient=False)
            target = paddle.to_tensor(np.array([1.0, 1.0], np.float32))
            l2 = paddle.sum((p2 - target) ** 2)
            l2.backward()
            p.grad = p2.grad
            return l2

        o.step(closure)
        np.testing.assert_allclose(t2n(p), [1.0, 1.0], atol=1e-4)


class TestLRSchedulers:
    def test_step_decay(self):
        s = opt.lr.StepDecay(0.1, step_size=2, gamma=0.5)
        lrs = []
        for _ in range(5):
            lrs.append(s())
            s.step()
        np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025])

    def test_cosine(self):
        s = opt.lr.CosineAnnealingDecay(1.0, T_max=10)
        assert abs(s() - 1.0) < 1e-9
        s.step(5)
        assert abs(s() - 0.5) < 1e-9
        s.step(10)
        assert abs(s() - 0.0) < 1e-9

    def test_linear_warmup_wraps_scheduler(self):
        inner = opt.lr.StepDecay(0.1, step_size=100)
        s = opt.lr.LinearWarmup(inner, warmup_steps=4, start_lr=0.0, end_lr=0.1)
        vals = []
        for _ in range(6):
            vals.append(s())
            s.step()
        np.testing.assert_allclose(vals[:4], [0.0, 0.025, 0.05, 0.075])
        np.testing.assert_allclose(vals[4:], [0.1, 0.1])

    def test_optimizer_uses_scheduler(self):
        m = nn.Linear(2, 2)
        sched = opt.lr.StepDecay(0.5, step_size=1, gamma=0.1)
        o = opt.SGD(sched, parameters=m.parameters())
        assert o.get_lr() == 0.5
        sched.step()
        assert abs(o.get_lr() - 0.05) < 1e-12

    def test_noam(self):
        s = opt.lr.NoamDecay(d_model=512, warmup_steps=10, learning_rate=1.0)
        s.step(5)
        expected = (512 ** -0.5) * min(5 ** -0.5, 5 * 10 ** -1.5)
        assert abs(s() - expected) < 1e-9

    def test_reduce_on_plateau(self):
        s = opt.lr.ReduceOnPlateau(0.1, patience=1, factor=0.5)
        s.step(1.0)
        s.step(1.0)
        s.step(1.0)
        assert abs(s() - 0.05) < 1e-12
