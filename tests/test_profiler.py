"""Profiler tests (reference analog: test/legacy_test/test_profiler.py,
test_newprofiler.py): scheduler state machine, span capture via RecordEvent
and the op hook, chrome-trace export shape, summary stats."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler as prof
from paddle_tpu.profiler import (Profiler, ProfilerState, ProfilerTarget,
                                 RecordEvent, export_chrome_tracing,
                                 make_scheduler)


class TestScheduler:
    def test_make_scheduler_cycle(self):
        s = make_scheduler(closed=1, ready=1, record=2, repeat=1)
        assert s(0) == ProfilerState.CLOSED
        assert s(1) == ProfilerState.READY
        assert s(2) == ProfilerState.RECORD
        assert s(3) == ProfilerState.RECORD_AND_RETURN
        assert s(4) == ProfilerState.CLOSED  # repeat=1 exhausted

    def test_skip_first(self):
        s = make_scheduler(closed=0, ready=0, record=1, skip_first=2)
        assert s(0) == ProfilerState.CLOSED
        assert s(1) == ProfilerState.CLOSED
        assert s(2) == ProfilerState.RECORD_AND_RETURN

    def test_tuple_scheduler(self):
        p = Profiler(scheduler=(1, 3))
        assert p.scheduler(0) == ProfilerState.CLOSED
        assert p.scheduler(1) in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN)


class TestCapture:
    def test_record_event_spans(self):
        p = Profiler(targets=[ProfilerTarget.CPU])
        p.start()
        with RecordEvent("my_span"):
            paddle.matmul(paddle.ones([8, 8]), paddle.ones([8, 8]))
        p.stop()
        names = [e[0] for e in p._events]
        assert "my_span" in names
        assert any(n == "op::matmul" for n in names)

    def test_hook_removed_after_stop(self):
        from paddle_tpu.core import op_hooks

        p = Profiler()
        p.start()
        p.stop()
        assert op_hooks.op_span_hook is None

    def test_step_schedule_arms_and_disarms(self):
        fired = []
        p = Profiler(scheduler=make_scheduler(closed=1, ready=0, record=1,
                                              repeat=1),
                     on_trace_ready=lambda pr: fired.append(True))
        p.start()               # step 0: CLOSED
        paddle.tanh(paddle.ones([4]))
        p.step()                # → step 1: RECORD_AND_RETURN (armed)
        paddle.tanh(paddle.ones([4]))
        p.step()                # → step 2: CLOSED (disarm + callback)
        p.stop()
        assert fired
        assert any(e[0] == "op::tanh" for e in p._events)

    def test_closed_state_records_nothing(self):
        p = Profiler(scheduler=lambda s: ProfilerState.CLOSED)
        p.start()
        with RecordEvent("ghost"):
            pass
        p.stop()
        assert not p._events

    def test_op_hook_fans_out_to_monitor(self):
        """The apply_op choke point serves BOTH consumers at once: the
        profiler records spans and chains to the monitor's histogram
        hook installed underneath it."""
        from paddle_tpu import monitor
        from paddle_tpu.core import op_hooks

        monitor.enable()
        monitor.reset()
        try:
            p = Profiler()
            p.start()
            paddle.matmul(paddle.ones([8, 8]), paddle.ones([8, 8]))
            p.stop()
            # profiler saw the span...
            assert any(e[0] == "op::matmul" for e in p._events)
            # ...the profiler restored the monitor hook on stop...
            assert op_hooks.op_span_hook is not None
            # ...and the monitor histogram got the same dispatch
            snap = monitor.snapshot()["metrics"]
            samples = snap["paddle_tpu_op_latency_seconds"]["samples"]
            mm = [s for s in samples if s["labels"]["op"] == "matmul"]
            assert mm and mm[0]["count"] >= 1
        finally:
            monitor.reset()
            monitor.disable()
        assert op_hooks.op_span_hook is None

    def test_reenable_under_profiler_does_not_cycle(self):
        """Re-installing the monitor hook while a profiler hook (whose
        chained prev IS the monitor hook) owns the slot must be a no-op
        — chaining a second copy would recurse on every dispatch."""
        from paddle_tpu import monitor

        monitor.enable()
        try:
            p = Profiler()
            p.start()
            monitor.enable()   # idempotent re-enable mid-window
            monitor.disable()  # can't leave the chain (profiler on top)
            monitor.enable()   # ...and must not chain a second copy
            paddle.tanh(paddle.ones([4]))  # RecursionError if cyclic
            p.stop()
            paddle.tanh(paddle.ones([4]))
        finally:
            monitor.disable()
        from paddle_tpu.core import op_hooks

        assert op_hooks.op_span_hook is None

    def test_stranded_hook_does_not_double_count_later_windows(self):
        """A profiler window that stops while the monitor sits on top
        strands its hook in the chain; it must stay DEAD in later
        windows (no duplicate spans) and be pruned when the monitor
        restores the slot."""
        from paddle_tpu import monitor
        from paddle_tpu.core import op_hooks

        p1 = Profiler()
        p1.start()
        monitor.enable()   # installs on top of p1's hook
        try:
            p1.stop()      # p1's hook is stranded under the monitor
            p2 = Profiler()
            p2.start()
            paddle.matmul(paddle.ones([4, 4]), paddle.ones([4, 4]))
            p2.stop()
            names = [e[0] for e in p2._events]
            assert names.count("op::matmul") == 1, names
        finally:
            monitor.disable()
        # restore skipped the dead stranded hook: slot is empty again
        assert op_hooks.op_span_hook is None

    def test_profiler_stop_preserves_monitor_enabled_after_start(self):
        """Monitor enabled AFTER the profiler armed: stop() must not rip
        the monitor hook out of the slot (it only restores when the slot
        still holds its own hook)."""
        from paddle_tpu import monitor
        from paddle_tpu.core import op_hooks

        p = Profiler()
        p.start()
        monitor.enable()
        monitor.reset()
        try:
            p.stop()
            paddle.matmul(paddle.ones([4, 4]), paddle.ones([4, 4]))
            snap = monitor.snapshot()["metrics"]
            mm = [s for s in
                  snap["paddle_tpu_op_latency_seconds"]["samples"]
                  if s["labels"]["op"] == "matmul"]
            assert mm and mm[0]["count"] >= 1
        finally:
            monitor.reset()
            monitor.disable()
        # disable() prunes the stranded dead profiler hook on restore
        assert op_hooks.op_span_hook is None


class TestExport:
    def test_chrome_trace_format(self, tmp_path):
        p = Profiler()
        p.start()
        with RecordEvent("outer"):
            paddle.exp(paddle.ones([4]))
        p.stop()
        path = p.export(str(tmp_path / "trace.json"))
        data = json.load(open(path))
        assert "traceEvents" in data
        ev = data["traceEvents"][0]
        assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(ev)
        assert ev["ph"] == "X"

    def test_on_trace_ready_exporter(self, tmp_path):
        p = Profiler(on_trace_ready=export_chrome_tracing(str(tmp_path)))
        p.start()
        with RecordEvent("x"):
            pass
        p.stop()
        files = list(tmp_path.glob("*.paddle_trace.json"))
        assert files

    def test_summary(self, capsys):
        p = Profiler()
        p.start()
        for _ in range(3):
            paddle.matmul(paddle.ones([4, 4]), paddle.ones([4, 4]))
        p.stop()
        stats = p.summary()
        assert stats["op::matmul"]["calls"] == 3
        assert "op::matmul" in capsys.readouterr().out
