"""Multi-tenant LoRA serving suite (ISSUE 13): one engine, many
fine-tunes.

Covers the batched-adapter contract on CPU:

- :class:`~paddle_tpu.serving.adapters.AdapterRegistry` lifecycle:
  load/unload/acquire/release, UNLOAD DEFERRAL while live slots
  reference the index, index recycling, capacity/rank/shape
  validation, resident snapshot;
- BITWISE PARITY (greedy): a mixed-adapter batch produces exactly the
  tokens of each adapter run alone (dense + paged, MHA + GQA) through
  ONE compiled segment program, and base-model rows on a LoRA-enabled
  engine are bitwise what a LoRA-free engine produces (index 0's
  zero rows gather an exact 0.0 delta);
- the MERGED-WEIGHTS oracle: a single adapter's output matches a model
  whose projection weights were merged with ``W + (B A)^T * alpha/r``
  (allclose — fp summation order differs by construction);
- ONE-compiled-program invariant: post-``warmup`` a mixed-adapter run
  (hot load included) pays ZERO monitored jit compiles;
- per-adapter PREFIX-CACHE NAMESPACES: cross-adapter warm hits are
  zero (generation-salted chain hashes), same-adapter hits still fire
  with bitwise warm-vs-cold parity, and reloading a name never hits
  the old weights' pages;
- composition with the serving stack: preempt-replay under forced
  optimistic pressure (adapter_idx survives replay), PR 4 engine
  restart replay, PR 7 speculative decoding, kv_dtype="int8" — all
  ``debug_pages=True``, leak-free;
- per-tenant quotas: a tenant over quota DEFERS while other tenants
  admit past it;
- the HTTP surface: strict unknown-field 400 (the typo'd ``adaptor``
  case), ``adapter`` round-trip, ``POST /adapters/load|unload``,
  registry state in ``/healthz``;
- router adapter affinity: requests prefer replicas with the adapter
  resident.
"""
import json
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.inference.generation import (
    ContinuousBatchingEngine, EngineFault, GenerationConfig,
    PagedContinuousBatchingEngine)
from paddle_tpu.serving import AdapterRegistry, Server
from paddle_tpu.serving.queue import RequestQueue

_MODELS = {}


def tiny_model(kv_heads=4):
    """One tiny llama per kv-head layout (4 = MHA, 2 = GQA), shared by
    the whole module: jit programs are keyed on shapes, so reusing the
    model keeps the suite to a handful of compiles."""
    if kv_heads not in _MODELS:
        paddle.seed(0)
        from paddle_tpu.models import LlamaForCausalLM, llama_config
        cfg = llama_config("tiny", num_hidden_layers=1,
                           num_key_value_heads=kv_heads)
        _MODELS[kv_heads] = (LlamaForCausalLM(cfg), cfg)
    return _MODELS[kv_heads]


def make_adapter(model, seed, targets=("q", "v"), rank=2, scale=0.6):
    """Seeded numpy (A, B) factors per target, sized from the model's
    lora_shapes hook. ``scale`` is large enough that adapter outputs
    actually diverge from base on the untrained tiny model."""
    _, shapes = model.lora_shapes(targets)
    rng = np.random.default_rng(seed)
    return {t: (rng.standard_normal((rank, d_in)).astype(np.float32)
                * scale,
                rng.standard_normal((d_out, rank)).astype(np.float32)
                * scale)
            for t, (d_in, d_out) in shapes.items()}


def paged_engine(model, max_batch=4, num_pages=64, page_size=4,
                 max_pages=8, **kw):
    kw.setdefault("debug_pages", True)
    kw.setdefault("lora_capacity", 3)
    kw.setdefault("lora_rank", 4)
    kw.setdefault("lora_targets", ("q", "v"))
    return PagedContinuousBatchingEngine(
        model, max_batch=max_batch, num_pages=num_pages,
        page_size=page_size, max_pages=max_pages, **kw)


def _greedy(n, adapter=None, eos=None):
    return GenerationConfig(max_new_tokens=n, adapter=adapter,
                            eos_token_id=eos)


def _run_one(eng, ids, n=6, adapter=None, seg=4):
    rid = eng.add_request(np.asarray(ids, np.int32),
                          _greedy(n, adapter))
    while eng.decode_segment(seg):
        pass
    return list(dict(eng.collect_finished())[rid])


def _assert_no_leaks(eng):
    assert eng.free_slots() == eng.max_batch
    assert eng.alloc.used_pages == 0
    assert (eng.alloc.free_pages + eng.alloc.cached_pages
            == eng.num_pages)
    eng.alloc.check()


PROMPT = list(range(1, 9))


# -- registry lifecycle ------------------------------------------------------
class TestAdapterRegistry:
    def _reg(self, capacity=2, rank=4):
        return AdapterRegistry(capacity, rank, ("q",), 1,
                               {"q": (8, 8)}, np.float32, "eng-test")

    def _ab(self, r=2, seed=0):
        rng = np.random.default_rng(seed)
        return (rng.standard_normal((r, 8)).astype(np.float32),
                rng.standard_normal((8, r)).astype(np.float32))

    def test_load_acquire_release_unload(self):
        reg = self._reg()
        idx = reg.load("a", {"q": self._ab()})
        assert idx == 1 and "a" in reg
        assert reg.acquire("a") == idx
        reg.release(idx)
        assert reg.unload("a") is True      # freed immediately
        assert "a" not in reg
        assert reg.resident()["free"] == 2

    def test_unload_defers_while_referenced(self):
        reg = self._reg()
        idx = reg.load("a", {"q": self._ab()})
        reg.acquire("a")
        assert reg.unload("a") is False     # deferred
        with pytest.raises(ValueError, match="unknown adapter"):
            reg.acquire("a")                # new requests rejected
        assert reg.resident()["draining"] == ["a"]
        reg.release(idx)                    # last live ref completes it
        assert reg.resident() == {"capacity": 2, "resident": 0,
                                  "free": 2, "adapters": [],
                                  "draining": []}

    def test_index_recycled_and_salt_fresh(self):
        reg = self._reg()
        i1 = reg.load("a", {"q": self._ab()})
        s1 = reg.salt(i1)
        reg.unload("a")
        i2 = reg.load("a", {"q": self._ab(seed=1)})
        assert i2 == i1                     # recycled
        assert reg.salt(i2) != s1           # but a FRESH namespace
        assert reg.salt(0) == b""           # base keeps the bare root

    def test_validation(self):
        reg = self._reg()
        reg.load("a", {"q": self._ab()})
        with pytest.raises(ValueError, match="already loaded"):
            reg.load("a", {"q": self._ab()})
        with pytest.raises(ValueError, match="not in the"):
            reg.load("b", {"nope": self._ab()})
        with pytest.raises(ValueError, match="rank"):
            reg.load("b", {"q": self._ab(r=5)})   # over the bank rank
        with pytest.raises(ValueError, match="B must be"):
            a, b = self._ab()
            reg.load("b", {"q": (a, b[:, :1])})   # rank mismatch
        reg.load("b", {"q": self._ab()})
        with pytest.raises(ValueError, match="registry full"):
            reg.load("c", {"q": self._ab()})

    def test_alpha_folds_into_bank(self):
        reg = self._reg()
        a, b = self._ab()
        reg.load("x", {"q": (a, b)}, alpha=4)   # r=2 -> scale 2.0
        A, B = reg.bank["q"]
        np.testing.assert_allclose(np.asarray(B[0, 1, :, :2]), b * 2.0,
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(A[0, 1, :2]), a,
                                   rtol=1e-6)
        # padded rank rows are zero
        assert not np.asarray(A[0, 1, 2:]).any()

    def test_name_bound_matches_generation_config(self):
        # a name loadable here but unreachable by GenerationConfig
        # would occupy a bank index forever
        reg = self._reg()
        with pytest.raises(ValueError, match="256"):
            reg.load("x" * 300, {"q": self._ab()})
        with pytest.raises(ValueError, match="adapter"):
            GenerationConfig(max_new_tokens=1, adapter="x" * 300)

    def test_release_all_completes_deferred(self):
        reg = self._reg()
        reg.load("a", {"q": self._ab()})
        reg.acquire("a")
        reg.unload("a")
        reg.release_all()                   # engine reset_state path
        assert reg.resident()["free"] == 2


# -- bitwise parity ----------------------------------------------------------
class TestLoraParity:
    @pytest.mark.parametrize("kv_heads", [4, 2])
    def test_mixed_batch_matches_solo_paged(self, kv_heads):
        model, _ = tiny_model(kv_heads)
        eng = paged_engine(model)
        eng.load_adapter("a1", make_adapter(model, 11))
        eng.load_adapter("a2", make_adapter(model, 22, scale=0.9))
        solo = {name: _run_one(eng, PROMPT, adapter=name)
                for name in (None, "a1", "a2")}
        assert solo["a1"] != solo[None] or solo["a2"] != solo[None]
        rids = {name: eng.add_request(np.asarray(PROMPT, np.int32),
                                      _greedy(6, name))
                for name in (None, "a1", "a2")}
        while eng.decode_segment(4):
            pass
        fin = eng.collect_finished()
        for name, rid in rids.items():
            assert list(fin[rid]) == solo[name], name
        _assert_no_leaks(eng)
        eng.close()

    def test_mixed_batch_matches_solo_dense(self):
        model, _ = tiny_model(4)
        eng = ContinuousBatchingEngine(model, max_batch=3, max_len=32,
                                       lora_capacity=2, lora_rank=4,
                                       lora_targets=("q", "v"))
        eng.load_adapter("a1", make_adapter(model, 11))
        solo = {name: _run_one(eng, PROMPT, adapter=name)
                for name in (None, "a1")}
        rids = {name: eng.add_request(np.asarray(PROMPT, np.int32),
                                      _greedy(6, name))
                for name in (None, "a1")}
        while eng.decode_segment(4):
            pass
        fin = eng.collect_finished()
        for name, rid in rids.items():
            assert list(fin[rid]) == solo[name], name
        eng.close()

    def test_base_rows_bitwise_vs_lora_free_engine(self):
        model, _ = tiny_model(4)
        plain = paged_engine(model, lora_capacity=0)
        ref = _run_one(plain, PROMPT)
        eng = paged_engine(model)
        eng.load_adapter("a1", make_adapter(model, 11))
        assert _run_one(eng, PROMPT) == ref   # delta gathered at row 0
        #                                       is exactly 0.0
        plain.close()
        eng.close()

    def test_merged_weights_oracle(self):
        """One adapter through the batched gather == the same deltas
        merged into the projection weights (allclose: the low-rank
        product and the merged matmul sum in different orders)."""
        model, cfg = tiny_model(4)
        params = make_adapter(model, 33, targets=("q", "v", "gate"),
                              rank=2, scale=0.3)
        eng = paged_engine(model, lora_capacity=1,
                           lora_targets=("q", "v", "gate"))
        eng.load_adapter("m", params, alpha=4)   # scale 2.0
        got = np.asarray(eng._run_prefill(
            np.asarray([PROMPT], np.int32), len(PROMPT),
            model.init_cache(1, 16), aidx=1)[0])
        # merge W' = W + (B A)^T * alpha/r into a fresh seeded clone
        paddle.seed(0)
        from paddle_tpu.models import LlamaForCausalLM, llama_config
        merged = LlamaForCausalLM(llama_config(
            "tiny", num_hidden_layers=1, num_key_value_heads=4))
        layer = merged.model.layers[0]
        projs = {"q": layer.self_attn.q_proj, "v": layer.self_attn.v_proj,
                 "gate": layer.mlp.gate_proj}
        for t, (a, b) in params.items():
            w = projs[t].weight
            w.set_value(np.asarray(w.value) + (b @ a).T * 2.0)
        eng2 = paged_engine(merged, lora_capacity=0)
        want = np.asarray(eng2._run_prefill(
            np.asarray([PROMPT], np.int32), len(PROMPT),
            merged.init_cache(1, 16))[0])
        np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)
        eng.close()
        eng2.close()

    def test_rank_padding_exact(self):
        """An r=2 adapter in an r=4 bank decodes bitwise like the same
        adapter in an r=2 bank — zero-padded factor rows contribute an
        exact 0."""
        model, _ = tiny_model(4)
        params = make_adapter(model, 44, rank=2)
        wide = paged_engine(model, lora_rank=4)
        narrow = paged_engine(model, lora_rank=2)
        wide.load_adapter("p", params)
        narrow.load_adapter("p", params)
        assert (_run_one(wide, PROMPT, adapter="p")
                == _run_one(narrow, PROMPT, adapter="p"))
        wide.close()
        narrow.close()


# -- one compiled program ----------------------------------------------------
class TestOneProgram:
    def test_zero_compiles_post_warmup(self):
        """warmup() pre-compiles the widened programs; afterwards a hot
        adapter load + a mixed-adapter batch pay ZERO monitored jit
        compiles — the whole point of the bank-as-argument design."""
        monitor.enable()
        model, _ = tiny_model(4)
        eng = paged_engine(model, prefill_chunk=8)
        eng.warmup(segment_steps=4)

        def misses():
            return monitor.jit_miss_by_fn()

        before = misses()
        eng.load_adapter("a1", make_adapter(model, 11))
        eng.load_adapter("a2", make_adapter(model, 22))
        for name in (None, "a1", "a2"):
            eng.add_request(np.asarray(PROMPT, np.int32),
                            _greedy(6, name))
        while eng.decode_segment(4):
            pass
        eng.collect_finished()
        after = misses()
        assert after == before, (before, after)
        _assert_no_leaks(eng)
        eng.close()


# -- hot load / unload through the serving gap -------------------------------
class TestHotLoadUnload:
    def test_server_load_unload_deferred(self):
        model, _ = tiny_model(4)
        eng = paged_engine(model)
        srv = Server(eng, segment_steps=2)
        try:
            srv.load_adapter("hot", make_adapter(model, 55))
            ref = list(srv.submit(np.asarray(PROMPT, np.int32),
                                  _greedy(8, "hot")).result(30))
            h = srv.submit(np.asarray(PROMPT, np.int32),
                           _greedy(24, "hot"))
            it = h.stream(timeout=30)
            next(it)                      # request is live in a slot
            assert srv.unload_adapter("hot") is False   # defers
            with pytest.raises(Exception):
                # new submissions naming it fail at admission
                srv.submit(np.asarray(PROMPT, np.int32),
                           _greedy(4, "hot")).result(30)
            assert list(h.result(60))[:8] == ref[:8]    # live request
            #                                             unharmed
            deadline = time.monotonic() + 10
            while (srv.engine.adapters.resident()["free"] == 2
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert srv.engine.adapters.resident()["free"] == 3
            # the freed index recycles for a hot load mid-serving
            srv.load_adapter("hot2", make_adapter(model, 66))
            assert "hot2" in srv.engine.adapters
        finally:
            srv.shutdown()
            _assert_no_leaks(eng)
            eng.close()

    def test_admin_needs_lora_engine(self):
        model, _ = tiny_model(4)
        eng = paged_engine(model, lora_capacity=0)
        srv = Server(eng, start=False)
        with pytest.raises(RuntimeError, match="lora_capacity"):
            srv.load_adapter("x", {})
        srv.shutdown()
        eng.close()


# -- per-adapter prefix-cache namespaces -------------------------------------
class TestPrefixSalting:
    def test_cross_adapter_hit_zero_same_adapter_hits(self):
        model, _ = tiny_model(4)
        eng = paged_engine(model, num_pages=64, prefix_cache=True)
        eng.load_adapter("s1", make_adapter(model, 71))
        eng.load_adapter("s2", make_adapter(model, 72))
        prompt = list(range(1, 13))        # 3 full pages
        cold = _run_one(eng, prompt, adapter="s1")
        assert eng.alloc.prefix_hits == 0
        # SAME prompt, different adapter: provably zero warm hits
        _run_one(eng, prompt, adapter="s2")
        assert eng.alloc.prefix_hits == 0
        _run_one(eng, prompt)              # base namespace: also cold
        assert eng.alloc.prefix_hits == 0
        # same adapter again: warm hit fires, bitwise parity
        warm = _run_one(eng, prompt, adapter="s1")
        assert eng.alloc.prefix_hits == 1
        assert warm == cold
        _assert_no_leaks(eng)
        eng.close()

    def test_reload_same_name_never_hits_old_pages(self):
        """Unload + reload of the SAME name gets a fresh generation
        salt: pages cached under the old weights can never serve the
        new ones (they would be silently wrong KV)."""
        model, _ = tiny_model(4)
        eng = paged_engine(model, num_pages=64, prefix_cache=True)
        eng.load_adapter("r", make_adapter(model, 81))
        prompt = list(range(1, 13))
        _run_one(eng, prompt, adapter="r")
        eng.unload_adapter("r")
        eng.load_adapter("r", make_adapter(model, 82))   # new weights
        _run_one(eng, prompt, adapter="r")
        assert eng.alloc.prefix_hits == 0
        _assert_no_leaks(eng)
        eng.close()

    def test_base_namespace_still_warm(self):
        model, _ = tiny_model(4)
        eng = paged_engine(model, num_pages=64, prefix_cache=True)
        eng.load_adapter("b1", make_adapter(model, 91))
        prompt = list(range(1, 13))
        cold = _run_one(eng, prompt)
        warm = _run_one(eng, prompt)
        assert eng.alloc.prefix_hits == 1 and warm == cold
        _assert_no_leaks(eng)
        eng.close()


# -- composition with the serving stack --------------------------------------
class TestCompose:
    def test_preempt_replay_keeps_adapter(self):
        """Forced optimistic pressure: preempted adapter requests
        replay — with their adapter_idx — bitwise identical to an
        unpressured run."""
        model, _ = tiny_model(4)
        roomy = paged_engine(model, num_pages=64)
        roomy.load_adapter("p1", make_adapter(model, 101))
        refs = [_run_one(roomy, PROMPT, n=10, adapter=a)
                for a in ("p1", "p1", None)]
        roomy.close()
        tight = paged_engine(model, num_pages=12,
                             admission_mode="optimistic")
        tight.load_adapter("p1", make_adapter(model, 101))
        srv = Server(tight, segment_steps=4, max_preemptions=10)
        try:
            hs = [srv.submit(np.asarray(PROMPT, np.int32),
                             _greedy(10, a))
                  for a in ("p1", "p1", None)]
            outs = [list(h.result(120)) for h in hs]
            assert outs == refs
            assert tight.alloc.preemptions >= 1   # pressure really hit
        finally:
            srv.shutdown()
            _assert_no_leaks(tight)
            tight.close()

    def test_engine_restart_replays_adapter(self):
        """A decode-seam EngineFault mid-run: the supervised restart
        replays the adapter request bitwise (the registry — bank and
        name map — survives reset_state)."""
        from paddle_tpu.testing.faults import FaultPlan, FaultyEngine

        model, _ = tiny_model(4)
        clean = paged_engine(model)
        clean.load_adapter("f1", make_adapter(model, 111))
        ref = _run_one(clean, PROMPT, n=10, adapter="f1")
        clean.close()
        eng = paged_engine(model)
        eng.load_adapter("f1", make_adapter(model, 111))
        plan = FaultPlan().raise_at(
            "decode", nth=2, exc=EngineFault("injected"))
        srv = Server(FaultyEngine(eng, plan), segment_steps=4,
                     max_restarts=3, restart_backoff_s=0.01)
        try:
            h = srv.submit(np.asarray(PROMPT, np.int32),
                           _greedy(10, "f1"))
            assert list(h.result(120)) == ref
            assert srv.restarts == 1
        finally:
            srv.shutdown()
            _assert_no_leaks(eng)
            eng.close()

    def test_spec_decode_with_adapter(self):
        """PR 7 composition: a speculating adapter request through the
        widened verify program is bitwise its plain-decode self."""
        model, _ = tiny_model(4)
        rep = (PROMPT * 3)[:20]            # repetitive: accepting case
        eng = paged_engine(model, max_pages=16, num_pages=96,
                           draft_k=4)
        eng.load_adapter("sp", make_adapter(model, 121))
        plain = _run_one(eng, rep, n=12, adapter="sp")
        rid = eng.add_request(
            np.asarray(rep, np.int32),
            GenerationConfig(max_new_tokens=12, adapter="sp",
                             speculative=True))
        while eng.decode_segment(4):
            pass
        spec = list(dict(eng.collect_finished())[rid])
        assert spec == plain
        assert eng.spec_stats()["forwards"] >= 1
        _assert_no_leaks(eng)
        eng.close()

    def test_int8_kv_with_adapters(self):
        """kv_dtype="int8" composition: a mixed-adapter batch through
        quantized pools matches its solo runs (solo vs mixed stays
        bitwise — both read the same quantized pipeline), leak-free
        under the scale-aware validator."""
        model, _ = tiny_model(4)
        eng = paged_engine(model, kv_dtype="int8")
        eng.load_adapter("q1", make_adapter(model, 131))
        solo = {a: _run_one(eng, PROMPT, adapter=a)
                for a in (None, "q1")}
        rids = {a: eng.add_request(np.asarray(PROMPT, np.int32),
                                   _greedy(6, a))
                for a in (None, "q1")}
        while eng.decode_segment(4):
            pass
        fin = eng.collect_finished()
        for a, rid in rids.items():
            assert list(fin[rid]) == solo[a], a
        _assert_no_leaks(eng)
        eng.close()


# -- per-tenant quotas -------------------------------------------------------
class TestTenantQuotas:
    def test_over_quota_defers_without_starving_others(self):
        """Tenant A's second request defers at its quota while tenant
        B — queued BEHIND it — admits and finishes; A's second admits
        once A's first retires."""
        model, _ = tiny_model(4)
        eng = paged_engine(model, max_batch=4)
        eng.load_adapter("A", make_adapter(model, 141))
        eng.load_adapter("B", make_adapter(model, 142))
        srv = Server(eng, segment_steps=2, tenant_quotas=1)
        try:
            a1 = srv.submit(np.asarray(PROMPT, np.int32),
                            _greedy(20, "A"))
            it = a1.stream(timeout=30)
            next(it)                       # A1 occupies A's one slot
            a2 = srv.submit(np.asarray(PROMPT, np.int32),
                            _greedy(4, "A"))
            b1 = srv.submit(np.asarray(PROMPT, np.int32),
                            _greedy(4, "B"))
            b1.result(60)                  # B passes the deferred A2
            assert a2.status == "queued"   # A over quota: still waiting
            a1.result(120)
            a2.result(60)                  # admits once A1 retired
        finally:
            srv.shutdown()
            _assert_no_leaks(eng)
            eng.close()

    def test_quota_dict_and_untracked_tenants(self):
        model, _ = tiny_model(4)
        eng = paged_engine(model, max_batch=4)
        srv = Server(eng, segment_steps=2,
                     tenant_quotas={"X": 1}, start=False)
        # dict caps only named tenants; base/None is untracked
        h = type("H", (), {"tenant": None})
        assert srv._tenant_ok(h)
        h2 = type("H2", (), {"tenant": "Y"})
        assert srv._tenant_ok(h2)
        srv.shutdown()
        eng.close()

    def test_quota_validation(self):
        model, _ = tiny_model(4)
        eng = paged_engine(model, lora_capacity=0)
        with pytest.raises(ValueError, match="tenant_quotas"):
            Server(eng, tenant_quotas="lots", start=False)
        with pytest.raises(ValueError, match="quota caps"):
            Server(eng, tenant_quotas={"a": 0}, start=False)
        eng.close()

    def test_queue_pop_admittable_skips_only_quota(self):
        q = RequestQueue(8)

        def mk(i, tenant):
            from paddle_tpu.serving.queue import RequestHandle
            return RequestHandle(i, [1], 1, _greedy(2),
                                 tenant=tenant)

        h0, h1, h2 = mk(0, "A"), mk(1, "A"), mk(2, "B")
        for h in (h0, h1, h2):
            q.put(h)
        # capacity-blocked head stops the scan (no bypass)
        assert q.pop_admittable(lambda h: False, lambda h: True) is None
        assert q.depth == 3
        # quota-blocked entries are skipped, FIFO otherwise
        got = q.pop_admittable(lambda h: True,
                               lambda h: h.tenant != "A")
        assert got is h2 and q.depth == 2


# -- HTTP surface ------------------------------------------------------------
class TestHTTPAdapters:
    @pytest.fixture()
    def served(self):
        from paddle_tpu.serving import serve_http

        model, _ = tiny_model(4)
        eng = paged_engine(model)
        srv = Server(eng, segment_steps=4)
        srv.load_adapter("web", make_adapter(model, 151))
        httpd = serve_http(srv)
        yield srv, eng, httpd.server_address[1]
        httpd.shutdown()
        srv.shutdown()
        eng.close()

    def _post(self, port, path, body):
        import http.client
        c = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        c.request("POST", path, json.dumps(body),
                  {"Content-Type": "application/json"})
        r = c.getresponse()
        out = (r.status, json.loads(r.read() or b"{}"))
        c.close()
        return out

    def _get(self, port, path):
        import http.client
        c = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        c.request("GET", path)
        r = c.getresponse()
        out = (r.status, json.loads(r.read() or b"{}"))
        c.close()
        return out

    def test_unknown_field_400_names_field(self, served):
        _, _, port = served
        st, body = self._post(port, "/generate",
                              {"prompt": PROMPT, "adaptor": "web"})
        assert st == 400
        assert "adaptor" in body["error"]          # names the typo
        assert "adapter" in body["error"]          # lists the fix

    def test_adapter_round_trip(self, served):
        srv, eng, port = served
        ref = list(srv.submit(np.asarray(PROMPT, np.int32),
                              _greedy(5, "web")).result(60))
        st, body = self._post(port, "/generate",
                              {"prompt": PROMPT, "max_new_tokens": 5,
                               "adapter": "web"})
        assert st == 200 and body["tokens"] == [int(t) for t in ref]
        # unknown adapter: the request fails with the cause, 500
        st, body = self._post(port, "/generate",
                              {"prompt": PROMPT, "max_new_tokens": 4,
                               "adapter": "nope"})
        assert st == 500 and "nope" in body["error"]

    def test_admin_load_unload_and_healthz(self, served):
        srv, eng, port = served
        model, _ = tiny_model(4)
        p = make_adapter(model, 161)
        weights = {t: {"a": a.tolist(), "b": b.tolist()}
                   for t, (a, b) in p.items()}
        st, body = self._post(port, "/adapters/load",
                              {"name": "adm", "weights": weights})
        assert st == 200 and body["index"] >= 1
        assert "adm" in body["adapters"]["adapters"]
        st, hz = self._get(port, "/healthz")
        assert st == 200 and "adm" in hz["lora"]["adapters"]
        st, body = self._post(port, "/adapters/unload",
                              {"name": "adm"})
        assert st == 200 and body["unloaded"] is True
        # validation errors are 400s
        st, body = self._post(port, "/adapters/load",
                              {"name": "bad"})
        assert st == 400 and "weights" in body["error"]
        st, body = self._post(port, "/adapters/unload",
                              {"name": "ghost"})
        assert st == 400 and "ghost" in body["error"]
        # admin bodies are strict too: a typo'd "aplha" must not
        # silently install scale-1.0 deltas
        st, body = self._post(port, "/adapters/load",
                              {"name": "t", "weights": weights,
                               "aplha": 32})
        assert st == 400 and "aplha" in body["error"]

    def test_admin_on_non_lora_engine_is_400(self):
        from paddle_tpu.serving import serve_http

        model, _ = tiny_model(4)
        eng = paged_engine(model, lora_capacity=0)
        srv = Server(eng, segment_steps=4)
        httpd = serve_http(srv)
        try:
            st, body = self._post(httpd.server_address[1],
                                  "/adapters/load", {"name": "x"})
            # permanently unsupported: 400, never a retryable 503
            assert st == 400 and "lora_capacity" in body["error"]
        finally:
            httpd.shutdown()
            srv.shutdown()
            eng.close()


# -- router adapter affinity -------------------------------------------------
class TestRouterAffinity:
    def test_prefers_adapter_resident_replica(self):
        from paddle_tpu.serving import ReplicaSpec, Router

        def factory():
            paddle.seed(0)
            from paddle_tpu.models import LlamaForCausalLM, llama_config
            m = LlamaForCausalLM(llama_config(
                "tiny", num_hidden_layers=1))
            return paged_engine(m, debug_pages=False)

        spec = ReplicaSpec(factory,
                           server_kwargs={"segment_steps": 4})
        router = Router(spec, replicas=2)
        try:
            # adapter resident on replica 1 ONLY
            model, _ = tiny_model(4)
            router._replicas[1].server.load_adapter(
                "aff", make_adapter(model, 171))
            for _ in range(3):   # affinity beats index-0 tie-breaks
                h = router.submit(np.asarray(PROMPT, np.int32),
                                  _greedy(4, "aff"))
                h.result(60)
                assert h.replica == 1
            # base requests still least-loaded (no affinity pin)
            h = router.submit(np.asarray(PROMPT, np.int32), _greedy(4))
            h.result(60)
        finally:
            router.shutdown()
