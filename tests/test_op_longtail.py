"""Long-tail op tests via the OpTest harness (reference pattern:
test/legacy_test/eager_op_test.py — numpy-reference check_output + finite-
difference check_grad for every op)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from op_test import check, check_grad, check_output


def r(*shape, seed=0, dtype=np.float32, lo=None, hi=None):
    rng = np.random.RandomState(seed)
    x = rng.randn(*shape).astype(dtype)
    if lo is not None:
        x = (lo + (hi - lo) * rng.rand(*shape)).astype(dtype)
    return x


class TestMathLongTail:
    def test_logit(self):
        check(paddle.logit, lambda x: np.log(x / (1 - x)),
              [r(3, 4, lo=0.1, hi=0.9)], name="logit")

    def test_logit_eps(self):
        x = r(8, lo=0.0, hi=1.0)
        got = paddle.logit(paddle.to_tensor(x), eps=0.2)
        xc = np.clip(x, 0.2, 0.8)
        np.testing.assert_allclose(np.asarray(got.value),
                                   np.log(xc / (1 - xc)), rtol=1e-5)

    def test_frexp(self):
        x = r(10, seed=3) * 100
        m, e = paddle.frexp(paddle.to_tensor(x))
        mm, ee = np.frexp(x)
        np.testing.assert_allclose(np.asarray(m.value), mm, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(e.value), ee)

    def test_i0e_i1e(self):
        import scipy.special as sp

        x = r(8, lo=0.1, hi=4.0)
        check_output(paddle.i0e, lambda v: sp.i0e(v), [x], rtol=1e-5,
                     name="i0e")
        check_output(paddle.i1e, lambda v: sp.i1e(v), [x], rtol=1e-5,
                     name="i1e")

    def test_sgn(self):
        check(paddle.sgn, np.sign, [r(3, 3, seed=5)], grad=False)

    def test_trapezoid(self):
        y = r(4, 8, seed=6)
        check(paddle.trapezoid, lambda v: np.trapezoid(v, axis=-1), [y],
              name="trapezoid")
        x = np.sort(r(8, seed=7, lo=0.0, hi=5.0))
        got = paddle.trapezoid(paddle.to_tensor(y), paddle.to_tensor(x))
        np.testing.assert_allclose(np.asarray(got.value),
                                   np.trapezoid(y, x, axis=-1), rtol=1e-5)

    def test_cumulative_trapezoid(self):
        import scipy.integrate as si

        y = r(3, 6, seed=8)
        got = paddle.cumulative_trapezoid(paddle.to_tensor(y), dx=0.5)
        np.testing.assert_allclose(np.asarray(got.value),
                                   si.cumulative_trapezoid(y, dx=0.5,
                                                           axis=-1),
                                   rtol=1e-5)
        check_grad(paddle.cumulative_trapezoid, [y], name="cumtrap")

    def test_renorm(self):
        x = r(4, 5, seed=9) * 3
        got = paddle.renorm(paddle.to_tensor(x), p=2.0, axis=0, max_norm=1.0)
        norms = np.linalg.norm(np.asarray(got.value).reshape(4, -1), axis=1)
        assert (norms <= 1.0 + 1e-5).all()
        check_grad(paddle.renorm, [x * 0.01],
                   kwargs=dict(p=2.0, axis=0, max_norm=1.0), name="renorm")

    def test_nanmedian_nanquantile(self):
        x = r(4, 6, seed=10)
        x[1, 2] = np.nan
        got = paddle.nanmedian(paddle.to_tensor(x), axis=1)
        np.testing.assert_allclose(np.asarray(got.value),
                                   np.nanmedian(x, axis=1), rtol=1e-6)
        gq = paddle.nanquantile(paddle.to_tensor(x), 0.3, axis=1)
        np.testing.assert_allclose(np.asarray(gq.value),
                                   np.nanquantile(x, 0.3, axis=1).astype(
                                       np.float32), rtol=1e-5)

    def test_vander(self):
        x = r(5, seed=11)
        check_output(paddle.vander, lambda v: np.vander(v), [x], name="vander")

    def test_add_n(self):
        xs = [r(3, 3, seed=s) for s in (1, 2, 3)]
        got = paddle.add_n([paddle.to_tensor(x) for x in xs])
        np.testing.assert_allclose(np.asarray(got.value), sum(xs), rtol=1e-6)

    def test_polygamma(self):
        import scipy.special as sp

        x = r(6, lo=0.5, hi=3.0)
        check_output(paddle.polygamma, lambda v, n: sp.polygamma(n, v), [x],
                     kwargs=dict(n=1), rtol=1e-4, name="polygamma")


class TestManipLongTail:
    def test_take(self):
        x = r(3, 4, seed=12)
        idx = np.array([0, 5, 11, 3], np.int32)
        check_output(paddle.take, lambda v, i: np.take(v, i), [x, idx],
                     name="take")
        # wrap / clip modes
        idx2 = np.array([-1, 14], np.int32)
        got = paddle.take(paddle.to_tensor(x), paddle.to_tensor(idx2),
                          mode="wrap")
        np.testing.assert_allclose(np.asarray(got.value),
                                   np.take(x, idx2, mode="wrap"))

    def test_diagonal(self):
        x = r(4, 5, seed=13)
        check(paddle.diagonal, lambda v: np.diagonal(v), [x], name="diagonal")
        check_output(paddle.diagonal,
                     lambda v, offset: np.diagonal(v, offset=offset),
                     [x], kwargs=dict(offset=1))

    def test_reverse_vsplit(self):
        x = r(4, 6, seed=14)
        got = paddle.reverse(paddle.to_tensor(x), axis=[0])
        np.testing.assert_allclose(np.asarray(got.value), x[::-1])
        parts = paddle.vsplit(paddle.to_tensor(x), 2)
        assert len(parts) == 2 and tuple(parts[0].shape) == (2, 6)

    def test_as_complex_real_roundtrip(self):
        x = r(3, 4, 2, seed=15)
        c = paddle.as_complex(paddle.to_tensor(x))
        back = paddle.as_real(c)
        np.testing.assert_allclose(np.asarray(back.value), x, rtol=1e-6)
        check_grad(lambda t: paddle.as_real(paddle.as_complex(t)), [x],
                   name="as_complex_real")

    def test_shape_rank_broadcast_shape(self):
        x = paddle.to_tensor(r(2, 3, 4))
        assert list(np.asarray(paddle.shape(x).value)) == [2, 3, 4]
        assert int(paddle.rank(x).value) == 3
        assert paddle.broadcast_shape([2, 1, 4], [3, 4]) == [2, 3, 4]


class TestLinalgLongTail:
    def test_cdist(self):
        import scipy.spatial.distance as sd

        a, b = r(5, 3, seed=16), r(4, 3, seed=17)
        check_output(paddle.cdist, lambda x, y: sd.cdist(x, y), [a, b],
                     rtol=1e-4, atol=1e-5, name="cdist")
        check_grad(paddle.cdist, [a, b], name="cdist")

    def test_tensordot(self):
        a, b = r(3, 4, 5, seed=18), r(4, 5, 6, seed=19)
        check(paddle.tensordot, lambda x, y: np.tensordot(x, y, axes=2),
              [a, b], rtol=1e-4, atol=1e-4, name="tensordot")

    def test_inv(self):
        x = r(3, 3, seed=20) + 3 * np.eye(3, dtype=np.float32)
        check(paddle.linalg.inv, np.linalg.inv, [x], rtol=1e-4, atol=1e-4,
              name="inv")

    def test_lu_unpack(self):
        x = r(4, 4, seed=21) + 4 * np.eye(4, dtype=np.float32)
        lu_t, piv, _ = paddle.linalg.lu(paddle.to_tensor(x), get_infos=True)
        p, l, u = paddle.linalg.lu_unpack(lu_t, piv)
        rec = (np.asarray(p.value) @ np.asarray(l.value)
               @ np.asarray(u.value))
        np.testing.assert_allclose(rec, x, rtol=1e-4, atol=1e-4)

    def test_pca_lowrank(self):
        x = r(20, 5, seed=22)
        u, s, v = paddle.linalg.pca_lowrank(paddle.to_tensor(x), q=3)
        assert tuple(u.shape) == (20, 3) and tuple(v.shape) == (5, 3)
        # principal directions capture more variance than random ones
        xc = x - x.mean(0)
        var = np.linalg.norm(xc @ np.asarray(v.value), axis=0).sum()
        rngdir = np.linalg.qr(r(5, 3, seed=23))[0]
        var_r = np.linalg.norm(xc @ rngdir, axis=0).sum()
        assert var >= var_r * 0.99


class TestInplaceAndPredicates:
    def test_inplace_math(self):
        x = paddle.to_tensor(np.array([1.0, 4.0, 9.0], np.float32))
        y = x.sqrt_()
        assert y is x
        np.testing.assert_allclose(np.asarray(x.value), [1, 2, 3])
        x.add_(paddle.to_tensor(np.ones(3, np.float32)))
        np.testing.assert_allclose(np.asarray(x.value), [2, 3, 4])

    def test_inplace_grad_flow(self):
        x = paddle.to_tensor(r(4, seed=24))
        x.stop_gradient = False
        z = x * 3.0
        z.exp_()
        z.sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad.value),
                                   3.0 * np.exp(3.0 * r(4, seed=24)),
                                   rtol=1e-5)

    def test_function_form(self):
        x = paddle.to_tensor(np.array([0.5], np.float32))
        paddle.tanh_(x)
        np.testing.assert_allclose(np.asarray(x.value), np.tanh(0.5),
                                   rtol=1e-6)

    def test_predicates(self):
        assert paddle.is_floating_point(paddle.to_tensor(r(2)))
        assert not paddle.is_integer(paddle.to_tensor(r(2)))
        assert paddle.is_integer(paddle.to_tensor(np.arange(3)))
        assert not paddle.is_complex(paddle.to_tensor(r(2)))
        c = paddle.complex(paddle.to_tensor(r(2)), paddle.to_tensor(r(2)))
        assert paddle.is_complex(c)

    def test_bucketize(self):
        seq = np.array([1.0, 3.0, 5.0, 7.0], np.float32)
        x = np.array([0.5, 3.0, 6.2], np.float32)
        got = paddle.bucketize(paddle.to_tensor(x), paddle.to_tensor(seq))
        np.testing.assert_array_equal(np.asarray(got.value),
                                      np.searchsorted(seq, x))

    def test_polar_complex(self):
        a, t = r(4, lo=0.5, hi=2.0), r(4, seed=25)
        got = paddle.polar(paddle.to_tensor(a), paddle.to_tensor(t))
        np.testing.assert_allclose(np.asarray(got.value),
                                   a * np.exp(1j * t), rtol=1e-5)

    def test_finfo_iinfo(self):
        assert paddle.finfo(paddle.float32).bits == 32
        assert paddle.finfo("bfloat16").max > 1e38
        assert paddle.iinfo(paddle.int32).max == 2**31 - 1

    def test_create_parameter_tolist(self):
        p = paddle.create_parameter([2, 3], "float32")
        assert tuple(p.shape) == (2, 3)
        assert paddle.tolist(paddle.to_tensor(np.arange(3))) == [0, 1, 2]


class TestSignalAndFFT:
    def test_stft_istft_roundtrip(self):
        sig = np.sin(np.arange(1024) * 0.05).astype(np.float32)
        S = paddle.signal.stft(paddle.to_tensor(sig), n_fft=128)
        rec = paddle.signal.istft(S, n_fft=128, length=1024)
        np.testing.assert_allclose(np.asarray(rec.value)[64:-64],
                                   sig[64:-64], atol=1e-4)

    def test_stft_matches_scipy(self):
        sig = r(512, seed=26)
        S = paddle.signal.stft(paddle.to_tensor(sig), n_fft=64,
                               hop_length=32, center=False)
        import numpy.fft as nf

        frames = np.stack([sig[i * 32:i * 32 + 64]
                           for i in range((512 - 64) // 32 + 1)])
        want = nf.rfft(frames, axis=-1).T
        np.testing.assert_allclose(np.asarray(S.value), want, atol=1e-3)

    def test_hfft2_ihfft2(self):
        x = r(4, 5, seed=27) + 1j * r(4, 5, seed=28)
        x = x.astype(np.complex64)
        out = paddle.fft.hfft2(paddle.to_tensor(x))
        back = paddle.fft.ihfft2(out)
        # roundtrip consistency on the hermitian part
        assert tuple(out.shape) == (4, 8)
        assert tuple(back.shape) == (4, 5)


class TestGeometric:
    def test_segment_ops(self):
        x = np.arange(12, dtype=np.float32).reshape(4, 3)
        ids = np.array([0, 0, 1, 1], np.int32)
        np.testing.assert_allclose(
            np.asarray(paddle.geometric.segment_sum(
                paddle.to_tensor(x), paddle.to_tensor(ids)).value),
            np.stack([x[:2].sum(0), x[2:].sum(0)]))
        np.testing.assert_allclose(
            np.asarray(paddle.geometric.segment_mean(
                paddle.to_tensor(x), paddle.to_tensor(ids)).value),
            np.stack([x[:2].mean(0), x[2:].mean(0)]))
        np.testing.assert_allclose(
            np.asarray(paddle.geometric.segment_max(
                paddle.to_tensor(x), paddle.to_tensor(ids)).value),
            np.stack([x[:2].max(0), x[2:].max(0)]))

    def test_send_u_recv_grad(self):
        x = r(4, 3, seed=29)
        src = np.array([0, 1, 2, 3], np.int32)
        dst = np.array([1, 1, 0, 0], np.int32)
        t = paddle.to_tensor(x)
        t.stop_gradient = False
        out = paddle.geometric.send_u_recv(
            t, paddle.to_tensor(src), paddle.to_tensor(dst))
        out.sum().backward()
        np.testing.assert_allclose(np.asarray(t.grad.value),
                                   np.ones_like(x))

    def test_send_ue_recv_and_uv(self):
        x = r(3, 2, seed=30)
        e = r(4, 2, seed=31)
        src = np.array([0, 1, 2, 0], np.int32)
        dst = np.array([1, 2, 0, 2], np.int32)
        out = paddle.geometric.send_ue_recv(
            paddle.to_tensor(x), paddle.to_tensor(e),
            paddle.to_tensor(src), paddle.to_tensor(dst),
            message_op="mul", reduce_op="sum", out_size=3)
        want = np.zeros((3, 2), np.float32)
        for k in range(4):
            want[dst[k]] += x[src[k]] * e[k]
        np.testing.assert_allclose(np.asarray(out.value), want, rtol=1e-5)
        uv = paddle.geometric.send_uv(
            paddle.to_tensor(x), paddle.to_tensor(x),
            paddle.to_tensor(src), paddle.to_tensor(dst), message_op="add")
        np.testing.assert_allclose(np.asarray(uv.value), x[src] + x[dst],
                                   rtol=1e-6)

    def test_sample_and_reindex(self):
        # CSC graph: 3 nodes; node0 <- {1,2}, node1 <- {2}, node2 <- {0,1}
        colptr = np.array([0, 2, 3, 5], np.int64)
        row = np.array([1, 2, 2, 0, 1], np.int64)
        nodes = np.array([0, 2], np.int64)
        nb, cnt = paddle.geometric.sample_neighbors(
            paddle.to_tensor(row), paddle.to_tensor(colptr),
            paddle.to_tensor(nodes), sample_size=-1)
        np.testing.assert_array_equal(np.asarray(cnt.value), [2, 2])
        src, dst, out_nodes = paddle.geometric.reindex_graph(
            paddle.to_tensor(nodes), nb, cnt)
        on = np.asarray(out_nodes.value)
        assert set(on[:2]) == {0, 2}
        assert int(np.asarray(dst.value).max()) <= 1
