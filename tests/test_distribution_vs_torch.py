"""paddle.distribution log_prob/entropy/KL depth vs torch.distributions
(an independent implementation of the same formulas).
"""
import numpy as np
import pytest

import paddle_tpu as paddle

torch = pytest.importorskip("torch")
import torch.distributions as TD  # noqa: E402

D = paddle.distribution


def _t(a):
    return paddle.to_tensor(np.ascontiguousarray(a))


def _np(x):
    return np.asarray(x.value if hasattr(x, "value") else x)


class TestLogProbs:
    def test_normal(self):
        loc = np.array([0.0, 1.0], np.float32)
        scale = np.array([1.0, 2.5], np.float32)
        v = np.array([0.3, -1.2], np.float32)
        got = _np(D.Normal(_t(loc), _t(scale)).log_prob(_t(v)))
        want = TD.Normal(torch.from_numpy(loc),
                         torch.from_numpy(scale)).log_prob(
                             torch.from_numpy(v)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_categorical_and_multinomial(self):
        logits = np.array([[0.1, 1.2, -0.3], [2.0, 0.0, 0.5]], np.float32)
        v = np.array([2, 0], np.int64)
        got = _np(D.Categorical(logits=_t(logits)).log_prob(_t(v)))
        want = TD.Categorical(logits=torch.from_numpy(logits)).log_prob(
            torch.from_numpy(v)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_beta_dirichlet(self):
        a = np.array([0.8, 2.0], np.float32)
        b = np.array([1.5, 0.9], np.float32)
        v = np.array([0.3, 0.7], np.float32)
        np.testing.assert_allclose(
            _np(D.Beta(_t(a), _t(b)).log_prob(_t(v))),
            TD.Beta(torch.from_numpy(a), torch.from_numpy(b)).log_prob(
                torch.from_numpy(v)).numpy(), rtol=1e-4, atol=1e-5)
        # (no Gamma: the reference snapshot's distribution __all__
        # has Beta/Dirichlet but not Gamma)
        conc = np.array([0.5, 1.5, 3.0], np.float32)
        x = np.array([0.2, 0.3, 0.5], np.float32)
        np.testing.assert_allclose(
            _np(D.Dirichlet(_t(conc)).log_prob(_t(x))),
            TD.Dirichlet(torch.from_numpy(conc)).log_prob(
                torch.from_numpy(x)).numpy(), rtol=1e-4, atol=1e-5)

    def test_laplace_lognormal_gumbel(self):
        loc = np.array([0.5], np.float32)
        sc = np.array([1.2], np.float32)
        v = np.array([0.9], np.float32)
        np.testing.assert_allclose(
            _np(D.Laplace(_t(loc), _t(sc)).log_prob(_t(v))),
            TD.Laplace(torch.from_numpy(loc),
                       torch.from_numpy(sc)).log_prob(
                           torch.from_numpy(v)).numpy(),
            rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            _np(D.LogNormal(_t(loc), _t(sc)).log_prob(_t(v))),
            TD.LogNormal(torch.from_numpy(loc),
                         torch.from_numpy(sc)).log_prob(
                             torch.from_numpy(v)).numpy(),
            rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            _np(D.Gumbel(_t(loc), _t(sc)).log_prob(_t(v))),
            TD.Gumbel(torch.from_numpy(loc),
                      torch.from_numpy(sc)).log_prob(
                          torch.from_numpy(v)).numpy(),
            rtol=1e-4, atol=1e-5)


class TestEntropyKL:
    def test_normal_entropy_and_kl(self):
        l1, s1 = np.float32(0.0), np.float32(1.0)
        l2, s2 = np.float32(1.0), np.float32(2.0)
        p = D.Normal(_t(l1), _t(s1))
        q = D.Normal(_t(l2), _t(s2))
        tp = TD.Normal(torch.tensor(l1), torch.tensor(s1))
        tq = TD.Normal(torch.tensor(l2), torch.tensor(s2))
        np.testing.assert_allclose(float(_np(p.entropy())),
                                   float(tp.entropy()), rtol=1e-5)
        np.testing.assert_allclose(
            float(_np(D.kl_divergence(p, q))),
            float(TD.kl_divergence(tp, tq)), rtol=1e-4)

    def test_categorical_kl(self):
        a = np.array([0.2, 1.0, -0.5], np.float32)
        b = np.array([1.0, 0.0, 0.3], np.float32)
        got = float(_np(D.kl_divergence(D.Categorical(logits=_t(a)),
                                        D.Categorical(logits=_t(b)))))
        want = float(TD.kl_divergence(
            TD.Categorical(logits=torch.from_numpy(a)),
            TD.Categorical(logits=torch.from_numpy(b))))
        np.testing.assert_allclose(got, want, rtol=1e-4)

    def test_sampling_moments(self):
        # statistical check: 50k samples match analytic mean/std at 2%
        d = D.Normal(_t(np.float32(2.0)), _t(np.float32(0.5)))
        s = _np(d.sample([50000]))
        assert abs(s.mean() - 2.0) < 0.02
        assert abs(s.std() - 0.5) < 0.02
