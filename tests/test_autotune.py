"""Kernel autotune cache tests (reference analog: test/legacy_test/
test_switch_autotune.py + phi/kernels/autotune/cache_test.cc)."""
import json
import os

import numpy as np
import pytest

from paddle_tpu.framework.flags import set_flags
from paddle_tpu.ops import autotune


@pytest.fixture(autouse=True)
def _isolated_cache(tmp_path, monkeypatch):
    # keep tests away from the user's persistent cache file
    monkeypatch.setenv(autotune._CACHE_ENV, str(tmp_path / "cache.json"))
    old = autotune._GLOBAL
    autotune._GLOBAL = autotune.AutoTuneCache()
    autotune._loaded[0] = True
    yield
    autotune._GLOBAL = old


class TestCache:
    def test_lookup_miss_then_hit(self):
        c = autotune.AutoTuneCache()
        assert c.lookup("op", (1, 2)) is None
        c.record("op", (1, 2), {"block": 128})
        assert c.lookup("op", (1, 2)) == {"block": 128}
        assert c.stats["hits"] == 1 and c.stats["misses"] == 1

    def test_persistence_roundtrip(self, tmp_path):
        p = str(tmp_path / "t.json")
        c = autotune.AutoTuneCache(path=p)
        c.record("flash", ("sq", 2048), {"block_q": 1024, "ms": 0.9})
        c.save()
        c2 = autotune.AutoTuneCache(path=p)
        assert c2.load()
        assert c2.lookup("flash", ("sq", 2048))["block_q"] == 1024

    def test_flag_gates_lookup(self):
        autotune.record("op", (3,), {"x": 1})
        set_flags({"FLAGS_use_autotune": False})
        try:
            assert autotune.lookup("op", (3,)) is None
        finally:
            set_flags({"FLAGS_use_autotune": True})
        assert autotune.lookup("op", (3,)) == {"x": 1}


class TestTune:
    def test_tune_picks_fastest_and_records(self):
        import time

        calls = []

        def runner(cfg):
            calls.append(cfg["n"])
            time.sleep(0.001 * cfg["n"])

        best = autotune.tune("toy", ("s", 1), [{"n": 3}, {"n": 1}, {"n": 2}],
                             runner, warmup=0, iters=1, save=False)
        assert best["n"] == 1
        assert autotune.lookup("toy", ("s", 1))["n"] == 1

    def test_tune_skips_failing_candidates(self):
        def runner(cfg):
            if cfg["n"] == 1:
                raise RuntimeError("does not fit VMEM")

        best = autotune.tune("toy2", ("s", 2), [{"n": 1}, {"n": 2}],
                             runner, warmup=0, iters=1, save=False)
        assert best["n"] == 2

    def test_tune_all_fail_raises(self):
        def runner(cfg):
            raise RuntimeError("boom")

        with pytest.raises(RuntimeError, match="no candidate"):
            autotune.tune("toy3", ("s",), [{"n": 1}], runner, save=False)


class TestFlashIntegration:
    def test_kernel_consults_cache(self):
        import jax.numpy as jnp

        from paddle_tpu.ops.flash_attention_kernel import flash_attention_bhsd

        # record a signature-matching config with a recognizable block size
        sig = autotune.flash_signature(128, 128, 32, True, "float32")
        autotune.record("flash_attention", sig,
                        {"block_q": 64, "block_k": 64, "ms": 0.1})
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(1, 2, 128, 32), jnp.float32)
        k = jnp.asarray(rng.randn(1, 2, 128, 32), jnp.float32)
        v = jnp.asarray(rng.randn(1, 2, 128, 32), jnp.float32)
        out = flash_attention_bhsd(q, k, v, causal=True)
        assert out.shape == q.shape
        assert autotune.get_cache().stats["hits"] >= 1

    def test_tune_flash_end_to_end_cpu(self):
        # interpret-mode is slow; tiniest shapes, fwd only, 2 candidates
        best = autotune.tune_flash(1, 1, 128, 16, causal=True,
                                   dtype="float32",
                                   candidates=((128, 128), (64, 64)),
                                   grad=False)
        assert "block_q" in best and "ms" in best
        assert autotune.lookup(
            "flash_attention",
            autotune.flash_signature(128, 128, 16, True,
                                     "float32")) is not None
