"""paddle.fft behavior-depth parity vs numpy.fft (VERDICT r3 #7).

Reference: python/paddle/fft.py — full fft/fft2/fftn/rfft/hfft families
with norm modes (backward/ortho/forward), n/s truncation+padding, and
axes edge cases. Every case here checks VALUES against numpy.fft (the
reference's own ground truth) at fp32-appropriate tolerance (x64 is
disabled on TPU; inputs are fp32/complex64).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.fft as pfft

NORMS = ("backward", "ortho", "forward")
RTOL, ATOL = 2e-4, 2e-4


def _t(a):
    return paddle.to_tensor(np.ascontiguousarray(a))


def _np(x):
    return np.asarray(x.value if hasattr(x, "value") else x)


def _close(got, want, msg=""):
    np.testing.assert_allclose(_np(got), want.astype(_np(got).dtype),
                               rtol=RTOL, atol=ATOL, err_msg=msg)


class TestFFT1DNorms:
    """Every 1-D transform x norm x n (pad/truncate/default) x axis."""

    @pytest.mark.parametrize("norm", NORMS)
    @pytest.mark.parametrize("n", [None, 6, 16])
    def test_fft_ifft(self, norm, n):
        rng = np.random.RandomState(0)
        a = (rng.randn(3, 10) + 1j * rng.randn(3, 10)).astype(np.complex64)
        _close(pfft.fft(_t(a), n=n, norm=norm),
               np.fft.fft(a, n=n, norm=norm), f"fft n={n} {norm}")
        _close(pfft.ifft(_t(a), n=n, norm=norm),
               np.fft.ifft(a, n=n, norm=norm), f"ifft n={n} {norm}")

    @pytest.mark.parametrize("norm", NORMS)
    @pytest.mark.parametrize("axis", [0, 1, -1])
    def test_fft_axis(self, norm, axis):
        rng = np.random.RandomState(1)
        a = (rng.randn(4, 6) + 1j * rng.randn(4, 6)).astype(np.complex64)
        _close(pfft.fft(_t(a), axis=axis, norm=norm),
               np.fft.fft(a, axis=axis, norm=norm))

    @pytest.mark.parametrize("norm", NORMS)
    @pytest.mark.parametrize("n", [None, 6, 16])
    def test_rfft_irfft(self, norm, n):
        rng = np.random.RandomState(2)
        a = rng.randn(3, 10).astype(np.float32)
        _close(pfft.rfft(_t(a), n=n, norm=norm),
               np.fft.rfft(a, n=n, norm=norm).astype(np.complex64))
        spec = np.fft.rfft(a).astype(np.complex64)
        _close(pfft.irfft(_t(spec), n=n, norm=norm),
               np.fft.irfft(spec, n=n, norm=norm))

    @pytest.mark.parametrize("norm", NORMS)
    @pytest.mark.parametrize("n", [None, 8, 18])
    def test_hfft_ihfft(self, norm, n):
        rng = np.random.RandomState(3)
        a = (rng.randn(2, 10) + 1j * rng.randn(2, 10)).astype(np.complex64)
        _close(pfft.hfft(_t(a), n=n, norm=norm),
               np.fft.hfft(a, n=n, norm=norm))
        r = rng.randn(2, 10).astype(np.float32)
        _close(pfft.ihfft(_t(r), n=n, norm=norm),
               np.fft.ihfft(r, n=n, norm=norm).astype(np.complex64))


class TestFFT2DAndND:
    @pytest.mark.parametrize("norm", NORMS)
    @pytest.mark.parametrize("axes", [(-2, -1), (0, 1), (1, 0), (-1, -2)])
    def test_fft2_axes(self, norm, axes):
        rng = np.random.RandomState(4)
        a = (rng.randn(5, 6) + 1j * rng.randn(5, 6)).astype(np.complex64)
        _close(pfft.fft2(_t(a), axes=axes, norm=norm),
               np.fft.fft2(a, axes=axes, norm=norm), f"{axes} {norm}")
        _close(pfft.ifft2(_t(a), axes=axes, norm=norm),
               np.fft.ifft2(a, axes=axes, norm=norm))

    @pytest.mark.parametrize("norm", NORMS)
    @pytest.mark.parametrize("s", [None, (4, 8), (8, 4)])
    def test_fft2_s(self, norm, s):
        rng = np.random.RandomState(5)
        a = (rng.randn(6, 6) + 1j * rng.randn(6, 6)).astype(np.complex64)
        _close(pfft.fft2(_t(a), s=s, norm=norm),
               np.fft.fft2(a, s=s, norm=norm))

    @pytest.mark.parametrize("norm", NORMS)
    def test_rfft2_irfft2(self, norm):
        rng = np.random.RandomState(6)
        a = rng.randn(4, 6).astype(np.float32)
        _close(pfft.rfft2(_t(a), norm=norm),
               np.fft.rfft2(a, norm=norm).astype(np.complex64))
        spec = np.fft.rfft2(a).astype(np.complex64)
        _close(pfft.irfft2(_t(spec), s=a.shape, norm=norm),
               np.fft.irfft2(spec, s=a.shape, norm=norm))

    @pytest.mark.parametrize("norm", NORMS)
    @pytest.mark.parametrize("axes", [None, (0,), (0, 2), (2, 1)])
    def test_fftn_axes_subsets(self, norm, axes):
        rng = np.random.RandomState(7)
        a = (rng.randn(3, 4, 5) + 1j * rng.randn(3, 4, 5)).astype(
            np.complex64)
        _close(pfft.fftn(_t(a), axes=axes, norm=norm),
               np.fft.fftn(a, axes=axes, norm=norm), f"{axes} {norm}")
        _close(pfft.ifftn(_t(a), axes=axes, norm=norm),
               np.fft.ifftn(a, axes=axes, norm=norm))

    @pytest.mark.parametrize("norm", NORMS)
    def test_rfftn_irfftn(self, norm):
        rng = np.random.RandomState(8)
        a = rng.randn(3, 4, 6).astype(np.float32)
        _close(pfft.rfftn(_t(a), norm=norm),
               np.fft.rfftn(a, norm=norm).astype(np.complex64))
        spec = np.fft.rfftn(a).astype(np.complex64)
        _close(pfft.irfftn(_t(spec), s=a.shape, norm=norm),
               np.fft.irfftn(spec, s=a.shape, norm=norm))


class TestHermitian2DND:
    """hfft2/ihfft2/hfftn/ihfftn — numpy has no nd-hermitian transforms;
    ground truth is the reference's own composition (c2c on the leading
    axes, c2r/r2c hermitian on the LAST axis — python/paddle/fft.py
    fftn_c2r/fftn_r2c order) built from numpy 1-D primitives."""

    @pytest.mark.parametrize("norm", NORMS)
    def test_hfft2_matches_composition(self, norm):
        rng = np.random.RandomState(9)
        a = (rng.randn(4, 6) + 1j * rng.randn(4, 6)).astype(np.complex64)
        want = np.fft.hfft(np.fft.fft(a, axis=0, norm=norm), axis=1,
                           norm=norm)
        _close(pfft.hfft2(_t(a)) if norm == "backward"
               else pfft.hfft2(_t(a), norm=norm), want)

    @pytest.mark.parametrize("norm", NORMS)
    def test_ihfft2_roundtrips_hfft2(self, norm):
        # hfft2(ihfft2(y)) == y for real y (the numpy 1-D contract,
        # lifted through the composition)
        rng = np.random.RandomState(10)
        y = rng.randn(4, 10).astype(np.float32)
        spec = pfft.ihfft2(_t(y), norm=norm)
        back = pfft.hfft2(spec, s=(4, 10), norm=norm)
        _close(back, y)

    @pytest.mark.parametrize("norm", NORMS)
    def test_hfftn_ihfftn_roundtrip_3d(self, norm):
        rng = np.random.RandomState(11)
        y = rng.randn(3, 4, 8).astype(np.float32)
        spec = pfft.ihfftn(_t(y), norm=norm)
        back = pfft.hfftn(spec, s=(3, 4, 8), norm=norm)
        _close(back, y)

    def test_hfftn_subset_axes(self):
        rng = np.random.RandomState(12)
        a = (rng.randn(3, 4, 5) + 1j * rng.randn(3, 4, 5)).astype(
            np.complex64)
        got = pfft.hfftn(_t(a), axes=(1, 2))
        want = np.fft.hfft(np.fft.fft(a, axis=1), axis=2)
        _close(got, want)


class TestHelpers:
    def test_fftfreq_rfftfreq(self):
        for n, d in ((8, 1.0), (7, 0.25)):
            np.testing.assert_allclose(_np(pfft.fftfreq(n, d)),
                                       np.fft.fftfreq(n, d), rtol=1e-6)
            np.testing.assert_allclose(_np(pfft.rfftfreq(n, d)),
                                       np.fft.rfftfreq(n, d), rtol=1e-6)

    @pytest.mark.parametrize("axes", [None, (0,), (0, 1)])
    def test_fftshift_roundtrip(self, axes):
        rng = np.random.RandomState(13)
        a = rng.randn(5, 6).astype(np.float32)
        sh = pfft.fftshift(_t(a), axes=axes)
        np.testing.assert_allclose(_np(sh), np.fft.fftshift(a, axes=axes))
        back = pfft.ifftshift(sh, axes=axes)
        np.testing.assert_allclose(_np(back), a)


class TestGrad:
    def _numeric_grad(self, f, x, eps=1e-3):
        g = np.zeros_like(x)
        for i in range(x.size):
            xp, xm = x.copy(), x.copy()
            xp.flat[i] += eps
            xm.flat[i] -= eps
            g.flat[i] = (f(xp) - f(xm)) / (2 * eps)
        return g

    def test_rfft_power_spectrum_grad(self):
        """AD through the r2c transform must match the numerical grad of
        sum(|rfft(x)|^2) (rfft is half-spectrum, so no closed form)."""
        rng = np.random.RandomState(14)
        x = rng.randn(8).astype(np.float32)

        def loss(v):
            s = jnp.fft.rfft(v)
            return jnp.sum(jnp.abs(s) ** 2)

        g = jax.grad(loss)(jnp.asarray(x))
        num = self._numeric_grad(lambda v: float(loss(jnp.asarray(v))), x)
        np.testing.assert_allclose(np.asarray(g), num, rtol=2e-2,
                                   atol=2e-2)

    def test_autograd_through_tensor_api(self):
        xv = np.random.RandomState(15).randn(8).astype(np.float32)
        x = paddle.to_tensor(xv)
        x.stop_gradient = False
        y = pfft.irfft(pfft.rfft(x))     # c2r(r2c(x)) == x, AD through both
        out = (y * y).sum()
        out.backward()
        assert x.grad is not None
        np.testing.assert_allclose(np.asarray(x.grad.value), 2 * xv,
                                   rtol=1e-3, atol=1e-3)
