"""Config-3 compile-only memory feasibility (VERDICT r3 #3).

BASELINE config 3 is Llama-2 13B/65B hybrid TP x PP x sharding; nothing at
toy shapes proves the placement actually FITS per-device HBM at real dims.
`hybrid_memory_analysis` AOT-compiles the full jitted hybrid train step at
13B dims over abstract sharded arguments on the 8-device virtual mesh and
reads XLA's buffer assignment. (The 64-device 65B sweep runs via
``python bench.py hybrid`` -> MEMORY_CONFIG3.json.)
"""
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.distributed.topology import build_mesh, set_mesh
from paddle_tpu.models.llama import llama_config
from paddle_tpu.models.llama_pp import (hybrid_memory_analysis,
                                        llama_param_shapes)


class TestParamShapes:
    def test_13b_param_count(self):
        cfg = llama_config("13b")
        ss, rs = llama_param_shapes(cfg)
        n = sum(int(np.prod(s)) for s in ss.values())
        n += sum(int(np.prod(s)) for s in rs.values())
        assert 12.5e9 < n < 13.5e9, n

    def test_65b_param_count(self):
        cfg = llama_config("65b")
        ss, rs = llama_param_shapes(cfg)
        n = sum(int(np.prod(s)) for s in ss.values())
        n += sum(int(np.prod(s)) for s in rs.values())
        assert 63e9 < n < 67e9, n


class Test13BCompileOnly:
    """13B on the 8-device mesh: pp2 x mp2 x sharding2, bf16 params,
    fp32 moments (ZeRO placement), seq 4096."""

    def test_13b_fits_v5p_budget(self):
        cfg = llama_config("13b")
        mesh = build_mesh(pp=2, mp=2, sharding=2)
        set_mesh(mesh)
        rep = hybrid_memory_analysis(
            cfg, mesh, accumulate_steps=8, seq_len=4096,
            remat=True, stash="input", hbm_budget=95 << 30)
        # params are bf16: 13B body+edges / (pp2 within body, mp2, zero2
        # on moments) — measured 38.8 GiB/device, comfortably under 95
        assert rep["fits"], json.dumps(rep)
        assert rep["per_device"]["argument_bytes"] < 25 << 30, rep
        # the analysis is real: arguments must be at least the per-device
        # param+moment shards (~>10 GiB), not a degenerate empty program
        assert rep["per_device"]["argument_bytes"] > 10 << 30, rep

    def test_stage_local_scaling_pp4_vs_pp2(self):
        """Per-device argument bytes must shrink when pp grows: the
        stage-local contract at 13B dims (body params 1/S per device)."""
        cfg = llama_config("13b")
        args = {}
        for pp, mp in ((2, 4), (4, 2)):
            mesh = build_mesh(pp=pp, mp=mp)
            set_mesh(mesh)
            rep = hybrid_memory_analysis(
                cfg, mesh, accumulate_steps=8, seq_len=2048,
                remat=True, stash="input", zero=False)
            args[pp] = rep["per_device"]["argument_bytes"]
        # body dominates 13B: pp4/mp2 args ≈ pp2/mp4 args (same total
        # split 8 ways) — but pp4 shards the OPTIMIZER+grads per stage
        # too; the strong assertion is both well under the replicated size
        total_bf16 = 13e9 * 2 + 13e9 * 8  # params + fp32 moments
        assert args[4] < total_bf16 / 4, args
        assert args[2] < total_bf16 / 4, args


class TestZeroStage3:
    def test_stage3_shrinks_at_rest_params(self):
        """ZeRO stage 3 (params sharded at rest over the sharding axis —
        BASELINE config 3's 'sharding-stage-3') must cut per-device
        ARGUMENT bytes vs stage 2 at the same mesh."""
        cfg = llama_config("13b")
        mesh = build_mesh(pp=2, mp=2, sharding=2)
        set_mesh(mesh)
        args = {}
        for stage in (2, 3):
            rep = hybrid_memory_analysis(
                cfg, mesh, accumulate_steps=8, seq_len=2048,
                remat=True, stash="input", zero_stage=stage)
            args[stage] = rep["per_device"]["argument_bytes"]
            assert rep["zero_stage"] == stage
        # stage 2 replicates bf16 params over `sharding`; stage 3 halves
        # the body/edge param share on this sharding=2 mesh
        assert args[3] < 0.85 * args[2], args

    def test_stage3_step_runs_tiny(self):
        """The stage-3 placement must EXECUTE, not just compile: one
        train step on tiny dims with params sharded at rest."""
        import numpy as np

        from paddle_tpu.models import LlamaForCausalLM
        from paddle_tpu.models.llama_functional import stack_params
        from paddle_tpu.models.llama_pp import build_llama_hybrid_step

        cfg = llama_config("tiny", num_hidden_layers=4)
        mesh = build_mesh(pp=2, mp=2, sharding=2)
        set_mesh(mesh)
        np.random.seed(0)
        model = LlamaForCausalLM(cfg)
        raw = {k: np.asarray(p.value) for k, p in model.named_parameters()}
        stacked, rest = stack_params(raw, cfg)
        step, prepare = build_llama_hybrid_step(
            cfg, mesh, accumulate_steps=4, lr=1e-3, zero_stage=3)
        blocks, edge, st = prepare(stacked, rest)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32)
        y = rng.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32)
        blocks, edge, st, loss = step(blocks, edge, st, ids, y)
        assert np.isfinite(float(loss))
