"""Native C++ component tests (blocking queue, host tracer, TCP store) and
their wiring into profiler/distributed (reference analogs:
test/cpp/fluid/framework/blocking_queue_test, tcp_store tests)."""
import threading
import time

import numpy as np
import pytest

from paddle_tpu import native


@pytest.fixture(scope="module", autouse=True)
def _need_native():
    if native.lib_path() is None:
        pytest.skip("native toolchain unavailable")


class TestBlockingQueue:
    def test_fifo_roundtrip(self):
        q = native.BlockingQueue(capacity=4)
        for i in range(3):
            q.push({"i": i, "x": np.full(4, i)})
        assert len(q) == 3
        for i in range(3):
            item = q.pop()
            assert item["i"] == i
            np.testing.assert_array_equal(item["x"], np.full(4, i))
        q.close()

    def test_backpressure_and_close(self):
        q = native.BlockingQueue(capacity=1)
        q.push(1)
        blocked = []

        def producer():
            blocked.append("start")
            q.push(2)  # blocks: queue full
            blocked.append("done")

        t = threading.Thread(target=producer)
        t.start()
        time.sleep(0.05)
        assert blocked == ["start"]
        assert q.pop() == 1  # frees a slot → producer completes
        t.join(timeout=5)
        assert "done" in blocked
        assert q.pop() == 2
        q.close()
        with pytest.raises(EOFError):
            q.pop()  # closed and drained

    def test_close_drains_remaining(self):
        q = native.BlockingQueue(capacity=4)
        q.push("a")
        q.close()
        assert q.pop() == "a"
        with pytest.raises(EOFError):
            q.pop()


class TestHostTracer:
    def test_record_drain(self):
        t = native.HostTracer(capacity=100)
        t.record("matmul", 10, 20)
        t.record("relu", 20, 25, tid=7)
        assert t.drain() == [("matmul", 10, 20, 0), ("relu", 20, 25, 7)]
        assert t.drain() == []

    def test_capacity_drops(self):
        t = native.HostTracer(capacity=2)
        for i in range(5):
            t.record("x", i, i + 1)
        assert len(t.drain()) == 2
        assert t.dropped == 3


class TestTCPStore:
    def test_set_get_add_wait(self):
        master = native.TCPStore(is_master=True)
        client = native.TCPStore(port=master.port)
        client.set("k", b"v1")
        assert master.get("k") == b"v1"
        assert master.add("ctr", 5) == 5
        assert client.add("ctr", 2) == 7
        done = []

        def waiter():
            client.wait("flag")
            done.append(True)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        assert not done
        master.set("flag", b"1")
        t.join(timeout=5)
        assert done
        client.close()
        master.close()

    def test_add_rejects_negative_amount(self):
        """Counters are nonnegative by contract — ADD's negative return
        space is reserved for transport errors, so a negative amount
        must be refused client-side before it can corrupt a counter
        into the error range."""
        master = native.TCPStore(is_master=True)
        try:
            assert master.add("nctr", 3) == 3
            with pytest.raises(ValueError):
                master.add("nctr", -1)
            # the refused add did not touch the counter
            assert master.add("nctr", 0) == 3
        finally:
            master.close()

    def test_barrier_pattern(self):
        """The reference's init_parallel_env barrier (parallel.py:1101):
        every rank add()s then wait()s for the count key."""
        master = native.TCPStore(is_master=True)
        clients = [native.TCPStore(port=master.port) for _ in range(3)]
        world = 3

        def rank(i):
            n = clients[i].add("barrier/counter", 1)
            if n == world:
                clients[i].set("barrier/release", b"1")
            clients[i].wait("barrier/release")

        ts = [threading.Thread(target=rank, args=(i,)) for i in range(world)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=10)
        assert master.get("barrier/release") == b"1"
        for c in clients:
            c.close()
        master.close()


class TestWiring:
    def test_profiler_uses_native_recorder(self):
        import paddle_tpu as paddle
        from paddle_tpu import profiler as prof
        from paddle_tpu.profiler import _recorder

        assert _recorder._native is not None
        p = prof.Profiler()
        p.start()
        paddle.tanh(paddle.ones([4]))
        p.stop()
        assert any(e[0] == "op::tanh" for e in p._events)

    def test_distributed_tcpstore_export(self):
        import paddle_tpu.distributed as dist

        s = dist.TCPStore(is_master=True)
        s.set("x", b"y")
        assert s.get("x") == b"y"
        s.close()


class TestTCPStoreWireHardening:
    """Wire sizes are untrusted (same class as the PS-table hardening):
    a huge SET length must yield an error reply + close — never a
    bad_alloc that std::terminate()s the in-process trainer."""

    def _raw(self, port):
        import socket

        s = socket.create_connection(("127.0.0.1", port), timeout=10)
        s.settimeout(10)
        return s

    def _recv_exact(self, sock, n):
        buf = b""
        while len(buf) < n:
            c = sock.recv(n - len(buf))
            if not c:
                return buf
            buf += c
        return buf

    def test_oversized_set_value_rejected_server_survives(self):
        import struct

        master = native.TCPStore(is_master=True)
        try:
            s = self._raw(master.port)
            # SET "k" with a 2^40-byte value length
            s.sendall(struct.pack("<BI", 0, 1) + b"k"
                      + struct.pack("<Q", 1 << 40))
            status, vlen = struct.unpack(
                "<qQ", self._recv_exact(s, 16))
            assert status == -3 and vlen == 0
            assert s.recv(1) == b""  # desynced stream closed
            s.close()
            # server alive: normal client traffic still works
            c = native.TCPStore(port=master.port)
            c.set("x", b"1")
            assert c.get("x") == b"1"
            c.close()
        finally:
            master.close()

    def test_oversized_key_closes_connection(self):
        import struct

        master = native.TCPStore(is_master=True)
        try:
            s = self._raw(master.port)
            s.sendall(struct.pack("<BI", 0, 1 << 20))  # 1 MiB key length
            assert self._recv_exact(s, 16) == b""      # closed, no reply
            s.close()
            c = native.TCPStore(port=master.port)
            c.add("n", 2)
            assert c.add("n", 3) == 5
            c.close()
        finally:
            master.close()
