"""Tests for the grind-1/2 parity surfaces: incubate API, static EMA/
metrics, callbacks ReduceLROnPlateau, distributed split, autograd
jacobian/hessian.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.static as static
from paddle_tpu import incubate, optimizer as opt


class TestIncubateAPI:
    def test_softmax_mask_fuse(self):
        x = np.random.RandomState(0).rand(2, 3, 4).astype(np.float32)
        mask = np.zeros((2, 3, 4), np.float32)
        mask[..., -1] = -1e9
        out = np.asarray(incubate.softmax_mask_fuse(
            paddle.to_tensor(x), paddle.to_tensor(mask)).numpy())
        np.testing.assert_allclose(out.sum(-1), 1.0, atol=1e-5)
        assert np.all(out[..., -1] < 1e-6)

    def test_softmax_mask_fuse_upper_triangle(self):
        x = np.random.RandomState(1).rand(1, 4, 4).astype(np.float32)
        out = np.asarray(incubate.softmax_mask_fuse_upper_triangle(
            paddle.to_tensor(x)).numpy())
        assert out[0, 0, 1] == 0 and out[0, 0, 0] == pytest.approx(1.0)
        np.testing.assert_allclose(out.sum(-1), 1.0, atol=1e-5)

    def test_identity_loss_reductions(self):
        x = paddle.to_tensor(np.asarray([1.0, 3.0], np.float32))
        assert float(incubate.identity_loss(x, "sum").numpy()) == 4.0
        assert float(incubate.identity_loss(x, "mean").numpy()) == 2.0
        np.testing.assert_allclose(
            np.asarray(incubate.identity_loss(x, "none").numpy()), [1, 3])

    def test_graph_khop_sampler(self):
        # CSC graph: 0 -> {1, 2}, 1 -> {2}, 2 -> {}
        row = paddle.to_tensor(np.asarray([1, 2, 2], np.int64))
        colptr = paddle.to_tensor(np.asarray([0, 2, 3, 3], np.int64))
        src, dst, nodes = incubate.graph_khop_sampler(
            row, colptr, paddle.to_tensor(np.asarray([0], np.int64)),
            sample_sizes=[2])
        n = np.asarray(nodes.numpy())
        assert n[0] == 0 and set(n.tolist()) <= {0, 1, 2}
        assert np.asarray(src.numpy()).shape == np.asarray(
            dst.numpy()).shape

    def test_lookahead_slow_weights(self):
        m = nn.Linear(4, 2)
        la = incubate.LookAhead(
            opt.SGD(learning_rate=0.5, parameters=m.parameters()),
            alpha=0.5, k=2)
        rng = np.random.RandomState(2)
        x = rng.randn(4, 4).astype(np.float32)
        y = rng.randn(4, 2).astype(np.float32)

        def step():
            loss = paddle.mean((m(paddle.to_tensor(x))
                                - paddle.to_tensor(y)) ** 2)
            loss.backward()
            la.step()
            la.clear_grad()

        step()
        w_after1 = np.asarray(m.weight.numpy()).copy()
        step()  # k=2: slow-weight interpolation fires
        w_after2 = np.asarray(m.weight.numpy())
        assert not np.allclose(w_after1, w_after2)

    def test_model_average_apply_restore(self):
        m = nn.Linear(3, 2)
        ma = incubate.ModelAverage(0.15, parameters=list(m.parameters()))
        for i in range(3):
            m.weight.set_value(m.weight.value + 1.0)
            ma.step()
        now = np.asarray(m.weight.numpy()).copy()
        with ma.apply():
            avg = np.asarray(m.weight.numpy()).copy()
        assert not np.allclose(now, avg)
        np.testing.assert_allclose(np.asarray(m.weight.numpy()), now)


class TestStaticExtras:
    def test_ema_update_apply_restore(self):
        paddle.enable_static()
        try:
            main = static.Program()
            startup = static.Program()
            with static.program_guard(main, startup):
                w = paddle.create_parameter([2, 2], "float32", name="ema_w")
            ema = static.ExponentialMovingAverage(0.5)
            w.set_value(np.ones((2, 2), np.float32))
            ema.update([w])
            w.set_value(np.full((2, 2), 3.0, np.float32))
            ema.update([w])
            cur = np.asarray(w.numpy()).copy()
            with ema.apply():
                shadow = np.asarray(w.numpy()).copy()
            assert shadow.mean() < cur.mean()
            np.testing.assert_allclose(np.asarray(w.numpy()), cur)
        finally:
            paddle.disable_static()

    def test_accuracy_topk(self):
        pred = paddle.to_tensor(np.asarray(
            [[0.1, 0.5, 0.4], [0.8, 0.1, 0.1]], np.float32))
        lbl = paddle.to_tensor(np.asarray([[2], [0]], np.int64))
        a1 = float(static.accuracy(pred, lbl, k=1).numpy())
        a2 = float(static.accuracy(pred, lbl, k=2).numpy())
        assert a1 == pytest.approx(0.5) and a2 == pytest.approx(1.0)

    def test_auc_ranks_perfect_separation(self):
        pred = paddle.to_tensor(np.asarray(
            [[0.1, 0.9], [0.9, 0.1], [0.2, 0.8], [0.7, 0.3]], np.float32))
        lbl = paddle.to_tensor(np.asarray([[1], [0], [1], [0]], np.int64))
        a, _, _ = static.auc(pred, lbl)
        assert float(a.numpy()) > 0.95


class TestReduceLROnPlateau:
    def test_reduces_after_patience(self):
        from paddle_tpu.hapi.callbacks import ReduceLROnPlateau

        class FakeOpt:
            def __init__(self):
                self.lr = 0.1

            def get_lr(self):
                return self.lr

            def set_lr(self, v):
                self.lr = v

        class FakeModel:
            _optimizer = FakeOpt()

        cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=2,
                               verbose=0, mode="min")
        cb.model = FakeModel()
        cb.on_epoch_end(0, {"loss": 1.0})
        for e in range(1, 3):
            cb.on_epoch_end(e, {"loss": 1.0})  # 2 stale epochs -> reduce
        assert FakeModel._optimizer.lr == pytest.approx(0.05)
        for e in range(3, 5):
            cb.on_epoch_end(e, {"loss": 1.0})  # plateau again -> reduce
        assert FakeModel._optimizer.lr == pytest.approx(0.025)


class TestDistributedSplit:
    def test_split_routes_to_mpu_linear(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed import fleet

        fleet.init(is_collective=True)
        x = paddle.to_tensor(
            np.random.RandomState(3).randn(2, 8).astype(np.float32))
        out = dist.split(x, (8, 4), "linear", axis=1, num_partitions=1)
        assert tuple(np.asarray(
            out.numpy() if hasattr(out, "numpy") else out).shape) == (2, 4)


class TestJacobianHessian:
    def test_jacobian_diag(self):
        from paddle_tpu.autograd import jacobian

        x = paddle.to_tensor(np.asarray([1.0, 2.0], np.float32))
        J = np.asarray(jacobian(lambda v: v * v, x).numpy())
        np.testing.assert_allclose(J, np.diag([2.0, 4.0]))

    def test_hessian_of_cubic(self):
        from paddle_tpu.autograd import hessian

        x = paddle.to_tensor(np.asarray([1.0, 2.0], np.float32))
        H = np.asarray(hessian(
            lambda v: paddle.sum(v * v * v), x).numpy())
        np.testing.assert_allclose(H, np.diag([6.0, 12.0]))


class TestHapiCallbackIntegration:
    def test_fit_with_reduce_lr_on_plateau(self):
        from paddle_tpu.hapi import Model
        from paddle_tpu.hapi.callbacks import ReduceLROnPlateau
        from paddle_tpu.io import Dataset

        class Flat(Dataset):
            def __init__(self, n=8):
                rng = np.random.RandomState(0)
                self.x = rng.rand(n, 4).astype(np.float32)
                self.y = rng.rand(n, 2).astype(np.float32)

            def __getitem__(self, i):
                return self.x[i], self.y[i]

            def __len__(self):
                return len(self.x)

        net = nn.Linear(4, 2)
        model = Model(net)
        optim = opt.SGD(learning_rate=0.0, parameters=net.parameters())
        model.prepare(optim, nn.MSELoss())
        cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=1,
                               verbose=0, mode="min", min_delta=0.0)
        # lr==0 -> loss constant -> plateau fires; lr halves from 0 stays 0
        model.fit(Flat(), batch_size=4, epochs=4, verbose=0,
                  callbacks=[cb])
        assert cb.best is not None and np.isfinite(cb.best)


class TestNNUtils:
    def test_weight_norm_roundtrip(self):
        lin = nn.Linear(4, 3)
        w0 = np.asarray(lin.weight.numpy()).copy()
        nn.utils.weight_norm(lin, dim=0)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 4).astype(np.float32))
        out = np.asarray(lin(x).numpy())
        ref = np.asarray(x.numpy()) @ w0 + np.asarray(lin.bias.numpy())
        np.testing.assert_allclose(out, ref, atol=1e-5)
        names = [n for n, _ in lin.named_parameters()]
        assert "weight_g" in names and "weight_v" in names
        nn.utils.remove_weight_norm(lin)
        np.testing.assert_allclose(np.asarray(lin.weight.numpy()), w0,
                                   atol=1e-5)

    def test_spectral_norm_unit_sigma(self):
        lin = nn.Linear(6, 6)
        nn.utils.spectral_norm(lin, n_power_iterations=5)
        lin.train()
        for _ in range(2):
            lin(paddle.to_tensor(
                np.random.randn(2, 6).astype(np.float32)))
        sig = np.linalg.svd(np.asarray(lin.weight.numpy()),
                            compute_uv=False)[0]
        assert 0.8 < sig < 1.2

    def test_vector_roundtrip_and_clip(self):
        m = nn.Linear(3, 3)
        before = [np.asarray(p.numpy()).copy() for p in m.parameters()]
        vec = nn.utils.parameters_to_vector(list(m.parameters()))
        nn.utils.vector_to_parameters(vec, list(m.parameters()))
        for b, p in zip(before, m.parameters()):
            np.testing.assert_allclose(b, np.asarray(p.numpy()))
        loss = paddle.sum(m(paddle.to_tensor(
            np.ones((2, 3), np.float32))) ** 2)
        loss.backward()
        nn.utils.clip_grad_norm_(list(m.parameters()), max_norm=0.1)
        g2 = np.sqrt(sum(
            float(np.sum(np.asarray(p.grad.numpy()) ** 2))
            for p in m.parameters()))
        assert g2 <= 0.11
        nn.utils.clip_grad_value_(list(m.parameters()), 0.01)
        for p in m.parameters():
            assert np.abs(np.asarray(p.grad.numpy())).max() <= 0.01 + 1e-7
