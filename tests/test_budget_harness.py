"""experiments/_budget.py — the spawn-with-budget harness that guards the
round record (bench watchdog) and the per-variant experiment isolation.

Reference analog: the reference's elastic/launch watchdogs
(fleet/launch/controller process management) kill worker process GROUPS
on timeout; this harness is the TPU-session equivalent and must never
orphan a child (an orphaned remote-compile helper holds the device claim
and wedges every later probe — observed 2026-07-31)."""
import os
import signal
import subprocess
import sys
import time

import pytest

# wall-clock-bound by design (children sleep out real timeout budgets):
# rides the slow tier (run with -m slow), not tier-1 — moved when the
# prefix-cache suite (round 11) pushed tier-1 against its 870s timeout
pytestmark = pytest.mark.slow

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "experiments"))

from _budget import run_budgeted  # noqa: E402


def test_fast_child_passes_through():
    # -I everywhere in children: the axon sitecustomize costs ~2.3s of
    # interpreter startup (it imports jax), which starves short test
    # budgets and makes "what did the child print before the kill"
    # nondeterministic
    r = run_budgeted([sys.executable, "-I", "-c", "print('hello'); "
                      "import sys; print('err', file=sys.stderr)"], 30)
    assert r.out.strip() == "hello"
    assert r.err.strip() == "err"
    assert r.returncode == 0
    assert not r.timed_out


def test_timeout_kills_whole_group():
    # child spawns a SAME-GROUP grandchild (the usual helper shape: plain
    # Popen inherits the group) then hangs; the budget's killpg must take
    # both.  The other shape — a grandchild in its OWN session, reachable
    # only via its parent's TERM trap — is what
    # test_sigterm_forwarded_to_child_group exercises (run_budgeted's
    # child is session-detached by construction).
    code = (
        "import subprocess, sys, time\n"
        "p = subprocess.Popen([sys.executable, '-I', '-c', 'import time; "
        "time.sleep(120)'])\n"
        "print('GRANDCHILD', p.pid, flush=True)\n"
        "time.sleep(120)\n")
    t0 = time.monotonic()
    r = run_budgeted([sys.executable, "-I", "-u", "-c", code], 3)
    assert r.timed_out
    assert time.monotonic() - t0 < 60  # budget + grace, not 120s
    gpid = int(r.out.split()[1])  # partial stdout salvaged
    # grandchild must be dead (or a reaped zombie) — signal 0 probes
    for _ in range(50):
        try:
            os.kill(gpid, 0)
        except ProcessLookupError:
            break
        # still alive: only acceptable as a zombie awaiting init's reap
        try:
            stat = open(f"/proc/{gpid}/stat").read().split()[2]
        except FileNotFoundError:  # reaped between probes — dead: success
            break
        if stat == "Z":
            break
        time.sleep(0.2)
    else:
        raise AssertionError(f"grandchild {gpid} survived the group kill")


def test_partial_stdout_salvaged_on_timeout():
    r = run_budgeted([sys.executable, "-I", "-u", "-c",
                      "print('evidence'); import time; time.sleep(60)"], 2)
    assert r.timed_out
    assert "evidence" in r.out


def test_sigterm_forwarded_to_child_group(tmp_path):
    """Outer TERM to the HARNESS process must kill the child group before
    the harness dies (the runbook's step-timeout path). The child is
    tagged with a unique argv marker so its survival is observable."""
    marker = f"budget_harness_marker_{os.getpid()}"
    exp_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "experiments")
    child = tmp_path / "tagged_child.py"
    child.write_text(f"# {marker}\nimport time\ntime.sleep(120)\n")
    helper = tmp_path / "helper.py"
    helper.write_text("\n".join([
        "import sys",
        f"sys.path.insert(0, {exp_dir!r})",
        "from _budget import run_budgeted",
        f"run_budgeted([sys.executable, '-I', '-u', {str(child)!r},",
        f"              {marker!r}], 100)",
    ]))
    p = subprocess.Popen([sys.executable, "-I", "-u", str(helper)])
    time.sleep(3)  # let the child start
    p.send_signal(signal.SIGTERM)
    rc = p.wait(timeout=30)
    assert rc in (128 + signal.SIGTERM, -signal.SIGTERM)
    # the tagged child must not survive its harness
    time.sleep(1)
    left = subprocess.run(["pgrep", "-f", marker],
                          capture_output=True, text=True)
    assert left.stdout.strip() == "", f"orphaned child: {left.stdout}"
