"""nn package tests — numerical parity vs numpy/torch references.

Mirrors the reference OpTest strategy (test/legacy_test/eager_op_test.py:378):
check_output against an independent reference implementation, check_grad via
comparison with torch autograd where convenient.
"""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def t2n(t):
    return np.asarray(t.numpy(), dtype=np.float32)


class TestLayerSystem:
    def test_parameter_registration(self):
        lin = nn.Linear(4, 3)
        assert len(lin.parameters()) == 2
        names = [n for n, _ in lin.named_parameters()]
        assert names == ["weight", "bias"]

    def test_nested_state_dict_roundtrip(self):
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        sd = m.state_dict()
        assert set(sd.keys()) == {"0.weight", "0.bias", "2.weight", "2.bias"}
        m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        missing, unexpected = m2.set_state_dict(sd)
        assert not missing and not unexpected
        x = paddle.randn([5, 4])
        np.testing.assert_allclose(t2n(m(x)), t2n(m2(x)), rtol=1e-6)

    def test_train_eval_propagates(self):
        m = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.5))
        m.eval()
        assert not m[1].training
        m.train()
        assert m[1].training

    def test_buffers(self):
        bn = nn.BatchNorm1D(4)
        buf_names = [n for n, _ in bn.named_buffers()]
        assert "_mean" in buf_names and "_variance" in buf_names
        assert "_mean" in bn.state_dict()

    def test_apply_and_children(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Linear(2, 2))
        count = []
        m.apply(lambda l: count.append(type(l).__name__))
        assert count.count("Linear") == 2

    def test_layerlist_and_dict(self):
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        ll.append(nn.Linear(2, 2))
        assert len(ll) == 4 and len(ll.parameters()) == 8
        ld = nn.LayerDict({"a": nn.Linear(2, 2)})
        ld["b"] = nn.Linear(2, 2)
        assert set(ld.keys()) == {"a", "b"}


class TestLinearConv:
    def test_linear_vs_numpy(self):
        lin = nn.Linear(6, 3)
        x = np.random.randn(4, 6).astype(np.float32)
        ref = x @ t2n(lin.weight) + t2n(lin.bias)
        np.testing.assert_allclose(t2n(lin(paddle.to_tensor(x))), ref, rtol=1e-5)

    def test_conv2d_vs_torch(self):
        conv = nn.Conv2D(3, 5, 3, stride=2, padding=1)
        x = np.random.randn(2, 3, 9, 9).astype(np.float32)
        tref = torch.nn.functional.conv2d(
            torch.tensor(x), torch.tensor(t2n(conv.weight)),
            torch.tensor(t2n(conv.bias)), stride=2, padding=1)
        np.testing.assert_allclose(
            t2n(conv(paddle.to_tensor(x))), tref.numpy(), rtol=1e-4, atol=1e-5)

    def test_conv2d_groups_dilation(self):
        conv = nn.Conv2D(4, 8, 3, groups=2, dilation=2, padding=2)
        x = np.random.randn(2, 4, 8, 8).astype(np.float32)
        tref = torch.nn.functional.conv2d(
            torch.tensor(x), torch.tensor(t2n(conv.weight)),
            torch.tensor(t2n(conv.bias)), padding=2, dilation=2, groups=2)
        np.testing.assert_allclose(
            t2n(conv(paddle.to_tensor(x))), tref.numpy(), rtol=1e-4, atol=1e-5)

    def test_conv2d_transpose_vs_torch(self):
        conv = nn.Conv2DTranspose(4, 6, 3, stride=2, padding=1, output_padding=1)
        x = np.random.randn(2, 4, 5, 5).astype(np.float32)
        tref = torch.nn.functional.conv_transpose2d(
            torch.tensor(x), torch.tensor(t2n(conv.weight)),
            torch.tensor(t2n(conv.bias)), stride=2, padding=1, output_padding=1)
        np.testing.assert_allclose(
            t2n(conv(paddle.to_tensor(x))), tref.numpy(), rtol=1e-4, atol=1e-5)

    def test_conv1d_and_3d_shapes(self):
        c1 = nn.Conv1D(2, 4, 3, padding=1)
        assert c1(paddle.randn([2, 2, 10])).shape == [2, 4, 10]
        c3 = nn.Conv3D(2, 4, 3, padding=1)
        assert c3(paddle.randn([1, 2, 5, 6, 7])).shape == [1, 4, 5, 6, 7]

    def test_conv_grad_flows(self):
        conv = nn.Conv2D(3, 4, 3)
        x = paddle.randn([1, 3, 6, 6])
        x.stop_gradient = False
        loss = paddle.sum(conv(x))
        loss.backward()
        assert conv.weight.grad is not None
        assert x.grad.shape == [1, 3, 6, 6]


class TestNorms:
    def test_batchnorm_train_stats(self):
        bn = nn.BatchNorm2D(3, momentum=0.9)
        x = np.random.randn(4, 3, 5, 5).astype(np.float32) * 2 + 1
        out = bn(paddle.to_tensor(x))
        mean = x.mean(axis=(0, 2, 3))
        var = x.var(axis=(0, 2, 3))
        ref = (x - mean[None, :, None, None]) / np.sqrt(var[None, :, None, None] + 1e-5)
        np.testing.assert_allclose(t2n(out), ref, rtol=1e-4, atol=1e-4)
        # running stats updated
        np.testing.assert_allclose(
            t2n(bn._mean), 0.1 * mean, rtol=1e-4, atol=1e-5)

    def test_batchnorm_eval_uses_running(self):
        bn = nn.BatchNorm2D(3)
        bn.eval()
        x = np.random.randn(2, 3, 4, 4).astype(np.float32)
        out = bn(paddle.to_tensor(x))
        np.testing.assert_allclose(t2n(out), x / np.sqrt(1 + 1e-5), rtol=1e-4)

    def test_layernorm_vs_torch(self):
        ln = nn.LayerNorm(8)
        x = np.random.randn(4, 6, 8).astype(np.float32)
        tref = torch.nn.functional.layer_norm(
            torch.tensor(x), (8,), torch.tensor(t2n(ln.weight)),
            torch.tensor(t2n(ln.bias)))
        np.testing.assert_allclose(t2n(ln(paddle.to_tensor(x))), tref.numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_groupnorm_vs_torch(self):
        gn = nn.GroupNorm(2, 6)
        x = np.random.randn(3, 6, 4, 4).astype(np.float32)
        tref = torch.nn.functional.group_norm(
            torch.tensor(x), 2, torch.tensor(t2n(gn.weight)),
            torch.tensor(t2n(gn.bias)))
        np.testing.assert_allclose(t2n(gn(paddle.to_tensor(x))), tref.numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_rmsnorm(self):
        rn = nn.RMSNorm(8)
        x = np.random.randn(2, 8).astype(np.float32)
        ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(t2n(rn(paddle.to_tensor(x))), ref, rtol=1e-4)


class TestPooling:
    def test_maxpool_vs_torch(self):
        x = np.random.randn(2, 3, 8, 8).astype(np.float32)
        out = F.max_pool2d(paddle.to_tensor(x), 2, 2)
        tref = torch.nn.functional.max_pool2d(torch.tensor(x), 2, 2)
        np.testing.assert_allclose(t2n(out), tref.numpy(), rtol=1e-6)

    def test_avgpool_padding_vs_torch(self):
        x = np.random.randn(2, 3, 7, 7).astype(np.float32)
        out = F.avg_pool2d(paddle.to_tensor(x), 3, 2, padding=1, exclusive=True)
        tref = torch.nn.functional.avg_pool2d(
            torch.tensor(x), 3, 2, padding=1, count_include_pad=False)
        np.testing.assert_allclose(t2n(out), tref.numpy(), rtol=1e-5)

    def test_adaptive_avg(self):
        x = np.random.randn(2, 3, 9, 9).astype(np.float32)
        out = F.adaptive_avg_pool2d(paddle.to_tensor(x), 3)
        tref = torch.nn.functional.adaptive_avg_pool2d(torch.tensor(x), 3)
        np.testing.assert_allclose(t2n(out), tref.numpy(), rtol=1e-5)

    def test_adaptive_nonuniform(self):
        x = np.random.randn(1, 2, 7, 5).astype(np.float32)
        out = F.adaptive_avg_pool2d(paddle.to_tensor(x), [3, 2])
        tref = torch.nn.functional.adaptive_avg_pool2d(torch.tensor(x), (3, 2))
        np.testing.assert_allclose(t2n(out), tref.numpy(), rtol=1e-5)


class TestLosses:
    def test_cross_entropy_vs_torch(self):
        logits = np.random.randn(8, 10).astype(np.float32)
        labels = np.random.randint(0, 10, (8,))
        out = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels))
        tref = torch.nn.functional.cross_entropy(
            torch.tensor(logits), torch.tensor(labels))
        np.testing.assert_allclose(float(out), float(tref), rtol=1e-5)

    def test_cross_entropy_ignore_index(self):
        logits = np.random.randn(6, 5).astype(np.float32)
        labels = np.array([0, 1, -100, 3, -100, 2])
        out = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels),
                              ignore_index=-100)
        tref = torch.nn.functional.cross_entropy(
            torch.tensor(logits), torch.tensor(labels), ignore_index=-100)
        np.testing.assert_allclose(float(out), float(tref), rtol=1e-5)

    def test_cross_entropy_soft_label(self):
        logits = np.random.randn(4, 6).astype(np.float32)
        soft = np.random.dirichlet(np.ones(6), 4).astype(np.float32)
        out = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(soft),
                              soft_label=True)
        tref = torch.nn.functional.cross_entropy(
            torch.tensor(logits), torch.tensor(soft))
        np.testing.assert_allclose(float(out), float(tref), rtol=1e-5)

    def test_bce_with_logits_vs_torch(self):
        z = np.random.randn(5, 3).astype(np.float32)
        y = np.random.randint(0, 2, (5, 3)).astype(np.float32)
        out = F.binary_cross_entropy_with_logits(
            paddle.to_tensor(z), paddle.to_tensor(y))
        tref = torch.nn.functional.binary_cross_entropy_with_logits(
            torch.tensor(z), torch.tensor(y))
        np.testing.assert_allclose(float(out), float(tref), rtol=1e-5)

    def test_kl_smooth_l1_mse(self):
        a = np.random.randn(4, 5).astype(np.float32)
        b = np.random.randn(4, 5).astype(np.float32)
        np.testing.assert_allclose(
            float(F.mse_loss(paddle.to_tensor(a), paddle.to_tensor(b))),
            float(torch.nn.functional.mse_loss(torch.tensor(a), torch.tensor(b))),
            rtol=1e-5)
        np.testing.assert_allclose(
            float(F.smooth_l1_loss(paddle.to_tensor(a), paddle.to_tensor(b))),
            float(torch.nn.functional.smooth_l1_loss(torch.tensor(a), torch.tensor(b))),
            rtol=1e-5)

    def test_ctc_loss_vs_torch(self):
        T, B, C, S = 12, 3, 6, 4
        logits = np.random.randn(T, B, C).astype(np.float32)
        log_probs = torch.tensor(logits).log_softmax(-1)
        labels = np.random.randint(1, C, (B, S))
        in_len = np.array([12, 10, 8])
        lb_len = np.array([4, 3, 2])
        tref = torch.nn.functional.ctc_loss(
            log_probs, torch.tensor(labels), torch.tensor(in_len),
            torch.tensor(lb_len), blank=0, reduction="mean")
        out = F.ctc_loss(
            paddle.to_tensor(log_probs.numpy()), paddle.to_tensor(labels),
            paddle.to_tensor(in_len), paddle.to_tensor(lb_len), blank=0)
        np.testing.assert_allclose(float(out), float(tref), rtol=1e-4)


class TestActivationsAttention:
    def test_gelu_softmax_vs_torch(self):
        x = np.random.randn(3, 7).astype(np.float32)
        np.testing.assert_allclose(
            t2n(F.gelu(paddle.to_tensor(x))),
            torch.nn.functional.gelu(torch.tensor(x)).numpy(), rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(
            t2n(F.softmax(paddle.to_tensor(x))),
            torch.tensor(x).softmax(-1).numpy(), rtol=1e-5, atol=1e-7)

    def test_sdpa_vs_torch(self):
        B, S, H, D = 2, 6, 2, 8
        q = np.random.randn(B, S, H, D).astype(np.float32)
        k = np.random.randn(B, S, H, D).astype(np.float32)
        v = np.random.randn(B, S, H, D).astype(np.float32)
        out = F.scaled_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            is_causal=True)
        tref = torch.nn.functional.scaled_dot_product_attention(
            torch.tensor(q).transpose(1, 2), torch.tensor(k).transpose(1, 2),
            torch.tensor(v).transpose(1, 2), is_causal=True).transpose(1, 2)
        np.testing.assert_allclose(t2n(out), tref.numpy(), rtol=1e-4, atol=1e-5)

    def test_flash_attention_matches_sdpa(self):
        B, S, H, D = 2, 8, 2, 4
        q = paddle.randn([B, S, H, D])
        k = paddle.randn([B, S, H, D])
        v = paddle.randn([B, S, H, D])
        out1, _ = F.flash_attention(q, k, v, causal=True)
        out2 = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        np.testing.assert_allclose(t2n(out1), t2n(out2), rtol=1e-4, atol=1e-5)


class TestRNN:
    def test_lstm_vs_torch(self):
        mine = nn.LSTM(4, 6)
        tref = torch.nn.LSTM(4, 6, batch_first=True)
        cell = mine.rnns[0].cell
        with torch.no_grad():
            tref.weight_ih_l0.copy_(torch.tensor(t2n(cell.weight_ih)))
            tref.weight_hh_l0.copy_(torch.tensor(t2n(cell.weight_hh)))
            tref.bias_ih_l0.copy_(torch.tensor(t2n(cell.bias_ih)))
            tref.bias_hh_l0.copy_(torch.tensor(t2n(cell.bias_hh)))
        x = np.random.randn(2, 5, 4).astype(np.float32)
        out, (h, c) = mine(paddle.to_tensor(x))
        tout, (th, tc) = tref(torch.tensor(x))
        np.testing.assert_allclose(t2n(out), tout.detach().numpy(), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(t2n(h), th.detach().numpy(), rtol=1e-4, atol=1e-5)

    def test_gru_shapes_and_grad(self):
        gru = nn.GRU(3, 5, num_layers=2)
        x = paddle.randn([2, 7, 3])
        x.stop_gradient = False
        out, h = gru(x)
        assert out.shape == [2, 7, 5] and h.shape == [2, 2, 5]
        paddle.sum(out).backward()
        assert x.grad is not None

    def test_rnn_sequence_length_masks(self):
        rnn = nn.SimpleRNN(2, 3)
        x = paddle.randn([2, 5, 2])
        out, h = rnn(x, sequence_length=paddle.to_tensor(np.array([5, 3])))
        assert np.allclose(t2n(out)[1, 3:], 0.0)


class TestTransformer:
    def test_encoder_decoder_roundtrip(self):
        model = nn.Transformer(d_model=16, nhead=4, num_encoder_layers=2,
                               num_decoder_layers=2, dim_feedforward=32)
        model.eval()
        src = paddle.randn([2, 5, 16])
        tgt = paddle.randn([2, 4, 16])
        out = model(src, tgt)
        assert out.shape == [2, 4, 16]

    def test_mha_cache_decode(self):
        mha = nn.MultiHeadAttention(16, 4)
        mha.eval()
        x = paddle.randn([2, 1, 16])
        cache = mha.gen_cache(x)
        out, cache = mha(x, x, x, cache=cache)
        assert out.shape == [2, 1, 16]
        assert cache.k.shape[1] == 1
        out2, cache = mha(x, x, x, cache=cache)
        assert cache.k.shape[1] == 2

    def test_mha_matches_full_attention(self):
        mha = nn.MultiHeadAttention(8, 2)
        mha.eval()
        x = paddle.randn([1, 4, 8])
        full = mha(x)
        # manual: project, sdpa, out-proj
        q = mha.q_proj(x); k = mha.k_proj(x); v = mha.v_proj(x)
        import paddle_tpu.ops.manipulation as M
        q = M.reshape(q, [1, 4, 2, 4]); k = M.reshape(k, [1, 4, 2, 4]); v = M.reshape(v, [1, 4, 2, 4])
        att = F.scaled_dot_product_attention(q, k, v)
        manual = mha.out_proj(M.reshape(att, [1, 4, 8]))
        np.testing.assert_allclose(t2n(full), t2n(manual), rtol=1e-5)


class TestCommonLayers:
    def test_embedding_padding_idx(self):
        emb = nn.Embedding(10, 4, padding_idx=0)
        ids = paddle.to_tensor(np.array([[0, 1], [2, 0]]))
        out = emb(ids)
        assert np.allclose(t2n(out)[0, 0], 0.0)
        assert np.allclose(t2n(out)[1, 1], 0.0)
        # grad to padding row must be zero
        loss = paddle.sum(emb(ids))
        loss.backward()
        assert np.allclose(t2n(emb.weight.grad)[0], 0.0)

    def test_dropout_modes(self):
        x = paddle.ones([1000])
        d = nn.Dropout(0.5)
        y = d(x)
        kept = t2n(y) != 0
        assert abs(kept.mean() - 0.5) < 0.1
        np.testing.assert_allclose(t2n(y)[kept], 2.0)
        d.eval()
        np.testing.assert_allclose(t2n(d(x)), 1.0)

    def test_pad_reflect(self):
        x = np.arange(9, dtype=np.float32).reshape(1, 1, 3, 3)
        out = F.pad(paddle.to_tensor(x), [1, 1, 1, 1], mode="reflect")
        tref = torch.nn.functional.pad(torch.tensor(x), (1, 1, 1, 1), mode="reflect")
        np.testing.assert_allclose(t2n(out), tref.numpy())

    def test_interpolate_bilinear(self):
        x = np.random.randn(1, 2, 4, 4).astype(np.float32)
        out = F.interpolate(paddle.to_tensor(x), size=[8, 8], mode="bilinear")
        tref = torch.nn.functional.interpolate(
            torch.tensor(x), size=(8, 8), mode="bilinear", align_corners=False)
        np.testing.assert_allclose(t2n(out), tref.numpy(), rtol=1e-4, atol=1e-5)

    def test_interpolate_align_corners(self):
        x = np.random.randn(1, 2, 4, 4).astype(np.float32)
        out = F.interpolate(paddle.to_tensor(x), size=[7, 7], mode="bilinear",
                            align_corners=True)
        tref = torch.nn.functional.interpolate(
            torch.tensor(x), size=(7, 7), mode="bilinear", align_corners=True)
        np.testing.assert_allclose(t2n(out), tref.numpy(), rtol=1e-4, atol=1e-5)

    def test_pixel_shuffle(self):
        x = np.random.randn(1, 8, 3, 3).astype(np.float32)
        out = F.pixel_shuffle(paddle.to_tensor(x), 2)
        tref = torch.nn.functional.pixel_shuffle(torch.tensor(x), 2)
        np.testing.assert_allclose(t2n(out), tref.numpy())

    def test_unfold_vs_torch(self):
        x = np.random.randn(2, 3, 6, 6).astype(np.float32)
        out = F.unfold(paddle.to_tensor(x), [2, 2], strides=2)
        tref = torch.nn.functional.unfold(torch.tensor(x), (2, 2), stride=2)
        np.testing.assert_allclose(t2n(out), tref.numpy(), rtol=1e-5)

    def test_initializers(self):
        from paddle_tpu.nn.initializer import (
            Constant, KaimingNormal, Normal, TruncatedNormal, XavierUniform)

        w = nn.Linear(100, 100, weight_attr=paddle.ParamAttr(
            initializer=Normal(0, 0.02))).weight
        assert abs(float(paddle.std(w)) - 0.02) < 0.005
        c = Constant(3.0)((2, 2))
        assert np.allclose(np.asarray(c), 3.0)
        tn = TruncatedNormal(0, 1.0)((1000,))
        assert np.abs(np.asarray(tn)).max() <= 2.0 + 1e-6
