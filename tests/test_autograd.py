"""Eager autograd engine tests (reference pattern: OpTest.check_grad
finite-difference checks, eager_op_test.py:2377 — here vs jax.grad)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle


def test_simple_backward():
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_chain_and_accumulation():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * 3
    z = y * y + x  # dz/dx = 2*3x*3 + 1 = 18x + 1 = 37
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), 37.0)


def test_grad_accumulates_across_backwards():
    x = paddle.to_tensor(1.0, stop_gradient=False)
    (x * 2).backward()
    (x * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), 5.0)


def test_matmul_grad_vs_jax():
    a_np = np.random.randn(3, 4).astype("float32")
    b_np = np.random.randn(4, 2).astype("float32")
    a = paddle.to_tensor(a_np, stop_gradient=False)
    b = paddle.to_tensor(b_np, stop_gradient=False)
    loss = paddle.matmul(a, b).sum()
    loss.backward()
    ga, gb = jax.grad(lambda x, y: (x @ y).sum(), argnums=(0, 1))(a_np, b_np)
    np.testing.assert_allclose(a.grad.numpy(), ga, rtol=1e-5)
    np.testing.assert_allclose(b.grad.numpy(), gb, rtol=1e-5)


def test_broadcast_grad():
    x = paddle.to_tensor(np.ones((3, 4), "float32"), stop_gradient=False)
    b = paddle.to_tensor(np.ones((4,), "float32"), stop_gradient=False)
    (x + b).sum().backward()
    np.testing.assert_allclose(b.grad.numpy(), [3, 3, 3, 3])


def test_stop_gradient_blocks():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = paddle.to_tensor([2.0], stop_gradient=True)
    (x * y).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    assert y.grad is None


def test_detach():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = (x * 2).detach()
    z = (y * x).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0])  # y treated const


def test_diamond_graph():
    x = paddle.to_tensor(3.0, stop_gradient=False)
    a = x * 2
    b = x * 5
    ((a + b) * a).sum().backward()
    # f = (2x+5x)*2x = 14x^2, df/dx = 28x = 84
    np.testing.assert_allclose(x.grad.numpy(), 84.0)


def test_multi_output_op_grad():
    x = paddle.to_tensor(np.arange(6, dtype="float32"), stop_gradient=False)
    parts = paddle.split(x, 3)
    (parts[0].sum() * 2 + parts[2].sum() * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 2, 0, 0, 3, 3])


def test_non_scalar_backward_needs_grad():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 2
    with pytest.raises(RuntimeError):
        y.backward()
    y.backward(paddle.to_tensor([1.0, 10.0]))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 20.0])


def test_retain_graph():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * x
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 8.0)
    with pytest.raises(RuntimeError):
        y.backward()


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient
    assert y._node is None


def test_grad_api():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = paddle.to_tensor([3.0, 4.0], stop_gradient=False)
    z = (x * y).sum()
    gx, gy = paddle.grad([z], [x, y])
    np.testing.assert_allclose(gx.numpy(), [3, 4])
    np.testing.assert_allclose(gy.numpy(), [1, 2])
    assert x.grad is None  # paddle.grad must not touch .grad


def test_hooks():
    x = paddle.to_tensor([1.0, 1.0], stop_gradient=False)
    seen = []

    def hook(g):
        seen.append(g.numpy().copy())
        return g * 2

    y = x * 3
    y.register_hook(hook)
    y.sum().backward()
    assert len(seen) == 1
    np.testing.assert_allclose(x.grad.numpy(), [6.0, 6.0])


def test_grad_through_getitem():
    x = paddle.to_tensor(np.arange(6, dtype="float32").reshape(2, 3),
                         stop_gradient=False)
    x[1, 2:].sum().backward()
    expected = np.zeros((2, 3), "float32")
    expected[1, 2] = 1
    np.testing.assert_allclose(x.grad.numpy(), expected)


def test_int_index_path_no_crash():
    x = paddle.to_tensor(np.random.randn(5, 3).astype("float32"),
                         stop_gradient=False)
    idx = paddle.to_tensor([0, 2, 4])
    paddle.gather(x, idx).sum().backward()
    assert x.grad.shape == [5, 3]
    np.testing.assert_allclose(x.grad.numpy().sum(), 9.0)


def test_softmax_cross_entropy_style_graph():
    logits_np = np.random.randn(4, 10).astype("float32")
    x = paddle.to_tensor(logits_np, stop_gradient=False)
    p = paddle.exp(x - paddle.logsumexp(x, axis=-1, keepdim=True))
    loss = -paddle.log(p[:, 0]).mean()
    loss.backward()

    def ref(v):
        lp = v - jax.scipy.special.logsumexp(v, axis=-1, keepdims=True)
        return -lp[:, 0].mean()

    g = jax.grad(ref)(logits_np)
    np.testing.assert_allclose(x.grad.numpy(), g, rtol=1e-4, atol=1e-5)


def test_clear_grad():
    x = paddle.to_tensor(1.0, stop_gradient=False)
    (x * 2).backward()
    x.clear_grad()
    assert x.grad is None
