"""Launcher / spawn / elastic tests (reference analogs:
test/legacy_test/test_launch_coverage.py, elastic manager tests)."""
import os
import subprocess
import sys
import textwrap

import pytest

from paddle_tpu.distributed.launch.main import ELASTIC_EXIT_CODE, launch


class TestLaunch:
    def _script(self, tmp_path, body):
        p = tmp_path / "train.py"
        p.write_text(textwrap.dedent(body))
        return str(p)

    def test_single_proc_success(self, tmp_path):
        script = self._script(tmp_path, """
            import os
            rank = os.environ["PADDLE_TRAINER_ID"]
            assert rank == os.environ["PADDLE_LOCAL_RANK"]
            assert os.environ["PADDLE_TRAINERS_NUM"] == "2"
            print("child ok", rank)
        """)
        rc = launch(["--nproc_per_node", "2", "--log_dir",
                     str(tmp_path / "log"), script])
        assert rc == 0
        logs = os.listdir(tmp_path / "log")
        assert "workerlog.0" in logs and "workerlog.1" in logs
        assert "child ok" in (tmp_path / "log" / "workerlog.0").read_text()

    def test_failure_propagates(self, tmp_path):
        script = self._script(tmp_path, "raise SystemExit(7)")
        rc = launch(["--log_dir", str(tmp_path / "log"), script])
        assert rc == 7

    def test_elastic_restart(self, tmp_path):
        # child fails with ELASTIC_EXIT_CODE once, then succeeds (state file)
        marker = tmp_path / "attempt"
        script = self._script(tmp_path, f"""
            import os, sys
            m = {str(marker)!r}
            if not os.path.exists(m):
                open(m, "w").write("1")
                sys.exit({ELASTIC_EXIT_CODE})
            print("recovered")
        """)
        rc = launch(["--elastic_level", "1", "--max_restarts", "2",
                     "--log_dir", str(tmp_path / "log"), script])
        assert rc == 0
        assert "recovered" in (tmp_path / "log" / "workerlog.0").read_text()

    def test_rank_env_across_nodes(self, tmp_path):
        script = self._script(tmp_path, """
            import os
            g = int(os.environ["PADDLE_TRAINER_ID"])
            l = int(os.environ["PADDLE_LOCAL_RANK"])
            assert g == 3 + l, (g, l)   # node_rank 1 × 3 procs → global 3..5
            assert os.environ["PADDLE_NNODES"] == "2"
            assert os.environ["PADDLE_TRAINERS_NUM"] == "6"
        """)
        rc = launch(["--nnodes", "2", "--node_rank", "1",
                     "--nproc_per_node", "3", "--log_dir",
                     str(tmp_path / "log"), script])
        assert rc == 0
        assert "AssertionError" not in (
            tmp_path / "log" / "workerlog.3").read_text()


class TestSpawn:
    def test_spawn_ranks(self, tmp_path):
        # run in a subprocess: mp 'spawn' start method needs an importable fn
        script = tmp_path / "sp.py"
        script.write_text(textwrap.dedent(f"""
            import os, sys
            sys.path.insert(0, {str(os.getcwd())!r})
            from paddle_tpu.distributed.spawn import spawn

            def worker(rank, base):
                path = os.path.join({str(tmp_path)!r}, f"r{{rank}}")
                open(path, "w").write(str(base + rank))

            if __name__ == "__main__":
                spawn(worker, args=(10,), nprocs=2)
        """))
        subprocess.run([sys.executable, str(script)], check=True, timeout=60)
        assert (tmp_path / "r0").read_text() == "10"
        assert (tmp_path / "r1").read_text() == "11"


class TestElastic:
    def test_manager_over_store(self):
        from paddle_tpu import native
        from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                          ElasticStatus)

        if native.lib_path() is None:
            pytest.skip("native lib unavailable")
        store = native.TCPStore(is_master=True)
        m = ElasticManager(store=store, np=2, heartbeat_interval=0.05)
        m.register()
        import time

        time.sleep(0.15)
        assert store.get("elastic/g0/node/0") == b"127.0.0.1"
        # heartbeat is a counter bump (native GET blocks on missing keys,
        # so freshness rides add(key, 0) reads)
        assert store.add("elastic/g0/hbc/0", 0) > 0
        assert m.watch() == ElasticStatus.HOLD
        m.signal_restart()
        assert m.watch() == ElasticStatus.RESTART
        assert m.exit(completed=False) == 101
        store.close()

    def test_disabled_without_store(self):
        from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                          ElasticStatus)

        m = ElasticManager()
        m.register()  # no-op
        assert m.watch() == ElasticStatus.COMPLETED
