"""hapi Model + callbacks + metric tests (reference analogs:
test/legacy_test/test_model.py, test_callbacks.py, test_metrics.py)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.hapi.callbacks import (Callback, EarlyStopping, ProgBarLogger)
from paddle_tpu.hapi.model import Model
from paddle_tpu.io import Dataset
from paddle_tpu.metric import Accuracy, Auc, Metric, Precision, Recall
from paddle_tpu.optimizer import AdamW


class ToyDataset(Dataset):
    def __init__(self, n=32, d=8, classes=4, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, d).astype(np.float32)
        self.y = rng.randint(0, classes, (n, 1)).astype(np.int64)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def make_model():
    net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))
    m = Model(net)
    m.prepare(optimizer=AdamW(learning_rate=1e-2,
                              parameters=net.parameters()),
              loss=nn.CrossEntropyLoss(),
              metrics=Accuracy())
    return m


class TestMetrics:
    def test_accuracy(self):
        acc = Accuracy()
        pred = paddle.to_tensor(
            np.array([[0.9, 0.1], [0.2, 0.8]], np.float32))
        label = paddle.to_tensor(np.array([[0], [0]], np.int64))
        correct = acc.compute(pred, label)
        acc.update(correct)
        assert acc.accumulate() == 0.5
        acc.reset()
        assert acc.accumulate() == 0.0

    def test_accuracy_topk(self):
        acc = Accuracy(topk=(1, 2))
        assert acc.name() == ["acc_top1", "acc_top2"]
        pred = paddle.to_tensor(np.array([[0.5, 0.3, 0.2]], np.float32))
        label = paddle.to_tensor(np.array([[1]], np.int64))
        acc.update(acc.compute(pred, label))
        top1, top2 = acc.accumulate()
        assert top1 == 0.0 and top2 == 1.0

    def test_precision_recall(self):
        p, r = Precision(), Recall()
        preds = np.array([0.9, 0.8, 0.2, 0.6], np.float32)
        labels = np.array([1, 0, 1, 1], np.int32)
        p.update(preds, labels)
        r.update(preds, labels)
        assert abs(p.accumulate() - 2 / 3) < 1e-6
        assert abs(r.accumulate() - 2 / 3) < 1e-6

    def test_functional_accuracy_index_labels(self):
        from paddle_tpu.metric import accuracy

        pred = np.array([[0.1, 0.9], [0.8, 0.2]], np.float32)
        label = np.array([[1], [0]], np.int64)  # [N,1] index convention
        assert float(accuracy(pred, label)) == 1.0

    def test_evaluate_without_loss(self):
        net = nn.Sequential(nn.Linear(8, 4))
        m = Model(net)
        m.prepare(metrics=Accuracy())
        logs = m.evaluate(ToyDataset(n=8), batch_size=8, verbose=0)
        assert "acc" in logs and "loss" not in logs

    def test_auc(self):
        auc = Auc()
        preds = np.array([[0.2, 0.8], [0.7, 0.3], [0.4, 0.6], [0.9, 0.1]],
                         np.float32)
        labels = np.array([[1], [0], [1], [0]], np.int64)
        auc.update(preds, labels)
        assert auc.accumulate() == 1.0  # perfectly separable

    def test_metric_abstract(self):
        with pytest.raises(TypeError):
            Metric()


class TestModel:
    def test_train_batch(self):
        m = make_model()
        x = np.random.randn(4, 8).astype(np.float32)
        y = np.random.randint(0, 4, (4, 1))
        out = m.train_batch([x], [y])
        loss, metrics = out
        assert np.isfinite(loss[0])

    def test_fit_reduces_loss_and_evaluates(self, capsys):
        m = make_model()
        ds = ToyDataset()
        m.fit(ds, ds, batch_size=8, epochs=2, verbose=0)
        res = m.evaluate(ds, batch_size=8, verbose=0)
        assert "acc" in res and "loss" in res

    def test_predict(self):
        class XOnly(Dataset):
            def __init__(self):
                self.x = np.random.randn(16, 8).astype(np.float32)

            def __getitem__(self, i):
                return self.x[i]

            def __len__(self):
                return 16

        m = make_model()
        outs = m.predict(XOnly(), batch_size=8, stack_outputs=True)
        assert outs[0].shape == (16, 4)

    def test_save_load_roundtrip(self, tmp_path):
        m = make_model()
        path = str(tmp_path / "ckpt" / "model")
        m.save(path)
        w0 = m.network[0].weight.numpy().copy()
        # poison then reload
        m.network[0].weight.set_value(np.zeros_like(w0))
        m.load(path)
        np.testing.assert_array_equal(m.network[0].weight.numpy(), w0)

    def test_parameters_passthrough(self):
        m = make_model()
        assert len(list(m.parameters())) == 4

    def test_prepare_validates_loss(self):
        with pytest.raises(TypeError):
            Model(nn.Linear(2, 2)).prepare(loss="nope")

    def test_prepare_validates_metric(self):
        with pytest.raises(TypeError):
            Model(nn.Linear(2, 2)).prepare(metrics="nope")


class TestCallbacks:
    def test_early_stopping_stops(self):
        m = make_model()
        es = EarlyStopping(monitor="loss", patience=1, verbose=0, mode="min")
        es.set_model(m)
        es.set_params({})
        es.on_train_begin()
        for loss in (1.0, 0.5, 0.6, 0.7):  # improves, then worsens twice
            es.on_eval_end({"loss": loss})
        assert m.stop_training
        assert es.best_value == 0.5

    def test_early_stopping_in_fit(self):
        # structural integration: fit wires eval logs into the callback
        m = make_model()
        es = EarlyStopping(monitor="loss", patience=0, verbose=0,
                           mode="max")  # "max" on loss → stops immediately
        ds = ToyDataset(n=8)
        m.fit(ds, ds, batch_size=8, epochs=10, verbose=0, callbacks=[es])
        assert m.stop_training

    def test_progbar_logs(self, capsys):
        m = make_model()
        ds = ToyDataset(n=8)
        m.fit(ds, batch_size=4, epochs=1, verbose=2, log_freq=1)
        out = capsys.readouterr().out
        assert "Epoch 1/1" in out and "loss" in out

    def test_model_checkpoint(self, tmp_path):
        m = make_model()
        ds = ToyDataset(n=8)
        m.fit(ds, batch_size=8, epochs=1, verbose=0,
              save_dir=str(tmp_path))
        assert (tmp_path / "final.pdparams").exists()
        assert (tmp_path / "0.pdparams").exists()


class TestSummaryFlops:
    def test_summary_counts(self, capsys):
        net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))
        res = paddle.summary(net, (1, 8))
        assert res["total_params"] == 8 * 32 + 32 + 32 * 4 + 4
        assert "Linear" in capsys.readouterr().out

    def test_flops_linear(self):
        net = nn.Sequential(nn.Linear(8, 32))
        n = paddle.flops(net, (1, 8))
        assert n == 2 * 32 * 8
