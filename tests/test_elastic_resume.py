"""Elastic END-TO-END loop (VERDICT r3 #5): train -> periodic sharded
checkpoints -> kill a worker mid-run -> launcher relaunches
(ELASTIC_EXIT_CODE path) -> restore -> the LOSS SEQUENCE continues within
tolerance of an unkilled run.

Reference: fleet/elastic/manager.py:120 watch loop + the fleet elastic test
cases, which relaunch real training. The prior tests proved detection and
re-admission separately; this one closes the loop with actual 2-process
data-parallel training (jax.distributed over gloo), orbax sharded
checkpoints, and loss continuity across the kill.
"""
import os
import socket

import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

native = pytest.importorskip("paddle_tpu.native")
try:
    _probe = native.TCPStoreServer(0)
    _probe.stop()
except Exception:  # pragma: no cover
    pytest.skip("native TCPStore unavailable", allow_module_level=True)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


TOTAL_STEPS = 16
CKPT_EVERY = 4
DIE_AT = 10          # gen-0 rank 1 dies at this step boundary (> last ckpt 8)
LR = 0.1


def reference_losses():
    """The unkilled run, replicated in plain numpy: full-batch GD on the
    same data/model/lr the workers use."""
    rngd = np.random.RandomState(0)
    X = rngd.randn(8, 4).astype(np.float32)
    Y = (X @ np.array([1.0, -2.0, 3.0, 0.5], np.float32))[:, None]
    w = np.zeros((4, 1), np.float32)
    losses = []
    for _ in range(TOTAL_STEPS):
        err = X @ w - Y
        losses.append(float(np.mean(err ** 2)))
        w = w - LR * (2.0 / X.shape[0]) * (X.T @ err)
    return losses


ELASTIC_TRAIN_WORKER = textwrap.dedent("""
    import os, sys, time
    sys.path.insert(0, {repo!r})

    rank = int(os.environ["PADDLE_TRAINER_ID"])
    master_port = int(os.environ["MASTER_PORT"])
    flag = {flag!r}
    results = {results!r}
    ckdir = {ckdir!r}
    gen = 1 if os.path.exists(flag) else 0

    # the launch CLI env is single-node; promote the two local procs into
    # a 2-process jax.distributed world. Coordinator port is generation-
    # scoped so gen-1's coordinator never collides with gen-0's socket.
    coord_port = master_port + 1000 + 7 * gen
    os.environ["PADDLE_TRAINER_ENDPOINTS"] = (
        f"127.0.0.1:{{coord_port}},127.0.0.1:{{coord_port}}")
    os.environ["PADDLE_NNODES"] = "2"
    os.environ["PADDLE_TRAINERS_NUM"] = "2"
    # the pytest env forces 8 virtual CPU devices; this worker must be ONE
    # device so the 2-process world has exactly 2
    os.environ["XLA_FLAGS"] = " ".join(
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f)

    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.parallel import get_store
    from paddle_tpu.distributed.topology import get_mesh
    from paddle_tpu.distributed.checkpoint import (save_state_dict,
                                                   load_state_dict)
    from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                      ElasticStatus)

    env = dist.init_parallel_env(dp=2)
    mesh = get_mesh()

    em = ElasticManager(store=get_store(), np=2, heartbeat_interval=0.2,
                        dead_timeout=1.2, generation=gen)
    em.rank = rank
    em.register()

    TOTAL, CKPT_EVERY, DIE_AT, LR = {total}, {ckpt_every}, {die_at}, {lr}
    rngd = np.random.RandomState(0)
    X = rngd.randn(8, 4).astype(np.float32)
    Y = (X @ np.array([1.0, -2.0, 3.0, 0.5], np.float32))[:, None]
    sh = NamedSharding(mesh, P("dp"))
    # each process contributes its half of the global batch (true dp)
    lo, hi = (0, 4) if rank == 0 else (4, 8)
    Xg = jax.make_array_from_process_local_data(sh, X[lo:hi], (8, 4))
    Yg = jax.make_array_from_process_local_data(sh, Y[lo:hi], (8, 1))
    rep = NamedSharding(mesh, P())

    @jax.jit
    def train_step(w, X, Y):
        err = X @ w - Y
        loss = jnp.mean(err ** 2)          # global mean: psum over dp
        g = jax.grad(lambda w: jnp.mean((X @ w - Y) ** 2))(w)
        return w - LR * g, loss

    start = 0
    w = jax.device_put(jnp.zeros((4, 1), jnp.float32), rep)
    latest = os.path.join(ckdir, "latest.txt")
    if gen == 1:
        assert os.path.exists(latest), "gen-1 must find a checkpoint"
        start = int(open(latest).read().strip())
        sd = {{"w": w}}
        load_state_dict(os.path.join(ckdir, f"step{{start}}"), sd)
        w = sd["w"]._value if hasattr(sd["w"], "_value") else sd["w"]

    for k in range(start, TOTAL):
        if gen == 0 and k == DIE_AT:
            if rank == 1:
                open(flag, "w").write("died")
                os._exit(1)            # simulated hardware failure
            # survivor: stop collective work, watch for the dead peer
            deadline = time.time() + 20
            while time.time() < deadline:
                if em.watch() == ElasticStatus.RESTART:
                    sys.exit(em.exit(completed=False))  # -> 101
                time.sleep(0.1)
            sys.exit(3)                # detection failed
        w, loss = train_step(w, Xg, Yg)
        if rank == 0:
            with open(results, "a") as f:
                f.write(f"{{gen}}:{{k}}:{{float(loss):.8f}}\\n")
        if (k + 1) % CKPT_EVERY == 0 and k + 1 < TOTAL:
            save_state_dict({{"w": w}}, os.path.join(ckdir,
                                                     f"step{{k + 1}}"))
            if rank == 0:
                with open(latest, "w") as f:
                    f.write(str(k + 1))
        em.watch()                     # heartbeat cadence rides the loop

    sys.exit(em.exit(completed=True))
""")


@pytest.mark.slow
class TestElasticTrainResume:
    def test_loss_continues_across_kill_and_relaunch(self, tmp_path):
        from paddle_tpu.distributed.launch.main import launch

        flag = str(tmp_path / "died.flag")
        results = str(tmp_path / "losses.txt")
        ckdir = str(tmp_path / "ckpt")
        os.makedirs(ckdir, exist_ok=True)
        script = tmp_path / "worker.py"
        script.write_text(ELASTIC_TRAIN_WORKER.format(
            repo=REPO, flag=flag, results=results, ckdir=ckdir,
            total=TOTAL_STEPS, ckpt_every=CKPT_EVERY, die_at=DIE_AT,
            lr=LR))
        port = _free_port()
        old_master = os.environ.get("PADDLE_MASTER")
        os.environ["PADDLE_MASTER"] = f"127.0.0.1:{port}"
        try:
            rc = launch(["--nproc_per_node", "2", "--elastic_level", "1",
                         "--max_restarts", "2", "--log_dir",
                         str(tmp_path / "log"), str(script)])
        finally:
            if old_master is None:
                os.environ.pop("PADDLE_MASTER", None)
            else:
                os.environ["PADDLE_MASTER"] = old_master
        assert rc == 0, rc

        ref = reference_losses()
        lines = open(results).read().strip().splitlines()
        got = [(int(g), int(k), float(v)) for g, k, v in
               (ln.split(":") for ln in lines)]
        gen0 = {k: v for g, k, v in got if g == 0}
        gen1 = {k: v for g, k, v in got if g == 1}
        # gen 0 trained up to the kill, checkpointing through step 8
        assert sorted(gen0) == list(range(0, DIE_AT)), sorted(gen0)
        # gen 1 resumed from the LAST CHECKPOINT (step 8), not from zero,
        # and finished the schedule
        last_ckpt = (DIE_AT // CKPT_EVERY) * CKPT_EVERY
        assert sorted(gen1) == list(range(last_ckpt, TOTAL_STEPS)), \
            sorted(gen1)
        # loss continuity: every recorded step matches the unkilled run
        for k, v in {**gen0, **gen1}.items():
            assert abs(v - ref[k]) < 1e-4, (k, v, ref[k])
