"""Multiprocess DataLoader tests (VERDICT r2 #7).

Reference contract (_DataLoaderIterMultiProcess dataloader_iter.py:358):
worker PROCESSES fetch+collate in parallel, results return in sampler
order, worker exceptions propagate, and Python-heavy (GIL-bound)
transforms actually speed up — the thread pool cannot deliver that.
"""
import os
import time

import numpy as np
import pytest

from paddle_tpu.io import DataLoader, Dataset
# the retry wrapper moved to paddle_tpu.testing so every wall-clock-
# sensitive suite (mp dataloader, serving watchdog timing, the router
# chaos tests) shares one load-flakiness policy
from paddle_tpu.testing import retry_under_load


class RangeDs(Dataset):
    def __init__(self, n=64):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.full((4,), i, dtype=np.float32), np.int64(i)


class GilBoundDs(Dataset):
    """Pure-python per-item work: holds the GIL the whole time."""

    def __init__(self, n=24, iters=1_200_000):
        self.n, self.iters = n, iters

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        acc = 0
        for j in range(self.iters):
            acc += j & 7
        return np.float32(acc + i)


class BadDs(Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i == 5:
            raise ValueError("poison item")
        return np.float32(i)


class PidDs(Dataset):
    def __len__(self):
        return 16

    def __getitem__(self, i):
        return np.int64(os.getpid())


class TestProcessWorkers:
    @retry_under_load
    def test_ordered_and_complete(self):
        loader = DataLoader(RangeDs(64), batch_size=8, num_workers=4)
        seen = []
        for xb, yb in loader:
            assert xb.shape == [8, 4]
            seen.extend(np.asarray(yb.value).tolist())
        assert seen == list(range(64))

    @retry_under_load
    def test_really_multiple_processes(self):
        loader = DataLoader(PidDs(), batch_size=2, num_workers=4)
        pids = set()
        for b in loader:
            pids.update(np.asarray(b.value).tolist())
        assert os.getpid() not in pids, "work ran in the parent"
        assert len(pids) >= 2, pids

    @retry_under_load
    def test_worker_exception_propagates(self):
        loader = DataLoader(BadDs(), batch_size=4, num_workers=2)
        with pytest.raises(RuntimeError, match="poison item"):
            list(loader)

    @retry_under_load
    def test_thread_fallback_flag(self):
        loader = DataLoader(RangeDs(32), batch_size=8, num_workers=2,
                            use_shared_memory=False)
        seen = []
        for xb, yb in loader:
            seen.extend(np.asarray(yb.value).tolist())
        assert seen == list(range(32))

    @retry_under_load
    def test_worker_init_fn_runs_in_worker(self):
        def init(wid):
            os.environ["DL_WORKER_MARK"] = str(wid)

        class MarkDs(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                return np.int64("DL_WORKER_MARK" in os.environ)

        loader = DataLoader(MarkDs(), batch_size=2, num_workers=2,
                            worker_init_fn=init)
        vals = [v for b in loader for v in np.asarray(b.value).tolist()]
        assert all(v == 1 for v in vals)
        assert "DL_WORKER_MARK" not in os.environ  # only in children

    @pytest.mark.slow
    @pytest.mark.skipif((os.cpu_count() or 1) < 2,
                        reason="speedup needs >=2 cores; this container "
                               "exposes 1 — process-parallelism itself is "
                               "asserted by test_really_multiple_processes")
    def test_gil_bound_speedup_vs_threads(self):
        """The whole point of process workers (>1.5x at num_workers=4 over
        the thread pool on CPU-bound transforms, multicore hosts)."""
        ds = GilBoundDs()

        def run(**kw):
            loader = DataLoader(ds, batch_size=2, num_workers=4, **kw)
            t0 = time.perf_counter()
            n = sum(1 for _ in loader)
            dt = time.perf_counter() - t0
            assert n == 12
            return dt

        t_threads = run(use_shared_memory=False)
        t_procs = run(use_shared_memory=True)
        assert t_procs * 1.5 < t_threads, (t_procs, t_threads)
