"""Tests for ASP, RPC, fleet fs, and the cost model.

Reference analogs: test/asp/test_asp_pruning_dynamic.py,
test/rpc/test_rpc_basic.py, test/collective/fleet/test_fs.py,
test/legacy_test/test_cost_model.py.
"""
import os
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt


class TestAspUtils:
    def test_get_mask_1d(self):
        from paddle_tpu.incubate.asp import check_mask_1d, get_mask_1d

        rng = np.random.RandomState(0)
        mat = rng.randn(8, 16)
        mask = get_mask_1d(mat, 2, 4)
        assert mask.shape == mat.shape
        assert check_mask_1d(mat * mask, 2, 4)
        # keeps exactly the 2 largest |.| of each group of 4
        groups = (np.abs(mat) * mask).reshape(-1, 4)
        raw = np.abs(mat).reshape(-1, 4)
        for g, r in zip(groups, raw):
            np.testing.assert_allclose(sorted(g[g > 0]), sorted(r)[-2:])

    def test_get_mask_2d_variants(self):
        from paddle_tpu.incubate.asp import (check_mask_2d,
                                             get_mask_2d_best,
                                             get_mask_2d_greedy)

        rng = np.random.RandomState(1)
        mat = rng.randn(8, 8)
        for fn in (get_mask_2d_greedy, get_mask_2d_best):
            mask = fn(mat, 2, 4)
            assert check_mask_2d(mat * mask, 2, 4), fn.__name__
        # best >= greedy in kept magnitude
        g = np.abs(mat * get_mask_2d_greedy(mat, 2, 4)).sum()
        b = np.abs(mat * get_mask_2d_best(mat, 2, 4)).sum()
        assert b >= g - 1e-9

    def test_calculate_density(self):
        from paddle_tpu.incubate.asp import calculate_density

        x = np.zeros((4, 4))
        x[0, 0] = 1.0
        assert calculate_density(x) == 1 / 16

    def test_nonmultiple_shapes_pad(self):
        from paddle_tpu.incubate.asp import check_mask_1d, get_mask_1d

        mat = np.random.RandomState(3).randn(3, 10)
        mask = get_mask_1d(mat, 2, 4)
        assert mask.shape == mat.shape
        assert check_mask_1d(mat * mask, 2, 4)


class TestAspModel:
    def test_prune_and_training_keeps_sparsity(self):
        from paddle_tpu.incubate import asp

        m = nn.Linear(16, 8)
        masks = asp.prune_model(m, n=2, m=4)
        assert "weight" in masks and "bias" not in masks
        w = np.asarray(m.weight.numpy())
        assert asp.check_sparsity(w, n=2, m=4)
        o = asp.decorate(opt.SGD(learning_rate=0.1,
                                 parameters=m.parameters()), m)
        rng = np.random.RandomState(0)
        for _ in range(3):
            x = rng.randn(4, 16).astype(np.float32)
            y = rng.randn(4, 8).astype(np.float32)
            loss = paddle.mean((m(paddle.to_tensor(x))
                                - paddle.to_tensor(y)) ** 2)
            loss.backward()
            o.step()
            o.clear_grad()
        w2 = np.asarray(m.weight.numpy())
        assert not np.allclose(w, w2)          # trained
        assert asp.check_sparsity(w2, n=2, m=4)  # still 2:4

    def test_excluded_layers(self):
        from paddle_tpu.incubate import asp

        m = nn.Linear(8, 8)
        asp.set_excluded_layers(m, ["weight"])
        try:
            masks = asp.prune_model(m)
            assert masks == {}
        finally:
            asp.reset_excluded_layers(m)

    def test_decorate_requires_model(self):
        from paddle_tpu.incubate import asp

        m = nn.Linear(4, 4)
        with pytest.raises(ValueError, match="model"):
            asp.decorate(opt.SGD(learning_rate=0.1,
                                 parameters=m.parameters()))


def _double(x):
    return x * 2


def _boom():
    raise ValueError("remote failure")


class TestRpc:
    def test_single_worker_rpc_roundtrip(self):
        from paddle_tpu.distributed import rpc

        rpc.init_rpc("worker0", rank=0, world_size=1)
        try:
            info = rpc.get_current_worker_info()
            assert info.name == "worker0" and info.rank == 0
            assert rpc.get_worker_info("worker0") == info
            assert rpc.get_all_worker_infos() == [info]
            out = rpc.rpc_sync("worker0", _double, args=(21,))
            assert out == 42
            fut = rpc.rpc_async("worker0", _double, args=(5,))
            assert fut.wait() == 10
        finally:
            rpc.shutdown()

    def test_remote_exception_propagates(self):
        from paddle_tpu.distributed import rpc

        rpc.init_rpc("w", rank=0, world_size=1)
        try:
            with pytest.raises(ValueError, match="remote failure"):
                rpc.rpc_sync("w", _boom)
        finally:
            rpc.shutdown()

    @pytest.mark.slow
    def test_two_process_rpc(self, tmp_path):
        import socket
        import subprocess
        import sys
        import textwrap

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = textwrap.dedent(f"""
            import sys
            sys.path.insert(0, {repo!r})
            rank = int(sys.argv[1])
            from paddle_tpu.distributed import rpc
            rpc.init_rpc(f"worker{{rank}}", rank=rank, world_size=2,
                         master_endpoint="127.0.0.1:{port}")
            import operator
            if rank == 0:
                out = rpc.rpc_sync("worker1", operator.add, args=(2, 3))
                assert out == 5, out
                print("RPC_OK", out)
            rpc.shutdown()
        """)
        p = tmp_path / "w.py"
        p.write_text(script)
        procs = [subprocess.Popen([sys.executable, str(p), str(r)],
                                  stdout=subprocess.PIPE,
                                  stderr=subprocess.PIPE, text=True)
                 for r in range(2)]
        outs = [pr.communicate(timeout=120) for pr in procs]
        for pr, (out, err) in zip(procs, outs):
            assert pr.returncode == 0, err[-1500:]
        assert "RPC_OK 5" in outs[0][0]


class TestFs:
    def test_localfs_surface(self, tmp_path):
        from paddle_tpu.distributed.fleet.utils import LocalFS

        fs = LocalFS()
        d = str(tmp_path / "dir")
        fs.mkdirs(d)
        assert fs.is_dir(d) and fs.is_exist(d)
        f = os.path.join(d, "a.txt")
        fs.touch(f)
        assert fs.is_file(f)
        with open(f, "w") as fh:
            fh.write("hello")
        assert fs.cat(f) == "hello"
        dirs, files = fs.ls_dir(d)
        assert files == ["a.txt"] and dirs == []
        f2 = os.path.join(d, "b.txt")
        fs.mv(f, f2)
        assert fs.is_file(f2) and not fs.is_exist(f)
        assert not fs.need_upload_download()
        fs.delete(d)
        assert not fs.is_exist(d)

    def test_hdfs_without_hadoop_raises(self):
        from paddle_tpu.distributed.fleet.utils import HDFSClient

        client = HDFSClient(hadoop_home="/nonexistent")
        with pytest.raises(RuntimeError, match="hadoop"):
            client.mkdirs("/tmp/x")


class TestCostModel:
    def test_profile_measure_static_program(self):
        import paddle_tpu.static as static
        from paddle_tpu.cost_model import CostModel

        paddle.enable_static()
        try:
            main = static.Program()
            startup = static.Program()
            with static.program_guard(main, startup):
                x = static.data(name="X", shape=[4, 8], dtype="float32")
                w = paddle.create_parameter([8, 2], "float32")
                out = paddle.matmul(x, w)
                loss = paddle.mean(out)
            cm = CostModel()
            rec = cm.profile_measure(
                startup, main, device="cpu", fetch_list=[loss],
                feed={"X": np.random.rand(4, 8).astype(np.float32)})
            assert rec["time_ms"] > 0
            assert "flops" in rec and rec["flops"] >= 0
        finally:
            paddle.disable_static()


def _loss(m, x, y):
    return paddle.mean((m(paddle.to_tensor(x)) - paddle.to_tensor(y)) ** 2)


def _data(rng, n=8):
    return (rng.randn(n, 4).astype(np.float32),
            rng.randn(n, 3).astype(np.float32))


class TestDistributedFusedLamb:
    def test_matches_lamb_single_device(self):
        from paddle_tpu.incubate.optimizer import DistributedFusedLamb

        rng = np.random.RandomState(12)
        x, y = _data(rng, 16)
        m1, m2 = nn.Linear(4, 3), nn.Linear(4, 3)
        m2.weight.set_value(m1.weight.value)
        m2.bias.set_value(m1.bias.value)
        lamb = opt.Lamb(learning_rate=0.01, parameters=m1.parameters())
        dfl = DistributedFusedLamb(learning_rate=0.01,
                                   parameters=m2.parameters())
        for _ in range(3):
            _loss(m1, x, y).backward()
            lamb.step()
            lamb.clear_grad()
            _loss(m2, x, y).backward()
            dfl.step()
            dfl.clear_grad()
        np.testing.assert_allclose(np.asarray(m1.weight.numpy()),
                                   np.asarray(m2.weight.numpy()),
                                   rtol=1e-5, atol=1e-6)

    def test_gradient_accumulation(self):
        from paddle_tpu.incubate.optimizer import DistributedFusedLamb

        rng = np.random.RandomState(13)
        m = nn.Linear(4, 3)
        dfl = DistributedFusedLamb(learning_rate=0.01,
                                   parameters=m.parameters(),
                                   gradient_accumulation_steps=2)
        w0 = np.asarray(m.weight.numpy()).copy()
        x, y = _data(rng)
        _loss(m, x, y).backward()
        dfl.step()
        dfl.clear_grad()
        np.testing.assert_array_equal(np.asarray(m.weight.numpy()), w0)
        _loss(m, x, y).backward()
        dfl.step()
        assert not np.allclose(np.asarray(m.weight.numpy()), w0)

    def test_sharded_moments_on_mesh(self):
        from paddle_tpu.distributed.topology import build_mesh, set_mesh
        from paddle_tpu.incubate.optimizer import DistributedFusedLamb

        mesh = build_mesh(dp=4, sharding=2)
        set_mesh(mesh)
        try:
            rng = np.random.RandomState(14)
            m = nn.Linear(8, 8)
            dfl = DistributedFusedLamb(learning_rate=0.01,
                                       parameters=m.parameters())
            x = rng.randn(4, 8).astype(np.float32)
            y = rng.randn(4, 8).astype(np.float32)
            paddle.mean((m(paddle.to_tensor(x))
                         - paddle.to_tensor(y)) ** 2).backward()
            dfl.step()
            mom = dfl._accumulators["moment1"]
            # check the SPEC, not the repr — the mesh repr always names the
            # 'sharding' axis even for replicated placements
            sharded = [
                v for v in mom.values()
                if any("sharding" in str(ax)
                       for ax in (getattr(getattr(v, "sharding", None),
                                          "spec", None) or ()))]
            assert sharded, "at least the weight moment should shard"
        finally:
            set_mesh(None)


class TestWeightOnlyLinear:
    def test_quant_dequant_roundtrip_error_bounded(self):
        from paddle_tpu.incubate.nn.functional import (weight_dequantize,
                                                       weight_quantize)

        rng = np.random.RandomState(20)
        w = rng.randn(64, 32).astype(np.float32)
        qw, scale = weight_quantize(paddle.to_tensor(w))
        assert np.asarray(qw.numpy()).dtype == np.int8
        back = np.asarray(weight_dequantize(qw, scale).numpy())
        # per-channel int8: max error bounded by scale/2 per channel
        err = np.abs(back - w)
        bound = np.asarray(scale.numpy())[None, :] * 0.5 + 1e-6
        assert np.all(err <= bound)

    def test_weight_only_linear_matches_fp(self):
        from paddle_tpu.incubate.nn.functional import (weight_only_linear,
                                                       weight_quantize)

        rng = np.random.RandomState(21)
        x = rng.randn(4, 64).astype(np.float32)
        w = rng.randn(64, 32).astype(np.float32)
        b = rng.randn(32).astype(np.float32)
        qw, scale = weight_quantize(paddle.to_tensor(w))
        out = weight_only_linear(paddle.to_tensor(x), qw,
                                 bias=paddle.to_tensor(b),
                                 weight_scale=scale)
        ref = x @ w + b
        np.testing.assert_allclose(np.asarray(out.numpy()), ref,
                                   rtol=0.05, atol=0.05 * np.abs(ref).max())

    def test_int4_grid(self):
        from paddle_tpu.incubate.nn.functional import weight_quantize

        rng = np.random.RandomState(22)
        w = rng.randn(16, 8).astype(np.float32)
        qw, scale = weight_quantize(paddle.to_tensor(w),
                                    algo="weight_only_int4")
        q = np.asarray(qw.numpy())
        assert q.min() >= -7 and q.max() <= 7

    def test_grad_flows_to_activation(self):
        from paddle_tpu.incubate.nn.functional import (weight_only_linear,
                                                       weight_quantize)

        rng = np.random.RandomState(23)
        x = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
        x.stop_gradient = False
        qw, scale = weight_quantize(
            paddle.to_tensor(rng.randn(8, 4).astype(np.float32)))
        out = weight_only_linear(x, qw, weight_scale=scale)
        paddle.sum(out).backward()
        assert x.grad is not None
        assert np.all(np.isfinite(np.asarray(x.grad.numpy())))
