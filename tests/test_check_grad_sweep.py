"""Systematic check_grad sweep over the public tensor-op surface
(VERDICT r4 #6 / Weak #7).

Reference analog: test/legacy_test/eager_op_test.py:2377 runs numeric
finite-difference check_grad per op across ~1,312 op-test files, with
test/white_list/ for the documented exceptions.  Here the same contract
is ONE sweep: every public callable on ``paddle_tpu`` must be

- AUTO      — grad-checked with generic float probes (unary/binary),
- SPECIAL   — grad-checked with op-specific inputs (domain constraints,
              index/shape arguments, factorization inputs), or
- WHITELIST — explicitly excluded, with a reason (non-differentiable,
              random, creation, state/config, covered elsewhere).

``test_surface_fully_classified`` fails when a NEW public op appears in
none of the three sets — adding an op forces adding its grad check (or
a reasoned exclusion), which is how the reference keeps per-op grad
coverage from rotting.  The sweep found and fixed real bugs on landing:
diag/diagflat/qr/svd/pinv/eigh/corrcoef/cond returned untaped Tensors
(silently dropped gradients).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_grad

RNG = np.random.RandomState(7)
X = (RNG.rand(3, 4).astype(np.float32) * 0.5 + 0.3)     # [0.3, 0.8]
Y = (RNG.rand(3, 4).astype(np.float32) * 0.5 + 0.9)     # [0.9, 1.4], != X
V4 = RNG.rand(4).astype(np.float32) + 0.5
A34 = RNG.randn(3, 4).astype(np.float32)
B45 = RNG.randn(4, 5).astype(np.float32)
SQ = RNG.randn(4, 4).astype(np.float32)
SPD = (SQ @ SQ.T + 4 * np.eye(4)).astype(np.float32)

# ---------------------------------------------------------------------------
# AUTO: generic probes suffice
# ---------------------------------------------------------------------------

AUTO_UNARY = [
    "abs", "absolute", "acos", "add_n", "amax", "amin", "angle", "as_real",
    "asin", "asinh", "assign", "atan", "atanh", "ceil", "clip", "clone",
    "concat", "conj", "corrcoef", "cos", "cosh", "cov", "cummax", "cummin",
    "cumsum", "cumulative_trapezoid", "deg2rad", "diag", "diagflat",
    "diagonal", "diff", "digamma", "erf", "erfinv", "exp", "expm1",
    "flatten", "floor", "frac", "i0", "i0e", "i1", "i1e", "imag", "lgamma",
    "log", "log10", "log1p", "log2", "logcumsumexp", "logit", "logsumexp",
    "max", "mean", "min", "nan_to_num", "nanmean",
    "nansum", "neg", "negative", "norm", "prod", "rad2deg", "real",
    "reciprocal", "rot90", "round", "rsqrt", "scale", "sgn", "sigmoid",
    "sign", "sin", "sinh", "sort", "sqrt", "square", "squeeze", "stack",
    "stanh", "std", "sum", "t", "tan", "tanh", "trace", "transpose",
    "trapezoid", "tril", "triu", "trunc", "var", "increment",
]
AUTO_BINARY = [
    "add", "atan2", "cdist", "copysign", "cross", "dist", "divide",
    "divide_no_nan", "dot", "fmax", "fmin", "heaviside", "hypot", "inner",
    "kron", "logaddexp", "maximum", "minimum", "mod", "multiply", "outer",
    "pow", "remainder", "subtract", "tensordot", "floor_divide",
    "floor_mod",
]

# ---------------------------------------------------------------------------
# SPECIAL: differentiable, but needs op-specific inputs / args
# ---------------------------------------------------------------------------

_idx = np.array([0, 2, 1], np.int64)
_mask = np.array([[True, False, True, False]] * 3)
_SPECIAL = {
    "acosh": (paddle.acosh, [X + 1.5], {}),
    # even-count medians interpolate between two order stats; finite
    # differences are only valid when the probe cannot reorder elements
    # — odd count + gaps >> 2*eps
    "median": (paddle.median,
               [(np.arange(15, dtype=np.float32).reshape(3, 5) * 0.05
                 + 0.1)[RNG.permutation(3)][:, RNG.permutation(5)]], {}),
    "addmm": (paddle.addmm, [RNG.randn(3, 5).astype(np.float32), A34, B45],
              {}),
    "bmm": (paddle.bmm, [RNG.randn(2, 3, 4).astype(np.float32),
                         RNG.randn(2, 4, 5).astype(np.float32)], {}),
    "matmul": (paddle.matmul, [A34, B45], {}),
    "mm": (paddle.mm, [A34, B45], {}),
    "mv": (paddle.mv, [A34, RNG.randn(4).astype(np.float32)], {}),
    "multi_dot": (lambda a, b, c: paddle.multi_dot([a, b, c]),
                  [A34, B45, RNG.randn(5, 2).astype(np.float32)], {}),
    "einsum": (lambda a, b: paddle.einsum("ij,jk->ik", a, b), [A34, B45],
               {}),
    "matrix_power": (lambda t: paddle.matrix_power(t, 2), [SQ], {}),
    "cholesky": (paddle.cholesky, [SPD], {}),
    "cholesky_solve": (paddle.cholesky_solve,
                       [RNG.randn(4, 2).astype(np.float32),
                        np.linalg.cholesky(SPD).astype(np.float32)], {}),
    "triangular_solve": (paddle.triangular_solve,
                         [np.triu(SPD).astype(np.float32),
                          RNG.randn(4, 2).astype(np.float32)], {}),
    "solve": (paddle.solve, [SPD, RNG.randn(4, 2).astype(np.float32)], {}),
    "det": (paddle.det, [SPD * 0.4], {}),
    "slogdet": (paddle.slogdet, [SPD], {}),
    "inv": (paddle.inv, [SPD], {}),
    "inverse": (paddle.inverse, [SPD], {}),
    "pinv": (paddle.pinv, [SPD], {}),
    "qr": (lambda t: paddle.qr(t)[1],
           [RNG.randn(4, 3).astype(np.float32)], {}),  # VJP needs m >= n
    "svd": (lambda t: paddle.svd(t)[1], [A34], {}),
    "eigh": (lambda t: paddle.eigh(t)[0], [SPD], {}),
    "eigvalsh": (paddle.eigvalsh, [SPD], {}),
    "cond": (paddle.cond, [SPD], {}),
    "cumprod": (paddle.cumprod, [X], {"dim": 0}),
    "vander": (paddle.vander, [V4], {}),
    "polygamma": (lambda t: paddle.polygamma(t, 1), [X + 1.0], {}),
    "ldexp": (lambda t: paddle.ldexp(t, paddle.to_tensor(
        np.full((3, 4), 2, np.int32))), [X], {}),
    "lerp": (paddle.lerp, [X, Y, np.float32(0.3)], {}),
    "quantile": (lambda t: paddle.quantile(t, 0.5, axis=1), [X], {}),
    "nanquantile": (lambda t: paddle.nanquantile(t, 0.5, axis=1), [X], {}),
    "kthvalue": (lambda t: paddle.kthvalue(t, 2, axis=1)[0], [X], {}),
    "topk": (lambda t: paddle.topk(t, 2, axis=1)[0], [X], {}),
    "renorm": (paddle.renorm, [X * 0.01], {"p": 2.0, "axis": 0,
                                           "max_norm": 1.0}),
    # shape / layout movers (linear: grads are scatters of the cotangent)
    "reshape": (lambda t: paddle.reshape(t, [4, 3]), [X], {}),
    "expand": (lambda t: paddle.expand(t, [2, 3, 4]), [X], {}),
    "broadcast_to": (lambda t: paddle.broadcast_to(t, [2, 3, 4]), [X], {}),
    "expand_as": (lambda t: paddle.expand_as(
        t, paddle.to_tensor(np.zeros((3, 4), np.float32))), [X[0]], {}),
    "tile": (lambda t: paddle.tile(t, [2, 1]), [X], {}),
    "repeat_interleave": (lambda t: paddle.repeat_interleave(t, 2, axis=0),
                          [X], {}),
    "unsqueeze": (lambda t: paddle.unsqueeze(t, 1), [X], {}),
    "unflatten": (lambda t: paddle.unflatten(t, 1, [2, 2]), [X], {}),
    "unfold": (lambda t: paddle.unfold(t, 1, 2, 1), [X], {}),
    "swapaxes": (lambda t: paddle.swapaxes(t, 0, 1), [X], {}),
    "moveaxis": (lambda t: paddle.moveaxis(t, 0, 1), [X], {}),
    "flip": (lambda t: paddle.flip(t, axis=0), [X], {}),
    "reverse": (lambda t: paddle.reverse(t, axis=[0]), [X], {}),
    "roll": (lambda t: paddle.roll(t, 1, axis=0), [X], {}),
    "pad": (lambda t: paddle.pad(t, [1, 1, 0, 2]), [X], {}),
    "crop": (lambda t: paddle.crop(t, shape=[2, 2], offsets=[0, 1]), [X],
             {}),
    "slice": (lambda t: paddle.slice(t, axes=[0, 1], starts=[0, 1],
                                     ends=[2, 3]), [X], {}),
    "strided_slice": (lambda t: paddle.strided_slice(
        t, axes=[1], starts=[0], ends=[4], strides=[2]), [X], {}),
    "split": (lambda t: paddle.split(t, 2, axis=1)[0], [X], {}),
    "chunk": (lambda t: paddle.chunk(t, 2, axis=1)[0], [X], {}),
    "tensor_split": (lambda t: paddle.tensor_split(t, 2, axis=1)[0], [X],
                     {}),
    "vsplit": (lambda t: paddle.vsplit(t, 3)[0], [X], {}),
    "meshgrid": (lambda a, b: paddle.meshgrid(a, b)[0], [V4, V4 * 2.0], {}),
    # index / mask consumers (closed-over integer/bool operands)
    "gather": (lambda t: paddle.gather(t, paddle.to_tensor(_idx)), [X], {}),
    "gather_nd": (lambda t: paddle.gather_nd(t, paddle.to_tensor(
        np.array([[0, 1], [2, 3]], np.int64))), [X], {}),
    "index_select": (lambda t: paddle.index_select(
        t, paddle.to_tensor(_idx)), [X], {}),
    "index_sample": (lambda t: paddle.index_sample(t, paddle.to_tensor(
        np.array([[0, 1], [1, 2], [3, 0]], np.int64))), [X], {}),
    "index_add": (lambda t, s: paddle.index_add(
        t, paddle.to_tensor(_idx), 0, s), [X, RNG.randn(3, 4).astype(
            np.float32)], {}),
    "index_fill": (lambda t: paddle.index_fill(
        t, paddle.to_tensor(np.array([1], np.int64)), 0, 0.5), [X], {}),
    "index_put": (lambda t, s: paddle.index_put(
        t, [paddle.to_tensor(np.array([0, 2], np.int64))], s),
        [X, RNG.randn(2, 4).astype(np.float32)], {}),
    "masked_fill": (lambda t: paddle.masked_fill(
        t, paddle.to_tensor(_mask), 0.5), [X], {}),
    "masked_select": (lambda t: paddle.masked_select(
        t, paddle.to_tensor(_mask)), [X], {}),
    "take": (lambda t: paddle.take(t, paddle.to_tensor(
        np.array([0, 5, 11], np.int64))), [X], {}),
    "take_along_axis": (lambda t: paddle.take_along_axis(
        t, paddle.to_tensor(np.array([[0, 1, 2, 0]], np.int64)), 0), [X],
        {}),
    "put_along_axis": (lambda t, s: paddle.put_along_axis(
        t, paddle.to_tensor(np.array([[0, 1, 2, 0]], np.int64)), s, 0),
        [X, RNG.randn(1, 4).astype(np.float32)], {}),
    "scatter": (lambda t, s: paddle.scatter(
        t, paddle.to_tensor(np.array([0, 2], np.int64)), s),
        [X, RNG.randn(2, 4).astype(np.float32)], {}),
    "scatter_nd": (lambda s: paddle.scatter_nd(paddle.to_tensor(
        np.array([[1], [3]], np.int64)), s, [5, 4]),
        [RNG.randn(2, 4).astype(np.float32)], {}),
    "scatter_nd_add": (lambda t, s: paddle.scatter_nd_add(
        t, paddle.to_tensor(np.array([[0], [2]], np.int64)), s),
        [X, RNG.randn(2, 4).astype(np.float32)], {}),
    "where": (lambda a, b: paddle.where(paddle.to_tensor(_mask), a, b),
              [X, Y], {}),
    "multiplex": (lambda a, b: paddle.multiplex(
        [a, b], paddle.to_tensor(np.array([0, 1, 0], np.int32))), [X, Y],
        {}),
}
# finite differences are loose for ill-conditioned spectra
_SPECIAL_TOL = {"eigh": (5e-2, 5e-3), "eigvalsh": (5e-2, 5e-3),
                "cond": (5e-2, 5e-3), "svd": (3e-2, 3e-3),
                "corrcoef": (3e-2, 3e-3), "det": (3e-2, 3e-2),
                "slogdet": (3e-2, 3e-3), "pinv": (3e-2, 3e-3)}

# ---------------------------------------------------------------------------
# WHITELIST: excluded, with reasons (reference: test/white_list/)
# ---------------------------------------------------------------------------

_W_BOOL = "boolean/comparison output — nothing to differentiate"
_W_INT = "integer/index output"
_W_CREATE = "creation op — output independent of any float input"
_W_RANDOM = "random sampling — finite differences see fresh draws"
_W_STATE = "state/config/introspection — not a tensor op"
_W_IO = "serialization/io"
_W_INPLACE = "in-place alias; grad flow covered by test_op_longtail " \
             "inplace tests"
_W_ELSEWHERE = "grad covered by a dedicated test"
WHITELIST = {
    # bool / comparison / logic
    "all": _W_BOOL, "any": _W_BOOL, "allclose": _W_BOOL, "isclose": _W_BOOL,
    "equal": _W_BOOL, "equal_all": _W_BOOL, "greater_equal": _W_BOOL,
    "greater_than": _W_BOOL, "less_equal": _W_BOOL, "less_than": _W_BOOL,
    "not_equal": _W_BOOL, "logical_and": _W_BOOL, "logical_not": _W_BOOL,
    "logical_or": _W_BOOL, "logical_xor": _W_BOOL, "isfinite": _W_BOOL,
    "isinf": _W_BOOL, "isnan": _W_BOOL, "isin": _W_BOOL,
    "is_empty": _W_BOOL, "is_tensor": _W_BOOL, "is_complex": _W_BOOL,
    "is_floating_point": _W_BOOL, "is_integer": _W_BOOL,
    "bitwise_and": _W_INT, "bitwise_not": _W_INT, "bitwise_or": _W_INT,
    "bitwise_xor": _W_INT,
    # integer / index outputs
    "argmax": _W_INT, "argmin": _W_INT, "argsort": _W_INT,
    "bincount": _W_INT, "bucketize": _W_INT, "count_nonzero": _W_INT,
    "nonzero": _W_INT, "numel": _W_INT, "one_hot": _W_INT, "rank": _W_INT,
    "searchsorted": _W_INT, "shape": _W_INT, "tril_indices": _W_INT,
    "triu_indices": _W_INT, "matrix_rank": _W_INT, "gcd": _W_INT,
    "lcm": _W_INT, "shard_index": _W_INT, "histogram": _W_INT,
    "unique": "selection with dedup — gradient undefined at merges",
    "unique_consecutive": "selection with dedup — gradient undefined",
    "mode": "majority selection — int index output drives it",
    "frexp": "mantissa/exponent decomposition — exponent is integer, "
             "mantissa piecewise; value parity tested in test_op_longtail",
    "nextafter": "adjacent-float step — no differentiation rule by design",
    # creation
    "arange": _W_CREATE, "empty": _W_CREATE, "empty_like": _W_CREATE,
    "eye": _W_CREATE, "full": _W_CREATE, "full_like": _W_CREATE,
    "linspace": _W_CREATE, "logspace": _W_CREATE, "ones": _W_CREATE,
    "ones_like": _W_CREATE, "zeros": _W_CREATE, "zeros_like": _W_CREATE,
    "create_tensor": _W_CREATE, "create_parameter": _W_CREATE,
    "to_tensor": _W_CREATE, "tolist": "python list output",
    # random
    "bernoulli": _W_RANDOM, "exponential_": _W_RANDOM,
    "multinomial": _W_RANDOM, "normal": _W_RANDOM, "normal_like": _W_RANDOM,
    "poisson": _W_RANDOM, "rand": _W_RANDOM, "rand_like": _W_RANDOM,
    "randint": _W_RANDOM, "randint_like": _W_RANDOM, "randn": _W_RANDOM,
    "randn_like": _W_RANDOM, "randperm": _W_RANDOM,
    "standard_normal": _W_RANDOM, "uniform": _W_RANDOM,
    "uniform_": _W_RANDOM, "pca_lowrank": _W_RANDOM,
    # state / config / introspection / control
    "batch": _W_STATE, "check_shape": _W_STATE, "broadcast_shape": _W_STATE,
    "device_count": _W_STATE, "disable_signal_handler": _W_STATE,
    "disable_static": _W_STATE, "enable_static": _W_STATE,
    "flops": _W_STATE, "get_cuda_rng_state": _W_STATE,
    "get_default_dtype": _W_STATE, "get_device": _W_STATE,
    "get_flags": _W_STATE, "get_rng_state": _W_STATE, "grad": _W_STATE,
    "in_dynamic_mode": _W_STATE, "is_compiled_with_cuda": _W_STATE,
    "is_compiled_with_tpu": _W_STATE, "is_grad_enabled": _W_STATE,
    "seed": _W_STATE, "set_cuda_rng_state": _W_STATE,
    "set_default_dtype": _W_STATE, "set_device": _W_STATE,
    "set_flags": _W_STATE, "set_grad_enabled": _W_STATE,
    "set_printoptions": _W_STATE, "set_rng_state": _W_STATE,
    "summary": _W_STATE, "synchronize": _W_STATE, "to_static": _W_STATE,
    "save": _W_IO, "load": _W_IO,
    # in-place variants
    "squeeze_": _W_INPLACE, "tanh_": _W_INPLACE, "pow_": _W_INPLACE,
    "index_add_": _W_INPLACE, "index_fill_": _W_INPLACE,
    "index_put_": _W_INPLACE, "scatter_": _W_INPLACE,
    "reshape_": _W_INPLACE, "unsqueeze_": _W_INPLACE,
    # complex-valued ops (complex AD path covered in test_op_longtail
    # as_complex/as_real roundtrip; fft AD in test_fft)
    "as_complex": _W_ELSEWHERE, "complex": _W_ELSEWHERE,
    "polar": _W_ELSEWHERE,
    # no JAX VJP / partial outputs — documented gaps, matching reference
    # behavior where grads exist only for the symmetric case (eigh)
    "eig": "complex general eigendecomposition — no JAX VJP; use eigh",
    "eigvals": "complex general eigenvalues — no JAX VJP; use eigvalsh",
    "lstsq": "multi-output (incl. int rank); solution-grad covered in "
             "test_linalg",
    "lu": "pivoted factorization int pivots; value parity in test_linalg",
    "lu_unpack": "consumes lu() output; value parity in test_op_longtail",
    "householder_product": "needs qr-internal (A, tau) operands; value "
                           "parity in test_linalg",
    # views over raw memory / aliasing helpers
    "as_strided": "raw-stride view; grad flow covered via strided_slice",
    "view": _W_ELSEWHERE, "view_as": _W_ELSEWHERE,
    "cast": "dtype mover; grad-through-cast covered in test_autograd",
    "nanmedian": _W_ELSEWHERE,  # AUTO would tie-break; test_op_longtail
    "broadcast_tensors": "multi-output broadcast; covered via "
                         "broadcast_to",
    "unbind": _W_ELSEWHERE, "unstack": _W_ELSEWHERE,
}


def _public_ops():
    out = []
    for n in sorted(dir(paddle)):
        if n.startswith("_"):
            continue
        f = getattr(paddle, n)
        if callable(f) and not isinstance(f, type):
            out.append(n)
    return out


def test_surface_fully_classified():
    """Every public op is AUTO, SPECIAL, or WHITELISTED — a new export
    without a grad check (or a reasoned exclusion) fails here."""
    known = set(AUTO_UNARY) | set(AUTO_BINARY) | set(_SPECIAL) \
        | set(WHITELIST)
    missing = [n for n in _public_ops() if n not in known]
    assert not missing, (
        f"new public ops without grad-check classification: {missing} — "
        "add them to AUTO_*, _SPECIAL (with inputs), or WHITELIST (with "
        "a reason) in tests/test_check_grad_sweep.py")
    # and the classification doesn't reference ops that no longer exist
    gone = [n for n in known if not hasattr(paddle, n)]
    assert not gone, f"classified ops no longer exported: {gone}"


def test_sweep_counts():
    checked = len(AUTO_UNARY) + len(AUTO_BINARY) + len(_SPECIAL)
    assert checked >= 180, checked  # coverage floor: fail loud on shrink


@pytest.mark.parametrize("op_name", AUTO_UNARY)
def test_auto_unary_grad(op_name):
    check_grad(getattr(paddle, op_name), [X.copy()], name=op_name)


@pytest.mark.parametrize("op_name", AUTO_BINARY)
def test_auto_binary_grad(op_name):
    check_grad(getattr(paddle, op_name), [X.copy(), Y.copy()], name=op_name)


@pytest.mark.parametrize("op_name", sorted(_SPECIAL))
def test_special_grad(op_name):
    fn, inputs, kwargs = _SPECIAL[op_name]
    rtol, atol = _SPECIAL_TOL.get(op_name, (1e-2, 1e-3))
    check_grad(fn, [np.copy(a) if isinstance(a, np.ndarray) else a
                    for a in inputs], kwargs, rtol=rtol, atol=atol,
               name=op_name)
