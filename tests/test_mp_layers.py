"""Tensor-parallel layer parity tests (reference pattern:
test/collective/fleet/hybrid_parallel_mp_layers.py — TP layers must match
single-device math)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed._spmd import layer_pspecs, shard_params
from paddle_tpu.distributed.fleet.layers.mpu import (
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding)
from paddle_tpu.distributed.topology import build_mesh, set_mesh


def t2n(t):
    return np.asarray(t.numpy())


@pytest.fixture(autouse=True)
def _mesh():
    mesh = build_mesh(mp=8)
    set_mesh(mesh)
    from paddle_tpu.distributed.communication import core

    core._reset_default_group()
    yield mesh


class TestColumnRowParallel:
    def test_column_parallel_eager_matches_linear(self, _mesh):
        layer = ColumnParallelLinear(16, 24, gather_output=True)
        x = np.random.randn(4, 16).astype(np.float32)
        out = layer(paddle.to_tensor(x))
        w = t2n(layer.weight)
        b = t2n(layer.bias)
        np.testing.assert_allclose(t2n(out), x @ w + b, rtol=1e-5, atol=1e-5)

    def test_row_parallel_eager_matches_linear(self, _mesh):
        layer = RowParallelLinear(24, 16, input_is_parallel=True)
        x = np.random.randn(4, 24).astype(np.float32)
        out = layer(paddle.to_tensor(x))
        w = t2n(layer.weight)
        b = t2n(layer.bias)
        np.testing.assert_allclose(t2n(out), x @ w + b, rtol=1e-5, atol=1e-5)

    def test_mlp_sharded_jit_matches_eager(self, _mesh):
        """column(gather=False) -> row(input_is_parallel) MLP under jit over
        the mp=8 mesh == eager single-device math."""
        col = ColumnParallelLinear(16, 32, gather_output=False)
        row = RowParallelLinear(32, 16, input_is_parallel=True)
        shard_params(col, _mesh)
        shard_params(row, _mesh)
        x = np.random.randn(8, 16).astype(np.float32)

        def f(xv):
            h = col(paddle.to_tensor(xv, stop_gradient=True))
            return row(h).value

        jitted = jax.jit(lambda xv: f(xv))
        got = np.asarray(jitted(x))
        w1, b1 = t2n(col.weight), t2n(col.bias)
        w2, b2 = t2n(row.weight), t2n(row.bias)
        expected = (x @ w1 + b1) @ w2 + b2
        np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-4)

    def test_manual_shard_map_matches_serial(self, _mesh):
        """Megatron manual path: run the column->row pair inside shard_map
        with weights sharded by hand; must equal serial matmul."""
        rng = np.random.RandomState(0)
        w1 = rng.randn(16, 32).astype(np.float32)
        w2 = rng.randn(32, 16).astype(np.float32)
        x = rng.randn(8, 16).astype(np.float32)
        from paddle_tpu.distributed.fleet.layers.mpu import mp_ops

        def step(xv, w1v, w2v):
            h = mp_ops._c_identity(paddle.to_tensor(xv))
            h = paddle.matmul(h, paddle.to_tensor(w1v))
            y = paddle.matmul(h, paddle.to_tensor(w2v))
            y = mp_ops._mp_allreduce(y)
            return y.value

        f = shard_map(
            step, mesh=_mesh,
            in_specs=(P(), P(None, "mp"), P("mp", None)),
            out_specs=P(),
        )
        got = np.asarray(jax.jit(f)(x, w1, w2))
        np.testing.assert_allclose(got, x @ w1 @ w2, rtol=1e-4, atol=1e-4)


class TestVocabParallelEmbedding:
    def test_eager_matches_take(self, _mesh):
        emb = VocabParallelEmbedding(64, 12)
        ids = np.random.randint(0, 64, (4, 7))
        out = emb(paddle.to_tensor(ids))
        expected = t2n(emb.weight)[ids]
        np.testing.assert_allclose(t2n(out), expected, rtol=1e-6)

    def test_manual_shard_map_matches_take(self, _mesh):
        rng = np.random.RandomState(1)
        table = rng.randn(64, 12).astype(np.float32)
        ids = rng.randint(0, 64, (4, 7))
        from paddle_tpu.distributed.fleet.layers.mpu import mp_ops

        def step(tbl, idx):
            out = mp_ops._c_lookup_table(paddle.to_tensor(tbl),
                                         paddle.to_tensor(idx))
            return out.value

        f = shard_map(step, mesh=_mesh, in_specs=(P("mp", None), P()),
                      out_specs=P())
        got = np.asarray(jax.jit(f)(table, ids.astype(np.int32)))
        np.testing.assert_allclose(got, table[ids], rtol=1e-5)


class TestParallelCrossEntropy:
    def test_matches_softmax_ce(self, _mesh):
        rng = np.random.RandomState(2)
        logits = rng.randn(6, 40).astype(np.float32)
        labels = rng.randint(0, 40, (6,))
        ce = ParallelCrossEntropy()
        loss = ce(paddle.to_tensor(logits), paddle.to_tensor(labels))
        # numpy reference
        m = logits.max(-1, keepdims=True)
        ex = np.exp(logits - m)
        ref = (np.log(ex.sum(-1, keepdims=True)) + m
               - np.take_along_axis(logits, labels[:, None], -1))
        np.testing.assert_allclose(t2n(loss), ref, rtol=1e-5, atol=1e-5)

    def test_manual_class_parallel_matches(self, _mesh):
        rng = np.random.RandomState(3)
        logits = rng.randn(6, 40).astype(np.float32)
        labels = rng.randint(0, 40, (6,)).astype(np.int32)
        from paddle_tpu.distributed.fleet.layers.mpu import mp_ops

        def step(lg, lb):
            out = mp_ops._c_softmax_with_cross_entropy(
                paddle.to_tensor(lg), paddle.to_tensor(lb))
            return out.value

        f = shard_map(step, mesh=_mesh, in_specs=(P(None, "mp"), P()),
                      out_specs=P())
        got = np.asarray(jax.jit(f)(logits, labels))
        m = logits.max(-1, keepdims=True)
        ex = np.exp(logits - m)
        ref = (np.log(ex.sum(-1, keepdims=True)) + m
               - np.take_along_axis(logits, labels[:, None].astype(np.int64), -1))
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


class TestRNGTracker:
    def test_named_streams_differ_and_restore(self, _mesh):
        from paddle_tpu.distributed.fleet.layers.mpu import (
            get_rng_state_tracker, model_parallel_random_seed)

        model_parallel_random_seed(1234)
        tracker = get_rng_state_tracker()
        x = paddle.to_tensor(np.ones((64, 64), np.float32))
        import paddle_tpu.nn.functional as F

        with tracker.rng_state():
            a = t2n(F.dropout(x, 0.5, training=True))
        b = t2n(F.dropout(x, 0.5, training=True))
        assert not np.allclose(a, b)

    def test_duplicate_seed_rejected(self, _mesh):
        from paddle_tpu.distributed.fleet.layers.mpu import RNGStatesTracker

        tr = RNGStatesTracker()
        tr.add("a", 1)
        with pytest.raises(ValueError):
            tr.add("b", 1)


class TestFleetFacade:
    def test_init_and_hcg(self, _mesh):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
        fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.get_hybrid_communicate_group()
        assert hcg.get_model_parallel_world_size() == 4
        assert hcg.get_data_parallel_world_size() == 2

    def test_distributed_model_tp_wrapper(self, _mesh):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"mp_degree": 8}
        fleet.init(is_collective=True, strategy=strategy)
        import paddle_tpu.nn as nn

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = ColumnParallelLinear(8, 16, gather_output=True)

            def forward(self, x):
                return self.fc(x)

        net = fleet.distributed_model(Net())
        x = np.random.randn(2, 8).astype(np.float32)
        out = net(paddle.to_tensor(x))
        assert tuple(out.shape) == (2, 16)
