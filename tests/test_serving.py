"""paddle_tpu.serving — online continuous-batching serving layer.

Covers the ISSUE-2 acceptance demo end to end on CPU: a Server over a
toy paged engine takes >= 8 concurrent requests with mixed prompt
lengths and PER-REQUEST GenerationConfigs, completes them interleaved
(continuous batching), streams tokens before completion, reclaims
capacity on cancellation, applies queue-full backpressure, and exports
TTFT / queue-depth via the monitor — plus the engine-level capacity
probe, cancellation, per-request-config threading, deadline, drain and
HTTP front-end contracts.
"""
import json
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.inference.generation import (CausalLMEngine,
                                             ContinuousBatchingEngine,
                                             GenerationConfig,
                                             PagedContinuousBatchingEngine)
from paddle_tpu.models import LlamaForCausalLM, llama_config
from paddle_tpu.serving import (DeadlineExpired, QueueFull,
                                RequestCancelled, RequestFailed,
                                RequestRejected, Server, serve_http)


def tiny_model(layers=1, seed=0):
    paddle.seed(seed)
    cfg = llama_config("tiny", num_hidden_layers=layers)
    return LlamaForCausalLM(cfg), cfg


def paged_engine(model, max_batch=3, num_pages=24, page_size=8,
                 max_pages=8):
    return PagedContinuousBatchingEngine(
        model, max_batch=max_batch, num_pages=num_pages,
        page_size=page_size, max_pages=max_pages)


@pytest.fixture()
def mon():
    monitor.enable()
    monitor.reset()
    yield monitor
    monitor.reset()
    monitor.disable()


def _prompts(rng, vocab, lens):
    return [rng.randint(0, vocab, (n,)).astype(np.int32) for n in lens]


class TestGenerationConfigValidation:
    """A malformed online request must be rejected at admission, not
    crash a shared decode segment mid-flight."""

    @pytest.mark.parametrize("kw", [
        {"max_new_tokens": 0}, {"max_new_tokens": -3},
        {"max_new_tokens": 2.0}, {"max_new_tokens": True},
        {"temperature": 0}, {"temperature": -0.5},
        {"temperature": float("nan")},
        {"top_k": -1}, {"top_k": 2.5},
        {"top_p": 0}, {"top_p": 0.0}, {"top_p": 1.5}, {"top_p": -0.1},
        {"eos_token_id": -2}, {"eos_token_id": 1.5},
    ])
    def test_bad_values_raise(self, kw):
        with pytest.raises(ValueError, match=next(iter(kw))):
            GenerationConfig(**kw)

    @pytest.mark.parametrize("kw", [
        {"max_new_tokens": 2 ** 31}, {"top_k": 2 ** 40},
        {"eos_token_id": 2 ** 31},
    ])
    def test_beyond_int32_rejected(self, kw):
        """Engine state is int32 on device: an oversized field must be
        rejected at construction — it used to pass validation and then
        overflow MID-admission, leaking the popped slot."""
        with pytest.raises(ValueError, match=next(iter(kw))):
            GenerationConfig(**kw)

    def test_good_values_normalize(self):
        cfg = GenerationConfig(max_new_tokens=np.int64(8),
                               temperature=1, top_k=np.int32(5),
                               top_p=1, eos_token_id=np.int64(3))
        assert (cfg.max_new_tokens, cfg.top_k, cfg.eos_token_id) == (8, 5, 3)
        assert isinstance(cfg.temperature, float)
        assert GenerationConfig().eos_token_id is None


class TestRequestQueue:
    """Ordering + bounded-size + reap semantics, no engine needed."""

    def _h(self, rid, priority=0, deadline=None):
        from paddle_tpu.serving import RequestHandle
        return RequestHandle(rid, [1], 1,
                             GenerationConfig(max_new_tokens=2),
                             priority=priority, deadline=deadline)

    def test_priority_then_fifo(self):
        from paddle_tpu.serving import RequestQueue
        q = RequestQueue(8)
        for h in (self._h(0, 5), self._h(1, 0), self._h(2, 0),
                  self._h(3, 2)):
            q.put(h)
        order = []
        while q.depth:
            order.append(q.pop_if(lambda h: True).id)
        # lower priority value first; FIFO within a priority
        assert order == [1, 2, 3, 0]

    def test_bounded_put_raises(self):
        from paddle_tpu.serving import RequestQueue
        q = RequestQueue(2)
        q.put(self._h(0))
        q.put(self._h(1))
        with pytest.raises(QueueFull):
            q.put(self._h(2))

    def test_reap_removes_deep_entries(self):
        from paddle_tpu.serving import RequestQueue
        q = RequestQueue(8)
        live = self._h(0, 0)
        expired = self._h(1, 3, deadline=time.monotonic() - 1)
        cancelled = self._h(2, 5)
        cancelled._cancel_requested = True
        for h in (live, expired, cancelled):
            q.put(h)
        dead = q.reap(time.monotonic())
        assert {h.id for h in dead} == {1, 2}
        assert q.depth == 1
        assert q.pop_if(lambda h: True).id == 0

    def test_pop_if_defers_on_false(self):
        from paddle_tpu.serving import RequestQueue
        q = RequestQueue(4)
        q.put(self._h(0))
        assert q.pop_if(lambda h: False) is None
        assert q.depth == 1


class TestCapacityProbe:
    """Public free_slots()/can_admit(): the scheduler path is probe +
    defer; add_request raising is the programmer-error path."""

    def test_dense_probe_and_loud_add(self):
        model, cfg = tiny_model()
        eng = ContinuousBatchingEngine(model, max_batch=2, max_len=32)
        gc = GenerationConfig(max_new_tokens=4, eos_token_id=None)
        assert eng.free_slots() == 2
        assert eng.can_admit(5, gc)
        # over max_len: probe says no (deferral would never help, and
        # add_request raises loudly for callers that skip the probe)
        assert not eng.can_admit(30, gc)
        with pytest.raises(ValueError, match="max_len"):
            eng.add_request(np.arange(30, dtype=np.int32), gc)
        rng = np.random.RandomState(0)
        for p in _prompts(rng, cfg.vocab_size, [4, 4]):
            eng.add_request(p, gc)
        assert eng.free_slots() == 0
        assert not eng.can_admit(4, gc)      # no free slot -> defer
        with pytest.raises(RuntimeError, match="free slot"):
            eng.add_request(np.arange(4, dtype=np.int32), gc)

    def test_paged_probe_sees_pool_pressure(self):
        model, cfg = tiny_model()
        # 6 pages * 8 = 48 tokens; each request reserves
        # ceil((18+6)/8) = 3 pages
        eng = paged_engine(model, max_batch=3, num_pages=6, page_size=8,
                           max_pages=6)
        gc = GenerationConfig(max_new_tokens=6, eos_token_id=None)
        assert eng.can_admit(18, gc)
        rng = np.random.RandomState(1)
        eng.add_request(rng.randint(0, cfg.vocab_size, (18,))
                        .astype(np.int32), gc)
        eng.add_request(rng.randint(0, cfg.vocab_size, (18,))
                        .astype(np.int32), gc)
        # slots free, pool full: probe defers, add_request is loud
        assert eng.free_slots() == 1
        assert not eng.can_admit(18, gc)
        with pytest.raises(RuntimeError, match="exhausted"):
            eng.add_request(rng.randint(0, cfg.vocab_size, (18,))
                            .astype(np.int32), gc)


class TestEngineCancellation:
    def test_cancel_mid_decode_releases_slot_and_pages(self, mon):
        model, cfg = tiny_model()
        eng = paged_engine(model, max_batch=2, num_pages=12)
        gc = GenerationConfig(max_new_tokens=30, eos_token_id=None)
        rng = np.random.RandomState(2)
        rid = eng.add_request(rng.randint(0, cfg.vocab_size, (6,))
                              .astype(np.int32), gc)
        eng.decode_segment(2)
        assert eng.partial_tokens(rid) is not None
        partial = eng.cancel_request(rid)
        # admission token + 2 segment tokens, slot AND pages reclaimed
        assert len(partial) == 3
        assert eng.free_slots() == 2
        assert eng.alloc.free_pages == eng.num_pages
        # a cancelled request never surfaces as finished
        assert rid not in eng.collect_finished()
        assert eng.partial_tokens(rid) is None
        # idempotent / unknown rid
        assert eng.cancel_request(rid) is None
        ev = {s["labels"]["event"]: s["value"]
              for s in monitor.snapshot()["metrics"]
              ["paddle_tpu_requests_total"]["samples"]}
        assert ev.get("cancelled") == 1

    def test_failed_admission_leaks_no_capacity(self):
        """add_request raising mid-admission (after the slot pop) must
        restore the slot and any page reservation."""
        model, cfg = tiny_model()
        eng = paged_engine(model, max_batch=2, num_pages=12)
        gc = GenerationConfig(max_new_tokens=4, eos_token_id=None)
        # force a failure AFTER capacity was claimed
        orig = eng._admit_state
        eng._admit_state = lambda *a: (_ for _ in ()).throw(
            RuntimeError("injected admit fault"))
        with pytest.raises(RuntimeError, match="injected"):
            eng.add_request(np.arange(6, dtype=np.int32), gc)
        eng._admit_state = orig
        assert eng.free_slots() == 2
        assert eng.alloc.free_pages == eng.num_pages
        # the engine still works afterwards
        rid = eng.add_request(np.arange(6, dtype=np.int32), gc)
        while eng.decode_segment(4):
            pass
        assert len(eng.collect_finished()[rid]) == 4

    def test_capacity_freed_for_next_request(self):
        model, cfg = tiny_model()
        # pool fits ONE reservation at a time
        eng = paged_engine(model, max_batch=2, num_pages=3, page_size=8,
                           max_pages=4)
        gc = GenerationConfig(max_new_tokens=10, eos_token_id=None)
        rng = np.random.RandomState(3)
        p1, p2 = _prompts(rng, cfg.vocab_size, [12, 12])
        rid = eng.add_request(p1, gc)
        assert not eng.can_admit(12, gc)
        eng.cancel_request(rid)
        assert eng.can_admit(12, gc)
        rid2 = eng.add_request(p2, gc)
        while eng.decode_segment(4, gc):
            pass
        assert len(eng.collect_finished()[rid2]) == 10


class TestPerRequestConfigs:
    """Per-request GenerationConfig threading: one compiled segment
    program serves a mixed greedy/sampled/eos batch, and the greedy
    request stays bitwise-parity with the dense engine."""

    def test_mixed_configs_single_program(self, mon):
        model, cfg = tiny_model(layers=2)
        rng = np.random.RandomState(4)
        p_greedy, p_samp, p_eos = _prompts(rng, cfg.vocab_size,
                                           [5, 9, 7])

        dense = CausalLMEngine(model, max_batch=1, max_len=64)
        gc_greedy = GenerationConfig(max_new_tokens=10, do_sample=False,
                                     eos_token_id=None)
        want = dense.generate(p_greedy[None], gc_greedy)[0, 5:]
        # an eos id the eos-request actually emits mid-stream
        probe = dense.generate(p_eos[None], GenerationConfig(
            max_new_tokens=10, eos_token_id=None))[0, 7:]
        eos = int(probe[3])

        eng = ContinuousBatchingEngine(model, max_batch=3, max_len=64)
        r1 = eng.add_request(p_greedy, gc_greedy)
        r2 = eng.add_request(p_samp, GenerationConfig(
            max_new_tokens=6, do_sample=True, temperature=0.7, top_k=9,
            top_p=0.9, seed=11, eos_token_id=None))
        r3 = eng.add_request(p_eos, GenerationConfig(
            max_new_tokens=10, eos_token_id=eos))
        while eng.decode_segment(3):
            pass
        outs = eng.collect_finished()
        np.testing.assert_array_equal(outs[r1], want)
        assert len(outs[r2]) == 6
        # the eos request stops at ITS eos; the greedy one ignores it
        o3 = list(outs[r3])
        assert o3[:4] == [int(t) for t in probe[:3]] + [eos]
        # ONE cb_segment compile across every config mix (the sampling
        # parameters are data, not trace constants)
        misses = monitor.jit_miss_by_fn()
        assert misses.get("cb_segment") == 1, misses

    def test_per_request_seed_threads_into_decode(self):
        """The request's seed drives ITS sampled trajectory (folded into
        every decode step's noise key), not just the admission token:
        same seed reproduces, different seed diverges."""
        model, cfg = tiny_model()
        rng = np.random.RandomState(5)
        p = rng.randint(0, cfg.vocab_size, (6,)).astype(np.int32)

        def run(seed):
            eng = ContinuousBatchingEngine(model, max_batch=1,
                                           max_len=64)
            rid = eng.add_request(p, GenerationConfig(
                max_new_tokens=16, do_sample=True, temperature=3.0,
                seed=seed, eos_token_id=None))
            while eng.decode_segment(4):
                pass
            return list(eng.collect_finished()[rid])

        assert run(1) == run(1)
        assert run(1) != run(2)


def _server(model_layers=1, **kw):
    model, cfg = tiny_model(layers=model_layers)
    defaults = dict(max_batch=3, num_pages=24, page_size=8, max_pages=8)
    eng_kw = {k: kw.pop(k) for k in list(kw)
              if k in ("max_batch", "num_pages", "page_size",
                       "max_pages")}
    eng = paged_engine(model, **{**defaults, **eng_kw})
    return Server(eng, **kw), eng, cfg


class TestServerOnline:
    def test_acceptance_demo_end_to_end(self, mon):
        """ISSUE-2 acceptance: >= 8 concurrent requests, mixed prompt
        lengths and per-request configs, interleaved completion,
        streaming before completion, TTFT/queue-depth in the export."""
        srv, eng, cfg = _server(max_queue=16, segment_steps=3)
        try:
            rng = np.random.RandomState(0)
            spec = [(5, 20), (9, 4), (3, 8), (12, 6), (4, 12), (7, 4),
                    (2, 16), (6, 5)]
            handles = []
            for i, (plen, mx) in enumerate(spec):
                p = rng.randint(0, cfg.vocab_size, (plen,)) \
                    .astype(np.int32)
                gc = GenerationConfig(max_new_tokens=mx,
                                      do_sample=(i % 3 == 0),
                                      temperature=0.9, seed=i,
                                      eos_token_id=None)
                handles.append(srv.submit(p, gc))

            # stream the FIRST (longest) request while the rest run
            seen = []
            def consume():
                for tok in handles[0].stream(timeout=60):
                    seen.append((tok, handles[0].status))
            t = threading.Thread(target=consume)
            t.start()
            outs = [h.result(timeout=120) for h in handles]
            t.join(60)

            # every request respected ITS OWN budget
            assert [len(o) for o in outs] == [mx for _, mx in spec]
            # interleaved (continuous-batched) completion: the 20-token
            # request 0 finished AFTER later-submitted short requests
            finished_before_0 = [i for i in range(1, 8)
                                 if handles[i].finish_ts
                                 < handles[0].finish_ts]
            assert finished_before_0, "no interleaving observed"
            # streamed tokens arrived BEFORE completion
            assert any(s == "running" for _, s in seen)
            assert [tok for tok, _ in seen] == [int(x) for x in outs[0]]
            # TTFT / queue-depth series visible via the monitor export
            snap = monitor.snapshot()["metrics"]
            ttft = snap["paddle_tpu_serving_ttft_seconds"]["samples"][0]
            assert ttft["count"] >= 8
            assert ttft["labels"]["server"] == srv.monitor_server
            assert "paddle_tpu_serving_queue_depth" in snap
            prom = monitor.render_prometheus()
            assert "paddle_tpu_serving_ttft_seconds_bucket" in prom
            assert "paddle_tpu_serving_queue_depth" in prom
        finally:
            srv.shutdown(drain=False)

    def test_cancel_reclaims_capacity_for_queued(self, mon):
        """One cancellation must free a slot (and pages) that a QUEUED
        request then takes — the acceptance demo's reclaim leg."""
        srv, eng, cfg = _server(max_batch=2, num_pages=10,
                                max_queue=8, segment_steps=2)
        try:
            rng = np.random.RandomState(1)
            long_cfg = GenerationConfig(max_new_tokens=56,
                                        eos_token_id=None)
            h1 = srv.submit(rng.randint(0, cfg.vocab_size, (6,))
                            .astype(np.int32), long_cfg)
            h2 = srv.submit(rng.randint(0, cfg.vocab_size, (6,))
                            .astype(np.int32), long_cfg)
            # both slots occupied; this one has to queue
            h3 = srv.submit(rng.randint(0, cfg.vocab_size, (4,))
                            .astype(np.int32),
                            GenerationConfig(max_new_tokens=5,
                                             eos_token_id=None))
            # wait until h1 is actually running (first token streamed)
            next(iter(h1.stream(timeout=60)))
            assert h3.status == "queued"
            h1.cancel()
            out3 = h3.result(timeout=120)
            assert len(out3) == 5
            with pytest.raises(RequestCancelled):
                h1.result(timeout=60)
            assert len(h1.tokens_so_far()) >= 1   # partials retained
            ev = {s["labels"]["event"]: s["value"]
                  for s in monitor.snapshot()["metrics"]
                  ["paddle_tpu_serving_requests_total"]["samples"]}
            assert ev.get("cancelled") == 1
            h2.cancel()
        finally:
            srv.shutdown(drain=False)

    def test_queue_full_rejection(self, mon):
        srv, eng, cfg = _server(max_batch=1, num_pages=24, max_queue=2,
                                segment_steps=2)
        try:
            rng = np.random.RandomState(2)
            gc = GenerationConfig(max_new_tokens=40, eos_token_id=None)
            hs = [srv.submit(rng.randint(0, cfg.vocab_size, (4,))
                             .astype(np.int32), gc)]
            next(iter(hs[0].stream(timeout=60)))   # slot occupied
            for _ in range(2):                     # fill the queue
                hs.append(srv.submit(
                    rng.randint(0, cfg.vocab_size, (4,))
                    .astype(np.int32), gc))
            with pytest.raises(QueueFull) as ei:
                srv.submit(rng.randint(0, cfg.vocab_size, (4,))
                           .astype(np.int32), gc)
            assert ei.value.reason == "queue_full"
            ev = {s["labels"]["event"]: s["value"]
                  for s in monitor.snapshot()["metrics"]
                  ["paddle_tpu_serving_requests_total"]["samples"]}
            assert ev.get("rejected_queue_full") == 1
            for h in hs:
                h.cancel()
        finally:
            srv.shutdown(drain=False)

    def test_deadline_expired_never_admits(self, mon):
        srv, eng, cfg = _server(max_batch=1, num_pages=24,
                                segment_steps=2)
        try:
            rng = np.random.RandomState(3)
            h1 = srv.submit(rng.randint(0, cfg.vocab_size, (4,))
                            .astype(np.int32),
                            GenerationConfig(max_new_tokens=48,
                                             eos_token_id=None))
            next(iter(h1.stream(timeout=60)))      # slot occupied
            h2 = srv.submit(rng.randint(0, cfg.vocab_size, (4,))
                            .astype(np.int32),
                            GenerationConfig(max_new_tokens=4,
                                             eos_token_id=None),
                            timeout_s=0.05)
            with pytest.raises(DeadlineExpired):
                h2.result(timeout=60)
            assert h2.engine_rid is None           # never admitted
            assert h2.tokens_so_far() == []
            ev = {s["labels"]["event"]: s["value"]
                  for s in monitor.snapshot()["metrics"]
                  ["paddle_tpu_serving_requests_total"]["samples"]}
            assert ev.get("expired") == 1
            h1.cancel()
        finally:
            srv.shutdown(drain=False)

    def test_drain_finishes_inflight_rejects_new(self):
        srv, eng, cfg = _server(segment_steps=3)
        try:
            rng = np.random.RandomState(4)
            hs = [srv.submit(rng.randint(0, cfg.vocab_size, (n,))
                             .astype(np.int32),
                             GenerationConfig(max_new_tokens=6,
                                              eos_token_id=None))
                  for n in (5, 8, 3, 6)]
            assert srv.drain(timeout=120)
            with pytest.raises(RequestRejected) as ei:
                srv.submit(np.arange(3, dtype=np.int32),
                           GenerationConfig(max_new_tokens=2))
            assert ei.value.reason == "draining"
            for h in hs:
                assert h.status == "finished"
                assert len(h.result(timeout=1)) == 6
        finally:
            srv.shutdown(drain=False)

    def test_scheduler_death_fails_handles_not_hangs(self):
        """If the loop dies (engine bug, XLA error), every outstanding
        handle must reach a terminal state — clients blocked in
        result() would otherwise hang forever — and healthz-facing
        status must say 'failed'. max_restarts=0 disables supervised
        recovery so the first engine fault IS the death (the recovery
        path has its own suite: test_serving_faults.py)."""
        srv, eng, cfg = _server(segment_steps=2, max_restarts=0)
        try:
            def boom(*a, **kw):
                raise RuntimeError("injected engine fault")
            eng.decode_segment = boom
            h = srv.submit(np.arange(4, dtype=np.int32),
                           GenerationConfig(max_new_tokens=8,
                                            eos_token_id=None))
            with pytest.raises(RequestFailed, match="scheduler died"):
                h.result(timeout=60)
            assert srv.status == "failed"
            # a dead server rejects instead of queueing into the void
            with pytest.raises(RequestRejected, match="scheduler died"):
                srv.submit(np.arange(3, dtype=np.int32),
                           GenerationConfig(max_new_tokens=2))
        finally:
            srv.shutdown(drain=False)

    def test_never_fitting_request_fails_fast(self):
        # pool holds 2 pages = 16 tokens total; prompt 20 fits max_len
        # (32) but can never reserve -> FAILED, not wedged-forever
        srv, eng, cfg = _server(max_batch=2, num_pages=2, page_size=8,
                                max_pages=4)
        try:
            h = srv.submit(np.arange(20, dtype=np.int32) % cfg.vocab_size,
                           GenerationConfig(max_new_tokens=4,
                                            eos_token_id=None))
            with pytest.raises(RequestFailed, match="never"):
                h.result(timeout=60)
            # prompt too long for max_len rejects AT SUBMIT
            with pytest.raises(ValueError, match="max_len"):
                srv.submit(np.arange(40, dtype=np.int32),
                           GenerationConfig(max_new_tokens=4))
        finally:
            srv.shutdown(drain=False)


class TestHTTPFrontend:
    def test_roundtrip_health_metrics_and_streaming(self, mon):
        srv, eng, cfg = _server(max_queue=8, segment_steps=2)
        httpd = serve_http(srv)
        port = httpd.server_address[1]
        from urllib.request import Request, urlopen
        try:
            # healthz
            with urlopen(f"http://127.0.0.1:{port}/healthz",
                         timeout=30) as r:
                health = json.load(r)
            assert health["status"] == "ok"
            assert health["free_slots"] == 3
            # non-streaming round trip
            body = json.dumps({"prompt": [1, 2, 3],
                               "max_new_tokens": 5}).encode()
            with urlopen(Request(
                    f"http://127.0.0.1:{port}/generate", data=body),
                    timeout=120) as r:
                out = json.load(r)
            assert len(out["tokens"]) == out["n_tokens"] == 5
            assert out["ttft_s"] > 0
            # streaming round trip: ndjson token lines then done line
            import http.client
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=120)
            conn.request("POST", "/generate", json.dumps(
                {"prompt": [4, 5, 6], "max_new_tokens": 8,
                 "stream": True}), {"Content-Type": "application/json"})
            resp = conn.getresponse()
            lines, stamps = [], []
            while True:
                line = resp.readline()
                if not line:
                    break
                lines.append(json.loads(line))
                stamps.append(time.monotonic())
            conn.close()
            assert [ln["token"] for ln in lines[:-1]] \
                and len(lines) == 9
            assert lines[-1] == {"done": True, "status": "finished",
                                 "n_tokens": 8,
                                 "request_id": lines[-1]["request_id"]}
            # tokens arrived incrementally, not as one post-hoc blob
            assert stamps[-1] > stamps[0]
            # /metrics re-exports the monitor registry
            with urlopen(f"http://127.0.0.1:{port}/metrics",
                         timeout=30) as r:
                prom = r.read().decode()
            assert "paddle_tpu_serving_ttft_seconds_bucket" in prom
        finally:
            httpd.shutdown()
            srv.shutdown(drain=False)

    def test_error_codes(self):
        from urllib.error import HTTPError
        from urllib.request import Request, urlopen

        srv, eng, cfg = _server()
        httpd = serve_http(srv)
        port = httpd.server_address[1]
        url = f"http://127.0.0.1:{port}/generate"
        try:
            # malformed config -> 400 before anything touches the engine
            for bad in ({"prompt": [1], "temperature": 0},
                        {"prompt": [1], "max_new_tokens": 0},
                        {"prompt": [1], "top_p": 2},
                        {"prompt": []}, {"prompt": "abc"}, {}):
                with pytest.raises(HTTPError) as ei:
                    urlopen(Request(url, data=json.dumps(bad).encode()),
                            timeout=30)
                assert ei.value.code == 400
            with pytest.raises(HTTPError) as ei:
                urlopen(f"http://127.0.0.1:{port}/nope", timeout=30)
            assert ei.value.code == 404
            # streaming request that expires before its first token ->
            # a real 504, not a 200 that apologizes in the trailer
            rng = np.random.RandomState(9)
            blocker = [srv.submit(rng.randint(0, cfg.vocab_size, (4,))
                                  .astype(np.int32),
                                  GenerationConfig(max_new_tokens=48,
                                                   eos_token_id=None))
                       for _ in range(3)]
            next(iter(blocker[0].stream(timeout=60)))
            with pytest.raises(HTTPError) as ei:
                urlopen(Request(url, data=json.dumps(
                    {"prompt": [1, 2], "max_new_tokens": 4,
                     "stream": True, "timeout_s": 0.05}).encode()),
                        timeout=60)
            assert ei.value.code == 504
            for h in blocker:
                h.cancel()
            # draining -> 503 with reason
            srv.drain(timeout=60)
            with pytest.raises(HTTPError) as ei:
                urlopen(Request(url, data=json.dumps(
                    {"prompt": [1], "max_new_tokens": 2}).encode()),
                        timeout=30)
            assert ei.value.code == 503
            assert json.load(ei.value)["reason"] == "draining"
        finally:
            httpd.shutdown()
            srv.shutdown(drain=False)


@pytest.mark.slow
class TestServeBenchSoak:
    def test_open_loop_soak(self, mon, capsys, tmp_path):
        """serve_bench drives a live Server open-loop and reports
        TTFT/TPOT/throughput percentiles (the PERF.md methodology)."""
        import importlib.util
        import os

        tools_dir = os.path.join(os.path.dirname(__file__), "..",
                                 "tools")

        def load(name):
            spec = importlib.util.spec_from_file_location(
                name, os.path.join(tools_dir, f"{name}.py"))
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            return mod

        sb = load("serve_bench")
        out = tmp_path / "soak.jsonl"
        assert sb.main(["--rate", "30", "--requests", "24",
                        "--max-new", "8", "--prompt-len", "3:12",
                        "--monitor-out", str(out)]) == 0
        text = capsys.readouterr().out
        assert "serve_ttft_p50" in text and "serve_throughput" in text
        assert out.exists()
        mr = load("monitor_report")
        with open(out) as f:
            rendered = mr.render(mr.load_jsonl(f), serving=True)
        assert "paddle_tpu_serving_ttft_seconds" in rendered
