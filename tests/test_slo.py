"""SLO-aware serving observability (ISSUE 15): mergeable latency
digests, per-tenant goodput/burn, fleet /stats rollup, and the
slow-replica skew detector.

Acceptance bars covered here:

- fleet percentiles are MERGE-EXACT: the digest of N merged shards
  equals the digest of the concatenated stream (identical counters,
  identical percentiles), and both sit within one log-bucket width of
  the true order statistic on synthetic data;
- per-tenant attribution holds under a mixed LoRA batch (tenant =
  adapter name, base traffic under "-");
- the skew detector flags a FaultPlan-hang-slowed replica — SLOW but
  alive — within one rolling window while every circuit breaker stays
  CLOSED (the failure mode breakers are structurally blind to);
- every new instance-labeled SLO/skew series retires at
  ``Server.shutdown()`` / ``Router.shutdown()``;
- the disabled path records nothing (FLAGS_enable_monitor gate).
"""
import json
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor, tracing
from paddle_tpu.inference.generation import (
    GenerationConfig, PagedContinuousBatchingEngine)
from paddle_tpu.models import LlamaForCausalLM, llama_config
from paddle_tpu.monitor.slo import (ALL_TENANTS, LatencyDigest,
                                    RollingDigest, SLOPolicy,
                                    SLOTracker, fleet_rollup,
                                    tenant_key)
from paddle_tpu.serving import (ReplicaSpec, Router, Server,
                                serve_http)
from paddle_tpu.testing.faults import FaultPlan, FaultyEngine

CFG = llama_config("tiny", num_hidden_layers=1)
PROMPT = np.arange(1, 7, dtype=np.int32)
# one bucket's relative width at the default 16 buckets/decade — the
# digest's percentile-accuracy contract
BUCKET_R = 10.0 ** (1.0 / 16.0)


@pytest.fixture()
def mon():
    monitor.enable()
    monitor.reset()
    yield monitor
    monitor.reset()
    monitor.disable()


def make_engine(**kw):
    paddle.seed(0)
    kw.setdefault("max_batch", 2)
    kw.setdefault("num_pages", 24)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_pages", 12)
    return PagedContinuousBatchingEngine(LlamaForCausalLM(CFG), **kw)


def _streams(n_streams=3, n=300, seed=0):
    import random

    rng = random.Random(seed)
    return [[rng.lognormvariate(-2.5, 1.2) for _ in range(n)]
            for _ in range(n_streams)]


def _digest_of(values):
    d = LatencyDigest()
    for v in values:
        d.observe(v)
    return d


# ---------------------------------------------------------------------------
class TestLatencyDigest:
    def test_merge_equals_concatenated_stream(self):
        """THE merge invariant: digest(shard A) ⊕ digest(shard B) ⊕ …
        is bit-identical to digest(concat(A, B, …)) — counters, count,
        sum, min/max, and therefore every percentile. Fleet p99 from
        merged replica shards IS the p99 of the fleet's whole request
        stream at digest resolution."""
        streams = _streams()
        merged = LatencyDigest()
        for s in streams:
            # through the wire format, like a fleet rollup would
            merged.merge(LatencyDigest.from_dict(
                json.loads(json.dumps(_digest_of(s).to_dict()))))
        concat = _digest_of([v for s in streams for v in s])
        assert merged.counts == concat.counts
        assert merged.count == concat.count
        assert merged.min == concat.min and merged.max == concat.max
        assert merged.sum == pytest.approx(concat.sum, rel=1e-12)
        for q in (50, 90, 99):
            assert merged.percentile(q) == concat.percentile(q)

    def test_percentile_within_one_bucket_width(self):
        """The acceptance tolerance: a digest percentile sits within
        one log-bucket width (factor BUCKET_R) of the exact order
        statistic of the same stream."""
        concat = [v for s in _streams() for v in s]
        d = _digest_of(concat)
        for q in (50, 90, 99):
            exact = float(np.percentile(concat, q,
                                        method="lower"))
            est = d.percentile(q)
            assert exact / BUCKET_R <= est <= exact * BUCKET_R * 1.001, \
                (q, exact, est)

    def test_merge_config_mismatch_raises(self):
        a = LatencyDigest(buckets_per_decade=16)
        b = LatencyDigest(buckets_per_decade=8)
        with pytest.raises(ValueError, match="different configs"):
            a.merge(b)

    def test_wire_roundtrip(self):
        d = _digest_of(_streams(1)[0])
        d2 = LatencyDigest.from_dict(
            json.loads(json.dumps(d.to_dict())))
        assert d2.counts == d.counts
        assert d2.percentile(99) == d.percentile(99)
        assert d2.summary() == d.summary()

    def test_empty_and_out_of_range(self):
        d = LatencyDigest(lo=1e-3, hi=10.0)
        assert d.percentile(50) is None
        assert d.mean is None
        # under/overflow land in the open bins; min/max stay exact
        d.observe(1e-6)
        d.observe(500.0)
        assert d.count == 2
        assert d.min == 1e-6 and d.max == 500.0
        assert d.percentile(99) == 500.0   # overflow reads the max

    def test_validation(self):
        with pytest.raises(ValueError):
            LatencyDigest(lo=1.0, hi=0.5)
        with pytest.raises(ValueError):
            LatencyDigest(buckets_per_decade=0)


class TestRollingDigest:
    def test_window_expiry(self):
        r = RollingDigest(window_s=6.0, shards=3)
        r.observe(1.0, now=0.0)
        r.observe(1.0, now=1.0)
        assert r.snapshot(now=1.0).count == 2
        # inside the window: still visible
        assert r.snapshot(now=5.0).count == 2
        # a full window later: expired wholesale
        assert r.snapshot(now=20.0).count == 0

    def test_validation(self):
        with pytest.raises(ValueError):
            RollingDigest(window_s=0)


class TestSLOPolicy:
    def test_validation(self):
        with pytest.raises(ValueError, match="at least one"):
            SLOPolicy()
        with pytest.raises(ValueError):
            SLOPolicy(ttft_p99_s=-1)
        with pytest.raises(ValueError):
            SLOPolicy(ttft_p99_s=1, goodput_target=1.5)
        with pytest.raises(ValueError):
            SLOPolicy(ttft_p99_s=1, fast_window_s=100,
                      slow_window_s=10)

    def test_misses_and_burn(self):
        p = SLOPolicy(ttft_p99_s=0.5, tpot_p99_s=0.05,
                      goodput_target=0.9)
        assert p.misses(0.4, 0.04, None) == []
        assert p.misses(0.6, 0.04, None) == ["ttft"]
        assert p.misses(0.6, 0.06, None) == ["ttft", "tpot"]
        # not-applicable values are skipped, never a miss
        assert p.misses(None, None, None) == []
        # burn: miss fraction over the 10% budget
        assert p.burn_rate(9, 1) == pytest.approx(1.0)
        assert p.burn_rate(0, 10) == pytest.approx(10.0)
        assert p.burn_rate(0, 0) is None


class TestSLOTracker:
    def test_goodput_and_burn_per_tenant(self, mon):
        tr = SLOTracker(policy=SLOPolicy(ttft_p99_s=0.1,
                                         tpot_p99_s=1.0))
        for _ in range(8):
            tr.record_finish("adA", 0.05, 0.01, 0.2, 4, 1.0)
        for _ in range(2):
            tr.record_finish("adA", 0.5, 0.01, 1.0, 4, 1.0)   # ttft miss
        tr.record_finish(None, 0.05, 0.01, 0.2, 4, 0.0)
        assert tr.goodput("adA") == pytest.approx(0.8)
        assert tr.goodput(tenant_key(None)) == 1.0
        stats = tr.tenant_stats()
        assert stats["adA"]["requests"] == 10
        assert stats["adA"]["tokens"] == 40
        assert stats["adA"]["kv_page_seconds"] == pytest.approx(10.0)
        assert stats["adA"]["burn_fast"] == pytest.approx(
            0.2 / 0.01, rel=1e-6)   # 20% miss over a 1% budget
        assert stats["-"]["goodput"] == 1.0
        per = tr.percentiles()
        assert per["tpot"]["adA"]["count"] == 10
        assert per["tpot"][ALL_TENANTS]["count"] == 11
        assert tr.rolling_tpot_p50() is not None

    def test_failure_is_a_miss(self, mon):
        tr = SLOTracker(policy=SLOPolicy(ttft_p99_s=10))
        tr.record_finish("adA", 0.1, 0.01, 0.2, 4)
        tr.record_failure("adA")
        assert tr.goodput("adA") == pytest.approx(0.5)
        assert tr.tenant_stats()["adA"]["failed"] == 1

    def test_disabled_path_records_nothing(self):
        monitor.disable()
        tr = SLOTracker(policy=SLOPolicy(ttft_p99_s=1))
        tr.observe("ttft", "adA", 0.1)
        tr.record_finish("adA", 0.1, 0.01, 0.2, 4, 1.0)
        tr.record_failure("adA")
        assert tr.tenant_stats() == {}
        assert tr.percentiles() == {}
        assert tr.snapshot() is None
        assert tr.rolling_tpot_p50() is None

    def test_policy_free_tracker_digests_and_costs(self, mon):
        tr = SLOTracker()   # no policy: digests + cost, no goodput
        tr.record_finish("adA", 0.1, 0.01, 0.2, 4, 2.0)
        assert tr.goodput("adA") is None
        st = tr.tenant_stats()
        assert st["adA"]["tokens"] == 4
        assert "goodput" not in st["adA"]
        assert tr.percentiles()["tpot"]["adA"]["count"] == 1


class TestFleetRollup:
    def test_fleet_percentile_merge_exact(self, mon):
        """ISSUE acceptance: fleet p99 from merged per-replica shards
        == p99 of the concatenated synthetic stream (digest-identical),
        and within one bucket width of the exact order statistic."""
        streams = _streams()
        trackers = [SLOTracker(policy=SLOPolicy(tpot_p99_s=0.05))
                    for _ in streams]
        for tr, s in zip(trackers, streams):
            for v in s:
                tr.record_finish("adA", 0.01, v, v * 2, 4, 0.0)
        roll = fleet_rollup([json.loads(json.dumps(tr.digests_dict()))
                             for tr in trackers])
        concat = [v for s in streams for v in s]
        exact_digest = _digest_of(concat)
        agg = roll["metrics"]["tpot"][ALL_TENANTS]
        assert agg["count"] == len(concat)
        for q, key in ((50, "p50"), (90, "p90"), (99, "p99")):
            assert agg[key] == pytest.approx(
                round(exact_digest.percentile(q), 6))
            true = float(np.percentile(concat, q, method="lower"))
            assert true / BUCKET_R <= agg[key] <= true * BUCKET_R * 1.001
        # goodput merges by SUMMING counters, not averaging rates
        met = sum(1 for v in concat if v <= 0.05)
        assert roll["tenants"]["adA"]["goodput"] == pytest.approx(
            round(met / len(concat), 4))

    def test_empty_and_single_shard(self, mon):
        assert fleet_rollup([])["tenants"] == {}
        tr = SLOTracker(policy=SLOPolicy(ttft_p99_s=1))
        tr.record_finish("t", 0.1, 0.01, 0.2, 4)
        one = fleet_rollup([tr.digests_dict()])
        assert one["tenants"]["t"]["requests"] == 1
        assert one["policy"]["ttft_p99_s"] == 1


# ---------------------------------------------------------------------------
class TestServerSLO:
    def test_mixed_lora_batch_attribution(self, mon):
        """Per-tenant attribution under a MIXED LoRA batch: tenant
        defaults to the adapter name (PR 13), base rides "-"; the slo
        block lands in load()/healthz, GET /stats serves the rollup,
        and every SLO/cost series retires at shutdown."""
        eng = make_engine(lora_capacity=2, lora_rank=2)
        srv = Server(eng, segment_steps=4, idle_wait_s=0.005,
                     slo_policy=SLOPolicy(ttft_p99_s=60.0,
                                          tpot_p99_s=60.0))
        httpd = None
        try:
            shapes = eng.adapters.shapes
            rng = np.random.default_rng(0)
            for name in ("adA", "adB"):
                params = {
                    t: (rng.standard_normal((2, di)).astype(np.float32)
                        * 0.05,
                        rng.standard_normal((do, 2)).astype(np.float32)
                        * 0.05)
                    for t, (di, do) in shapes.items()}
                srv.load_adapter(name, params)
            mix = ["adA", "adA", "adB", None, None, None]
            handles = [srv.submit(PROMPT, GenerationConfig(
                max_new_tokens=4, eos_token_id=None, adapter=a))
                for a in mix]
            for h in handles:
                h.result(timeout=120)
            # attribution: the drawn mix, exactly
            stats = srv.stats()
            tens = stats["tenants"]
            assert tens["adA"]["requests"] == 2
            assert tens["adB"]["requests"] == 1
            assert tens["-"]["requests"] == 3
            assert tens["adA"]["tokens"] == 8
            assert tens["adA"]["goodput"] == 1.0
            assert tens["adA"]["kv_page_seconds"] > 0
            # digests carry every latency family per tenant + "*"
            mets = stats["metrics"]
            for metric in ("ttft", "tpot", "queue_wait", "e2e"):
                assert mets[metric][ALL_TENANTS]["count"] == 6, metric
            assert mets["ttft"]["adA"]["count"] == 2
            # healthz carries the compact slo block
            snap = srv.load()
            assert snap["slo"]["tenants"]["adB"]["goodput"] == 1.0
            assert snap["slo"]["policy"]["ttft_p99_s"] == 60.0
            # HTTP GET /stats round-trip (the same payload)
            httpd = serve_http(srv)
            port = httpd.server_address[1]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/stats",
                    timeout=10) as resp:
                assert resp.status == 200
                body = json.loads(resp.read())
            assert body["tenants"]["adA"]["requests"] == 2
            assert body["server"] == srv.monitor_server
        finally:
            if httpd is not None:
                httpd.shutdown()
            srv.shutdown()
            eng.close()
        # series-lifecycle bar (PT003): nothing labeled with this
        # server survives shutdown — goodput gauge, miss counters,
        # tenant token/kv-cost counters included
        leaked = []
        for name, meta in monitor.snapshot()["metrics"].items():
            for s in meta["samples"]:
                if s["labels"].get("server") == srv.monitor_server:
                    leaked.append((name, s["labels"]))
        assert leaked == [], leaked

    def test_tight_policy_scores_misses(self, mon):
        """A policy no CPU run can meet: goodput 0, burn >> 1, and the
        per-dimension miss counters move."""
        eng = make_engine()
        srv = Server(eng, segment_steps=4, idle_wait_s=0.005,
                     slo_policy=SLOPolicy(ttft_p99_s=1e-9,
                                          tpot_p99_s=1e-9,
                                          goodput_target=0.5))
        try:
            for _ in range(3):
                srv.submit(PROMPT, GenerationConfig(
                    max_new_tokens=4,
                    eos_token_id=None)).result(timeout=120)
            assert srv.slo.goodput(None) == 0.0
            ts = srv.stats()["tenants"]["-"]
            assert ts["missed"] == 3 and ts["met"] == 0
            assert ts["burn_fast"] == pytest.approx(2.0)   # 100% / 50%
            c = monitor.counter(
                "paddle_tpu_serving_slo_misses_total", "",
                ("server", "tenant", "slo"))
            assert c.labels(server=srv.monitor_server, tenant="-",
                            slo="ttft").value == 3
            g = monitor.gauge("paddle_tpu_serving_goodput", "",
                              ("server", "tenant"))
            assert g.labels(server=srv.monitor_server,
                            tenant="-").value == 0.0
        finally:
            srv.shutdown()
            eng.close()

    def test_slo_policy_validation(self, mon):
        eng = make_engine()
        try:
            with pytest.raises(ValueError, match="slo_policy"):
                Server(eng, start=False, slo_policy="tight")
        finally:
            eng.close()


# ---------------------------------------------------------------------------
def _fleet_kwargs(warmup=False):
    return {"segment_steps": 2, "idle_wait_s": 0.005,
            "warmup": warmup,
            "slo_policy": SLOPolicy(ttft_p99_s=60.0, tpot_p99_s=60.0)}


class TestRouterSLO:
    def test_stats_is_merge_exact_and_slow_routes_last(self, mon):
        specs = [ReplicaSpec(make_engine,
                             server_kwargs=_fleet_kwargs())
                 for _ in range(2)]
        router = Router(specs, skew_interval_s=30.0)
        try:
            router.wait_ready()
            for _ in range(6):
                router.submit(PROMPT, GenerationConfig(
                    max_new_tokens=4,
                    eos_token_id=None)).result(timeout=120)
            st = router.stats()
            # fleet count == sum over replicas; the rollup of the
            # replicas' own shards reproduces /stats EXACTLY. (A
            # replica the least-loaded tiebreak starved contributes an
            # EMPTY metrics block, not a missing one — sequential
            # submits against an idle fleet all land on the first
            # candidate, which is itself worth pinning here.)
            per_rep = [e.get("metrics", {}).get("ttft", {})
                       .get(ALL_TENANTS, {}).get("count", 0)
                       for e in st["replicas"]]
            agg = st["metrics"]["ttft"][ALL_TENANTS]
            assert agg["count"] == sum(per_rep) == 6
            manual = fleet_rollup(
                [rep.server.slo.digests_dict()
                 for rep in router._replicas])
            assert manual["metrics"]["ttft"][ALL_TENANTS] == agg
            assert st["tenants"]["-"]["goodput"] == 1.0
            assert st["skew"]["slow_replicas"] == []
            # a SLOW replica scores behind every non-slow candidate
            # (but stays routable — slow != open breaker)
            with router._lock:
                router._replicas[0].slow = True
            h = router.submit(PROMPT, GenerationConfig(
                max_new_tokens=4, eos_token_id=None))
            h.result(timeout=120)
            assert h.replica == 1
            assert router.load()["slow_replicas"] == [0]
        finally:
            router.shutdown()
        leaked = []
        for name, meta in monitor.snapshot()["metrics"].items():
            for s in meta["samples"]:
                if s["labels"].get("router") == router.monitor_router:
                    leaked.append((name, s["labels"]))
        assert leaked == [], leaked

    @pytest.mark.parametrize("n_replicas", [2, 3])
    def test_skew_detector_flags_hang_slowed_replica(self, mon,
                                                     tmp_path,
                                                     n_replicas):
        """ISSUE acceptance: a FaultPlan-hang-slowed replica — every
        decode_segment stalls 120 ms, but every request SUCCEEDS — is
        flagged SLOW within one rolling window while every breaker
        stays CLOSED and every status stays ok. This is the replica
        the breaker machinery cannot see: zero failures, all latency.
        The flip also dumps the flight recorder (tracing on).
        Parametrized down to the 2-REPLICA fleet: the leave-one-out
        baseline keeps the smallest fleet detectable (a global median
        over two would be the mean of both — unreachable at
        factor >= 2)."""
        plan = FaultPlan()

        def slow_factory():
            plan.hang_at("decode", nth=1, seconds=0.12, times=2 ** 31)
            return FaultyEngine(make_engine(), plan)

        # warmup=True: a cold replica's first-request prefill compiles
        # would inflate ITS TPOT by seconds and drown the injected
        # 120 ms skew in compile noise
        specs = [ReplicaSpec(slow_factory,
                             server_kwargs=_fleet_kwargs(warmup=True))
                 ] + [
            ReplicaSpec(make_engine,
                        server_kwargs=_fleet_kwargs(warmup=True))
            for _ in range(n_replicas - 1)]
        tracing.configure(dump_dir=str(tmp_path))
        tracing.enable()
        router = Router(specs, skew_factor=2.0, skew_min_requests=2,
                        skew_interval_s=0.2, monitor_interval_s=0.05)
        try:
            router.wait_ready()
            # drive traffic straight into each replica Server: the
            # detector reads the TRACKERS, and least-loaded routing
            # would starve the hung replica of the samples it needs
            # to be judged (everything piles onto the fast ones —
            # which is correct routing, but a nondeterministic load
            # shape for this test)
            for rep in router._replicas:
                handles = [rep.server.submit(PROMPT, GenerationConfig(
                    max_new_tokens=6, eos_token_id=None))
                    for _ in range(3)]
                for h in handles:
                    h.result(timeout=120)
            deadline = time.monotonic() + 15.0
            flagged = None
            while time.monotonic() < deadline:
                slow = router.load()["slow_replicas"]
                if slow:
                    flagged = slow
                    break
                time.sleep(0.1)
            assert flagged == [0], (
                f"skew detector never flagged the hang-slowed replica "
                f"(got {flagged!r})")
            snap = router.load()
            for e in snap["replicas"]:
                # slow-but-ALIVE: breakers closed, statuses ok — the
                # skew verdict is orthogonal to the failure machinery
                assert e["breaker"]["state"] == "closed", e
                assert e["status"] == "ok", e
            assert snap["replicas"][0]["slow"] is True
            st = router.stats()
            assert st["skew"]["slow_replicas"] == [0]
            p50s = {e["replica"]: e.get("tpot_p50_s")
                    for e in st["replicas"]}
            assert p50s[0] is not None
            # the detector's own criterion (leave-one-out median of
            # the PEERS' p50s), re-derived from /stats
            import statistics
            vals = [v for i, v in p50s.items()
                    if i != 0 and v is not None]
            assert p50s[0] > 2.0 * statistics.median(vals)
            # the flip dumped the black box. The flag is set (under
            # the router lock) BEFORE the monitor thread writes the
            # dump file, so a poll that caught the flag the instant it
            # flipped may be microseconds ahead of the dump — wait it
            # out, bounded.
            dump_deadline = time.monotonic() + 5.0
            while (not router.flight_dumps
                   and time.monotonic() < dump_deadline):
                time.sleep(0.05)
            assert router.flight_dumps, \
                "slow flip should write a flight-recorder dump"
            assert "replica_slow_0" in router.flight_dumps[-1]
            # the gauge reads 1 for the slow replica
            g = monitor.gauge("paddle_tpu_router_replica_slow", "",
                              ("router", "replica"))
            assert g.labels(router=router.monitor_router,
                            replica="0").value == 1
        finally:
            plan.release_hangs()
            router.shutdown()
            tracing.disable()
            tracing.clear()

    def test_skew_knob_validation(self, mon):
        spec = ReplicaSpec(make_engine,
                           server_kwargs={"segment_steps": 2})
        with pytest.raises(ValueError, match="skew_factor"):
            Router(spec, skew_factor=1.0, start=False).shutdown(
                drain=False)
        with pytest.raises(ValueError, match="skew_min_requests"):
            Router(spec, skew_min_requests=0, start=False).shutdown(
                drain=False)
