"""paddle.sparse.nn tests (VERDICT r2 sparse-depth gap).

Reference contract (python/paddle/sparse/nn): activations preserve
structure, softmax normalizes over PRESENT entries only, BatchNorm
normalizes value channels over active elements, convs/pool keep sparse
in/out, SubmConv keeps the input's active sites.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu.sparse as sparse
from paddle_tpu.sparse import nn as snn


def coo_2d():
    # [[0, 2, 0], [-3, 0, 4]]
    return sparse.sparse_coo_tensor(
        np.asarray([[0, 1, 1], [1, 0, 2]]),
        np.asarray([2.0, -3.0, 4.0], np.float32), shape=(2, 3))


class TestActivations:
    def test_relu_structure_preserved(self):
        out = snn.ReLU()(coo_2d())
        assert out.is_sparse_coo()
        np.testing.assert_allclose(np.asarray(out.to_dense().value),
                                   [[0, 2, 0], [0, 0, 4]])

    def test_relu6(self):
        x = sparse.sparse_coo_tensor(np.asarray([[0], [0]]),
                                     np.asarray([9.0], np.float32), (1, 1))
        out = snn.ReLU6()(x)
        assert float(np.asarray(out.to_dense().value)[0, 0]) == 6.0

    def test_leaky_relu(self):
        out = snn.LeakyReLU(0.1)(coo_2d())
        np.testing.assert_allclose(np.asarray(out.to_dense().value),
                                   [[0, 2, 0], [-0.3, 0, 4]], rtol=1e-6)


class TestSoftmax:
    def test_present_entries_only(self):
        """Missing entries are -inf, NOT zero: row [0, 2, 0] with one
        present entry softmaxes to 1.0 at that entry."""
        out = snn.Softmax()(coo_2d())
        d = np.asarray(out.to_dense().value)
        np.testing.assert_allclose(d[0], [0, 1.0, 0], atol=1e-6)
        # row 1 has entries -3 and 4 at cols 0, 2
        e = np.exp([-3.0 - 4.0, 0.0])  # shifted by max
        np.testing.assert_allclose(d[1], [e[0] / e.sum(), 0,
                                          e[1] / e.sum()], rtol=1e-5)


class TestBatchNorm:
    def test_normalizes_active_values_only(self):
        # 3 active sites with C=4 channel vectors
        vals = np.random.RandomState(0).randn(3, 4).astype(np.float32) * 5
        x = sparse.sparse_coo_tensor(np.asarray([[0, 2, 5]]), vals,
                                     shape=(8, 4))
        bn = snn.BatchNorm(4)
        out = bn(x)
        got = np.asarray(out.values().value)
        np.testing.assert_allclose(got.mean(0), 0.0, atol=1e-5)
        np.testing.assert_allclose(got.std(0), 1.0, atol=1e-2)
        # structure untouched
        np.testing.assert_array_equal(
            np.asarray(out.indices().value), [[0, 2, 5]])

    def test_sync_variant_same_math(self):
        vals = np.random.RandomState(1).randn(4, 2).astype(np.float32)
        x = sparse.sparse_coo_tensor(np.asarray([[0, 1, 2, 3]]), vals,
                                     shape=(4, 2))
        a = snn.BatchNorm(2)(x)
        b = snn.SyncBatchNorm(2)(x)
        np.testing.assert_allclose(np.asarray(a.values().value),
                                   np.asarray(b.values().value), rtol=1e-6)


class TestConvPool:
    def test_conv3d_matches_dense(self):
        rng = np.random.RandomState(0)
        dense = rng.randn(1, 4, 4, 4, 2).astype(np.float32)
        dense[dense < 0.5] = 0  # sparsify
        x = sparse.SparseTensor(
            jax.experimental.sparse.BCOO.fromdense(jnp.asarray(dense),
                                                   n_dense=1))
        conv = snn.Conv3D(2, 3, kernel_size=2, bias_attr=False)
        out = conv(x)
        ref = jax.lax.conv_general_dilated(
            jnp.asarray(dense), conv.weight.value, (1, 1, 1), [(0, 0)] * 3,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        np.testing.assert_allclose(np.asarray(out.to_dense().value),
                                   np.asarray(ref), rtol=1e-4, atol=1e-5)

    def test_subm_conv_keeps_active_sites(self):
        rng = np.random.RandomState(0)
        dense = np.zeros((1, 4, 4, 1), np.float32)
        dense[0, 1, 1, 0] = 1.0
        dense[0, 2, 3, 0] = 2.0
        x = sparse.SparseTensor(
            jax.experimental.sparse.BCOO.fromdense(jnp.asarray(dense),
                                                   n_dense=1))
        conv = snn.SubmConv2D(1, 1, kernel_size=3, bias_attr=False)
        out = np.asarray(conv(x).to_dense().value)
        active = (dense != 0).any(-1)
        assert (out[~active] == 0).all()   # submanifold: no dilation

    def test_max_pool3d(self):
        dense = np.zeros((1, 2, 2, 2, 1), np.float32)
        dense[0, 0, 0, 0, 0] = 3.0
        dense[0, 1, 1, 1, 0] = 5.0
        x = sparse.SparseTensor(
            jax.experimental.sparse.BCOO.fromdense(jnp.asarray(dense),
                                                   n_dense=1))
        out = snn.MaxPool3D(kernel_size=2)(x)
        np.testing.assert_allclose(
            np.asarray(out.to_dense().value).ravel(), [5.0])


class TestReviewRegressions:
    def test_softmax_preserves_csr(self):
        x = sparse.sparse_csr_tensor(np.asarray([0, 1, 3]),
                                     np.asarray([1, 0, 2]),
                                     np.asarray([1.0, 2.0, 3.0], np.float32),
                                     (2, 3))
        out = snn.Softmax()(x)
        assert out.is_sparse_csr()

    def test_subm_stride_raises(self):
        with pytest.raises(ValueError, match="stride 1"):
            snn.functional.subm_conv2d(coo_2d(), np.zeros((1, 1, 1, 1)),
                                       stride=2)

    def test_maxpool_list_padding(self):
        dense = np.ones((1, 2, 2, 2, 1), np.float32)
        x = sparse.SparseTensor(
            jax.experimental.sparse.BCOO.fromdense(jnp.asarray(dense),
                                                   n_dense=1))
        out = snn.MaxPool3D(kernel_size=2, padding=[1, 1, 1])(x)
        # stride defaults to kernel: (2 + 2*1 - 2)//2 + 1 = 2 per dim
        assert np.asarray(out.to_dense().value).shape == (1, 2, 2, 2, 1)

    def test_conv_weights_reproducible_with_seed(self):
        import paddle_tpu as paddle

        paddle.seed(123)
        w1 = np.asarray(snn.Conv3D(2, 3, 2).weight.value)
        paddle.seed(123)
        w2 = np.asarray(snn.Conv3D(2, 3, 2).weight.value)
        np.testing.assert_array_equal(w1, w2)


class TestSubmGatherGEMM:
    """True sparse path (VERDICT r3 #4): gather-GEMM submanifold conv must
    match the dense lowering on random sparse inputs AND never materialize
    the dense volume (128^3 at ~0.5% density)."""

    def _random_sparse(self, rng, shape_sp, cin, density, nd):
        # unique random active coords, NONZERO channel vectors
        n_total = int(np.prod(shape_sp))
        nnz = max(4, int(n_total * density))
        flat = rng.choice(n_total, size=nnz, replace=False)
        coords = np.stack(np.unravel_index(flat, shape_sp), axis=1)
        coords = np.concatenate(
            [np.zeros((nnz, 1), np.int64), coords], axis=1)  # batch 0
        vals = rng.randn(nnz, cin).astype(np.float32) + 0.1
        dense = np.zeros((1,) + shape_sp + (cin,), np.float32)
        dense[tuple(coords.T)] = vals
        bcoo = jax.experimental.sparse.BCOO(
            (jnp.asarray(vals), jnp.asarray(coords)),
            shape=(1,) + shape_sp + (cin,))
        return sparse.SparseTensor(bcoo), dense

    def _dense_ref(self, dense, conv, nd):
        out = jax.lax.conv_general_dilated(
            jnp.asarray(dense), conv.weight.value, (1,) * nd, "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC") if nd == 2
            else ("NDHWC", "DHWIO", "NDHWC"))
        if conv.bias is not None:
            out = out + conv.bias.value
        active = (dense != 0).any(-1, keepdims=True)
        return np.asarray(jnp.where(active, out, 0))

    def test_parity_3d_random(self):
        rng = np.random.RandomState(7)
        x, dense = self._random_sparse(rng, (6, 7, 5), cin=3,
                                       density=0.15, nd=3)
        conv = snn.SubmConv3D(3, 4, kernel_size=3)
        out = np.asarray(conv(x).to_dense().value)
        ref = self._dense_ref(dense, conv, 3)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_parity_2d_even_kernel(self):
        rng = np.random.RandomState(8)
        x, dense = self._random_sparse(rng, (9, 8), cin=2,
                                       density=0.2, nd=2)
        conv = snn.SubmConv2D(2, 3, kernel_size=2, bias_attr=False)
        out = np.asarray(conv(x).to_dense().value)
        ref = self._dense_ref(dense, conv, 2)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_parity_2d_dilation(self):
        rng = np.random.RandomState(9)
        x, dense = self._random_sparse(rng, (10, 10), cin=2,
                                       density=0.2, nd=2)
        conv = snn.SubmConv2D(2, 2, kernel_size=3, bias_attr=False,
                              dilation=2)
        out = np.asarray(conv(x).to_dense().value)
        ref = np.asarray(jax.lax.conv_general_dilated(
            jnp.asarray(dense), conv.weight.value, (1, 1), "SAME",
            rhs_dilation=(2, 2),
            dimension_numbers=("NHWC", "HWIO", "NHWC")))
        active = (dense != 0).any(-1, keepdims=True)
        ref = np.asarray(jnp.where(jnp.asarray(active), ref, 0))
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_grad_flows_through_gather_gemm(self):
        rng = np.random.RandomState(10)
        x, _ = self._random_sparse(rng, (5, 5), cin=2, density=0.2, nd=2)
        conv = snn.SubmConv2D(2, 2, kernel_size=3, bias_attr=False)

        def loss(w):
            out = snn.functional.subm_conv2d(x, w)
            return jnp.sum(out._value.data ** 2)

        g = jax.grad(loss)(conv.weight.value)
        assert float(jnp.max(jnp.abs(g))) > 0

    def test_128cubed_never_densifies(self):
        """128^3 grid at ~0.5% density: compiled temp memory must be a
        small multiple of the nnz working set — orders of magnitude under
        the 128^3 dense volume the old lowering materialized."""
        rng = np.random.RandomState(11)
        grid, cin, cout = (128, 128, 128), 4, 4
        nnz = int(np.prod(grid) * 0.005)          # ~10k sites
        flat = rng.choice(np.prod(grid), size=nnz, replace=False)
        coords = np.stack(np.unravel_index(flat, grid), axis=1)
        coords = np.concatenate(
            [np.zeros((nnz, 1), np.int64), coords], axis=1)
        vals = rng.randn(nnz, cin).astype(np.float32)
        bcoo = jax.experimental.sparse.BCOO(
            (jnp.asarray(vals), jnp.asarray(coords)),
            shape=(1,) + grid + (cin,))
        w = jnp.asarray(rng.randn(3, 3, 3, cin, cout).astype(np.float32))

        def f(data, w):
            v = jax.experimental.sparse.BCOO(
                (data, jnp.asarray(coords)), shape=(1,) + grid + (cin,))
            return snn._subm_gather_gemm(v, w, None, 1, 3).values().value

        c = jax.jit(f).lower(jnp.asarray(vals), w).compile()
        tmp = c.memory_analysis().temp_size_in_bytes
        dense_out = int(np.prod(grid)) * cout * 4        # 33.5 MB
        dense_in = int(np.prod(grid)) * cin * 4          # 33.5 MB
        # measured temp: 9.06 MB = the K·nnz·C gather working set
        # (27 x 10485 x 4ch x 4B ~ 4.5MB, ~2x for einsum operands) —
        # the old dense lowering materialized input + output + conv
        # temps >= 67 MB, and the gap grows as grid^3 while this path
        # stays nnz-bound
        assert tmp < (dense_in + dense_out) // 4, (tmp, dense_in + dense_out)


class TestDensifyGuard:
    """The strided-conv/pool dense fallbacks must announce themselves at
    runtime above a volume threshold (VERDICT r4 Weak #4 / #8): warn by
    default, refuse under PADDLE_TPU_SPARSE_DENSIFY=error, stay silent
    under =silent and below the threshold."""

    def _big_coo(self, shape=(1, 40, 40, 40, 2)):
        d = np.zeros(shape, np.float32)
        d[0, 0, 0, 0, 0] = 1.0
        d[0, 3, 5, 7, 1] = 2.0
        return sparse.sparse_coo_tensor_from_dense(d) if hasattr(
            sparse, "sparse_coo_tensor_from_dense") else \
            sparse.SparseTensor(
                jax.experimental.sparse.BCOO.fromdense(
                    jnp.asarray(d), n_batch=0, n_dense=1))

    def test_warns_above_threshold(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_SPARSE_DENSIFY_WARN_ELEMS", "1000")
        x = self._big_coo()
        w = np.random.RandomState(0).randn(2, 2, 2, 2, 3).astype(np.float32)
        with pytest.warns(RuntimeWarning, match="DENSE.*volume"):
            snn.functional.conv3d(x, w, stride=2)
        with pytest.warns(RuntimeWarning, match="max_pool3d"):
            snn.functional.max_pool3d(x, 2, stride=2)

    def test_error_mode_refuses(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_SPARSE_DENSIFY_WARN_ELEMS", "1000")
        monkeypatch.setenv("PADDLE_TPU_SPARSE_DENSIFY", "error")
        x = self._big_coo()
        w = np.random.RandomState(0).randn(2, 2, 2, 2, 3).astype(np.float32)
        with pytest.raises(ValueError, match="DENSE.*volume"):
            snn.functional.conv3d(x, w, stride=2)

    def test_below_threshold_and_silent_are_quiet(self, monkeypatch):
        import warnings as _w

        x = self._big_coo()          # 128k elements < default 2^24
        w = np.random.RandomState(0).randn(2, 2, 2, 2, 3).astype(np.float32)
        with _w.catch_warnings():
            _w.simplefilter("error", RuntimeWarning)
            snn.functional.conv3d(x, w, stride=2)   # no warning
        monkeypatch.setenv("PADDLE_TPU_SPARSE_DENSIFY_WARN_ELEMS", "1000")
        monkeypatch.setenv("PADDLE_TPU_SPARSE_DENSIFY", "silent")
        with _w.catch_warnings():
            _w.simplefilter("error", RuntimeWarning)
            snn.functional.conv3d(x, w, stride=2)   # acknowledged

    def test_subm_gather_gemm_path_never_guarded(self):
        """The REAL sparse path (submanifold gather-GEMM) must not warn
        at any size — it never densifies."""
        import warnings as _w

        x = self._big_coo()
        w = np.random.RandomState(0).randn(2, 2, 2, 2, 3).astype(np.float32)
        with _w.catch_warnings():
            _w.simplefilter("error", RuntimeWarning)
            snn.functional.subm_conv3d(x, w)        # gather-GEMM route
