"""Bounded-compile bucketed + chunked prefill (ISSUE-3).

Covers the two acceptance demos — (a) >= 6 distinct prompt lengths
compile at most len(buckets) prefill programs with tokens identical to
the unbucketed engine, (b) a long prompt admitted in >= 4 chunks during
active decoding interleaves decode segments between chunks and matches
single-shot prefill — plus the bitwise parity contracts they rest on
(padded-bucket and chunked prefill reproduce exact prefill logits AND
KV bit for bit, dense and paged), warmup (no request-path compiles
after ``Server(warmup=True)``), and the heap free-list determinism.
"""
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.inference.generation import (CausalLMEngine,
                                             ContinuousBatchingEngine,
                                             GenerationConfig,
                                             PagedContinuousBatchingEngine,
                                             prefill_buckets_for)
from paddle_tpu.models import LlamaForCausalLM, llama_config
from paddle_tpu.serving import Server, serve_http


def tiny_model(layers=2, seed=0, **cfg_kw):
    paddle.seed(seed)
    cfg = llama_config("tiny", num_hidden_layers=layers, **cfg_kw)
    return LlamaForCausalLM(cfg), cfg


@pytest.fixture()
def mon():
    monitor.enable()
    monitor.reset()
    yield monitor
    monitor.reset()
    monitor.disable()


def _jit_misses():
    # summed per entry point: the counter carries ("fn", "program")
    # since the ledger split, and one fn compiles many programs
    samples = monitor.snapshot()["metrics"].get(
        "paddle_tpu_jit_cache_miss_total", {}).get("samples", [])
    out = {}
    for s in samples:
        fn = s["labels"]["fn"]
        out[fn] = out.get(fn, 0) + int(s["value"])
    return out


def _val(x):
    return np.asarray(getattr(x, "value", x))


class TestBucketSpec:
    def test_auto_powers_of_two(self):
        assert prefill_buckets_for("auto", 256) == (16, 32, 64, 128, 256)
        assert prefill_buckets_for("auto", 48) == (16, 32, 48)
        assert prefill_buckets_for("auto", 8) == (8,)

    def test_explicit_extended_to_max_len(self):
        # every admissible prompt must land in SOME bucket
        assert prefill_buckets_for([8, 24], 64) == (8, 24, 64)
        assert prefill_buckets_for((32, 8, 8), 32) == (8, 32)

    def test_disabled_and_invalid(self):
        assert prefill_buckets_for(None, 64) is None
        with pytest.raises(ValueError, match="max_len"):
            prefill_buckets_for([128], 64)
        with pytest.raises(ValueError, match="positive"):
            prefill_buckets_for([0, 8], 64)

    def test_engine_knob_validation(self):
        model, _ = tiny_model(layers=1)
        with pytest.raises(ValueError, match="prefill_chunk"):
            ContinuousBatchingEngine(model, max_batch=1, max_len=32,
                                     prefill_chunk=0)
        # a chunk that doesn't divide max_len would let a final chunk
        # window overhang the cache, where dynamic_update_slice CLAMPS
        # and silently overwrites earlier prompt KV — rejected up front
        with pytest.raises(ValueError, match="multiple"):
            ContinuousBatchingEngine(model, max_batch=1, max_len=100,
                                     prefill_chunk=64)


class TestPrefillParityBitwise:
    """Padded-bucket and chunked prefill must reproduce EXACT prefill —
    last-position logits and the KV written for real positions — bit
    for bit (ops/pallas.prefix_chunk_attention shares the one-shot
    flash fallback's reduction structure; masked pad columns contribute
    exact float zeros). Driven through the engines' OWN jitted prefill
    programs (the production path, and fast — eager model calls are
    minutes-scale here); two layers so layer-2 KV also covers attention
    -output propagation."""

    def _kv_prefix(self, caches, plen):
        return [(_val(k)[:, :plen], _val(v)[:, :plen])
                for k, v in caches]

    def _exact(self, eng, ids, plen):
        import jax.numpy as jnp

        logits, caches = eng._prefill(eng.params, ids,
                                      eng.model.init_cache(1, 64),
                                      jnp.int32(plen - 1))
        return _val(logits), caches

    @pytest.mark.parametrize("kv_heads", [4, 2])
    def test_padded_and_chunked_prefill_bitwise(self, kv_heads):
        import jax.numpy as jnp

        model, cfg = tiny_model(num_key_value_heads=kv_heads)
        eng = CausalLMEngine(model, max_batch=1, max_len=64,
                             prefill_buckets=None, prefill_chunk=4)
        ids = np.random.RandomState(0).randint(
            0, cfg.vocab_size, (1, 13)).astype(np.int32)
        want_logits, want_caches = self._exact(eng, ids, 13)
        want_kv = self._kv_prefix(want_caches, 13)

        # padded to bucket 16, same program, last_idx still 12
        padded = np.pad(ids, ((0, 0), (0, 3)))
        got_logits, got_caches = self._exact(eng, padded, 13)
        np.testing.assert_array_equal(want_logits, got_logits)
        for (wk, wv), (gk, gv) in zip(want_kv,
                                      self._kv_prefix(got_caches, 13)):
            np.testing.assert_array_equal(wk, gk)
            np.testing.assert_array_equal(wv, gv)

        # chunked: 4-token chunks at traced offsets (the
        # prefix_chunk_attention path), ONE compiled program
        caches = model.init_cache(1, 64)
        pos, C = 0, 4
        while pos < 13:
            chunk = ids[:, pos:pos + C]
            r = chunk.shape[1]
            if r < C:
                chunk = np.pad(chunk, ((0, 0), (0, C - r)))
            logits, caches = eng._prefill_chunk(
                eng.params, chunk, caches, jnp.int32(pos),
                jnp.int32(r - 1))
            pos += C
        np.testing.assert_array_equal(want_logits, _val(logits))
        for (wk, wv), (gk, gv) in zip(want_kv,
                                      self._kv_prefix(caches, 13)):
            np.testing.assert_array_equal(wk, gk)
            np.testing.assert_array_equal(wv, gv)

    def test_dense_engine_generate_parity(self):
        # bucketed-program parity is the bitwise test above; here the
        # offline engine's CHUNKED generate path (shared by speculative
        # prefill) must reproduce exact generate end to end
        model, cfg = tiny_model(layers=1)
        ids = np.random.RandomState(2).randint(
            0, cfg.vocab_size, (2, 11)).astype(np.int32)
        gc = GenerationConfig(max_new_tokens=6)
        want = CausalLMEngine(model, max_batch=2, max_len=64,
                              prefill_buckets=None).generate(ids, gc)
        chunked = CausalLMEngine(model, max_batch=2, max_len=64,
                                 prefill_chunk=4)
        np.testing.assert_array_equal(want, chunked.generate(ids, gc))


PLENS = (3, 5, 9, 12, 17, 30)   # spans buckets 16/16/16/16/32/32
_REF = {}                       # memoized unbucketed reference outputs


def _serve(eng, prompts, gc):
    return [list(o) for o in eng.serve(prompts, gc, segment_steps=4)]


def _prompts(cfg):
    rng = np.random.RandomState(3)
    return [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
            for n in PLENS]


def _reference(model, cfg):
    """Unbucketed (exact-length prefill) engine outputs — the parity
    target for both the dense and paged bucketed engines (their outputs
    are byte-identical, asserted by PR 2's engine tests)."""
    if "want" not in _REF:
        gc = GenerationConfig(max_new_tokens=6, eos_token_id=None)
        _REF["want"] = _serve(ContinuousBatchingEngine(
            model, max_batch=3, max_len=64, prefill_buckets=None),
            _prompts(cfg), gc)
    return _REF["want"]


class TestBoundedCompile:
    """ISSUE-3 acceptance: >= 6 requests with distinct prompt lengths
    compile at most len(buckets) prefill programs (monitored_jit miss
    counters), with tokens identical to the unbucketed engine."""

    def test_dense_engine(self, mon):
        model, cfg = tiny_model(layers=1)
        prompts = _prompts(cfg)
        gc = GenerationConfig(max_new_tokens=6, eos_token_id=None)
        want = _reference(model, cfg)
        monitor.reset()
        eng = ContinuousBatchingEngine(model, max_batch=3, max_len=64)
        assert len(set(PLENS)) >= 6
        got = _serve(eng, prompts, gc)
        assert got == want
        misses = _jit_misses()
        assert misses.get("cb_prefill", 0) <= len(eng.prefill_buckets), \
            misses
        # the mix above actually exercises more lengths than buckets
        assert len(set(PLENS)) > misses.get("cb_prefill", 0)

    def test_paged_engine(self, mon):
        model, cfg = tiny_model(layers=1)
        prompts = _prompts(cfg)
        gc = GenerationConfig(max_new_tokens=6, eos_token_id=None)
        want = _reference(model, cfg)
        monitor.reset()
        eng = PagedContinuousBatchingEngine(
            model, max_batch=3, num_pages=24, page_size=8, max_pages=8)
        got = _serve(eng, prompts, gc)
        assert got == want
        misses = _jit_misses()
        assert misses.get("cb_prefill", 0) <= len(eng.prefill_buckets), \
            misses
        # per-bucket admission counters exported for dashboards
        buckets = {s["labels"]["bucket"]: s["value"]
                   for s in monitor.snapshot()["metrics"]
                   ["paddle_tpu_prefill_requests_total"]["samples"]}
        assert sum(buckets.values()) == len(PLENS)


class TestChunkedAdmission:
    """ISSUE-3 acceptance: one long prompt (>= 4 chunks) admitted during
    active decoding — decode segments run BETWEEN chunks (bounded gap
    work) and the final output matches single-shot prefill."""

    def test_server_interleaves_decode_between_chunks(self, mon):
        model, cfg = tiny_model(layers=1)
        rng = np.random.RandomState(5)
        long_p = rng.randint(0, cfg.vocab_size, (30,)).astype(np.int32)
        gc = GenerationConfig(max_new_tokens=8, eos_token_id=None)

        single = PagedContinuousBatchingEngine(
            model, max_batch=3, num_pages=24, page_size=8, max_pages=8)
        rid = single.add_request(long_p, gc)
        while single.decode_segment(2):
            pass
        want = list(single.collect_finished()[rid])

        eng = PagedContinuousBatchingEngine(
            model, max_batch=3, num_pages=24, page_size=8, max_pages=8,
            prefill_chunk=8)
        events = []
        ds, ac = eng.decode_segment, eng.admit_chunk
        eng.decode_segment = \
            lambda n, cfg=None: (events.append("seg"), ds(n, cfg))[1]
        eng.admit_chunk = \
            lambda adm: (events.append("chunk"), ac(adm))[1]
        srv = Server(eng, max_queue=8, segment_steps=2)
        try:
            h_short = srv.submit(
                rng.randint(0, cfg.vocab_size, (5,)).astype(np.int32),
                GenerationConfig(max_new_tokens=24, eos_token_id=None))
            next(iter(h_short.stream(timeout=60)))   # decoding active
            h_long = srv.submit(long_p, gc)
            got = list(h_long.result(timeout=120))
            assert got == want
            assert len(h_short.result(timeout=120)) == 24
            chunk_idx = [i for i, e in enumerate(events)
                         if e == "chunk"]
            assert len(chunk_idx) == 4               # ceil(30/8)
            # bounded gap work: a decode segment ran between chunks
            assert any("seg" in events[a + 1:b]
                       for a, b in zip(chunk_idx, chunk_idx[1:])), \
                events
        finally:
            srv.shutdown(drain=False)

    def test_deadline_expiring_mid_admission_aborts(self, mon):
        """Chunked admission spans many gaps, so the admission deadline
        must keep applying AFTER the request leaves the queue: a
        deadline passing mid-admission aborts it (EXPIRED, capacity
        reclaimed) instead of decoding for a client that gave up."""
        import time as _time

        from paddle_tpu.serving import DeadlineExpired

        model, cfg = tiny_model(layers=1)
        eng = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=24, page_size=8, max_pages=8,
            prefill_chunk=8)
        real = eng.admit_chunk
        eng.admit_chunk = \
            lambda adm: (_time.sleep(0.05), real(adm))[1]
        srv = Server(eng, segment_steps=2)
        try:
            h = srv.submit(np.arange(30, dtype=np.int32)
                           % cfg.vocab_size,
                           GenerationConfig(max_new_tokens=8,
                                            eos_token_id=None),
                           timeout_s=0.08)   # expires after ~1 chunk
            with pytest.raises(DeadlineExpired):
                h.result(timeout=60)
            deadline = _time.monotonic() + 10
            while (eng.free_slots() < 2
                   and _time.monotonic() < deadline):
                _time.sleep(0.01)
            assert eng.free_slots() == 2
            assert eng.alloc.free_pages == eng.num_pages
        finally:
            srv.shutdown(drain=False)

    def test_cancel_mid_chunked_admission_reclaims(self):
        model, cfg = tiny_model(layers=1)
        eng = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=12, page_size=8, max_pages=8,
            prefill_chunk=8)
        gc = GenerationConfig(max_new_tokens=8, eos_token_id=None)
        p = np.arange(30, dtype=np.int32) % cfg.vocab_size
        adm = eng.begin_admit(p, gc)
        assert eng.free_slots() == 1
        assert eng.alloc.free_pages < eng.num_pages   # reserved UP FRONT
        assert not eng.admit_chunk(adm)
        eng.abort_admit(adm)
        eng.abort_admit(adm)                           # idempotent
        assert eng.free_slots() == 2
        assert eng.alloc.free_pages == eng.num_pages
        with pytest.raises(RuntimeError, match="admission"):
            eng.admit_chunk(adm)
        # capacity is genuinely reusable afterwards
        rid = eng.add_request(p, gc)
        while eng.decode_segment(4):
            pass
        assert len(eng.collect_finished()[rid]) == 8


class TestWarmup:
    def test_engine_warmup_precompiles_all_buckets(self, mon):
        model, cfg = tiny_model(layers=1)
        eng = PagedContinuousBatchingEngine(
            model, max_batch=3, num_pages=24, page_size=8, max_pages=8,
            prefill_chunk=8)
        out = eng.warmup(segment_steps=4)
        assert set(out) >= {f"prefill_{b}" for b in eng.prefill_buckets}
        assert "prefill_chunk" in out and "segment_4" in out
        before = _jit_misses()
        assert before.get("cb_prefill", 0) == len(eng.prefill_buckets)
        # warmup time is exported for the serving dashboards
        warm = monitor.snapshot()["metrics"][
            "paddle_tpu_prefill_warmup_seconds"]["samples"]
        assert warm and warm[0]["value"] > 0
        rng = np.random.RandomState(6)
        gc = GenerationConfig(max_new_tokens=4, eos_token_id=None)
        prompts = [rng.randint(0, cfg.vocab_size, (n,))
                   .astype(np.int32) for n in PLENS]
        _serve(eng, prompts, gc)
        after = _jit_misses()
        # NO user request paid a prefill/segment compile
        assert after.get("cb_prefill", 0) == before.get("cb_prefill", 0)
        assert after.get("cb_segment", 0) == before.get("cb_segment", 0)
        with pytest.raises(RuntimeError, match="idle"):
            eng.add_request(prompts[0], gc)
            eng.warmup()

    def test_server_warmup_reports_warming_then_ready(self, mon):
        import json
        from urllib.error import HTTPError
        from urllib.request import urlopen

        model, cfg = tiny_model(layers=1)
        eng = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=12, page_size=8, max_pages=4)
        gate = threading.Event()
        real = eng.warmup
        eng.warmup = lambda n=None: (gate.wait(30), real(n))[1]
        srv = Server(eng, warmup=True)
        httpd = serve_http(srv)
        port = httpd.server_address[1]
        try:
            assert srv.status == "warming"
            with pytest.raises(HTTPError) as ei:   # readiness gate: 503
                urlopen(f"http://127.0.0.1:{port}/healthz", timeout=30)
            assert ei.value.code == 503
            assert json.load(ei.value)["status"] == "warming"
            # submissions QUEUE during warmup instead of failing
            h = srv.submit(np.arange(4, dtype=np.int32),
                           GenerationConfig(max_new_tokens=3,
                                            eos_token_id=None))
            gate.set()
            assert srv.wait_ready(60)
            assert len(h.result(timeout=120)) == 3
            with urlopen(f"http://127.0.0.1:{port}/healthz",
                         timeout=30) as r:
                assert json.load(r)["status"] == "ok"
        finally:
            gate.set()
            httpd.shutdown()
            srv.shutdown(drain=False)


class TestWarmupFailure:
    def test_wait_ready_unblocks_when_warmup_dies(self):
        """A warmup crash must not hang wait_ready() forever — the
        event fires on the way out and status says 'failed'."""
        model, cfg = tiny_model(layers=1)
        eng = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=12, page_size=8, max_pages=4)
        eng.warmup = lambda n=None: (_ for _ in ()).throw(
            RuntimeError("injected warmup fault"))
        srv = Server(eng, warmup=True)
        try:
            assert srv.wait_ready(30)
            assert srv.status == "failed"
            from paddle_tpu.serving import RequestRejected
            with pytest.raises(RequestRejected, match="warmup fault"):
                srv.submit(np.arange(3, dtype=np.int32),
                           GenerationConfig(max_new_tokens=2))
        finally:
            srv.shutdown(drain=False)


class TestFreeListDeterminism:
    """Heap-backed free lists (engine slots + KV pages): admission order
    stays deterministic — lowest id first — after aborts and
    cancellations, without the old O(n log n) sort per retirement."""

    def test_slot_order_after_aborts(self):
        model, cfg = tiny_model(layers=1)
        eng = ContinuousBatchingEngine(model, max_batch=4, max_len=32)
        gc = GenerationConfig(max_new_tokens=8, eos_token_id=None)
        rng = np.random.RandomState(7)

        def admit():
            return eng.add_request(
                rng.randint(0, cfg.vocab_size, (4,)).astype(np.int32),
                gc)

        r0, r1, r2 = admit(), admit(), admit()
        slot_of = {r: s for s, r in eng._slot_req.items()}
        assert [slot_of[r] for r in (r0, r1, r2)] == [0, 1, 2]
        eng.cancel_request(r1)
        eng.cancel_request(r0)
        # a failed admission (abort path) returns its slot too
        orig = eng._admit_state
        eng._admit_state = lambda *a: (_ for _ in ()).throw(
            RuntimeError("injected"))
        with pytest.raises(RuntimeError, match="injected"):
            admit()
        eng._admit_state = orig
        # lowest freed slot is reused first, deterministically
        r3, r4 = admit(), admit()
        slot_of = {r: s for s, r in eng._slot_req.items()}
        assert slot_of[r3] == 0 and slot_of[r4] == 1

    def test_page_allocator_reuses_lowest_pages(self):
        from paddle_tpu.inference.paged_cache import PageAllocator

        alloc = PageAllocator(num_pages=8, page_size=4, max_batch=4,
                              max_pages=4)
        alloc.ensure(0, 8)    # pages 0,1
        alloc.ensure(1, 8)    # pages 2,3
        alloc.free_slot(0)
        alloc.ensure(2, 12)   # must take lowest free: 0,1,4
        assert list(alloc.page_table[2][:3]) == [0, 1, 4]
        alloc.close()


@pytest.mark.slow
class TestChunkedPrefillSoak:
    def test_long_prompt_soak(self, mon):
        """Long-prompt chunked-prefill soak: many mixed admissions with
        several multi-chunk prompts in flight back to back, outputs
        matching the unchunked engine throughout."""
        model, cfg = tiny_model(layers=1)
        rng = np.random.RandomState(8)
        gc = GenerationConfig(max_new_tokens=8, eos_token_id=None)
        lens = [rng.randint(3, 100) for _ in range(24)]
        prompts = [rng.randint(0, cfg.vocab_size, (n,))
                   .astype(np.int32) for n in lens]

        def outputs(prefill_chunk):
            eng = PagedContinuousBatchingEngine(
                model, max_batch=4, num_pages=64, page_size=8,
                max_pages=16, prefill_chunk=prefill_chunk)
            srv = Server(eng, max_queue=32, segment_steps=3,
                         warmup=True)
            try:
                handles = [srv.submit(p, gc) for p in prompts]
                return [list(h.result(timeout=300)) for h in handles]
            finally:
                srv.shutdown(drain=False)

        assert outputs(prefill_chunk=16) == outputs(prefill_chunk=None)
