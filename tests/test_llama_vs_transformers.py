"""Flagship-model oracle: our LlamaForCausalLM vs HuggingFace
transformers' (the canonical implementation) with IDENTICAL weights —
verifies the whole stack (RoPE convention, GQA head grouping, RMSNorm
epsilon placement, SwiGLU, logits head) in one shot. Also the
functional scan-over-layers form and the KV-cache decode path against
the same oracle.
"""
import numpy as np
import pytest

# minutes-scale multi-device/parity suite on the CPU backend:
# rides the slow tier (run with -m slow), not tier-1
pytestmark = pytest.mark.slow

import paddle_tpu as paddle
from paddle_tpu.models import LlamaForCausalLM
from paddle_tpu.models.llama import LlamaConfig

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def build_pair(kvh=2, layers=2, hidden=32, inter=64, heads=4, vocab=97):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=vocab, hidden_size=hidden, intermediate_size=inter,
        num_hidden_layers=layers, num_attention_heads=heads,
        num_key_value_heads=kvh, max_position_embeddings=128,
        rms_norm_eps=1e-6, rope_theta=10000.0, tie_word_embeddings=False,
        attn_implementation="eager")
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()

    cfg = LlamaConfig(
        vocab_size=vocab, hidden_size=hidden, intermediate_size=inter,
        num_hidden_layers=layers, num_attention_heads=heads,
        num_key_value_heads=kvh, max_position_embeddings=128,
        rms_norm_eps=1e-6, rope_theta=10000.0)
    ours = LlamaForCausalLM(cfg)
    ours.eval()

    hf_sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    for name, p in ours.named_parameters():
        v = hf_sd[name]
        if name.endswith("proj.weight") or name == "lm_head.weight":
            v = v.T          # torch Linear stores [out, in]; ours [in, out]
        assert tuple(v.shape) == tuple(p.shape), (name, v.shape, p.shape)
        p.set_value(paddle.to_tensor(np.ascontiguousarray(v)))
    return ours, hf, cfg


class TestLogitsParity:
    @pytest.mark.parametrize("kvh", [4, 2])
    def test_forward_logits_match(self, kvh):
        ours, hf, _ = build_pair(kvh=kvh)
        rng = np.random.RandomState(1)
        ids = rng.randint(0, 97, (2, 11)).astype(np.int64)
        want = hf(torch.from_numpy(ids)).logits.detach().numpy()
        got = np.asarray(ours(paddle.to_tensor(ids.astype(np.int32))).value)
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3,
                                   err_msg=f"kvh={kvh}")

    def test_functional_form_matches_hf(self):
        from paddle_tpu.models.llama_functional import (forward,
                                                        stack_params)

        ours, hf, cfg = build_pair()
        rng = np.random.RandomState(2)
        ids = rng.randint(0, 97, (1, 9)).astype(np.int64)
        params = {k: p.value for k, p in ours.named_parameters()}
        stacked, rest = stack_params(params, cfg)
        got = np.asarray(forward(stacked, rest,
                                 np.asarray(ids, np.int32), cfg,
                                 remat=False))
        want = hf(torch.from_numpy(ids)).logits.detach().numpy()
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)

    def test_greedy_generation_matches_hf(self):
        from paddle_tpu.inference.generation import (CausalLMEngine,
                                                     GenerationConfig)

        ours, hf, _ = build_pair()
        rng = np.random.RandomState(3)
        ids = rng.randint(0, 97, (1, 7)).astype(np.int64)
        want = hf.generate(torch.from_numpy(ids), max_new_tokens=8,
                           do_sample=False).numpy()
        eng = CausalLMEngine(ours, max_batch=1, max_len=64)
        got = eng.generate(ids.astype(np.int32),
                           GenerationConfig(max_new_tokens=8))
        np.testing.assert_array_equal(got, want)


class TestGPT2VsTransformers:
    """Our GPT (GPT-2 architecture) vs HF GPT2 with shared weights.
    HF Conv1D already stores [in, out] like our Linear — no transpose
    except the lm_head torch Linear."""

    def test_gpt2_logits_match(self):
        import transformers as tr

        hf_cfg = tr.GPT2Config(
            vocab_size=97, n_positions=64, n_embd=32, n_layer=2, n_head=4,
            n_inner=64, activation_function="gelu_new",
            resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
            attn_implementation="eager")
        torch.manual_seed(0)
        hf = tr.GPT2LMHeadModel(hf_cfg).eval()

        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        ours = GPTForCausalLM(GPTConfig(
            vocab_size=97, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=64,
            max_position_embeddings=64, dropout=0.0))
        ours.eval()
        sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
        m = {
            "model.embed_tokens.weight": "transformer.wte.weight",
            "model.embed_positions.weight": "transformer.wpe.weight",
            "model.ln_f.weight": "transformer.ln_f.weight",
            "model.ln_f.bias": "transformer.ln_f.bias",
            "lm_head.weight": ("transformer.wte.weight", "T"),
        }
        for i in range(2):
            pre = f"model.layers.{i}."
            h = f"transformer.h.{i}."
            m[pre + "ln_1.weight"] = h + "ln_1.weight"
            m[pre + "ln_1.bias"] = h + "ln_1.bias"
            m[pre + "ln_2.weight"] = h + "ln_2.weight"
            m[pre + "ln_2.bias"] = h + "ln_2.bias"
            m[pre + "attn.qkv_proj.weight"] = h + "attn.c_attn.weight"
            m[pre + "attn.qkv_proj.bias"] = h + "attn.c_attn.bias"
            m[pre + "attn.out_proj.weight"] = h + "attn.c_proj.weight"
            m[pre + "attn.out_proj.bias"] = h + "attn.c_proj.bias"
            m[pre + "mlp.fc_in.weight"] = h + "mlp.c_fc.weight"
            m[pre + "mlp.fc_in.bias"] = h + "mlp.c_fc.bias"
            m[pre + "mlp.fc_out.weight"] = h + "mlp.c_proj.weight"
            m[pre + "mlp.fc_out.bias"] = h + "mlp.c_proj.bias"
        for name, p in ours.named_parameters():
            src = m[name]
            if isinstance(src, tuple):
                v = sd[src[0]].T
            else:
                v = sd[src]
            assert tuple(v.shape) == tuple(p.shape), (name, v.shape,
                                                      p.shape)
            p.set_value(paddle.to_tensor(np.ascontiguousarray(v)))

        rng = np.random.RandomState(5)
        ids = rng.randint(0, 97, (2, 10)).astype(np.int64)
        want = hf(torch.from_numpy(ids)).logits.detach().numpy()
        got = np.asarray(ours(paddle.to_tensor(
            ids.astype(np.int32))).value)
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)
