"""Custom C++ op extension point (the phi/capi + utils/cpp_extension roles).

Reference: cpp_extension.load (cpp_extension.py:799) JIT-compiles custom
ops authored against the extension ABI (op_meta_info.h:874 PD_BUILD_OP,
phi/capi C ABI). TPU-native form: ops compile against paddle_tpu_ext.h,
run as host callbacks (eager AND inside jax.jit via pure_callback), with
<name>_grad exports becoming the VJP. Tests compile REAL C++ with g++.
"""
import os
import shutil
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.utils.cpp_extension import (CppExtension, CUDAExtension,
                                            get_build_directory, load)

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no g++ in image")

RELU_SRC = textwrap.dedent("""
    #include "paddle_tpu_ext.h"

    extern "C" PT_KERNEL(custom_relu) {
      const float* x = (const float*)in[0].data;
      float* y = (float*)out[0].data;
      for (int64_t i = 0; i < in[0].numel; ++i)
        y[i] = x[i] > 0.f ? x[i] : 0.f;
      return 0;
    }

    /* grad: receives (x, dy) and writes dx */
    extern "C" PT_KERNEL(custom_relu_grad) {
      const float* x = (const float*)in[0].data;
      const float* dy = (const float*)in[1].data;
      float* dx = (float*)out[0].data;
      for (int64_t i = 0; i < in[0].numel; ++i)
        dx[i] = x[i] > 0.f ? dy[i] : 0.f;
      return 0;
    }
""")

AXPY_SRC = textwrap.dedent("""
    #include "paddle_tpu_ext.h"

    /* two inputs, output shaped like input 0; int error path for bad
       dtype exercises the error contract */
    extern "C" PT_KERNEL(axpy2) {
      if (in[0].dtype != PT_FLOAT32 || in[1].dtype != PT_FLOAT32) return 7;
      const float* a = (const float*)in[0].data;
      const float* b = (const float*)in[1].data;
      float* y = (float*)out[0].data;
      for (int64_t i = 0; i < in[0].numel; ++i) y[i] = 2.f * a[i] + b[i];
      return 0;
    }
""")


@pytest.fixture(scope="module")
def relu_mod(tmp_path_factory):
    d = tmp_path_factory.mktemp("ext")
    src = d / "relu.cc"
    src.write_text(RELU_SRC)
    mod = load(name="custom_relu_lib", sources=[str(src)],
               build_directory=str(d))
    mod.def_op("custom_relu")
    return mod


class TestLoadAndRun:
    def test_eager_matches_jnp(self, relu_mod):
        x = np.random.RandomState(0).randn(4, 5).astype(np.float32)
        y = relu_mod.custom_relu(x)
        np.testing.assert_array_equal(np.asarray(y), np.maximum(x, 0))

    def test_tensor_in_tensor_out(self, relu_mod):
        x = paddle.to_tensor(np.array([-1.0, 2.0], np.float32))
        y = relu_mod.custom_relu(x)
        assert hasattr(y, "_value")
        np.testing.assert_array_equal(np.asarray(y.value), [0.0, 2.0])

    def test_under_jit(self, relu_mod):
        x = np.random.RandomState(1).randn(8).astype(np.float32)

        @jax.jit
        def f(v):
            return relu_mod.custom_relu(v) * 2.0

        np.testing.assert_allclose(np.asarray(f(jnp.asarray(x))),
                                   np.maximum(x, 0) * 2.0, rtol=1e-6)

    def test_grad_export_becomes_vjp(self, relu_mod):
        x = np.random.RandomState(2).randn(16).astype(np.float32)

        def loss(v):
            return jnp.sum(relu_mod.custom_relu(v) ** 2)

        g = jax.grad(loss)(jnp.asarray(x))
        want = np.where(x > 0, 2 * np.maximum(x, 0), 0.0)
        np.testing.assert_allclose(np.asarray(g), want, rtol=1e-5,
                                   atol=1e-6)

    def test_compile_cache_reused(self, tmp_path):
        src = tmp_path / "relu2.cc"
        src.write_text(RELU_SRC)
        m1 = load(name="cache_probe", sources=[str(src)],
                  build_directory=str(tmp_path))
        m2 = load(name="cache_probe", sources=[str(src)],
                  build_directory=str(tmp_path))
        assert m1._path == m2._path
        assert len([f for f in os.listdir(tmp_path)
                    if f.endswith(".so")]) == 1


class TestMultiInputAndErrors:
    def test_two_input_op(self, tmp_path):
        src = tmp_path / "axpy.cc"
        src.write_text(AXPY_SRC)
        mod = load(name="axpy_lib", sources=[str(src)],
                   build_directory=str(tmp_path))
        op = mod.def_op("axpy2")
        a = np.arange(6, dtype=np.float32).reshape(2, 3)
        b = np.ones((2, 3), np.float32)
        np.testing.assert_allclose(np.asarray(op(a, b)), 2 * a + b)

    def test_kernel_error_code_raises(self, tmp_path):
        src = tmp_path / "axpy_err.cc"
        src.write_text(AXPY_SRC)
        mod = load(name="axpy_err_lib", sources=[str(src)],
                   build_directory=str(tmp_path))
        op = mod.def_op("axpy2")
        bad = np.ones((2,), np.int32)
        with pytest.raises(Exception, match="error code 7"):
            op(bad, bad)

    def test_compile_error_is_actionable(self, tmp_path):
        src = tmp_path / "broken.cc"
        src.write_text("this is not C++")
        with pytest.raises(RuntimeError, match="compilation failed"):
            load(name="broken", sources=[str(src)],
                 build_directory=str(tmp_path))

    def test_cuda_extension_raises_with_pallas_pointer(self):
        with pytest.raises(RuntimeError, match="Pallas"):
            CUDAExtension(sources=["x.cu"])

    def test_cpp_extension_is_setuptools_extension(self, tmp_path):
        ext = CppExtension(sources=["a.cc"], name="my_ops")
        from setuptools import Extension

        assert isinstance(ext, Extension)
        assert any("cpp_extension" in d for d in ext.include_dirs)

    def test_build_directory_env(self, monkeypatch, tmp_path):
        monkeypatch.setenv("PADDLE_EXTENSION_DIR", str(tmp_path / "bd"))
        assert get_build_directory() == str(tmp_path / "bd")
        assert os.path.isdir(str(tmp_path / "bd"))
