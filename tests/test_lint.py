"""tools/lint — the invariant-aware static analysis suite (PR 11).

Three layers:

- per-checker FIXTURE tests: each of PT001-PT006 fires on a seeded
  violation and stays quiet on the blessed idiom (the checker's
  contract, independent of the live tree);
- engine tests: fingerprint stability under line drift, annotation
  parsing, baseline load/validation/round-trip;
- the TIER-1 GATE: the full suite over ``paddle_tpu/`` reports zero
  unbaselined findings against the checked-in baseline — the "no NEW
  violations" CI bar.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.lint import (BaselineError, apply_baseline,        # noqa: E402
                        default_baseline_path, generate_baseline,
                        lint_paths, lint_source, load_baseline,
                        write_baseline)


def ids(findings, checker=None):
    return [f.checker for f in findings
            if checker is None or f.checker == checker]


def only(findings, checker):
    return [f for f in findings if f.checker == checker]


# ---------------------------------------------------------------------------
# PT001 — recompile hazard
# ---------------------------------------------------------------------------
class TestPT001:
    def test_fires_on_jit_per_call(self):
        src = (
            "import jax\n"
            "class M:\n"
            "    def step(self, x):\n"
            "        fn = jax.jit(lambda a: a + 1)\n"
            "        return fn(x)\n")
        f = only(lint_source(src), "PT001")
        assert len(f) == 1 and f[0].line == 4
        assert "fresh trace cache" in f[0].message

    def test_fires_on_immediate_call(self):
        src = ("import jax\n"
               "def probe(x):\n"
               "    return jax.jit(lambda a: a * 2)(x)\n")
        f = only(lint_source(src), "PT001")
        assert len(f) == 1 and "immediately called" in f[0].message

    def test_fires_in_loop_and_on_decorated_local_def(self):
        src = (
            "import jax\n"
            "def run(xs):\n"
            "    outs = []\n"
            "    for x in xs:\n"
            "        fn = jax.jit(lambda a: a)\n"
            "        outs.append(fn(x))\n"
            "    @jax.jit\n"
            "    def inner(a):\n"
            "        return a\n"
            "    return outs, inner\n")
        f = only(lint_source(src), "PT001")
        assert len(f) == 2
        assert any("inside a loop" in x.message for x in f)
        assert any("re-jitted every call" in x.message for x in f)

    def test_fires_on_static_hint_param_without_static_argnames(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        def seg(state, n_steps):\n"
            "            return state\n"
            "        self._seg = jax.jit(seg)\n")
        f = only(lint_source(src), "PT001")
        assert len(f) == 1 and "static_argnames" in f[0].message

    def test_quiet_on_blessed_idioms(self):
        src = (
            "import jax, functools\n"
            "from .. import monitor\n"
            "JITTED = jax.jit(lambda a: a)\n"           # module level
            "@functools.partial(jax.jit, static_argnames=('eps',))\n"
            "def k(x, eps):\n"
            "    return x\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._fn = monitor.monitored_jit(lambda a: a)\n"
            "        self._cache = {}\n"
            "        self._lazy = None\n"
            "    def _decode_fn(self, n_steps):\n"
            "        if n_steps not in self._cache:\n"
            "            def seg(s, n_steps):\n"
            "                return s\n"
            "            self._cache[n_steps] = jax.jit(\n"
            "                seg, static_argnames=('n_steps',))\n"
            "        return self._cache[n_steps]\n"
            "    def fn(self):\n"
            "        if self._lazy is None:\n"
            "            self._lazy = jax.jit(lambda a: a)\n"
            "        return self._lazy\n"
            "def build(f):\n"
            "    return jax.jit(f)\n")
        assert only(lint_source(src), "PT001") == []

    def test_keyed_cache_blesses_static_hint(self):
        src = (
            "import jax\n"
            "class E:\n"
            "    def seg_fn(self, n_steps):\n"
            "        def seg(s, n_steps):\n"
            "            return s\n"
            "        self._c[n_steps] = jax.jit(seg)\n"
            "        return self._c[n_steps]\n")
        assert only(lint_source(src), "PT001") == []

    def test_escape_needs_reason(self):
        base = ("import jax\n"
                "def probe(x):\n"
                "    {esc}\n"
                "    return jax.jit(lambda a: a)(x)\n")
        bad = lint_source(base.format(esc="# lint: allow-recompile"))
        assert any("requires a reason" in f.message
                   for f in only(bad, "PT001"))
        good = lint_source(base.format(
            esc="# lint: allow-recompile(one-shot probe)"))
        assert only(good, "PT001") == []


# ---------------------------------------------------------------------------
# PT002 — host sync in hot path
# ---------------------------------------------------------------------------
class TestPT002:
    HOT = (
        "import numpy as np\n"
        "class S:\n"
        "    def _gap(self):  # lint: hot-path\n"
        "        toks = np.asarray(self.toks_dev)\n"
        "        v = self.x.item()\n"
        "        n = int(self.lens[0])\n"
        "        self._helper()\n"
        "    def _helper(self):\n"
        "        import jax\n"
        "        jax.device_get(self.y)\n"
        "    def cold(self):\n"
        "        return np.asarray(self.toks_dev)\n")

    def test_fires_in_hot_and_transitively_not_in_cold(self):
        f = only(lint_source(self.HOT), "PT002")
        details = sorted(x.detail for x in f)
        assert details == [".item()", "int()", "jax.device_get",
                           "np.asarray"]
        # the reached-from context names the root
        helper = [x for x in f if x.context == "S._helper"][0]
        assert "reached from S._gap" in helper.message
        assert all(x.context != "S.cold" for x in f)

    def test_quiet_without_annotation(self):
        src = self.HOT.replace("  # lint: hot-path", "")
        assert only(lint_source(src), "PT002") == []

    def test_escape_hatch_requires_reason(self):
        src = (
            "import numpy as np\n"
            "class S:\n"
            "    def _gap(self):  # lint: hot-path\n"
            "        # lint: allow-host-sync(collection readback)\n"
            "        toks = np.asarray(self.toks_dev)\n"
            "        done = np.asarray(self.done_dev)  "
            "# lint: allow-host-sync\n")
        f = only(lint_source(src), "PT002")
        assert len(f) == 1 and "REASON is required" in f[0].message

    def test_escape_covers_multiline_statement(self):
        src = (
            "import numpy as np\n"
            "class S:\n"
            "    def _gap(self):  # lint: hot-path\n"
            "        # lint: allow-host-sync(host-list copy)\n"
            "        ids = np.concatenate(\n"
            "            [self.a,\n"
            "             np.asarray(self.b, np.int32)])\n")
        assert only(lint_source(src), "PT002") == []

    def test_host_to_device_not_flagged(self):
        src = (
            "import jax.numpy as jnp\n"
            "class S:\n"
            "    def _gap(self):  # lint: hot-path\n"
            "        x = jnp.asarray([1, 2])\n"
            "        busy = bool(self._active or self._adm)\n"
            "        n = int(local_host_array[0])\n")
        assert only(lint_source(src), "PT002") == []


# ---------------------------------------------------------------------------
# PT003 — series lifecycle
# ---------------------------------------------------------------------------
class TestPT003:
    def test_fires_without_retirement(self):
        src = (
            "from .. import monitor\n"
            "class Pool:\n"
            "    def _pages(self):\n"
            "        return monitor.gauge('x_pages', 'h', ('pool',))\n"
            "    def close(self):\n"
            "        pass\n")
        f = only(lint_source(src), "PT003")
        assert len(f) == 1 and f[0].detail == "x_pages"
        assert "never retired" in f[0].message

    def test_fires_without_any_retirement_root(self):
        src = ("from .. import monitor\n"
               "class Pool:\n"
               "    def _pages(self):\n"
               "        return monitor.gauge('x_pages', 'h', ('pool',))\n")
        assert len(only(lint_source(src), "PT003")) == 1

    def test_fires_outside_class(self):
        src = ("from .. import monitor\n"
               "G = monitor.gauge('x_depth', 'h', ('loader',))\n")
        f = only(lint_source(src), "PT003")
        assert len(f) == 1 and "outside a class" in f[0].message

    def test_quiet_on_name_tuple_remove_series_idiom(self):
        src = (
            "from .. import monitor\n"
            "class Srv:\n"
            "    def _req(self):\n"
            "        return monitor.counter('x_req', 'h',\n"
            "                               ('server', 'event'))\n"
            "    def shutdown(self):\n"
            "        for name in ('x_req',):\n"
            "            monitor.remove_series(name, server=self.lbl)\n")
        assert only(lint_source(src), "PT003") == []

    def test_quiet_on_helper_remove_idiom_via_close_chain(self):
        src = (
            "from .. import monitor\n"
            "class Pool:\n"
            "    def _pages(self):\n"
            "        return monitor.gauge('x_pages', 'h', ('pool',))\n"
            "    def close(self):\n"
            "        self._retire_all()\n"
            "    def _retire_all(self):\n"
            "        self._pages().remove(pool=self.lbl)\n")
        assert only(lint_source(src), "PT003") == []

    def test_retires_series_annotation_and_base_class_root(self):
        src = (
            "from .. import monitor\n"
            "class Base:\n"
            "    def close(self):\n"
            "        monitor.remove_series('x_tps', engine=self.lbl)\n"
            "class Eng(Base):\n"
            "    def _tps(self):\n"
            "        return monitor.gauge('x_tps', 'h', ('engine',))\n"
            "class Cb:\n"
            "    def _fit(self):\n"
            "        return monitor.gauge('x_fit', 'h', ('fit',))\n"
            "    # lint: retires-series\n"
            "    def on_train_end(self):\n"
            "        self._fit().remove(fit=self.lbl)\n")
        assert only(lint_source(src), "PT003") == []

    def test_non_instance_labels_ignored(self):
        src = ("from .. import monitor\n"
               "C = monitor.counter('x_total', 'h', ('event',))\n")
        assert only(lint_source(src), "PT003") == []


# ---------------------------------------------------------------------------
# PT004 — lock discipline
# ---------------------------------------------------------------------------
class TestPT004:
    SRC = (
        "import threading\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._flag = False     # guarded-by: self._lock\n"
        "        self._free = []        # guarded-by: scheduler-thread\n"
        "    def good(self):\n"
        "        with self._lock:\n"
        "            self._flag = True\n"
        "    def bad(self):\n"
        "        return self._flag\n"
        "    def owned(self):\n"
        "        return len(self._free)\n")

    def test_fires_outside_lock_only(self):
        f = only(lint_source(self.SRC), "PT004")
        assert len(f) == 1
        assert f[0].context == "S.bad" and f[0].detail == "_flag"

    def test_thread_ownership_form_not_enforced(self):
        f = only(lint_source(self.SRC), "PT004")
        assert all(x.detail != "_free" for x in f)

    def test_escape_hatch(self):
        src = self.SRC.replace(
            "        return self._flag",
            "        # lint: allow-unlocked(atomic read)\n"
            "        return self._flag")
        assert only(lint_source(src), "PT004") == []

    def test_missing_lock_declaration_is_config_error(self):
        src = (
            "class S:\n"
            "    def __init__(self):\n"
            "        self._flag = False  # guarded-by: self._nope\n"
            "    def read(self):\n"
            "        return self._flag\n")
        f = only(lint_source(src), "PT004")
        assert len(f) == 1 and "never creates" in f[0].message


# ---------------------------------------------------------------------------
# PT005 — flag gating
# ---------------------------------------------------------------------------
class TestPT005:
    def test_fires_on_ungated_trace_and_monitor_calls(self):
        src = (
            "from .. import monitor\n"
            "from .. import tracing as trace\n"
            "class S:\n"
            "    def seam(self):\n"
            "        trace.event('queue.enqueue', rid=3)\n"
            "        self._req().labels(server=self.lbl).inc()\n"
            "        monitor.histogram('x_s', 'h').observe(0.1)\n")
        f = only(lint_source(src), "PT005")
        assert len(f) == 3

    def test_quiet_when_gated(self):
        src = (
            "from .. import monitor\n"
            "from .. import tracing as trace\n"
            "class S:\n"
            "    def seam(self):\n"
            "        if trace.enabled():\n"
            "            trace.event('queue.enqueue', rid=3)\n"
            "        if monitor.enabled():\n"
            "            self._req().labels(server=self.lbl).inc()\n"
            "    def early(self):\n"
            "        if not monitor.enabled():\n"
            "            return\n"
            "        monitor.histogram('x_s', 'h').observe(0.1)\n"
            "    def not_metrics(self):\n"
            "        self._wake.set()\n"          # threading.Event, ok
            "        self.arr.at[0].set(1)\n")    # jax .at update, ok
        assert only(lint_source(src), "PT005") == []

    def test_internal_ring_and_store_rules(self):
        src = (
            "_enabled = False\n"
            "def event(phase):\n"
            "    _ring.append((phase,))\n"
            "def gated_event(phase):\n"
            "    if not _enabled:\n"
            "        return\n"
            "    _ring.append((phase,))\n"
            "class Counter:\n"
            "    def _inc(self, key, amount):\n"
            "        self._values[key] = amount\n"
            "    def _inc_gated(self, key, amount):\n"
            "        if not _enabled:\n"
            "            return\n"
            "        self._values[key] = amount\n")
        f = only(lint_source(src, filename="paddle_tpu/tracing/x.py"),
                 "PT005")
        assert sorted(x.detail for x in f) == ["ring-append",
                                               "values-store"]
        # outside the observability packages the internal rules are off
        assert only(lint_source(src, filename="paddle_tpu/io/x.py"),
                    "PT005") == []

    def test_escape_hatch(self):
        src = (
            "from .. import tracing as trace\n"
            "def seam():\n"
            "    # lint: allow-ungated(cold admin path, never hot)\n"
            "    trace.event('configured')\n")
        assert only(lint_source(src), "PT005") == []


# ---------------------------------------------------------------------------
# PT006 — blocking socket I/O in a hot path
# ---------------------------------------------------------------------------
class TestPT006:
    HOT = (
        "from urllib.request import urlopen\n"
        "import http.client\n"
        "class R:\n"
        "    def status(self):  # lint: hot-path\n"
        "        r = urlopen(self.url)\n"
        "        return self._poll()\n"
        "    def _poll(self):\n"
        "        conn = http.client.HTTPConnection(self.host)\n"
        "        conn.request('GET', '/healthz')\n"
        "        return conn.getresponse()\n"
        "    def cold(self):\n"
        "        return urlopen(self.url)\n")

    def test_fires_in_hot_and_transitively_not_in_cold(self):
        f = only(lint_source(self.HOT), "PT006")
        details = sorted(x.detail for x in f)
        assert details == [".getresponse()", "HTTPConnection",
                           "urlopen"]
        poll = [x for x in f if x.context == "R._poll"]
        assert poll and all("reached from R.status" in x.message
                            for x in poll)
        assert all(x.context != "R.cold" for x in f)

    def test_quiet_without_annotation(self):
        src = self.HOT.replace("  # lint: hot-path", "")
        assert only(lint_source(src), "PT006") == []

    def test_bounded_timeout_quiets_constructors_not_reads(self):
        src = (
            "from urllib.request import urlopen\n"
            "import socket\n"
            "class R:\n"
            "    def load(self):  # lint: hot-path\n"
            "        r = urlopen(self.url, timeout=2.0)\n"
            "        c = socket.create_connection(self.addr,\n"
            "                                     timeout=self.t)\n"
            "        return c.recv(4096)\n")
        f = only(lint_source(src), "PT006")
        # the timeout-bounded opener/constructor are fine; the raw
        # recv has no per-call bound and still needs the escape hatch
        assert [x.detail for x in f] == [".recv()"]

    def test_explicit_timeout_none_still_fires(self):
        src = (
            "from urllib.request import urlopen\n"
            "class R:\n"
            "    def load(self):  # lint: hot-path\n"
            "        return urlopen(self.url, timeout=None)\n")
        f = only(lint_source(src), "PT006")
        assert [x.detail for x in f] == ["urlopen"]

    def test_escape_hatch_requires_reason(self):
        src = (
            "class R:\n"
            "    def load(self):  # lint: hot-path\n"
            "        # lint: allow-blocking-io(reader thread's whole "
            "job is this wait)\n"
            "        a = self.sock.recv(4096)\n"
            "        b = self.sock.recv(4096)  # lint: allow-blocking-io\n")
        f = only(lint_source(src), "PT006")
        assert len(f) == 1 and "REASON is required" in f[0].message


# ---------------------------------------------------------------------------
# engine: annotations, fingerprints, baseline
# ---------------------------------------------------------------------------
class TestEngine:
    def test_unknown_directive_is_config_error(self):
        f = lint_source("x = 1  # lint: allow-hostsync(typo)\n")
        assert [x.checker for x in f] == ["PT000"]
        assert "unknown lint directive" in f[0].message

    def test_fingerprints_stable_under_line_drift(self):
        src = ("import numpy as np\n"
               "class S:\n"
               "    def _gap(self):  # lint: hot-path\n"
               "        a = np.asarray(self.x)\n"
               "        b = np.asarray(self.y)\n")
        before = [f.fingerprint for f in lint_source(src)]
        shifted = "# a comment\n# another\n\n" + src
        after = [f.fingerprint for f in lint_source(shifted)]
        assert before == after and len(before) == 2
        # ...and the two identical details stay distinguishable
        assert before[0] != before[1]

    def test_baseline_requires_justification(self, tmp_path):
        p = tmp_path / "b.json"
        p.write_text(json.dumps({"entries": [
            {"fingerprint": "PT001|f.py|ctx|jit:x|0",
             "justification": "   "}]}))
        with pytest.raises(BaselineError):
            load_baseline(str(p))
        p.write_text("{not json")
        with pytest.raises(BaselineError):
            load_baseline(str(p))

    def test_apply_baseline_suppresses_and_reports_stale(self):
        findings = lint_source(
            "import jax\n"
            "def probe(x):\n"
            "    return jax.jit(lambda a: a)(x)\n")
        fp = findings[0].fingerprint
        baseline = {fp: {"fingerprint": fp, "justification": "ok"},
                    "PT009|gone.py|x|y|0": {
                        "fingerprint": "PT009|gone.py|x|y|0",
                        "justification": "stale"}}
        new, suppressed, stale = apply_baseline(findings, baseline)
        assert new == [] and len(suppressed) == 1
        assert stale == ["PT009|gone.py|x|y|0"]

    def test_orphaned_escape_does_not_cross_blank_line(self):
        """An escape comment whose statement was deleted (blank line
        left behind) must NOT silently suppress the next statement."""
        src = ("import numpy as np\n"
               "class S:\n"
               "    def _gap(self):  # lint: hot-path\n"
               "        # lint: allow-host-sync(stale orphan)\n"
               "\n"
               "        toks = np.asarray(self.toks_dev)\n")
        assert len(only(lint_source(src), "PT002")) == 1

    def test_unknown_directive_reported_once(self):
        src = ("# lint: allow-hostsync(typo)\n"
               "\n"
               "x = 1\n"
               "y = 2\n")
        f = [x for x in lint_source(src) if x.checker == "PT000"]
        assert len(f) == 1 and f[0].line == 1

    def test_scoped_run_neither_stales_nor_drops_foreign_entries(self):
        from tools.lint.core import generate_baseline as gen
        findings = lint_source(
            "import jax\n"
            "def probe(x):\n"
            "    return jax.jit(lambda a: a)(x)\n",
            filename="pkg/a.py")
        foreign_fp = "PT003|pkg/b.py|Pool._pages|x_pages|0"
        baseline = {foreign_fp: {"fingerprint": foreign_fp,
                                 "justification": "kept"}}
        # a run covering only pkg/a.py: the pkg/b.py entry is not stale
        new, _sup, stale = apply_baseline(
            findings, baseline, covered_files={"pkg/a.py"})
        assert stale == [] and len(new) == 1
        # ...and regeneration over that scope carries it forward
        doc = gen(findings, previous=baseline,
                  covered_files={"pkg/a.py"})
        fps = [e["fingerprint"] for e in doc["entries"]]
        assert foreign_fp in fps
        kept = [e for e in doc["entries"]
                if e["fingerprint"] == foreign_fp][0]
        assert kept["justification"] == "kept"
        # a checker-subset run is scope-bounded the same way
        _new2, _sup2, stale2 = apply_baseline(
            [], baseline, covered_files={"pkg/b.py"},
            covered_checks=["PT001"])
        assert stale2 == []
        # a FULL-scope run does declare it stale
        _new3, _sup3, stale3 = apply_baseline(
            [], baseline, covered_files={"pkg/b.py"})
        assert stale3 == [foreign_fp]

    def test_baseline_round_trip_regenerates_identically(self, tmp_path):
        findings = lint_source(
            "import jax\n"
            "def probe(x):\n"
            "    f = jax.jit(lambda a: a)\n"
            "    return f(x)\n")
        doc = generate_baseline(findings)
        doc["entries"][0]["justification"] = "a real reason"
        p = tmp_path / "baseline.json"
        write_baseline(doc, str(p))
        reloaded = load_baseline(str(p))
        doc2 = generate_baseline(findings, previous=reloaded)
        assert doc2["entries"] == doc["entries"]
        p2 = tmp_path / "baseline2.json"
        write_baseline(doc2, str(p2))
        assert p.read_text() == p2.read_text()


# ---------------------------------------------------------------------------
# the tier-1 gate + CLI
# ---------------------------------------------------------------------------
class TestRepoGate:
    def test_zero_unbaselined_findings_in_paddle_tpu(self):
        """THE bar: the live tree is clean against the checked-in
        baseline. A new recompile hazard / hot-path sync / series leak
        / unlocked guarded field / ungated seam fails HERE, at the
        violating line, before it ships."""
        findings = lint_paths([os.path.join(REPO, "paddle_tpu")],
                              root=REPO)
        baseline = load_baseline(default_baseline_path())
        new, _suppressed, stale = apply_baseline(findings, baseline)
        assert new == [], (
            "UNBASELINED lint findings (fix, annotate, or triage into "
            "tools/lint/baseline.json with a justification):\n\n"
            + "\n".join(f.render() for f in new))
        assert stale == [], (
            "stale baseline entries (the code they suppressed is gone "
            "- prune with --fix-baseline):\n" + "\n".join(stale))

    def test_checked_in_baseline_is_fully_reviewed(self):
        baseline = load_baseline(default_baseline_path())
        unreviewed = [fp for fp, e in baseline.items()
                      if e["justification"].startswith("UNREVIEWED")]
        assert unreviewed == []

    def test_hot_path_ground_truth_is_annotated(self):
        """The PT002/PT004 ground-truth annotations the linter depends
        on must stay in place — deleting one silently turns the
        checker off for that path."""
        from tools.lint.core import Module
        from tools.lint.checks.host_sync import hot_functions
        expected = {
            "paddle_tpu/serving/scheduler.py": {"Server._gap",
                                                "Server.load"},
            "paddle_tpu/serving/router.py": {"Router.load"},
            # the cross-process replica's router-facing seam: cached-
            # snapshot reads only — PT006's ground truth (PR 17)
            "paddle_tpu/serving/remote.py": {
                "RemoteReplica.status", "RemoteReplica.load",
                "RemoteReplica.num_active",
                "RemoteReplica.flight_dumps",
                "_RemoteQueue.depth", "_RemoteAlloc.free_pages",
                "_RemoteAdapters.__contains__"},
            "paddle_tpu/inference/generation.py": {
                "ContinuousBatchingEngine.decode_segment",
                "ContinuousBatchingEngine._decode_segment_spec",
                "ContinuousBatchingEngine.load",
                "PagedContinuousBatchingEngine.decode_segment",
                "PagedContinuousBatchingEngine.grow_for_segment"},
        }
        for rel, want in expected.items():
            with open(os.path.join(REPO, rel)) as f:
                mod = Module(rel, f.read())
            got = {mod.qualname(fn) for fn in hot_functions(mod)}
            assert want <= got, f"{rel}: hot roots {want - got} missing"

    def test_cli_summary_and_exit_codes(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("import jax\n"
                       "def f(x):\n"
                       "    return jax.jit(lambda a: a)(x)\n")
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        r = subprocess.run(
            [sys.executable, "-m", "tools.lint", str(bad),
             "--no-baseline"],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=120)
        assert r.returncode == 1
        assert "PT001" in r.stdout and "fingerprint:" in r.stdout
        r2 = subprocess.run(
            [sys.executable, "-m", "tools.lint", str(bad),
             "--no-baseline", "--checks", "PT003", "--summary"],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=120)
        assert r2.returncode == 0
        assert "paddle_tpu-lint summary" in r2.stdout
