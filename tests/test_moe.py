"""MoE tests (reference analogs: test/collective/test_moe_api.py and the
dispatch math of global_scatter/global_gather): routing correctness with
ample capacity, capacity drop behavior, gates, training, ep-mesh parity."""
import numpy as np
import pytest

# minutes-scale multi-device/parity suite on the CPU backend:
# rides the slow tier (run with -m slow), not tier-1
pytestmark = pytest.mark.slow

import jax
import jax.numpy as jnp
import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.topology import build_mesh, set_mesh
from paddle_tpu.incubate.distributed.models.moe import (GShardGate, MoELayer,
                                                        NaiveGate, SwitchGate)
from paddle_tpu.incubate.distributed.models.moe.moe_layer import (
    _build_dispatch, moe_combine, moe_dispatch)

D = 8


def experts(n, d=D):
    return [nn.Sequential(nn.Linear(d, 2 * d), nn.ReLU(), nn.Linear(2 * d, d))
            for _ in range(n)]


class TestDispatchMath:
    def test_positions_unique_per_expert(self):
        idx = jnp.array([[0], [0], [1], [0]], jnp.int32)
        val = jnp.ones((4, 1), jnp.float32)
        disp, comb = _build_dispatch(idx, val, num_expert=2, capacity=4)
        # expert 0 received tokens 0,1,3 in slots 0,1,2
        assert bool(disp[0, 0, 0]) and bool(disp[1, 0, 1]) and bool(disp[3, 0, 2])
        assert bool(disp[2, 1, 0])
        # each (e, c) slot holds at most one token
        assert int(jnp.max(jnp.sum(disp, axis=0))) == 1

    def test_capacity_drop(self):
        idx = jnp.zeros((5, 1), jnp.int32)  # all tokens → expert 0
        val = jnp.ones((5, 1), jnp.float32)
        disp, comb = _build_dispatch(idx, val, num_expert=2, capacity=2)
        assert int(jnp.sum(disp)) == 2  # 3 dropped
        # dropped tokens have zero combine weight → output zeros for them
        assert float(jnp.sum(comb[2:])) == 0.0

    def test_round_trip_identity(self):
        # with capacity >= T and top-1 full-weight routing, dispatch+combine
        # reproduces per-token expert outputs exactly
        T, E, C = 6, 3, 6
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(T, D).astype(np.float32))
        idx = jnp.asarray(rng.randint(0, E, (T, 1)).astype(np.int32))
        val = jnp.ones((T, 1), jnp.float32)
        ein, comb = moe_dispatch(x, idx, val, E, C)
        # identity experts
        y = moe_combine(ein, comb, x.dtype)
        np.testing.assert_allclose(np.asarray(y), np.asarray(x), rtol=1e-5)

    def test_topk_weights_normalized(self):
        idx = jnp.array([[0, 1]], jnp.int32)
        val = jnp.array([[3.0, 1.0]], jnp.float32)
        disp, comb = _build_dispatch(idx, val, num_expert=2, capacity=2)
        np.testing.assert_allclose(float(jnp.sum(comb)), 1.0, rtol=1e-6)
        np.testing.assert_allclose(float(jnp.sum(comb[0, 0])), 0.75, rtol=1e-6)


class TestGates:
    def test_naive_gate_shapes(self):
        g = NaiveGate(D, num_expert=4, topk=2)
        val, idx = g(paddle.ones([6, D]))
        assert tuple(val.shape) == (6, 2) and tuple(idx.shape) == (6, 2)

    def test_gshard_sets_aux_loss(self):
        g = GShardGate(D, num_expert=4)
        val, idx = g(paddle.to_tensor(np.random.randn(6, D).astype(np.float32)))
        loss = g.get_loss()
        assert loss is not None and np.isfinite(float(loss))
        assert g.get_loss() is None  # cleared

    def test_switch_gate_top1(self):
        g = SwitchGate(D, num_expert=4)
        g.eval()
        val, idx = g(paddle.to_tensor(np.random.randn(6, D).astype(np.float32)))
        assert tuple(idx.shape) == (6, 1)
        assert g.get_loss() is not None

    def test_gate_topk_validation(self):
        with pytest.raises(ValueError):
            GShardGate(D, 4, topk=3)
        with pytest.raises(ValueError):
            SwitchGate(D, 4, topk=2)


class TestMoELayer:
    def test_forward_shape(self):
        moe = MoELayer(D, experts(4), gate={"type": "naive", "top_k": 2},
                       capacity_factor=2.0)
        x = paddle.to_tensor(np.random.randn(2, 5, D).astype(np.float32))
        y = moe(x)
        assert tuple(y.shape) == (2, 5, D)

    def test_single_expert_matches_dense(self):
        # one expert with huge capacity ≡ just running the FFN
        ffn = experts(1)[0]
        moe = MoELayer(D, [ffn], gate={"type": "naive", "top_k": 1},
                       capacity_factor=10.0)
        x = paddle.to_tensor(np.random.randn(7, D).astype(np.float32))
        y = moe(x)
        ref = ffn(x)
        np.testing.assert_allclose(y.numpy(), ref.numpy(), rtol=1e-4)

    def test_training_reduces_loss(self):
        from paddle_tpu.optimizer import AdamW

        moe = MoELayer(D, experts(4), gate={"type": "switch"},
                       capacity_factor=4.0)
        opt = AdamW(learning_rate=1e-2, parameters=moe.parameters())
        x = paddle.to_tensor(np.random.randn(16, D).astype(np.float32))
        losses = []
        for _ in range(5):
            y = moe(x)
            loss = ((y - 1.0) ** 2).mean() + 0.01 * moe.gate.get_loss()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_ep_mesh_parity(self):
        """Same numbers with and without an ep axis on the mesh (the
        reference's MoE parity contract, adapted to GSPMD placement)."""
        x = np.random.randn(8, D).astype(np.float32)
        moe = MoELayer(D, experts(4), gate={"type": "naive", "top_k": 2},
                       capacity_factor=4.0)
        set_mesh(build_mesh(dp=8))
        y_ref = moe(paddle.to_tensor(x)).numpy()
        set_mesh(build_mesh(ep=4, dp=2))
        y_ep = moe(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(y_ref, y_ep, rtol=1e-5)

    def test_jit_path(self):
        from paddle_tpu.nn.functional_call import functional_call

        moe = MoELayer(D, experts(2), gate={"type": "naive", "top_k": 1},
                       capacity_factor=4.0)
        params = {k: p.value for k, p in moe.named_parameters()}
        x = np.random.randn(6, D).astype(np.float32)

        @jax.jit
        def f(params, x):
            return functional_call(moe, params, paddle.Tensor(x))

        y = f(params, x)
        y2 = moe(paddle.Tensor(x))
        np.testing.assert_allclose(np.asarray(y), y2.numpy(), rtol=2e-4,
                                   atol=1e-5)


class TestSortedDispatch:
    """Sort-based dispatch (VERDICT r4 #7): the dense GShard path builds
    two [T, E, C] tensors; the segment-sort plan must reproduce it
    EXACTLY (same keep/drop set — token ranking is choice-major then
    token order in both) while compiling with temp memory bounded by
    O(T·k) index arrays + the [E·C, d] expert buffer at 1.3B-MoE dims."""

    def _route(self, T=64, E=8, k=2, seed=0, frac_dropped=0.2):
        rng = np.random.RandomState(seed)
        idx = rng.randint(0, E, (T, k)).astype(np.int32)
        drop = rng.rand(T, k) < frac_dropped
        idx = np.where(drop, -1, idx)
        val = rng.rand(T, k).astype(np.float32)
        return idx, val

    @pytest.mark.parametrize("cap_factor", [2.0, 0.4])
    def test_exact_parity_with_dense(self, cap_factor):
        from paddle_tpu.incubate.distributed.models.moe.moe_layer import (
            moe_combine, moe_combine_sorted, moe_dispatch,
            moe_dispatch_sorted)

        T, E, k, d = 64, 8, 2, 16
        idx, val = self._route(T, E, k)
        capacity = max(1, int(np.ceil(cap_factor * k * T / E)))
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(T, d).astype(np.float32))
        ein_d, comb = moe_dispatch(x, jnp.asarray(idx), jnp.asarray(val),
                                   E, capacity)
        ein_s, (ts, ws, slot, kept) = moe_dispatch_sorted(
            x, jnp.asarray(idx), jnp.asarray(val), E, capacity)
        np.testing.assert_allclose(np.asarray(ein_s), np.asarray(ein_d),
                                   rtol=1e-6, atol=1e-6)
        eo = jnp.asarray(rng.randn(E, capacity, d).astype(np.float32))
        y_d = moe_combine(eo, comb, jnp.float32)
        y_s = moe_combine_sorted(eo, ts, ws, slot, kept, T, jnp.float32)
        np.testing.assert_allclose(np.asarray(y_s), np.asarray(y_d),
                                   rtol=1e-5, atol=1e-5)

    def test_grad_parity_with_dense(self):
        """d(y)/d(x) and d(y)/d(val) agree between the paths — the gate
        must learn identically whichever dispatch runs."""
        from paddle_tpu.incubate.distributed.models.moe.moe_layer import (
            moe_combine, moe_combine_sorted, moe_dispatch,
            moe_dispatch_sorted)

        T, E, k, d = 32, 4, 2, 8
        idx, val = self._route(T, E, k, seed=3)
        capacity = max(1, int(np.ceil(1.2 * k * T / E)))
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(T, d).astype(np.float32))
        idxj, valj = jnp.asarray(idx), jnp.asarray(val)

        def f_dense(xv, vv):
            ein, comb = moe_dispatch(xv, idxj, vv, E, capacity)
            return jnp.sum(moe_combine(ein * 1.5, comb, jnp.float32) ** 2)

        def f_sort(xv, vv):
            ein, plan = moe_dispatch_sorted(xv, idxj, vv, E, capacity)
            return jnp.sum(moe_combine_sorted(ein * 1.5, *plan, T,
                                              jnp.float32) ** 2)

        gd = jax.grad(f_dense, argnums=(0, 1))(x, valj)
        gs = jax.grad(f_sort, argnums=(0, 1))(x, valj)
        for a, b in zip(gs, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_layer_output_parity_across_modes(self):
        T_, E_ = 16, 4
        rng = np.random.RandomState(5)
        xin = rng.randn(2, T_ // 2, D).astype(np.float32)
        outs = {}
        for mmode in ("dense", "sort"):
            paddle.seed(11)
            layer = MoELayer(
                D, experts=[nn.Linear(D, D) for _ in range(E_)],
                gate={"type": "gshard", "top_k": 2},
                dispatch_mode=mmode)
            outs[mmode] = np.asarray(
                layer(paddle.to_tensor(xin)).value, np.float32)
        np.testing.assert_allclose(outs["sort"], outs["dense"],
                                   rtol=1e-5, atol=1e-5)

    def test_no_tec_materialization_at_1b3_dims(self):
        """Compile-only at ERNIE-MoE scale (T=8192, E=64, d=2048, top-2):
        the dense path's [T, E, C] pair alone is ~1.2 GB; the sorted
        dispatch+combine round trip must compile with temp memory far
        below that (the plan is O(T·k); the expert buffer dominates)."""
        T, E, k, d = 8192, 64, 2, 2048
        capacity = int(np.ceil(1.2 * k * T / E))          # 308
        tec_bytes = T * E * capacity * 4                   # one fp32 [T,E,C]
        from paddle_tpu.incubate.distributed.models.moe.moe_layer import (
            moe_combine_sorted, moe_dispatch_sorted)

        def roundtrip(x, idx, val):
            ein, plan = moe_dispatch_sorted(x, idx, val, E, capacity)
            return moe_combine_sorted(ein * 2.0, *plan, T, jnp.float32)

        lowered = jax.jit(roundtrip).lower(
            jax.ShapeDtypeStruct((T, d), jnp.bfloat16),
            jax.ShapeDtypeStruct((T, k), jnp.int32),
            jax.ShapeDtypeStruct((T, k), jnp.float32))
        mem = lowered.compile().memory_analysis()
        temp = int(getattr(mem, "temp_size_in_bytes", 0))
        assert temp > 0, "memory analysis degenerate"
        assert temp < tec_bytes // 2, (
            f"sorted dispatch temp {temp/2**20:.0f} MiB not clearly below "
            f"a single [T,E,C] one-hot ({tec_bytes/2**20:.0f} MiB) — is it "
            "materializing dense routing tensors?")

    def test_auto_mode_picks_sort_at_scale(self):
        layer = MoELayer(D, experts=[nn.Linear(D, D) for _ in range(4)],
                         gate={"type": "naive", "top_k": 2})
        assert layer.dispatch_mode == "auto"
        with pytest.raises(ValueError, match="dispatch_mode"):
            MoELayer(D, experts=[nn.Linear(D, D)], dispatch_mode="fast")

    def test_auto_threshold_policy(self):
        from paddle_tpu.incubate.distributed.models.moe.moe_layer import (
            _pick_dispatch_mode)

        assert _pick_dispatch_mode(16, 4, 8) == "dense"
        # ERNIE-MoE scale: T=8192, E=64, C=308 -> 161M > 2^24
        assert _pick_dispatch_mode(8192, 64, 308) == "sort"
        # boundary: exactly at the threshold stays dense, one past flips
        assert _pick_dispatch_mode(1 << 24, 1, 1) == "dense"
        assert _pick_dispatch_mode((1 << 24) + 1, 1, 1) == "sort"

    def test_parity_under_ep_sharded_mesh(self):
        """Both dispatch paths must agree UNDER SPMD too: the expert
        buffers ride an ep-sharded constraint (the reference's
        global_scatter boundary) and the sorted plan's scatter/gather
        must partition without changing results. (Measured on the
        8-device CPU mesh: the sorted lowering also uses fewer
        collectives and ~2.3x less temp memory than the dense einsum —
        not asserted, XLA strategy choices move between versions.)"""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from paddle_tpu.distributed.topology import get_mesh
        from paddle_tpu.incubate.distributed.models.moe.moe_layer import (
            moe_combine_sorted, moe_dispatch_sorted)

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        T, E, k, d = 256, 8, 2, 16
        cap = int(np.ceil(1.2 * k * T / E))
        prev = get_mesh()
        m = build_mesh(ep=8)
        set_mesh(m)
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(T, d).astype(np.float32))
        idx = jnp.asarray(rng.randint(0, E, (T, k)).astype(np.int32))
        val = jnp.asarray(rng.rand(T, k).astype(np.float32))
        w = jnp.asarray(rng.randn(E, d, d).astype(np.float32) * 0.1)

        def f(mode):
            def g(x, idx, val, w):
                if mode == "sort":
                    ein, plan = moe_dispatch_sorted(x, idx, val, E, cap)
                else:
                    ein, comb = moe_dispatch(x, idx, val, E, cap)
                ein = jax.lax.with_sharding_constraint(
                    ein, NamedSharding(m, P("ep")))
                out = jnp.einsum("ecd,edf->ecf", ein, w)
                if mode == "sort":
                    return moe_combine_sorted(out, *plan, T, jnp.float32)
                return moe_combine(out, comb, jnp.float32)

            return np.asarray(jax.jit(g)(x, idx, val, w))

        try:
            np.testing.assert_allclose(f("sort"), f("dense"), rtol=1e-4,
                                       atol=1e-5)
        finally:
            set_mesh(prev)  # don't leak the ep mesh to other tests
