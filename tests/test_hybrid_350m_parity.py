"""Hybrid-parallel parity at 350M per-layer dimensions (VERDICT r4 #5).

The tiny-dims hybrid tests (test_pp_sharded.py) prove the composition
compiles and descends; THIS file is the largest correctness proof the
CPU environment can host: a 4-layer slice of the EXACT 350M llama layer
geometry (hidden 1024, 16 heads -> head_dim 64, intermediate 2816,
vocab 32000 — models/llama.py preset table) trained for 3 steps under
the residual-stashing 1F1B hybrid schedule (dp2 x pp2 x mp2 on the
8-device virtual mesh, models/llama_pp.py build_llama_hybrid_step) must
reproduce the SERIAL single-device AdamW trajectory step for step.

Loss parity at step 0 checks forward sharding; trajectory parity at
steps 1..2 checks the gradients and optimizer update too (AdamW's
m-hat/v-hat ratio amplifies any grad mismatch immediately).

head_dim 64 also routes these shapes through the sub-lane flash plan on
device — on CPU the interpret path runs, but the hand-split decoder
backward (models/llama_residual.py) is the same code the TPU executes.

Reference analog: test/collective/fleet/hybrid_parallel_pp_transformer.py
(loss parity of the pipeline composition vs the single-process model).
"""
import numpy as np
import pytest

import jax

from paddle_tpu.distributed.topology import build_mesh, set_mesh
from paddle_tpu.models.llama import LlamaConfig
from paddle_tpu.models.llama_functional import build_train_step, stack_params
from paddle_tpu.models.llama_pp import build_llama_hybrid_step

pytestmark = pytest.mark.slow


def _cfg_350m_slice(layers=4):
    return LlamaConfig(
        vocab_size=32000, hidden_size=1024, intermediate_size=2816,
        num_hidden_layers=layers, num_attention_heads=16,
        num_key_value_heads=16, max_position_embeddings=256)


def _params(cfg, seed=0):
    from paddle_tpu.models import LlamaForCausalLM

    np.random.seed(seed)
    model = LlamaForCausalLM(cfg)
    return stack_params({k: p.value for k, p in model.named_parameters()},
                        cfg)


def test_resid_1f1b_hybrid_matches_serial_trajectory():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg = _cfg_350m_slice()
    stacked, rest = _params(cfg)
    rng = np.random.RandomState(1)
    B, S, steps = 8, 128, 3
    ids = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    y = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)

    # serial reference trajectory (copies first: hybrid prepare()/step()
    # donate + may alias buffers)
    s_np = jax.tree_util.tree_map(np.asarray, stacked)
    r_np = jax.tree_util.tree_map(np.asarray, rest)
    step_s, init_s = build_train_step(cfg, lr=1e-3, remat=False)
    st = init_s(stacked, rest)
    serial = []
    for _ in range(steps):
        stacked, rest, st, loss = step_s(stacked, rest, st, ids, y)
        serial.append(float(loss))

    # residual-stashing 1F1B over dp2 x pp2 x mp2
    mesh = build_mesh(dp=2, pp=2, mp=2, sharding=1,
                      devices=jax.devices()[:8])
    set_mesh(mesh)
    step_h, prepare = build_llama_hybrid_step(
        cfg, mesh, accumulate_steps=4, lr=1e-3, remat=False,
        stash="residuals")
    blocks, edge, sth = prepare(jax.tree_util.tree_map(np.copy, s_np),
                                jax.tree_util.tree_map(np.copy, r_np))
    hybrid = []
    for _ in range(steps):
        blocks, edge, sth, loss = step_h(blocks, edge, sth, ids, y)
        hybrid.append(float(loss))

    assert all(np.isfinite(hybrid)), hybrid
    # step-0 parity = forward sharding; steps 1..2 = grad + AdamW parity
    np.testing.assert_allclose(hybrid, serial, rtol=2e-3, atol=2e-4)
    assert hybrid[-1] < hybrid[0]  # and it actually trains
