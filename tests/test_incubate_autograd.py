"""incubate.autograd functional-AD tests (reference
incubate/autograd/__init__.py surface: Jacobian/Hessian/jvp/vjp + prim
toggles)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate import autograd as iag


def t(x):
    return paddle.to_tensor(np.asarray(x, np.float32))


class TestJacobianHessian:
    def test_jacobian_matches_analytic(self):
        # f(x) = [x0^2, 2*x1] -> J = [[2x0, 0], [0, 2]]
        def f(x):
            import paddle_tpu as paddle

            return paddle.concat([(x[0] ** 2).reshape([1]),
                                  (2 * x[1]).reshape([1])])

        x = t([3.0, 5.0])
        J = iag.Jacobian(f, x)
        np.testing.assert_allclose(J[:].value, [[6.0, 0.0], [0.0, 2.0]],
                                   rtol=1e-6)
        assert J.shape == (2, 2)

    def test_hessian_of_quadratic(self):
        def f(x):
            return (x * x).sum()

        H = iag.Hessian(f, t([1.0, 2.0, 3.0]))
        np.testing.assert_allclose(H[:].value, 2 * np.eye(3), rtol=1e-6)


class TestJvpVjp:
    def test_jvp(self):
        def f(x):
            return x ** 3

        out, tang = iag.jvp(f, t([2.0]), t([1.0]))
        np.testing.assert_allclose(out.value, [8.0], rtol=1e-6)
        np.testing.assert_allclose(tang.value, [12.0], rtol=1e-6)  # 3x^2

    def test_vjp(self):
        def f(x):
            return x ** 2

        out, g = iag.vjp(f, t([3.0, 4.0]), t([1.0, 1.0]))
        np.testing.assert_allclose(g.value, [6.0, 8.0], rtol=1e-6)

    def test_vjp_multi_input(self):
        def f(a, b):
            return a * b

        out, (ga, gb) = iag.vjp(f, [t([2.0]), t([5.0])], t([1.0]))
        np.testing.assert_allclose(ga.value, [5.0], rtol=1e-6)
        np.testing.assert_allclose(gb.value, [2.0], rtol=1e-6)


class TestPrimToggles:
    def test_toggles(self):
        assert iag.prim_enabled() is False
        iag.enable_prim()
        assert iag.prim_enabled() is True
        iag.disable_prim()
        assert iag.prim_enabled() is False
        assert iag.prim2orig() is None

    def test_forward_grad_actionable(self):
        with pytest.raises(NotImplementedError, match="jvp"):
            iag.forward_grad(None, None)


class TestReviewRegressions:
    def test_grad_delegates(self):
        x = t([2.0, 3.0])
        x.stop_gradient = False
        y = (x ** 2).sum()
        (g,) = iag.grad(y, [x])
        np.testing.assert_allclose(g.value, [4.0, 6.0], rtol=1e-6)

    def test_hessian_multi_input_cross_terms(self):
        # f(x, y) = x*y -> full hessian [[0, 1], [1, 0]]
        def f(a, b):
            return (a * b).sum()

        H = iag.Hessian(f, [t([1.0]), t([1.0])])
        np.testing.assert_allclose(H[:].value, [[0.0, 1.0], [1.0, 0.0]],
                                   atol=1e-6)

    def test_vjp_multi_output(self):
        def f(a):
            return (a * 2, a * 3)

        out, g = iag.vjp(f, t([1.0, 1.0]))
        np.testing.assert_allclose(g.value, [5.0, 5.0], rtol=1e-6)  # 2+3


class TestJacobianLayouts:
    def test_scalar_second_input(self):
        # f(a, b) = a * b with b scalar: J = [diag-ish | a] (3, 4)
        def f(a, b):
            return a * b

        J = iag.Jacobian(f, [t([1.0, 2.0, 3.0]), t(2.0)])
        m = np.asarray(J[:].value)
        assert m.shape == (3, 4)
        np.testing.assert_allclose(m[:, :3], 2.0 * np.eye(3), rtol=1e-6)
        np.testing.assert_allclose(m[:, 3], [1.0, 2.0, 3.0], rtol=1e-6)

    def test_batch_axis_validation(self):
        with pytest.raises(ValueError, match="batch_axis"):
            iag.Jacobian(lambda x: x, t([1.0]), batch_axis=1)

    def test_hessian_rejects_vector_output(self):
        with pytest.raises(TypeError, match="scalar-output"):
            iag.Hessian(lambda x: x ** 2, t([1.0, 2.0]))[:]

    def test_pure_fp16_decorate_is_o2(self):
        from paddle_tpu.static import amp as samp
        from paddle_tpu.optimizer import SGD

        opt = samp.decorate(SGD(learning_rate=0.1), use_pure_fp16=True)
        assert opt._level == "O2" and opt._dtype == "float16"
