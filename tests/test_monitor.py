"""paddle_tpu.monitor tests: registry semantics (counter/gauge/histogram,
labels, reset), Prometheus/JSONL export round-trip, the instrumented
choke points (op hook, dataloader, paged KV cache, Model.fit callback,
jit tracker), and the disabled-flag zero-overhead contract (no per-op
callable installed, mutators no-op)."""
import json
import os
import re
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor


@pytest.fixture()
def mon():
    """Enabled + clean registry; always disabled again afterwards so the
    profiler suite's `op_span_hook is None` assertions stay true."""
    monitor.enable()
    monitor.reset()
    yield monitor
    monitor.reset()
    monitor.disable()


class TestRegistry:
    def test_counter_inc_and_labels(self, mon):
        c = mon.counter("t_requests_total", "test", ("route",))
        c.labels(route="a").inc()
        c.labels(route="a").inc(2)
        c.labels(route="b").inc(5)
        assert c.labels(route="a").value == 3
        assert c.labels(route="b").value == 5

    def test_counter_monotonic(self, mon):
        c = mon.counter("t_mono_total", "test")
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_unlabeled_vs_labeled_mismatch(self, mon):
        c = mon.counter("t_lbl_total", "test", ("x",))
        with pytest.raises(ValueError):
            c.inc()  # declared labels, used bare
        with pytest.raises(ValueError):
            c.labels(wrong="v").inc()  # wrong label name

    def test_gauge_set_inc_dec(self, mon):
        g = mon.gauge("t_depth", "test")
        g.set(4)
        g.inc()
        g.dec(2)
        assert g.value == 3

    def test_histogram_buckets_sum_count(self, mon):
        h = mon.histogram("t_lat_seconds", "test", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        val = h.value
        assert val["count"] == 5
        assert val["sum"] == pytest.approx(56.05)
        # cumulative: <=0.1 → 1, <=1.0 → 3, <=10.0 → 4 (+Inf implicit 5)
        assert val["buckets"][0.1] == 1
        assert val["buckets"][1.0] == 3
        assert val["buckets"][10.0] == 4

    def test_get_or_create_returns_same_object(self, mon):
        a = mon.counter("t_same_total", "test")
        b = mon.counter("t_same_total", "other help ignored")
        assert a is b

    def test_kind_conflict_raises(self, mon):
        mon.counter("t_conflict", "test")
        with pytest.raises(TypeError):
            mon.gauge("t_conflict", "test")

    def test_labelnames_conflict_raises(self, mon):
        mon.counter("t_lblconf_total", "test", ("a",))
        with pytest.raises(ValueError):
            mon.counter("t_lblconf_total", "test", ("b",))

    def test_reset_zeroes_but_keeps_registration(self, mon):
        c = mon.counter("t_reset_total", "test")
        c.inc(7)
        mon.reset()
        assert c.value == 0
        c.inc()  # the same object keeps working after reset
        assert c.value == 1


class TestDisabled:
    def test_mutators_noop_when_disabled(self, mon):
        c = mon.counter("t_off_total", "test")
        g = mon.gauge("t_off_g", "test")
        h = mon.histogram("t_off_h", "test")
        monitor.disable()
        c.inc()
        g.set(9)
        h.observe(1.0)
        monitor.enable()
        assert c.value == 0
        assert g.value == 0
        assert h.value["count"] == 0

    def test_no_op_hook_when_disabled(self):
        from paddle_tpu.core import op_hooks

        monitor.disable()
        assert op_hooks.op_span_hook is None
        paddle.matmul(paddle.ones([4, 4]), paddle.ones([4, 4]))
        assert op_hooks.op_span_hook is None

    def test_flag_toggles_hook(self):
        from paddle_tpu.core import op_hooks

        paddle.set_flags({"FLAGS_enable_monitor": True})
        try:
            assert monitor.enabled()
            assert op_hooks.op_span_hook is not None
        finally:
            paddle.set_flags({"FLAGS_enable_monitor": False})
        assert not monitor.enabled()
        assert op_hooks.op_span_hook is None


class TestOpHook:
    def test_op_latency_histogram_records(self, mon):
        paddle.matmul(paddle.ones([8, 8]), paddle.ones([8, 8]))
        snap = mon.snapshot()["metrics"]
        samples = snap["paddle_tpu_op_latency_seconds"]["samples"]
        mm = [s for s in samples if s["labels"]["op"] == "matmul"]
        assert mm and mm[0]["count"] >= 1
        assert mm[0]["sum"] > 0


class TestJitTracker:
    def test_cache_miss_counting(self, mon):
        import jax.numpy as jnp

        f = monitor.monitored_jit(lambda x: x + 1, name="t_f")
        f(jnp.ones((2, 2)))
        f(jnp.ones((2, 2)))       # cache hit: no new compile
        f(jnp.ones((3, 3)))       # new shape: compile
        assert monitor.jit_miss_by_fn().get("t_f") == 2
        snap = mon.snapshot()["metrics"]
        # the counters split per PROGRAM (ledger PR): each compiled
        # shape is its own series, so a "who compiled post-warmup"
        # assertion can NAME the violating program, not just the fn
        miss = [s for s in
                snap["paddle_tpu_jit_cache_miss_total"]["samples"]
                if s["labels"]["fn"] == "t_f"]
        assert len(miss) == 2 and all(s["value"] == 1 for s in miss)
        pids = {s["labels"]["program"] for s in miss}
        assert len(pids) == 2
        assert all(pid.startswith("t_f:") for pid in pids)
        secs = [s for s in
                snap["paddle_tpu_jit_compile_seconds_total"]["samples"]
                if s["labels"]["fn"] == "t_f"]
        assert len(secs) == 2 and all(s["value"] > 0 for s in secs)
        assert {s["labels"]["program"] for s in secs} == pids


PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"          # metric name
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'  # first label
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})?'
    r" [-+0-9.eEinfa]+$")                 # value (incl inf/nan)


class TestExport:
    def test_prometheus_text_parses(self, mon):
        mon.counter("t_exp_total", "counts things", ("k",)).labels(
            k="v 1").inc(3)
        mon.gauge("t_exp_gauge", "a gauge").set(2.5)
        mon.histogram("t_exp_seconds", "a histogram",
                      buckets=(1.0,)).observe(0.5)
        text = mon.render_prometheus()
        seen_types = {}
        for line in text.strip().splitlines():
            if line.startswith("# TYPE"):
                _, _, name, kind = line.split()
                seen_types[name] = kind
                continue
            if line.startswith("#"):
                continue
            assert PROM_LINE.match(line), f"unparseable line: {line!r}"
        assert seen_types["t_exp_total"] == "counter"
        assert seen_types["t_exp_gauge"] == "gauge"
        assert seen_types["t_exp_seconds"] == "histogram"
        assert 't_exp_total{k="v 1"} 3' in text
        # histogram contract: bucket lines + _sum + _count
        assert 't_exp_seconds_bucket{le="1.0"} 1' in text
        assert 't_exp_seconds_bucket{le="+Inf"} 1' in text
        assert "t_exp_seconds_count 1" in text

    def test_snapshot_shape(self, mon):
        mon.counter("t_snap_total", "test").inc(2)
        snap = mon.snapshot()
        assert "ts" in snap
        m = snap["metrics"]["t_snap_total"]
        assert m["type"] == "counter"
        assert m["samples"][0]["value"] == 2
        # built-in callback gauge works on every backend
        live = snap["metrics"]["paddle_tpu_live_array_bytes"]
        assert live["samples"][0]["value"] >= 0

    def test_jsonl_roundtrip(self, mon, tmp_path):
        mon.counter("t_jsonl_total", "test", ("who",)).labels(
            who="me").inc(4)
        mon.histogram("t_jsonl_seconds", "test").observe(0.25)
        path = str(tmp_path / "snap.jsonl")
        n = mon.write_jsonl(path, extra={"run": "r1"})
        assert n > 0
        recs = [json.loads(line) for line in open(path)]
        assert all("metric" in r and "ts" in r for r in recs)
        ctr = [r for r in recs if r["metric"] == "t_jsonl_total"][0]
        assert ctr["value"] == 4
        assert ctr["labels"] == {"who": "me"}
        assert ctr["unit"] == "count"
        assert ctr["run"] == "r1"
        hist = [r for r in recs if r["metric"] == "t_jsonl_seconds"][0]
        assert hist["count"] == 1
        assert hist["value"] == pytest.approx(0.25)  # mean
        assert hist["unit"] == "s"

    def test_monitor_report_cli_renders(self, mon, tmp_path):
        sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                        os.pardir, "tools"))
        try:
            import monitor_report
        finally:
            sys.path.pop(0)
        mon.gauge("t_cli_bytes", "test").set(123)
        path = str(tmp_path / "snap.jsonl")
        mon.write_jsonl(path)
        with open(path) as f:
            records = monitor_report.load_jsonl(f)
        out = monitor_report.render(records, filter_="t_cli")
        assert "t_cli_bytes" in out and "123" in out

    def test_http_server_endpoints(self, mon):
        from urllib.request import urlopen

        mon.counter("t_http_total", "test").inc()
        server = mon.start_http_server(port=0)
        try:
            port = server.server_address[1]
            with urlopen(f"http://127.0.0.1:{port}/metrics") as r:
                text = r.read().decode()
            assert "t_http_total 1" in text
            with urlopen(f"http://127.0.0.1:{port}/metrics.json") as r:
                snap = json.load(r)
            assert snap["metrics"]["t_http_total"]["samples"][0][
                "value"] == 1
        finally:
            server.shutdown()


class TestDataLoaderGauges:
    def _loader(self, n=12, batch_size=4, **kw):
        from paddle_tpu.io import DataLoader, Dataset

        class DS(Dataset):
            def __len__(self):
                return n

            def __getitem__(self, i):
                return np.full((4,), i, np.float32)

        return DataLoader(DS(), batch_size=batch_size, **kw)

    def test_wait_histogram_and_batch_counter(self, mon):
        batches = list(self._loader())
        assert len(batches) == 3
        snap = mon.snapshot()["metrics"]
        wait = snap["paddle_tpu_dataloader_wait_seconds"]["samples"][0]
        assert wait["count"] == 3
        total = snap["paddle_tpu_dataloader_batches_total"]["samples"][0]
        assert total["value"] == 3

    def test_thread_workers_report_queue_depth(self, mon):
        batches = list(self._loader(num_workers=2,
                                    use_shared_memory=False))
        assert len(batches) == 3
        snap = mon.snapshot()["metrics"]
        assert "paddle_tpu_dataloader_queue_depth" in snap
        wait = snap["paddle_tpu_dataloader_wait_seconds"]["samples"][0]
        assert wait["count"] == 3

    def test_disabled_records_nothing(self):
        monitor.disable()
        monitor.reset()
        list(self._loader())
        snap = monitor.snapshot()["metrics"]
        m = snap.get("paddle_tpu_dataloader_batches_total")
        assert m is None or not m["samples"]


class TestPagedCacheGauges:
    def test_occupancy_follows_ensure_and_free(self, mon):
        from paddle_tpu.inference.paged_cache import PageAllocator

        alloc = PageAllocator(num_pages=8, page_size=4, max_batch=2,
                              max_pages=4)
        pool = alloc.monitor_pool
        # the pages gauge carries the storage dtype since quantized KV
        # (int8 pools hold ~2x pages at fixed HBM, so a page count is
        # only comparable with its dtype attached)
        pages = mon.gauge("paddle_tpu_kv_pages", "",
                          ("pool", "state", "kv_dtype"))
        lab = dict(pool=pool, kv_dtype="bf16")
        assert pages.labels(state="free", **lab).value == 8
        alloc.ensure(0, 10)  # 3 pages
        assert pages.labels(state="free", **lab).value == 5
        assert pages.labels(state="used", **lab).value == 3
        occ = mon.gauge("paddle_tpu_kv_page_occupancy_ratio", "",
                        ("pool",))
        assert occ.labels(pool=pool).value == pytest.approx(3 / 8)
        alloc.free_slot(0)
        assert pages.labels(state="free", **lab).value == 8
        assert occ.labels(pool=pool).value == 0.0

    def test_two_pools_publish_independently(self, mon):
        from paddle_tpu.inference.paged_cache import PageAllocator

        a = PageAllocator(num_pages=8, page_size=4, max_batch=2,
                          max_pages=4)
        b = PageAllocator(num_pages=4, page_size=4, max_batch=2,
                          max_pages=2)
        a.ensure(0, 8)   # 2 of 8 pages
        b.ensure(0, 4)   # 1 of 4 pages
        occ = mon.gauge("paddle_tpu_kv_page_occupancy_ratio", "",
                        ("pool",))
        assert occ.labels(pool=a.monitor_pool).value == pytest.approx(
            2 / 8)
        assert occ.labels(pool=b.monitor_pool).value == pytest.approx(
            1 / 4)


class TestSeriesRetirement:
    def test_no_per_instance_series_survive_close_and_shutdown(
            self, mon):
        """ONE regression for the remove_series hardening PRs 3-7 each
        re-fixed by hand: after ``Server.shutdown()`` + engine
        ``close()``, the registry must hold ZERO series labeled with
        any of the retired instances' labels (server=..., engine=...,
        pool=...) — whatever metric family they rode in on. A metric
        added later with a forgotten retirement fails HERE instead of
        in a future PR's review cycle."""
        import numpy as np

        import paddle_tpu as paddle
        from paddle_tpu.inference.generation import (
            GenerationConfig, PagedContinuousBatchingEngine)
        from paddle_tpu.models import LlamaForCausalLM, llama_config
        from paddle_tpu.serving import Server

        paddle.seed(0)
        cfg = llama_config("tiny", num_hidden_layers=1)
        model = LlamaForCausalLM(cfg)
        eng = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=16, page_size=4,
            max_pages=8, prefix_cache=True)
        srv = Server(eng, segment_steps=4)
        labels = {"server": srv.monitor_server,
                  "engine": eng._monitor_engine,
                  "pool": eng.alloc.monitor_pool}
        h = srv.submit(np.arange(1, 7, dtype=np.int32),
                       GenerationConfig(max_new_tokens=4,
                                        eos_token_id=None))
        h.result(timeout=120)

        def instance_series():
            leaked = []
            for name, meta in monitor.snapshot()["metrics"].items():
                for s in meta["samples"]:
                    for k, v in labels.items():
                        if s["labels"].get(k) == v:
                            leaked.append((name, s["labels"]))
            return leaked

        # the run exercised the instrumented paths: the instances ARE
        # exporting series before retirement (else the assert below
        # would pass vacuously)
        assert instance_series(), "no per-instance series were created"
        srv.shutdown()
        eng.close()
        leaked = instance_series()
        assert leaked == [], (
            f"per-instance series survived shutdown+close (add them "
            f"to the owner's retirement list): {leaked}")

    def test_ledger_series_retire_with_engine(self, mon):
        """Same contract extended to the program ledger: after
        ``Server.shutdown()`` + ``engine.close()`` the registry holds
        ZERO {program=...} series for the programs the engine owned
        (dispatches/seconds counters and the MFU gauge), and the
        ledger itself has dropped the records."""
        import numpy as np

        import paddle_tpu as paddle
        from paddle_tpu.inference.generation import (
            GenerationConfig, PagedContinuousBatchingEngine)
        from paddle_tpu.models import LlamaForCausalLM, llama_config
        from paddle_tpu.monitor import ledger
        from paddle_tpu.serving import Server

        paddle.seed(0)
        ledger.reset()
        ledger.enable()
        try:
            cfg = llama_config("tiny", num_hidden_layers=1)
            model = LlamaForCausalLM(cfg)
            eng = PagedContinuousBatchingEngine(
                model, max_batch=2, num_pages=16, page_size=4,
                max_pages=8)
            srv = Server(eng, segment_steps=4)
            h = srv.submit(np.arange(1, 7, dtype=np.int32),
                           GenerationConfig(max_new_tokens=4,
                                            eos_token_id=None))
            h.result(timeout=120)
            owned = set(ledger.owned_programs(eng._monitor_engine))
            assert owned, "engine registered no ledger programs"

            def ledger_series():
                leaked = []
                snap = monitor.snapshot()["metrics"]
                for name in (ledger.DISPATCH_COUNTER,
                             ledger.SECONDS_COUNTER,
                             ledger.MFU_GAUGE):
                    for samp in snap.get(name, {}).get("samples", []):
                        if samp["labels"].get("program") in owned:
                            leaked.append((name, samp["labels"]))
                return leaked

            assert ledger_series(), "no ledger series were created"
            srv.shutdown()
            eng.close()
            leaked = ledger_series()
            assert leaked == [], (
                f"ledger series survived shutdown+close: {leaked}")
            assert ledger.owned_programs(eng._monitor_engine) == []
            for pid in owned:
                assert pid not in ledger.profile()["programs"]
        finally:
            ledger.disable()
            ledger.reset()


@pytest.mark.slow
class TestEndToEndAcceptance:
    """ISSUE acceptance: snapshot() carries step throughput, jit compile
    count, HBM bytes, dataloader wait, and KV-page occupancy after a
    small Model.fit + paged-decode run on the CPU backend."""

    def test_fit_and_paged_decode_populate_snapshot(self, mon):
        from paddle_tpu import nn
        import paddle_tpu.optimizer as opt
        from paddle_tpu.io import Dataset

        class DS(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                rng = np.random.RandomState(i)
                return (rng.randn(8).astype(np.float32),
                        rng.randn(2).astype(np.float32))

        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                            nn.Linear(16, 2))
        model = paddle.Model(net)
        model.prepare(
            optimizer=opt.SGD(learning_rate=0.01,
                              parameters=net.parameters()),
            loss=nn.MSELoss())
        model.fit(DS(), batch_size=4, epochs=1, verbose=0)

        from paddle_tpu.inference.generation import (
            GenerationConfig, PagedContinuousBatchingEngine)
        from paddle_tpu.models import LlamaForCausalLM, llama_config

        cfg = llama_config("tiny", num_hidden_layers=1)
        eng = PagedContinuousBatchingEngine(
            LlamaForCausalLM(cfg), max_batch=2, num_pages=16,
            page_size=8, max_pages=8)
        outs = eng.serve([np.array([[1, 2, 3]], np.int32),
                          np.array([[4, 5]], np.int32)],
                         GenerationConfig(max_new_tokens=4),
                         segment_steps=2)
        assert all(o.shape == (4,) for o in outs)

        snap = mon.snapshot()["metrics"]
        required = (
            "paddle_tpu_train_throughput_samples_per_sec",  # throughput
            "paddle_tpu_train_step_seconds",
            "paddle_tpu_jit_cache_miss_total",              # compiles
            "paddle_tpu_hbm_bytes",                         # HBM
            "paddle_tpu_live_array_bytes",                  # HBM proxy
            "paddle_tpu_dataloader_wait_seconds",           # starvation
            "paddle_tpu_kv_page_occupancy_ratio",           # paged KV
            "paddle_tpu_kv_admission_seconds",
            "paddle_tpu_generated_tokens_total",
        )
        for name in required:
            assert name in snap and snap[name]["samples"], name
        tokens = snap["paddle_tpu_generated_tokens_total"]["samples"][0]
        assert tokens["value"] >= 8  # 2 requests x 4 new tokens
        req = {s["labels"]["event"]: s["value"]
               for s in snap["paddle_tpu_requests_total"]["samples"]}
        assert req == {"admitted": 2, "finished": 2}
        # the whole registry still exports cleanly after a real run
        text = mon.render_prometheus()
        for line in text.strip().splitlines():
            if not line.startswith("#"):
                assert PROM_LINE.match(line), line
