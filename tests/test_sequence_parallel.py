"""Sequence-parallel tests: ring attention and Ulysses must match exact
single-device attention bit-for-bit-ish on an 8-way sp mesh (the parity
contract extends SURVEY.md §4.2 to the new sp axis)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from paddle_tpu.distributed.sequence_parallel import (ring_attention,
                                                      split_sequence,
                                                      ulysses_attention)
from paddle_tpu.distributed.topology import build_mesh, set_mesh

B, S, H, D = 2, 32, 8, 16


def ref_attention(q, k, v, causal):
    scale = 1.0 / np.sqrt(D)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v)


def qkv(seed=0):
    rng = np.random.RandomState(seed)
    return [jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
            for _ in range(3)]


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_exact_on_sp_mesh(self, causal):
        mesh = build_mesh(sp=8)
        set_mesh(mesh)
        q, k, v = qkv()
        ref = ref_attention(q, k, v, causal)
        out = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, causal=causal, mesh=mesh))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_single_device_fallback(self):
        mesh = build_mesh(dp=8)  # no sp axis
        q, k, v = qkv(1)
        out = ring_attention(q, k, v, causal=True, mesh=mesh)
        ref = ref_attention(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_grads_flow(self):
        mesh = build_mesh(sp=4, dp=2)
        set_mesh(mesh)
        q, k, v = qkv(2)

        def loss(q, k, v):
            return jnp.sum(ring_attention(q, k, v, causal=True, mesh=mesh) ** 2)

        def ref_loss(q, k, v):
            return jnp.sum(ref_attention(q, k, v, True) ** 2)

        g = jax.jit(jax.grad(loss))(q, k, v)
        g_ref = jax.grad(ref_loss)(q, k, v)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   rtol=1e-3, atol=1e-3)


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_exact(self, causal):
        mesh = build_mesh(sp=8)
        set_mesh(mesh)
        q, k, v = qkv(3)
        ref = ref_attention(q, k, v, causal)
        out = jax.jit(lambda q, k, v: ulysses_attention(
            q, k, v, causal=causal, mesh=mesh))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_head_divisibility_check(self):
        mesh = build_mesh(sp=8)
        q = jnp.zeros((1, 16, 4, 8))  # 4 heads, sp=8
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, q, q, mesh=mesh)

    def test_composes_with_sp_sharded_input(self):
        mesh = build_mesh(sp=8)
        set_mesh(mesh)
        q, k, v = qkv(4)

        @jax.jit
        def f(q, k, v):
            q = split_sequence(q, mesh)
            k = split_sequence(k, mesh)
            v = split_sequence(v, mesh)
            return ulysses_attention(q, k, v, causal=True, mesh=mesh)

        out = f(q, k, v)
        ref = ref_attention(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
