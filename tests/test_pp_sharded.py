"""Stage-local compiled PP tests.

Contract (VERDICT r2 #3): params+grads+opt-state must be per-device 1/S of
the replicated path — the reason PP exists at 65B (reference per-stage param
ownership: meta_parallel/parallel_layers/pp_layers.py:239) — while the 1F1B
numerics stay identical to the serial model.
"""
import numpy as np
import pytest

# minutes-scale multi-device/parity suite on the CPU backend:
# rides the slow tier (run with -m slow), not tier-1
pytestmark = pytest.mark.slow

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from paddle_tpu.distributed.fleet.meta_parallel.pp_sharded import (
    blocks_from_stacked, build_sharded_1f1b_grad_fn, stacked_from_blocks)
from paddle_tpu.distributed.topology import build_mesh, set_mesh
from paddle_tpu.models.llama import LlamaConfig
from paddle_tpu.models.llama_functional import build_loss_fn, stack_params
from paddle_tpu.models.llama_pp import (build_llama_hybrid_step,
                                        llama_pp_fns)


def tiny_cfg(layers=8):
    return LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=layers, num_attention_heads=4,
        num_key_value_heads=4, max_position_embeddings=64)


def make_params(cfg, seed=0):
    from paddle_tpu.models import LlamaForCausalLM

    np.random.seed(seed)
    model = LlamaForCausalLM(cfg)
    params = {k: p.value for k, p in model.named_parameters()}
    return stack_params(params, cfg)


def batch(cfg, b=8, s=16, seed=1):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, (b, s)).astype(np.int32)
    y = rng.randint(0, cfg.vocab_size, (b, s)).astype(np.int32)
    return ids, y


class TestBlockLayout:
    def test_roundtrip(self):
        x = {"w": jnp.arange(8 * 3 * 5, dtype=jnp.float32).reshape(8, 3, 5)}
        for S, V in [(4, 1), (2, 2), (8, 1), (1, 1)]:
            b = blocks_from_stacked(x, S, V)
            assert b["w"].shape[:3] == (S, V, 8 // (S * V))
            np.testing.assert_array_equal(stacked_from_blocks(b)["w"], x["w"])

    def test_chunk_placement(self):
        # block[s, k] must hold virtual stage p = k*S + s == layers
        # [p*lpc, (p+1)*lpc)
        x = {"w": jnp.arange(8, dtype=jnp.float32)}
        b = blocks_from_stacked(x, 2, 2)["w"]  # lpc = 2
        for s in range(2):
            for k in range(2):
                p = k * 2 + s
                np.testing.assert_array_equal(b[s, k], [2 * p, 2 * p + 1])

    def test_indivisible_raises(self):
        with pytest.raises(ValueError, match="divisible"):
            blocks_from_stacked({"w": jnp.zeros((6, 2))}, 4, 1)


class TestShardedParity:
    """pp=4 stage-local 1F1B == serial llama loss AND grads."""

    def setup_method(self):
        self.mesh = build_mesh(pp=4, dp=2)
        set_mesh(self.mesh)

    def _parity(self, S, V, mesh):
        cfg = tiny_cfg(8)
        stacked, rest = make_params(cfg)
        ids, y = batch(cfg)
        ref = jax.value_and_grad(
            lambda p: build_loss_fn(cfg, remat=False)(
                p["s"], p["r"], ids, y))({"s": stacked, "r": rest})
        first, body, last = llama_pp_fns(cfg, remat=False)
        gf = build_sharded_1f1b_grad_fn(first, body, last,
                                        accumulate_steps=4, mesh=mesh,
                                        num_virtual_stages=V)
        blocks = blocks_from_stacked(stacked, S, V)
        blocks = {k: jax.device_put(v, NamedSharding(mesh, P("pp")))
                  for k, v in blocks.items()}
        loss, (gb, ge) = jax.jit(gf)(blocks, rest, ids, y)
        ref_loss, ref_g = ref
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=2e-4, atol=2e-5)
        got = stacked_from_blocks(gb)
        for k in stacked:
            np.testing.assert_allclose(np.asarray(got[k]),
                                       np.asarray(ref_g["s"][k]),
                                       rtol=2e-3, atol=2e-4, err_msg=k)
        for k in rest:
            np.testing.assert_allclose(np.asarray(ge[k]),
                                       np.asarray(ref_g["r"][k]),
                                       rtol=2e-3, atol=2e-4, err_msg=k)

    def test_pp4_parity(self):
        self._parity(4, 1, self.mesh)

    def test_pp2_interleaved_v2_parity(self):
        mesh = build_mesh(pp=2, dp=4)
        self._parity(2, 2, mesh)

    def test_serial_s1_matches(self):
        cfg = tiny_cfg(4)
        stacked, rest = make_params(cfg)
        ids, y = batch(cfg, b=4)
        mesh = build_mesh(dp=8)
        first, body, last = llama_pp_fns(cfg, remat=False)
        gf = build_sharded_1f1b_grad_fn(first, body, last,
                                        accumulate_steps=2, mesh=mesh)
        blocks = blocks_from_stacked(stacked, 1, 1)
        loss, _ = gf(blocks, rest, ids, y)
        ref = build_loss_fn(cfg, remat=False)(stacked, rest, ids, y)
        np.testing.assert_allclose(float(loss), float(ref), rtol=2e-4,
                                   atol=2e-5)


class TestStageLocalMemory:
    """The memory contract: per-device param/grad bytes scale as 1/S."""

    def _compiled(self, S, layers=8):
        cfg = tiny_cfg(layers)
        # widen so body params dominate activations
        cfg.hidden_size, cfg.intermediate_size = 64, 256
        stacked, rest = make_params(cfg)
        mesh = build_mesh(pp=S, dp=8 // S)
        first, body, last = llama_pp_fns(cfg, remat=False)
        gf = build_sharded_1f1b_grad_fn(first, body, last,
                                        accumulate_steps=4, mesh=mesh)
        blocks = blocks_from_stacked(stacked, S, 1)
        blocks = {k: jax.device_put(v, NamedSharding(mesh, P("pp")))
                  for k, v in blocks.items()}
        ids, y = batch(cfg, b=4, s=8)
        c = jax.jit(gf).lower(blocks, rest, ids, y).compile()
        return c, blocks

    def test_block_args_sharded_over_pp(self):
        c, blocks = self._compiled(4)
        # every block input sharding splits dim 0 four ways -> per-device
        # argument bytes for the body are exactly 1/4 of the global
        in_sh = c.input_shardings[0]
        n_pp_sharded = 0
        for s in jax.tree.leaves(in_sh, is_leaf=lambda x: hasattr(x, "spec")):
            spec = getattr(s, "spec", None)
            if spec and len(spec) and spec[0] == "pp":
                n_pp_sharded += 1
        assert n_pp_sharded >= len(blocks), (n_pp_sharded, len(blocks))

    def test_temp_memory_scales_with_stages(self):
        """Grad accumulation (the dominant temp at big-param/small-act
        shapes) must be stage-local: pp=4 temp ≲ pp=2 temp · 0.7."""
        c2, _ = self._compiled(2)
        c4, _ = self._compiled(4)
        t2 = c2.memory_analysis().temp_size_in_bytes
        t4 = c4.memory_analysis().temp_size_in_bytes
        assert t4 < t2 * 0.7, (t4, t2)


class TestHybridStep:
    """Composed dp x mp x pp x sharding step (BASELINE config 3 shape)."""

    def _run(self, dp, pp, mp, sharding, V=1, params=None):
        cfg = tiny_cfg(8)
        mesh = build_mesh(dp=dp, pp=pp, mp=mp, sharding=sharding)
        set_mesh(mesh)
        stacked, rest = params if params else make_params(cfg)
        ids, y = batch(cfg)
        step, prepare = build_llama_hybrid_step(
            cfg, mesh, accumulate_steps=4, num_virtual_stages=V,
            lr=1e-2, remat=False)
        blocks, edge, st = prepare(stacked, rest)
        b, e, st, l0 = step(blocks, edge, st, ids, y)
        for _ in range(3):
            b, e, st, l = step(b, e, st, ids, y)
        assert float(l) < float(l0), (float(l), float(l0))
        return float(l0)

    def test_2x2x2x1(self):
        cfg = tiny_cfg(8)
        stacked, rest = make_params(cfg)
        ids, y = batch(cfg)
        # ref BEFORE the hybrid step: step donates its buffers and
        # prepare()'s device_put may alias the originals
        ref = float(build_loss_fn(cfg, remat=False)(stacked, rest, ids, y))
        l_a = self._run(dp=2, pp=2, mp=2, sharding=1,
                        params=(stacked, rest))
        # loss at step0 must agree with the serial model (parity across
        # composition modes, reference fleet/model.py:134-170)
        np.testing.assert_allclose(l_a, ref, rtol=5e-3, atol=5e-4)

    def test_1x2x2x2(self):
        self._run(dp=1, pp=2, mp=2, sharding=2)

    def test_interleaved_2x2_v2(self):
        self._run(dp=2, pp=2, mp=1, sharding=2, V=2)


class TestHybridCheckpointReshape:
    """5.4 depth: a checkpoint saved at pp=4 reloads at pp=2 (canonical
    stacked layout — reference needs pp_parallel_adaptor for this)."""

    def test_save_pp4_load_pp2_loss_identical(self, tmp_path):
        from paddle_tpu.models.llama_pp import (load_hybrid_checkpoint,
                                                save_hybrid_checkpoint)

        cfg = tiny_cfg(8)
        stacked, rest = make_params(cfg)
        ids, y = batch(cfg)
        ref = float(build_loss_fn(cfg, remat=False)(stacked, rest, ids, y))

        mesh4 = build_mesh(pp=4, dp=2)
        set_mesh(mesh4)
        b4 = blocks_from_stacked(stacked, 4, 1)
        save_hybrid_checkpoint(str(tmp_path / "ck"), b4, rest)

        mesh2 = build_mesh(pp=2, dp=4)
        set_mesh(mesh2)
        blocks2, edge2 = load_hybrid_checkpoint(str(tmp_path / "ck"), cfg,
                                                mesh2)
        first, body, last = llama_pp_fns(cfg, remat=False)
        gf = build_sharded_1f1b_grad_fn(first, body, last,
                                        accumulate_steps=4, mesh=mesh2)
        loss, _ = jax.jit(gf)(blocks2, edge2, ids, y)
        np.testing.assert_allclose(float(loss), ref, rtol=2e-4, atol=2e-5)


class TestRematParity:
    """The bench path runs remat=True (jax.checkpoint inside the scanned
    body) — its interplay with the per-tick vjp must not change numerics."""

    def test_pp4_remat_loss_matches_no_remat(self):
        cfg = tiny_cfg(8)
        stacked, rest = make_params(cfg)
        ids, y = batch(cfg)
        mesh = build_mesh(pp=4, dp=2)
        set_mesh(mesh)
        losses = {}
        grads = {}
        for remat in (False, True):
            first, body, last = llama_pp_fns(cfg, remat=remat)
            gf = build_sharded_1f1b_grad_fn(first, body, last,
                                            accumulate_steps=4, mesh=mesh)
            blocks = blocks_from_stacked(stacked, 4, 1)
            blocks = {k: jax.device_put(v, NamedSharding(mesh, P("pp")))
                      for k, v in blocks.items()}
            loss, (gb, _) = jax.jit(gf)(blocks, rest, ids, y)
            losses[remat] = float(loss)
            grads[remat] = stacked_from_blocks(gb)
        np.testing.assert_allclose(losses[True], losses[False], rtol=1e-6)
        for k in grads[True]:
            np.testing.assert_allclose(np.asarray(grads[True][k]),
                                       np.asarray(grads[False][k]),
                                       rtol=1e-5, atol=1e-6, err_msg=k)
