"""Distributed checkpoint tests: sharded save → resharded load across
different mesh layouts (reference contract:
hybrid_parallel_pp_save_load.py / dist_save round-trips)."""
import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import checkpoint as dck
from paddle_tpu.distributed._spmd import set_pspec
from paddle_tpu.distributed.topology import build_mesh, set_mesh


class TestShardedCheckpoint:
    def test_roundtrip_plain(self, tmp_path):
        sd = {"w": paddle.to_tensor(np.random.randn(8, 4).astype(np.float32)),
              "b": paddle.to_tensor(np.zeros(4, np.float32))}
        dck.save_state_dict(sd, str(tmp_path / "ck"))
        out = dck.load_state_dict(str(tmp_path / "ck"))
        np.testing.assert_array_equal(out["w"].numpy(), sd["w"].numpy())

    def test_sharded_save_resharded_load(self, tmp_path):
        # save from an mp-sharded layout...
        set_mesh(build_mesh(mp=8))
        w = np.random.randn(16, 32).astype(np.float32)
        t = paddle.to_tensor(w)
        set_pspec(t, P(None, "mp"))
        from paddle_tpu.distributed._spmd import named_sharding

        t._value = jax.device_put(t._value, named_sharding(P(None, "mp")))
        dck.save_state_dict({"w": t}, str(tmp_path / "ck"))

        # ...load into a DIFFERENT layout (sharding axis over dim 0)
        set_mesh(build_mesh(sharding=8))
        target = paddle.to_tensor(np.zeros((16, 32), np.float32))
        set_pspec(target, P("sharding", None))
        dck.load_state_dict(str(tmp_path / "ck"), {"w": target})
        np.testing.assert_array_equal(np.asarray(target._value), w)
        assert "sharding" in str(target._value.sharding.spec)

    def test_model_state_dict_roundtrip(self, tmp_path):
        set_mesh(build_mesh(dp=8))
        m = nn.Sequential(nn.Linear(8, 16), nn.Linear(16, 4))
        sd = m.state_dict()
        dck.save_state_dict(sd, str(tmp_path / "model_ck"))
        m2 = nn.Sequential(nn.Linear(8, 16), nn.Linear(16, 4))
        sd2 = m2.state_dict()
        dck.load_state_dict(str(tmp_path / "model_ck"), sd2)
        for k in sd:
            np.testing.assert_array_equal(
                np.asarray(sd2[k]._value), sd[k].numpy())

    def test_reshard_state_dict(self):
        set_mesh(build_mesh(sharding=4, dp=2))
        sd = {"w": paddle.to_tensor(np.random.randn(8, 8).astype(np.float32))}
        out = dck.reshard_state_dict(sd, {"w": P("sharding", None)})
        assert "sharding" in str(out["w"]._value.sharding.spec)
        np.testing.assert_array_equal(out["w"].numpy(), sd["w"].numpy())
