"""Optimizer trajectory parity vs torch: identical params/grads/hparams
must produce the same parameter sequences (update rules, bias
correction, decoupled weight decay, epsilon placement are where
optimizer ports silently drift).
"""
import numpy as np
import pytest

import paddle_tpu as paddle

torch = pytest.importorskip("torch")


def run_paddle(opt_name, steps, lr=0.1, **kw):
    w0 = np.linspace(-1, 1, 6).astype(np.float32).reshape(2, 3)
    p = paddle.to_tensor(w0.copy())
    p.stop_gradient = False
    opt_cls = getattr(paddle.optimizer, opt_name)
    opt = opt_cls(learning_rate=lr, parameters=[p], **kw)
    traj = []
    for i in range(steps):
        loss = ((p * p) * (i + 1) * 0.1).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
        traj.append(np.asarray(p.value).copy())
    return traj


def run_torch(opt_cls, steps, lr=0.1, **kw):
    w0 = np.linspace(-1, 1, 6).astype(np.float32).reshape(2, 3)
    p = torch.from_numpy(w0.copy()).requires_grad_(True)
    opt = opt_cls([p], lr=lr, **kw)
    traj = []
    for i in range(steps):
        opt.zero_grad()
        loss = ((p * p) * (i + 1) * 0.1).sum()
        loss.backward()
        opt.step()
        traj.append(p.detach().numpy().copy())
    return traj


def assert_traj(got, want, rtol=1e-4, atol=1e-5):
    for i, (g, w) in enumerate(zip(got, want)):
        np.testing.assert_allclose(g, w, rtol=rtol, atol=atol,
                                   err_msg=f"step {i}")


class TestTrajectories:
    def test_sgd(self):
        assert_traj(run_paddle("SGD", 5),
                    run_torch(torch.optim.SGD, 5))

    def test_momentum(self):
        assert_traj(run_paddle("Momentum", 5, momentum=0.9),
                    run_torch(torch.optim.SGD, 5, momentum=0.9))

    def test_adam(self):
        assert_traj(
            run_paddle("Adam", 6, beta1=0.9, beta2=0.99, epsilon=1e-8),
            run_torch(torch.optim.Adam, 6, betas=(0.9, 0.99), eps=1e-8))

    def test_adamw_decoupled_decay(self):
        assert_traj(
            run_paddle("AdamW", 6, weight_decay=0.05),
            run_torch(torch.optim.AdamW, 6, weight_decay=0.05))

    def test_adagrad(self):
        # paddle Adagrad default initial_accumulator_value=0 matches torch
        assert_traj(
            run_paddle("Adagrad", 5, epsilon=1e-10),
            run_torch(torch.optim.Adagrad, 5, eps=1e-10))

    def test_rmsprop(self):
        assert_traj(
            run_paddle("RMSProp", 5, rho=0.9, epsilon=1e-8),
            run_torch(torch.optim.RMSprop, 5, alpha=0.9, eps=1e-8))

    def test_adamax(self):
        assert_traj(
            run_paddle("Adamax", 5, beta1=0.9, beta2=0.995, epsilon=1e-8),
            run_torch(torch.optim.Adamax, 5, betas=(0.9, 0.995),
                      eps=1e-8))

    def test_adadelta(self):
        assert_traj(
            run_paddle("Adadelta", 5, rho=0.95, epsilon=1e-6),
            run_torch(torch.optim.Adadelta, 5, rho=0.95, eps=1e-6))


class TestLRSchedules:
    """LR schedule value sequences vs torch equivalents."""

    def _pd_seq(self, sched, steps, metric=None):
        out = []
        for _ in range(steps):
            out.append(float(sched()))
            if metric is not None:
                sched.step(metric)
            else:
                sched.step()
        return out

    def _th_seq(self, sched_cls, steps, lr=0.1, metric=None, **kw):
        p = torch.nn.Parameter(torch.zeros(1))
        opt = torch.optim.SGD([p], lr=lr)
        s = sched_cls(opt, **kw)
        out = []
        for _ in range(steps):
            out.append(opt.param_groups[0]["lr"])
            opt.step()
            if metric is not None:
                s.step(metric)
            else:
                s.step()
        return out

    def test_step_decay(self):
        got = self._pd_seq(paddle.optimizer.lr.StepDecay(
            learning_rate=0.1, step_size=3, gamma=0.5), 10)
        want = self._th_seq(torch.optim.lr_scheduler.StepLR, 10,
                            step_size=3, gamma=0.5)
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_multistep_decay(self):
        got = self._pd_seq(paddle.optimizer.lr.MultiStepDecay(
            learning_rate=0.1, milestones=[2, 5], gamma=0.1), 8)
        want = self._th_seq(torch.optim.lr_scheduler.MultiStepLR, 8,
                            milestones=[2, 5], gamma=0.1)
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_exponential_decay(self):
        got = self._pd_seq(paddle.optimizer.lr.ExponentialDecay(
            learning_rate=0.1, gamma=0.8), 6)
        want = self._th_seq(torch.optim.lr_scheduler.ExponentialLR, 6,
                            gamma=0.8)
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_cosine_annealing(self):
        got = self._pd_seq(paddle.optimizer.lr.CosineAnnealingDecay(
            learning_rate=0.1, T_max=10, eta_min=0.01), 10)
        want = self._th_seq(torch.optim.lr_scheduler.CosineAnnealingLR,
                            10, T_max=10, eta_min=0.01)
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_reduce_on_plateau(self):
        sched = paddle.optimizer.lr.ReduceOnPlateau(
            learning_rate=0.1, factor=0.5, patience=1, cooldown=0)
        metrics = [1.0, 1.0, 1.0, 0.5, 0.7, 0.7, 0.7]
        got = []
        for m in metrics:
            got.append(float(sched()))
            sched.step(paddle.to_tensor(np.float32(m)))
        p = torch.nn.Parameter(torch.zeros(1))
        opt = torch.optim.SGD([p], lr=0.1)
        s = torch.optim.lr_scheduler.ReduceLROnPlateau(
            opt, factor=0.5, patience=1, cooldown=0)
        want = []
        for m in metrics:
            want.append(opt.param_groups[0]["lr"])
            s.step(m)
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_lambda_decay(self):
        got = self._pd_seq(paddle.optimizer.lr.LambdaDecay(
            learning_rate=0.1, lr_lambda=lambda e: 0.9 ** e), 6)
        want = self._th_seq(torch.optim.lr_scheduler.LambdaLR, 6,
                            lr_lambda=lambda e: 0.9 ** e)
        np.testing.assert_allclose(got, want, rtol=1e-6)
