"""Optimizer trajectory parity vs torch: identical params/grads/hparams
must produce the same parameter sequences (update rules, bias
correction, decoupled weight decay, epsilon placement are where
optimizer ports silently drift).
"""
import numpy as np
import pytest

import paddle_tpu as paddle

torch = pytest.importorskip("torch")


def run_paddle(opt_name, steps, lr=0.1, **kw):
    w0 = np.linspace(-1, 1, 6).astype(np.float32).reshape(2, 3)
    p = paddle.to_tensor(w0.copy())
    p.stop_gradient = False
    opt_cls = getattr(paddle.optimizer, opt_name)
    opt = opt_cls(learning_rate=lr, parameters=[p], **kw)
    traj = []
    for i in range(steps):
        loss = ((p * p) * (i + 1) * 0.1).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
        traj.append(np.asarray(p.value).copy())
    return traj


def run_torch(opt_cls, steps, lr=0.1, **kw):
    w0 = np.linspace(-1, 1, 6).astype(np.float32).reshape(2, 3)
    p = torch.from_numpy(w0.copy()).requires_grad_(True)
    opt = opt_cls([p], lr=lr, **kw)
    traj = []
    for i in range(steps):
        opt.zero_grad()
        loss = ((p * p) * (i + 1) * 0.1).sum()
        loss.backward()
        opt.step()
        traj.append(p.detach().numpy().copy())
    return traj


def assert_traj(got, want, rtol=1e-4, atol=1e-5):
    for i, (g, w) in enumerate(zip(got, want)):
        np.testing.assert_allclose(g, w, rtol=rtol, atol=atol,
                                   err_msg=f"step {i}")


class TestTrajectories:
    def test_sgd(self):
        assert_traj(run_paddle("SGD", 5),
                    run_torch(torch.optim.SGD, 5))

    def test_momentum(self):
        assert_traj(run_paddle("Momentum", 5, momentum=0.9),
                    run_torch(torch.optim.SGD, 5, momentum=0.9))

    def test_adam(self):
        assert_traj(
            run_paddle("Adam", 6, beta1=0.9, beta2=0.99, epsilon=1e-8),
            run_torch(torch.optim.Adam, 6, betas=(0.9, 0.99), eps=1e-8))

    def test_adamw_decoupled_decay(self):
        assert_traj(
            run_paddle("AdamW", 6, weight_decay=0.05),
            run_torch(torch.optim.AdamW, 6, weight_decay=0.05))

    def test_adagrad(self):
        # paddle Adagrad default initial_accumulator_value=0 matches torch
        assert_traj(
            run_paddle("Adagrad", 5, epsilon=1e-10),
            run_torch(torch.optim.Adagrad, 5, eps=1e-10))

    def test_rmsprop(self):
        assert_traj(
            run_paddle("RMSProp", 5, rho=0.9, epsilon=1e-8),
            run_torch(torch.optim.RMSprop, 5, alpha=0.9, eps=1e-8))

    def test_adamax(self):
        assert_traj(
            run_paddle("Adamax", 5, beta1=0.9, beta2=0.995, epsilon=1e-8),
            run_torch(torch.optim.Adamax, 5, betas=(0.9, 0.995),
                      eps=1e-8))

    def test_adadelta(self):
        assert_traj(
            run_paddle("Adadelta", 5, rho=0.95, epsilon=1e-6),
            run_torch(torch.optim.Adadelta, 5, rho=0.95, eps=1e-6))
