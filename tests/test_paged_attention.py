"""Paged KV-cache decode attention (ops/paged_attention.py +
inference/paged_cache.py).

Reference analog: fused_multi_transformer's decode MHA over contiguous
per-batch cache slabs (fused_multi_transformer_op.cu.h:745); the paged
form completes SURVEY §7's "KV-cache decode kernel with paged/ragged
batching" — the oracle here is the already-parity-tested ragged
``decode_mha`` run over each row's pages gathered dense.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.inference.paged_cache import (PagedKVCache, gather_dense,
                                              write_tokens)
from paddle_tpu.ops.paged_attention import paged_decode_mha
from paddle_tpu.ops.pallas_kernels import decode_mha


def _filled_cache(lens, H=4, D=16, PS=8, MAXP=None, num_pages=None,
                  dtype=jnp.float32, seed=0, interleave=True):
    """Build a pool whose page assignment is deliberately FRAGMENTED:
    slots allocate pages token-by-token in round-robin, so consecutive
    pages of one sequence are scattered across the pool."""
    rng = np.random.RandomState(seed)
    B = len(lens)
    MAXP = MAXP or -(-int(max(lens)) // PS)
    num_pages = num_pages or B * MAXP
    cache = PagedKVCache(num_pages, PS, H, D, B, MAXP, dtype=dtype)
    if interleave:
        for t in range(int(max(lens))):
            for b in range(B):
                if t < lens[b]:
                    cache.ensure(b, t + 1)
    else:
        for b in range(B):
            cache.ensure(b, int(lens[b]))
    for b in range(B):
        n = int(lens[b])
        kt = jnp.asarray(rng.randn(n, H, D), dtype)
        vt = jnp.asarray(rng.randn(n, H, D), dtype)
        cache.k, cache.v = write_tokens(
            cache.k, cache.v, cache.page_table,
            jnp.full((n,), b, jnp.int32), jnp.arange(n, dtype=jnp.int32),
            kt, vt)
    return cache


def _ref(cache, q, lens):
    B = q.shape[0]
    kd = jnp.stack([gather_dense(cache.k, cache.page_table, b)
                    for b in range(B)])
    vd = jnp.stack([gather_dense(cache.v, cache.page_table, b)
                    for b in range(B)])
    return decode_mha(q, kd, vd, jnp.asarray(lens))


class TestPagedParity:
    @pytest.mark.parametrize("dtype,tol", [(jnp.float32, 1e-5),
                                           (jnp.bfloat16, 2e-2)])
    def test_fragmented_pages_match_ragged_kernel(self, dtype, tol):
        lens = np.array([5, 17, 48, 1], np.int32)
        cache = _filled_cache(lens, dtype=dtype)
        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(4, 4, 16), dtype)
        out = paged_decode_mha(q, cache.k, cache.v, cache.page_table,
                               jnp.asarray(lens))
        ref = _ref(cache, q, lens)
        assert out.dtype == dtype
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            rtol=tol, atol=tol)

    def test_page_order_is_what_the_table_says(self):
        """Same pool contents, contiguous vs fragmented tables: results
        must depend only on the table's logical order."""
        lens = np.array([23, 9], np.int32)
        a = _filled_cache(lens, seed=3, interleave=True)
        b = _filled_cache(lens, seed=3, interleave=False)
        assert not np.array_equal(np.asarray(a.page_table),
                                  np.asarray(b.page_table))
        rng = np.random.RandomState(2)
        q = jnp.asarray(rng.randn(2, 4, 16), jnp.float32)
        oa = paged_decode_mha(q, a.k, a.v, a.page_table, jnp.asarray(lens))
        ob = paged_decode_mha(q, b.k, b.v, b.page_table, jnp.asarray(lens))
        np.testing.assert_allclose(np.asarray(oa), np.asarray(ob),
                                   rtol=1e-5, atol=1e-6)

    def test_length_1_and_full_page_edges(self):
        lens = np.array([1, 8, 16], np.int32)  # page boundaries exactly
        cache = _filled_cache(lens, PS=8)
        rng = np.random.RandomState(4)
        q = jnp.asarray(rng.randn(3, 4, 16), jnp.float32)
        out = paged_decode_mha(q, cache.k, cache.v, cache.page_table,
                               jnp.asarray(lens))
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_ref(cache, q, lens)),
                                   rtol=1e-5, atol=1e-5)


class TestAllocator:
    def test_alloc_free_reuse_cycle(self):
        c = PagedKVCache(4, 8, 2, 8, max_batch=3, max_pages=2)
        c.ensure(0, 16)                     # 2 pages
        c.ensure(1, 9)                      # 2 pages (ceil)
        assert c.free_pages == 0
        with pytest.raises(RuntimeError, match="exhausted"):
            c.ensure(2, 1)
        c.free_slot(0)
        assert c.free_pages == 2
        c.ensure(2, 8)                      # reuses a freed page
        assert c.free_pages == 1
        # retired slot's table row is unmapped
        assert int(np.asarray(c.page_table)[0].max()) == -1

    def test_ensure_is_idempotent_and_incremental(self):
        c = PagedKVCache(8, 4, 2, 8, max_batch=1, max_pages=8)
        c.ensure(0, 3)
        assert c.free_pages == 7
        c.ensure(0, 3)                      # no growth
        assert c.free_pages == 7
        c.ensure(0, 5)                      # one more page
        assert c.free_pages == 6
        assert c.can_fit(0, 32) and not c.can_fit(0, 33)

    def test_pool_memory_beats_dense_slabs_on_skewed_lengths(self):
        """The point of paging: B=8 slots, max_len 256, but only one
        long request — dense slabs hold B*max_len tokens, the pool holds
        the tokens in flight."""
        lens = [256, 8, 8, 8, 8, 8, 8, 8]
        PS = 16
        pages_needed = sum(-(-n // PS) for n in lens)   # 23
        dense_pages = 8 * (256 // PS)                   # 128
        assert pages_needed * 4 < dense_pages
        c = PagedKVCache(pages_needed, PS, 4, 16, max_batch=8,
                         max_pages=256 // PS)
        for b, n in enumerate(lens):
            c.ensure(b, n)                  # fits exactly, no error
        assert c.free_pages == 0


class TestWritePath:
    def test_batched_write_lands_in_right_pages(self):
        lens = np.array([10, 20], np.int32)
        cache = _filled_cache(lens, PS=8)
        # overwrite position 9 of row 0 and 17 of row 1 in ONE call
        k_new = jnp.ones((2, 4, 16), jnp.float32) * 7
        v_new = jnp.ones((2, 4, 16), jnp.float32) * 9
        cache.k, cache.v = write_tokens(
            cache.k, cache.v, cache.page_table,
            jnp.array([0, 1], jnp.int32), jnp.array([9, 17], jnp.int32),
            k_new, v_new)
        kd0 = np.asarray(gather_dense(cache.k, cache.page_table, 0))
        kd1 = np.asarray(gather_dense(cache.k, cache.page_table, 1))
        np.testing.assert_array_equal(kd0[9], np.full((4, 16), 7.0))
        np.testing.assert_array_equal(kd1[17], np.full((4, 16), 7.0))
        assert not np.any(kd0[8] == 7.0)    # neighbors untouched


class TestWriteGuards:
    def test_unmapped_write_is_dropped_not_wrapped(self):
        """A write at a position with no mapped page (-1 table entry)
        must be DROPPED — JAX scatter would wrap -1 to the LAST pool
        page and corrupt whoever owns it."""
        c = PagedKVCache(4, 8, 2, 8, max_batch=2, max_pages=2,
                         dtype=jnp.float32)
        c.ensure(1, 16)   # slot 1 owns pages; slot 0 owns NONE
        marker = jnp.full((1, 2, 8), 123.0, jnp.float32)
        before_last = np.asarray(c.k)[-1].copy()
        c.k, c.v = write_tokens(c.k, c.v, c.page_table,
                                jnp.array([0], jnp.int32),
                                jnp.array([0], jnp.int32), marker, marker)
        np.testing.assert_array_equal(np.asarray(c.k)[-1], before_last)
        assert not np.any(np.asarray(c.k) == 123.0)

    def test_ensure_rejects_beyond_max_pages(self):
        c = PagedKVCache(8, 4, 2, 8, max_batch=1, max_pages=2)
        with pytest.raises(ValueError, match="max_pages"):
            c.ensure(0, 12)        # needs 3 pages, table holds 2
        assert c.free_pages == 8   # nothing leaked from the free list


class TestPagedEngine:
    """End-to-end serving over the paged pool: the
    PagedContinuousBatchingEngine must reproduce the ragged engine's
    outputs exactly (same model, same sampling stream) while holding
    only tokens-in-flight worth of cache."""

    def _model(self, layers=2):
        import paddle_tpu as paddle
        from paddle_tpu.models import LlamaForCausalLM, llama_config

        paddle.seed(0)
        cfg = llama_config("tiny", num_hidden_layers=layers)
        return LlamaForCausalLM(cfg), cfg

    def test_greedy_matches_ragged_engine(self):
        from paddle_tpu.inference.generation import (
            ContinuousBatchingEngine, GenerationConfig,
            PagedContinuousBatchingEngine)

        model, cfg = self._model()
        gcfg = GenerationConfig(max_new_tokens=12, do_sample=False,
                                eos_token_id=None)
        rng = np.random.RandomState(0)
        prompts = [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
                   for n in (5, 9, 3)]
        outs_r = ContinuousBatchingEngine(
            model, max_batch=3, max_len=64).serve(prompts, gcfg,
                                                  segment_steps=4)
        paged = PagedContinuousBatchingEngine(
            model, max_batch=3, num_pages=12, page_size=8, max_pages=8)
        outs_p = paged.serve(prompts, gcfg, segment_steps=4)
        for a, b in zip(outs_r, outs_p):
            np.testing.assert_array_equal(a, b)
        # every page returned after all requests retired
        assert paged.alloc.free_pages == 12

    def test_oversubscribed_continuous_serve(self):
        """More requests than slots, sampled decoding, mixed prompt
        lengths — the admission loop must cycle pages correctly."""
        from paddle_tpu.inference.generation import (
            GenerationConfig, PagedContinuousBatchingEngine)

        model, cfg = self._model()
        paged = PagedContinuousBatchingEngine(
            model, max_batch=3, num_pages=12, page_size=8, max_pages=8)
        gcfg = GenerationConfig(max_new_tokens=10, do_sample=True, seed=7,
                                temperature=0.9, eos_token_id=None)
        rng = np.random.RandomState(1)
        prompts = [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
                   for n in (4, 30, 2, 11, 7, 19)]
        outs = paged.serve(prompts, gcfg, segment_steps=3)
        assert all(len(o) == 10 for o in outs)
        assert paged.alloc.free_pages == 12

    def test_pool_exhaustion_is_loud(self):
        from paddle_tpu.inference.generation import (
            GenerationConfig, PagedContinuousBatchingEngine)

        model, cfg = self._model(layers=1)
        # pool holds 2 pages = 16 tokens TOTAL; a 20-token prompt cannot
        # ever fit and must fail loudly at admission
        paged = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=2, page_size=8, max_pages=4)
        gcfg = GenerationConfig(max_new_tokens=4, eos_token_id=None)
        with pytest.raises(RuntimeError, match="pool exhausted"):
            paged.add_request(np.arange(20, dtype=np.int32), gcfg)
        assert paged._free == [0, 1]   # the slot was NOT consumed

    def test_reservation_prevents_mid_decode_exhaustion(self):
        """Admission reserves prompt+max_new_tokens, so two requests
        that cannot run CONCURRENTLY are serialized by serve() instead
        of exhausting the pool mid-decode and losing both (r5 review
        crash repro)."""
        from paddle_tpu.inference.generation import (
            GenerationConfig, PagedContinuousBatchingEngine)

        model, cfg = self._model(layers=1)
        # 8 pages * 8 = 64 tokens total; each request reserves
        # 25+10=35 tokens = 5 pages, so only ONE fits at a time
        paged = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=8, page_size=8, max_pages=8)
        gcfg = GenerationConfig(max_new_tokens=10, do_sample=False,
                                eos_token_id=None)
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, cfg.vocab_size, (25,)).astype(np.int32)
                   for _ in range(2)]
        outs = paged.serve(prompts, gcfg, segment_steps=4)
        assert all(len(o) == 10 for o in outs)
        assert paged.alloc.free_pages == 8

    def test_serve_defers_transient_pool_pressure(self):
        """A free SLOT with a transiently full pool must defer admission
        to the next segment gap, not raise out of serve()."""
        from paddle_tpu.inference.generation import (
            GenerationConfig, PagedContinuousBatchingEngine)

        model, cfg = self._model(layers=1)
        paged = PagedContinuousBatchingEngine(
            model, max_batch=3, num_pages=6, page_size=8, max_pages=6)
        gcfg = GenerationConfig(max_new_tokens=6, do_sample=False,
                                eos_token_id=None)
        rng = np.random.RandomState(4)
        # each reserves ceil((18+6)/8)=3 pages; pool holds 2 at a time,
        # 3 slots exist -> slot free while pool full
        prompts = [rng.randint(0, cfg.vocab_size, (18,)).astype(np.int32)
                   for _ in range(4)]
        outs = paged.serve(prompts, gcfg, segment_steps=3)
        assert all(len(o) == 6 for o in outs)
        assert paged.alloc.free_pages == 6


class TestPagedGQA:
    """Hq > Hkv: the kernel shares KV heads in-kernel (query head i uses
    kv head i // g, the gqa_decode_attention convention)."""

    def test_gqa_parity_vs_dense_gqa_kernel(self):
        from paddle_tpu.ops._decode import gqa_decode_attention

        lens = np.array([13, 30], np.int32)
        Hq, Hkv, D, PS = 4, 2, 16, 8
        cache = _filled_cache(lens, H=Hkv, D=D, PS=PS)
        rng = np.random.RandomState(6)
        q = jnp.asarray(rng.randn(2, Hq, D), jnp.float32)
        out = paged_decode_mha(q, cache.k, cache.v, cache.page_table,
                               jnp.asarray(lens))
        kd = jnp.stack([gather_dense(cache.k, cache.page_table, b)
                        for b in range(2)])
        vd = jnp.stack([gather_dense(cache.v, cache.page_table, b)
                        for b in range(2)])
        ref = gqa_decode_attention(q, kd, vd, jnp.asarray(lens))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_non_divisible_heads_rejected(self):
        cache = _filled_cache(np.array([8], np.int32), H=3)
        q = jnp.zeros((1, 4, 16), jnp.float32)
        with pytest.raises(ValueError, match="multiple"):
            paged_decode_mha(q, cache.k, cache.v, cache.page_table,
                             jnp.asarray([8], jnp.int32))

    def test_engine_with_gqa_model(self):
        import paddle_tpu as paddle
        from paddle_tpu.inference.generation import (
            ContinuousBatchingEngine, GenerationConfig,
            PagedContinuousBatchingEngine)
        from paddle_tpu.models import LlamaForCausalLM, llama_config

        paddle.seed(0)
        cfg = llama_config("tiny", num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=2)
        model = LlamaForCausalLM(cfg)
        gcfg = GenerationConfig(max_new_tokens=8, do_sample=False,
                                eos_token_id=None)
        rng = np.random.RandomState(2)
        prompts = [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
                   for n in (6, 11)]
        outs_r = ContinuousBatchingEngine(
            model, max_batch=2, max_len=64).serve(prompts, gcfg,
                                                  segment_steps=4)
        outs_p = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=10, page_size=8,
            max_pages=8).serve(prompts, gcfg, segment_steps=4)
        for a, b in zip(outs_r, outs_p):
            np.testing.assert_array_equal(a, b)

    def test_serve_capacity_probe_accepts_tensor_prompts(self):
        """The probe and add_request must normalize prompts identically
        (a bare np.asarray on a Tensor is a size-1 object array)."""
        import paddle_tpu as paddle
        from paddle_tpu.inference.generation import (
            GenerationConfig, PagedContinuousBatchingEngine)
        from paddle_tpu.models import LlamaForCausalLM, llama_config

        paddle.seed(0)
        model = LlamaForCausalLM(llama_config("tiny",
                                              num_hidden_layers=1))
        paged = PagedContinuousBatchingEngine(
            model, max_batch=2, num_pages=6, page_size=8, max_pages=6)
        gcfg = GenerationConfig(max_new_tokens=6, do_sample=False,
                                eos_token_id=None)
        rng = np.random.RandomState(5)
        prompts = [paddle.to_tensor(
            rng.randint(0, 64, (18,)).astype(np.int32)) for _ in range(3)]
        outs = paged.serve(prompts, gcfg, segment_steps=3)
        assert all(len(o) == 6 for o in outs)


class TestSpeculativeDecoding:
    """Lossless n-gram speculative decoding on CausalLMEngine: outputs
    must be byte-identical to plain greedy generate(); the win is model
    forwards per token (reference has no speculative path; TPU decode
    is HBM-bound so verifying k+1 positions costs ~one forward)."""

    def _eng(self, layers=2, max_len=256):
        import paddle_tpu as paddle
        from paddle_tpu.inference.generation import CausalLMEngine
        from paddle_tpu.models import LlamaForCausalLM, llama_config

        paddle.seed(0)
        model = LlamaForCausalLM(llama_config("tiny",
                                              num_hidden_layers=layers))
        return CausalLMEngine(model, max_batch=1, max_len=max_len)

    def test_exact_match_and_fewer_forwards(self):
        from paddle_tpu.inference.generation import GenerationConfig

        eng = self._eng()
        cfg = GenerationConfig(max_new_tokens=32, do_sample=False,
                               eos_token_id=None)
        rng = np.random.RandomState(0)
        rand = rng.randint(0, 64, (1, 24)).astype(np.int32)
        rep = np.tile(np.array([[5, 6, 7, 8]], np.int32), (1, 8))
        for prompt in (rand, rep):
            ref = eng.generate(prompt, cfg)
            spec = eng.generate_speculative(prompt, cfg, draft_k=6)
            np.testing.assert_array_equal(ref, spec)
        # the model's own greedy continuations are self-repetitive on
        # tiny models, so n-gram lookup accepts multi-token drafts
        stats = eng.last_spec_stats
        assert stats["tokens"] == 32
        assert stats["forwards"] < stats["tokens"], stats
        # the speedup bar is DERIVED from the measured acceptance, not a
        # hard tokens/forward constant: tiny-model acceptance rates move
        # with the float env (CPU vs TPU reduction order flips near-tied
        # argmaxes), but every accepted draft token is exactly one saved
        # forward, so with eos=None the accounting identity
        # tokens == forwards + accepted must hold bit-for-bit and the
        # drafts must be doing real work (accepted > 0).
        assert stats["accepted_draft_tokens"] > 0, stats
        assert (stats["tokens"]
                == stats["forwards"] + stats["accepted_draft_tokens"]), stats
        expect = stats["tokens"] / stats["forwards"]
        assert abs(stats["tokens_per_forward"] - expect) < 1e-12, stats

    def test_eos_freeze_matches_generate(self):
        """generate() freezes finished rows on eos (emitting eos for the
        rest of the budget); speculative must reproduce that exactly.
        Pick the eos id the model actually produces so the path runs."""
        from paddle_tpu.inference.generation import GenerationConfig

        eng = self._eng()
        probe = GenerationConfig(max_new_tokens=12, do_sample=False,
                                 eos_token_id=None)
        prompt = np.tile(np.array([[9, 3]], np.int32), (1, 6))
        free_run = eng.generate(prompt, probe)[0, prompt.shape[1]:]
        eos = int(free_run[4])         # something it emits mid-stream
        cfg = GenerationConfig(max_new_tokens=12, do_sample=False,
                               eos_token_id=eos)
        np.testing.assert_array_equal(
            eng.generate(prompt, cfg),
            eng.generate_speculative(prompt, cfg, draft_k=4))

    def test_contract_errors(self):
        from paddle_tpu.inference.generation import GenerationConfig

        eng = self._eng(layers=1)
        with pytest.raises(ValueError, match="greedy-only"):
            eng.generate_speculative(
                np.zeros((1, 4), np.int32),
                GenerationConfig(max_new_tokens=4, do_sample=True))
        with pytest.raises(ValueError, match="B=1"):
            eng.generate_speculative(
                np.zeros((2, 4), np.int32),
                GenerationConfig(max_new_tokens=4, do_sample=False))

    def test_max_len_tail_fallback(self):
        """Near max_len there is no headroom for draft_k+1-wide
        verification — the tail must finish with 1-wide steps and still
        match generate()."""
        from paddle_tpu.inference.generation import GenerationConfig

        eng = self._eng(layers=1, max_len=40)
        cfg = GenerationConfig(max_new_tokens=14, do_sample=False,
                               eos_token_id=None)
        prompt = np.tile(np.array([[5, 6]], np.int32), (1, 12))  # 24+14=38
        np.testing.assert_array_equal(
            eng.generate(prompt, cfg),
            eng.generate_speculative(prompt, cfg, draft_k=8))

    def test_budget_zero_rejected_at_construction(self):
        """max_new_tokens=0 used to reach generate() and lean on the
        'always emit the prefill token' corner; online serving wants
        malformed budgets rejected at ADMISSION, so the config now
        validates at construction (see GenerationConfig)."""
        from paddle_tpu.inference.generation import GenerationConfig

        with pytest.raises(ValueError, match="max_new_tokens"):
            GenerationConfig(max_new_tokens=0, do_sample=False,
                             eos_token_id=None)

    def test_ngram_index_matches_linear_scan(self):
        """The incremental index must reproduce the naive most-recent-
        earlier-occurrence lookup (and never match the current tail)."""
        from paddle_tpu.inference.generation import _NgramIndex

        rng = np.random.RandomState(8)
        ctx = [int(t) for t in rng.randint(0, 5, 60)]

        def naive(arr, k, n_max):
            L = len(arr)
            for n in range(min(n_max, L - 1), 0, -1):
                for i in range(L - n - 1, -1, -1):
                    if arr[i:i + n] == arr[L - n:]:
                        cont = arr[i + n:i + n + k]
                        if cont:
                            return (cont + [cont[-1]]
                                    * (k - len(cont)))[:k]
            return [arr[-1]] * k

        idx = _NgramIndex(3)
        for L in range(4, 61):
            got = idx.propose(ctx[:L], 4)
            # both must be VALID continuations of the longest matched
            # suffix; "most recent" may differ (the index keeps the last
            # REGISTERED occurrence), so compare against the contract:
            # the proposed continuation follows some earlier occurrence
            # of the current suffix
            want = naive(ctx[:L], 4, 3)
            assert len(got) == len(want) == 4
            # deterministic cross-check at n=1: both continue SOME
            # earlier occurrence of the last token
            if ctx[:L][:-1].count(ctx[L - 1]) == 0:
                assert got == [ctx[L - 1]] * 4
