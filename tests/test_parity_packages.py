"""Tests for the audio / text / hub / onnx parity packages.

Reference test analogs: test/legacy_test/test_audio_functions.py,
test_audio_logmel_feature.py, test_viterbi_decode_op.py, test_hub.py.
"""
import math
import os
import wave

import numpy as np
import pytest

import paddle_tpu as paddle


class TestAudioFunctional:
    def test_hz_mel_roundtrip(self):
        from paddle_tpu.audio.functional import hz_to_mel, mel_to_hz

        for htk in (False, True):
            for hz in (60.0, 440.0, 8000.0):
                mel = hz_to_mel(hz, htk=htk)
                back = mel_to_hz(mel, htk=htk)
                assert abs(back - hz) < 1e-2 * hz

    def test_mel_frequencies_monotone(self):
        from paddle_tpu.audio.functional import mel_frequencies

        f = np.asarray(mel_frequencies(40, 0.0, 8000.0).numpy())
        assert f.shape == (40,)
        assert np.all(np.diff(f) > 0)
        assert abs(f[0]) < 1e-3 and abs(f[-1] - 8000.0) < 1.0

    def test_fbank_matrix_shape_and_rowsum(self):
        from paddle_tpu.audio.functional import compute_fbank_matrix

        fb = np.asarray(compute_fbank_matrix(16000, 512, n_mels=26).numpy())
        assert fb.shape == (26, 257)
        assert np.all(fb >= 0)
        assert np.all(fb.sum(axis=1) > 0)  # every filter is nonempty

    def test_power_to_db_matches_formula(self):
        from paddle_tpu.audio.functional import power_to_db

        x = np.asarray([1.0, 0.1, 0.01], np.float32)
        db = np.asarray(power_to_db(x, top_db=None).numpy())
        np.testing.assert_allclose(db, 10 * np.log10(x), rtol=1e-5)
        db2 = np.asarray(power_to_db(x, top_db=10.0).numpy())
        assert db2.min() >= db2.max() - 10.0

    def test_create_dct_orthonormal(self):
        from paddle_tpu.audio.functional import create_dct

        d = np.asarray(create_dct(13, 40).numpy())
        assert d.shape == (40, 13)
        gram = d.T @ d
        np.testing.assert_allclose(gram, np.eye(13), atol=1e-4)

    @pytest.mark.parametrize("name", ["hann", "hamming", "blackman",
                                      "triang", "bohman", "cosine"])
    def test_windows_match_scipy_shapes(self, name):
        from paddle_tpu.audio.functional import get_window

        w = np.asarray(get_window(name, 64))
        assert w.shape == (64,)
        assert w.max() <= 1.0 + 1e-9
        # symmetry of the periodic window: w[1:] mirrors around center
        # (fp32 atol — x64 is disabled, float64 canonicalizes to float32)
        np.testing.assert_allclose(w[1:], w[1:][::-1], atol=1e-6)

    def test_gaussian_tuple_window(self):
        from paddle_tpu.audio.functional import get_window

        w = np.asarray(get_window(("gaussian", 7), 64))
        assert w.shape == (64,)
        assert w.argmax() in (31, 32)


class TestAudioFeatures:
    def _sine(self, sr=8000, secs=0.5, freq=440.0):
        t = np.arange(int(sr * secs)) / sr
        return np.sin(2 * math.pi * freq * t).astype(np.float32)

    def test_spectrogram_peak_at_tone(self):
        from paddle_tpu.audio.features import Spectrogram

        sr, freq = 8000, 1000.0
        x = self._sine(sr=sr, freq=freq)
        spec = Spectrogram(n_fft=512, hop_length=160)
        out = np.asarray(spec(paddle.to_tensor(x[None])).numpy())[0]
        assert out.shape[0] == 257
        peak_bin = out.mean(axis=1).argmax()
        expect = round(freq / (sr / 2) * 256)
        assert abs(int(peak_bin) - expect) <= 1

    def test_mel_and_logmel_and_mfcc_shapes(self):
        from paddle_tpu.audio.features import (LogMelSpectrogram, MFCC,
                                               MelSpectrogram)

        x = self._sine()
        mel = MelSpectrogram(sr=8000, n_fft=512, n_mels=40, f_max=4000.0)
        m = np.asarray(mel(paddle.to_tensor(x[None])).numpy())
        assert m.shape[1] == 40
        logmel = LogMelSpectrogram(sr=8000, n_fft=512, n_mels=40,
                                   f_max=4000.0)
        lm = np.asarray(logmel(paddle.to_tensor(x[None])).numpy())
        assert lm.shape == m.shape
        mfcc = MFCC(sr=8000, n_mfcc=13, n_fft=512, n_mels=40, f_max=4000.0)
        c = np.asarray(mfcc(paddle.to_tensor(x[None])).numpy())
        assert c.shape[1] == 13


class TestAudioBackend:
    def test_wav_save_load_roundtrip(self, tmp_path):
        from paddle_tpu.audio import info, load, save

        sr = 8000
        x = (0.5 * np.sin(np.linspace(0, 100, 1600))).astype(np.float32)
        path = str(tmp_path / "t.wav")
        save(path, x[None], sr)
        meta = info(path)
        assert meta.sample_rate == sr and meta.num_channels == 1
        back, sr2 = load(path)
        assert sr2 == sr
        np.testing.assert_allclose(np.asarray(back.numpy())[0], x,
                                   atol=1e-3)

    def test_backend_registry(self):
        from paddle_tpu.audio import backends

        assert backends.get_current_backend() == "wave_backend"
        assert "wave_backend" in backends.list_available_backends()
        with pytest.raises(NotImplementedError):
            backends.set_backend("soundfile")


class TestViterbi:
    def _brute(self, emis, trans, length, include):
        n = trans.shape[0]
        best, best_path = -1e30, None
        import itertools

        for path in itertools.product(range(n), repeat=length):
            s = emis[0, path[0]]
            if include:
                s += trans[n - 1, path[0]]
            for t in range(1, length):
                s += trans[path[t - 1], path[t]] + emis[t, path[t]]
            if include:
                s += trans[path[-1], n - 2]
            if s > best:
                best, best_path = s, path
        return best, best_path

    @pytest.mark.parametrize("include", [False, True])
    def test_matches_bruteforce(self, include):
        from paddle_tpu.text import viterbi_decode

        rng = np.random.RandomState(0)
        b, L, n = 3, 5, 4
        emis = rng.randn(b, L, n).astype(np.float32)
        trans = rng.randn(n, n).astype(np.float32)
        lens = np.asarray([5, 3, 1], np.int64)
        scores, paths = viterbi_decode(paddle.to_tensor(emis),
                                       paddle.to_tensor(trans),
                                       paddle.to_tensor(lens),
                                       include_bos_eos_tag=include)
        scores = np.asarray(scores.numpy())
        paths = np.asarray(paths.numpy())
        assert paths.shape == (b, 5)
        for i in range(b):
            ref_s, ref_p = self._brute(emis[i], trans, int(lens[i]), include)
            np.testing.assert_allclose(scores[i], ref_s, rtol=1e-5)
            assert tuple(paths[i, :int(lens[i])]) == ref_p
            assert np.all(paths[i, int(lens[i]):] == 0)

    def test_layer_wrapper(self):
        from paddle_tpu.text import ViterbiDecoder

        rng = np.random.RandomState(1)
        emis = rng.randn(2, 4, 3).astype(np.float32)
        trans = rng.randn(3, 3).astype(np.float32)
        dec = ViterbiDecoder(paddle.to_tensor(trans),
                             include_bos_eos_tag=False)
        s, p = dec(paddle.to_tensor(emis),
                   paddle.to_tensor(np.asarray([4, 4], np.int64)))
        assert np.asarray(p.numpy()).shape == (2, 4)


class TestTextDatasets:
    def test_uci_housing_local(self, tmp_path):
        from paddle_tpu.text import UCIHousing

        rng = np.random.RandomState(0)
        raw = rng.rand(50, 14).astype(np.float32)
        path = str(tmp_path / "housing.data")
        np.savetxt(path, raw)
        train = UCIHousing(data_file=path, mode="train")
        test = UCIHousing(data_file=path, mode="test")
        assert len(train) == 40 and len(test) == 10
        x, y = train[0]
        assert x.shape == (13,) and y.shape == (1,)

    def test_gated_without_data(self):
        from paddle_tpu.text import WMT14

        with pytest.raises(RuntimeError, match="no network egress"):
            WMT14()

    def test_imikolov_local(self, tmp_path):
        from paddle_tpu.text import Imikolov

        d = tmp_path / "ptb"
        d.mkdir()
        text = "the quick fox " * 30
        (d / "ptb.train.txt").write_text(text + "\n" + text)
        (d / "ptb.valid.txt").write_text(text)
        ds = Imikolov(data_file=str(d), mode="train", window_size=3,
                      min_word_freq=2)
        assert len(ds) > 0
        assert ds[0].shape == (4,)


class TestHub:
    def test_local_hub_list_help_load(self, tmp_path):
        hubconf = tmp_path / "hubconf.py"
        hubconf.write_text(
            "dependencies = []\n"
            "def toy_model(scale=2):\n"
            "    'Builds a toy model.'\n"
            "    return {'scale': scale}\n")
        import paddle_tpu.hub as hub

        names = hub.list(str(tmp_path), source="local")
        assert "toy_model" in names
        assert "toy" in hub.help(str(tmp_path), "toy_model", source="local")
        out = hub.load(str(tmp_path), "toy_model", source="local", scale=5)
        assert out == {"scale": 5}

    def test_remote_sources_gated(self, tmp_path):
        import paddle_tpu.hub as hub

        with pytest.raises(RuntimeError, match="egress"):
            hub.list("owner/repo", source="github")


class TestOnnxExport:
    def test_export_emits_aot_artifact(self, tmp_path):
        import warnings

        from paddle_tpu import nn, onnx, static

        lay = nn.Linear(4, 2)
        path = str(tmp_path / "model")
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            onnx.export(lay, path,
                        input_spec=[static.InputSpec([None, 4], "float32")])
        assert os.path.exists(path + ".pdiparams")
        assert os.path.exists(path + ".stablehlo")
        with pytest.raises(ValueError):
            onnx.export(lay, path)  # input_spec required
