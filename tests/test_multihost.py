"""Multi-host reality check: 2 real processes, launch env contract,
init_parallel_env + TCPStore rendezvous + a cross-process collective.

Reference analog: test/legacy_test/test_collective_base.py:146 (spawns
worker processes, rendezvous over TCP store, runs a collective, compares).
TPU-native: each worker is a separate JAX process with its own CPU
device; jax.distributed.initialize wires them into one global mesh and the
psum rides gloo (the CPU stand-in for ICI/DCN collectives).
"""
import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


WORKER = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.parallel import get_store

    env = dist.init_parallel_env(dp=2)

    # 1) TCPStore rendezvous: each rank publishes, reads the peer's key
    store = get_store()
    assert store is not None, "TCPStore must come up from MASTER_ADDR/PORT"
    rank = env.rank
    store.set(f"hello_{{rank}}", str(100 + rank))
    peer = int(store.get(f"hello_{{1 - rank}}"))
    assert peer == 100 + (1 - rank), peer

    # 2) cross-process collective: psum over the global 2-device mesh
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.distributed.topology import get_mesh

    mesh = get_mesh()
    assert int(np.prod(list(mesh.shape.values()))) == 2, mesh.shape
    local = jnp.full((1, 4), float(rank + 1))
    glob = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp", None)), np.asarray(local), (2, 4))

    def f(x):
        return jax.lax.psum(x, "dp")

    out = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=P("dp", None),
                      out_specs=P("dp", None)))(glob)
    got = np.asarray(out.addressable_shards[0].data)[0, 0]
    assert got == 3.0, got  # 1 + 2 summed across processes

    # 3) group ranks reflect the process, not a hardcoded 0
    from paddle_tpu.distributed.topology import Group
    g = Group("dp", mesh)
    assert g.rank == rank, (g.rank, rank)
    assert g.nranks == 2

    print(json.dumps({{"rank": rank, "peer": peer, "psum": float(got)}}))
""")


@pytest.mark.slow
class TestTwoProcessCollective:
    def test_two_process_psum_and_store(self, tmp_path):
        coord = _free_port()
        master = _free_port()
        script = tmp_path / "worker.py"
        script.write_text(WORKER.format(repo=REPO))
        procs = []
        for rank in range(2):
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)  # 1 CPU device per process
            env.update({
                "JAX_PLATFORMS": "cpu",
                # reference launch env contract (launch/main.py)
                "PADDLE_TRAINER_ENDPOINTS":
                    f"127.0.0.1:{coord},127.0.0.1:{coord + 0}",
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_NNODES": "2",
                "PADDLE_TRAINERS_NUM": "2",
                "MASTER_ADDR": "127.0.0.1",
                "MASTER_PORT": str(master),
            })
            procs.append(subprocess.Popen(
                [sys.executable, str(script)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
        outs = []
        for rank, p in enumerate(procs):
            try:
                out, err = p.communicate(timeout=180)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail(f"rank {rank} timed out")
            assert p.returncode == 0, f"rank {rank} failed:\n{err[-2000:]}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
        assert {o["rank"] for o in outs} == {0, 1}
        assert all(o["psum"] == 3.0 for o in outs)
