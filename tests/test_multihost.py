"""Multi-host reality check: 2 real processes, launch env contract,
init_parallel_env + TCPStore rendezvous + a cross-process collective.

Reference analog: test/legacy_test/test_collective_base.py:146 (spawns
worker processes, rendezvous over TCP store, runs a collective, compares).
TPU-native: each worker is a separate JAX process with its own CPU
device; jax.distributed.initialize wires them into one global mesh and the
psum rides gloo (the CPU stand-in for ICI/DCN collectives).
"""
import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


WORKER = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.parallel import get_store

    env = dist.init_parallel_env(dp=2)

    # 1) TCPStore rendezvous: each rank publishes, reads the peer's key
    store = get_store()
    assert store is not None, "TCPStore must come up from MASTER_ADDR/PORT"
    rank = env.rank
    store.set(f"hello_{{rank}}", str(100 + rank))
    peer = int(store.get(f"hello_{{1 - rank}}"))
    assert peer == 100 + (1 - rank), peer

    # 2) cross-process collective: psum over the global 2-device mesh
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.distributed.topology import get_mesh

    mesh = get_mesh()
    assert int(np.prod(list(mesh.shape.values()))) == 2, mesh.shape
    local = jnp.full((1, 4), float(rank + 1))
    glob = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp", None)), np.asarray(local), (2, 4))

    def f(x):
        return jax.lax.psum(x, "dp")

    out = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=P("dp", None),
                      out_specs=P("dp", None)))(glob)
    got = np.asarray(out.addressable_shards[0].data)[0, 0]
    assert got == 3.0, got  # 1 + 2 summed across processes

    # 3) group ranks reflect the process, not a hardcoded 0
    from paddle_tpu.distributed.topology import Group
    g = Group("dp", mesh)
    assert g.rank == rank, (g.rank, rank)
    assert g.nranks == 2

    print(json.dumps({{"rank": rank, "peer": peer, "psum": float(got)}}))
""")


@pytest.mark.slow
def _run_workers(worker_src: str, n: int, tmp_path, timeout: float):
    """Spawn ``n`` rank processes under the reference launch env contract
    and return their parsed per-rank JSON outputs.  Every worker is
    killed on ANY exit path — one crashed rank must not orphan gloo-
    coupled survivors blocking forever on the dead peer."""
    coord = _free_port()
    master = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(worker_src.format(repo=REPO))
    procs = []
    try:
        for rank in range(n):
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)  # 1 CPU device per process
            env.update({
                "JAX_PLATFORMS": "cpu",
                # reference launch env contract (launch/main.py)
                "PADDLE_TRAINER_ENDPOINTS": ",".join(
                    f"127.0.0.1:{coord}" for _ in range(n)),
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_NNODES": str(n),
                "PADDLE_TRAINERS_NUM": str(n),
                "MASTER_ADDR": "127.0.0.1",
                "MASTER_PORT": str(master),
            })
            procs.append(subprocess.Popen(
                [sys.executable, str(script)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
        outs = []
        for rank, p in enumerate(procs):
            try:
                out, err = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                pytest.fail(f"rank {rank} timed out")
            assert p.returncode == 0, f"rank {rank} failed:\n{err[-2000:]}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
        return outs
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


class TestTwoProcessCollective:
    def test_two_process_psum_and_store(self, tmp_path):
        outs = _run_workers(WORKER, 2, tmp_path, timeout=180)
        assert {o["rank"] for o in outs} == {0, 1}
        assert all(o["psum"] == 3.0 for o in outs)


HYBRID_WORKER = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    import paddle_tpu.distributed as dist

    env = dist.init_parallel_env(dp=2, mp=2)
    rank = env.rank

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.distributed.topology import Group, get_mesh

    mesh = get_mesh()
    assert int(np.prod(list(mesh.shape.values()))) == 4, mesh.shape
    # device for rank r sits at (dp=r//2, mp=r%2); shard value = rank+1
    local = jnp.full((1, 1), float(rank + 1))
    glob = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp", "mp")), np.asarray(local), (2, 2))

    def f(x):
        return jax.lax.psum(x, "dp"), jax.lax.psum(x, "mp")

    col, row = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=P("dp", "mp"),
        out_specs=(P("dp", "mp"), P("dp", "mp"))))(glob)
    i, j = rank // 2, rank % 2
    got_col = float(np.asarray(col.addressable_shards[0].data)[0, 0])
    got_row = float(np.asarray(row.addressable_shards[0].data)[0, 0])
    # column sum over dp: (1+j) + (3+j); row sum over mp: (1+2i) + (2+2i)
    assert got_col == 4.0 + 2 * j, (rank, got_col)
    assert got_row == 3.0 + 4 * i, (rank, got_row)

    # axis groups report the right coordinates per process
    assert Group("dp", mesh).rank == i and Group("dp", mesh).nranks == 2
    assert Group("mp", mesh).rank == j and Group("mp", mesh).nranks == 2

    print(json.dumps({{"rank": rank, "col": got_col, "row": got_row}}))
""")


@pytest.mark.slow
class TestFourProcessHybridCollective:
    def test_four_process_dp_mp_psums(self, tmp_path):
        """4 REAL processes on a dp2 x mp2 hybrid mesh: per-axis psums
        ride gloo across process boundaries and every rank verifies its
        own shard (reference analog: the 4-card hybrid collective cases
        under test/collective/)."""
        outs = _run_workers(HYBRID_WORKER, 4, tmp_path, timeout=300)
        assert {o["rank"] for o in outs} == {0, 1, 2, 3}
        # every rank's shard agreed with the analytic per-axis sums
        assert [o["col"] for o in sorted(outs, key=lambda o: o["rank"])] \
            == [4.0, 6.0, 4.0, 6.0]
        assert [o["row"] for o in sorted(outs, key=lambda o: o["rank"])] \
            == [3.0, 3.0, 7.0, 7.0]
