"""Chaos suite for the fault-isolated serving path (ISSUE 4).

Covers the blast-radius contract end to end on CPU, driven by the
deterministic injection harness (`paddle_tpu.testing.faults`):

- per-request CONTAINMENT: a fault injected at a request-scoped seam
  (admission call, prefill inside the abort guard, chunked-prefill
  chunk) fails ONLY the poisoned request with its cause; concurrent
  requests complete with token parity vs a fault-free run, and after
  drain the slot heap and page free-list show zero leaked capacity;
- supervised ENGINE RECOVERY: an engine-scoped fault during
  ``decode_segment`` triggers reset + replay (re-prefill of
  prompt + generated) within ``max_restarts``; greedy in-flight
  requests finish with IDENTICAL final tokens; per-request
  ``max_replays`` and server ``max_restarts`` budgets both enforce,
  the latter falling through to the fatal path (prompt terminal
  states, never hangs);
- the STALL WATCHDOG: an injected hang flips ``/healthz`` to
  ``degraded`` (503) within ``stall_timeout_s`` and clears when the
  loop beats again; a degraded server rejects submissions with reason;
- satellites: client-disconnect reclaim (BrokenPipe mid-stream →
  cancel → slot AND pages back), failed/degraded HTTP surfacing,
  shutdown/drain during warmup and submit-after-crash returning
  promptly, monitor fault/restart/degraded export, and the
  serve_bench chaos soak (slow tier).
"""
import json
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.inference.generation import (CausalLMEngine, EngineFault,
                                             GenerationConfig,
                                             PagedContinuousBatchingEngine,
                                             RequestFault, classify_fault)
from paddle_tpu.serving import (ControlPlane, ControlPolicy,
                                ElasticController, RequestCancelled,
                                RequestFailed, RequestRejected, Server,
                                serve_http)
from paddle_tpu.testing.faults import (NET_SITES, SITES, FaultPlan,
                                       FaultyEngine, InjectedFault,
                                       NetworkFaultPlan)


def tiny_model(layers=1, seed=0):
    paddle.seed(seed)
    from paddle_tpu.models import LlamaForCausalLM, llama_config
    cfg = llama_config("tiny", num_hidden_layers=layers)
    return LlamaForCausalLM(cfg), cfg


def paged_engine(model, max_batch=3, num_pages=24, page_size=8,
                 max_pages=8, **kw):
    # the whole chaos suite runs with the allocator's invariant
    # validator armed: a reclaim bug on any abort/retire path fails
    # loudly at the faulty op instead of corrupting a neighbour's KV
    kw.setdefault("debug_pages", True)
    return PagedContinuousBatchingEngine(
        model, max_batch=max_batch, num_pages=num_pages,
        page_size=page_size, max_pages=max_pages, **kw)


def faulty_server(plan=None, model_layers=1, **kw):
    """(server, RAW engine, model cfg) — the engine is wrapped in a
    FaultyEngine when a plan is given; capacity assertions go against
    the raw engine."""
    model, cfg = tiny_model(layers=model_layers)
    eng_keys = ("max_batch", "num_pages", "page_size", "max_pages",
                "prefill_buckets", "prefill_chunk")
    eng_kw = {k: kw.pop(k) for k in list(kw) if k in eng_keys}
    raw = paged_engine(model, **eng_kw)
    eng = FaultyEngine(raw, plan) if plan is not None else raw
    return Server(eng, **kw), raw, cfg


@pytest.fixture()
def mon():
    monitor.enable()
    monitor.reset()
    yield monitor
    monitor.reset()
    monitor.disable()


def _greedy(n):
    return GenerationConfig(max_new_tokens=n, eos_token_id=None)


def _oracle(model, prompts, maxes, max_len=64):
    """Expected greedy tokens per prompt via the dense engine (bitwise
    parity with the continuous-batching engines is established by the
    existing suites)."""
    dense = CausalLMEngine(model, max_batch=1, max_len=max_len)
    return [dense.generate(p[None], _greedy(m))[0, len(p):]
            for p, m in zip(prompts, maxes)]


def _assert_no_leaks(eng):
    assert eng.free_slots() == eng.max_batch
    assert eng.alloc.free_pages == eng.num_pages


class TestTaxonomy:
    def test_classify_fault(self):
        assert classify_fault(RequestFault("x"), "decode") == "request"
        assert classify_fault(EngineFault("x"), "admit") == "engine"
        for site in ("admit", "prefill", "chunk"):
            assert classify_fault(RuntimeError("x"), site) == "request"
        for site in ("decode", "collect", "cancel"):
            assert classify_fault(RuntimeError("x"), site) == "engine"
        assert classify_fault(KeyboardInterrupt(), "admit") == "fatal"
        assert classify_fault(SystemExit(), "decode") == "fatal"


class TestFaultPlan:
    def test_nth_and_times_deterministic(self):
        plan = FaultPlan()
        plan.raise_at("decode", nth=2, times=2)
        plan.fire("decode")                    # call 1: clean
        with pytest.raises(InjectedFault, match="call 2"):
            plan.fire("decode")
        with pytest.raises(InjectedFault):
            plan.fire("decode")
        plan.fire("decode")                    # rule retired
        assert [(s, n) for s, n, _ in plan.injected] == [
            ("decode", 2), ("decode", 3)]
        assert plan.calls["decode"] == 4

    def test_sites_are_independent_and_validated(self):
        plan = FaultPlan().raise_at("admit", nth=1)
        plan.fire("decode")                    # other seams untouched
        with pytest.raises(InjectedFault):
            plan.fire("admit")
        with pytest.raises(ValueError, match="unknown site"):
            plan.raise_at("nope")
        assert set(SITES) == {"admit", "prefill", "chunk", "decode",
                              "collect", "preempt"}

    def test_hang_bounded_and_releasable(self):
        plan = FaultPlan().hang_at("decode", nth=1, seconds=30)
        t = threading.Timer(0.05, plan.release_hangs)
        t.start()
        t0 = time.monotonic()
        plan.fire("decode")                    # returns once released
        assert time.monotonic() - t0 < 5
        t.join()

    def test_custom_exception_passthrough(self):
        plan = FaultPlan().raise_at("decode",
                                    exc=EngineFault("device lost"))
        with pytest.raises(EngineFault, match="device lost"):
            plan.fire("decode")

    def test_plan_reassignment_rearms_proxy_seams(self):
        """``fe.plan = new_plan`` between scenarios must stay on the
        PROXY and rearm every seam — including the engine-internal
        prefill shadow — not forward to the wrapped engine as a dead
        attribute while the seams keep firing the stale plan."""
        model, cfg = tiny_model()
        raw = paged_engine(model)
        fe = FaultyEngine(raw, FaultPlan())
        fe.decode_segment(1)                   # original plan: clean
        fe.plan = FaultPlan().raise_at("decode", nth=1)
        assert "plan" not in vars(raw)         # no dead engine attr
        with pytest.raises(InjectedFault):
            fe.decode_segment(1)
        fe.plan = FaultPlan().raise_at("prefill", nth=1)
        p = np.random.RandomState(0).randint(
            0, cfg.vocab_size, (6,)).astype(np.int32)
        with pytest.raises(InjectedFault):     # prefill shadow rearmed
            fe.add_request(p, _greedy(4))
        assert raw.free_slots() == raw.max_batch   # abort guard ran
        assert raw.alloc.free_pages == raw.num_pages
        raw.alloc.check()


class TestEngineReset:
    def test_reset_state_reclaims_everything_and_still_serves(self):
        """reset_state (the recovery hook) must rebuild to a state
        indistinguishable from fresh: full slot heap and page pool, no
        collectables, and subsequent greedy decode identical."""
        model, cfg = tiny_model()
        eng = paged_engine(model, max_batch=2, num_pages=12)
        rng = np.random.RandomState(0)
        p = rng.randint(0, cfg.vocab_size, (6,)).astype(np.int32)
        want = _oracle(model, [p], [5])[0]
        eng.add_request(p, _greedy(30))
        eng.add_request(rng.randint(0, cfg.vocab_size, (4,))
                        .astype(np.int32), _greedy(30))
        eng.decode_segment(2)
        eng.reset_state()
        _assert_no_leaks(eng)
        assert eng.collect_finished() == {}
        rid = eng.add_request(p, _greedy(5))
        while eng.decode_segment(4):
            pass
        np.testing.assert_array_equal(eng.collect_finished()[rid], want)
        _assert_no_leaks(eng)


class TestRequestContainment:
    def test_prefill_fault_fails_one_alone_with_parity(self, mon):
        """A fault INSIDE the second admission's prefill (capacity
        already claimed) fails only that request with its cause; the
        neighbours finish with token parity vs a fault-free run and
        nothing leaks."""
        model, _ = tiny_model()
        rng = np.random.RandomState(1)
        prompts = [rng.randint(0, 100, (n,)).astype(np.int32)
                   for n in (5, 7, 4)]
        want = _oracle(model, [prompts[0], prompts[2]], [8, 6])

        plan = FaultPlan().raise_at("prefill", nth=2)
        srv, eng, cfg = faulty_server(plan, max_batch=3,
                                      segment_steps=2)
        try:
            h1 = srv.submit(prompts[0], _greedy(8))
            h2 = srv.submit(prompts[1], _greedy(8))
            h3 = srv.submit(prompts[2], _greedy(6))
            with pytest.raises(RequestFailed, match="injected fault"):
                h2.result(timeout=120)
            np.testing.assert_array_equal(h1.result(timeout=120),
                                          want[0])
            np.testing.assert_array_equal(h3.result(timeout=120),
                                          want[1])
            # the loop kept serving: no restart, status stays ok
            assert srv.restarts == 0
            assert srv.status == "ok"
            fs = srv.fault_stats()
            # the prefill raise surfaces at the admission seam
            assert fs["faults"] == {("request", "admit"): 1}
            assert srv.drain(timeout=120)
            _assert_no_leaks(eng)
            # monitor export (before shutdown retires the series)
            snap = monitor.snapshot()["metrics"]
            s = snap["paddle_tpu_serving_faults_total"]["samples"][0]
            assert s["labels"]["kind"] == "request"
            assert s["labels"]["site"] == "admit"
            assert s["value"] == 1
        finally:
            srv.shutdown(drain=False)

    def test_admit_seam_fault_fails_one_alone(self):
        """A fault at the admission CALL seam (before any capacity is
        claimed) — same containment, zero leak."""
        plan = FaultPlan().raise_at("admit", nth=1)
        srv, eng, cfg = faulty_server(plan, max_batch=2,
                                      segment_steps=2)
        try:
            h1 = srv.submit(np.arange(4, dtype=np.int32), _greedy(4))
            with pytest.raises(RequestFailed, match="injected fault"):
                h1.result(timeout=120)
            h2 = srv.submit(np.arange(5, dtype=np.int32), _greedy(4))
            assert len(h2.result(timeout=120)) == 4
            assert srv.drain(timeout=120)
            _assert_no_leaks(eng)
        finally:
            srv.shutdown(drain=False)

    def test_chunk_fault_fails_long_request_alone(self, mon):
        """A fault on the SECOND chunk of a chunked admission fails the
        long request only (admit_chunk's abort guard reclaims the
        up-front slot + worst-case pages); a concurrent short request
        completes with parity."""
        model, _ = tiny_model()
        rng = np.random.RandomState(2)
        long_p = rng.randint(0, 100, (20,)).astype(np.int32)
        short_p = rng.randint(0, 100, (4,)).astype(np.int32)
        want = _oracle(model, [short_p], [6])[0]

        plan = FaultPlan().raise_at("chunk", nth=2)
        srv, eng, cfg = faulty_server(
            plan, max_batch=2, num_pages=24, page_size=8, max_pages=8,
            prefill_chunk=8, segment_steps=2)
        try:
            hl = srv.submit(long_p, _greedy(6))
            hs = srv.submit(short_p, _greedy(6))
            with pytest.raises(RequestFailed, match="injected fault"):
                hl.result(timeout=120)
            np.testing.assert_array_equal(hs.result(timeout=120), want)
            assert srv.fault_stats()["faults"] == {
                ("request", "chunk"): 1}
            assert srv.drain(timeout=120)
            _assert_no_leaks(eng)
        finally:
            srv.shutdown(drain=False)


class TestEngineRecovery:
    def test_decode_fault_recovers_with_identical_tokens(self, mon):
        """An EngineFault mid-serving triggers ONE supervised restart;
        both in-flight greedy requests replay (re-prefill of
        prompt + generated) and finish with final tokens identical to
        a fault-free run; zero leaked capacity after drain."""
        model, _ = tiny_model()
        rng = np.random.RandomState(3)
        p1 = rng.randint(0, 100, (6,)).astype(np.int32)
        p2 = rng.randint(0, 100, (9,)).astype(np.int32)
        want = _oracle(model, [p1, p2], [10, 7])

        plan = FaultPlan().raise_at(
            "decode", nth=2, exc=EngineFault("injected device loss"))
        srv, eng, cfg = faulty_server(plan, max_batch=2,
                                      segment_steps=2,
                                      restart_backoff_s=0.01)
        try:
            h1 = srv.submit(p1, _greedy(10))
            h2 = srv.submit(p2, _greedy(7))
            np.testing.assert_array_equal(h1.result(timeout=120),
                                          want[0])
            np.testing.assert_array_equal(h2.result(timeout=120),
                                          want[1])
            assert srv.restarts == 1
            fs = srv.fault_stats()
            assert fs["faults"] == {("engine", "decode"): 1}
            assert len(fs["recovery_s"]) == 1
            assert fs["degraded"] is None and srv.status == "ok"
            # at most one replay each, and the server still serves
            assert h1._replays <= 1 and h2._replays <= 1
            h3 = srv.submit(p1, _greedy(3))
            assert len(h3.result(timeout=120)) == 3
            assert srv.drain(timeout=120)
            _assert_no_leaks(eng)
            # monitor export (before shutdown retires the series)
            snap = monitor.snapshot()["metrics"]
            restarts = snap["paddle_tpu_serving_restarts_total"][
                "samples"]
            assert restarts[0]["value"] == 1
            assert "paddle_tpu_serving_recovery_seconds" in snap
        finally:
            srv.shutdown(drain=False)

    def test_engine_fault_during_admission_replays_request(self):
        """An EngineFault raised at the ADMISSION seam escalates to
        recovery with the triggering request riding along — it replays
        after the reset instead of being stranded."""
        plan = FaultPlan().raise_at(
            "admit", nth=1, exc=EngineFault("admission device loss"))
        srv, eng, cfg = faulty_server(plan, max_batch=2,
                                      segment_steps=2,
                                      restart_backoff_s=0.01)
        try:
            h = srv.submit(np.arange(5, dtype=np.int32), _greedy(4))
            assert len(h.result(timeout=120)) == 4
            assert srv.restarts == 1
            assert h._replays == 1
            assert srv.drain(timeout=120)
            _assert_no_leaks(eng)
        finally:
            srv.shutdown(drain=False)

    def test_chunked_replay_rides_chunked_admission(self):
        """A replay whose prompt + generated exceeds prefill_chunk
        re-admits CHUNKED (one fixed-shape chunk per gap) and still
        finishes with the fault-free greedy tokens."""
        model, _ = tiny_model()
        rng = np.random.RandomState(4)
        long_p = rng.randint(0, 100, (20,)).astype(np.int32)
        want = _oracle(model, [long_p], [10])[0]

        # decode calls 1-2 are the no-op segments interleaved with the
        # 3-chunk admission; the fault lands mid-decode, with tokens
        # already emitted, so the replay prompt (20 + generated) is
        # longer than the chunk and takes the chunked path
        plan = FaultPlan().raise_at(
            "decode", nth=5, exc=EngineFault("mid-decode loss"))
        srv, eng, cfg = faulty_server(
            plan, max_batch=2, num_pages=24, page_size=8, max_pages=8,
            prefill_chunk=8, segment_steps=2, restart_backoff_s=0.01)
        try:
            h = srv.submit(long_p, _greedy(10))
            np.testing.assert_array_equal(h.result(timeout=120), want)
            assert srv.restarts == 1
            assert h._replays == 1
            assert srv.drain(timeout=120)
            _assert_no_leaks(eng)
        finally:
            srv.shutdown(drain=False)

    def test_replay_budget_fails_request_server_survives(self):
        """Two consecutive engine faults with max_replays=1: the
        in-flight request exceeds ITS replay budget and fails with the
        fault as cause, but the SERVER recovers and serves new work."""
        plan = FaultPlan().raise_at(
            "decode", nth=1, times=2, exc=EngineFault("flaky device"))
        srv, eng, cfg = faulty_server(plan, max_batch=2,
                                      segment_steps=2, max_replays=1,
                                      restart_backoff_s=0.01)
        try:
            h = srv.submit(np.arange(5, dtype=np.int32), _greedy(6))
            with pytest.raises(RequestFailed,
                               match="exceeded its replay budget"):
                h.result(timeout=120)
            assert srv.restarts == 2
            h2 = srv.submit(np.arange(4, dtype=np.int32), _greedy(3))
            assert len(h2.result(timeout=120)) == 3
            assert srv.status in ("ok", "draining")
            assert srv.drain(timeout=120)
            _assert_no_leaks(eng)
        finally:
            srv.shutdown(drain=False)

    def test_rebuild_failure_fails_inflight_never_hangs(self):
        """If reset_state() ITSELF raises during recovery, the
        snapshotted in-flight handles must still reach terminal FAILED
        (parked for the fatal _finalize) — clients must never hang —
        and the degraded flag must not survive into the failed state."""
        plan = FaultPlan().raise_at(
            "decode", nth=1, exc=EngineFault("device loss"))
        srv, eng, cfg = faulty_server(plan, max_batch=2,
                                      segment_steps=2,
                                      restart_backoff_s=0.01)
        try:
            def broken_rebuild():
                raise RuntimeError("rebuild also failed")
            eng.reset_state = broken_rebuild
            h = srv.submit(np.arange(4, dtype=np.int32), _greedy(6))
            # the diagnosis must carry the REBUILD failure, not claim
            # an exhausted restart budget (the budget wasn't)
            with pytest.raises(RequestFailed, match="rebuild"):
                h.result(timeout=120)
            assert srv.status == "failed"
            assert srv.fault_stats()["degraded"] is None
            assert ("engine", "reset") in srv.fault_stats()["faults"]
        finally:
            srv.shutdown(drain=False)

    def test_admission_engine_fault_with_zero_restarts_terminal(self):
        """max_restarts=0 + an EngineFault at the ADMISSION seam: the
        triggering handle is in no collection yet (popped from the
        queue) — it must still reach terminal FAILED, not be
        stranded."""
        plan = FaultPlan().raise_at(
            "admit", nth=1, exc=EngineFault("admission device loss"))
        srv, eng, cfg = faulty_server(plan, max_batch=2,
                                      segment_steps=2, max_restarts=0)
        try:
            h = srv.submit(np.arange(4, dtype=np.int32), _greedy(4))
            with pytest.raises(RequestFailed, match="scheduler died"):
                h.result(timeout=120)
            assert srv.status == "failed"
        finally:
            srv.shutdown(drain=False)

    def test_chunked_replay_ignores_admission_deadline(self):
        """The admission deadline was met the first time the request
        admitted; a chunked REPLAY crossing it mid-recovery (backoff
        longer than the deadline) must complete, not EXPIRE."""
        plan = FaultPlan().raise_at(
            "decode", nth=3, exc=EngineFault("mid-decode loss"))
        srv, eng, cfg = faulty_server(
            plan, max_batch=2, num_pages=24, page_size=8, max_pages=8,
            prefill_chunk=8, segment_steps=2, warmup=True,
            restart_backoff_s=1.0)   # backoff alone outlives the ddl
        try:
            assert srv.wait_ready(timeout=300)
            h = srv.submit(np.arange(12, dtype=np.int32) % 97,
                           _greedy(8), timeout_s=0.8)
            assert len(h.result(timeout=120)) == 8
            assert srv.restarts == 1
            assert h._replays == 1
        finally:
            srv.shutdown(drain=False)

    def test_restart_budget_falls_through_to_fatal(self):
        """A persistent engine fault exhausts max_restarts and falls
        through to the fatal path: handles reach terminal FAILED
        promptly (no hung result()), status reads 'failed', and
        submit-after-crash rejects immediately with the cause."""
        plan = FaultPlan().raise_at(
            "decode", nth=1, times=1000,
            exc=EngineFault("persistent device loss"))
        srv, eng, cfg = faulty_server(plan, max_batch=2,
                                      segment_steps=2, max_restarts=1,
                                      max_replays=100,
                                      restart_backoff_s=0.01)
        try:
            h = srv.submit(np.arange(4, dtype=np.int32), _greedy(6))
            with pytest.raises(RequestFailed, match="scheduler died"):
                h.result(timeout=120)
            assert srv.status == "failed"
            assert srv.restarts == 1       # the one allowed restart
            assert srv.wait_ready(timeout=10)
            with pytest.raises(RequestRejected,
                               match="scheduler died") as ei:
                srv.submit(np.arange(3, dtype=np.int32), _greedy(2))
            assert ei.value.reason == "shutdown"
        finally:
            srv.shutdown(drain=False)


class TestStallWatchdog:
    def test_timeout_below_idle_heartbeat_rejected(self):
        """An idle loop only beats every idle_wait_s; a stall timeout
        at/below that cadence would flap a healthy idle server into
        degraded — rejected at construction."""
        model, _ = tiny_model()
        eng = paged_engine(model)
        with pytest.raises(ValueError, match="idle_wait_s"):
            Server(eng, idle_wait_s=0.02, stall_timeout_s=0.03,
                   start=False)
        with pytest.raises(ValueError, match="> 0"):
            Server(eng, stall_timeout_s=0, start=False)


    def test_hang_flips_healthz_degraded_then_recovers(self, mon):
        """An injected hang in decode flips /healthz to degraded (503)
        within stall_timeout_s; a degraded server rejects submissions
        with reason; once the hang releases the status returns to ok
        and the wedged request completes."""
        plan = FaultPlan().hang_at("decode", nth=1, seconds=60)
        srv, eng, cfg = faulty_server(plan, max_batch=2,
                                      segment_steps=2,
                                      stall_timeout_s=0.2)
        httpd = serve_http(srv)
        port = httpd.server_address[1]
        from urllib.error import HTTPError
        from urllib.request import Request, urlopen

        def healthz():
            try:
                with urlopen(f"http://127.0.0.1:{port}/healthz",
                             timeout=10) as r:
                    return r.status, json.load(r)
            except HTTPError as e:
                return e.code, json.load(e)

        try:
            h = srv.submit(np.arange(4, dtype=np.int32), _greedy(4))
            deadline = time.monotonic() + 30
            code = body = None
            while time.monotonic() < deadline:
                code, body = healthz()
                if body["status"] == "degraded":
                    break
                time.sleep(0.02)
            assert body["status"] == "degraded", body
            assert code == 503
            assert ("stall", "loop") in srv.fault_stats()["faults"]
            snap = monitor.snapshot()["metrics"]
            deg = snap["paddle_tpu_serving_degraded"]["samples"][0]
            assert deg["value"] == 1
            # degraded rejects instead of queueing into a stalled loop
            with pytest.raises(RequestRejected, match="degraded") as ei:
                srv.submit(np.arange(3, dtype=np.int32), _greedy(2))
            assert ei.value.reason == "degraded"
            body_http = json.dumps({"prompt": [1, 2],
                                    "max_new_tokens": 2}).encode()
            with pytest.raises(HTTPError) as he:
                urlopen(Request(f"http://127.0.0.1:{port}/generate",
                                data=body_http), timeout=10)
            assert he.value.code == 503
            assert json.load(he.value)["reason"] == "degraded"
            # release the hang: the loop beats, degraded clears, and
            # the wedged request finishes
            plan.release_hangs()
            assert len(h.result(timeout=120)) == 4
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                code, body = healthz()
                if body["status"] == "ok":
                    break
                time.sleep(0.02)
            assert body["status"] == "ok" and code == 200
        finally:
            plan.release_hangs()
            httpd.shutdown()
            srv.shutdown(drain=False)


class TestHTTPSatellites:
    def test_client_disconnect_reclaims_slot_and_pages(self):
        """BrokenPipeError mid-stream (serving/http.py cancel path):
        the slot AND its KV pages must actually return to the pool at
        the next gap — free-slot heap and page free-list back to full
        after the disconnect drains."""
        srv, eng, cfg = faulty_server(None, max_batch=2,
                                      segment_steps=2)
        httpd = serve_http(srv)
        port = httpd.server_address[1]
        try:
            import http.client
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=60)
            conn.request("POST", "/generate", json.dumps(
                {"prompt": [3, 1, 4], "max_new_tokens": 4000,
                 "stream": True}),
                {"Content-Type": "application/json"})
            resp = conn.getresponse()
            line = resp.readline()          # first streamed token
            assert b"token" in line
            # abrupt client disconnect mid-stream
            conn.sock.close()
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if (eng.free_slots() == eng.max_batch
                        and eng.alloc.free_pages == eng.num_pages):
                    break
                time.sleep(0.02)
            _assert_no_leaks(eng)
            # the server is still healthy for the next client
            h = srv.submit(np.arange(3, dtype=np.int32), _greedy(3))
            assert len(h.result(timeout=120)) == 3
        finally:
            httpd.shutdown()
            srv.shutdown(drain=False)

    def test_failed_server_healthz_503_and_reject(self):
        """A failed (dead-scheduler) server: /healthz 503 with
        status 'failed' in the body, and POST /generate rejects
        immediately with a reason instead of queueing."""
        plan = FaultPlan().raise_at(
            "decode", nth=1, exc=EngineFault("boom"))
        srv, eng, cfg = faulty_server(plan, max_batch=2,
                                      segment_steps=2, max_restarts=0)
        httpd = serve_http(srv)
        port = httpd.server_address[1]
        from urllib.error import HTTPError
        from urllib.request import Request, urlopen
        try:
            h = srv.submit(np.arange(4, dtype=np.int32), _greedy(4))
            with pytest.raises(RequestFailed):
                h.result(timeout=120)
            assert srv.status == "failed"
            with pytest.raises(HTTPError) as ei:
                urlopen(f"http://127.0.0.1:{port}/healthz", timeout=10)
            assert ei.value.code == 503
            body = json.load(ei.value)
            assert body["status"] == "failed"
            assert body["restarts"] == 0
            with pytest.raises(HTTPError) as ei:
                urlopen(Request(
                    f"http://127.0.0.1:{port}/generate",
                    data=json.dumps({"prompt": [1],
                                     "max_new_tokens": 2}).encode()),
                    timeout=10)
            assert ei.value.code == 503
            err = json.load(ei.value)
            assert err["reason"] == "shutdown"
            assert "scheduler died" in err["error"]
        finally:
            httpd.shutdown()
            srv.shutdown(drain=False)


class TestWarmupLifecycle:
    def test_shutdown_during_warmup_returns_promptly(self):
        """shutdown() issued while the server is still warming must
        come back with every queued handle in a terminal state — no
        hung result()/wait_ready() (builds on the PR 3 _ready-in-
        finally fix)."""
        srv, eng, cfg = faulty_server(None, max_batch=2,
                                      segment_steps=2, warmup=True)
        try:
            # submissions queue while warming
            h = srv.submit(np.arange(4, dtype=np.int32), _greedy(4))
            srv.shutdown(drain=False, timeout=300)
            assert srv.wait_ready(timeout=10)
            assert srv.status == "stopped"
            assert h.done and h.status == "cancelled"
            with pytest.raises(RequestCancelled):
                h.result(timeout=10)
        finally:
            srv.shutdown(drain=False)

    def test_drain_during_warmup_completes_queued(self):
        """drain() issued mid-warmup waits for warmup + the queued
        work, then returns True with everything finished."""
        srv, eng, cfg = faulty_server(None, max_batch=2,
                                      segment_steps=2, warmup=True)
        try:
            hs = [srv.submit(np.arange(n, dtype=np.int32) % 97,
                             _greedy(4)) for n in (3, 5)]
            assert srv.drain(timeout=600)
            for h in hs:
                assert h.status == "finished"
                assert len(h.result(timeout=10)) == 4
            _assert_no_leaks(eng)
        finally:
            srv.shutdown(drain=False)


class TestTooling:
    def test_monitor_report_serving_shows_fault_columns(self):
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "monitor_report", os.path.join(
                os.path.dirname(__file__), "..", "tools",
                "monitor_report.py"))
        mr = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mr)
        records = [
            {"metric": "paddle_tpu_serving_faults_total",
             "labels": {"server": "server0", "kind": "engine",
                        "site": "decode"}, "value": 2},
            {"metric": "paddle_tpu_serving_restarts_total",
             "labels": {"server": "server0"}, "value": 2},
            {"metric": "paddle_tpu_serving_degraded",
             "labels": {"server": "server0"}, "value": 0},
            {"metric": "paddle_tpu_serving_recovery_seconds",
             "labels": {"server": "server0"}, "value": 0.04,
             "count": 2, "sum": 0.08},
            {"metric": "paddle_tpu_something_else", "labels": {},
             "value": 1},
        ]
        out = mr.render(records, serving=True)
        assert "paddle_tpu_serving_faults_total" in out
        assert "kind=engine" in out and "site=decode" in out
        assert "paddle_tpu_serving_restarts_total" in out
        assert "paddle_tpu_serving_degraded" in out
        assert "paddle_tpu_serving_recovery_seconds" in out
        assert "something_else" not in out


class TestFlightRecorder:
    """Chaos-suite wiring for the flight recorder (ISSUE 9): an
    engine-scoped fault must leave a black-box dump behind, its final
    events must NAME the faulting site, and the dump path must surface
    through ``fault_stats()`` and ``/healthz``."""

    @pytest.fixture()
    def tr(self, tmp_path):
        from paddle_tpu import tracing
        tracing.clear()
        tracing.enable(dump_dir=str(tmp_path))
        yield tracing
        tracing.disable()
        tracing.clear()

    def test_engine_fault_dumps_and_names_site(self, tr):
        plan = FaultPlan().raise_at("decode", nth=2,
                                    exc=EngineFault("injected"))
        srv, raw, mcfg = faulty_server(plan, restart_backoff_s=0.01,
                                       segment_steps=4)
        try:
            prompts = [np.arange(1, 7, dtype=np.int32) + i
                       for i in range(2)]
            hs = [srv.submit(p, _greedy(10)) for p in prompts]
            for h in hs:
                h.result(timeout=180)
            fs = srv.fault_stats()
            assert fs["restarts"] == 1
            assert fs["flight_dumps"], \
                "engine fault produced no flight-recorder dump"
            path = fs["flight_dumps"][-1]
            doc = json.load(open(path))
            assert doc["otherData"]["reason"] == "engine_fault_decode"
            # the final events name the faulting site: the seam's
            # fault-classification event AND the injection marker
            faults = [e for e in doc["traceEvents"]
                      if e["name"] == "fault"]
            assert faults and faults[-1]["args"]["site"] == "decode"
            assert faults[-1]["args"]["kind"] == "engine"
            inject = [e for e in doc["traceEvents"]
                      if e["name"] == "fault.injected"]
            assert inject and inject[-1]["args"]["site"] == "decode"
            # ... and the dump path reaches /healthz
            httpd = serve_http(srv, port=0)
            try:
                port = httpd.server_address[1]
                from urllib.request import urlopen
                body = json.loads(urlopen(
                    f"http://127.0.0.1:{port}/healthz",
                    timeout=10).read())
                assert body["flight_dump"] == path
            finally:
                httpd.shutdown()
        finally:
            srv.shutdown()
        _assert_no_leaks(raw)

    def test_restart_backoff_replay_traced(self, tr):
        """The recovery trail lands in the ring AFTER the dump: the
        next dump (or a live /trace read) shows backoff -> restart ->
        replay -> re-admit for the surviving request."""
        from paddle_tpu import tracing
        plan = FaultPlan().raise_at("decode", nth=2,
                                    exc=EngineFault("injected"))
        srv, raw, _ = faulty_server(plan, restart_backoff_s=0.01,
                                    segment_steps=4)
        try:
            h = srv.submit(np.arange(1, 7, dtype=np.int32),
                           _greedy(10))
            h.result(timeout=180)
            ph = [e["phase"] for e in h.timeline()]
            i = ph.index
            assert i("replay") < ph.index("admit", i("replay"))
            names = [e["phase"] for e in tracing.events()]
            assert "backoff" in names and "restart" in names \
                and "recover" in names
            j = names.index
            assert j("backoff") < j("restart") < j("recover")
        finally:
            srv.shutdown()
        _assert_no_leaks(raw)

    def test_no_dump_when_tracing_disabled(self):
        from paddle_tpu import tracing
        assert not tracing.enabled()
        plan = FaultPlan().raise_at("decode", nth=2,
                                    exc=EngineFault("injected"))
        srv, raw, _ = faulty_server(plan, restart_backoff_s=0.01,
                                    segment_steps=4)
        try:
            h = srv.submit(np.arange(1, 7, dtype=np.int32),
                           _greedy(10))
            h.result(timeout=180)
            fs = srv.fault_stats()
            assert fs["restarts"] == 1
            # no recorder armed -> no black box, honestly empty
            assert fs["flight_dumps"] == []
            assert h.timeline() == []
        finally:
            srv.shutdown()
        _assert_no_leaks(raw)

    def test_preemption_storm_dumps_once(self, tr):
        """The storm trigger fires on preemption DENSITY (not any
        single preemption) and re-arms only after a full window —
        driven synthetically through _park_preempted so the test does
        not depend on pool-thrash timing."""
        import types

        from paddle_tpu.serving.queue import RequestHandle
        srv = Server(types.SimpleNamespace(max_len=64), start=False)
        srv.STORM_PREEMPTS = 3
        try:
            for k in range(3):
                h = RequestHandle(k, np.arange(3), 3, _greedy(4))
                h._trace_rid = f"{srv.monitor_server}:{k}"
                srv._park_preempted(h)
            dumps = srv.fault_stats()["flight_dumps"]
            assert len(dumps) == 1
            doc = json.load(open(dumps[0]))
            assert doc["otherData"]["reason"] == "preemption_storm"
            storm = [e for e in doc["traceEvents"]
                     if e["name"] == "preempt.storm"]
            assert storm and storm[-1]["args"]["count"] == 3
            # within the same window a 4th preemption does NOT re-dump
            h = RequestHandle(9, np.arange(3), 3, _greedy(4))
            h._trace_rid = f"{srv.monitor_server}:9"
            srv._park_preempted(h)
            assert len(srv.fault_stats()["flight_dumps"]) == 1
        finally:
            srv.shutdown(drain=False)


class TestControlPlaneUnit:
    """Overload control plane (ISSUE 19), host-side unit surface:
    burn-rate shed windows, the brownout ladder's engage-immediately /
    disengage-hysteretically asymmetry, config degradation semantics,
    and the elastic controller's provable flap resistance — all driven
    through explicit synthetic clocks (the same code paths production
    ticks through, minus the wall clock)."""

    def test_policy_validation(self):
        with pytest.raises(ValueError, match="shed_burn"):
            ControlPolicy(shed_burn=0)
        with pytest.raises(ValueError, match="non-decreasing"):
            ControlPolicy(rung_up=(0.5, 0.4, 0.8, 0.9))
        with pytest.raises(ValueError, match="engage thresholds"):
            ControlPolicy(rung_up=(0.5, 0.9))
        with pytest.raises(ValueError, match="scale_up_depth"):
            ControlPolicy(scale_up_depth=0.2, scale_down_depth=0.5)
        with pytest.raises(ValueError, match="ControlPolicy"):
            ControlPlane(object())

    def test_shed_window_lifecycle(self):
        pol = ControlPolicy(shed_burn=2.0, shed_min_count=2,
                            tick_interval_s=0.0)
        cp = ControlPlane(pol, fast_window_s=10.0)
        stats = {"hot": {"burn_fast": 3.0, "met": 1, "missed": 3},
                 "cold": {"burn_fast": 0.1, "met": 4, "missed": 0},
                 "thin": {"burn_fast": 9.0, "met": 1, "missed": 0},
                 "idle": {"burn_fast": None}}
        dec = cp.tick(100.0, queue_depth=0, max_queue=64,
                      tenant_stats=stats)
        # only the hot tenant with enough scored requests sheds ("thin"
        # has a loud burn off one request — one unlucky request must
        # not shed a tenant)
        assert dec["shed"] == [("hot", 110.0)]
        assert cp.shed_check("hot", 104.0) == pytest.approx(6.0)
        assert cp.shed_check("cold", 104.0) is None
        assert cp.shed_check(None, 104.0) is None
        # a hot burn forces at least rung 1 even with an empty queue
        assert dec["rung"] >= 1
        assert cp.snapshot()["shed_active"] == ["hot"]
        # re-firing while hot EXTENDS the window without a new "shed"
        dec = cp.tick(105.0, queue_depth=0, max_queue=64,
                      tenant_stats={"hot": stats["hot"]})
        assert dec["shed"] == []
        assert cp.shed_check("hot", 105.0) == pytest.approx(10.0)
        # window expiry: tick reports the unshed, shed_check clears
        dec = cp.tick(116.0, queue_depth=0, max_queue=64,
                      tenant_stats={})
        assert dec["unshed"] == ["hot"]
        assert cp.shed_check("hot", 116.5) is None

    def test_ladder_engages_immediately_disengages_one_per_dwell(self):
        pol = ControlPolicy(tick_interval_s=0.0, rung_dwell_s=2.0,
                            rung_hysteresis=0.15)
        cp = ControlPlane(pol)
        # overload is urgent: the ladder jumps straight to rung 4
        dec = cp.tick(0.0, queue_depth=60, max_queue=64,
                      tenant_stats=None)
        assert (dec["prev_rung"], dec["rung"]) == (0, 4)
        assert cp.snapshot()["rung_action"] == "prefix_pause"
        # load vanished, but dwell not served: hold the rung
        dec = cp.tick(1.0, queue_depth=0, max_queue=64,
                      tenant_stats=None)
        assert dec["rung"] == 4
        # disengage is ONE rung per dwell, never a cliff
        rungs = [cp.tick(3.0 + 2.5 * i, queue_depth=0, max_queue=64,
                         tenant_stats=None)["rung"] for i in range(4)]
        assert rungs == [3, 2, 1, 0]

    def test_ladder_does_not_flap_inside_the_hysteresis_band(self):
        pol = ControlPolicy(tick_interval_s=0.0, rung_dwell_s=1.0,
                            rung_hysteresis=0.15)
        cp = ControlPlane(pol)
        assert cp.tick(0.0, queue_depth=33, max_queue=64,
                       tenant_stats=None)["rung"] == 1   # occ 0.516
        # oscillate between 0.40 and 0.52 — both above the disengage
        # threshold (0.5 - 0.15): the rung must hold forever
        for i in range(1, 12):
            depth = 26 if i % 2 else 33
            dec = cp.tick(2.0 * i, queue_depth=depth, max_queue=64,
                          tenant_stats=None)
            assert dec["rung"] == 1
        # dropping BELOW the band releases it (dwell long since met)
        assert cp.tick(30.0, queue_depth=8, max_queue=64,
                       tenant_stats=None)["rung"] == 0

    def test_tick_rate_limits_itself(self):
        cp = ControlPlane(ControlPolicy(tick_interval_s=1.0))
        assert cp.tick(0.0, queue_depth=0, max_queue=8,
                       tenant_stats=None) is not None
        assert cp.tick(0.5, queue_depth=0, max_queue=8,
                       tenant_stats=None) is None
        assert cp.tick(1.5, queue_depth=0, max_queue=8,
                       tenant_stats=None) is not None

    def test_degrade_cfg_and_quota_cap(self):
        cp = ControlPlane(ControlPolicy(brownout_max_new=3,
                                        tick_interval_s=0.0))
        cfg = GenerationConfig(max_new_tokens=64, speculative=True)
        # rung 0/1: the client's object passes through untouched
        assert cp.degrade_cfg(cfg) is cfg
        assert cp.quota_cap(4) == 4
        cp.rung = 1
        assert cp.degrade_cfg(cfg) is cfg
        assert cp.quota_cap(4) == 2 and cp.quota_cap(1) == 1
        cp.rung = 2
        out = cp.degrade_cfg(cfg)
        assert out is not cfg and out.max_new_tokens == 3
        assert out.speculative is True        # rung 2 only caps length
        assert cfg.max_new_tokens == 64       # never mutates the input
        cp.rung = 3
        out = cp.degrade_cfg(cfg)
        assert out.max_new_tokens == 3 and out.speculative is False
        # an already-short request is not lengthened
        short = GenerationConfig(max_new_tokens=2)
        assert cp.degrade_cfg(short).max_new_tokens == 2

    def test_elastic_flap_resistance_under_oscillating_load(self):
        pol = ControlPolicy(scale_up_depth=4.0, scale_down_depth=0.5,
                            scale_signals=3, scale_cooldown_s=10.0)
        ec = ElasticController(pol, min_replicas=1, max_replicas=4)
        # load oscillating across both thresholds every tick: each
        # flip resets the opposite streak — NO scale event, ever
        decisions = [ec.decide(float(t), routable=2,
                               queue_depth=(20 if t % 2 == 0 else 0))
                     for t in range(24)]
        assert decisions == [0] * 24

    def test_elastic_sustained_signal_fires_once_per_cooldown(self):
        pol = ControlPolicy(scale_up_depth=4.0, scale_down_depth=0.5,
                            scale_signals=3, scale_cooldown_s=10.0)
        ec = ElasticController(pol, min_replicas=1, max_replicas=4)
        ups = [ec.decide(float(t), routable=2, queue_depth=20)
               for t in range(10)]
        # streak completes on the third agreeing tick; the cooldown
        # then blocks every further verdict inside the window
        assert ups == [0, 0, 1, 0, 0, 0, 0, 0, 0, 0]
        # the streak kept accumulating through the cooldown, so a
        # STILL-sustained signal fires the instant the window opens
        ups2 = [ec.decide(13.0 + t, routable=3, queue_depth=30)
                for t in range(3)]
        assert ups2 == [1, 0, 0]
        # bounds: never above max_replicas, never below min_replicas
        assert [ec.decide(40.0 + t, routable=4, queue_depth=99)
                for t in range(4)] == [0] * 4
        down = ElasticController(pol, min_replicas=2)
        assert [down.decide(float(t), routable=2, queue_depth=0)
                for t in range(6)] == [0] * 6
        # a hot burn forces the up side even with an empty queue
        burn = ElasticController(pol, min_replicas=1, max_replicas=4)
        assert [burn.decide(float(t), routable=1, queue_depth=0,
                            burn_max=5.0)
                for t in range(3)] == [0, 0, 1]


class TestPenaltyBand:
    """Satellite: queue priority aging must not resurrect a shed
    tenant's entries past the burn window — deprioritized entries age
    WITHIN the penalty band."""

    def test_aging_stays_in_band_until_window_expires(self):
        from paddle_tpu.serving import RequestHandle, RequestQueue
        q = RequestQueue(max_size=16, age_after_s=0.01)
        now = time.monotonic()
        hot = RequestHandle(1, np.arange(3), 3, _greedy(4),
                            priority=0, tenant="hot")
        cold = RequestHandle(2, np.arange(3), 3, _greedy(4),
                             priority=0, tenant="cold")
        q.penalize("hot", 8, now + 30.0)
        q.put(hot)
        q.put(cold)
        eff = {h.id: e for e, _, h in q._heap}
        assert eff[1] == 8 and eff[2] == 0     # band applies at put
        # a huge aging credit: the cold tenant ages freely, the shed
        # tenant clamps strictly above base — it can NEVER reach
        # parity with healthy tenants while the window is open
        q.reap(now + 1.0)                      # credit ~100 levels
        eff = {h.id: e for e, _, h in q._heap}
        assert eff[2] < 0
        assert eff[1] == 1                     # base + 1, not base
        head = q.pop_if(lambda h: True)
        assert head is cold
        q.put(cold)
        # window expiry sweeps the penalty and normal aging resumes
        q.reap(now + 31.0)
        eff = {h.id: e for e, _, h in q._heap}
        assert eff[1] < 0
        # unpenalize() releases early, restoring base before aging
        q2 = RequestQueue(max_size=4)
        h3 = RequestHandle(3, np.arange(3), 3, _greedy(4),
                           priority=1, tenant="hot")
        q2.penalize("hot", 8, now + 30.0)
        q2.put(h3)
        assert q2._heap[0][0] == 9
        q2.unpenalize("hot")
        assert q2._heap[0][0] == 1


class TestOverloadControl:
    """Integration: the control plane wired into the Server — shed
    429s with Retry-After, trace/metric/healthz observability, the
    shed-storm flight dump, and brownout degradation hitting only
    FUTURE admissions."""

    @pytest.fixture()
    def tr(self, tmp_path):
        from paddle_tpu import tracing
        tracing.clear()
        tracing.enable(dump_dir=str(tmp_path))
        yield tracing
        tracing.disable()
        tracing.clear()

    def test_shed_rejects_with_retry_after_and_traces(self, mon, tr):
        srv, eng, _ = faulty_server(
            None, max_batch=2, segment_steps=2,
            control_policy=ControlPolicy(tick_interval_s=0.0))
        try:
            # open a shed window directly (production opens it from
            # the burn-rate tick; the submit path is what's under
            # test). Window sized so it cannot lazily expire while the
            # cold request below decodes on a loaded box; written
            # under the control lock — the gap tick iterates this dict
            with srv.control._lock:
                srv.control._shed_until["hot"] = (
                    time.monotonic() + 300.0)
            with pytest.raises(RequestRejected,
                               match="fast-burn") as ei:
                srv.submit(np.arange(4, dtype=np.int32), _greedy(4),
                           tenant="hot")
            assert ei.value.reason == "shed"
            assert 0 < ei.value.retry_after_s <= 300.0
            # other tenants are untouched
            h = srv.submit(np.arange(4, dtype=np.int32), _greedy(4),
                           tenant="cold")
            assert len(h.result(timeout=120)) == 4
            # observability: trace event, counter, and the /healthz
            # control block all tell the same story
            shed_ev = [e for e in tr.events()
                       if e["phase"] == "control.shed"]
            assert shed_ev and shed_ev[-1]["tenant"] == "hot"
            assert shed_ev[-1]["reason"] == "burn_rate"
            snap = monitor.snapshot()["metrics"]
            s = snap["paddle_tpu_serving_sheds_total"]["samples"][0]
            assert s["labels"]["tenant"] == "hot"
            assert s["labels"]["reason"] == "burn_rate"
            assert s["value"] == 1
            ctl = srv.load()["control"]
            assert ctl["sheds"] == {"hot": {"burn_rate": 1}}
            assert ctl["shed_active"] == ["hot"]
            # the window expires: the tenant is admittable again
            with srv.control._lock:
                srv.control._shed_until["hot"] = (
                    time.monotonic() - 0.1)
            h = srv.submit(np.arange(4, dtype=np.int32), _greedy(3),
                           tenant="hot")
            assert len(h.result(timeout=120)) == 3
            assert srv.drain(timeout=120)
            _assert_no_leaks(eng)
        finally:
            srv.shutdown(drain=False)

    def test_http_429_retry_after_and_healthz_control_block(self):
        from urllib.error import HTTPError
        from urllib.request import Request, urlopen
        srv, eng, _ = faulty_server(
            None, max_batch=2, segment_steps=2,
            control_policy=ControlPolicy())
        httpd = serve_http(srv)
        port = httpd.server_address[1]
        try:
            with srv.control._lock:
                srv.control._shed_until["hot"] = (
                    time.monotonic() + 300.0)
            body = json.dumps({"prompt": [1, 2], "max_new_tokens": 2,
                               "tenant": "hot"}).encode()
            with pytest.raises(HTTPError) as ei:
                urlopen(Request(f"http://127.0.0.1:{port}/generate",
                                data=body), timeout=10)
            assert ei.value.code == 429
            ra = ei.value.headers.get("Retry-After")
            assert ra is not None and 1 <= int(ra) <= 300
            err = json.load(ei.value)
            assert err["reason"] == "shed"
            assert 0 < err["retry_after_s"] <= 300.0
            # /healthz carries the control block
            with urlopen(f"http://127.0.0.1:{port}/healthz",
                         timeout=10) as r:
                hb = json.loads(r.read())
            assert hb["control"]["rung"] == 0
            assert hb["control"]["rung_action"] == "off"
            assert hb["control"]["sheds"]["hot"]["burn_rate"] >= 1
            assert hb["control"]["shed_active"] == ["hot"]
        finally:
            httpd.shutdown()
            srv.shutdown(drain=False)

    def test_queue_full_429_derives_retry_after_from_depth(self):
        """The pre-existing queue_full 429 now also answers with a
        Retry-After — derived from backlog depth, not a burn window."""
        from urllib.error import HTTPError
        from urllib.request import Request, urlopen
        import types
        srv = Server(types.SimpleNamespace(max_len=64), start=False,
                     max_queue=1)
        httpd = serve_http(srv)
        port = httpd.server_address[1]
        try:
            srv.submit(np.arange(3, dtype=np.int32), _greedy(2))
            body = json.dumps({"prompt": [1],
                               "max_new_tokens": 2}).encode()
            with pytest.raises(HTTPError) as ei:
                urlopen(Request(f"http://127.0.0.1:{port}/generate",
                                data=body), timeout=10)
            assert ei.value.code == 429
            err = json.load(ei.value)
            assert err["reason"] == "queue_full"
            assert err["retry_after_s"] > 0
            assert int(ei.value.headers["Retry-After"]) >= 1
        finally:
            httpd.shutdown()
            srv.shutdown(drain=False)

    def test_shed_storm_dumps_once_per_window(self, tr):
        """A shed STORM leaves exactly one flight dump per window —
        same density trigger + re-arm discipline as the preemption
        storm (driven synthetically through _note_shed)."""
        import types
        srv = Server(types.SimpleNamespace(max_len=64), start=False,
                     control_policy=ControlPolicy())
        srv.SHED_STORM = 3
        try:
            for _ in range(3):
                srv._note_shed("hot", "burn_rate")
            dumps = srv.fault_stats()["flight_dumps"]
            assert len(dumps) == 1
            doc = json.load(open(dumps[0]))
            assert doc["otherData"]["reason"] == "shed_storm"
            storm = [e for e in doc["traceEvents"]
                     if e["name"] == "control.shed_storm"]
            assert storm and storm[-1]["args"]["count"] == 3
            sheds = [e for e in doc["traceEvents"]
                     if e["name"] == "control.shed"]
            assert len(sheds) == 3
            # within the window, further sheds do NOT re-dump
            srv._note_shed("hot", "burn_rate")
            assert len(srv.fault_stats()["flight_dumps"]) == 1
        finally:
            srv.shutdown(drain=False)

    def test_brownout_degrades_future_admissions_only(self, tr):
        """Rung 2 engaged mid-flight: the already-admitted request
        keeps its full budget (rung transitions are bitwise-neutral
        for running work); the next admission is capped — and the
        handle's cfg carries the cap, so a preemption would replay the
        DEGRADED budget."""
        # dwell sized so the empty-queue gap tick can never disengage
        # the hand-set rung before the capped submit lands (disengage
        # needs now - _rung_since >= rung_dwell_s)
        pol = ControlPolicy(brownout_max_new=3, tick_interval_s=0.0,
                            rung_dwell_s=3600.0)
        srv, eng, _ = faulty_server(None, max_batch=2,
                                    segment_steps=2,
                                    control_policy=pol)
        try:
            h1 = srv.submit(np.arange(1, 5, dtype=np.int32),
                            _greedy(8))
            deadline = time.monotonic() + 60
            while h1.status == "queued":
                assert time.monotonic() < deadline, "never admitted"
                time.sleep(0.005)
            with srv.control._lock:  # engage (test seam; production
                #                      engages via the gap tick)
                srv.control.rung = 2
                srv.control._rung_since = time.monotonic()
            h2 = srv.submit(np.arange(2, 7, dtype=np.int32),
                            _greedy(8))
            assert len(h1.result(timeout=120)) == 8   # untouched
            assert len(h2.result(timeout=120)) == 3   # capped
            assert h2.cfg.max_new_tokens == 3
            assert srv.drain(timeout=120)
            _assert_no_leaks(eng)
        finally:
            srv.shutdown(drain=False)


class TestNetworkFaultPlan:
    """Satellite: the RemoteReplica wire seam — bounded delay /
    connection drop / mid-stream half-close under the same
    deterministic FaultPlan discipline, in a site namespace separate
    from the engine seams."""

    def test_namespace_and_actions(self):
        assert set(NET_SITES) == {"generate", "kv_import"}
        plan = NetworkFaultPlan()
        plan.delay_at("generate", nth=1, seconds=0.01)
        plan.drop_at("generate", nth=2)
        plan.half_close_at("generate", nth=3, after=2)
        t0 = time.monotonic()
        assert plan.fire("generate") is None      # delay, then clean
        assert time.monotonic() - t0 >= 0.01
        with pytest.raises(ConnectionResetError, match="drop"):
            plan.fire("generate")
        assert plan.fire("generate") == {"action": "half_close",
                                         "after": 2}
        assert plan.fire("generate") is None      # rules retired
        assert plan.injected == [("generate", 1, "delay"),
                                 ("generate", 2, "drop"),
                                 ("generate", 3, "half_close")]
        assert plan.calls == {"generate": 4, "kv_import": 0}
        # the namespaces never cross: engine sites are invalid here
        with pytest.raises(ValueError, match="unknown site"):
            plan.drop_at("decode")
        with pytest.raises(ValueError, match="unknown site"):
            FaultPlan().raise_at("generate")
        # delays are releasable, like hangs
        slow = NetworkFaultPlan().delay_at("kv_import", seconds=30)
        t = threading.Timer(0.05, slow.release_hangs)
        t.start()
        t0 = time.monotonic()
        slow.fire("kv_import")
        assert time.monotonic() - t0 < 5
        t.join()

    def test_drop_and_half_close_against_live_replica(self):
        """End to end over a real socket: a dropped /generate surfaces
        as the replica-unreachable error (what the router failovers
        on); a mid-stream half-close tears the stream after exactly N
        relayed tokens, the handle resolves FAILED (never hangs), and
        the server reclaims the sheared request's capacity."""
        from paddle_tpu.serving import RemoteReplica
        srv, eng, _ = faulty_server(None, max_batch=2,
                                    segment_steps=2)
        httpd = serve_http(srv)
        port = httpd.server_address[1]
        # wire hardening OFF: this test pins the RAW fault surface the
        # retry/resume layers are built on (a retried drop succeeds
        # and a half-close resumes — covered by test_wire_hardening)
        rep = RemoteReplica(f"http://127.0.0.1:{port}",
                            wire_retries=0, max_resumes=0)
        plan = NetworkFaultPlan()
        rep.fault_plan = plan
        try:
            assert rep.wait_ready(timeout=120)
            plan.drop_at("generate", nth=1)
            with pytest.raises(RuntimeError, match="unreachable"):
                rep.submit(np.arange(4, dtype=np.int32), _greedy(4))
            # call 2: clean — the plan injects exactly where told
            h = rep.submit(np.arange(4, dtype=np.int32), _greedy(4))
            assert len(h.result(timeout=120)) == 4
            plan.half_close_at("generate", nth=3, after=2)
            h = rep.submit(np.arange(4, dtype=np.int32), _greedy(6))
            with pytest.raises(RequestFailed, match="stream"):
                h.result(timeout=120)
            assert len(h.tokens_so_far()) == 2
            assert plan.injected == [
                ("generate", 1, "drop"), ("generate", 3, "half_close")]
            # the server side reclaims the sheared request (broken-
            # pipe guard): capacity back to full
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if (eng.free_slots() == eng.max_batch
                        and eng.alloc.free_pages == eng.num_pages):
                    break
                time.sleep(0.02)
            _assert_no_leaks(eng)
            # the kv_import seam counts and injects the same way (the
            # endpoint itself is exercised by the remote suite)
            plan.drop_at("kv_import", nth=1)
            with pytest.raises(ConnectionResetError):
                rep.import_kv_raw(b"\x00" * 16)
            assert plan.calls["kv_import"] == 1
        finally:
            rep.close()
            httpd.shutdown()
            srv.shutdown(drain=False)


class TestElasticFleet:
    """Tentpole (elastic actuator): scale-down drains — never fails an
    in-flight handle — parks the slot as ``scaled_down``, and scale-up
    revives it from its own spec; every event traced."""

    @pytest.fixture()
    def tr(self, tmp_path):
        from paddle_tpu import tracing
        tracing.clear()
        tracing.enable(dump_dir=str(tmp_path))
        yield tracing
        tracing.disable()
        tracing.clear()

    def test_scale_down_never_fails_inflight_then_revives(self, tr):
        from paddle_tpu.serving import ReplicaSpec, Router

        def factory():
            model, _ = tiny_model()
            return paged_engine(model, max_batch=2)

        spec = ReplicaSpec(factory,
                           server_kwargs={"segment_steps": 2,
                                          "idle_wait_s": 0.005})
        r = Router(spec, replicas=2, monitor_interval_s=0.05)
        try:
            assert r.wait_ready(timeout=600)
            hs = [r.submit(np.arange(1, 6, dtype=np.int32),
                           _greedy(12)) for _ in range(4)]
            assert r.scale_to(1, timeout=600) == 1
            for h in hs:                       # the PR 9 bar: every
                #                                in-flight handle lands
                assert len(h.result(timeout=600)) == 12
            snap = r.load()
            assert len(snap["scaled_down"]) == 1
            assert snap["replicas"][snap["scaled_down"][0]][
                "status"] == "scaled_down"
            # parked capacity does not read as a degraded fleet
            assert snap["status"] == "ok"
            # the shrunken fleet still serves
            h = r.submit(np.arange(3, dtype=np.int32), _greedy(4))
            assert len(h.result(timeout=120)) == 4
            # revive: back to 2, the revived slot takes traffic
            assert r.scale_to(2, timeout=600) == 2
            assert r.load()["scaled_down"] == []
            hs = [r.submit(np.arange(3, dtype=np.int32), _greedy(4))
                  for _ in range(4)]
            for h in hs:
                assert len(h.result(timeout=120)) == 4
            ev = [e for e in tr.events()
                  if e["phase"] == "control.scale"]
            assert [e["action"] for e in ev] == ["down", "up"]
        finally:
            r.shutdown(drain=False)

    def test_elastic_knob_validation(self):
        from paddle_tpu.serving import ReplicaSpec, Router

        def factory():
            model, _ = tiny_model()
            return paged_engine(model)

        spec = ReplicaSpec(factory)
        with pytest.raises(ValueError, match="elastic"):
            Router(spec, replicas=2, elastic=object(), start=False)
        with pytest.raises(ValueError, match="elastic_interval_s"):
            Router(spec, replicas=2, elastic=ControlPolicy(),
                   elastic_interval_s=0, start=False)


@pytest.mark.slow
class TestChaosSoak:
    def test_serve_bench_under_injected_faults(self, mon, capsys):
        """The chaos soak: serve_bench drives open-loop load with
        seeded engine faults injected at the decode seam; the run
        completes, reports the fault/restart/recovery BENCH records,
        and every arrival is accounted for (survived + failed +
        rejected == requests)."""
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "serve_bench", os.path.join(
                os.path.dirname(__file__), "..", "tools",
                "serve_bench.py"))
        sb = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(sb)
        assert sb.main([
            "--rate", "30", "--requests", "24", "--max-new", "8",
            "--prompt-len", "3:12", "--fault-rate", "0.3",
            "--fault-site", "decode", "--fault-kind", "engine",
            "--max-restarts", "1000", "--restart-backoff", "0.01",
            "--seed", "3"]) == 0
        text = capsys.readouterr().out
        recs = {}
        for line in text.splitlines():
            try:
                r = json.loads(line)
            except json.JSONDecodeError:
                continue
            recs[r["metric"]] = r["value"]
        assert "serve_faults_injected" in recs
        assert "serve_restarts" in recs
        assert recs["serve_requests_survived"] \
            + recs["serve_requests_failed"] \
            + recs["serve_rejected"] == 24
        if recs["serve_restarts"]:
            assert "serve_recovery_p50" in recs
