"""Native (C++) parameter-server data plane (distributed/ps/native.py
over native/src/ps_table.cc).

Reference analog: the brpc data-plane tests
(test/legacy_test/test_dist_fleet_ps*.py exercise pull/push/save through
the brpc service); here the same contracts run over the native TCP
protocol, PLUS a cross-plane guarantee the reference never needed:
tables built through the native plane are bit-identical to the Python
plane (shared splitmix64 row init), so the planes are interchangeable
per cluster.
"""
import os

import numpy as np
import pytest

from paddle_tpu import native as native_lib
from paddle_tpu.distributed.ps import PsClient, PsServer, TableConfig

pytestmark = pytest.mark.skipif(
    native_lib.lib_path() is None,
    reason="native toolchain unavailable (g++ build failed)")


def _native():
    from paddle_tpu.distributed.ps.native import (NativePsClient,
                                                  NativePsServer)

    return NativePsServer, NativePsClient


def _pair(n=2):
    NativePsServer, NativePsClient = _native()
    srvs = [NativePsServer(i, n) for i in range(n)]
    c = NativePsClient([f"127.0.0.1:{s.port}" for s in srvs])
    return srvs, c


class TestNativePlane:
    def test_pull_deterministic_and_sharded(self):
        srvs, c = _pair(2)
        try:
            c.create_table(TableConfig("emb", dim=4, seed=3))
            ids = np.array([0, 1, 2, 3, 7, 10], np.int64)
            a = c.pull_sparse("emb", ids)
            np.testing.assert_array_equal(a, c.pull_sparse("emb", ids))
            stats = c.stats()
            assert stats[0]["emb"] == 3 and stats[1]["emb"] == 3
        finally:
            c.stop_servers()

    @pytest.mark.parametrize("opt", ["sgd", "adagrad", "adam"])
    def test_server_side_optimizers_move_rows(self, opt):
        srvs, c = _pair(2)
        try:
            c.create_table(TableConfig("t", dim=3, optimizer=opt, lr=0.1))
            ids = np.array([4, 5], np.int64)
            before = c.pull_sparse("t", ids)
            c.push_sparse("t", ids, np.ones((2, 3), np.float32))
            after = c.pull_sparse("t", ids)
            assert np.all(after < before)  # positive grads move weights down
        finally:
            c.stop_servers()

    def test_dense_params(self):
        srvs, c = _pair(1)
        try:
            c.init_dense("w", np.arange(5, dtype=np.float32))
            c.push_dense("w", np.ones(5, np.float32), lr=0.5)
            np.testing.assert_allclose(
                c.pull_dense("w"), np.arange(5, dtype=np.float32) - 0.5)
        finally:
            c.stop_servers()

    def test_barrier_positions(self):
        srvs, c = _pair(1)
        try:
            assert c.barrier("b", world=1) == 1
            assert c.barrier("b", world=1) == 1  # next generation
        finally:
            c.stop_servers()

    def test_save_load_roundtrip(self, tmp_path):
        NativePsServer, NativePsClient = _native()
        srvs, c = _pair(2)
        try:
            c.create_table(TableConfig("t", dim=3, optimizer="sgd", lr=0.5))
            ids = np.array([4, 5, 6, 9], np.int64)
            c.push_sparse("t", ids, np.ones((4, 3), np.float32))
            want = c.pull_sparse("t", ids)
            c.save(str(tmp_path))
            files = sorted(os.listdir(tmp_path))
            assert files == ["t.shard0.psbin", "t.shard1.psbin"]
        finally:
            c.stop_servers()
        fresh = [NativePsServer(i, 2) for i in range(2)]
        c2 = NativePsClient([f"127.0.0.1:{s.port}" for s in fresh])
        try:
            for s in fresh:
                s.load_model(str(tmp_path))
            c2.create_table(TableConfig("t", dim=3, optimizer="sgd", lr=0.5))
            np.testing.assert_array_equal(c2.pull_sparse("t", ids), want)
            # RESUMED training honors the re-created config (create_table
            # adopts cfg onto restored rows — load defaults to sgd/0.01,
            # which would silently train wrong otherwise)
            c2.push_sparse("t", ids, np.ones((4, 3), np.float32))
            np.testing.assert_allclose(c2.pull_sparse("t", ids),
                                       want - 0.5, rtol=1e-6)
        finally:
            c2.stop_servers()

    def test_load_rejects_truncated_file(self, tmp_path):
        NativePsServer, NativePsClient = _native()
        srvs, c = _pair(1)
        try:
            c.create_table(TableConfig("t", dim=3))
            c.pull_sparse("t", np.array([1, 2, 3], np.int64))
            c.save(str(tmp_path))
        finally:
            c.stop_servers()
        path = tmp_path / "t.shard0.psbin"
        blob = path.read_bytes()
        path.write_bytes(blob[:-5])  # truncate mid-row (crash/full disk)
        fresh = NativePsServer(0, 1)
        try:
            with pytest.raises(OSError, match="native rc="):
                fresh.load_model(str(tmp_path))
        finally:
            fresh.stop()

    def test_stats_discovers_other_clients_tables(self):
        """stats() reports EVERY server-side table via the LIST op —
        including tables a monitoring client never created (Python-plane
        parity)."""
        NativePsServer, NativePsClient = _native()
        srvs, c1 = _pair(1)
        try:
            c1.create_table(TableConfig("emb", dim=4))
            c1.pull_sparse("emb", np.arange(5, dtype=np.int64))
            c2 = NativePsClient([f"127.0.0.1:{srvs[0].port}"])
            assert c2.stats() == [{"emb": 5}]
            c2.close()
        finally:
            c1.stop_servers()

    def test_newline_table_name_refused(self):
        srvs, c = _pair(1)
        try:
            with pytest.raises(ValueError, match="newline"):
                c.create_table(TableConfig("a\nb", dim=2))
        finally:
            c.stop_servers()

    def test_convert_save_roundtrips_both_ways(self, tmp_path):
        """convert_save bridges the per-plane save formats: a Python-
        plane save restores on a native server after conversion with
        bit-identical rows (and the loud format errors point here)."""
        from paddle_tpu.distributed.ps.native import (NativePsClient,
                                                      NativePsServer,
                                                      convert_save)

        psrv = PsServer(0, 1).start()
        pc = PsClient([f"127.0.0.1:{psrv.port}"])
        try:
            pc.create_table(TableConfig("t", dim=3, seed=5))
            ids = np.array([1, 2, 9], np.int64)
            want = pc.pull_sparse("t", ids)
            pc.save(str(tmp_path))
        finally:
            pc.stop_servers()
        nsrv = NativePsServer(0, 1)
        try:
            with pytest.raises(ValueError, match="convert_save"):
                nsrv.load_model(str(tmp_path))
            convert_save(str(tmp_path), to="native")
            nsrv.load_model(str(tmp_path))
            nc = NativePsClient([f"127.0.0.1:{nsrv.port}"])
            nc.create_table(TableConfig("t", dim=3, seed=5))
            np.testing.assert_array_equal(nc.pull_sparse("t", ids), want)
            nc.close()
        finally:
            nsrv.stop()
        # and back: psbin -> npz restores on a fresh Python server
        for f in tmp_path.glob("*.npz"):
            f.unlink()
        convert_save(str(tmp_path), to="python")
        psrv2 = PsServer(0, 1).start()
        pc2 = PsClient([f"127.0.0.1:{psrv2.port}"])
        try:
            psrv2.load_model(str(tmp_path))
            pc2.create_table(TableConfig("t", dim=3, seed=5))
            np.testing.assert_array_equal(pc2.pull_sparse("t", ids), want)
        finally:
            pc2.stop_servers()

    def test_entry_policies_refused(self):
        from paddle_tpu.distributed import CountFilterEntry

        srvs, c = _pair(1)
        try:
            with pytest.raises(ValueError, match="Python-data-plane"):
                c.create_table(TableConfig("g", dim=2,
                                           entry=CountFilterEntry(2)))
        finally:
            c.stop_servers()


class TestCrossPlaneParity:
    """The load-bearing guarantee: both planes produce IDENTICAL tables
    for identical traffic (shared splitmix64 init; same f32 server-side
    optimizer math). sgd/adagrad are bit-exact; adam's bias-correction
    uses double intermediates whose final f32 rounding may differ by one
    ulp across planes."""

    def _python_pair(self, n=2):
        srvs = [PsServer(i, n).start() for i in range(n)]
        c = PsClient([f"127.0.0.1:{s.port}" for s in srvs])
        return srvs, c

    def test_init_bit_exact(self):
        nsrv, nc = _pair(2)
        psrv, pc = self._python_pair(2)
        try:
            for c in (nc, pc):
                c.create_table(TableConfig("e", dim=8, seed=7))
            ids = np.array([0, 1, 5, 12, 999, -3], np.int64)
            np.testing.assert_array_equal(nc.pull_sparse("e", ids),
                                          pc.pull_sparse("e", ids))
        finally:
            nc.stop_servers()
            pc.stop_servers()

    @pytest.mark.parametrize("opt,tol", [("sgd", 0.0), ("adagrad", 0.0),
                                         ("adam", 1e-6)])
    def test_trajectory_parity(self, opt, tol):
        nsrv, nc = _pair(2)
        psrv, pc = self._python_pair(2)
        try:
            for c in (nc, pc):
                c.create_table(TableConfig("t", dim=4, optimizer=opt,
                                           lr=0.1, seed=1))
            rng = np.random.RandomState(0)
            ids = np.array([2, 3, 8, 11], np.int64)
            for _ in range(5):
                g = rng.randn(4, 4).astype(np.float32)
                nc.push_sparse("t", ids, g)
                pc.push_sparse("t", ids, g)
            a, b = nc.pull_sparse("t", ids), pc.pull_sparse("t", ids)
            if tol == 0.0:
                np.testing.assert_array_equal(a, b)
            else:
                np.testing.assert_allclose(a, b, rtol=0, atol=tol)
        finally:
            nc.stop_servers()
            pc.stop_servers()


class TestFleetFlowNative:
    def test_fleet_roles_pick_native_plane(self, monkeypatch):
        """fleet.init_server/init_worker honor PADDLE_PS_DATA_PLANE, and
        the default (auto) prefers the native binary-protocol plane when
        the toolchain built it — plain tables shouldn't ride pickle
        (VERDICT r4 Weak #5)."""
        from paddle_tpu.distributed.fleet import _ps_plane
        from paddle_tpu.distributed.ps.native import (NativePsClient,
                                                      NativePsServer)

        monkeypatch.setenv("PADDLE_PS_DATA_PLANE", "native")
        srv_cls, cli_cls = _ps_plane()
        assert srv_cls is NativePsServer and cli_cls is NativePsClient
        monkeypatch.setenv("PADDLE_PS_DATA_PLANE", "python")
        srv_cls, cli_cls = _ps_plane()
        assert srv_cls is PsServer and cli_cls is PsClient
        # auto: this suite is gated on the toolchain, so native wins
        monkeypatch.delenv("PADDLE_PS_DATA_PLANE")
        srv_cls, cli_cls = _ps_plane()
        assert srv_cls is NativePsServer and cli_cls is NativePsClient

    def test_distributed_embedding_over_native_plane(self):
        """DistributedEmbedding works unchanged over the native client
        (same pull/push surface)."""
        from paddle_tpu.distributed.ps import DistributedEmbedding

        srvs, c = _pair(2)
        try:
            emb = DistributedEmbedding(c, "emb", dim=4, optimizer="sgd",
                                       lr=0.5)
            ids = np.array([[1, 2], [3, 4]], np.int64)
            rows = emb.pull(ids)
            assert rows.shape == (2, 2, 4)
            g = np.ones((2, 2, 4), np.float32)
            emb.push(ids, g)
            np.testing.assert_allclose(emb.pull(ids), rows - 0.5,
                                       rtol=1e-6)
        finally:
            c.stop_servers()


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return buf  # closed — caller distinguishes
        buf += chunk
    return buf


def _raw_req(sock, op, name=b"", n=0, payload=b""):
    import struct

    sock.sendall(struct.pack("<BI", op, len(name)) + name
                 + struct.pack("<Q", n) + payload)


def _raw_resp(sock):
    import struct

    hdr = _recv_exact(sock, 16)
    if len(hdr) < 16:
        return None, b""  # connection closed before a reply
    status, plen = struct.unpack("<qQ", hdr)
    return status, _recv_exact(sock, plen) if plen else b""


class TestWireHardening:
    """The wire-supplied sizes/names are untrusted (ADVICE r4): an
    overflowing or huge count must produce an error status — never an
    under-allocated buffer, a bad_alloc in a detached thread
    (std::terminate kills the in-process trainer), or a path escape."""

    def _raw(self, port):
        import socket

        s = socket.create_connection(("127.0.0.1", port), timeout=10)
        s.settimeout(10)
        return s

    def test_overflowing_push_count_rejected_server_survives(self):
        import struct

        srvs, c = _pair(1)
        try:
            c.create_table(TableConfig("emb", dim=8))
            s = self._raw(srvs[0].port)
            # n*(8+dim*4) overflows uint64 — before the fix this under-
            # allocated payload and the apply loop read OOB
            _raw_req(s, 2, b"emb", n=2 ** 61, payload=struct.pack("<I", 8))
            status, _ = _raw_resp(s)
            assert status == -6
            assert s.recv(1) == b""  # desynced stream is closed
            s.close()
            # the server (and the trainer process hosting it) is alive
            ids = np.array([1, 2], np.int64)
            assert c.pull_sparse("emb", ids).shape == (2, 8)
        finally:
            c.stop_servers()

    def test_huge_nonoverflowing_pull_rejected(self):
        srvs, c = _pair(1)
        try:
            c.create_table(TableConfig("emb", dim=8))
            s = self._raw(srvs[0].port)
            # n*8 = 4 GiB: no overflow, but resize would bad_alloc in a
            # detached thread -> std::terminate before the cap existed
            _raw_req(s, 1, b"emb", n=2 ** 29)
            status, _ = _raw_resp(s)
            assert status == -6
            s.close()
            assert c.stats() == [{"emb": 0}]
        finally:
            c.stop_servers()

    def test_dense_init_over_cap_rejected(self):
        srvs, c = _pair(1)
        try:
            s = self._raw(srvs[0].port)
            _raw_req(s, 3, b"w", n=2 ** 30)  # 4 GiB of floats
            status, _ = _raw_resp(s)
            assert status == -6
            s.close()
        finally:
            c.stop_servers()

    @pytest.mark.parametrize("bad", [b"../evil", b"a/b", b"", b"x" * 300])
    def test_create_rejects_path_escaping_names_server_side(self, bad):
        """native.py validates client-side; a RAW client must hit the
        same wall server-side — table names become save-file path
        components."""
        import struct

        srvs, c = _pair(1)
        try:
            s = self._raw(srvs[0].port)
            # wire TableCfg: sizeof==40 (2 bytes pad before seed, 4
            # trailing pad after init_range)
            cfg = struct.pack("<IBB2xQ5f4x", 4, 0, 0, 0, 0.01, 0.9,
                              0.999, 1e-8, 0.1)
            _raw_req(s, 0, bad, payload=cfg)
            status, _ = _raw_resp(s)
            assert status == -6
            s.close()
        finally:
            c.stop_servers()

    def test_load_dim_mismatch_is_error(self, tmp_path):
        """Loading a .psbin with a different dim into an existing table
        must fail loudly (-4) — short rows would make later PULL/PUSH
        memcpys run past the row buffer."""
        NativePsServer, NativePsClient = _native()
        d = str(tmp_path)
        srvs, c = _pair(1)
        try:
            c.create_table(TableConfig("t", dim=4, seed=1))
            c.pull_sparse("t", np.array([1, 2], np.int64))
            c.save(d)
        finally:
            c.stop_servers()
        srv2 = NativePsServer(0, 1)
        c2 = NativePsClient([f"127.0.0.1:{srv2.port}"])
        try:
            c2.create_table(TableConfig("t", dim=8, seed=1))
            with pytest.raises(OSError, match="rc=-4"):
                srv2.load_model(d)
        finally:
            c2.stop_servers()

    def test_barrier_abort_on_stop_is_not_success(self):
        """A stop-woken barrier waiter must NOT receive its arrival
        position (callers would proceed as if all peers arrived)."""
        import threading

        srvs, c = _pair(1)
        try:
            got = {}
            s1 = self._raw(srvs[0].port)

            def waiter():
                _raw_req(s1, 6, b"bar", n=2)  # world=2, only 1 arrives
                got["status"], _ = _raw_resp(s1)

            t = threading.Thread(target=waiter)
            t.start()
            import time as _t

            _t.sleep(0.3)  # let the waiter block in the barrier
            s2 = self._raw(srvs[0].port)
            _raw_req(s2, 9)  # STOP wakes the waiter via stop+notify
            _raw_resp(s2)
            s2.close()
            t.join(timeout=10)
            assert not t.is_alive()
            assert got["status"] == -9
            s1.close()
        finally:
            c.stop_servers()
