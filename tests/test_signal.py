"""paddle.signal behavior depth (reference python/paddle/signal.py).

Oracles: torch.stft/istft (an independent implementation of the same
conventions — center/pad_mode/normalized/onesided, [*, bins, frames]
layout) plus analytic invariants (round-trip reconstruction, pure-tone
peak bin, COLA normalization).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.signal as psig

torch = pytest.importorskip("torch")


def _t(a):
    return paddle.to_tensor(np.ascontiguousarray(a))


def _np(x):
    return np.asarray(x.value if hasattr(x, "value") else x)


def hann(n):
    return np.hanning(n + 1)[:-1].astype(np.float32)


class TestStftVsTorch:
    @pytest.mark.parametrize("n_fft,hop", [(64, 16), (64, 32), (32, 8)])
    def test_matches_torch_hann(self, n_fft, hop):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 400).astype(np.float32)
        w = hann(n_fft)
        got = _np(psig.stft(_t(x), n_fft, hop_length=hop, window=_t(w)))
        want = torch.stft(torch.from_numpy(x), n_fft, hop_length=hop,
                          window=torch.from_numpy(w),
                          return_complex=True).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)

    def test_matches_torch_no_center(self):
        rng = np.random.RandomState(1)
        x = rng.randn(300).astype(np.float32)
        got = _np(psig.stft(_t(x), 64, hop_length=16, center=False))
        want = torch.stft(torch.from_numpy(x), 64, hop_length=16,
                          center=False, return_complex=True).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)

    def test_matches_torch_normalized_twosided(self):
        rng = np.random.RandomState(2)
        x = rng.randn(256).astype(np.float32)
        got = _np(psig.stft(_t(x), 32, hop_length=8, normalized=True,
                            onesided=False))
        want = torch.stft(torch.from_numpy(x), 32, hop_length=8,
                          normalized=True, onesided=False,
                          return_complex=True).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)

    def test_win_length_padding(self):
        rng = np.random.RandomState(3)
        x = rng.randn(256).astype(np.float32)
        w = hann(24)
        got = _np(psig.stft(_t(x), 32, hop_length=8, win_length=24,
                            window=_t(w)))
        want = torch.stft(torch.from_numpy(x), 32, hop_length=8,
                          win_length=24, window=torch.from_numpy(w),
                          return_complex=True).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


class TestInvariants:
    @pytest.mark.parametrize("n_fft,hop", [(64, 16), (32, 8)])
    def test_roundtrip_reconstruction(self, n_fft, hop):
        rng = np.random.RandomState(4)
        x = rng.randn(2, 320).astype(np.float32)
        w = hann(n_fft)
        spec = psig.stft(_t(x), n_fft, hop_length=hop, window=_t(w))
        back = _np(psig.istft(spec, n_fft, hop_length=hop, window=_t(w),
                              length=320))
        np.testing.assert_allclose(back, x, rtol=1e-3, atol=1e-3)

    def test_pure_tone_peak_bin(self):
        n_fft, fs = 128, 1000.0
        f0 = 250.0                       # -> bin 32
        t = np.arange(1024) / fs
        x = np.sin(2 * np.pi * f0 * t).astype(np.float32)
        spec = np.abs(_np(psig.stft(_t(x), n_fft,
                                    hop_length=n_fft // 4,
                                    window=_t(hann(n_fft)))))
        peak = spec.mean(axis=-1).argmax()
        assert peak == round(f0 * n_fft / fs), peak

    def test_istft_matches_torch(self):
        rng = np.random.RandomState(5)
        x = rng.randn(300).astype(np.float32)
        w = hann(64)
        spec_t = torch.stft(torch.from_numpy(x), 64, hop_length=16,
                            window=torch.from_numpy(w),
                            return_complex=True)
        want = torch.istft(spec_t, 64, hop_length=16,
                           window=torch.from_numpy(w), length=300).numpy()
        got = _np(psig.istft(_t(spec_t.numpy()), 64, hop_length=16,
                             window=_t(w), length=300))
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)

    def test_grad_flows_through_stft(self):
        x = paddle.to_tensor(
            np.random.RandomState(6).randn(128).astype(np.float32))
        x.stop_gradient = False
        spec = psig.stft(x, 32, hop_length=8)
        mag = (spec.real() ** 2 + spec.imag() ** 2) \
            if hasattr(spec, "real") and callable(
                getattr(spec, "real", None)) else None
        if mag is None:
            loss = (spec.abs() ** 2).sum()
        else:
            loss = mag.sum()
        loss.backward()
        assert x.grad is not None
        assert float(np.abs(_np(x.grad)).max()) > 0
