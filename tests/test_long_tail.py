"""Long-tail package tests: fft, sparse, distribution, quantization
(reference analogs: test/fft/, test/legacy_test/test_sparse_*,
test/distribution/, test/quantization/)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
import paddle_tpu as paddle
from paddle_tpu import nn


class TestFFT:
    def test_fft_roundtrip(self):
        x = paddle.to_tensor(np.random.randn(8).astype(np.float32))
        y = paddle.fft.ifft(paddle.fft.fft(x))
        np.testing.assert_allclose(np.real(y.numpy()), x.numpy(), atol=1e-5)

    def test_rfft_matches_numpy(self):
        x = np.random.randn(16).astype(np.float32)
        y = paddle.fft.rfft(paddle.to_tensor(x))
        np.testing.assert_allclose(y.numpy(), np.fft.rfft(x), rtol=1e-4,
                                   atol=1e-5)

    def test_fft2_and_shift(self):
        x = np.random.randn(4, 4).astype(np.float32)
        y = paddle.fft.fftshift(paddle.fft.fft2(paddle.to_tensor(x)))
        ref = np.fft.fftshift(np.fft.fft2(x))
        np.testing.assert_allclose(y.numpy(), ref, rtol=1e-4, atol=1e-4)

    def test_fftfreq(self):
        np.testing.assert_allclose(paddle.fft.fftfreq(8).numpy(),
                                   np.fft.fftfreq(8).astype(np.float32))

    def test_grad_flows(self):
        x = paddle.to_tensor(np.random.randn(8).astype(np.float32))
        x.stop_gradient = False
        y = paddle.fft.rfft(x)
        loss = (y.abs() ** 2).sum()
        loss.backward()
        assert x.grad is not None


class TestSparse:
    def test_coo_roundtrip(self):
        indices = [[0, 1, 2], [1, 2, 0]]
        values = [1.0, 2.0, 3.0]
        s = paddle.sparse.sparse_coo_tensor(indices, values, shape=[3, 3])
        assert s.is_sparse_coo()
        assert s.nnz == 3
        dense = s.to_dense().numpy()
        assert dense[0, 1] == 1.0 and dense[1, 2] == 2.0 and dense[2, 0] == 3.0

    def test_csr(self):
        s = paddle.sparse.sparse_csr_tensor(
            [0, 1, 2], [1, 0], [5.0, 6.0], shape=[2, 2])
        assert s.is_sparse_csr()
        d = s.to_dense().numpy()
        assert d[0, 1] == 5.0 and d[1, 0] == 6.0

    def test_matmul(self):
        s = paddle.sparse.sparse_coo_tensor([[0, 1], [0, 1]], [2.0, 3.0],
                                            shape=[2, 2])
        y = np.random.randn(2, 4).astype(np.float32)
        out = paddle.sparse.matmul(s, jnp.asarray(y))
        ref = np.diag([2.0, 3.0]).astype(np.float32) @ y
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)

    def test_relu_and_add(self):
        s = paddle.sparse.sparse_coo_tensor([[0, 1], [0, 1]], [-1.0, 2.0],
                                            shape=[2, 2])
        r = paddle.sparse.relu(s)
        assert r.to_dense().numpy()[0, 0] == 0.0
        out = paddle.sparse.add(s, s)
        assert out.to_dense().numpy()[1, 1] == 4.0

    def test_masked_matmul(self):
        x = np.ones((2, 3), np.float32)
        y = np.ones((3, 2), np.float32)
        mask = paddle.sparse.sparse_coo_tensor([[0], [1]], [1.0], shape=[2, 2])
        out = paddle.sparse.masked_matmul(jnp.asarray(x), jnp.asarray(y), mask)
        d = out.to_dense().numpy()
        assert d[0, 1] == 3.0 and d[0, 0] == 0.0


class TestDistribution:
    def test_normal_logprob_entropy_kl(self):
        from paddle_tpu.distribution import Normal, kl_divergence

        p = Normal(0.0, 1.0)
        np.testing.assert_allclose(float(p.log_prob(0.0)._value),
                                   -0.5 * np.log(2 * np.pi), rtol=1e-6)
        np.testing.assert_allclose(float(p.entropy()._value),
                                   0.5 + 0.5 * np.log(2 * np.pi), rtol=1e-6)
        q = Normal(1.0, 2.0)
        kl = float(kl_divergence(p, q)._value)
        ref = np.log(2.0) + (1 + 1) / (2 * 4) - 0.5
        np.testing.assert_allclose(kl, ref, rtol=1e-5)

    def test_sampling_moments(self):
        from paddle_tpu.distribution import Gumbel, Laplace, Normal, Uniform

        paddle.seed(0)
        for dist, mean, tol in [
                (Normal(2.0, 0.5), 2.0, 0.05),
                (Uniform(0.0, 4.0), 2.0, 0.1),
                (Laplace(1.0, 1.0), 1.0, 0.1),
                (Gumbel(0.0, 1.0), float(np.euler_gamma), 0.1)]:
            s = dist.sample([20000])
            assert abs(float(jnp.mean(s._value)) - mean) < tol, type(dist)

    def test_categorical(self):
        from paddle_tpu.distribution import Categorical

        c = Categorical(logits=np.log([0.2, 0.8]).astype(np.float32))
        lp = c.log_prob(paddle.to_tensor(np.array(1)))
        np.testing.assert_allclose(float(lp._value), np.log(0.8), rtol=1e-5)
        ent = float(c.entropy()._value)
        ref = -(0.2 * np.log(0.2) + 0.8 * np.log(0.8))
        np.testing.assert_allclose(ent, ref, rtol=1e-5)

    def test_beta_dirichlet(self):
        from paddle_tpu.distribution import Beta, Dirichlet

        b = Beta(2.0, 3.0)
        np.testing.assert_allclose(float(b.mean._value), 0.4, rtol=1e-6)
        d = Dirichlet(np.array([1.0, 2.0, 2.0], np.float32))
        np.testing.assert_allclose(np.asarray(d.mean._value),
                                   [0.2, 0.4, 0.4], rtol=1e-5)
        s = d.sample()
        np.testing.assert_allclose(float(jnp.sum(s._value)), 1.0, rtol=1e-5)

    def test_beta_logprob_closed_form(self):
        from paddle_tpu.distribution import Beta

        b = Beta(2.0, 2.0)
        # pdf(x; 2,2) = 6x(1-x) → log pdf(0.5) = log(1.5)
        np.testing.assert_allclose(float(b.log_prob(0.5)._value),
                                   np.log(1.5), rtol=1e-5)

    def test_multinomial(self):
        from paddle_tpu.distribution import Multinomial

        m = Multinomial(10, np.array([0.3, 0.7], np.float32))
        s = m.sample()
        assert float(jnp.sum(s._value)) == 10.0
        lp = m.log_prob(paddle.to_tensor(np.array([3.0, 7.0])))
        assert np.isfinite(float(lp._value))

    def test_kl_unregistered_raises(self):
        from paddle_tpu.distribution import Gumbel, Normal, kl_divergence

        with pytest.raises(NotImplementedError):
            kl_divergence(Normal(0.0, 1.0), Gumbel(0.0, 1.0))


class TestQuantization:
    def test_quant_dequant_roundtrip(self):
        from paddle_tpu.quantization import dequant, quant

        x = paddle.to_tensor(np.array([0.5, -1.0, 0.25], np.float32))
        q = quant(x, scale=1.0)
        assert q._value.dtype == jnp.int8
        back = dequant(q, scale=1.0)
        np.testing.assert_allclose(back.numpy(), x.numpy(), atol=1e-2)

    def test_fake_quant_ste_grad(self):
        from paddle_tpu.quantization import fake_quant

        x = paddle.to_tensor(np.array([0.3, 2.0], np.float32))
        x.stop_gradient = False
        y = fake_quant(x, scale=1.0)
        y.sum().backward()
        # STE: grad 1 inside [-scale, scale], 0 outside
        np.testing.assert_array_equal(x.grad.numpy(), [1.0, 0.0])

    def test_absmax_observer(self):
        from paddle_tpu.quantization import AbsmaxObserver

        obs = AbsmaxObserver()
        obs(paddle.to_tensor(np.array([0.5, -3.0], np.float32)))
        obs(paddle.to_tensor(np.array([1.0], np.float32)))
        assert float(obs.scales()._value) == 3.0

    def test_qat_swaps_and_trains(self):
        from paddle_tpu.optimizer import SGD
        from paddle_tpu.quantization import QAT, QuantConfig, QuantedLinear

        model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        qat = QAT(QuantConfig())
        model = qat.quantize(model)
        assert isinstance(model[0], QuantedLinear)
        opt = SGD(learning_rate=0.1, parameters=model.parameters())
        x = paddle.to_tensor(np.random.randn(4, 4).astype(np.float32))
        losses = []
        for _ in range(5):
            loss = (model(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_ptq_observes(self):
        from paddle_tpu.quantization import PTQ

        model = nn.Sequential(nn.Linear(4, 2))
        ptq = PTQ()
        model = ptq.quantize(model, inplace=True)
        model(paddle.to_tensor(np.random.randn(8, 4).astype(np.float32)))
        assert ptq._observers and float(ptq._observers[0].scales()._value) > 0

    def test_ptq_convert_freezes_calibrated_scale(self):
        from paddle_tpu.quantization import PTQ, QuantedLinear

        model = nn.Sequential(nn.Linear(4, 2))
        ptq = PTQ()
        model = ptq.quantize(model, inplace=True)
        calib = np.zeros((4, 4), np.float32)
        calib[0, 0] = 7.0  # absmax = 7
        model(paddle.to_tensor(calib))
        model = ptq.convert(model)
        ql = model[0]
        assert isinstance(ql, QuantedLinear)
        assert abs(ql.activation_quanter._scale - 7.0) < 1e-6
        assert ql.weight_quanter._scale is not None

    def test_quantize_not_inplace_preserves_original(self):
        from paddle_tpu.quantization import QAT, QuantConfig, QuantedLinear

        model = nn.Sequential(nn.Linear(4, 2))
        q = QAT(QuantConfig()).quantize(model, inplace=False)
        assert isinstance(q[0], QuantedLinear)
        assert not isinstance(model[0], QuantedLinear)  # original untouched

    def test_fake_quanter_under_jit(self):
        from paddle_tpu.quantization import QAT, QuantConfig

        model = QAT(QuantConfig()).quantize(nn.Sequential(nn.Linear(4, 2)))
        # observe once eagerly, then trace: tracer-guard must not crash
        x = np.random.randn(2, 4).astype(np.float32)
        model(paddle.to_tensor(x))
        from paddle_tpu.nn.functional_call import functional_call

        params = {k: p.value for k, p in model.named_parameters()}
        out = jax.jit(
            lambda p, v: functional_call(model, p, paddle.Tensor(v)))(
                params, x)
        assert out.shape == (2, 2)

    def test_masked_matmul_keeps_mask_pattern(self):
        # product is exactly 0 at a masked position: entry must survive
        x = np.array([[1.0, -1.0]], np.float32)
        y = np.array([[1.0], [1.0]], np.float32)  # x @ y == 0
        mask = paddle.sparse.sparse_coo_tensor([[0], [0]], [1.0],
                                               shape=[1, 1])
        out = paddle.sparse.masked_matmul(jnp.asarray(x), jnp.asarray(y),
                                          mask)
        assert out.nnz == 1  # pattern preserved despite 0 value
        assert float(out.values().numpy()[0]) == 0.0
