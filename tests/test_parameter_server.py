"""Parameter-server training (closes the brpc-PS descope with a real
host-side PS: sharded sparse tables, server-side optimizers, trainer
pull/push, the fleet role flow, DistributedEmbedding autograd).

Reference: paddle/fluid/distributed/ps/ (PsService, sparse tables with
accessor-side optimize) + fleet.init_server/run_server/init_worker.
"""
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.distributed.ps import (DistributedEmbedding, PsClient,
                                       PsServer, TableConfig)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _servers(n):
    srvs = [PsServer(i, n).start() for i in range(n)]
    eps = [f"127.0.0.1:{s.port}" for s in srvs]
    return srvs, eps


class TestShardedTables:
    def test_pull_initializes_deterministically(self):
        srvs, eps = _servers(2)
        try:
            c = PsClient(eps)
            c.create_table(TableConfig("emb", dim=4, seed=3))
            ids = np.array([0, 1, 2, 3, 7, 10], np.int64)
            a = c.pull_sparse("emb", ids)
            b = c.pull_sparse("emb", ids)
            assert a.shape == (6, 4)
            np.testing.assert_array_equal(a, b)   # stable across pulls
            # rows land on their owning shard only (id % n_servers)
            stats = c.stats()
            assert stats[0]["emb"] == 3 and stats[1]["emb"] == 3
        finally:
            for s in srvs:
                s.stop()

    def test_push_sgd_moves_rows(self):
        srvs, eps = _servers(2)
        try:
            c = PsClient(eps)
            c.create_table(TableConfig("t", dim=3, optimizer="sgd", lr=0.5))
            ids = np.array([4, 5], np.int64)
            before = c.pull_sparse("t", ids)
            g = np.ones((2, 3), np.float32)
            c.push_sparse("t", ids, g)
            after = c.pull_sparse("t", ids)
            np.testing.assert_allclose(after, before - 0.5, rtol=1e-6)
            # untouched row unchanged
            other = c.pull_sparse("t", np.array([6], np.int64))
            c.push_sparse("t", ids, g)
            np.testing.assert_array_equal(
                c.pull_sparse("t", np.array([6], np.int64)), other)
        finally:
            for s in srvs:
                s.stop()

    @pytest.mark.parametrize("opt", ["adagrad", "adam"])
    def test_server_side_optimizers(self, opt):
        srvs, eps = _servers(1)
        try:
            c = PsClient(eps)
            c.create_table(TableConfig("t", dim=2, optimizer=opt, lr=0.1))
            ids = np.array([1], np.int64)
            w = c.pull_sparse("t", ids)
            for _ in range(5):
                c.push_sparse("t", ids, np.ones((1, 2), np.float32))
            w2 = c.pull_sparse("t", ids)
            assert (w2 < w).all()          # descended against +1 grads
        finally:
            for s in srvs:
                s.stop()

    def test_dense_params(self):
        srvs, eps = _servers(2)
        try:
            c = PsClient(eps)
            c.init_dense("bias", np.zeros((3,), np.float32))
            c.push_dense("bias", np.array([1.0, 2.0, 3.0], np.float32),
                         lr=0.1)
            np.testing.assert_allclose(c.pull_dense("bias"),
                                       [-0.1, -0.2, -0.3], rtol=1e-6)
        finally:
            for s in srvs:
                s.stop()

    def test_save_writes_all_shards(self, tmp_path):
        srvs, eps = _servers(2)
        try:
            c = PsClient(eps)
            c.create_table(TableConfig("emb", dim=4))
            c.pull_sparse("emb", np.arange(10, dtype=np.int64))
            c.save(str(tmp_path))
            files = sorted(os.listdir(tmp_path))
            assert files == ["emb.shard0.npz", "emb.shard1.npz"]
            total = sum(len(np.load(tmp_path / f)["ids"]) for f in files)
            assert total == 10
        finally:
            for s in srvs:
                s.stop()


class TestDistributedEmbedding:
    def test_training_converges_eager_backward(self):
        """Embedding regression end-to-end in the paddle eager API: the
        forward pulls rows, loss.backward() fires the gradient hook which
        pushes sparse grads, server-side SGD updates the table."""
        import paddle_tpu as paddle

        srvs, eps = _servers(2)
        try:
            c = PsClient(eps)
            emb = DistributedEmbedding(c, "emb", dim=4, optimizer="sgd",
                                       lr=0.2, init_range=0.01)
            rng = np.random.RandomState(0)
            target = rng.randn(8, 4).astype(np.float32)
            ids_all = np.arange(8, dtype=np.int64)
            first = float(np.mean(
                (c.pull_sparse("emb", ids_all) - target) ** 2))
            for step in range(50):
                ids = rng.choice(8, size=4, replace=False).astype(np.int64)
                rows = emb(paddle.to_tensor(ids))
                tgt = paddle.to_tensor(target[ids])
                loss = ((rows - tgt) ** 2).sum()
                loss.backward()
            final = float(np.mean(
                (c.pull_sparse("emb", ids_all) - target) ** 2))
            assert final < 0.05 * first, (first, final)
        finally:
            for s in srvs:
                s.stop()

    def test_functional_pull_push(self):
        """The jit-friendly explicit pair: grads from jax.grad w.r.t. the
        pulled rows, pushed back by the caller."""
        srvs, eps = _servers(1)
        try:
            c = PsClient(eps)
            emb = DistributedEmbedding(c, "e2", dim=3, optimizer="sgd",
                                       lr=0.5)
            ids = np.array([1, 2], np.int64)
            rows = emb.pull(ids)
            g = jax.grad(lambda r: jnp.sum(r ** 2))(jnp.asarray(rows))
            emb.push(ids, np.asarray(g))
            after = emb.pull(ids)
            np.testing.assert_allclose(after, rows - 0.5 * 2 * rows,
                                       rtol=1e-5)
        finally:
            for s in srvs:
                s.stop()


PS_NODE = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    import numpy as np
    import paddle_tpu.distributed.fleet as fleet
    from paddle_tpu.distributed.ps import TableConfig

    rm = fleet.PaddleCloudRoleMaker(is_collective=False)
    fleet.init(rm)
    if fleet.is_server():
        fleet.init_server()
        fleet.run_server()          # blocks until trainers stop it
        sys.exit(0)
    # trainer
    client = fleet.init_worker()
    client.create_table(TableConfig("emb", dim=2, optimizer="sgd", lr=0.1))
    tid = int(os.environ["PADDLE_TRAINER_ID"])
    ids = np.array([tid, 10 + tid], np.int64)
    rows = client.pull_sparse("emb", ids)
    client.push_sparse("emb", ids, np.ones_like(rows))
    after = client.pull_sparse("emb", ids)
    assert np.allclose(after, rows - 0.1), (rows, after)
    print("trainer", tid, "ok", flush=True)
    fleet.stop_worker()
""")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.mark.slow
class TestFleetRoleFlow:
    @pytest.mark.parametrize("plane", ["python", "native"])
    def test_two_servers_two_trainers_processes(self, tmp_path, plane):
        """The reference deployment shape: PSERVER and TRAINER processes
        wired purely by the env contract; last trainer stops servers.
        Parametrized over BOTH data planes (PADDLE_PS_DATA_PLANE) — the
        native C++ plane must carry the identical fleet flow."""
        import paddle_tpu.distributed.ps as distributed_ps  # noqa: F401

        if plane == "native":
            from paddle_tpu import native as native_lib

            if native_lib.lib_path() is None:
                pytest.skip("native toolchain unavailable")
        ports = [_free_port(), _free_port()]
        eps = ",".join(f"127.0.0.1:{p}" for p in ports)
        script = tmp_path / "node.py"
        script.write_text(PS_NODE.format(repo=REPO))
        procs = []

        def env_for(role, idx):
            env = dict(os.environ)
            env.update({
                "TRAINING_ROLE": role,
                "PADDLE_PSERVERS_IP_PORT_LIST": eps,
                "PADDLE_TRAINERS_NUM": "2",
                "JAX_PLATFORMS": "cpu",
                "PADDLE_PS_DATA_PLANE": plane,
            })
            if role == "PSERVER":
                env["POD_IP"] = "127.0.0.1"
                env["PADDLE_PORT"] = str(ports[idx])
            else:
                env["PADDLE_TRAINER_ID"] = str(idx)
            return env

        for i in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, str(script)], env=env_for("PSERVER", i),
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
        import time

        time.sleep(1.0)                      # let servers bind
        for i in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, str(script)], env=env_for("TRAINER", i),
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
        outs = []
        for i, p in enumerate(procs):
            try:
                out, err = p.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail(f"PS node {i} timed out")
            assert p.returncode == 0, f"node {i}:\n{err[-2000:]}"
            outs.append(out)
        assert "trainer 0 ok" in outs[2] + outs[3]
        assert "trainer 1 ok" in outs[2] + outs[3]


class TestAutoPlaneFallback:
    """PADDLE_PS_DATA_PLANE=auto when the native build is unavailable:
    python-plane fallback ONLY for a local single-node group; every
    other shape keeps the loud mixed-plane error."""

    def _role_maker(self, eps, trainers=1, monkeypatch=None):
        import paddle_tpu.distributed.fleet as fleet

        monkeypatch.setenv("TRAINING_ROLE", "TRAINER")
        monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST", eps)
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", str(trainers))
        monkeypatch.delenv("POD_IP", raising=False)
        return fleet.PaddleCloudRoleMaker(is_collective=False)

    @pytest.fixture()
    def fleet_auto_unavailable(self, monkeypatch):
        """fleet with the native-build probe forced to 'unavailable' and
        the plane env unset (auto)."""
        import paddle_tpu.distributed.fleet as fleet

        monkeypatch.delenv("PADDLE_PS_DATA_PLANE", raising=False)
        monkeypatch.setattr(fleet._ps_plane, "_auto", "unavailable")
        saved = fleet._fleet_state.get("role_maker")
        yield fleet
        fleet._fleet_state["role_maker"] = saved
        fleet._ps_plane._auto = None

    def test_local_single_node_falls_back_with_warning(
            self, fleet_auto_unavailable, monkeypatch):
        fleet = fleet_auto_unavailable
        fleet._fleet_state["role_maker"] = self._role_maker(
            "127.0.0.1:7001", monkeypatch=monkeypatch)
        with pytest.warns(RuntimeWarning, match="python plane"):
            srv_cls, _ = fleet._ps_plane()
        assert "Native" not in srv_cls.__name__

    def test_remote_single_server_still_raises(
            self, fleet_auto_unavailable, monkeypatch):
        fleet = fleet_auto_unavailable
        fleet._fleet_state["role_maker"] = self._role_maker(
            "some-remote-host.example:7001", monkeypatch=monkeypatch)
        with pytest.raises(RuntimeError, match="native data plane"):
            fleet._ps_plane()

    def test_multi_trainer_still_raises(self, fleet_auto_unavailable,
                                        monkeypatch):
        fleet = fleet_auto_unavailable
        fleet._fleet_state["role_maker"] = self._role_maker(
            "127.0.0.1:7001", trainers=4, monkeypatch=monkeypatch)
        with pytest.raises(RuntimeError, match="native data plane"):
            fleet._ps_plane()

    def test_multi_server_still_raises(self, fleet_auto_unavailable,
                                       monkeypatch):
        fleet = fleet_auto_unavailable
        fleet._fleet_state["role_maker"] = self._role_maker(
            "127.0.0.1:7001,127.0.0.1:7002", monkeypatch=monkeypatch)
        with pytest.raises(RuntimeError, match="native data plane"):
            fleet._ps_plane()

    def test_malformed_empty_host_still_raises(
            self, fleet_auto_unavailable, monkeypatch):
        fleet = fleet_auto_unavailable
        fleet._fleet_state["role_maker"] = self._role_maker(
            ":7001", monkeypatch=monkeypatch)
        with pytest.raises(RuntimeError, match="native data plane"):
            fleet._ps_plane()

    def test_hostname_counts_as_local(self, fleet_auto_unavailable,
                                      monkeypatch):
        fleet = fleet_auto_unavailable
        fleet._fleet_state["role_maker"] = self._role_maker(
            f"{socket.gethostname()}:7001", monkeypatch=monkeypatch)
        with pytest.warns(RuntimeWarning, match="python plane"):
            srv_cls, _ = fleet._ps_plane()
        assert "Native" not in srv_cls.__name__


class TestSaveRestore:
    def test_init_server_dirname_restores_tables(self, tmp_path,
                                                 monkeypatch):
        """fleet.init_server(dirname) loads a prior save (reference
        load-model-on-init contract), per shard. Pinned to the python
        plane: the save here is .npz (save formats are per-plane, and
        the auto default may pick native)."""
        import paddle_tpu.distributed.fleet as fleet
        from paddle_tpu.distributed.fleet import Role, UserDefinedRoleMaker

        monkeypatch.setenv("PADDLE_PS_DATA_PLANE", "python")

        srvs, eps = _servers(2)
        c = PsClient(eps)
        c.create_table(TableConfig("emb", dim=3, seed=9))
        ids = np.arange(6, dtype=np.int64)
        want = c.pull_sparse("emb", ids)
        c.push_sparse("emb", ids, np.full((6, 3), 0.5, np.float32))
        want = c.pull_sparse("emb", ids)          # post-update rows
        c.save(str(tmp_path))
        c.stop_servers()

        # fresh servers restored from the save must serve the SAME rows
        # (explicit role maker: no env needed)
        restored = []
        new_eps = []
        for i in range(2):
            rm = UserDefinedRoleMaker(
                is_collective=False, current_id=i, worker_num=1,
                role=Role.SERVER,
                server_endpoints=["127.0.0.1:0", "127.0.0.1:0"])
            fleet.init(rm)
            # port 0 endpoints: bind ephemeral, collect real ports
            srv = fleet.init_server(str(tmp_path))
            srv.start()
            restored.append(srv)
            new_eps.append(f"127.0.0.1:{srv.port}")
        try:
            c2 = PsClient(new_eps)
            got = c2.pull_sparse("emb", ids)
            np.testing.assert_allclose(got, want, rtol=1e-6)
        finally:
            for s in restored:
                s.stop()
            fleet.init()                      # leave PS mode for the suite


class TestEntryPolicies:
    """Entry-admission policies (reference distributed/entry_attr.py)
    applied by the shard at push time."""

    def test_count_filter_entry_delays_admission(self):
        from paddle_tpu.distributed import CountFilterEntry

        srvs, eps = _servers(1)
        try:
            c = PsClient(eps)
            c.create_table(TableConfig("cf", dim=2,
                                       entry=CountFilterEntry(3), lr=1.0))
            ids = np.array([5], np.int64)
            g = np.ones((1, 2), np.float32)
            # pushes 1 and 2: below threshold -> row not stored
            c.push_sparse("cf", ids, g)
            c.push_sparse("cf", ids, g)
            np.testing.assert_array_equal(c.pull_sparse("cf", ids), 0.0)
            assert c.stats()[0]["cf"] == 0
            # push 3 admits AND applies
            c.push_sparse("cf", ids, g)
            assert c.stats()[0]["cf"] == 1
            assert not np.allclose(c.pull_sparse("cf", ids), 0.0)
        finally:
            for s in srvs:
                s.stop()

    def test_probability_entry_filters_some_rows(self):
        from paddle_tpu.distributed import ProbabilityEntry

        srvs, eps = _servers(1)
        try:
            c = PsClient(eps)
            c.create_table(TableConfig("pe", dim=2,
                                       entry=ProbabilityEntry(0.5)))
            ids = np.arange(200, dtype=np.int64)
            c.push_sparse("pe", ids, np.ones((200, 2), np.float32))
            n = c.stats()[0]["pe"]
            assert 60 < n < 140, n          # ~half admitted
            # decision is sticky: repeat pushes change nothing
            c.push_sparse("pe", ids, np.ones((200, 2), np.float32))
            assert c.stats()[0]["pe"] == n
        finally:
            for s in srvs:
                s.stop()

    def test_show_click_entry_stats(self):
        from paddle_tpu.distributed import ShowClickEntry

        e = ShowClickEntry("show", "click")
        assert e._to_attr() == "show_click_entry:show:click"
        srvs, eps = _servers(2)
        try:
            c = PsClient(eps)
            c.create_table(TableConfig("ctr", dim=2, entry=e))
            ids = np.array([1, 2, 3], np.int64)
            c.push_show_click("ctr", ids, [1, 1, 1], [0, 1, 0])
            c.push_show_click("ctr", ids, [1, 0, 1], [1, 0, 0])
            got = c.pull_show_click("ctr", ids)
            np.testing.assert_allclose(got, [[2, 1], [1, 1], [2, 0]])
        finally:
            for s in srvs:
                s.stop()

    def test_entry_validation(self):
        from paddle_tpu.distributed import (CountFilterEntry,
                                            ProbabilityEntry)

        with pytest.raises(ValueError):
            ProbabilityEntry(0.0)
        with pytest.raises(ValueError):
            CountFilterEntry(0)
        assert ProbabilityEntry(0.25)._to_attr() == "probability_entry:0.25"
        assert CountFilterEntry(7)._to_attr() == "count_filter_entry:7"


class TestPSDatasets:
    """InMemoryDataset / QueueDataset over the MultiSlot text format
    (reference fleet/dataset/dataset.py), fed by MultiSlotDataGenerator."""

    def _write_files(self, tmp_path, n_files=2, lines_per=5):
        paths = []
        for fi in range(n_files):
            p = tmp_path / f"part-{fi}.txt"
            rows = []
            for li in range(lines_per):
                uid = fi * lines_per + li
                rows.append(f"uid:1 {uid} feat:3 {uid} {uid+1} {uid+2} "
                            f"label:1 {uid % 2}")
            p.write_text("\n".join(rows) + "\n")
            paths.append(str(p))
        return paths

    def test_in_memory_load_shuffle_iterate(self, tmp_path):
        from paddle_tpu.distributed import InMemoryDataset

        ds = InMemoryDataset()
        ds.init(batch_size=4)
        ds.set_filelist(self._write_files(tmp_path))
        ds.load_into_memory()
        assert ds.get_memory_data_size() == 10
        before = [int(s["uid"][0]) for s in ds._memory]
        ds.local_shuffle()
        after = [int(s["uid"][0]) for s in ds._memory]
        assert sorted(before) == sorted(after) and before != after
        batches = list(ds)
        assert [len(b) for b in batches] == [4, 4, 2]
        sample = batches[0][0]
        assert set(sample) == {"uid", "feat", "label"}
        assert sample["feat"].shape == (3,)
        ds.release_memory()
        assert ds.get_memory_data_size() == 0

    def test_queue_dataset_streams_and_refuses_shuffle(self, tmp_path):
        from paddle_tpu.distributed import QueueDataset

        ds = QueueDataset()
        ds.init(batch_size=3)
        ds.set_filelist(self._write_files(tmp_path, n_files=1,
                                          lines_per=7))
        assert sum(len(b) for b in ds) == 7
        with pytest.raises(NotImplementedError):
            ds.local_shuffle()

    def test_generator_to_dataset_pipeline(self, tmp_path):
        """MultiSlotDataGenerator output parses back through the dataset
        (the reference pipe_command contract, run in-process)."""
        import paddle_tpu.distributed.fleet as fleet

        class Gen(fleet.MultiSlotDataGenerator):
            def generate_sample(self, line):
                def g():
                    uid, label = line.strip().split(",")
                    yield [("uid", [int(uid)]), ("label", [int(label)])]
                return g

        gen = Gen()
        raw = ["7,1", "8,0"]
        out_lines = []
        for ln in raw:
            for sample in gen.generate_sample(ln)():
                out_lines.append(gen._format(sample))
        p = tmp_path / "gen.txt"
        p.write_text("\n".join(out_lines) + "\n")

        from paddle_tpu.distributed import InMemoryDataset

        ds = InMemoryDataset()
        ds.init(batch_size=2)
        ds.set_filelist([str(p)])
        ds.load_into_memory()
        (batch,) = list(ds)
        assert [int(s["uid"][0]) for s in batch] == [7, 8]
        assert [int(s["label"][0]) for s in batch] == [1, 0]
