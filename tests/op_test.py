"""OpTest harness — the reference's op-unit-test pattern, TPU-native.

Reference: test/legacy_test/eager_op_test.py:378 — define op + numpy inputs;
``check_output`` (:2193) compares against a numpy reference; ``check_grad``
(:2377) numeric finite-difference checking vs the registered grad. Here the
"registered grad" is the eager tape (core/autograd) over jax VJPs, so
check_grad exercises apply_op + backward end to end for every op it covers.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


def _to_np(x):
    if isinstance(x, Tensor):
        return np.asarray(x.value)
    if isinstance(x, (tuple, list)):
        return [_to_np(v) for v in x]
    return np.asarray(x)


def check_output(op, ref, inputs, kwargs=None, rtol=1e-5, atol=1e-6,
                 name=""):
    """Run `op(*inputs, **kwargs)` through the eager API and compare with the
    numpy reference `ref(*inputs, **kwargs)` (or an explicit expected array
    if `ref` is not callable)."""
    kwargs = kwargs or {}
    tensors = [paddle.to_tensor(x) if isinstance(x, np.ndarray) else x
               for x in inputs]
    got = op(*tensors, **kwargs)
    want = ref(*inputs, **kwargs) if callable(ref) else ref
    got_np = _to_np(got)
    want_np = _to_np(want)
    if isinstance(got_np, list) or isinstance(want_np, list):
        assert isinstance(got_np, list) and isinstance(want_np, list)
        for g, w in zip(got_np, want_np):
            np.testing.assert_allclose(g, w, rtol=rtol, atol=atol,
                                       err_msg=name)
    else:
        np.testing.assert_allclose(got_np, want_np, rtol=rtol, atol=atol,
                                   err_msg=name)
    return got


def check_grad(op, inputs, kwargs=None, wrt=None, eps=1e-3, rtol=1e-2,
               atol=1e-3, name=""):
    """Finite-difference gradient check of the eager backward().

    A random projection w makes the scalar loss sum(op(x) * w); the analytic
    grad from `.backward()` must match central differences at a handful of
    probe coordinates per input.
    """
    kwargs = kwargs or {}
    wrt = wrt if wrt is not None else [i for i, x in enumerate(inputs)
                                       if isinstance(x, np.ndarray)
                                       and np.issubdtype(x.dtype,
                                                         np.floating)]
    rng = np.random.RandomState(0)

    def make_tensors(arrs):
        ts = []
        for i, x in enumerate(arrs):
            if isinstance(x, np.ndarray):
                t = paddle.to_tensor(x)
                if i in wrt:
                    t.stop_gradient = False
                ts.append(t)
            else:
                ts.append(x)
        return ts

    def fwd_np(arrs):
        out = op(*make_tensors(arrs), **kwargs)
        if isinstance(out, (tuple, list)):
            out = out[0]
        return out

    out0 = fwd_np(inputs)
    # standard_normal handles 0-d outputs too (rng.randn(*()) returns a
    # bare float) — scalar-returning reductions are grad-checkable
    w = rng.standard_normal(np.asarray(out0.value).shape) \
        .astype(np.float32)
    w_t = paddle.to_tensor(w)

    # analytic
    tensors = make_tensors(inputs)
    out = op(*tensors, **kwargs)
    if isinstance(out, (tuple, list)):
        out = out[0]
    (out.astype("float32") * w_t).sum().backward()

    for i in wrt:
        g = np.asarray(tensors[i].grad.value, np.float64)
        x = inputs[i]
        flat_idx = rng.choice(x.size, size=min(4, x.size), replace=False)
        for fi in flat_idx:
            idx = np.unravel_index(fi, x.shape)
            xp = x.copy(); xp[idx] += eps
            xm = x.copy(); xm[idx] -= eps
            ip = list(inputs); ip[i] = xp
            im = list(inputs); im[i] = xm
            lp = float((np.asarray(fwd_np(ip).value, np.float64) * w).sum())
            lm = float((np.asarray(fwd_np(im).value, np.float64) * w).sum())
            fd = (lp - lm) / (2 * eps)
            np.testing.assert_allclose(
                g[idx], fd, rtol=rtol, atol=atol,
                err_msg=f"{name} input{i} at {idx}")


def check(op, ref, inputs, kwargs=None, grad=True, rtol=1e-5, atol=1e-6,
          grad_rtol=1e-2, grad_atol=1e-3, name=""):
    """check_output + (optionally) check_grad in one call."""
    check_output(op, ref, inputs, kwargs, rtol, atol, name)
    if grad:
        check_grad(op, inputs, kwargs, rtol=grad_rtol, atol=grad_atol,
                   name=name)
