"""Auto-tuner tests (reference analog: test/auto_tuner/).

Covers candidate generation, prune rules (incl. history-based OOM prune),
grid-search ordering, recorder CSV round-trip, the full tune() loop with a
stubbed runner, and one real subprocess trial on the virtual mesh.
"""
import json
import os
import sys

import pytest

from paddle_tpu.distributed.auto_tuner import (AutoTuner, HistoryRecorder,
                                               default_candidates, run_trial,
                                               search_all, tune)
from paddle_tpu.distributed.auto_tuner.prune import (prune_by_degree_product,
                                                     prune_by_mbs,
                                                     prune_by_memory_history,
                                                     prune_by_mp, prune_by_pp)

MODEL_CFG = {"preset": "tiny", "hidden_size": 16, "vocab_size": 32,
             "num_layers": 4, "num_attention_heads": 4,
             "global_batch_size": 8, "seq_len": 16}


def _cfg(**over):
    base = {"num_devices": 8, "model_cfg": MODEL_CFG}
    base.update(over)
    return base


class TestCandidates:
    def test_auto_degrees_are_divisors(self):
        c = default_candidates(_cfg())
        assert c["dp_degree"] == [1, 2, 4, 8]
        assert c["mp_degree"] == [1, 2, 4, 8]
        assert c["micro_batch_size"] == [1, 2, 4, 8]
        assert c["sharding_stage"] == [1, 2, 3]
        assert c["use_recompute"] == ["none", "full"]

    def test_explicit_candidates_pass_through(self):
        c = default_candidates(_cfg(mp_degree=[1, 2], micro_batch_size=4,
                                    use_recompute=False))
        assert c["mp_degree"] == [1, 2]
        assert c["micro_batch_size"] == [4]
        assert c["use_recompute"] == ["none"]

    def test_search_all_ordering_prefers_cheap_configs(self):
        tc = _cfg()
        tc["candidates"] = default_candidates(tc)
        tasks = search_all(tc)
        first = tasks[0]
        assert first["mp_degree"] == 1 and first["pp_degree"] == 1
        assert first["use_recompute"] == "none"
        # larger micro batch comes before smaller at equal parallelism
        assert first["micro_batch_size"] == 8


class TestPrune:
    def test_degree_product(self):
        tc = _cfg()
        bad = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 1,
               "sharding_degree": 1}
        good = {"dp_degree": 4, "mp_degree": 2, "pp_degree": 1,
                "sharding_degree": 1}
        assert prune_by_degree_product(tc, bad)
        assert not prune_by_degree_product(tc, good)

    def test_mp_divisibility(self):
        tc = _cfg()
        assert prune_by_mp(tc, {"mp_degree": 3})       # 16 % 3 != 0
        assert not prune_by_mp(tc, {"mp_degree": 4})

    def test_pp_layers(self):
        tc = _cfg()
        assert prune_by_pp(tc, {"pp_degree": 3})       # 4 % 3 != 0
        assert not prune_by_pp(tc, {"pp_degree": 2, "micro_batch_size": 1,
                                    "dp_degree": 1, "sharding_degree": 1})

    def test_pp_needs_enough_microbatches(self):
        tc = _cfg()
        # gbs=8, mbs=4, dp=2 → acc=1 < pp=2 → prune
        assert prune_by_pp(tc, {"pp_degree": 2, "micro_batch_size": 4,
                                "dp_degree": 2, "sharding_degree": 1})

    def test_mbs_divides_local_batch(self):
        tc = _cfg()
        assert prune_by_mbs(tc, {"micro_batch_size": 3, "dp_degree": 1,
                                 "sharding_degree": 1})
        assert not prune_by_mbs(tc, {"micro_batch_size": 2, "dp_degree": 2,
                                     "sharding_degree": 1})

    def test_oom_history_prunes_bigger_mbs(self):
        tc = _cfg()
        hist = [{"dp_degree": 8, "mp_degree": 1, "pp_degree": 1,
                 "sharding_degree": 1, "sharding_stage": 1,
                 "micro_batch_size": 1, "use_recompute": "full",
                 "error": "oom"}]
        cur = dict(hist[0], micro_batch_size=2)
        cur.pop("error")
        assert prune_by_memory_history(tc, cur, hist)
        other = dict(cur, mp_degree=2, dp_degree=4)
        assert not prune_by_memory_history(tc, other, hist)


class TestSearchLoop:
    def test_search_once_walks_valid_space(self):
        tuner = AutoTuner(_cfg(use_recompute=False, sharding_stage=1))
        seen = []
        while True:
            cfg = tuner.search_once()
            if cfg is None:
                break
            seen.append(cfg)
        assert seen, "search space should not be empty"
        n = 8
        for cfg in seen:
            assert (cfg["dp_degree"] * cfg["mp_degree"] * cfg["pp_degree"]
                    * cfg["sharding_degree"]) == n

    def test_task_limit(self):
        tuner = AutoTuner(_cfg(task_limit=3))
        got = [tuner.search_once() for _ in range(10)]
        assert sum(c is not None for c in got) <= 3


class TestRecorder:
    def test_best_and_csv_roundtrip(self, tmp_path):
        r = HistoryRecorder()
        r.add_cfg(job_id=1, mp_degree=1, tokens_per_sec=10.0)
        r.add_cfg(job_id=2, mp_degree=2, tokens_per_sec=30.0)
        r.add_cfg(job_id=3, mp_degree=4, tokens_per_sec=None, error="oom")
        best, err = r.get_best("tokens_per_sec", "Maximize")
        assert not err and best["job_id"] == 2
        p = str(tmp_path / "history.csv")
        r.store_history(p)
        r2 = HistoryRecorder()
        hist, err = r2.load_history(p)
        assert not err and len(hist) == 3
        assert hist[0]["job_id"] == 2  # sorted order persisted, numeric
        # loaded metrics must sort numerically, not lexicographically
        best2, err2 = r2.get_best("tokens_per_sec", "Maximize")
        assert not err2 and best2["tokens_per_sec"] == 30.0

    def test_get_best_empty(self):
        r = HistoryRecorder()
        best, err = r.get_best("tokens_per_sec", "Maximize")
        assert err and best is None


class TestTune:
    def test_tune_with_stub_runner_returns_best(self, tmp_path):
        calls = []

        def fake_run(cfg):
            calls.append(cfg)
            # favor mp=2: pretend it is fastest
            tps = 100.0 if cfg["mp_degree"] == 2 else 10.0
            return {"tokens_per_sec": tps}

        csv_path = str(tmp_path / "hist.csv")
        best = tune(_cfg(use_recompute=False, sharding_stage=1,
                         micro_batch_size=1, task_limit=50),
                    run_fn=fake_run, history_csv=csv_path)
        assert best is not None and best["mp_degree"] == 2
        assert os.path.exists(csv_path)
        assert len(calls) >= 2

    def test_oom_feedback_surfaces_best_fitting_config(self):
        seen = []

        def fake_run(cfg):
            seen.append(dict(cfg))
            if cfg["micro_batch_size"] >= 4:
                return {"error": "oom"}
            return {"tokens_per_sec": float(cfg["micro_batch_size"])}

        mc = dict(MODEL_CFG, global_batch_size=64)
        best = tune(_cfg(model_cfg=mc, use_recompute=False, sharding_stage=1,
                         dp_degree=8, mp_degree=1, pp_degree=1,
                         sharding_degree=1),
                    run_fn=fake_run)
        # most-memory-hungry config tried first; OOMs recorded, best is the
        # largest mbs that fits
        mbs_tried = [c["micro_batch_size"] for c in seen]
        assert mbs_tried == [8, 4, 2, 1]
        assert best["micro_batch_size"] == 2


@pytest.mark.slow
class TestRealTrial:
    def test_subprocess_trial_flat(self):
        cfg = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 1,
               "sharding_degree": 2, "sharding_stage": 2,
               "micro_batch_size": 2, "use_recompute": "none"}
        rec = run_trial(cfg, {"num_devices": 8, "model_cfg": MODEL_CFG,
                              "steps_per_trial": 1, "trial_timeout": 300})
        assert "error" not in rec, rec
        assert rec["tokens_per_sec"] > 0
