"""Request-lifecycle tracing + flight recorder suite (ISSUE 9).

Covers ``paddle_tpu.tracing`` end to end on CPU:

- the RECORDER: near-zero disabled path (no events, shared null span),
  bounded ring with tail-preserving reconfiguration, begin-time-ordered
  timelines keyed by rid (batch-wide ``rids`` events fan out to every
  carried request), Chrome-trace export through the profiler's shared
  writer, flight dumps with reason metadata, flag sync
  (``FLAGS_enable_trace``);
- SERVER integration: a request's timeline shows
  queue → admit (with the prefill bucket) → segments → finish in
  order; chunked admissions record one event per prefill chunk; THE
  acceptance scenario — a preempted-and-replayed request's timeline
  shows queue → admit → segments → preempt → replay → admit → finish,
  surviving the engine-rid change;
- the HTTP debug surface: ``GET /trace?rid=`` returns the timeline,
  bare ``/trace`` the newest events, and a disabled recorder is an
  honest 404;
- the serve_bench TTFT decomposition (queue/prefill/gap shares sum to
  the server-side TTFT) and the ``monitor_report --trace`` phase
  table / slowest-requests view.

The flight-recorder triggers (engine fault / stall / preemption storm)
are exercised where the faults are injected — the chaos suite
(``tests/test_serving_faults.py`` ``TestFlightRecorder``); the
monitor-registry retirement regression lives in ``tests/test_monitor.py``.
"""
import json
import os
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import tracing as trace
from paddle_tpu.inference.generation import (GenerationConfig,
                                             PagedContinuousBatchingEngine)
from paddle_tpu.serving import Server, serve_http

_MODEL = None


def tiny_model():
    """ONE tiny llama shared by the whole module (jit programs are
    keyed on shapes — same page_size/bucket shapes below keep the
    suite to a handful of compiles)."""
    global _MODEL
    if _MODEL is None:
        paddle.seed(0)
        from paddle_tpu.models import LlamaForCausalLM, llama_config
        cfg = llama_config("tiny", num_hidden_layers=1)
        _MODEL = (LlamaForCausalLM(cfg), cfg)
    return _MODEL


def paged_engine(model, max_batch=4, num_pages=64, page_size=4,
                 max_pages=8, **kw):
    kw.setdefault("debug_pages", True)
    return PagedContinuousBatchingEngine(
        model, max_batch=max_batch, num_pages=num_pages,
        page_size=page_size, max_pages=max_pages, **kw)


def _greedy(n):
    return GenerationConfig(max_new_tokens=n, eos_token_id=None)


def _prompts(cfg, n, plen=6, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, cfg.vocab_size, (plen,)).astype(np.int32)
            for _ in range(n)]


@pytest.fixture()
def tr(tmp_path):
    """Tracing armed for one test, ring cleared both ways, dumps into
    the test's tmp dir."""
    trace.clear()
    trace.enable(dump_dir=str(tmp_path))
    yield trace
    trace.disable()
    trace.clear()
    trace.configure(capacity=trace.DEFAULT_CAPACITY)


class TestRecorder:
    def test_disabled_is_noop(self):
        trace.disable()
        trace.clear()
        trace.event("x", rid=1)
        trace.record("y", rid=1, dur_ns=100)
        assert trace.events() == []
        # the disabled span is THE shared null object: no allocation
        assert trace.span("z", rid=1) is trace.NULL_SPAN
        with trace.span("z"):
            pass
        assert trace.events() == []
        # no black box was recording -> no dump to write
        assert trace.dump("whatever") is None

    def test_flag_sync(self):
        paddle.set_flags({"FLAGS_enable_trace": True})
        assert trace.enabled()
        paddle.set_flags({"FLAGS_enable_trace": False})
        assert not trace.enabled()
        trace.enable()
        assert trace.enabled()
        assert paddle.get_flags("FLAGS_enable_trace")[
            "FLAGS_enable_trace"]
        trace.disable()

    def test_ring_bound_and_reconfigure(self, tr):
        trace.configure(capacity=4)
        for i in range(10):
            trace.event("e", rid=i)
        evs = trace.events()
        assert [e["rid"] for e in evs] == [6, 7, 8, 9]
        # shrinking keeps the newest tail
        trace.configure(capacity=2)
        assert [e["rid"] for e in trace.events()] == [8, 9]
        with pytest.raises(ValueError):
            trace.configure(capacity=0)

    def test_reconfigure_smaller_twice_rebuilds(self, tr):
        """Regression (ISSUE 16 satellite): a second configure() with a
        SMALLER capacity must rebuild the ring — newest tail kept,
        subsequent recording bounded by the new capacity — and a
        same-capacity call must be an idempotent no-op (events
        untouched)."""
        trace.configure(capacity=8)
        for i in range(8):
            trace.event("e", rid=i)
        trace.configure(capacity=4)       # first shrink
        assert [e["rid"] for e in trace.events()] == [4, 5, 6, 7]
        trace.configure(capacity=2)       # second, smaller again
        assert [e["rid"] for e in trace.events()] == [6, 7]
        trace.event("e", rid=99)          # the NEW bound is live
        assert [e["rid"] for e in trace.events()] == [7, 99]
        trace.configure(capacity=2)       # same capacity: no-op
        assert [e["rid"] for e in trace.events()] == [7, 99]
        trace.configure(capacity=16)      # growing keeps everything
        assert [e["rid"] for e in trace.events()] == [7, 99]

    def test_timeline_order_and_rids_fanout(self, tr):
        trace.event("queue.enqueue", rid="s:1")
        with trace.span("admit", rid="s:1", plen=6, bucket=8):
            pass
        # batch-wide event carrying both requests
        trace.record("segment", dur_ns=1000, rids=("s:1", "s:2"),
                     steps=4)
        trace.event("finish", rid="s:2", status="finished")
        t1 = trace.timeline("s:1")
        assert [e["phase"] for e in t1] == ["queue.enqueue", "admit",
                                           "segment"]
        assert t1[1]["bucket"] == 8 and t1[1]["dur_ns"] >= 0
        t2 = trace.timeline("s:2")
        assert [e["phase"] for e in t2] == ["segment", "finish"]
        # timelines sort by BEGIN time even though spans land in the
        # ring at their end
        assert all(a["ts_ns"] <= b["ts_ns"]
                   for a, b in zip(t1, t1[1:]))

    def test_export_chrome_and_dump(self, tr, tmp_path):
        trace.event("queue.enqueue", rid="s:1", depth=2)
        trace.record("admit", rid="s:1", dur_ns=2_000_000, bucket=16)
        p = trace.export_chrome(str(tmp_path / "t.json"))
        doc = json.load(open(p))
        assert doc["displayTimeUnit"] == "ms"
        evs = doc["traceEvents"]
        assert {e["name"] for e in evs} == {"queue.enqueue", "admit"}
        span = next(e for e in evs if e["name"] == "admit")
        assert span["ph"] == "X" and abs(span["dur"] - 2000) < 1
        assert span["args"]["rid"] == "s:1"
        inst = next(e for e in evs if e["name"] == "queue.enqueue")
        assert inst["ph"] == "i" and inst["args"]["depth"] == 2
        # the flight dump carries its reason and lands in dump_dir
        d = trace.dump("unit reason")
        assert os.path.dirname(d) == str(tmp_path)
        doc2 = json.load(open(d))
        assert doc2["otherData"]["reason"] == "unit reason"
        assert len(doc2["traceEvents"]) == 2


class TestServerTimeline:
    def test_lifecycle_order_and_bucket(self, tr):
        model, mcfg = tiny_model()
        eng = paged_engine(model)
        srv = Server(eng, segment_steps=4)
        hs = [srv.submit(p, _greedy(8)) for p in _prompts(mcfg, 2)]
        for h in hs:
            h.result(timeout=120)
        tl = hs[0].timeline()
        ph = [e["phase"] for e in tl]
        i = ph.index
        assert (i("queue.enqueue") < i("queue.dequeue") < i("admit")
                < i("segment") < i("finish"))
        admit = tl[i("admit")]
        assert admit["plen"] == 6 and admit["bucket"] == 16  # 6 -> 16
        assert not admit["replay"]
        assert tl[i("finish")]["status"] == "finished"
        # server-side lookup by PUBLIC request id matches the handle's
        assert srv.request_timeline(hs[0].id) == tl
        # the two requests' timelines are distinct but share segments
        tl2 = hs[1].timeline()
        assert tl2[0]["rid"] != tl[0]["rid"]
        # engine-level prefill events recorded the bucket choice too
        assert any(e["phase"] == "engine.prefill" and e["bucket"] == 16
                   for e in trace.events())
        srv.shutdown()

    def test_chunked_admission_traces_each_chunk(self, tr):
        model, mcfg = tiny_model()
        eng = paged_engine(model, num_pages=64, max_pages=16,
                           prefill_chunk=8)
        srv = Server(eng, segment_steps=4)
        p = _prompts(mcfg, 1, plen=20)[0]
        h = srv.submit(p, _greedy(6))
        h.result(timeout=120)
        ph = [e["phase"] for e in h.timeline()]
        assert "admit.begin" in ph
        # 20 tokens @ chunk 8 -> 3 chunks, each its own gap event
        assert ph.count("prefill_chunk") == 3
        assert "admit.done" in ph
        assert (ph.index("admit.begin")
                < ph.index("prefill_chunk")
                < ph.index("admit.done") < ph.index("finish"))
        srv.shutdown()

    def test_preempted_and_replayed_timeline(self, tr):
        """THE acceptance scenario: a preempted-and-replayed request's
        timeline shows queue → admit → segments → preempt → replay →
        (re-)admit → finish IN ORDER, keyed by the handle id — the
        engine rid changed at replay and the timeline must not care."""
        model, mcfg = tiny_model()
        prompts = _prompts(mcfg, 4)
        # 4 x (6 + 20) tokens = 28 worst-case pages; 14 forces pressure
        eng = paged_engine(model, num_pages=14,
                           admission_mode="optimistic",
                           kv_watermark=1.0)
        srv = Server(eng, segment_steps=4, max_preemptions=50)
        hs = [srv.submit(p, _greedy(20)) for p in prompts]
        for h in hs:
            h.result(timeout=180)
        assert eng.alloc.preemptions >= 1
        victims = [h for h in hs if h._preempts > 0]
        assert victims
        h = victims[0]
        ph = [e["phase"] for e in h.timeline()]
        i = ph.index
        assert (i("queue.enqueue") < i("admit") < i("preempt")
                < i("replay") < i("finish"))
        # a decode segment ran between the first admission and the
        # preemption, and the replay re-admitted (a SECOND admit, with
        # replay=True, after the replay marker)
        assert "segment" in ph[i("admit"):i("preempt")]
        admits = [j for j, p_ in enumerate(ph) if p_ == "admit"]
        assert len(admits) >= 2 and admits[-1] > i("replay")
        tl = h.timeline()
        assert tl[admits[-1]]["replay"] is True
        assert tl[i("finish")]["status"] == "finished"
        srv.shutdown()

    def test_http_trace_endpoint(self, tr):
        model, mcfg = tiny_model()
        eng = paged_engine(model)
        srv = Server(eng, segment_steps=4)
        httpd = serve_http(srv, port=0)
        port = httpd.server_address[1]
        try:
            h = srv.submit(_prompts(mcfg, 1)[0], _greedy(6))
            h.result(timeout=120)
            doc = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/trace?rid={h.id}",
                timeout=10))
            assert doc["request_id"] == h.id
            phases = [e["phase"] for e in doc["events"]]
            assert "admit" in phases and phases[-1] == "finish"
            # bare /trace: the newest buffered events
            doc2 = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/trace", timeout=10))
            assert doc2["n"] > 0
            # malformed rid is a 400, not a crash
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/trace?rid=abc",
                    timeout=10)
            assert ei.value.code == 400
            # disabled recorder is an honest 404 with the enable hint
            trace.disable()
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/trace?rid={h.id}",
                    timeout=10)
            assert ei.value.code == 404
            assert "FLAGS_enable_trace" in json.load(ei.value)["error"]
        finally:
            httpd.shutdown()
            srv.shutdown()


def _tools():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                    os.pardir, "tools"))
    try:
        import monitor_report
        import serve_bench
    finally:
        sys.path.pop(0)
    return serve_bench, monitor_report


class TestToolViews:
    def test_ttft_decomposition_sums_to_ttft(self, tr):
        """The serve_bench decomposition's three shares sum to the
        server-side TTFT per request (synthetic events with known
        spacing)."""
        serve_bench, _ = _tools()
        import time as _t

        t0 = _t.perf_counter_ns()
        with trace._lock:   # hand-build deterministic timestamps
            trace._ring.append((t0, 0, "s:1", "queue.enqueue", None))
            trace._ring.append((t0 + 10_000_000, 0, "s:1",
                                "queue.dequeue", None))
            trace._ring.append((t0 + 10_000_000, 30_000_000, "s:1",
                                "admit", None))
            trace._ring.append((t0 + 50_000_000, 0, "s:1",
                                "first_token", None))
        # a preempted request's REPLAY re-admission lands after the
        # first token (ring order is end-time order) and must NOT
        # inflate the prefill share
        with trace._lock:
            trace._ring.append((t0 + 90_000_000, 40_000_000, "s:1",
                                "admit", {"replay": True}))
        qs, ps, gs = serve_bench._ttft_decomposition()
        assert qs == [pytest.approx(0.010)]
        assert ps == [pytest.approx(0.030)]
        assert gs == [pytest.approx(0.010)]       # 50 - 10 - 30 ms

    def test_monitor_report_trace_view(self, tr, tmp_path):
        _, monitor_report = _tools()
        model, mcfg = tiny_model()
        eng = paged_engine(model)
        srv = Server(eng, segment_steps=4)
        hs = [srv.submit(p, _greedy(6)) for p in _prompts(mcfg, 2)]
        for h in hs:
            h.result(timeout=120)
        srv.shutdown()
        p = trace.export_chrome(str(tmp_path / "run.json"))
        out = monitor_report.render_trace(json.load(open(p)), top=2)
        assert "PHASE" in out and "admit" in out and "segment" in out
        assert "top 2 slowest requests" in out
        assert "dominant:" in out
        # the CLI route works end to end
        assert monitor_report.main(["--trace", p, "--top", "1"]) == 0
