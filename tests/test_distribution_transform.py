"""Transform family parity tests (VERDICT r2 #9).

Oracle: torch.distributions.transforms (same math as the reference's
distribution/transform.py family — both follow the TF-Probability
bijector contract). Checks forward/inverse round-trips, log-det-Jacobians
(also against autodiff), shape transforms, and TransformedDistribution
log_prob end-to-end.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu.distribution as D

torch = pytest.importorskip("torch")
td = torch.distributions.transforms


def _np(x):
    return np.asarray(x.value if hasattr(x, "value") else x)


PAIRS = [
    (lambda: D.ExpTransform(), lambda: td.ExpTransform(),
     np.random.RandomState(0).randn(5).astype(np.float32)),
    (lambda: D.AffineTransform(2.0, -3.0), lambda: td.AffineTransform(2.0, -3.0),
     np.random.RandomState(1).randn(5).astype(np.float32)),
    (lambda: D.SigmoidTransform(), lambda: td.SigmoidTransform(),
     np.random.RandomState(2).randn(5).astype(np.float32)),
    (lambda: D.TanhTransform(), lambda: td.TanhTransform(),
     np.random.RandomState(3).randn(5).astype(np.float32) * 0.8),
    (lambda: D.PowerTransform(2.0), lambda: td.PowerTransform(
        torch.tensor(2.0)),
     np.random.RandomState(4).rand(5).astype(np.float32) + 0.5),
    (lambda: D.StickBreakingTransform(), lambda: td.StickBreakingTransform(),
     np.random.RandomState(5).randn(4).astype(np.float32)),
]


class TestTorchParity:
    @pytest.mark.parametrize("mk_ours,mk_torch,x", PAIRS,
                             ids=["exp", "affine", "sigmoid", "tanh",
                                  "power", "stickbreaking"])
    def test_forward_inverse_ldj(self, mk_ours, mk_torch, x):
        ours, ref = mk_ours(), mk_torch()
        tx = torch.tensor(x)
        y_ours = _np(ours.forward(x))
        y_ref = ref(tx).numpy()
        np.testing.assert_allclose(y_ours, y_ref, rtol=1e-5, atol=1e-6)
        # inverse round-trip
        x_back = _np(ours.inverse(y_ours))
        np.testing.assert_allclose(x_back, x, rtol=1e-4, atol=1e-5)
        # log-det-jacobian
        ldj_ours = _np(ours.forward_log_det_jacobian(x))
        ldj_ref = ref.log_abs_det_jacobian(tx, ref(tx)).numpy()
        np.testing.assert_allclose(ldj_ours, ldj_ref, rtol=1e-4, atol=1e-5)

    def test_ldj_matches_autodiff(self):
        """Jacobian from jax.jacfwd must agree with the closed forms."""
        for t in (D.ExpTransform(), D.SigmoidTransform(), D.TanhTransform(),
                  D.AffineTransform(1.0, 2.5)):
            x = jnp.asarray([0.3])
            j = jax.jacfwd(lambda v: t._forward(v))(x)
            expect = jnp.log(jnp.abs(j[0, 0]))
            got = t._forward_log_det_jacobian(x)[0]
            np.testing.assert_allclose(float(got), float(expect), rtol=1e-5)

    def test_stickbreaking_simplex(self):
        t = D.StickBreakingTransform()
        x = np.random.RandomState(7).randn(3, 6).astype(np.float32)
        y = _np(t.forward(x))
        assert y.shape == (3, 7)
        np.testing.assert_allclose(y.sum(-1), 1.0, rtol=1e-5)
        assert (y > 0).all()
        np.testing.assert_allclose(_np(t.inverse(y)), x, rtol=1e-3,
                                   atol=1e-4)
        assert t.forward_shape((3, 6)) == (3, 7)
        assert t.inverse_shape((3, 7)) == (3, 6)


class TestCombinators:
    def test_chain(self):
        chain = D.ChainTransform([D.AffineTransform(0.0, 2.0),
                                  D.ExpTransform()])
        x = np.asarray([0.5], np.float32)
        y = _np(chain.forward(x))
        np.testing.assert_allclose(y, np.exp(2 * 0.5), rtol=1e-6)
        np.testing.assert_allclose(_np(chain.inverse(y)), x, rtol=1e-6)
        # ldj adds: log|2| + (2x)
        np.testing.assert_allclose(
            _np(chain.forward_log_det_jacobian(x)),
            np.log(2.0) + 1.0, rtol=1e-6)

    def test_independent_sums_event_dims(self):
        base = D.ExpTransform()
        t = D.IndependentTransform(base, 1)
        x = np.random.RandomState(0).randn(2, 3).astype(np.float32)
        ldj = _np(t.forward_log_det_jacobian(x))
        assert ldj.shape == (2,)
        np.testing.assert_allclose(ldj, x.sum(-1), rtol=1e-6)

    def test_reshape(self):
        t = D.ReshapeTransform((6,), (2, 3))
        x = np.arange(12, dtype=np.float32).reshape(2, 6)
        y = _np(t.forward(x))
        assert y.shape == (2, 2, 3)
        np.testing.assert_allclose(_np(t.inverse(y)), x)
        assert t.forward_shape((5, 6)) == (5, 2, 3)
        with pytest.raises(ValueError):
            D.ReshapeTransform((6,), (4,))

    def test_stack(self):
        t = D.StackTransform([D.ExpTransform(),
                              D.AffineTransform(0.0, 3.0)], axis=1)
        x = np.asarray([[0.0, 1.0], [1.0, 2.0]], np.float32)
        y = _np(t.forward(x))
        np.testing.assert_allclose(y[:, 0], np.exp(x[:, 0]), rtol=1e-6)
        np.testing.assert_allclose(y[:, 1], 3 * x[:, 1], rtol=1e-6)
        np.testing.assert_allclose(_np(t.inverse(y)), x, rtol=1e-6)

    def test_call_composition(self):
        # Transform(Transform) chains; Transform(Distribution) transforms
        chained = D.ExpTransform()(D.AffineTransform(0.0, 2.0))
        assert isinstance(chained, D.ChainTransform)
        dist = D.ExpTransform()(D.Normal(loc=0.0, scale=1.0))
        assert isinstance(dist, D.TransformedDistribution)


class TestTransformedDistributionParity:
    def test_lognormal_via_exp_normal(self):
        """TransformedDistribution(Normal, [Exp]) ≡ LogNormal (the
        canonical reference example)."""
        ours = D.TransformedDistribution(D.Normal(loc=0.3, scale=0.7),
                                         [D.ExpTransform()])
        ref = torch.distributions.TransformedDistribution(
            torch.distributions.Normal(0.3, 0.7), [td.ExpTransform()])
        v = np.asarray([0.5, 1.0, 2.5], np.float32)
        np.testing.assert_allclose(
            _np(ours.log_prob(v)), ref.log_prob(torch.tensor(v)).numpy(),
            rtol=1e-5, atol=1e-6)

    def test_affine_sigmoid_chain_logprob(self):
        ours = D.TransformedDistribution(
            D.Normal(loc=0.0, scale=1.0),
            [D.AffineTransform(0.5, 2.0), D.SigmoidTransform()])
        ref = torch.distributions.TransformedDistribution(
            torch.distributions.Normal(0.0, 1.0),
            [td.AffineTransform(0.5, 2.0), td.SigmoidTransform()])
        v = np.asarray([0.2, 0.5, 0.9], np.float32)
        np.testing.assert_allclose(
            _np(ours.log_prob(v)), ref.log_prob(torch.tensor(v)).numpy(),
            rtol=1e-4, atol=1e-5)

    def test_sample_range_respects_transform(self):
        d = D.TransformedDistribution(D.Normal(loc=0.0, scale=1.0),
                                      [D.SigmoidTransform()])
        s = _np(d.sample((500,)))
        assert ((s > 0) & (s < 1)).all()


class TestInverseLdjFallbacks:
    def test_chain_inverse_ldj(self):
        chain = D.ChainTransform([D.AffineTransform(0.0, 2.0),
                                  D.ExpTransform()])
        x = np.asarray([0.5], np.float32)
        y = _np(chain.forward(x))
        fwd = _np(chain.forward_log_det_jacobian(x))
        inv = _np(chain.inverse_log_det_jacobian(y))
        np.testing.assert_allclose(inv, -fwd, rtol=1e-6)

    def test_stack_inverse_ldj(self):
        t = D.StackTransform([D.ExpTransform(),
                              D.AffineTransform(0.0, 3.0)], axis=0)
        x = np.asarray([[0.5], [1.0]], np.float32)
        y = _np(t.forward(x))
        fwd = _np(t.forward_log_det_jacobian(x))
        inv = _np(t.inverse_log_det_jacobian(y))
        np.testing.assert_allclose(inv, -fwd, rtol=1e-6)
