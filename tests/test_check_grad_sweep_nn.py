"""check_grad sweep over ``paddle_tpu.nn.functional`` (VERDICT r4 #6,
second half of the breadth program — tests/test_check_grad_sweep.py
covers the tensor-op surface).

The torch-oracle program (test_functional_vs_torch.py) verifies VALUES;
this sweep verifies the eager tape's GRADIENTS by central finite
differences for every functional export: AUTO for generic-probe ops,
SPECIAL for ops needing shaped/indexed inputs, WHITELIST with a written
reason otherwise.  ``test_nn_surface_fully_classified`` makes new
exports fail until they are classified.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from op_test import check_grad

RNG = np.random.RandomState(11)
X = RNG.rand(3, 8).astype(np.float32) * 0.5 + 0.3
IMG1 = RNG.randn(2, 3, 16).astype(np.float32)            # N, C, L
IMG2 = RNG.randn(2, 3, 8, 8).astype(np.float32)          # N, C, H, W
IMG3 = RNG.randn(1, 2, 4, 4, 4).astype(np.float32)       # N, C, D, H, W
W1 = RNG.randn(4, 3, 3).astype(np.float32) * 0.2         # Cout, Cin, K
W2 = RNG.randn(4, 3, 3, 3).astype(np.float32) * 0.2
W3 = RNG.randn(3, 2, 2, 2, 2).astype(np.float32) * 0.2
LOGITS = RNG.randn(4, 5).astype(np.float32)
LABELS = RNG.randint(0, 5, (4,)).astype(np.int64)
PROBS = (RNG.rand(4, 5).astype(np.float32) * 0.8 + 0.1)
TARGETS = (RNG.rand(4, 5).astype(np.float32) * 0.8 + 0.1)
SIGNS = np.sign(RNG.randn(4, 5)).astype(np.float32)
BMASK = (RNG.rand(4, 5) > 0.5).astype(np.float32)
GRID = (RNG.rand(2, 4, 4, 2) * 1.6 - 0.8).astype(np.float32)
LOG_LBL = (RNG.rand(4, 1) > 0.5).astype(np.float32)

AUTO_UNARY = [
    "celu", "diag_embed", "elu", "gelu", "glu", "hardshrink",
    "hardsigmoid", "hardswish", "hardtanh", "label_smooth",
    "leaky_relu", "log_sigmoid", "log_softmax", "mish", "normalize",
    "pdist", "relu", "relu6", "selu", "sigmoid", "silu", "softmax",
    "softplus", "softshrink", "softsign", "swish", "tanh", "tanhshrink",
    "thresholded_relu",
]

_SPECIAL = {
    # convolutions (weights get FD-checked too — wrt covers all floats)
    "conv1d": (F.conv1d, [IMG1, W1], {}),
    "conv2d": (F.conv2d, [IMG2, W2], {}),
    "conv3d": (F.conv3d, [IMG3, W3], {}),
    "conv1d_transpose": (F.conv1d_transpose,
                         [IMG1, RNG.randn(3, 4, 3).astype(np.float32) * .2],
                         {}),
    "conv2d_transpose": (F.conv2d_transpose,
                         [IMG2,
                          RNG.randn(3, 4, 3, 3).astype(np.float32) * .2],
                         {}),
    "conv3d_transpose": (F.conv3d_transpose,
                         [IMG3,
                          RNG.randn(2, 3, 2, 2, 2).astype(np.float32) * .2],
                         {}),
    "linear": (F.linear, [X, RNG.randn(8, 4).astype(np.float32),
                          RNG.randn(4).astype(np.float32)], {}),
    "bilinear": (F.bilinear,
                 [RNG.randn(4, 3).astype(np.float32),
                  RNG.randn(4, 5).astype(np.float32),
                  RNG.randn(2, 3, 5).astype(np.float32)], {}),
    "prelu": (F.prelu, [IMG2, np.full((3,), 0.25, np.float32)], {}),
    "maxout": (lambda t: F.maxout(t, groups=2),
               [RNG.randn(2, 4, 5, 5).astype(np.float32)], {}),
    "embedding": (lambda w: F.embedding(
        paddle.to_tensor(np.array([[0, 2], [1, 3]], np.int64)), w),
        [RNG.randn(5, 6).astype(np.float32)], {}),
    # pooling
    "avg_pool1d": (lambda t: F.avg_pool1d(t, 2), [IMG1], {}),
    "avg_pool2d": (lambda t: F.avg_pool2d(t, 2), [IMG2], {}),
    "avg_pool3d": (lambda t: F.avg_pool3d(t, 2), [IMG3], {}),
    "max_pool1d": (lambda t: F.max_pool1d(t, 2), [IMG1], {}),
    "max_pool2d": (lambda t: F.max_pool2d(t, 2), [IMG2], {}),
    "max_pool3d": (lambda t: F.max_pool3d(t, 2), [IMG3], {}),
    "adaptive_avg_pool1d": (lambda t: F.adaptive_avg_pool1d(t, 4), [IMG1],
                            {}),
    "adaptive_avg_pool2d": (lambda t: F.adaptive_avg_pool2d(t, 4), [IMG2],
                            {}),
    "adaptive_avg_pool3d": (lambda t: F.adaptive_avg_pool3d(t, 2), [IMG3],
                            {}),
    "adaptive_max_pool1d": (lambda t: F.adaptive_max_pool1d(t, 4), [IMG1],
                            {}),
    "adaptive_max_pool2d": (lambda t: F.adaptive_max_pool2d(t, 4), [IMG2],
                            {}),
    "adaptive_max_pool3d": (lambda t: F.adaptive_max_pool3d(t, 2), [IMG3],
                            {}),
    # norms (running stats are float inputs: their grads FD-check too)
    "batch_norm": (lambda t: F.batch_norm(
        t, paddle.to_tensor(np.zeros(3, np.float32)),
        paddle.to_tensor(np.ones(3, np.float32)), training=False), [IMG2],
        {}),
    "layer_norm": (lambda t, w, b: F.layer_norm(t, [8], weight=w, bias=b),
                   [X, np.ones(8, np.float32) + 0.1,
                    np.zeros(8, np.float32)], {}),
    "group_norm": (lambda t: F.group_norm(t, num_groups=3), [IMG2], {}),
    # needs spatial dims: on 2D input the per-instance mean is the
    # identity and the output (and every grad) is exactly zero — a
    # vacuous check (r5 review finding)
    "instance_norm": (F.instance_norm, [IMG2], {}),
    "local_response_norm": (lambda t: F.local_response_norm(t, 3),
                            [IMG2], {}),
    # losses: logits/probs + closed-over integer labels
    # labels are closed over: the reference does not differentiate
    # losses w.r.t. their targets, and neither does the tape
    "binary_cross_entropy": (lambda t: F.binary_cross_entropy(
        t, paddle.to_tensor(TARGETS)), [PROBS], {}),
    "binary_cross_entropy_with_logits": (
        lambda t: F.binary_cross_entropy_with_logits(
            t, paddle.to_tensor(TARGETS)), [LOGITS], {}),
    "cross_entropy": (lambda t: F.cross_entropy(
        t, paddle.to_tensor(LABELS)), [LOGITS], {}),
    "nll_loss": (lambda t: F.nll_loss(
        F.log_softmax(t), paddle.to_tensor(LABELS)), [LOGITS], {}),
    "softmax_with_cross_entropy": (lambda t: F.softmax_with_cross_entropy(
        t, paddle.to_tensor(LABELS[:, None])), [LOGITS], {}),
    "kl_div": (lambda t: F.kl_div(F.log_softmax(t), paddle.to_tensor(
        PROBS / PROBS.sum(-1, keepdims=True))), [LOGITS], {}),
    "l1_loss": (F.l1_loss, [LOGITS, TARGETS], {}),
    "mse_loss": (F.mse_loss, [LOGITS, TARGETS], {}),
    "smooth_l1_loss": (F.smooth_l1_loss, [LOGITS, TARGETS], {}),
    "soft_margin_loss": (lambda t: F.soft_margin_loss(
        t, paddle.to_tensor(SIGNS)), [LOGITS], {}),
    "sigmoid_focal_loss": (lambda t: F.sigmoid_focal_loss(
        t, paddle.to_tensor(BMASK)), [LOGITS], {}),
    "hinge_embedding_loss": (lambda t: F.hinge_embedding_loss(
        t, paddle.to_tensor(SIGNS)), [LOGITS], {}),
    "margin_ranking_loss": (lambda a, b: F.margin_ranking_loss(
        a, b, paddle.to_tensor(SIGNS)),
        [LOGITS, LOGITS[::-1].copy()], {}),
    "cosine_embedding_loss": (lambda a, b: F.cosine_embedding_loss(
        a, b, paddle.to_tensor(np.array([1, -1, 1, 1], np.float32))),
        [LOGITS, LOGITS[::-1].copy()], {}),
    "triplet_margin_loss": (F.triplet_margin_loss,
                            [LOGITS, LOGITS[::-1].copy(),
                             (LOGITS * 0.5 + 0.1).copy()], {}),
    "triplet_margin_with_distance_loss": (
        F.triplet_margin_with_distance_loss,
        [LOGITS, LOGITS[::-1].copy(), (LOGITS * 0.5 + 0.1).copy()], {}),
    "multi_label_soft_margin_loss": (
        lambda t: F.multi_label_soft_margin_loss(
            t, paddle.to_tensor(BMASK)), [LOGITS], {}),
    "multi_margin_loss": (lambda t: F.multi_margin_loss(
        t, paddle.to_tensor(LABELS)), [LOGITS], {}),
    "poisson_nll_loss": (F.poisson_nll_loss, [LOGITS, PROBS], {}),
    "gaussian_nll_loss": (lambda t, v: F.gaussian_nll_loss(
        t, paddle.to_tensor(TARGETS), v), [LOGITS, PROBS], {}),
    "log_loss": (lambda t: F.log_loss(
        t, paddle.to_tensor(LOG_LBL)), [PROBS[:, :1].copy()], {}),
    "square_error_cost": (F.square_error_cost, [LOGITS, TARGETS], {}),
    "npair_loss": (lambda a, p: F.npair_loss(
        a, p, paddle.to_tensor(LABELS)), [LOGITS, LOGITS[::-1].copy()],
        {}),
    "dice_loss": (lambda t: F.dice_loss(
        F.softmax(t), paddle.to_tensor(LABELS[:, None])), [LOGITS], {}),
    "ctc_loss": (lambda t: F.ctc_loss(
        t, paddle.to_tensor(np.array([[1, 2]], np.int32)),
        paddle.to_tensor(np.array([4], np.int64)),
        paddle.to_tensor(np.array([2], np.int64))),
        [RNG.randn(4, 1, 3).astype(np.float32)], {}),
    # attention / similarity / layout
    "scaled_dot_product_attention": (
        F.scaled_dot_product_attention,
        [RNG.randn(1, 4, 2, 8).astype(np.float32),
         RNG.randn(1, 4, 2, 8).astype(np.float32),
         RNG.randn(1, 4, 2, 8).astype(np.float32)], {}),
    "cosine_similarity": (F.cosine_similarity,
                          [LOGITS, LOGITS[::-1].copy()], {}),
    "pairwise_distance": (F.pairwise_distance,
                          [LOGITS, LOGITS[::-1].copy()], {}),
    "pixel_shuffle": (lambda t: F.pixel_shuffle(t, 2),
                      [RNG.randn(1, 4, 3, 3).astype(np.float32)], {}),
    "pixel_unshuffle": (lambda t: F.pixel_unshuffle(t, 2),
                        [RNG.randn(1, 1, 4, 4).astype(np.float32)], {}),
    "channel_shuffle": (lambda t: F.channel_shuffle(t, 2),
                        [RNG.randn(1, 4, 3, 3).astype(np.float32)], {}),
    "temporal_shift": (lambda t: F.temporal_shift(t, seg_num=2,
                                                  shift_ratio=0.25),
                       [RNG.randn(4, 4, 3, 3).astype(np.float32)], {}),
    "fold": (lambda t: F.fold(t, output_sizes=[4, 4], kernel_sizes=[2, 2],
                              strides=2),
             [RNG.randn(1, 12, 4).astype(np.float32)], {}),
    "unfold": (lambda t: F.unfold(t, kernel_sizes=[2, 2], strides=2),
               [IMG2], {}),
    "pad": (lambda t: F.pad(t, [1, 1, 1, 1]), [IMG2], {}),
    "zeropad2d": (lambda t: F.zeropad2d(t, [1, 1, 1, 1]), [IMG2], {}),
    "grid_sample": (lambda t: F.grid_sample(
        t, paddle.to_tensor(GRID)), [IMG2], {}),
    "affine_grid": (lambda t: F.affine_grid(t, [2, 3, 4, 4]),
                    [RNG.randn(2, 2, 3).astype(np.float32)], {}),
    "interpolate": (lambda t: F.interpolate(t, scale_factor=2,
                                            mode="bilinear"), [IMG2], {}),
    "upsample": (lambda t: F.upsample(t, scale_factor=2, mode="nearest"),
                 [IMG2], {}),
    "hsigmoid_loss": (lambda t, w: F.hsigmoid_loss(
        t, paddle.to_tensor(LABELS), 5, w),
        [LOGITS, RNG.randn(4, 5).astype(np.float32)], {}),
    "margin_cross_entropy": (lambda t: F.margin_cross_entropy(
        t, paddle.to_tensor(LABELS), reduction="mean"), [LOGITS], {}),
    # deterministic when told so
    "dropout": (lambda t: F.dropout(t, p=0.5, training=False), [X], {}),
}
_SPECIAL_TOL = {
    # max-pool style selections + bilinear resampling: FD probes can
    # cross selection boundaries; keep checks meaningful but tolerant
    "grid_sample": (5e-2, 5e-3), "margin_cross_entropy": (5e-2, 5e-3),
    "ctc_loss": (5e-2, 5e-3), "instance_norm": (5e-2, 5e-3),
}

_W_RANDOM = "random sampling — finite differences see fresh draws"
_W_INT = "integer/bool output"
_W_INPLACE = "in-place alias of the taped op"
WHITELIST = {
    "alpha_dropout": _W_RANDOM, "dropout2d": _W_RANDOM,
    "dropout3d": _W_RANDOM, "gumbel_softmax": _W_RANDOM,
    "rrelu": _W_RANDOM, "class_center_sample": _W_RANDOM,
    "elu_": _W_INPLACE, "relu_": _W_INPLACE, "softmax_": _W_INPLACE,
    "tanh_": _W_INPLACE,
    "one_hot": _W_INT, "sequence_mask": _W_INT, "gather_tree": _W_INT,
    "flash_attention": "kernel grads covered by test_flash_attention "
                       "(incl. FD in TestDropout)",
    "flash_attn_unpadded": "covered by test_flash_attention varlen tests",
    "sparse_attention": "covered by test_flash_attention "
                        "TestSparseAttentionGather",
    "max_unpool1d": "consumes max_pool indices; value+grad covered in "
                    "test_functional_vs_torch",
    "max_unpool2d": "consumes max_pool indices; covered in "
                    "test_functional_vs_torch",
    "max_unpool3d": "consumes max_pool indices; covered in "
                    "test_functional_vs_torch",
    "rnnt_loss": "lattice DP loss; value parity covered in "
                 "test_nn_decode_losses",
}


def _public_fns():
    out = []
    for n in sorted(dir(F)):
        if n.startswith("_"):
            continue
        f = getattr(F, n)
        if callable(f) and not isinstance(f, type):
            out.append(n)
    return out


def test_nn_surface_fully_classified():
    known = set(AUTO_UNARY) | set(_SPECIAL) | set(WHITELIST)
    missing = [n for n in _public_fns() if n not in known]
    assert not missing, (
        f"new nn.functional exports without grad-check classification: "
        f"{missing} — add to AUTO_UNARY, _SPECIAL, or WHITELIST in "
        "tests/test_check_grad_sweep_nn.py")
    gone = [n for n in known if not hasattr(F, n)]
    assert not gone, f"classified fns no longer exported: {gone}"


@pytest.mark.parametrize("op_name", AUTO_UNARY)
def test_nn_auto_grad(op_name):
    rtol, atol = _SPECIAL_TOL.get(op_name, (1e-2, 1e-3))
    check_grad(getattr(F, op_name), [X.copy()], rtol=rtol, atol=atol,
               name=op_name)


@pytest.mark.parametrize("op_name", sorted(_SPECIAL))
def test_nn_special_grad(op_name):
    fn, inputs, kwargs = _SPECIAL[op_name]
    rtol, atol = _SPECIAL_TOL.get(op_name, (1e-2, 1e-3))
    check_grad(fn, [np.copy(a) if isinstance(a, np.ndarray) else a
                    for a in inputs], kwargs, rtol=rtol, atol=atol,
               name=op_name)
