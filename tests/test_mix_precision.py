"""Main-grad mixed precision tests (VERDICT r2 fleet-utils gap).

Reference contract (fleet/utils/mix_precision_utils.py): bf16 compute,
fp32 main_grad accumulation across micro-batches, optimizer steps on fp32
masters — micro-batch grad accumulation must NOT lose bf16 precision, and
params must stay bf16 after the step.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.fleet.utils.mix_precision_utils import (
    MixPrecisionLayer, MixPrecisionOptimizer, MixPrecisionScaler)
from paddle_tpu.optimizer import SGD


def _mk(seed=0):
    np.random.seed(seed)
    m = nn.Linear(8, 4)
    return m


class TestMainGrad:
    def test_params_become_bf16_and_main_grad_fp32(self):
        m = MixPrecisionLayer(_mk(), dtype="bfloat16")
        p = m._layers.weight
        assert p._value.dtype == jnp.bfloat16
        x = paddle.to_tensor(np.ones((2, 8), np.float32).astype(jnp.bfloat16))
        loss = m(x).sum()
        loss.backward()
        assert p.main_grad is not None
        assert p.main_grad._value.dtype == jnp.float32

    def test_micro_batch_accumulation_fp32_exact(self):
        """Accumulating K tiny grads must happen in fp32: in bf16 the
        small addends would be swallowed."""
        m = MixPrecisionLayer(_mk(), dtype="bfloat16")
        p = m._layers.weight
        big = paddle.to_tensor(np.full((1, 8), 256.0, np.float32)
                               .astype(jnp.bfloat16))
        tiny = paddle.to_tensor(np.full((1, 8), 0.5, np.float32)
                                .astype(jnp.bfloat16))
        m(big).sum().backward()
        p.grad = None
        for _ in range(4):
            m(tiny).sum().backward()
            p.grad = None
        got = np.asarray(p.main_grad._value, np.float32)[:, 0]
        # 256 + 4*0.5 = 258; bf16 running sum would round each +0.5 away
        np.testing.assert_allclose(got, 258.0, rtol=1e-6)

    def test_optimizer_steps_master_weights(self):
        m = MixPrecisionLayer(_mk(), dtype="bfloat16")
        opt = MixPrecisionOptimizer(
            SGD(learning_rate=0.5,
                parameters=list(m._layers.parameters())))
        p = m._layers.weight
        w0 = np.asarray(p._value, np.float32).copy()
        x = paddle.to_tensor(np.ones((2, 8), np.float32).astype(jnp.bfloat16))
        m(x).sum().backward()
        opt.step()
        opt.clear_grad()
        assert p._value.dtype == jnp.bfloat16        # stays low precision
        w1 = np.asarray(p._value, np.float32)
        assert not np.allclose(w0, w1)               # actually stepped
        assert p.main_grad is None                   # cleared
        # master drift: repeated tiny steps apply exactly through fp32
        master = opt._masters[id(p)]
        assert master.dtype == jnp.float32

    def test_scaler_shim(self):
        s = MixPrecisionScaler()
        loss = paddle.to_tensor(np.float32(2.0))
        assert float(s.scale(loss).value) == 2.0


class TestMomentDtype:
    def test_bf16_moments_fp32_math(self):
        from paddle_tpu.optimizer.functional import adamw_init, adamw_update

        params = {"w": jnp.ones((8, 8), jnp.bfloat16)}
        st = adamw_init(params, moment_dtype=jnp.bfloat16)
        assert st.m["w"].dtype == jnp.bfloat16
        g = {"w": jnp.full((8, 8), 0.01, jnp.bfloat16)}
        st, params = adamw_update(g, st, params, lr=1e-2)
        assert st.m["w"].dtype == jnp.bfloat16       # stored compact
        assert params["w"].dtype == jnp.bfloat16
        assert np.isfinite(np.asarray(st.v["w"], np.float32)).all()

    def test_default_unchanged(self):
        from paddle_tpu.optimizer.functional import adamw_init

        st = adamw_init({"w": jnp.ones((4,), jnp.bfloat16)})
        assert st.m["w"].dtype == jnp.float32


class TestHybridParallelInferenceHelper:
    def test_wrap_model_sharded_forward_parity(self):
        from paddle_tpu.distributed.fleet.utils.hybrid_parallel_inference \
            import HybridParallelInferenceHelper
        from paddle_tpu.distributed.topology import build_mesh, set_mesh
        from paddle_tpu.distributed.fleet.layers.mpu import (
            ColumnParallelLinear, RowParallelLinear)

        from paddle_tpu.distributed.topology import get_mesh

        prev = get_mesh()
        mesh = build_mesh(mp=2, dp=4)
        set_mesh(mesh)
        self._prev_mesh = prev
        m = nn.Sequential(
            ColumnParallelLinear(16, 32, gather_output=False),
            RowParallelLinear(32, 8, input_is_parallel=True))
        x = np.random.RandomState(0).randn(4, 16).astype(np.float32)
        ref = m(paddle.to_tensor(x))
        helper = HybridParallelInferenceHelper(num_mp=2, mesh=mesh)
        fwd, params = helper.wrap_model(m)
        out = fwd(params, x)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(ref.value), rtol=2e-5,
                                   atol=2e-6)
        # TP placement actually happened: some param is mp-sharded
        assert any("mp" in str(v.sharding.spec) for v in params.values())
        set_mesh(self._prev_mesh)  # don't leak the mp mesh to other tests
