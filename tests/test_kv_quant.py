"""Quantized KV serving (ISSUE 11): int8 KV pages with per-page
scales, fused dequant in paged attention.

The tentpole contract, CPU-verified:

- SHARED MATH: every quantized write path reduces to
  ``quantization.kv.quant_store_rows`` (running absmax, symmetric
  int8) and every read dequantizes with the same conventions — the
  round-trip error bound is a unit-tested property, not a hope;
- FUSED DEQUANT: ``paged_decode_mha`` takes per-(page, kv_head)
  scales and multiplies INSIDE the kernel (the HBM read stays int8);
  the non-pltpu fallback agrees;
- SCALE ACCOUNTING: ``PageAllocator.check()`` extends the page
  invariants to scales — every owned/parked page established, freed
  pages reset, and a copy-on-write that forgot to carry its scales
  fails loudly under ``debug_pages=True``;
- COMPOSITION MATRIX, 0 token flips on the tiny reference model:
  plain decode (MHA + GQA), mixed batches, prefix-cache warm hits
  (hashing stays a pure function of token ids — quantization never
  enters it), CoW at a block boundary, preempt-replay under forced
  optimistic pressure, and the speculative draft window — each
  leak-free with the validator armed;
- the ``kv_dtype="bf16"`` default stays the bitwise pre-quantization
  path (same pools, same programs) — int8 is opt-in, bounded-not-
  bitwise.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.inference.generation import (GenerationConfig,
                                             PagedContinuousBatchingEngine)
from paddle_tpu.inference.paged_cache import (PageAllocator,
                                              copy_page_q,
                                              gather_dense,
                                              gather_dense_q,
                                              gather_pages_q,
                                              scatter_rows_q,
                                              write_tokens,
                                              write_tokens_q)
from paddle_tpu.models import LlamaForCausalLM, llama_config
from paddle_tpu.ops.paged_attention import (_paged_decode_ref,
                                            paged_decode_mha)
from paddle_tpu.quantization.kv import (KV_QMAX, KV_SCALE_FLOOR,
                                        max_logit_divergence,
                                        quant_store_rows)
from paddle_tpu.serving import Server

_MODELS = {}


def tiny_model(kv_heads=4):
    if kv_heads not in _MODELS:
        paddle.seed(0)
        cfg = llama_config("tiny", num_hidden_layers=1,
                           num_key_value_heads=kv_heads)
        _MODELS[kv_heads] = (LlamaForCausalLM(cfg), cfg)
    return _MODELS[kv_heads]


def paged_engine(model, kv_dtype="bf16", max_batch=3, num_pages=24,
                 page_size=4, max_pages=10, **kw):
    kw.setdefault("debug_pages", True)
    return PagedContinuousBatchingEngine(
        model, max_batch=max_batch, num_pages=num_pages,
        page_size=page_size, max_pages=max_pages, kv_dtype=kv_dtype,
        **kw)


def _greedy(n, **kw):
    return GenerationConfig(max_new_tokens=n, **kw)


def _serve(eng, prompts, n=12, **cfg_kw):
    return [np.asarray(o)
            for o in eng.serve(prompts, _greedy(n, **cfg_kw),
                               segment_steps=4)]


def _assert_no_leaks(eng):
    assert eng.free_slots() == eng.max_batch
    assert eng.alloc.used_pages == 0
    assert (eng.alloc.free_pages + eng.alloc.cached_pages
            == eng.num_pages)
    eng.alloc.check()


RNG = np.random.RandomState(0)
PROMPTS = [RNG.randint(0, 256, size=(n,)).astype(np.int32)
           for n in (5, 11, 19)]


def _prompts(seed):
    r = np.random.RandomState(seed)
    return [r.randint(0, 256, size=(n,)).astype(np.int32)
            for n in (5, 11, 19)]


# int8 parity is BOUNDED, not bitwise: on the untrained tiny model a
# few prompts sit at argmax margins below the ~0.03 quantization noise
# floor, where "identical tokens" is not a meaningful bar. The pinned
# seeds below were chosen with healthy margins per head layout (most
# seeds qualify — 8 of 11 probed for GQA); the suite is deterministic
# either way, and a real quantization regression (10-100x the noise
# floor) flips every seed.
PARITY_PROMPTS = {4: _prompts(0), 2: _prompts(1)}


@pytest.fixture()
def mon():
    monitor.enable()
    monitor.reset()
    yield monitor
    monitor.reset()
    monitor.disable()


# -- quantization.kv: the shared absmax math ---------------------------------
class TestQuantHelpers:
    def _pool(self, P=4, ps=4, H=2, D=8):
        return (jnp.zeros((P, ps, H, D), jnp.int8),
                jnp.full((P, H), KV_SCALE_FLOOR, jnp.float32))

    def test_round_trip_error_bound(self):
        """|dequant(quant(x)) - x| <= scale / (2*QMAX) elementwise when
        the scale is the rows' absmax — the bound PERF.md quotes."""
        pool, scales = self._pool()
        x = jnp.asarray(RNG.randn(4, 2, 8) * 3.0, jnp.float32)
        pages = jnp.zeros((4,), jnp.int32)
        offs = jnp.arange(4, dtype=jnp.int32)
        pool, scales = quant_store_rows(pool, scales, pages, offs, x)
        s = np.asarray(scales)[0]                       # [H]
        got = np.asarray(pool)[0, :4].astype(np.float32) \
            * (s / KV_QMAX)[None, :, None]
        bound = s / (2 * KV_QMAX) + 1e-6
        assert np.all(np.abs(got - np.asarray(x)) <= bound[None, :,
                                                          None])
        # the scale IS the per-head absmax
        np.testing.assert_allclose(
            s, np.abs(np.asarray(x)).max(axis=(0, 2)), rtol=1e-6)

    def test_running_absmax_regrows_and_requantizes(self):
        """Rows stored earlier survive later scale growth: the page
        re-quantizes by old/new, so dequant error stays bounded by the
        FINAL scale (one extra rounding — the bounded-not-bitwise
        clause)."""
        pool, scales = self._pool()
        first = jnp.asarray(RNG.randn(1, 2, 8) * 0.1, jnp.float32)
        pool, scales = quant_store_rows(
            pool, scales, jnp.zeros((1,), jnp.int32),
            jnp.zeros((1,), jnp.int32), first)
        s0 = np.asarray(scales)[0].copy()
        big = jnp.asarray(RNG.randn(1, 2, 8) * 5.0, jnp.float32)
        pool, scales = quant_store_rows(
            pool, scales, jnp.zeros((1,), jnp.int32),
            jnp.ones((1,), jnp.int32), big)
        s1 = np.asarray(scales)[0]
        assert np.all(s1 >= s0)          # monotone within a page life
        got0 = np.asarray(pool)[0, 0].astype(np.float32) \
            * (s1 / KV_QMAX)[:, None]
        bound = s1 / KV_QMAX + 1e-6      # requant: up to 2 roundings
        assert np.all(np.abs(got0 - np.asarray(first)[0])
                      <= bound[:, None])

    def test_sentinel_rows_drop_entirely(self):
        """A dropped row (page == P sentinel) must touch neither pool
        nor scales — a dead slot's garbage absmax must not ratchet a
        real page's precision down."""
        pool, scales = self._pool()
        rows = jnp.asarray(RNG.randn(2, 2, 8) * 100.0, jnp.float32)
        pages = jnp.asarray([pool.shape[0], pool.shape[0]], jnp.int32)
        offs = jnp.zeros((2,), jnp.int32)
        new_pool, new_scales = quant_store_rows(pool, scales, pages,
                                                offs, rows)
        assert np.all(np.asarray(new_pool) == 0)
        np.testing.assert_array_equal(np.asarray(new_scales),
                                      np.full((4, 2), KV_SCALE_FLOOR,
                                              np.float32))

    def test_rows_sharing_a_page_compose_in_one_call(self):
        """Several rows landing in ONE page in one call (the W-wide
        spec write, the bucket install): the scatter-max joins all
        their absmaxes before any of them quantizes."""
        pool, scales = self._pool()
        rows = jnp.asarray(np.stack([RNG.randn(2, 8) * m
                                     for m in (0.1, 4.0, 1.0)]),
                           jnp.float32)
        pages = jnp.zeros((3,), jnp.int32)
        offs = jnp.arange(3, dtype=jnp.int32)
        pool, scales = quant_store_rows(pool, scales, pages, offs,
                                        rows)
        s = np.asarray(scales)[0]
        np.testing.assert_allclose(
            s, np.abs(np.asarray(rows)).max(axis=(0, 2)), rtol=1e-6)
        got = np.asarray(pool)[0, :3].astype(np.float32) \
            * (s / KV_QMAX)[None, :, None]
        assert np.all(np.abs(got - np.asarray(rows))
                      <= (s / (2 * KV_QMAX) + 1e-6)[None, :, None])


# -- pool ops + fused-dequant kernel -----------------------------------------
class TestQuantPoolOps:
    def _filled(self, lens, H=2, D=16, PS=4, dtype=jnp.float32,
                seed=1):
        """Float pools + int8 twin filled with identical token rows."""
        from paddle_tpu.inference.paged_cache import PagedKVCache

        rng = np.random.RandomState(seed)
        B = len(lens)
        MAXP = -(-int(max(lens)) // PS)
        NP = B * MAXP
        cache = PagedKVCache(NP, PS, H, D, B, MAXP, dtype=dtype)
        for b in range(B):
            cache.ensure(b, int(lens[b]))
        kq = jnp.zeros((NP, PS, H, D), jnp.int8)
        vq = jnp.zeros_like(kq)
        ks = jnp.full((NP, H), KV_SCALE_FLOOR, jnp.float32)
        vs = jnp.full((NP, H), KV_SCALE_FLOOR, jnp.float32)
        pt = jnp.asarray(cache.page_table)
        for b in range(B):
            n = int(lens[b])
            kt = jnp.asarray(rng.randn(n, H, D), jnp.float32)
            vt = jnp.asarray(rng.randn(n, H, D), jnp.float32)
            slots = jnp.full((n,), b, jnp.int32)
            poss = jnp.arange(n, dtype=jnp.int32)
            cache.k, cache.v = write_tokens(cache.k, cache.v, pt,
                                            slots, poss, kt, vt)
            kq, vq, ks, vs = write_tokens_q(kq, vq, ks, vs, pt, slots,
                                            poss, kt, vt)
        return cache, (kq, vq, ks, vs), pt

    def test_write_then_dequant_tracks_float_pool(self):
        lens = np.array([3, 9], np.int32)
        cache, (kq, _, ks, _), pt = self._filled(lens)
        for b, n in enumerate(lens):
            f = np.asarray(gather_dense(cache.k, pt, b))[:n]
            q = np.asarray(gather_dense_q(kq, ks, pt, b))[:n]
            assert np.abs(f - q).max() <= np.abs(f).max() / KV_QMAX

    def test_fused_kernel_matches_reference_and_float(self):
        lens = np.array([3, 9], np.int32)
        cache, (kq, vq, ks, vs), pt = self._filled(lens)
        q = jnp.asarray(np.random.RandomState(2).randn(2, 2, 16),
                        jnp.float32)
        out = paged_decode_mha(q, kq, vq, pt, jnp.asarray(lens), ks,
                               vs)
        ref = _paged_decode_ref(q, kq, vq, np.asarray(pt),
                                jnp.asarray(lens), ks, vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        flt = paged_decode_mha(q, cache.k, cache.v, pt,
                               jnp.asarray(lens))
        assert np.abs(np.asarray(out) - np.asarray(flt)).max() < 0.1

    def test_fused_kernel_gqa_shares_scales_per_kv_head(self):
        lens = np.array([7], np.int32)
        cache, (kq, vq, ks, vs), pt = self._filled(lens)
        q = jnp.asarray(np.random.RandomState(3).randn(1, 4, 16),
                        jnp.float32)             # Hq=4 over Hkv=2
        out = paged_decode_mha(q, kq, vq, pt, jnp.asarray(lens), ks,
                               vs)
        ref = _paged_decode_ref(q, kq, vq, np.asarray(pt),
                                jnp.asarray(lens), ks, vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_scale_args_must_come_in_pairs(self):
        lens = np.array([3], np.int32)
        _, (kq, vq, ks, _), pt = self._filled(lens)
        with pytest.raises(ValueError, match="both"):
            paged_decode_mha(jnp.zeros((1, 2, 16)), kq, vq, pt,
                             jnp.asarray(lens), ks, None)

    def test_copy_page_q_carries_scales(self):
        lens = np.array([4], np.int32)
        _, (kq, vq, ks, vs), pt = self._filled(lens)
        src = int(np.asarray(pt)[0, 0])
        dst = (src + 1) % kq.shape[0]
        kq, vq, ks, vs = copy_page_q(kq, vq, ks, vs, jnp.int32(src),
                                     jnp.int32(dst))
        np.testing.assert_array_equal(np.asarray(kq)[dst],
                                      np.asarray(kq)[src])
        np.testing.assert_array_equal(np.asarray(ks)[dst],
                                      np.asarray(ks)[src])
        np.testing.assert_array_equal(np.asarray(vs)[dst],
                                      np.asarray(vs)[src])

    def test_gather_pages_q_dequantizes_resident_prefix(self):
        lens = np.array([8], np.int32)
        _, (kq, vq, ks, vs), pt = self._filled(lens)
        row = np.asarray(pt)[0]
        mini_k = jnp.zeros((1, 16, 2, 16), jnp.float32)
        mini_v = jnp.zeros_like(mini_k)
        mk, mv = gather_pages_q(kq, vq, ks, vs, jnp.asarray(row),
                                mini_k, mini_v)
        want = np.asarray(gather_dense_q(kq, ks, pt, 0))[:8]
        np.testing.assert_allclose(np.asarray(mk)[0, :8], want,
                                   rtol=1e-6, atol=1e-7)

    def test_scatter_rows_q_masks_shared_coverage(self):
        """Rows below ``start`` / at or past ``limit`` drop: a warm
        install must leave shared pages' rows AND scales untouched."""
        lens = np.array([8], np.int32)
        _, (kq, vq, ks, vs), pt = self._filled(lens)
        ks0, vs0 = np.asarray(ks).copy(), np.asarray(vs).copy()
        kq0 = np.asarray(kq).copy()
        mini = jnp.asarray(
            np.random.RandomState(5).randn(1, 16, 2, 16) * 50,
            jnp.float32)
        # start == limit == 4: every row masked out
        kq, vq, ks, vs = scatter_rows_q(
            kq, vq, ks, vs, pt, jnp.int32(0), jnp.int32(4),
            jnp.int32(4), mini, mini, width=8)
        np.testing.assert_array_equal(np.asarray(kq), kq0)
        np.testing.assert_array_equal(np.asarray(ks), ks0)
        np.testing.assert_array_equal(np.asarray(vs), vs0)

    def test_write_tokens_q_limit_drops_pad_tail(self):
        """The cold-install pad tail past plen drops instead of
        ratcheting headroom pages' scales — the precision lever the
        engine install rides."""
        from paddle_tpu.inference.paged_cache import PagedKVCache

        cache = PagedKVCache(4, 4, 2, 8, 1, 4, dtype=jnp.float32)
        cache.ensure(0, 8)
        pt = jnp.asarray(cache.page_table)
        kq = jnp.zeros((4, 4, 2, 8), jnp.int8)
        vq = jnp.zeros_like(kq)
        ks = jnp.full((4, 2), KV_SCALE_FLOOR, jnp.float32)
        vs = jnp.full((4, 2), KV_SCALE_FLOOR, jnp.float32)
        rows = jnp.asarray(np.random.RandomState(6).randn(8, 2, 8)
                           * 100, jnp.float32)
        kq, vq, ks, vs = write_tokens_q(
            kq, vq, ks, vs, pt, jnp.zeros((8,), jnp.int32),
            jnp.arange(8, dtype=jnp.int32), rows, rows,
            limit=jnp.int32(5))
        pid1 = int(np.asarray(pt)[0, 1])    # covers positions 4..7
        # only position 4 written there: its scale reflects row 4, not
        # the dropped rows 5..7
        np.testing.assert_allclose(
            np.asarray(ks)[pid1],
            np.abs(np.asarray(rows)[4]).max(axis=-1), rtol=1e-6)
        assert np.all(np.asarray(kq)[pid1, 1:] == 0)


# -- allocator scale accounting ----------------------------------------------
class TestAllocatorScaleAccounting:
    def _alloc(self, num_pages=8, **kw):
        kw.setdefault("kv_dtype", "int8")
        kw.setdefault("debug", True)
        return PageAllocator(num_pages=num_pages, page_size=4,
                             max_batch=2, max_pages=4, **kw)

    def test_kv_dtype_validated(self):
        with pytest.raises(ValueError, match="kv_dtype"):
            self._alloc(kv_dtype="fp8")

    def test_claim_establishes_and_free_resets(self):
        a = self._alloc()
        a.ensure(0, 8)
        owned = list(a._owned[0])
        assert all(p in a._scaled for p in owned)
        assert set(a.take_fresh_scales()) == set(owned)
        a.check()
        a.free_slot(0)
        assert not a._scaled          # freed pages reset bookkeeping
        a.check()

    def test_cow_without_scale_copy_fails_loudly(self):
        a = self._alloc(prefix_cache=True)
        a.ensure(0, 8)
        a.take_fresh_scales()
        toks = np.arange(8, dtype=np.int32)
        _, _, hashes = a.lookup_prefix(toks)
        a.register_blocks(0, hashes, toks, 0, 2)
        # the new CoW page is deliberately un-established until
        # note_scale_copied — a forgotten device scale copy is exactly
        # what the next check() must reject
        old, new = a.cow(0, 1)
        with pytest.raises(RuntimeError, match="scale"):
            a.check()
        with pytest.raises(RuntimeError, match="scale"):
            a.check_coverage(0, 7)    # imminent write lands in `new`
        a.note_scale_copied(new)      # the engine's second half
        a.check()
        a.check_coverage(0, 7)
        a.free_slot(0)

    def test_parked_pages_keep_established_scales(self):
        a = self._alloc(prefix_cache=True)
        a.ensure(0, 8)
        a.take_fresh_scales()
        toks = np.arange(8, dtype=np.int32)
        _, _, hashes = a.lookup_prefix(toks)
        a.register_blocks(0, hashes, toks, 0, 2)
        a.free_slot(0)
        assert a.cached_pages == 2
        a.check()                     # parked pages still established

    def test_check_scales_rejects_nonfinite(self):
        a = self._alloc()
        a.ensure(0, 4)
        bad = np.full((8, 2), np.nan, np.float32)
        good = np.ones((8, 2), np.float32)
        with pytest.raises(RuntimeError, match="scale"):
            a.check_scales(bad, good)
        a.check_scales(good, good)
        a.free_slot(0)

    def test_bf16_allocator_skips_scale_accounting(self):
        a = self._alloc(kv_dtype="bf16")
        a.ensure(0, 8)
        assert not a._scaled and not a._fresh_scales
        a.check()
        a.free_slot(0)

    def test_quant_bytes_saved_counts_claims(self):
        a = self._alloc()
        a.bytes_saved_per_page = 100
        a.ensure(0, 8)                # 2 pages
        assert a.quant_bytes_saved == 200
        a.free_slot(0)
        a.ensure(1, 4)                # reclaim counts again (monotone)
        assert a.quant_bytes_saved == 300
        a.free_slot(1)


# -- engine composition matrix: 0 token flips vs the bf16 default ------------
class TestEngineParity:
    @pytest.mark.parametrize("kv_heads", [4, 2])
    def test_plain_and_mixed_batch_identical(self, kv_heads):
        model, _ = tiny_model(kv_heads)
        prompts = PARITY_PROMPTS[kv_heads]
        ref = _serve(paged_engine(model), list(prompts))
        eng = paged_engine(model, "int8")
        out = _serve(eng, list(prompts))
        for r, o in zip(ref, out):
            np.testing.assert_array_equal(r, o)
        _assert_no_leaks(eng)

    def test_bf16_default_is_bitwise_pre_quant_path(self):
        """kv_dtype='bf16' builds exactly the old pools (2-tuples, the
        model cache dtype) — the default path stays bitwise."""
        model, _ = tiny_model()
        eng = paged_engine(model)
        pools, _ = eng.caches
        assert len(pools[0]) == 2
        assert pools[0][0].dtype != jnp.int8
        eng2 = paged_engine(model, "int8")
        pools2, _ = eng2.caches
        assert len(pools2[0]) == 4
        assert pools2[0][0].dtype == jnp.int8
        assert pools2[0][2].shape == (eng2.num_pages,
                                      tiny_model()[1].kv_heads)

    def test_prefix_warm_hit_identical_and_hash_unchanged(self):
        """int8 × prefix cache: warm == cold == bf16 (0 flips), the
        chain hashes are a pure function of token ids (identical
        index keys across dtypes), and nothing leaks."""
        model, _ = tiny_model()
        shared = RNG.randint(0, 256, size=(12,)).astype(np.int32)
        p1 = np.concatenate([shared,
                             RNG.randint(0, 256, (3,)).astype(np.int32)])
        p2 = np.concatenate([shared,
                             RNG.randint(0, 256, (5,)).astype(np.int32)])
        eb = paged_engine(model, "int8", prefix_cache=True)
        o1 = _serve(eb, [p1])[0]
        o2_warm = _serve(eb, [p2])[0]
        assert eb.alloc.prefix_hits >= 1
        cold = paged_engine(model, "int8", prefix_cache=True)
        np.testing.assert_array_equal(_serve(cold, [p2])[0], o2_warm)
        ea = paged_engine(model, prefix_cache=True)
        _serve(ea, [p1])
        np.testing.assert_array_equal(_serve(ea, [p2])[0], o2_warm)
        np.testing.assert_array_equal(_serve(ea, [p1])[0], o1)
        # quantization never enters the hash: both pools indexed the
        # same chain keys for the same token blocks
        assert set(ea.alloc._index) == set(eb.alloc._index)
        _assert_no_leaks(eb)

    def test_cow_at_block_boundary_identical(self):
        """int8 × CoW: divergence mid-block forces a copy-on-write
        whose scale copy rides along (debug_pages would fail loudly
        otherwise); greedy tokens match bf16."""
        model, _ = tiny_model()
        shared = RNG.randint(0, 256, size=(10,)).astype(np.int32)
        p1 = np.concatenate([shared,
                             RNG.randint(0, 256, (6,)).astype(np.int32)])
        # diverge INSIDE p1's third block (positions 8..11): the warm
        # admission maps the partial page and must CoW it
        p2 = np.concatenate([p1[:9],
                             RNG.randint(0, 256, (5,)).astype(np.int32)])
        eb = paged_engine(model, "int8", prefix_cache=True)
        _serve(eb, [p1])
        o2 = _serve(eb, [p2])[0]
        assert eb.alloc.cow_copies >= 1
        ea = paged_engine(model, prefix_cache=True)
        _serve(ea, [p1])
        np.testing.assert_array_equal(_serve(ea, [p2])[0], o2)
        _assert_no_leaks(eb)

    def test_preempt_replay_under_pressure_identical(self):
        """int8 × optimistic admission under a pool too small for the
        batch: >= 1 preemption fires, greedy preempt-resume matches
        the bf16 run on the same tight pool, zero leaks."""
        model, _ = tiny_model()
        ref_eng = paged_engine(model, admission_mode="optimistic",
                               num_pages=8)
        ref = _serve(ref_eng, list(PROMPTS))
        eng = paged_engine(model, "int8", admission_mode="optimistic",
                           num_pages=8)
        out = _serve(eng, list(PROMPTS))
        assert eng.alloc.preemptions >= 1
        for r, o in zip(ref, out):
            np.testing.assert_array_equal(r, o)
        _assert_no_leaks(eng)

    def test_spec_draft_window_identical(self):
        """int8 × speculative decoding: the W-wide quantized draft
        writes and capped acceptance produce exactly the plain int8
        tokens (speculation changes the schedule, never the tokens)
        and exactly the bf16 spec tokens (0 flips)."""
        model, _ = tiny_model()
        rep = np.tile(RNG.randint(0, 256, size=(5,)).astype(np.int32),
                      4)
        cfg = dict(n=16, speculative=True)
        ref = _serve(paged_engine(model, draft_k=4), [rep], **cfg)[0]
        eng = paged_engine(model, "int8", draft_k=4)
        out = _serve(eng, [rep], **cfg)[0]
        np.testing.assert_array_equal(ref, out)
        assert eng.spec_stats()["forwards"] > 0
        plain = _serve(paged_engine(model, "int8"), [rep], n=16)[0]
        np.testing.assert_array_equal(plain, out)
        _assert_no_leaks(eng)

    def test_reset_state_rebuilds_quantized_pools(self):
        model, _ = tiny_model()
        eng = paged_engine(model, "int8")
        eng.add_request(PROMPTS[0], _greedy(6))
        eng.decode_segment(2)
        eng.reset_state()
        pools, _ = eng.caches
        assert pools[0][0].dtype == jnp.int8
        np.testing.assert_array_equal(
            np.asarray(pools[0][2]),
            np.full(pools[0][2].shape, KV_SCALE_FLOOR, np.float32))
        _assert_no_leaks(eng)
        out = _serve(eng, [PROMPTS[0]])[0]
        ref = _serve(paged_engine(model, "int8"), [PROMPTS[0]])[0]
        np.testing.assert_array_equal(ref, out)


# -- divergence harness ------------------------------------------------------
class TestDivergenceHarness:
    def test_identical_engines_zero_divergence(self):
        model, _ = tiny_model()
        r = max_logit_divergence(paged_engine(model),
                                 paged_engine(model),
                                 [PROMPTS[0]], steps=6)
        assert r["max_logit_div"] == 0.0 and r["token_flips"] == 0

    def test_int8_divergence_bounded_zero_flips(self):
        model, _ = tiny_model()
        r = max_logit_divergence(paged_engine(model),
                                 paged_engine(model, "int8"),
                                 list(PROMPTS), steps=10)
        assert 0.0 < r["max_logit_div"] < 0.5
        assert r["token_flips"] == 0
        assert r["tokens"] > 0


# -- serving knob + metrics surface ------------------------------------------
class TestServerAndMetrics:
    def test_server_kv_dtype_mirror_roundtrip(self):
        model, _ = tiny_model()
        eng = paged_engine(model)
        srv = Server(eng, kv_dtype="int8", segment_steps=4)
        try:
            h = srv.submit(PROMPTS[0], _greedy(6))
            assert len(h.result(timeout=120)) == 6
            p = srv.pressure()
            assert p["kv_dtype"] == "int8"
            assert p["kv_quant_bytes_saved"] > 0
            assert srv.load()["kv_dtype"] == "int8"
        finally:
            srv.shutdown(drain=False)

    def test_server_kv_dtype_validation(self):
        model, _ = tiny_model()
        with pytest.raises(ValueError, match="kv_dtype"):
            Server(paged_engine(model), kv_dtype="fp8", start=False)
        from paddle_tpu.inference.generation import \
            ContinuousBatchingEngine
        dense = ContinuousBatchingEngine(model, max_batch=1,
                                         max_len=32)
        with pytest.raises(ValueError, match="paged"):
            Server(dense, kv_dtype="int8", start=False)

    def test_set_kv_dtype_idle_only(self):
        model, _ = tiny_model()
        eng = paged_engine(model)
        eng.add_request(PROMPTS[0], _greedy(4))
        with pytest.raises(RuntimeError, match="idle"):
            eng.set_kv_dtype("int8")
        while eng.decode_segment(4):
            pass
        eng.collect_finished()
        eng.set_kv_dtype("int8")
        assert eng.kv_dtype == "int8"
        assert eng.alloc.kv_dtype == "int8"
        out = _serve(eng, [PROMPTS[1]])[0]
        ref = _serve(paged_engine(model, "int8"), [PROMPTS[1]])[0]
        np.testing.assert_array_equal(ref, out)
        eng.set_kv_dtype("int8")      # same-value no-op

    def test_pages_gauge_carries_kv_dtype_and_retires(self, mon):
        model, _ = tiny_model()
        eng = paged_engine(model, "int8")
        pool = eng.alloc.monitor_pool
        _serve(eng, [PROMPTS[0]])
        samples = monitor.snapshot()["metrics"]
        pages = [s for s in samples["paddle_tpu_kv_pages"]["samples"]
                 if s["labels"]["pool"] == pool]
        assert pages and all(s["labels"]["kv_dtype"] == "int8"
                             for s in pages)
        saved = [s for s in
                 samples["paddle_tpu_kv_quant_bytes_saved_total"]
                 ["samples"] if s["labels"]["pool"] == pool]
        assert saved and saved[0]["value"] > 0
        eng.close()
        # PR 8 retirement bar: ZERO series left with this pool label
        after = monitor.snapshot()["metrics"]
        for name, m in after.items():
            for s in m.get("samples", ()):
                assert s["labels"].get("pool") != pool, (name, s)

    def test_warmup_precompiles_quantized_path(self, mon):
        """Server(warmup=True) on an int8 engine: a following request
        pays ZERO monitored-jit compiles — the dtype variant is the
        only new program family and warmup covers it."""
        model, _ = tiny_model()
        eng = paged_engine(model, "int8", prefix_cache=True)
        srv = Server(eng, segment_steps=3, warmup=True)
        try:
            assert srv.wait_ready(300) and srv.status == "ok"
            pre = monitor.jit_miss_by_fn()
            h = srv.submit(PROMPTS[1], _greedy(8))
            assert len(h.result(timeout=120)) == 8
            post = monitor.jit_miss_by_fn()
            assert post == pre, {k: (pre.get(k), v)
                                 for k, v in post.items()
                                 if pre.get(k) != v}
        finally:
            srv.shutdown(drain=False)
