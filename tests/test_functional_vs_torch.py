"""nn.functional geometry/resampling ops vs torch — the classic
convention bug nests (align_corners, padding modes, NCHW layouts,
normalized grids). torch.nn.functional is an independent implementation
of the same reference semantics (paddle mirrors torch here), so
disagreement means a real convention bug.
"""
import numpy as np
import pytest

import paddle_tpu as paddle

torch = pytest.importorskip("torch")
import torch.nn.functional as TF  # noqa: E402

F = paddle.nn.functional
RTOL, ATOL = 1e-3, 1e-3


def _t(a):
    return paddle.to_tensor(np.ascontiguousarray(a))


def _np(x):
    return np.asarray(x.value if hasattr(x, "value") else x)


def rand(*s, seed=0):
    return np.random.RandomState(seed).randn(*s).astype(np.float32)


class TestInterpolate:
    @pytest.mark.parametrize("mode,align", [
        ("nearest", False),
        ("bilinear", False), ("bilinear", True),
        ("bicubic", False), ("bicubic", True),
    ])
    def test_upsample_2d_modes(self, mode, align):
        x = rand(2, 3, 5, 7, seed=1)
        kw = {} if mode == "nearest" else {"align_corners": align}
        got = _np(F.interpolate(_t(x), size=(10, 13), mode=mode, **kw))
        want = TF.interpolate(torch.from_numpy(x), size=(10, 13),
                              mode=mode, **kw).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL,
                                   err_msg=f"{mode} align={align}")

    @pytest.mark.parametrize("align", [False, True])
    def test_downsample_bilinear(self, align):
        x = rand(1, 2, 12, 16, seed=2)
        got = _np(F.interpolate(_t(x), size=(5, 7), mode="bilinear",
                                align_corners=align))
        want = TF.interpolate(torch.from_numpy(x), size=(5, 7),
                              mode="bilinear", align_corners=align).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)

    def test_scale_factor(self):
        x = rand(1, 2, 6, 6, seed=3)
        got = _np(F.interpolate(_t(x), scale_factor=2.0, mode="nearest"))
        want = TF.interpolate(torch.from_numpy(x),
                              scale_factor=2.0, mode="nearest").numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)

    def test_linear_1d_and_trilinear_3d(self):
        x1 = rand(2, 3, 9, seed=4)
        got = _np(F.interpolate(_t(x1), size=(15,), mode="linear",
                                align_corners=True))
        want = TF.interpolate(torch.from_numpy(x1), size=15,
                              mode="linear", align_corners=True).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)
        x3 = rand(1, 2, 4, 5, 6, seed=5)
        got = _np(F.interpolate(_t(x3), size=(8, 7, 9), mode="trilinear",
                                align_corners=False))
        want = TF.interpolate(torch.from_numpy(x3), size=(8, 7, 9),
                              mode="trilinear",
                              align_corners=False).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)


class TestGridSample:
    @pytest.mark.parametrize("mode", ["bilinear", "nearest"])
    @pytest.mark.parametrize("pad", ["zeros", "border", "reflection"])
    @pytest.mark.parametrize("align", [False, True])
    def test_grid_sample_full_matrix(self, mode, pad, align):
        x = rand(2, 3, 6, 7, seed=6)
        grid = (np.random.RandomState(7).rand(2, 5, 4, 2).astype(
            np.float32) * 2.4 - 1.2)       # includes out-of-bounds
        got = _np(F.grid_sample(_t(x), _t(grid), mode=mode,
                                padding_mode=pad, align_corners=align))
        want = TF.grid_sample(torch.from_numpy(x),
                              torch.from_numpy(grid), mode=mode,
                              padding_mode=pad,
                              align_corners=align).numpy()
        np.testing.assert_allclose(
            got, want, rtol=RTOL, atol=ATOL,
            err_msg=f"{mode}/{pad}/align={align}")

    def test_affine_grid_matches_torch(self):
        theta = np.array([[[0.8, 0.1, 0.2], [-0.1, 0.9, -0.3]]],
                         np.float32)
        for align in (False, True):
            got = _np(F.affine_grid(_t(theta), [1, 3, 5, 6],
                                    align_corners=align))
            want = TF.affine_grid(torch.from_numpy(theta), [1, 3, 5, 6],
                                  align_corners=align).numpy()
            np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL,
                                       err_msg=f"align={align}")


class TestPadAndShuffle:
    @pytest.mark.parametrize("mode", ["reflect", "replicate", "circular"])
    def test_pad_modes_4d(self, mode):
        x = rand(2, 3, 5, 6, seed=8)
        pads = [1, 2, 2, 1]
        got = _np(F.pad(_t(x), pads, mode=mode))
        want = TF.pad(torch.from_numpy(x), pads, mode=mode).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)

    def test_pad_constant_value(self):
        x = rand(2, 3, 4, 4, seed=9)
        got = _np(F.pad(_t(x), [1, 1, 2, 0], mode="constant", value=3.5))
        want = TF.pad(torch.from_numpy(x), [1, 1, 2, 0],
                      mode="constant", value=3.5).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)

    def test_pixel_shuffle_roundtrip_and_torch(self):
        x = rand(2, 8, 3, 4, seed=10)
        got = _np(F.pixel_shuffle(_t(x), 2))
        want = TF.pixel_shuffle(torch.from_numpy(x), 2).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)
        back = _np(F.pixel_unshuffle(_t(got), 2))
        np.testing.assert_allclose(back, x, rtol=RTOL, atol=ATOL)

    def test_unfold_fold_roundtrip(self):
        x = rand(1, 2, 6, 6, seed=11)
        u = F.unfold(_t(x), kernel_sizes=3, strides=3)
        want_u = TF.unfold(torch.from_numpy(x), 3, stride=3).numpy()
        np.testing.assert_allclose(_np(u), want_u, rtol=RTOL, atol=ATOL)
        back = _np(F.fold(u, output_sizes=[6, 6], kernel_sizes=3,
                          strides=3))
        np.testing.assert_allclose(back, x, rtol=RTOL, atol=ATOL)


class TestPooling:
    @pytest.mark.parametrize("ceil", [False, True])
    def test_max_pool2d_ceil_mode(self, ceil):
        x = rand(2, 3, 7, 9, seed=12)
        got = _np(F.max_pool2d(_t(x), kernel_size=3, stride=2,
                               padding=1, ceil_mode=ceil))
        want = TF.max_pool2d(torch.from_numpy(x), 3, stride=2, padding=1,
                             ceil_mode=ceil).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)

    @pytest.mark.parametrize("exclusive", [True, False])
    def test_avg_pool2d_count_include_pad(self, exclusive):
        # paddle exclusive=True == torch count_include_pad=False
        x = rand(1, 2, 6, 6, seed=13)
        got = _np(F.avg_pool2d(_t(x), kernel_size=3, stride=2, padding=1,
                               exclusive=exclusive))
        want = TF.avg_pool2d(torch.from_numpy(x), 3, stride=2, padding=1,
                             count_include_pad=not exclusive).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)

    def test_adaptive_pools_uneven(self):
        # 7 -> 3 forces uneven windows: the classic adaptive-pool bug
        x = rand(2, 3, 7, 7, seed=14)
        got = _np(F.adaptive_avg_pool2d(_t(x), output_size=3))
        want = TF.adaptive_avg_pool2d(torch.from_numpy(x), 3).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)
        got = _np(F.adaptive_max_pool2d(_t(x), output_size=3))
        want = TF.adaptive_max_pool2d(torch.from_numpy(x), 3).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)

    def test_max_pool1d_3d(self):
        x1 = rand(2, 3, 11, seed=15)
        got = _np(F.max_pool1d(_t(x1), kernel_size=2, stride=2))
        want = TF.max_pool1d(torch.from_numpy(x1), 2, stride=2).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)
        x3 = rand(1, 2, 4, 6, 6, seed=16)
        got = _np(F.max_pool3d(_t(x3), kernel_size=2, stride=2))
        want = TF.max_pool3d(torch.from_numpy(x3), 2, stride=2).numpy()
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)


class TestConvs:
    @pytest.mark.parametrize("groups", [1, 2])
    @pytest.mark.parametrize("dilation", [1, 2])
    def test_conv2d_groups_dilation(self, groups, dilation):
        x = rand(2, 4, 9, 9, seed=17)
        w = rand(6, 4 // groups, 3, 3, seed=18) * 0.2
        b = rand(6, seed=19)
        got = _np(F.conv2d(_t(x), _t(w), _t(b), stride=2, padding=2,
                           dilation=dilation, groups=groups))
        want = TF.conv2d(torch.from_numpy(x), torch.from_numpy(w),
                         torch.from_numpy(b), stride=2, padding=2,
                         dilation=dilation, groups=groups).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-3)

    @pytest.mark.parametrize("output_padding", [0, 1])
    def test_conv2d_transpose_output_padding(self, output_padding):
        x = rand(1, 3, 5, 5, seed=20)
        w = rand(3, 4, 3, 3, seed=21) * 0.2
        got = _np(F.conv2d_transpose(_t(x), _t(w), stride=2, padding=1,
                                     output_padding=output_padding))
        want = TF.conv_transpose2d(torch.from_numpy(x),
                                   torch.from_numpy(w), stride=2,
                                   padding=1,
                                   output_padding=output_padding).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-3)

    def test_conv1d_and_3d(self):
        x1 = rand(2, 3, 12, seed=22)
        w1 = rand(5, 3, 4, seed=23) * 0.2
        got = _np(F.conv1d(_t(x1), _t(w1), stride=2, padding=1))
        want = TF.conv1d(torch.from_numpy(x1), torch.from_numpy(w1),
                         stride=2, padding=1).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-3)
        x3 = rand(1, 2, 5, 6, 6, seed=24)
        w3 = rand(4, 2, 3, 3, 3, seed=25) * 0.2
        got = _np(F.conv3d(_t(x3), _t(w3), padding=1))
        want = TF.conv3d(torch.from_numpy(x3), torch.from_numpy(w3),
                         padding=1).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-3)
